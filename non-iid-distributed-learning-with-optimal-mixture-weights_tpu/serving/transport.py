"""Dispatch transports: the process-boundary seam of the serving plane.

Everything the serving vertical proved until now — chaos-proven
failover (PR 7), artifact cold start (PR 9), burn-rate admission and
autoscaling (PR 14) — held inside ONE process, because
``FailoverRouter`` dispatched by direct call. This module extracts
that call into a typed :class:`DispatchTransport` interface and adds a
second implementation that crosses a real process boundary over a real
wire, so "replica" can become "host" (ROADMAP direction 1) with the
router, the chaos plane, and the whole control stack unchanged:

- :class:`InProcessTransport` — the extracted direct-call path.
  ``dispatch`` is ``engine.predict`` verbatim; a :class:`~serving.
  replica.Replica` built without an explicit transport gets one, so
  every pre-existing replica/chaos/control/rollout behavior is
  byte-identical.
- :class:`SocketTransport` — a stdlib-TCP client speaking the
  length-prefixed frame protocol below to a :class:`PodWorker`
  process. Each dispatch carries the batch, the model version pin,
  the REMAINING deadline budget (connect/read timeouts are derived
  from it — a request whose caller gave up must not hold a socket
  open), and a ``TRACECTX.v1`` header (``utils.trace.inject_context``
  finally gets its consumer: the worker's spans join the router-side
  request trace, one request still landing exactly one ``"request"``
  span). Connection loss triggers reconnect-with-backoff: a failed
  connect opens a fast-fail window that doubles up to a cap, so a
  dead worker costs the failover walk microseconds, not a connect
  timeout per dispatch.
- :class:`PodWorker` — the server side: a worker process hosting an
  engine (the bench loads a PR 9 AOT artifact — zero compiles),
  serving dispatch frames, answering ``hello``/``stats`` metadata
  queries, and accepting the ``swap`` version-announce control frame
  so a mid-stream ``swap_weights`` propagates to every pod worker
  under ONE agreed version number (the cross-process half of the
  PR 6 registry follow-on).
- :class:`PodClientEngine` — the engine-interface facade the router
  and service see over a worker pod: metadata from the worker
  handshake, a ``pop_timings`` slot the socket transports stamp (so
  spans carry the version the WIRE reported), and the broadcasting
  ``swap_weights``.

**Failure taxonomy.** Transport failures classify into the existing
serving taxonomy — nothing downstream grows a socket-aware special
case:

========================  ============================================
wire failure              classified as
========================  ============================================
connect refused / reset   :class:`TransportRefused` (transient
                          ``ConnectionError``): the router's circuit
                          breaker counts it and the failover walk
                          requeues the in-flight batch — exactly the
                          ``ReplicaUnavailable`` path PR 7 built
read timeout / partition  :class:`TransportTimeout` (transient): same
                          requeue; the connection is dropped (a
                          half-open socket must not poison the next
                          dispatch)
budget exhausted          :class:`TransportTimeout` BEFORE any I/O —
                          the deadline contract crosses the hop
malformed frame           :class:`FrameError` (``ValueError``):
                          PERMANENT and loud — truncated, oversized,
                          or garbage frames are protocol bugs, and
                          the service's transient classifier
                          deliberately refuses to retry ValueErrors
========================  ============================================

When every survivor fails a pass the router still raises its own
transient ``ReplicaUnavailable`` / terminal ``NoReplicasAvailable`` —
the PR 7/14 failover-and-autoscale machinery works across processes
without modification.

**Frame protocol** (version :data:`FRAME_SCHEMA`)::

    +------+------------+-------------+----------------+---------+
    | b"FW1" magic (4)  | !I hdr_len  | !I payload_len | header  |
    | + version byte    |             |                | JSON    |
    +------+------------+-------------+----------------+---------+
    | payload bytes (raw little-endian array / npz weights)      |
    +------------------------------------------------------------+

Header kinds: ``dispatch`` (rows/cols/dtype/version/budget_s/trace)
-> ``result`` (rows/cols/dtype/version/worker) or ``error``
(message + transient flag); ``hello``/``stats`` -> ``meta``;
``swap`` (version + npz payload) -> ``ok``. Both sides bound frames
at ``max_frame_bytes`` and reject violations loudly.

**Network chaos.** A seeded :class:`~serving.chaos.NetChaosPlan`
(grammar ``partition=/refuse=/lag=RATE[:MS]/kill_host=H@K`` — same
same-seed-bitwise-same-schedule contract as ``ChaosSpec``/``LoadSpec``)
injects at THIS layer, per ``(host, dispatch)`` cell: refuse fails the
connect, partition hangs then times out exactly like a blackholed
route, lag stretches the hop, and a scripted kill SIGKILLs the worker
process through the ``kill_cb`` hook — real failure modes on the real
wire, where the in-process ``ChaosFault`` plane could only pantomime
them.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import socket
import struct
import threading
import time

import numpy as np

from ..utils.trace import extract_context, format_context, get_tracer
from .chaos import (NET_LAG, NET_PARTITION, NET_REFUSE,
                    resolve_net_chaos)

#: Frame-protocol version tag (rides every header; bumped on
#: incompatible changes — the two sides of the wire may be different
#: builds, so compatibility is checked per frame, loudly).
FRAME_SCHEMA = "PODFRAME.v1"

#: Wire magic: 3 protocol bytes + the protocol generation. A frame not
#: opening with this is garbage (a stray client, a port collision) and
#: must fail loudly, never be length-interpreted.
FRAME_MAGIC = b"FW1\x01"

#: ``(magic, header_len, payload_len)`` prefix.
_PREFIX = struct.Struct("!4sII")

#: Default per-frame bound. A 4096-row float32 batch at width 1024 is
#: ~16 MiB; 64 MiB leaves headroom for weight announces while keeping
#: a corrupt length prefix from allocating gigabytes.
MAX_FRAME_BYTES = 64 * 1024 * 1024


class TransportError(ConnectionError):
    """A TRANSIENT wire failure (reset, refused, timeout, EOF
    mid-frame). A ``ConnectionError`` on purpose: the service's
    transient classifier and the router's circuit breaker treat it
    exactly like the in-process ``ChaosFault``/``ReplicaUnavailable``
    failures it stands in for — the requeue/retry machinery needs no
    socket-aware special case."""


class TransportRefused(TransportError):
    """Connect refused / connection reset — the worker is not
    answering RIGHT NOW (dead, restarting, or chaos-refused). Feeds
    the circuit breaker; the failover walk moves to a survivor."""


class TransportTimeout(TransportError):
    """The dispatch outlived its bounded timeout (a partitioned route,
    a wedged worker) or its deadline budget was exhausted before any
    I/O. The connection is dropped — a half-open exchange must never
    leak a stale response into the NEXT dispatch's read."""


class SyncTimeout(TransportTimeout):
    """A rejoin ``sync`` peer accepted the connection but never
    answered within its bounded budget — the wedged (dead-but-
    accepting) peer. Typed so the resync loop can COUNT it and move to
    the next peer instead of letting one wedged process stall a
    rejoining worker's pre-serve handshake indefinitely (ISSUE 18
    satellite: the rejoin path must come up in bounded time whatever
    one peer does)."""


class FrameError(ValueError):
    """A malformed frame: bad magic, truncated prefix/body, a length
    past ``max_frame_bytes``, or an undecodable header. PERMANENT and
    loud (``ValueError`` — the service's transient classifier refuses
    to retry it): a protocol violation is a bug, and retrying the same
    bytes can only fail the same way, slower."""


# ---------------------------------------------------------------------
# frame codec
# ---------------------------------------------------------------------

def _recv_exact(sock: socket.socket, n: int, what: str) -> bytes:
    """Read exactly ``n`` bytes or raise: a clean EOF before the first
    byte is a :class:`TransportError` (the peer closed between frames
    — ordinary worker death), EOF mid-``what`` is a :class:`FrameError`
    (a TRUNCATED frame — the protocol violation the tests pin)."""
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(n - got)
        except socket.timeout as e:
            raise TransportTimeout(
                f"timed out reading {what} ({got}/{n} bytes)") from e
        except OSError as e:
            raise TransportError(
                f"connection lost reading {what}: {e}") from e
        if not chunk:
            if got == 0 and what == "frame prefix":
                raise TransportError(
                    "peer closed the connection (EOF at frame "
                    "boundary)")
            raise FrameError(
                f"truncated frame: EOF after {got}/{n} bytes of {what}")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def write_frame(sock: socket.socket, header: dict,
                payload: bytes = b"",
                max_frame_bytes: int = MAX_FRAME_BYTES) -> None:
    """Serialize one frame onto ``sock``. The sender enforces the same
    bound the receiver does — an oversized batch must fail HERE, in
    the caller's stack, not as a peer-side rejection."""
    hdr = json.dumps({"schema": FRAME_SCHEMA, **header}).encode()
    if len(hdr) + len(payload) > max_frame_bytes:
        raise FrameError(
            f"frame of {len(hdr) + len(payload)} bytes exceeds the "
            f"{max_frame_bytes}-byte bound")
    try:
        sock.sendall(_PREFIX.pack(FRAME_MAGIC, len(hdr), len(payload))
                     + hdr + payload)
    except socket.timeout as e:
        raise TransportTimeout(f"timed out sending frame: {e}") from e
    except OSError as e:
        raise TransportError(f"connection lost sending frame: {e}") \
            from e


def read_frame(sock: socket.socket,
               max_frame_bytes: int = MAX_FRAME_BYTES) -> tuple:
    """Read one ``(header, payload)`` frame. Violations are loud and
    typed (:class:`FrameError`): bad magic, a length past the bound,
    truncation, or an undecodable header — never silently skipped,
    never length-interpreted garbage."""
    prefix = _recv_exact(sock, _PREFIX.size, "frame prefix")
    magic, hdr_len, pay_len = _PREFIX.unpack(prefix)
    if magic != FRAME_MAGIC:
        raise FrameError(
            f"bad frame magic {magic!r} (expected {FRAME_MAGIC!r}) — "
            "not a pod frame stream")
    if hdr_len + pay_len > max_frame_bytes:
        raise FrameError(
            f"frame of {hdr_len + pay_len} bytes exceeds the "
            f"{max_frame_bytes}-byte bound")
    hdr_bytes = _recv_exact(sock, hdr_len, "frame header")
    payload = _recv_exact(sock, pay_len, "frame payload") if pay_len \
        else b""
    try:
        header = json.loads(hdr_bytes)
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameError(f"undecodable frame header: {e}") from None
    if not isinstance(header, dict) \
            or header.get("schema") != FRAME_SCHEMA:
        raise FrameError(
            f"frame header schema {header.get('schema') if isinstance(header, dict) else header!r} "
            f"is not {FRAME_SCHEMA!r}")
    return header, payload


def pack_batch(X: np.ndarray) -> tuple[dict, bytes]:
    """``(header fields, payload)`` of one dispatch batch: raw
    C-contiguous bytes plus the shape/dtype the receiver needs to
    reconstruct it exactly."""
    X = np.ascontiguousarray(X)
    return ({"rows": int(X.shape[0]), "cols": int(X.shape[1]),
             "dtype": str(X.dtype)}, X.tobytes())


def unpack_batch(header: dict, payload: bytes) -> np.ndarray:
    """Inverse of :func:`pack_batch`; size disagreements between the
    header and the payload are a loud :class:`FrameError`."""
    try:
        rows, cols = int(header["rows"]), int(header["cols"])
        dtype = np.dtype(str(header["dtype"]))
    except (KeyError, TypeError, ValueError) as e:
        raise FrameError(f"malformed batch header: {e}") from None
    want = rows * cols * dtype.itemsize
    if want != len(payload):
        raise FrameError(
            f"batch payload of {len(payload)} bytes disagrees with "
            f"header ({rows}x{cols} {dtype} = {want} bytes)")
    return np.frombuffer(payload, dtype=dtype).reshape(rows, cols)


def pack_weights(params: dict, rff=None) -> bytes:
    """Serialize a weight set for the ``swap`` version-announce frame:
    one npz blob, params under ``p:<key>``, the RFF pair (when fused)
    under ``r:W``/``r:b``."""
    arrays = {f"p:{k}": np.asarray(v) for k, v in params.items()}
    if rff is not None:
        arrays["r:W"] = np.asarray(rff[0])
        arrays["r:b"] = np.asarray(rff[1])
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def unpack_weights(blob: bytes) -> tuple:
    """Inverse of :func:`pack_weights`: ``(params, rff_or_None)``."""
    try:
        with np.load(io.BytesIO(blob), allow_pickle=False) as z:
            params = {k[2:]: z[k] for k in z.files
                      if k.startswith("p:")}
            rff = ((z["r:W"], z["r:b"])
                   if "r:W" in z.files and "r:b" in z.files else None)
    except Exception as e:
        raise FrameError(f"undecodable weight payload: {e}") from None
    if not params:
        raise FrameError("weight payload carries no parameters")
    return params, rff


def weights_fingerprint(params: dict, rff=None,
                        version: int = 0) -> str:
    """Content fingerprint of one weight set under one version, the
    sync/announce-frame analogue of the PR 9 artifact
    ``host_fingerprint``: sha256 over the version number plus every
    array's name, dtype, shape, and raw bytes, in sorted name order.

    Computed over CONTENT, never over the npz blob — ``np.savez``
    embeds zip member timestamps, so byte-hashing the blob would make
    the same weights fingerprint differently across packings. Two
    workers serving the same weights under the same version agree on
    this string whatever process packed the frame; a byzantine peer
    serving forged weights under a stolen version cannot match an
    honest quorum's fingerprint without the honest bytes."""
    h = hashlib.sha256()
    h.update(f"v{int(version)}".encode())
    arrays = {f"p:{k}": np.asarray(v) for k, v in params.items()}
    if rff is not None:
        arrays["r:W"] = np.asarray(rff[0])
        arrays["r:b"] = np.asarray(rff[1])
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        h.update(f"|{name}:{a.dtype.str}:{a.shape}|".encode())
        h.update(a.tobytes())
    return h.hexdigest()


# ---------------------------------------------------------------------
# the transport interface
# ---------------------------------------------------------------------

class DispatchTransport:
    """One replica's dispatch boundary, as the router sees it:
    ``dispatch(X, version=, deadline=, trace_ctx=, record_timings=)``
    returns the logits or raises into the serving failure taxonomy
    (transient ``ConnectionError`` family -> circuit breaker +
    requeue; ``ValueError`` family -> permanent, fail fast). The
    deadline is an absolute ``perf_counter`` time — implementations
    derive their timeouts from what REMAINS of it."""

    def dispatch(self, X, version: int | None = None,
                 deadline: float | None = None, trace_ctx=None,
                 record_timings: bool = True):
        raise NotImplementedError

    def close(self) -> None:
        """Release any held connection (idempotent)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class InProcessTransport(DispatchTransport):
    """The extracted direct-call path: exactly the ``engine.predict``
    invocation ``FailoverRouter`` made before this seam existed —
    byte-identical behavior, which is what lets every pre-existing
    replica/chaos/control/rollout test pass unchanged. ``deadline``
    and ``trace_ctx`` are accepted and unused: an in-process call
    cannot be usefully bounded mid-dispatch, and its spans already
    share the caller's process-local tracer."""

    def __init__(self, engine):
        self.engine = engine

    def dispatch(self, X, version: int | None = None,
                 deadline: float | None = None, trace_ctx=None,
                 record_timings: bool = True):
        return self.engine.predict(X, version=version,
                                   record_timings=record_timings)


class SocketTransport(DispatchTransport):
    """TCP dispatch to one :class:`PodWorker` (module docstring).

    ``client`` (a :class:`PodClientEngine`, optional): the shared
    facade whose single-consumer ``pop_timings`` slot a timed dispatch
    stamps — how the wire-reported model version reaches request
    spans. ``chaos``/``host_index``/``kill_cb``: the seeded network
    fault plane (``serving.chaos.NetChaosPlan`` or spec string),
    consulted once per dispatch at THIS host's row; a scripted kill
    invokes ``kill_cb(host_index)`` (the bench passes a SIGKILL) and
    then dispatches into the dying worker — the real mid-batch death.

    Reconnect-with-backoff: a failed connect opens a fast-fail window
    (``backoff_ms`` doubling to ``backoff_cap_ms``) during which
    dispatches raise :class:`TransportRefused` immediately instead of
    paying a connect timeout each — the failover walk stays fast while
    a worker is down, and one successful connect resets the window.
    """

    def __init__(self, address, client=None, host_index: int = 0,
                 chaos=None, kill_cb=None,
                 connect_timeout_s: float = 1.0,
                 io_timeout_s: float = 10.0,
                 backoff_ms: float = 25.0,
                 backoff_cap_ms: float = 1000.0,
                 max_frame_bytes: int = MAX_FRAME_BYTES,
                 n_hosts: int | None = None):
        host, port = address
        self.address = (str(host), int(port))
        self.client = client
        self.host_index = int(host_index)
        self.connect_timeout_s = float(connect_timeout_s)
        self.io_timeout_s = float(io_timeout_s)
        self.backoff_s = backoff_ms / 1e3
        self.backoff_cap_s = backoff_cap_ms / 1e3
        self.max_frame_bytes = int(max_frame_bytes)
        self._plan = resolve_net_chaos(
            chaos, (self.host_index + 1 if n_hosts is None
                    else int(n_hosts)))
        self._kill_cb = kill_cb
        self._kills_fired: set[int] = set()
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()        # counters / backoff state
        self._io_lock = threading.Lock()     # one exchange per socket
        self._dispatches = 0
        self._connect_failures = 0
        self._connected_once = False
        self._next_attempt = 0.0
        self.reconnects = 0
        self.faults_injected = {"partition": 0, "refuse": 0, "lag": 0,
                                "kill": 0}

    # -- stats ---------------------------------------------------------
    @property
    def dispatches(self) -> int:
        with self._lock:
            return self._dispatches

    def stats(self) -> dict:
        with self._lock:
            return {"address": list(self.address),
                    "dispatches": self._dispatches,
                    "reconnects": self.reconnects,
                    "connect_failures": self._connect_failures,
                    "faults_injected": dict(self.faults_injected)}

    # -- connection management ----------------------------------------
    def _drop_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass  # already torn down; the drop is what matters
            self._sock = None

    def close(self) -> None:
        with self._io_lock:
            self._drop_locked()

    def _ensure_conn(self, timeout_s: float) -> socket.socket:
        """The held connection, or a fresh one — fast-failing inside
        the reconnect-backoff window so a dead worker costs the
        failover walk microseconds per pass."""
        if self._sock is not None:
            return self._sock
        now = time.perf_counter()
        with self._lock:
            if now < self._next_attempt:
                raise TransportRefused(
                    f"worker {self.address} in reconnect backoff "
                    f"({self._next_attempt - now:.3f}s left)")
        try:
            sock = socket.create_connection(
                self.address, timeout=min(timeout_s,
                                          self.connect_timeout_s))
        except OSError as e:
            with self._lock:
                self._connect_failures += 1
                delay = min(self.backoff_cap_s, self.backoff_s
                            * (2 ** min(self._connect_failures - 1, 8)))
                self._next_attempt = time.perf_counter() + delay
            raise TransportRefused(
                f"connect to worker {self.address} failed: {e}") from e
        with self._lock:
            self._connect_failures = 0
            self._next_attempt = 0.0
            if self._connected_once:
                # only a connect AFTER a drop is a reconnect — the
                # first lazy connect must not inflate the recovery
                # evidence the pod bench records
                self.reconnects += 1
            self._connected_once = True
        self._sock = sock
        return sock

    # -- chaos ---------------------------------------------------------
    def _inject(self, k: int, budget_s: float | None) -> None:
        """Consult the network-chaos plan for dispatch ``k`` — BEFORE
        any I/O, where a real route failure would land."""
        plan = self._plan
        if plan is None:
            return
        if self._kill_cb is not None:
            kill_at = plan.kill_at(self.host_index)
            with self._lock:
                # check-and-mark atomically: a concurrent dispatch
                # (the off-thread probe) must not double-fire the kill
                fire = (kill_at is not None and k >= kill_at
                        and kill_at not in self._kills_fired)
                if fire:
                    self._kills_fired.add(kill_at)
                    self.faults_injected["kill"] += 1
            if fire:
                # SIGKILL the worker, then dispatch into the corpse:
                # the send/read below fails with reset/EOF — the real
                # mid-batch worker death, not a simulated one
                self._kill_cb(self.host_index)
        role = plan.role(self.host_index, k)
        if role == NET_REFUSE:
            with self._lock:
                self.faults_injected["refuse"] += 1
            with self._io_lock:
                self._drop_locked()
            raise TransportRefused(
                f"net-chaos refused connect to worker {self.address} "
                f"(dispatch {k})")
        if role == NET_PARTITION:
            with self._lock:
                self.faults_injected["partition"] += 1
            with self._io_lock:
                # a partitioned route wedges the established
                # connection too: drop it so the next dispatch
                # reconnects instead of reading a dead socket
                self._drop_locked()
            stall = plan.partition_s if budget_s is None \
                else min(plan.partition_s, budget_s)
            time.sleep(max(0.0, stall))
            raise TransportTimeout(
                f"net-chaos partition: worker {self.address} "
                f"unreachable for {stall:.3f}s (dispatch {k})")
        if role == NET_LAG:
            with self._lock:
                self.faults_injected["lag"] += 1
            time.sleep(plan.lag_s)

    # -- dispatch ------------------------------------------------------
    def dispatch(self, X, version: int | None = None,
                 deadline: float | None = None, trace_ctx=None,
                 record_timings: bool = True):
        with self._lock:
            k = self._dispatches
            self._dispatches += 1
        budget = (None if deadline is None
                  else deadline - time.perf_counter())
        self._inject(k, budget)
        if deadline is not None:
            # re-read AFTER injection: a lag stall spends real budget,
            # and a stale pre-stall read would let work whose caller
            # already gave up cross the wire with a positive-looking
            # budget_s header
            budget = deadline - time.perf_counter()
        if budget is not None and budget <= 0:
            # the deadline contract crosses the hop: a request whose
            # caller already gave up must not spend wire time
            raise TransportTimeout(
                "deadline budget exhausted before dispatch")
        timeout = self.io_timeout_s if budget is None \
            else max(1e-3, min(self.io_timeout_s, budget))
        X = np.asarray(X, np.float32)
        single = X.ndim == 1
        if single:
            # same row/batch duality as engine.predict: a (d,) row
            # crosses the wire as (1, d) and comes back as a row
            X = X[None, :]
        hdr, payload = pack_batch(X)
        hdr.update(kind="dispatch", version=version, budget_s=budget)
        if trace_ctx is not None:
            hdr["trace"] = (trace_ctx if isinstance(trace_ctx, str)
                            else format_context(trace_ctx))
        t0 = time.perf_counter()
        # the exchange region holds the I/O lock across the socket
        # round-trip BY DESIGN: one in-flight exchange per connection
        # IS the frame protocol (a second thread's interleaved frames
        # would corrupt both exchanges); contention is the off-thread
        # shadow probe only, and the socket timeout bounds the hold
        self._io_lock.acquire()  # graftlint: disable=GL004 one exchange per connection is the frame-protocol invariant; interleaved frames would corrupt both exchanges, the socket timeout bounds the hold, and contention is the off-thread probe only
        try:
            sock = self._ensure_conn(timeout)
            try:
                sock.settimeout(timeout)
                write_frame(sock, hdr, payload, self.max_frame_bytes)
                resp, body = read_frame(sock, self.max_frame_bytes)
            except (TransportError, FrameError):
                # either way the exchange is dead: a half-open socket
                # (request sent, response unread) must never leak a
                # stale response into the next dispatch's read
                self._drop_locked()
                raise
        finally:
            self._io_lock.release()
        if resp.get("kind") == "error":
            msg = f"worker {self.address}: {resp.get('error')}"
            if resp.get("transient", True):
                raise TransportError(msg)
            raise RuntimeError(msg)
        if resp.get("kind") != "result":
            raise FrameError(
                f"unexpected response kind {resp.get('kind')!r} to a "
                "dispatch frame")
        out = unpack_batch(resp, body)
        if resp.get("ndim") == 1:
            # the worker's engine answered 1-D: restore the rank the
            # wire's (rows, cols) framing flattened into a column
            out = out.reshape(-1)
        if single:
            out = out[0]
        if record_timings and self.client is not None:
            # the wire-reported version (what the WORKER served), not
            # a client-side guess — post-swap spans must not lie
            self.client._timings = {
                "pad_s": 0.0,
                "dispatch_s": time.perf_counter() - t0,
                "bucket": int(resp.get("bucket", 0)),
                "version": resp.get("version"),
            }
        return out


# ---------------------------------------------------------------------
# the engine facade over a pod
# ---------------------------------------------------------------------

class PodClientEngine:
    """The engine interface the router/service see over a worker pod:
    static metadata (buckets/input_dim/num_classes) from the worker
    handshake, a single-consumer ``pop_timings`` slot the socket
    transports stamp, ``compile_count`` structurally zero (nothing on
    the client side ever compiles — the pod's zero-recompile story is
    per WORKER, read via ``stats`` frames), and a broadcasting
    ``swap_weights`` (the version-announce control frame): one agreed
    version number announced to every endpoint, so the pod swaps in
    agreement instead of each worker auto-numbering its own."""

    def __init__(self, endpoints, connect_timeout_s: float = 5.0,
                 max_frame_bytes: int = MAX_FRAME_BYTES):
        self.endpoints = [(str(h), int(p)) for h, p in endpoints]
        if not self.endpoints:
            raise ValueError("PodClientEngine needs >= 1 endpoint")
        self.connect_timeout_s = float(connect_timeout_s)
        self.max_frame_bytes = int(max_frame_bytes)
        self._timings: dict | None = None
        self.last_announce: dict | None = None
        #: optional per-endpoint announce observer, called as
        #: ``on_announce(endpoint, ok)`` after EACH announce attempt
        #: (inside the swap critical section). The scenario oracle
        #: uses it to script the mid-announce rejoin race — a worker
        #: restarting between two attempts of ONE announce.
        self.on_announce = None
        errs = []
        meta = None
        for ep in self.endpoints:
            try:
                meta, _ = self.control(ep, {"kind": "hello"})
                break
            except (TransportError, FrameError, OSError) as e:
                errs.append(f"{ep}: {e}")
        if meta is None:
            raise TransportRefused(
                "no pod worker answered the hello handshake: "
                + "; ".join(errs))
        self.buckets = tuple(int(b) for b in meta["buckets"])
        self.input_dim = int(meta["input_dim"])
        self.num_classes = int(meta["num_classes"])
        self._version = int(meta["version"])
        # announce epoch fence (ISSUE 18): a fresh client joins at the
        # pod's last-seen epoch so its first announce outranks every
        # announce the pod already heard; absent in a pre-epoch
        # worker's hello -> 0, byte-compatible both ways
        self._epoch = int(meta.get("epoch", 0))
        self._vlock = threading.Lock()
        # serializes whole announces (pick -> broadcast -> commit):
        # two concurrent swaps racing into one version number would
        # hand different weight sets the same identity — the exact
        # divergence the announce frame exists to prevent
        self._swap_lock = threading.Lock()

    # -- engine-interface surface -------------------------------------
    @property
    def version(self) -> int:
        with self._vlock:
            return self._version

    @property
    def compile_count(self) -> int:
        return 0  # the client never compiles; workers report their own

    def warmup(self) -> int:
        """Workers warmed themselves (artifact-loaded: nothing to
        warm). The client has no ladder to compile."""
        return 0

    def pop_timings(self) -> dict | None:
        t, self._timings = self._timings, None
        return t

    def predict(self, X, version=None, record_timings=True):
        """Deliberately unroutable: dispatch goes through the
        replicas' transports (the router fronts this facade). A direct
        call reaching here is a wiring bug worth failing loudly."""
        raise TypeError(
            "PodClientEngine does not dispatch; route through a "
            "FailoverRouter over SocketTransport replicas")

    # -- control frames ------------------------------------------------
    def control(self, endpoint, header: dict,
                payload: bytes = b"") -> tuple:
        """One short-lived control exchange (hello/stats/swap/stop) on
        its OWN connection — control must never interleave with an
        in-flight dispatch exchange on a transport's socket."""
        with socket.create_connection(
                endpoint, timeout=self.connect_timeout_s) as sock:
            sock.settimeout(self.connect_timeout_s)
            write_frame(sock, header, payload, self.max_frame_bytes)
            return read_frame(sock, self.max_frame_bytes)

    def worker_stats(self) -> list:
        """Per-endpoint ``stats`` metadata for the workers that
        answer; unreachable workers report ``{"dead": True}`` — the
        bench reads survivor ``compile_count`` through this."""
        out = []
        for ep in self.endpoints:
            try:
                meta, _ = self.control(ep, {"kind": "stats"})
                out.append(meta)
            except (TransportError, FrameError, OSError) as e:
                out.append({"endpoint": list(ep), "dead": True,
                            "error": str(e)})
        return out

    def swap_weights(self, params=None, rff=None,
                     version: int | None = None) -> int:
        """The version-announce broadcast: pick ONE new version number
        (explicit, or announced-live + 1), pack the weights once, and
        announce to every endpoint. Returns the agreed version once at
        least one worker acked; dead workers are skipped (their
        circuits are open anyway — a worker that rejoins catches up
        itself via the ``sync`` handshake: ``PodWorker(peers=...)``
        re-requests the agreed version from the pod on start, closing
        the announce gap without operator re-feeding, ISSUE 16).
        Raises :class:`TransportError` when NO worker
        acked — an announce nobody heard must not bump the client's
        notion of live.

        ISSUE 18 hardening, byte-compatible on clean paths: the
        announce header carries a MONOTONIC EPOCH (one per announce,
        fenced worker-side — a replayed or out-of-order announce is
        refused loudly) and the :func:`weights_fingerprint` of the
        announced content (a worker verifies the unpacked bytes match
        before installing). After a first pass with at least one ack,
        failed endpoints get ONE straggler re-pass: a worker that
        restarted mid-announce (the ``restart_during_announce`` race)
        is back by then and either installs the version or refuses it
        as stale because its rejoin sync already delivered it —
        either way the pod converges on one version without waiting
        for the next announce."""
        if params is None:
            raise ValueError(
                "pod swap_weights needs params (flip-only version= "
                "swaps need the cross-process registry, not yet here)")
        # the WHOLE announce is one critical section — version pick,
        # broadcast, commit. Released piecemeal, two concurrent swaps
        # would both pick live+1 and interleave their broadcasts:
        # each worker accepts whichever arrives first and rejects the
        # other, so the pod serves DIFFERENT weights under one agreed
        # number. Holding a lock across the socket round-trips is the
        # invariant, not an accident (the artifacts._EXPORT_LOCK
        # precedent): swaps are operator-cadence rare and never the
        # dispatch path — dispatch transports have their own sockets.
        self._swap_lock.acquire()  # graftlint: disable=GL004 announce atomicity IS the version-agreement contract (two interleaved broadcasts would serve different weights under one version number); swaps are operator-cadence, never the dispatch path, and dispatch rides separate sockets
        try:
            with self._vlock:
                v = (self._version + 1 if version is None
                     else int(version))
            epoch = getattr(self, "_epoch", 0) + 1
            blob = pack_weights(params, rff)
            header = {"kind": "swap", "version": v, "epoch": epoch,
                      "fingerprint": weights_fingerprint(params, rff,
                                                         v)}
            hook = getattr(self, "on_announce", None)
            acks, failed = 0, []
            for ep in self.endpoints:
                ok = False
                try:
                    resp, _ = self.control(ep, header, blob)
                except (TransportError, FrameError, OSError) as e:
                    failed.append((ep, f"{ep}: {e}"))
                else:
                    if resp.get("kind") == "ok":
                        acks += 1
                        ok = True
                    else:
                        failed.append((ep,
                                       f"{ep}: {resp.get('error')}"))
                if hook is not None:
                    hook(ep, ok)
            if not acks:
                raise TransportError(
                    f"version announce v{v} reached no worker: "
                    + "; ".join(msg for _, msg in failed))
            if failed:
                # the straggler re-pass (never when NOBODY acked: a
                # fully dark pod is the caller's error above). One
                # bounded retry per first-pass failure; a still-dead
                # endpoint keeps its original failure entry
                still = []
                for ep, msg in failed:
                    try:
                        resp, _ = self.control(ep, header, blob)
                    except (TransportError, FrameError, OSError):
                        still.append((ep, msg))
                        continue
                    if resp.get("kind") == "ok":
                        acks += 1
                    else:
                        still.append((ep,
                                      f"{ep}: {resp.get('error')}"))
                failed = still
            with self._vlock:
                self._version = v
            self._epoch = epoch
            self.last_announce = {"version": v, "acks": acks,
                                  "failures": [msg for _, msg
                                               in failed]}
            return v
        finally:
            self._swap_lock.release()


# ---------------------------------------------------------------------
# the worker side
# ---------------------------------------------------------------------

class PodWorker:
    """One serving process of the pod: accepts frame connections and
    serves ``dispatch``/``hello``/``stats``/``swap``/``stop`` frames
    over the engine it hosts (the bench loads a PR 9 AOT artifact, so
    the worker is ready in load-milliseconds with zero compiles; tests
    host stubs). One handler thread per connection — the router holds
    one long-lived dispatch connection per replica, control frames
    arrive on their own short-lived ones.

    With an enabled ``tracer``, every served dispatch lands one
    ``"pod_dispatch"`` span under the TRACECTX the frame carried —
    the worker's side of the one-trace-across-the-hop contract (the
    router-side ``"request"`` span count stays exactly one per
    request; these are its remote children)."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 worker_id: int = 0, tracer=None,
                 max_frame_bytes: int = MAX_FRAME_BYTES, peers=None,
                 forge_sync=None):
        """``peers`` (ISSUE 16, the announce-gap fix): pod endpoints
        this worker re-requests the agreed weight version from on
        :meth:`start`. A worker rejoining after SIGKILL restarts from
        its checkpoint — STALE weights under a stale version — and
        version announces only reach workers alive at announce time,
        so without the handshake the rejoiner serves old weights under
        the pod's name until an operator re-feeds it. With peers set,
        ``start`` syncs BEFORE accepting connections: the worker asks
        each peer (``sync`` frame), installs the newest version found,
        and only then serves.

        ``forge_sync`` (ISSUE 18, test-only byzantine mode): when set
        to an integer version, this worker answers ``sync`` requests
        with FORGED weights — same-shape garbage drawn from a PRNG
        keyed on the forged version, claimed under that version. The
        scenario fuzzer uses it to model a byzantine sync peer; honest
        deployments never set it."""
        self.engine = engine
        self.worker_id = int(worker_id)
        self.peers = [(str(h), int(p)) for h, p in (peers or [])]
        self.forge_sync = None if forge_sync is None else int(forge_sync)
        self.resyncs = 0
        self.sync_timeouts = 0
        self.stale_refused = 0
        self.forge_rejected = 0
        # the announce fence (ISSUE 18): highest announce epoch this
        # worker has accepted (or adopted via rejoin sync), and the
        # content fingerprint it installed under it
        self._epoch = 0
        self._last_fingerprint = None
        self.tracer = tracer if tracer is not None else get_tracer()
        self.max_frame_bytes = int(max_frame_bytes)
        # capability check once, like ServingService does: whether the
        # hosted engine's predict takes version=/record_timings= (a
        # test stub may take neither)
        import inspect
        try:
            sig = inspect.signature(engine.predict).parameters
            self._predict_version = "version" in sig
            self._predict_untimed = "record_timings" in sig
        except (TypeError, ValueError):
            self._predict_version = False
            self._predict_untimed = False
        self._listener = socket.create_server((host, int(port)))
        self.host, self.port = self._listener.getsockname()[:2]
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._conns: set = set()
        self._lock = threading.Lock()
        self.dispatches = 0
        self.swaps = 0
        self.errors = 0
        self.frame_errors = 0

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "PodWorker":
        if self.peers:
            # sync BEFORE serve: a rejoiner must not answer dispatches
            # with checkpoint-stale weights while the agreed version
            # is one frame away
            self.resync()
        t = threading.Thread(target=self._accept_loop,
                             name=f"pod-worker-{self.worker_id}",
                             daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            # shutdown BEFORE close: on Linux, closing a listening
            # socket does not wake a thread blocked in accept() —
            # shutdown does (the accepter sees EINVAL and exits)
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass  # never connected / already down
        try:
            self._listener.close()
        except OSError:
            pass  # listener already down — stop is idempotent
        with self._lock:
            conns = list(self._conns)
            threads = list(self._threads)
        for conn in conns:
            # wake every handler blocked in read_frame: a stop must
            # not wait out idle keep-alive connections
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass  # already closing on its own
        for t in threads:
            t.join(timeout=5.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _sync_one(self, ep, timeout_s: float) -> tuple:
        """One peer's ``sync`` exchange on its own short-lived
        connection, bounded by ``timeout_s``. A peer that accepted the
        connection but never answers within the budget — the wedged
        dead-but-accepting process — raises :class:`SyncTimeout` so
        the resync loop can COUNT it and move on instead of stalling
        the rejoiner's pre-serve handshake behind one bad peer."""
        try:
            with socket.create_connection(ep, timeout=timeout_s) as sock:
                sock.settimeout(timeout_s)
                write_frame(sock, {"kind": "sync"})
                return read_frame(sock, self.max_frame_bytes)
        except socket.timeout as e:
            raise SyncTimeout(
                f"sync peer {ep[0]}:{ep[1]} accepted but never "
                f"answered within {timeout_s:.1f}s") from e
        except TransportTimeout as e:
            raise SyncTimeout(
                f"sync peer {ep[0]}:{ep[1]} timed out mid-frame: "
                f"{e}") from e

    def resync(self, timeout_s: float = 5.0) -> int | None:
        """Re-request the pod's agreed weight version from ``peers``.

        Asks every peer (each on its own short-lived connection, the
        control-frame discipline), then installs the NEWEST version
        found when it is newer than what this worker serves — newest,
        not first-answering, because a pod mid-announce has peers on
        two versions and joining the older side would re-open the gap
        one announce later. Unreachable or weightless peers are
        skipped: a lone survivor restarting a dead pod has nobody to
        ask and must still come up.

        ``timeout_s`` is the TOTAL handshake budget, not a per-peer
        one: each peer gets at most the budget's remainder, a wedged
        peer raises (and counts) :class:`SyncTimeout` instead of
        hanging, and a spent budget ends the loop — the rejoiner comes
        up in bounded time whatever its peers do.

        Byzantine hardening (ISSUE 18), in trust order: a reply
        carrying a ``fingerprint`` that does not hash its own payload
        is dropped outright (a corrupt or lazily-forged peer); then,
        when a strict majority of the fingerprinted replies agree on
        one fingerprint, every disagreeing fingerprinted reply is
        dropped too — a self-consistent forger hashes its own garbage
        correctly, so only quorum unmasks it. Without a strict
        majority (two honest peers mid-announce legitimately disagree)
        nothing is dropped and the newest ``(version, epoch)`` wins as
        before. Legacy replies without fingerprints never enter the
        quorum. Returns the installed version, or None when nothing
        newer was found."""
        my_v = int(getattr(self.engine, "version", 0))
        deadline = time.monotonic() + float(timeout_s)
        replies = []  # (version, epoch, fingerprint|None, payload)
        for ep in self.peers:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break  # budget spent: serve with the best found so far
            try:
                resp, payload = self._sync_one(ep, remaining)
            except SyncTimeout:
                with self._lock:
                    self.sync_timeouts += 1
                continue  # wedged peer: ask the next one
            except (TransportError, FrameError, OSError):
                continue  # dead/refusing peer: ask the next one
            if resp.get("kind") != "weights":
                continue  # peer hosts nothing exportable
            v = int(resp.get("version", 0))
            epoch = int(resp.get("epoch", 0))
            fp = resp.get("fingerprint")
            if fp is not None:
                params, rff = unpack_weights(payload)
                if weights_fingerprint(params, rff, v) != str(fp):
                    # the reply disowns its own payload: corrupt wire
                    # or a forger too lazy to re-hash — drop it loudly
                    with self._lock:
                        self.forge_rejected += 1
                    continue
                fp = str(fp)
            replies.append((v, epoch, fp, payload))
        fingerprinted = [r for r in replies if r[2] is not None]
        if fingerprinted:
            tally = {}
            for _, _, fp, _ in fingerprinted:
                tally[fp] = tally.get(fp, 0) + 1
            top_fp = max(tally, key=lambda k: (tally[k], k))
            if tally[top_fp] * 2 > len(fingerprinted):
                # strict majority: the pod agrees on one content hash,
                # so a self-consistent minority reply is a forgery
                # (or hopelessly stale) — reject, count, move on
                rejected = [r for r in fingerprinted if r[2] != top_fp]
                if rejected:
                    with self._lock:
                        self.forge_rejected += len(rejected)
                replies = [r for r in replies
                           if r[2] is None or r[2] == top_fp]
        best = None
        for v, epoch, _, payload in replies:
            if v <= my_v:
                continue
            if best is None or (v, epoch) > (best[0], best[1]):
                best = (v, epoch, payload)
        if best is None:
            return None
        best_v, best_epoch, best_payload = best
        params, rff = unpack_weights(best_payload)
        v = self.engine.swap_weights(params, rff=rff, version=best_v)
        with self._lock:
            self.resyncs += 1
            if best_epoch > self._epoch:
                # adopt the pod's announce epoch: the fence must hold
                # across a rejoin, or the next stale announce would
                # look fresh to this worker
                self._epoch = best_epoch
        return int(v)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed: shutdown
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            with self._lock:
                self._conns.add(conn)
                # prune finished handlers as connections arrive:
                # control frames open one short-lived connection
                # each, and a long-lived worker polled for stats
                # would otherwise grow one dead Thread object per
                # poll, forever. Under the lock: stop() snapshots
                # this list concurrently
                self._threads = [th for th in self._threads
                                 if th.is_alive()]
                self._threads.append(t)
            t.start()

    # -- the serve loop ------------------------------------------------
    def _serve_conn(self, conn: socket.socket) -> None:
        """One connection's request/response loop until EOF. A
        malformed frame answers a loud error frame and DROPS the
        connection (resynchronizing inside a corrupt byte stream is
        guesswork); handler failures answer typed error frames and the
        loop continues — a worker thread must never die silently."""
        try:
            self._serve_conn_loop(conn)
        finally:
            with self._lock:
                self._conns.discard(conn)

    def _serve_conn_loop(self, conn: socket.socket) -> None:
        with conn:
            while not self._stop.is_set():
                try:
                    header, payload = read_frame(conn,
                                                 self.max_frame_bytes)
                except TransportError:
                    return  # peer closed / reset: normal end of stream
                except FrameError as e:
                    with self._lock:
                        self.frame_errors += 1
                    try:
                        write_frame(conn, {
                            "kind": "error", "error": str(e),
                            "transient": False})
                    except (TransportError, FrameError):
                        pass  # peer is gone; the count above stands
                    return
                try:
                    resp, body = self._handle(header, payload)
                except Exception as e:
                    with self._lock:
                        self.errors += 1
                    resp, body = {"kind": "error",
                                  "error": f"{type(e).__name__}: {e}",
                                  "transient": not isinstance(
                                      e, (ValueError, TypeError,
                                          KeyError))}, b""
                try:
                    write_frame(conn, resp, body, self.max_frame_bytes)
                except (TransportError, FrameError):
                    return  # peer gone mid-response; nothing to save
                if header.get("kind") == "stop":
                    self._stop.set()
                    for sock in (self._listener,):
                        try:
                            sock.shutdown(socket.SHUT_RDWR)
                        except OSError:
                            pass  # never connected
                        try:
                            sock.close()
                        except OSError:
                            pass  # accept loop exits either way
                    return

    def _meta(self) -> dict:
        with self._lock:
            served = self.dispatches
            swaps = self.swaps
            errors = self.errors
            resyncs = self.resyncs
            sync_timeouts = self.sync_timeouts
            stale_refused = self.stale_refused
            forge_rejected = self.forge_rejected
            epoch = self._epoch
        return {
            "kind": "meta", "worker": self.worker_id,
            "epoch": epoch,
            "resyncs": resyncs,
            "sync_timeouts": sync_timeouts,
            "stale_refused": stale_refused,
            "forge_rejected": forge_rejected,
            "buckets": [int(b) for b in self.engine.buckets],
            "input_dim": int(self.engine.input_dim),
            "num_classes": int(self.engine.num_classes),
            "version": int(getattr(self.engine, "version", 0)),
            "compile_count": int(getattr(self.engine,
                                         "compile_count", 0)),
            "dispatches": served, "swaps": swaps, "errors": errors,
            "pid": os.getpid(),
        }

    def _handle(self, header: dict, payload: bytes) -> tuple:
        kind = header.get("kind")
        if kind in ("hello", "stats", "ping"):
            return self._meta(), b""
        if kind == "stop":
            return {"kind": "ok"}, b""
        if kind == "swap":
            return self._handle_swap(header, payload)
        if kind == "sync":
            return self._handle_sync()
        if kind == "dispatch":
            return self._handle_dispatch(header, payload)
        raise FrameError(f"unknown frame kind {kind!r}")

    def _handle_swap(self, header: dict, payload: bytes) -> tuple:
        """The version-announce control frame: install the announced
        weights under the ANNOUNCED version number and make them live
        — every worker of the pod lands on the same number, so
        post-swap dispatches report one agreed ``model_version``
        whichever worker serves them.

        Hardened (ISSUE 18), optional-field byte-compatible: an
        announce carrying an ``epoch`` at or below the last accepted
        one is REFUSED loudly (a replayed/stale announce installing
        old weights over new is exactly the announce-race corruption;
        the refusal is a permanent typed error, never a silent drop),
        and an announce carrying a ``fingerprint`` is verified against
        the unpacked content before anything installs. Frames from a
        pre-epoch client carry neither field and behave as before."""
        version = header.get("version")
        if not isinstance(version, int):
            raise FrameError(
                f"swap frame needs an integer version, got {version!r}")
        epoch = header.get("epoch")
        if epoch is not None:
            epoch = int(epoch)
            with self._lock:
                stale = epoch <= self._epoch
                if stale:
                    self.stale_refused += 1
                    last = self._epoch
            if stale:
                return {"kind": "error", "transient": False,
                        "error": f"stale announce epoch {epoch} "
                                 f"refused: worker {self.worker_id} "
                                 f"already accepted epoch {last} — "
                                 "re-announce from the live client"
                        }, b""
        params, rff = unpack_weights(payload)
        claimed = header.get("fingerprint")
        if claimed is not None:
            actual = weights_fingerprint(params, rff, version)
            if actual != str(claimed):
                with self._lock:
                    self.forge_rejected += 1
                return {"kind": "error", "transient": False,
                        "error": f"announce v{version} fingerprint "
                                 f"mismatch: header claims "
                                 f"{str(claimed)[:12]}.., payload "
                                 f"hashes {actual[:12]}.. — refusing "
                                 "to install unverifiable weights"
                        }, b""
        v = self.engine.swap_weights(params, rff=rff, version=version)
        with self._lock:
            self.swaps += 1
            if epoch is not None:
                self._epoch = epoch
            if claimed is not None:
                self._last_fingerprint = str(claimed)
        return {"kind": "ok", "version": int(v),
                "worker": self.worker_id}, b""

    def _handle_sync(self) -> tuple:
        """A rejoining peer's weight request (:meth:`resync`): serve
        the LIVE weights under their version so the rejoiner lands on
        the pod's agreed state without operator involvement. A worker
        whose engine exports no weight pytree answers its meta instead
        — the rejoiner skips it and asks the next peer.

        A worker in ``forge_sync`` byzantine mode (test-only) serves
        same-shape garbage under the forged version instead: weights
        drawn from a PRNG keyed on that version, so the forgery is
        deterministic per scenario and structurally indistinguishable
        from an honest reply without content verification.

        Hardened replies (ISSUE 18) also carry the announce ``epoch``
        and a content ``fingerprint`` computed LIVE over the served
        payload. The forger computes a SELF-CONSISTENT fingerprint
        over its forged weights — content hashing alone cannot unmask
        it, which is exactly why :meth:`resync` also runs the
        strict-majority quorum over fingerprints."""
        params = getattr(self.engine, "params", None)
        if params is None:
            return self._meta(), b""
        rff = getattr(self.engine, "rff", None)
        version = int(getattr(self.engine, "version", 0))
        if self.forge_sync is not None:
            params, version = self._forge_params(params), self.forge_sync
        blob = pack_weights(params, rff)
        with self._lock:
            epoch = self._epoch
        return {"kind": "weights",
                "version": version,
                "epoch": epoch,
                "fingerprint": weights_fingerprint(params, rff, version),
                "worker": self.worker_id}, blob

    def _forge_params(self, params) -> dict:
        rng = np.random.RandomState(int(self.forge_sync) % (2 ** 32))
        return {k: rng.standard_normal(np.shape(v)).astype(
                    np.asarray(v).dtype)
                for k, v in params.items()}

    def _handle_dispatch(self, header: dict, payload: bytes) -> tuple:
        budget = header.get("budget_s")
        if budget is not None and float(budget) <= 0:
            # the deadline crossed the wire: refuse work nobody waits
            # for (transient — the router sheds/retries, not us)
            return {"kind": "error", "transient": True,
                    "error": "deadline budget exhausted at the "
                             "worker"}, b""
        X = unpack_batch(header, payload)
        version = header.get("version")
        t0 = time.perf_counter()
        kw = {}
        if self._predict_version:
            kw["version"] = version
        if self._predict_untimed:
            # out-of-band: concurrent connections (router dispatch +
            # an off-thread probe) must not race the hosted engine's
            # single-consumer timing slot
            kw["record_timings"] = False
        out = self.engine.predict(X, **kw)
        dur = time.perf_counter() - t0
        served_ver = (int(version) if version is not None
                      else int(getattr(self.engine, "version", 0)))
        with self._lock:
            self.dispatches += 1
        if self.tracer.enabled:
            ctx_raw = header.get("trace")
            if ctx_raw:
                # the TRACECTX consumer: this span joins the
                # router-side request trace — same trace id across
                # the process boundary, parented under the dispatch
                ctx = extract_context(ctx_raw)
                self.tracer.emit(
                    "pod_dispatch", ctx.trace_id, t0, dur,
                    parent_id=ctx.parent_id,
                    attrs={"worker": self.worker_id,
                           "rows": int(X.shape[0]),
                           "model_version": served_ver})
        resp = {"kind": "result", "worker": self.worker_id,
                "version": served_ver,
                "rows": int(out.shape[0]),
                "cols": int(out.shape[1]) if out.ndim == 2 else 1,
                # carry the rank: a hosted engine returning 1-D
                # predictions must come back 1-D on the client, or
                # the two transports stop being shape-equivalent
                "ndim": int(out.ndim),
                "dtype": str(out.dtype)}
        # .tobytes() serializes any layout C-ordered — engines return
        # host ndarrays, so no extra conversion (or device sync) here
        return resp, out.tobytes()


def worker_main(port_file: str, artifact_dir: str | None = None,
                checkpoint: str | None = None, host: str = "127.0.0.1",
                worker_id: int = 0, trace_dir: str | None = None,
                buckets=None, engine=None, peers=None) -> None:
    """Subprocess entry: host one pod worker until killed or told to
    ``stop``. ``artifact_dir`` loads a PR 9 AOT artifact
    (``ServingEngine.from_artifact`` — ready in load-milliseconds,
    ``compile_count`` 0); ``engine`` injects one directly (tests).
    The bound port is published by writing ``port_file`` ATOMICALLY
    (tmp + rename) once the listener is up — the spawner polls it.
    ``trace_dir`` streams the worker's spans through a rotating JSONL
    writer (O(1) memory; parts named ``podworker<id>-*``), which is
    how the bench reads the cross-process trace back. ``peers`` lists
    pod endpoints to re-request the agreed weight version from before
    serving (the rejoin handshake — pass the surviving workers when
    respawning a killed one)."""
    tracer = None
    if trace_dir:
        from ..utils.trace import RotatingJsonlWriter, Tracer
        tracer = Tracer(writer=RotatingJsonlWriter(
            trace_dir, prefix=f"podworker{worker_id}"))
    if engine is None:
        from .engine import ServingEngine
        if artifact_dir:
            engine = ServingEngine.from_artifact(artifact_dir,
                                                 checkpoint=checkpoint)
        elif checkpoint:
            engine = ServingEngine.load(
                checkpoint,
                **({} if buckets is None
                   else {"buckets": tuple(buckets)}))
            engine.warmup()
        else:
            raise ValueError(
                "worker_main needs artifact_dir, checkpoint, or "
                "engine=")
    worker = PodWorker(engine, host=host, worker_id=worker_id,
                       tracer=tracer, peers=peers)
    worker.start()
    tmp = f"{port_file}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{worker.port}\n")
    os.replace(tmp, port_file)
    # serve until SIGKILLed (the chaos plane's exit) or stopped by a
    # control frame; the accept thread is the worker's lifetime
    while not worker._stop.wait(0.2):
        pass
