from .reporting import (Logger, check_significance, format_trace_summary,
                        load_results, print_acc, print_time,
                        trace_stage_summary)
from .trace import (NULL_TRACER, TRACE_SCHEMA, Tracer, configure,
                    get_tracer, read_jsonl)

__all__ = [
    "Logger",
    "NULL_TRACER",
    "TRACE_SCHEMA",
    "Tracer",
    "check_significance",
    "configure",
    "format_trace_summary",
    "get_tracer",
    "load_results",
    "print_acc",
    "print_time",
    "read_jsonl",
    "trace_stage_summary",
]
