from .reporting import Logger, check_significance, load_results, print_acc, print_time

__all__ = [
    "Logger",
    "check_significance",
    "load_results",
    "print_acc",
    "print_time",
]
