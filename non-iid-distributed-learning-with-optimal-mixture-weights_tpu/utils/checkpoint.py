"""Optional model checkpointing (orbax-backed, plain-pickle fallback).

The reference persists ONLY the final metric matrices
(``/root/reference/exp.py:132-143``) — no model state, no resume. This
module adds the optional capability the SURVEY §5 plan called for:
saving ``(global_params, mixture_weights, round)`` per algorithm so a
trained model can be reloaded for inference or a run can be resumed.
Orbax is used when importable (the standard JAX checkpointing library,
async-safe, device-aware); otherwise a plain pickle of host arrays.
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np


def _to_host(tree):
    import jax

    # leave plain-Python scalars/strings (e.g. 'server_opt_kind') alone:
    # a 0-d numpy str array would round-trip poorly through orbax
    return jax.tree.map(
        lambda x: x if isinstance(x, (str, bool, int, float))
        else np.asarray(x),
        tree,
    )


def save_checkpoint(path: str, params, p=None, round_idx: int | None = None,
                    extra: dict | None = None, rff=None,
                    feature_dtype=None) -> str:
    """Save algorithm state under ``path`` (a directory). Returns the
    path actually written.

    ``rff`` is the setup's ``(W, b)`` feature-map draw. Model params
    alone can only score PRE-MAPPED features; the draw is what makes the
    checkpoint self-contained for serving raw inputs
    (``serving.ServingEngine.load`` fuses it into the predictor).
    ``feature_dtype`` marks a narrow-feature training run
    (``prepare_setup(feature_dtype=...)``): without the marker, serving
    would silently score float32 features against a head trained on
    narrow ones.
    """
    state: dict[str, Any] = {"params": _to_host(params)}
    if p is not None:
        state["p"] = np.asarray(p)
    if round_idx is not None:
        state["round"] = int(round_idx)
    if rff is not None:
        state["rff_W"] = np.asarray(rff[0])
        state["rff_b"] = np.asarray(rff[1])
    if feature_dtype is not None:
        # stored as the canonical name string ('bfloat16' — np.dtype
        # resolves numpy/jax scalar types, dtype objects, and names);
        # the serving side feeds it back through astype
        state["feature_dtype"] = str(np.dtype(feature_dtype))
    if extra:
        # e.g. optimizer-state leaf tuples ('p_opt'/'server_opt' from
        # return_state=True) — host-convert like params
        state.update({k: _to_host(v) for k, v in extra.items()})
    os.makedirs(path, exist_ok=True)
    # Each save leaves exactly ONE layout under `path`: load_checkpoint
    # prefers an orbax dir over state.pkl, so a layout left behind by an
    # EARLIER save (orbax then, pickle now — or a partial orbax tree
    # from an interrupted attempt) would silently shadow the fresh
    # state. Serving makes that load-bearing: a stale shadowed
    # checkpoint means wrong params (or a missing rff draw) served with
    # no error.
    try:
        import orbax.checkpoint as ocp

        ckpt = os.path.join(os.path.abspath(path), "orbax")
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(ckpt, state, force=True)
        try:
            os.remove(os.path.join(path, "state.pkl"))
        except OSError:
            pass
        return ckpt
    except Exception:
        import shutil

        # stale-orbax removal BEFORE the pickle lands: load_checkpoint
        # prefers an orbax dir, so (a) if the removal fails this save
        # fails loudly instead of looking successful while shadowed,
        # and (b) a crash between the two steps leaves NO checkpoint
        # (loud FileNotFoundError on load) rather than the stale one
        # silently serving the earlier round's params
        stale = os.path.join(os.path.abspath(path), "orbax")
        shutil.rmtree(stale, ignore_errors=True)
        if os.path.isdir(stale):
            raise RuntimeError(
                f"stale orbax layout at {stale} could not be removed "
                "and would shadow the pickle fallback on load; remove "
                "it manually")
        out = os.path.join(path, "state.pkl")
        with open(out, "wb") as f:
            pickle.dump(state, f)
        return out


def load_checkpoint(path: str) -> dict:
    """Load a checkpoint written by :func:`save_checkpoint` (either
    layout)."""
    orbax_dir = os.path.join(path, "orbax")
    if os.path.isdir(orbax_dir):
        import orbax.checkpoint as ocp

        with ocp.PyTreeCheckpointer() as ckptr:
            return ckptr.restore(os.path.abspath(orbax_dir))
    pkl = os.path.join(path, "state.pkl")
    if os.path.exists(pkl):
        with open(pkl, "rb") as f:
            return pickle.load(f)
    if os.path.isdir(path) and os.path.exists(
        os.path.join(path, "_CHECKPOINT_METADATA")
    ):
        # a bare orbax dir was passed directly
        import orbax.checkpoint as ocp

        with ocp.PyTreeCheckpointer() as ckptr:
            return ckptr.restore(os.path.abspath(path))
    raise FileNotFoundError(f"no checkpoint under {path}")
