"""Optional model checkpointing (orbax-backed, plain-pickle fallback).

The reference persists ONLY the final metric matrices
(``/root/reference/exp.py:132-143``) — no model state, no resume. This
module adds the optional capability the SURVEY §5 plan called for:
saving ``(global_params, mixture_weights, round)`` per algorithm so a
trained model can be reloaded for inference or a run can be resumed.
Orbax is used when importable (the standard JAX checkpointing library,
async-safe, device-aware); otherwise a plain pickle of host arrays.
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np


class CheckpointError(RuntimeError):
    """A checkpoint exists at ``path`` but could not be restored —
    truncated/corrupt pickle, a broken orbax tree, or a state dict
    missing required entries. Typed (instead of whatever bare
    traceback the storage layer happened to raise) so serving and
    resume flows can tell "this checkpoint is damaged, name the file"
    apart from programming errors; a missing checkpoint stays
    ``FileNotFoundError``."""

    def __init__(self, path: str, detail: str):
        self.path = path
        super().__init__(
            f"checkpoint at {path} could not be loaded: {detail}")


def _to_host(tree):
    import jax

    # leave plain-Python scalars/strings (e.g. 'server_opt_kind') alone:
    # a 0-d numpy str array would round-trip poorly through orbax
    return jax.tree.map(
        lambda x: x if isinstance(x, (str, bool, int, float))
        else np.asarray(x),
        tree,
    )


def save_checkpoint(path: str, params, p=None, round_idx: int | None = None,
                    extra: dict | None = None, rff=None,
                    feature_dtype=None, reputation=None,
                    defense_state: dict | None = None) -> str:
    """Save algorithm state under ``path`` (a directory). Returns the
    path actually written.

    ``rff`` is the setup's ``(W, b)`` feature-map draw. Model params
    alone can only score PRE-MAPPED features; the draw is what makes the
    checkpoint self-contained for serving raw inputs
    (``serving.ServingEngine.load`` fuses it into the predictor).
    ``feature_dtype`` marks a narrow-feature training run
    (``prepare_setup(feature_dtype=...)``): without the marker, serving
    would silently score float32 features against a head trained on
    narrow ones. ``reputation`` is the final per-client trust vector of
    a rep-defended run (``res['reputation']`` under
    ``return_state=True``): resuming through a checkpoint without it
    restarts every client — including a quarantined attacker — at full
    trust. ``defense_state`` carries the remaining cross-round defense
    carry as a small dict of scalars/arrays — today the
    ``quarantine:auto`` threshold estimate (``{'zq': res['zq']}``);
    without it a resumed auto-threshold run re-tunes from the Z=5
    start. (``reputation`` predates this dict and stays a top-level
    key for checkpoint compatibility.)
    """
    state: dict[str, Any] = {"params": _to_host(params)}
    if p is not None:
        state["p"] = np.asarray(p)
    if round_idx is not None:
        state["round"] = int(round_idx)
    if reputation is not None:
        state["reputation"] = np.asarray(reputation, np.float32)
    if defense_state:
        state["defense_state"] = {
            k: np.asarray(v, np.float32)
            for k, v in defense_state.items()}
    if rff is not None:
        state["rff_W"] = np.asarray(rff[0])
        state["rff_b"] = np.asarray(rff[1])
    if feature_dtype is not None:
        # stored as the canonical name string ('bfloat16' — np.dtype
        # resolves numpy/jax scalar types, dtype objects, and names);
        # the serving side feeds it back through astype
        state["feature_dtype"] = str(np.dtype(feature_dtype))
    if extra:
        # e.g. optimizer-state leaf tuples ('p_opt'/'server_opt' from
        # return_state=True) — host-convert like params
        state.update({k: _to_host(v) for k, v in extra.items()})
    os.makedirs(path, exist_ok=True)
    # Each save leaves exactly ONE layout under `path`: load_checkpoint
    # prefers an orbax dir over state.pkl, so a layout left behind by an
    # EARLIER save (orbax then, pickle now — or a partial orbax tree
    # from an interrupted attempt) would silently shadow the fresh
    # state. Serving makes that load-bearing: a stale shadowed
    # checkpoint means wrong params (or a missing rff draw) served with
    # no error.
    try:
        import orbax.checkpoint as ocp

        ckpt = os.path.join(os.path.abspath(path), "orbax")
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(ckpt, state, force=True)
        try:
            os.remove(os.path.join(path, "state.pkl"))
        except OSError:
            pass
        return ckpt
    except Exception:
        import shutil

        # stale-orbax removal BEFORE the pickle lands: load_checkpoint
        # prefers an orbax dir, so (a) if the removal fails this save
        # fails loudly instead of looking successful while shadowed,
        # and (b) a crash between the two steps leaves NO checkpoint
        # (loud FileNotFoundError on load) rather than the stale one
        # silently serving the earlier round's params
        stale = os.path.join(os.path.abspath(path), "orbax")
        shutil.rmtree(stale, ignore_errors=True)
        if os.path.isdir(stale):
            raise RuntimeError(
                f"stale orbax layout at {stale} could not be removed "
                "and would shadow the pickle fallback on load; remove "
                "it manually")
        out = os.path.join(path, "state.pkl")
        with open(out, "wb") as f:
            pickle.dump(state, f)
        return out


def load_checkpoint(path: str) -> dict:
    """Load a checkpoint written by :func:`save_checkpoint` (either
    layout).

    A checkpoint that EXISTS but cannot be restored — truncated or
    corrupt ``state.pkl``, broken orbax tree — raises
    :class:`CheckpointError` naming the offending file instead of the
    storage layer's bare traceback (an ``EOFError`` with no path is
    useless on a box serving dozens of checkpoints); a missing
    checkpoint stays ``FileNotFoundError``.
    """
    orbax_dir = os.path.join(path, "orbax")
    if os.path.isdir(orbax_dir):
        return _restore_orbax(orbax_dir)
    pkl = os.path.join(path, "state.pkl")
    if os.path.exists(pkl):
        try:
            with open(pkl, "rb") as f:
                return pickle.load(f)
        except Exception as e:  # truncated write, corrupt bytes, ...
            raise CheckpointError(
                pkl, f"{type(e).__name__}: {e}") from e
    if os.path.isdir(path) and os.path.exists(
        os.path.join(path, "_CHECKPOINT_METADATA")
    ):
        # a bare orbax dir was passed directly
        return _restore_orbax(path)
    raise FileNotFoundError(f"no checkpoint under {path}")


def _restore_orbax(orbax_dir: str) -> dict:
    import orbax.checkpoint as ocp

    try:
        with ocp.PyTreeCheckpointer() as ckptr:
            return ckptr.restore(os.path.abspath(orbax_dir))
    except Exception as e:  # partial tree from an interrupted save, ...
        raise CheckpointError(
            orbax_dir, f"{type(e).__name__}: {e}") from e
