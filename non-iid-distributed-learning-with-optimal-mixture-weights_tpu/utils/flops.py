"""First-principles FLOPs accounting shared by bench.py / scale_bench.py.

One definition so the two harnesses cannot drift (r4 advisor): the
client local-SGD cost of one *client-update* (= one client's full local
training for one communication round) is

    3 · fwd_flops_per_sample(...) · epochs · n_mean

with bwd ≈ 2× fwd (`x^T g` for the weight grad plus the input-side
grad). The forward count has two regimes: GEMM-only models (every
weight leaf 2-D — the linear flagship and the MLPs, i.e. everything
bench.py times) use the weight-shape formula 2·in·out per GEMM, which
every committed artifact used; models with higher-rank weight leaves
(conv kernels) use XLA's cost model on the lowered forward, because
parameter shapes cannot express a conv's output-size-proportional work
— so the two harnesses agree wherever they measure the same model, and
conv configs (scale_bench only) get an honest count the formula cannot
give. This counts the client forward/backward ONLY — FedAMW's p-solver
and logit cache are excluded (callers must label such records; see
PERFORMANCE.md § MFU/roofline for the derivation and the measured
utilization tables).
"""

from __future__ import annotations

import numpy as np


def fwd_flops_per_sample(params, apply_fn=None, d=None,
                         with_provenance=False):
    """Forward FLOPs for one sample.

    GEMM-only models (every weight leaf 2-D): 2·(in·out) summed over
    the weight matrices (bias adds are negligible and skipped) — the
    documented formula every committed artifact used.

    Models with higher-rank weight leaves (conv kernels, 4-D HWIO):
    parameter shapes alone cannot give the cost — a conv does work
    proportional to its OUTPUT spatial size, reusing each kernel weight
    across positions — so when ``apply_fn``/``d`` are provided the
    count comes from XLA's own cost model on the lowered single-sample
    forward (exact for any model, including elementwise ops).

    ``with_provenance=True`` returns ``(flops, basis)`` instead of the
    bare count, where ``basis`` is the counting method actually used:
    ``'xla-cost-model'`` (cost_analysis on the lowered forward — counts
    elementwise/bias/activation work too), ``'gemm-formula'`` (the
    matmul-only 2·in·out count, exact regime for all-2-D models), or
    ``'gemm-formula-undercount'`` (the formula applied to a model with
    conv leaves because cost_analysis was unavailable). Emitters must
    attach the basis to EVERY record they write — the two bases are not
    directly comparable, and provenance only on the undercount case
    left the rest ambiguous (round-4 advisor); the undercount case
    additionally warrants a human-readable note, because the JSON
    artifact is what gets committed.
    """
    import jax

    leaves = jax.tree.leaves(params)
    has_high_rank = any(np.ndim(w) > 2 for w in leaves)
    basis = "gemm-formula"
    if apply_fn is not None and d is not None and has_high_rank:
        import jax.numpy as jnp

        cost = (
            jax.jit(apply_fn)
            .lower(params, jnp.zeros((1, d), jnp.float32))
            .compile()
            .cost_analysis()
        )
        if isinstance(cost, (list, tuple)):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        flops = (cost or {}).get("flops", 0.0)
        if flops:
            return ((int(flops), "xla-cost-model") if with_provenance
                    else int(flops))
        # the GEMM formula below is WRONG for >2-D leaves (it would
        # count only the linear head, a ~10x undercount for convs) —
        # never degrade silently on a runtime whose cost_analysis is
        # absent (plausible on experimental PJRT plugins)
        import warnings

        warnings.warn(
            "fwd_flops_per_sample: XLA cost_analysis unavailable on "
            "this runtime; falling back to the 2-D GEMM formula, which "
            "UNDERCOUNTS models with conv kernels — treat the FLOPs "
            "fields of this record as a lower bound",
            RuntimeWarning, stacklevel=2)
        basis = "gemm-formula-undercount"
    elif has_high_rank:
        # no apply_fn/d to lower with: same undercount, same contract
        basis = "gemm-formula-undercount"
    flops = sum(
        2 * int(np.prod(np.shape(w)))
        for w in leaves
        if np.ndim(w) == 2
    )
    return (flops, basis) if with_provenance else flops


def client_update_flops(fwd_per_sample: float, epochs: int,
                        n_mean: float) -> float:
    """FLOPs of one client-update (fwd+bwd ≈ 3× fwd, `epochs` passes
    over a mean shard of `n_mean` samples). `n_mean` must average over
    the SAME client population the updates/s rate counts (padded/empty
    clients contribute 0 samples but still count as updates)."""
    return 3.0 * fwd_per_sample * epochs * n_mean
