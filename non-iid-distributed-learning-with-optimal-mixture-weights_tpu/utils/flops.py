"""First-principles FLOPs accounting shared by bench.py / scale_bench.py.

One definition so the two harnesses cannot drift (r4 advisor): the
client local-SGD cost of one *client-update* (= one client's full local
training for one communication round) is

    3 · fwd_flops_per_sample(params) · epochs · n_mean

with fwd counted from the model's actual weight matrices (2·in·out per
GEMM) and bwd ≈ 2× fwd (`x^T g` for the weight grad plus the input-side
grad). This counts the client GEMMs ONLY — FedAMW's p-solver and logit
cache are excluded (callers must label such records; see
PERFORMANCE.md § MFU/roofline for the derivation and the measured
utilization tables).
"""

from __future__ import annotations

import numpy as np


def fwd_flops_per_sample(params) -> int:
    """Forward FLOPs for one sample: 2·(in·out) summed over the
    model's 2-D weight leaves (bias adds are negligible and skipped)."""
    import jax

    return sum(
        2 * int(np.prod(np.shape(w)))
        for w in jax.tree.leaves(params)
        if np.ndim(w) == 2
    )


def client_update_flops(fwd_per_sample: float, epochs: int,
                        n_mean: float) -> float:
    """FLOPs of one client-update (fwd+bwd ≈ 3× fwd, `epochs` passes
    over a mean shard of `n_mean` samples). `n_mean` must average over
    the SAME client population the updates/s rate counts (padded/empty
    clients contribute 0 samples but still count as updates)."""
    return 3.0 * fwd_per_sample * epochs * n_mean
