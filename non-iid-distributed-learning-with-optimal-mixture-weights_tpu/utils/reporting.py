"""Post-hoc paper tooling: significance tests and LaTeX table emitters.

Reference ``functions/utils.py:351-378`` (``check_significance``,
``print_acc``, ``print_time``) and the trivial flushing ``Logger``
(``utils.py:25-30``). These operate on the ``(algorithms, n_repeats)``
accuracy/time matrices produced by the experiment driver.
"""

from __future__ import annotations

import pickle

import numpy as np

# Paired one-sided t threshold the reference hard-codes (~t_{0.05, df=10}).
T_THRESHOLD = 1.812


def check_significance(test_arr, best_arr, threshold: float = T_THRESHOLD) -> bool:
    """True when ``best_arr`` significantly beats ``test_arr`` (paired
    t-statistic above the threshold) — reference ``utils.py:351-353``."""
    diff = np.asarray(best_arr, dtype=float) - np.asarray(test_arr, dtype=float)
    denom = np.std(diff) / np.sqrt(len(diff))
    if denom == 0:
        # zero variance: a constant positive gap is inf/denominator in the
        # reference (-> significant); identical rows are 0/0 (-> not)
        return bool(np.mean(diff) > 0)
    return float(np.mean(diff) / denom) > threshold


def print_acc(matrix) -> str:
    """LaTeX row: best row bold, rows NOT significantly worse underlined
    (reference ``utils.py:355-367``)."""
    matrix = np.asarray(matrix, dtype=float)
    best_index = int(np.argmax(np.mean(matrix, axis=1)))
    best_row = matrix[best_index]
    out = []
    for i, row in enumerate(matrix):
        cell = f"{row.mean():.2f}$\\pm${row.std():.2f}"
        if i == best_index:
            out.append("&\\textbf{" + cell + "} ")
        elif check_significance(row, best_row):
            out.append("&" + cell + " ")
        else:
            out.append("&\\underline{" + cell + "} ")
    return "".join(out)


def print_time(matrix) -> str:
    """LaTeX row of mean times, fastest bold (reference ``utils.py:369-378``)."""
    matrix = np.asarray(matrix, dtype=float)
    best_index = int(np.argmin(np.mean(matrix, axis=1)))
    out = []
    for i, row in enumerate(matrix):
        cell = f"{row.mean():.2f}"
        out.append("&\\textbf{" + cell + "} " if i == best_index else "&" + cell + " ")
    return "".join(out)


def fault_summary(fault_counts: dict) -> dict:
    """Aggregate a ``fault_counts`` record (the per-round dropped /
    straggled / corrupted / quarantined vectors a faulted run's result
    carries, ``algorithms.core._round_based``) into run totals:
    per-kind totals, the worst single round, and how many rounds saw
    any fault at all."""
    kinds = ("dropped", "straggled", "corrupted", "quarantined")
    arrs = {k: np.asarray(fault_counts[k], dtype=int) for k in kinds}
    # "lied" (work-fraction liars, fedcore.faults lie=) is optional so
    # records from before the reputation plane still summarize
    if "lied" in fault_counts:
        arrs["lied"] = np.asarray(fault_counts["lied"], dtype=int)
    any_fault = sum(arrs[k] for k in arrs if k != "quarantined")
    return {
        **{f"total_{k}": int(arrs[k].sum()) for k in arrs},
        "rounds": int(next(iter(arrs.values())).shape[0]),
        "rounds_with_faults": int(np.count_nonzero(any_fault)),
        "worst_round_faults": int(any_fault.max()) if any_fault.size else 0,
    }


def format_fault_report(name: str, fault_counts: dict) -> str:
    """One human-readable line per algorithm for the driver's stdout
    (``exp.py`` prints this after each faulted run): totals plus the
    invariant the quarantine is supposed to hold — every non-finite
    report caught (quarantined >= corrupted for nan/inf modes)."""
    s = fault_summary(fault_counts)
    lied = (f"{s['total_lied']} lied-frac, " if s.get("total_lied")
            else "")
    return (f"{name} faults: {s['total_dropped']} dropped, "
            f"{s['total_straggled']} straggled, "
            f"{s['total_corrupted']} corrupted, {lied}"
            f"{s['total_quarantined']} quarantined over "
            f"{s['rounds_with_faults']}/{s['rounds']} rounds "
            f"(worst round: {s['worst_round_faults']} faulty clients)")


def defense_summary(defense: dict) -> dict:
    """Aggregate a ``defense`` record (the per-round telemetry an
    active ``robust_agg`` spec attaches to a run's result,
    ``algorithms.core._round_based``) into run totals: scored-
    quarantine totals and the hottest z score, krum pick spread
    (which clients the selection trusted most/least), and the
    final/worst Weiszfeld residual. Only the keys the spec actually
    emitted appear."""
    out = {"robust_agg": defense["robust_agg"]}
    if "z_quarantined" in defense:
        zq = np.asarray(defense["z_quarantined"], dtype=int)
        out["total_z_quarantined"] = int(zq.sum())
        out["rounds_with_z_quarantine"] = int(np.count_nonzero(zq))
        out["max_z"] = float(np.max(defense["z_max"]))
    if "z_threshold" in defense:
        # quarantine:auto — where the auto-tuned threshold started and
        # where the observed clean-z distribution steered it
        thr = np.asarray(defense["z_threshold"], dtype=float)
        out["z_threshold_first"] = float(thr[0])
        out["z_threshold_final"] = float(thr[-1])
    if "reputation" in defense:
        rep = np.asarray(defense["reputation"], dtype=float)
        valid = np.asarray(
            defense.get("client_valid", np.ones(rep.shape[1])),
            dtype=bool)
        idx = np.flatnonzero(valid)
        final = rep[-1][idx]
        out["rep_final_mean"] = float(final.mean())
        out["rep_least_trusted"] = (int(idx[final.argmin()]),
                                    float(final.min()))
        rg = np.asarray(defense["rep_gated"], dtype=int)
        out["total_rep_gated"] = int(rg.sum())
        out["rounds_with_rep_gate"] = int(np.count_nonzero(rg))
    if "frac_clamped" in defense:
        fc = np.asarray(defense["frac_clamped"], dtype=int)
        out["total_frac_clamped"] = int(fc.sum())
    if "krum_pick_counts" in defense:
        picks = np.asarray(defense["krum_pick_counts"], dtype=int)
        # restrict the per-client stats to REAL clients: inert padded
        # ones (mesh-even packing; 'client_valid' from the run's
        # sizes) are never present and must not be reported as
        # "never selected"
        valid = np.asarray(
            defense.get("client_valid", np.ones_like(picks)),
            dtype=bool)
        idx = np.flatnonzero(valid)
        vp = picks[idx]
        out["krum_most_picked"] = (int(idx[vp.argmax()]),
                                   int(vp.max()))
        out["krum_least_picked"] = (int(idx[vp.argmin()]),
                                    int(vp.min()))
        out["krum_never_picked"] = int(np.sum(vp == 0))
    if "geomed_residual" in defense:
        res = np.asarray(defense["geomed_residual"], dtype=float)
        out["geomed_final_residual"] = float(res[-1])
        out["geomed_worst_residual"] = float(res.max())
    return out


def format_defense_report(name: str, defense: dict) -> str:
    """One human-readable line per algorithm for the driver's stdout
    (``exp.py`` prints this after each defended run), mirroring
    :func:`format_fault_report` for the defense side: what the spec
    was, what the scored quarantine caught, whom krum trusted, and
    whether Weiszfeld converged."""
    s = defense_summary(defense)
    bits = [f"{name} defense [{s['robust_agg']}]:"]
    if "total_z_quarantined" in s:
        bits.append(
            f"{s['total_z_quarantined']} z-quarantined over "
            f"{s['rounds_with_z_quarantine']} rounds "
            f"(max z {s['max_z']:.2f})")
    if "z_threshold_final" in s:
        bits.append(
            f"auto z threshold {s['z_threshold_first']:.2f} -> "
            f"{s['z_threshold_final']:.2f}")
    if "rep_final_mean" in s:
        li, lv = s["rep_least_trusted"]
        bits.append(
            f"reputation: mean {s['rep_final_mean']:.2f} final, "
            f"client {li} least trusted at {lv:.2f}, "
            f"{s['total_rep_gated']} rep-gated over "
            f"{s['rounds_with_rep_gate']} rounds")
    if "total_frac_clamped" in s:
        bits.append(
            f"{s['total_frac_clamped']} work-fraction claims clamped")
    if "krum_most_picked" in s:
        mi, mc = s["krum_most_picked"]
        li, lc = s["krum_least_picked"]
        bits.append(
            f"krum picks: client {mi} x{mc} most, client {li} x{lc} "
            f"least, {s['krum_never_picked']} never selected")
    if "geomed_final_residual" in s:
        bits.append(
            f"weiszfeld residual {s['geomed_final_residual']:.2e} "
            f"final / {s['geomed_worst_residual']:.2e} worst")
    return " ".join(bits) if len(bits) > 1 else (
        bits[0] + " active (no per-round telemetry for this spec)")


def trace_stage_summary(records) -> dict:
    """Aggregate trace span records (``utils.trace``) per stage name:
    count, total seconds, and mean/p50/p95 milliseconds. Annotations
    (zero-duration point events) are counted separately per name so a
    retry storm is visible next to the stage it hit."""
    stages: dict[str, list] = {}
    notes: dict[str, int] = {}
    for r in records:
        if r.get("kind") == "annotation":
            notes[r["name"]] = notes.get(r["name"], 0) + 1
        else:
            stages.setdefault(r["name"], []).append(float(r["dur_s"]))
    out = {}
    for name, durs in stages.items():
        a = np.asarray(durs, dtype=float)
        # nearest-rank percentiles, the same method
        # serving.metrics.LatencyHistogram uses
        p50, p95 = np.percentile(a, [50, 95], method="inverted_cdf")
        out[name] = {
            "count": int(a.size),
            "total_s": round(float(a.sum()), 6),
            "mean_ms": round(float(a.mean()) * 1e3, 4),
            "p50_ms": round(float(p50) * 1e3, 4),
            "p95_ms": round(float(p95) * 1e3, 4),
        }
    return {"stages": out, "annotations": notes}


def format_trace_summary(label: str, records) -> str:
    """Human-readable per-stage table for a trace (the trace-plane
    mirror of :func:`format_fault_report`): one line per stage with
    count / total / mean / p50 / p95, stages sorted by total cost so
    the expensive one reads first, annotations footed below. Printed by
    ``exp.py --trace_dir`` and ``serve_bench.py``'s traced leg."""
    s = trace_stage_summary(records)
    if not s["stages"] and not s["annotations"]:
        return f"{label} trace: no spans recorded"
    lines = [f"{label} trace ({sum(v['count'] for v in s['stages'].values())}"
             f" spans):"]
    width = max((len(n) for n in s["stages"]), default=0)
    for name, st in sorted(s["stages"].items(),
                           key=lambda kv: -kv[1]["total_s"]):
        lines.append(
            f"  {name:<{width}}  x{st['count']:<6d} "
            f"total {st['total_s']:9.3f}s  mean {st['mean_ms']:9.3f}ms  "
            f"p50 {st['p50_ms']:9.3f}ms  p95 {st['p95_ms']:9.3f}ms")
    for name, n in sorted(s["annotations"].items()):
        lines.append(f"  ! {name}: {n} event(s)")
    return "\n".join(lines)


def format_rollout_report(rollout: dict) -> str:
    """One human-readable line for a continuous-deployment leg (the
    ``rollout`` section ``serve_bench.py`` emits — swap latency,
    canary/drill verdicts, the hot-swap zero-recompile pin, and where
    the service ended up relative to training): the serve-side mirror
    of :func:`format_fault_report`."""
    bits = [f"rollout [{rollout.get('mode', '?')}]:",
            f"{rollout['swaps']} swaps"]
    if rollout.get("swap_p50_ms") is not None:
        bits.append(f"(p50 {rollout['swap_p50_ms']}ms, max "
                    f"{rollout.get('swap_max_ms')}ms)")
    if "canary" in rollout:
        canary_ms = rollout.get("canary_ms")
        bits.append(f"canary {rollout['canary']}"
                    + (f" in {canary_ms}ms" if canary_ms else ""))
    if rollout.get("rollback_drill"):
        bits.append(f"drill {rollout['rollback_drill']}")
    bits.append(f"in-flight p95 {rollout.get('inflight_p95_ms')}ms")
    bits.append(
        f"recompiles {rollout.get('recompiles_during_swaps')}")
    if "final_version" in rollout:
        bits.append(f"serving v{rollout['final_version']} "
                    f"({rollout.get('staleness_rounds', 0)} rounds "
                    "behind newest)")
    return " ".join(str(b) for b in bits)


def format_failover_report(chaos: dict) -> str:
    """One human-readable line for a chaos-injected failover leg (the
    ``chaos`` section ``serve_bench.py`` emits — replica deaths,
    requeues, hedge wins, the tail with and without chaos, and the
    zero-lost / zero-recompile pins): the failover-plane mirror of
    :func:`format_rollout_report`."""
    bits = [f"chaos [{chaos.get('replicas', '?')} replicas]:",
            f"{chaos.get('kills_observed', 0)}/"
            f"{chaos.get('kills_planned', 0)} kills",
            f"{chaos.get('requeues', 0)} requeues",
            f"{chaos.get('hedge_wins', 0)}/{chaos.get('hedges', 0)} "
            "hedge wins"]
    bits.append(f"{chaos.get('resolved_ok', 0)} ok + "
                f"{chaos.get('deadline_exceeded', 0)} deadline of "
                f"{chaos.get('requests', 0)} "
                f"({chaos.get('lost', '?')} lost)")
    bits.append(f"p95 {chaos.get('p95_ms_chaos')}ms vs "
                f"{chaos.get('p95_ms_clean')}ms clean")
    bits.append(f"recompiles {chaos.get('recompiles_during_chaos')}")
    return " ".join(str(b) for b in bits)


def format_overload_report(ov: dict) -> str:
    """One human-readable line for the elastic-serving overload leg
    (the ``overload`` section ``serve_bench.py`` emits — the
    autoscaled fleet's SLO-good-per-replica-second against every
    fixed fleet, interactive protection, shed and scale counters):
    the control-plane mirror of :func:`format_failover_report`."""
    fleets = ov.get("fleets", {})
    auto = fleets.get("autoscaled", {})
    fixed = {name: rec.get("good_per_replica_s")
             for name, rec in sorted(fleets.items())
             if name != "autoscaled"}
    bits = [
        "overload:",
        f"autoscaled {auto.get('good_per_replica_s')} good/replica-s "
        f"vs fixed {fixed}",
        f"(beats all: {ov.get('autoscaled_beats_every_fixed')})",
        f"interactive attainment "
        f"{auto.get('attainment', {}).get('interactive')}",
        f"batch shed {ov.get('batch_shed', 0)}",
        f"scale-ups {ov.get('scale_ups', 0)} "
        f"(peak {auto.get('replicas_peak')})",
        f"lost {ov.get('lost_accepted', 0)}",
        f"recompiles {ov.get('recompiles_during_overload', 0)}",
    ]
    return " ".join(str(b) for b in bits)


def load_results(path: str) -> dict:
    """Load an ``exp1_{dataset}.pkl`` result dict (driver schema)."""
    with open(path, "rb") as f:
        return pickle.load(f)


class Logger:
    """Line-buffered file logger (reference ``utils.py:25-30``)."""

    def __init__(self, filename: str):
        self.log = open(filename, "w")

    def write(self, content: str) -> None:
        self.log.write(content)
        self.log.flush()
