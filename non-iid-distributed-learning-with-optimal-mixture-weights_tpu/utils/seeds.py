"""Splittable sub-seed derivation for composed seeded grammars.

Every adversity grammar in the repo (``fedcore.faults.FaultSpec``,
``serving.chaos.ChaosSpec``/``LoadSpec``/``NetChaosSpec``) owns one
``seed`` and expands it into a bitwise-reproducible schedule via
``np.random.RandomState(seed)``. Composing them under ONE master seed
(the ``scenario`` package) needs per-grammar sub-seeds, and the obvious
``seed``/``seed+1``/``seed+k`` arithmetic is a collision machine:
master 7's "chaos" stream is master 8's "faults" stream, so two
campaigns at adjacent seeds silently share schedules, and two grammars
under one master are correlated whenever their offsets collide.

:func:`derive_seed` is the splittable fix — a keyed hash of
``(master, label path)``. Distinct labels give independent streams
under one master; distinct masters give independent streams under one
label; and the derivation is a pure function of its arguments, so the
same master always re-derives the identical sub-seed (the grammar
determinism contract survives the composition). The hash is blake2b,
truncated to 32 bits because that is the exact seed domain
``np.random.RandomState`` accepts.

The derivation is pinned bit-for-bit by ``tests/test_scenario.py`` —
changing this function invalidates every committed campaign regression,
which is why the label separator and digest size are spelled out here
rather than left to a library default.
"""

from __future__ import annotations

import hashlib

import numpy as np

#: Byte separating path components inside the hash input. A dedicated
#: separator keeps ("ab", "c") and ("a", "bc") distinct — without it
#: two different label paths could concatenate to one hash input.
_SEP = b"\x1f"

#: RandomState's seed domain: [0, 2**32).
_SEED_BITS = 32


def derive_seed(master: int, *labels) -> int:
    """One 32-bit sub-seed for ``labels`` under ``master``.

    ``labels`` is a path of strings/ints naming the stream (e.g.
    ``("faults",)`` or ``("scenario", 17)``). Deterministic, splittable
    (different paths never share a stream by construction of the
    keyed hash), and valid as a ``np.random.RandomState`` seed.
    """
    master = int(master)
    if master < 0:
        raise ValueError(f"master seed must be >= 0, got {master}")
    if not labels:
        raise ValueError(
            "derive_seed needs at least one label — deriving the "
            "master back out of itself would recreate the shared "
            "stream this helper exists to remove")
    h = hashlib.blake2b(digest_size=_SEED_BITS // 8)
    h.update(str(master).encode("ascii"))
    for lab in labels:
        if not isinstance(lab, (str, int)):
            raise TypeError(
                f"derive_seed labels must be str or int, got "
                f"{type(lab).__name__}")
        h.update(_SEP)
        h.update(str(lab).encode("utf-8"))
    return int.from_bytes(h.digest(), "big")


def derive_rng(master: int, *labels) -> np.random.RandomState:
    """A ``RandomState`` over :func:`derive_seed` — the one-liner the
    scenario plan builders use."""
    return np.random.RandomState(derive_seed(master, *labels))
