"""Unified telemetry plane: typed time-series metrics, SLO signals,
standard-wire exporters, and device-time attribution.

PR 5 gave the stack spans and snapshot percentiles; every signal was a
point-in-time aggregate. This module is the missing half: signals as
TIME SERIES, so rolling windows, rates, and error-budget burn rates are
computable at any point — the input the continuous-batching and
elastic-fleet directions (ROADMAP 3/4) read. Stdlib-only, same rule as
``utils/trace.py`` and ``serving/metrics.py``: a serving box must not
grow runtime deps for its observability.

Four pieces:

- :class:`Registry` — a thread-safe typed instrument registry.
  ``counter`` / ``gauge`` / ``histogram``, each addressed by name +
  label set (one instrument per distinct label set, Prometheus-style).
  Every instrument is backed by a fixed-capacity **ring buffer** of
  ``(monotonic_t, value)`` samples (:class:`TimeSeries`): past the
  capacity the OLDEST samples are overwritten — for metrics the newest
  window is the one that matters, the opposite degradation from the
  trace collector's keep-oldest (span accounting needs every id;
  a rate needs the recent tail). ``Registry(enabled=False)`` keeps
  cumulative values but skips the series appends — the cheap mode the
  paired ``telemetry_overhead`` bench leg measures against.
- :class:`SloEvaluator` — per-class attainment and error-budget burn
  rate over configurable rolling windows, computed from a latency
  histogram's raw sample series. Burn rate is the standard SRE signal
  (``(1 - attainment) / (1 - objective)``): 1.0 burns the budget
  exactly at the objective's rate, >1 is the admission-control /
  autoscaling trigger ROADMAP direction 4 consumes.
- Exporters: :func:`render_prometheus` (text exposition format) and
  :func:`spans_to_otlp` / :func:`registry_to_otlp` (OTLP-shaped JSON —
  the ``resourceSpans`` / ``resourceMetrics`` envelope, hex ids,
  typed attribute values — so any OTLP-speaking collector ingests the
  repo's traces and metrics without a custom shim).
  ``tools/obs_export.py`` is the CLI over both.
- Device-time attribution: :func:`parse_profiler_trace` reads the
  Chrome-format ``*.trace.json.gz`` a ``jax.profiler`` capture writes
  and sums the busy time on DEVICE lanes (``/device:...`` processes);
  :func:`attribute_device_time` correlates that with host-timed
  dispatch to split XLA queue/transfer time out of the blocking
  ``device_ms`` stage. On CPU (and any host whose profiler yields no
  device lane) the split degrades to ``source == "none"`` — graceful
  and tested, never a guess dressed as a measurement.

The process-global registry (:func:`get_registry` /
:func:`reset_registry`) mirrors the tracer's configure path: the
training side (``algorithms/core.py``) records per-round series into it
when the global tracer is enabled (``exp.py --trace_dir``), so one flag
turns on the whole plane.
"""

from __future__ import annotations

import bisect
import dataclasses
import glob
import gzip
import hashlib
import json
import os
import threading
import time

#: Schema tag of a serialized registry dump (``Registry.dump``); bumped
#: on incompatible record changes, same discipline as TRACE.v1.
TELEMETRY_SCHEMA = "TELEMETRY.v1"

#: Default ring-buffer capacity per instrument: at one sample per
#: round/request event this holds the recent tail every rolling-window
#: computation needs at a few KB per instrument.
DEFAULT_CAPACITY = 4096

#: Default histogram bucket bounds, in SECONDS (latency-shaped:
#: sub-millisecond through tens of seconds, Prometheus-style).
DEFAULT_BOUNDS_S = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_KINDS = ("counter", "gauge", "histogram")


class TimeSeries:
    """Fixed-capacity ring buffer of ``(t, value)`` samples.

    O(1) append; past ``capacity`` the oldest sample is overwritten and
    counted (``dropped``) — a metrics window wants the newest tail.
    NOT internally locked: the owning instrument serializes access.
    """

    __slots__ = ("capacity", "_t", "_v", "_total")

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self._t: list[float] = [0.0] * self.capacity
        self._v: list[float] = [0.0] * self.capacity
        self._total = 0

    def append(self, t: float, v: float) -> None:
        i = self._total % self.capacity
        self._t[i] = t
        self._v[i] = v
        self._total += 1

    def __len__(self) -> int:
        return min(self._total, self.capacity)

    @property
    def dropped(self) -> int:
        """Samples overwritten at the ring boundary (0 until wrap)."""
        return max(0, self._total - self.capacity)

    def items(self) -> list[tuple[float, float]]:
        """Snapshot copy, oldest -> newest."""
        n = len(self)
        if self._total <= self.capacity:
            return list(zip(self._t[:n], self._v[:n]))
        start = self._total % self.capacity
        idx = list(range(start, self.capacity)) + list(range(start))
        return [(self._t[i], self._v[i]) for i in idx]

    def window(self, t_min: float) -> list[tuple[float, float]]:
        """Samples with ``t >= t_min``, oldest -> newest."""
        return [(t, v) for t, v in self.items() if t >= t_min]


class _Instrument:
    """Shared machinery: identity, lock, ring-buffer series."""

    kind = "abstract"
    __slots__ = ("name", "labels", "series", "_registry", "_lock")

    def __init__(self, registry: "Registry", name: str,
                 labels: tuple):
        self.name = name
        self.labels = labels  # sorted (key, value) tuple, hashable
        self.series = TimeSeries(registry.capacity)
        self._registry = registry
        self._lock = threading.Lock()

    def _now(self, t: float | None) -> float:
        return self._registry.clock() if t is None else float(t)

    @property
    def label_dict(self) -> dict:
        return dict(self.labels)

    def series_state(self) -> tuple[list, int]:
        """Locked snapshot ``(items, dropped)`` of the ring series —
        the ONE sanctioned way for readers outside this instrument
        (``Registry.dump``) to see it; an unlocked ``series.items()``
        racing an append across the wrap boundary could pair a fresh
        timestamp with a stale value."""
        with self._lock:
            return self.series.items(), self.series.dropped

    def series_counts(self) -> tuple[int, int]:
        """Locked ``(retained, dropped)`` sizes — the O(1) read for
        counting (``Registry.points_recorded``), no snapshot copy."""
        with self._lock:
            return len(self.series), self.series.dropped


class Counter(_Instrument):
    """Monotonic cumulative count. The series stores the CUMULATIVE
    value at each increment, so a window rate is two lookups."""

    kind = "counter"
    __slots__ = ("_value",)

    def __init__(self, registry, name, labels):
        super().__init__(registry, name, labels)
        self._value = 0.0

    def inc(self, n: float = 1.0, t: float | None = None) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease "
                             f"(inc {n})")
        with self._lock:
            self._value += n
            if self._registry.enabled:
                self.series.append(self._now(t), self._value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def rate(self, window_s: float, now: float | None = None) -> float:
        """Increments per second over the trailing window — the
        cumulative value now minus the cumulative value at the window
        start, over the window. With no samples before the window (and
        none dropped) the base is an honest zero; after ring wraparound
        the oldest RETAINED sample bounds what is knowable and the rate
        degrades to the observable delta (never an overestimate)."""
        with self._lock:
            now = self._now(now)
            cutoff = now - float(window_s)
            base = None
            for t, v in self.series.items():
                if t <= cutoff:
                    base = v
                else:
                    break
            if base is None:
                if self.series.dropped:
                    items = self.series.items()
                    base = items[0][1] if items else 0.0
                else:
                    base = 0.0
            return max(0.0, self._value - base) / float(window_s)


class Gauge(_Instrument):
    """Last-write-wins value; the series is its trajectory."""

    kind = "gauge"
    __slots__ = ("_value",)

    def __init__(self, registry, name, labels):
        super().__init__(registry, name, labels)
        self._value = 0.0

    def set(self, v: float, t: float | None = None) -> None:
        with self._lock:
            self._value = float(v)
            if self._registry.enabled:
                self.series.append(self._now(t), self._value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def window_stats(self, window_s: float,
                     now: float | None = None) -> dict:
        """min/mean/max/last over the trailing window (None-valued when
        the window holds no samples)."""
        with self._lock:
            now = self._now(now)
            vals = [v for _, v in self.series.window(now - window_s)]
        if not vals:
            return {"n": 0, "min": None, "mean": None, "max": None,
                    "last": None}
        return {"n": len(vals), "min": min(vals),
                "mean": sum(vals) / len(vals), "max": max(vals),
                "last": vals[-1]}


class Histogram(_Instrument):
    """Bucketed distribution + raw-sample ring series.

    The cumulative count/sum/bucket counts are the Prometheus/OTLP
    export surface; the raw series is what rolling-window percentiles
    and SLO attainment read (exact over the retained tail)."""

    kind = "histogram"
    __slots__ = ("bounds", "_bucket_counts", "_count", "_sum")

    def __init__(self, registry, name, labels,
                 bounds=DEFAULT_BOUNDS_S):
        super().__init__(registry, name, labels)
        b = tuple(float(x) for x in bounds)
        if list(b) != sorted(set(b)):
            raise ValueError(f"histogram bounds must be strictly "
                             f"increasing, got {bounds!r}")
        self.bounds = b
        self._bucket_counts = [0] * (len(b) + 1)  # +Inf tail
        self._count = 0
        self._sum = 0.0

    def _observe_locked(self, v: float, now: float) -> None:
        self._count += 1
        self._sum += v
        self._bucket_counts[bisect.bisect_left(self.bounds, v)] += 1
        if self._registry.enabled:
            self.series.append(now, v)

    def observe(self, v: float, t: float | None = None) -> None:
        with self._lock:
            self._observe_locked(float(v), self._now(t))

    def observe_many(self, values, t: float | None = None) -> None:
        """Observe a batch of values under ONE lock round-trip (and
        one clock read) — the serving metrics record whole micro-
        batches, and per-value locking was a measurable slice of the
        plane's cost under continuous batching's many small batches.
        Series samples share the batch timestamp, which is also the
        honest shape: they were observed together."""
        with self._lock:
            now = self._now(t)
            for v in values:
                self._observe_locked(float(v), now)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def bucket_counts(self) -> list[int]:
        with self._lock:
            return list(self._bucket_counts)

    def window_values(self, window_s: float,
                      now: float | None = None) -> list[float]:
        with self._lock:
            now = self._now(now)
            return [v for _, v in self.series.window(now - window_s)]

    def percentile(self, q: float, window_s: float | None = None,
                   now: float | None = None) -> float | None:
        """Nearest-rank percentile over the raw series (whole retained
        tail, or the trailing ``window_s``); None with no samples."""
        with self._lock:
            now = self._now(now)
            if window_s is None:
                vals = [v for _, v in self.series.items()]
            else:
                vals = [v for _, v in self.series.window(now - window_s)]
        if not vals:
            return None
        vals.sort()
        idx = min(len(vals) - 1,
                  max(0, -(-q * len(vals) // 100) - 1))
        return vals[int(idx)]


class Registry:
    """Thread-safe instrument registry with label sets.

    One instrument per ``(kind, name, label set)``; re-requesting the
    same triple returns the SAME instrument (the idempotent
    Prometheus-client contract — callers never cache children to stay
    correct, they just ask again). A name re-used under a different
    kind raises: one name, one type, or every exporter lies.

    ``enabled=False`` keeps cumulative values exact but skips every
    ring-buffer append — the "plane off" mode the serve bench's paired
    ``telemetry_overhead`` leg measures against. ``clock`` is
    injectable (tests drive synthetic monotonic time); default is
    ``time.monotonic``.
    """

    def __init__(self, enabled: bool = True,
                 capacity: int = DEFAULT_CAPACITY, clock=time.monotonic):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.enabled = bool(enabled)
        self.capacity = int(capacity)
        self.clock = clock
        # wall/monotonic anchor pair: exporters map the monotonic
        # series timestamps onto the unix epoch with it (spans stay
        # wall-clock-free; the anchor lives HERE, at the edge)
        self.anchor = {"unix_s": time.time(), "mono_s": clock()}
        self._lock = threading.Lock()
        self._instruments: dict[tuple, _Instrument] = {}
        self._help: dict[str, str] = {}
        self._kinds: dict[str, str] = {}
        self._bounds: dict[str, tuple] = {}

    # -- creation -----------------------------------------------------
    def _get(self, kind: str, name: str, help: str, labels: dict | None,
             bounds=None) -> _Instrument:
        if not name or any(c in name for c in '{}" \n'):
            raise ValueError(f"bad instrument name {name!r}")
        key_labels = tuple(sorted((str(k), str(v))
                                  for k, v in (labels or {}).items()))
        key = (name, key_labels)
        with self._lock:
            prev_kind = self._kinds.get(name)
            if prev_kind is not None and prev_kind != kind:
                raise TypeError(
                    f"instrument {name!r} is a {prev_kind}, requested "
                    f"as a {kind} — one name, one type")
            if bounds is not None and name in self._bounds \
                    and tuple(float(b) for b in bounds) != \
                    self._bounds[name]:
                raise ValueError(
                    f"histogram {name!r} already registered with "
                    "different bounds — label sets of one family "
                    "share one bucket layout")
            inst = self._instruments.get(key)
            if inst is None:
                if kind == "counter":
                    inst = Counter(self, name, key_labels)
                elif kind == "gauge":
                    inst = Gauge(self, name, key_labels)
                else:
                    b = (self._bounds.get(name)
                         or tuple(float(x) for x in
                                  (bounds or DEFAULT_BOUNDS_S)))
                    inst = Histogram(self, name, key_labels, b)
                    self._bounds[name] = inst.bounds
                self._instruments[key] = inst
                self._kinds[name] = kind
                if help:
                    self._help[name] = help
            elif help and name not in self._help:
                self._help[name] = help
            return inst

    def counter(self, name: str, help: str = "",
                labels: dict | None = None) -> Counter:
        return self._get("counter", name, help, labels)

    def lookup(self, name: str,
               labels: dict | None = None) -> _Instrument | None:
        """The non-creating read: the instrument for ``(name, label
        set)`` or None when nothing has registered it — what read-only
        consumers (the SLO evaluator) use, so polling can never mint
        phantom empty families into the export surface."""
        key_labels = tuple(sorted((str(k), str(v))
                                  for k, v in (labels or {}).items()))
        with self._lock:
            return self._instruments.get((name, key_labels))

    def gauge(self, name: str, help: str = "",
              labels: dict | None = None) -> Gauge:
        return self._get("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: dict | None = None,
                  bounds=None) -> Histogram:
        return self._get("histogram", name, help, labels, bounds=bounds)

    # -- introspection ------------------------------------------------
    def instruments(self) -> list[_Instrument]:
        with self._lock:
            return sorted(self._instruments.values(),
                          key=lambda i: (i.name, i.labels))

    def help_text(self, name: str) -> str:
        with self._lock:
            return self._help.get(name, "")

    def points_recorded(self) -> int:
        """Total ring-buffer samples currently retained + overwritten —
        how much series data the plane actually produced."""
        total = 0
        for inst in self.instruments():
            retained, dropped = inst.series_counts()
            total += retained + dropped
        return total

    def snapshot(self) -> dict:
        """Flat ``{"name{k=v,...}": value}`` view — counters/gauges by
        value, histograms as ``{count, sum}``."""
        out = {}
        for inst in self.instruments():
            key = inst.name
            if inst.labels:
                key += "{" + ",".join(f"{k}={v}"
                                      for k, v in inst.labels) + "}"
            if inst.kind == "histogram":
                out[key] = {"count": inst.count,
                            "sum": round(inst.sum, 9)}
            else:
                out[key] = inst.value
        return out

    def dump(self) -> dict:
        """Serializable full state (``TELEMETRY.v1``): every
        instrument with its cumulative value and retained series.
        ``tools/obs_export.py`` converts this to OTLP JSON or
        Prometheus text offline."""
        metrics = []
        for inst in self.instruments():
            items, dropped = inst.series_state()
            rec = {
                "name": inst.name,
                "kind": inst.kind,
                "help": self.help_text(inst.name),
                "labels": inst.label_dict,
                "series": [[round(t, 9), v] for t, v in items],
                "series_dropped": dropped,
            }
            if inst.kind == "histogram":
                rec["count"] = inst.count
                rec["sum"] = inst.sum
                rec["bounds"] = list(inst.bounds)
                rec["bucket_counts"] = inst.bucket_counts()
            else:
                rec["value"] = inst.value
            metrics.append(rec)
        return {"schema": TELEMETRY_SCHEMA, "anchor": dict(self.anchor),
                "metrics": metrics}


# ---------------------------------------------------------------------
# SLO evaluation
# ---------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SloClass:
    """One service class: a request is GOOD iff its latency lands at or
    under ``threshold_ms``; ``objective`` is the target good-fraction
    (0.99 = 1% error budget).

    ``default_timeout_s`` (ISSUE 15, the PR 14 follow-on): the request
    DEADLINE this class implies — what ``ServingService(slo_classes=)``
    applies when a submit names the class but hand-picks no
    ``timeout_s``. None derives it as ``4 x threshold_ms``: a request
    that has already quadrupled its SLO bound is SLO-bad whatever
    happens next, so holding the caller longer only burns queue
    residency the control plane charges against everyone else. The
    vocabulary owning the timeout is what lets callers stop picking
    deadlines per call; an explicit ``timeout_s=`` still wins."""

    name: str
    threshold_ms: float
    objective: float = 0.99
    default_timeout_s: float | None = None

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective} "
                f"(1.0 leaves a zero error budget — burn rate would "
                "divide by zero)")
        if self.threshold_ms <= 0:
            raise ValueError(
                f"threshold_ms must be positive, got {self.threshold_ms}")
        if self.default_timeout_s is not None \
                and self.default_timeout_s <= 0:
            raise ValueError(
                f"default_timeout_s must be positive when set, got "
                f"{self.default_timeout_s}")

    def timeout_s(self) -> float:
        """The class's request deadline, in seconds: the explicit
        ``default_timeout_s`` when set, else ``4 x threshold_ms``."""
        return (self.default_timeout_s
                if self.default_timeout_s is not None
                else 4.0 * self.threshold_ms / 1e3)


#: The default service classes (ROADMAP direction 4's vocabulary):
#: interactive traffic against a tight bound, batch against a loose one.
DEFAULT_SLO_CLASSES = (SloClass("interactive", threshold_ms=50.0,
                                objective=0.99),
                       SloClass("batch", threshold_ms=500.0,
                                objective=0.95))


class SloEvaluator:
    """Per-class SLO attainment + error-budget burn rate over rolling
    windows, read from a latency histogram family in ``registry``
    (label ``class=<name>``, values in SECONDS — the family
    ``ServeMetrics`` records) plus the per-class deadline-miss counter
    family (``miss_metric``): a request whose deadline expired
    UNSERVED is SLO-bad regardless of how long it waited — judging it
    by its waited time would read a 50ms death as "good" under a
    100ms threshold, hiding overload from the burn signal exactly
    when callers run deadlines tighter than the class objective.

    ``evaluate()`` is a pure read (no instrument mutation): safe to
    poll from any thread at any cadence — the admission-control /
    autoscaler consumers this plane exists for.
    """

    def __init__(self, registry: Registry,
                 metric: str = "serve_request_latency_seconds",
                 classes=DEFAULT_SLO_CLASSES,
                 windows_s=(60.0, 300.0),
                 miss_metric: str = "serve_deadline_misses_total"):
        if not classes:
            raise ValueError("need at least one SloClass")
        if not windows_s or any(w <= 0 for w in windows_s):
            raise ValueError(f"windows must be positive, got {windows_s}")
        self.registry = registry
        self.metric = metric
        self.miss_metric = miss_metric
        self.classes = tuple(classes)
        self.windows_s = tuple(float(w) for w in windows_s)

    def _window_record(self, cls: SloClass, window_s: float,
                       now: float) -> dict:
        """ONE class x window evaluation — the single definition both
        :meth:`evaluate` and :meth:`burn_rates` share (two copies of
        this arithmetic would let the admission controller and the
        SLO export disagree about the same window). ``total`` counts
        served requests PLUS deadline misses; only served
        under-threshold requests are ``good``."""
        hist = self.registry.lookup(self.metric,
                                    labels={"class": cls.name})
        vals = (hist.window_values(window_s, now=now)
                if isinstance(hist, Histogram) else [])
        miss = self.registry.lookup(self.miss_metric,
                                    labels={"class": cls.name})
        missed = (int(round(miss.rate(window_s, now=now) * window_s))
                  if isinstance(miss, Counter) else 0)
        total = len(vals) + missed
        thr_s = cls.threshold_ms / 1e3
        good = sum(1 for v in vals if v <= thr_s)
        budget = 1.0 - cls.objective
        if total:
            att = good / total
            err = 1.0 - att
            burn = err / budget
        else:
            att = err = burn = None
        return {
            "total": total, "good": good, "missed": missed,
            "attainment": None if att is None else round(att, 6),
            "error_rate": None if err is None else round(err, 6),
            "budget": round(budget, 6),
            "burn_rate": None if burn is None else round(burn, 4),
        }

    def evaluate(self, now: float | None = None) -> dict:
        """``{"schema": "SLO.v1", "classes": {name: {objective,
        threshold_ms, windows: {"60s": {total, good, attainment,
        error_rate, budget, burn_rate}}}}}``.

        ``attainment``/``burn_rate`` are None over an empty window (no
        traffic is not 100% good — an autoscaler must see "no data",
        not a perfect score)."""
        now = self.registry.clock() if now is None else float(now)
        out: dict = {"schema": "SLO.v1", "now_s": round(now, 6),
                     "metric": self.metric, "classes": {}}
        for cls in self.classes:
            # non-creating lookups throughout (_window_record):
            # evaluating a class that has seen no traffic must not
            # register a phantom empty family into every subsequent
            # export (evaluate() is a pure read)
            rec: dict = {"objective": cls.objective,
                         "threshold_ms": cls.threshold_ms,
                         "windows": {}}
            for w in self.windows_s:
                rec["windows"][f"{int(w)}s"] = \
                    self._window_record(cls, w, now)
            out["classes"][cls.name] = rec
        return out

    def burn_rates(self, window_s: float | None = None,
                   now: float | None = None) -> dict:
        """One window's records only — ``{class_name: window_record}``
        with the same fields ``evaluate`` emits (``total`` / ``good`` /
        ``attainment`` / ``burn_rate`` ...), over ``window_s`` (default:
        the evaluator's first configured window). The admission
        controller and autoscaler poll exactly one window per tick;
        computing every configured window there would be wasted work
        on the submit path."""
        w = self.windows_s[0] if window_s is None else float(window_s)
        if w <= 0:
            raise ValueError(f"window must be positive, got {window_s}")
        now = self.registry.clock() if now is None else float(now)
        return {cls.name: self._window_record(cls, w, now)
                for cls in self.classes}


# ---------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------

def _prom_labels(labels) -> str:
    if not labels:
        return ""
    items = labels.items() if isinstance(labels, dict) else labels
    parts = []
    for k, v in items:
        escaped = str(v).replace("\\", "\\\\").replace('"', '\\"')
        parts.append(f'{k}="{escaped}"')
    return "{" + ",".join(parts) + "}"


def _prom_num(v: float) -> str:
    f = float(v)
    if f != f:  # NaN — a diverging run's loss gauge must still render
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return str(int(f)) if f == int(f) else repr(f)


def render_prometheus(source) -> str:
    """Prometheus text exposition of a :class:`Registry` (or a
    ``Registry.dump()`` dict): ``# HELP`` / ``# TYPE`` headers per
    family, one sample line per label set, histograms as the standard
    ``_bucket{le=...}`` / ``_sum`` / ``_count`` triplet with a
    cumulative ``+Inf`` bucket."""
    dump = source.dump() if isinstance(source, Registry) else source
    if not isinstance(dump, dict) or "metrics" not in dump:
        raise ValueError("render_prometheus needs a Registry or a "
                         f"{TELEMETRY_SCHEMA} dump dict")
    by_name: dict[str, list[dict]] = {}
    for rec in dump["metrics"]:
        by_name.setdefault(rec["name"], []).append(rec)
    lines: list[str] = []
    for name in sorted(by_name):
        recs = by_name[name]
        kind = recs[0]["kind"]
        help_text = recs[0].get("help") or ""
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        for rec in recs:
            labels = rec.get("labels") or {}
            if kind == "histogram":
                cum = 0
                bounds = rec["bounds"]
                for b, n in zip(bounds, rec["bucket_counts"]):
                    cum += n
                    le = dict(labels, le=_prom_num(b))
                    lines.append(f"{name}_bucket{_prom_labels(le)} {cum}")
                cum += rec["bucket_counts"][len(bounds)]
                le = dict(labels, le="+Inf")
                lines.append(f"{name}_bucket{_prom_labels(le)} {cum}")
                lines.append(f"{name}_sum{_prom_labels(labels)} "
                             f"{_prom_num(rec['sum'])}")
                lines.append(f"{name}_count{_prom_labels(labels)} "
                             f"{rec['count']}")
            else:
                lines.append(f"{name}{_prom_labels(labels)} "
                             f"{_prom_num(rec['value'])}")
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str) -> dict:
    """Minimal inverse of :func:`render_prometheus` (the round-trip
    check the tests pin, and a debugging convenience): ``{sample_name
    {labels}: float}`` — histogram bucket/sum/count lines appear under
    their suffixed names."""
    out: dict[str, float] = {}
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln or ln.startswith("#"):
            continue
        try:
            key, val = ln.rsplit(None, 1)
        except ValueError:
            raise ValueError(f"unparseable exposition line {ln!r}")
        out[key] = float(val)
    return out


# ---------------------------------------------------------------------
# OTLP-shaped JSON
# ---------------------------------------------------------------------

def _otlp_value(v) -> dict:
    """An OTLP ``AnyValue``: typed wrapper keyed by JSON type."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}  # OTLP JSON carries 64-bit as str
    if isinstance(v, float):
        return {"doubleValue": _otlp_double(v)}
    return {"stringValue": str(v)}


def _otlp_double(f: float):
    """proto3 JSON spells non-finite doubles as strings — a bare NaN
    in the output would be invalid JSON to every OTLP collector (and a
    diverging run's loss IS NaN)."""
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "Infinity" if f > 0 else "-Infinity"
    return f


def _otlp_attrs(d: dict) -> list[dict]:
    return [{"key": str(k), "value": _otlp_value(v)}
            for k, v in d.items()]


def _otlp_trace_id(raw: str) -> str:
    """Deterministic 16-byte hex trace id from a repo-native id
    (``req-42``): OTLP requires fixed-width binary ids, the repo uses
    readable counters — a keyed hash maps one onto the other stably,
    and the raw id rides along as an attribute."""
    return hashlib.md5(raw.encode()).hexdigest()


def _otlp_span_id(raw: str) -> str:
    return hashlib.md5(raw.encode()).hexdigest()[:16]


def _nanos(mono_s: float, anchor: dict | None) -> str:
    """Monotonic seconds -> unix nanos via the wall/monotonic anchor
    pair; with no anchor, the monotonic value maps directly (a
    RELATIVE timeline — ordering and durations exact, epoch arbitrary,
    and the output says so via the caller's resource attrs)."""
    if anchor:
        mono_s = (float(anchor["unix_s"])
                  + (mono_s - float(anchor["mono_s"])))
    return str(max(0, int(mono_s * 1e9)))


def spans_to_otlp(spans, anchor: dict | None = None,
                  service_name: str = "fedamw_tpu") -> dict:
    """TRACE.v1 span records -> an OTLP-shaped ``resourceSpans``
    envelope: hex trace/span/parent ids (raw ids preserved as
    attributes), unix-nano timestamps via ``anchor`` (the
    ``{"unix_s", "mono_s"}`` pair the trace export header carries),
    attrs as typed OTLP attributes. Annotations (zero-duration point
    events) ride as zero-length spans with ``kind_raw=annotation``."""
    out_spans = []
    for r in spans:
        attrs = dict(r.get("attrs") or {})
        attrs["id_raw"] = r["span_id"]
        attrs["trace_id_raw"] = r["trace_id"]
        if r.get("kind") and r["kind"] != "span":
            attrs["kind_raw"] = r["kind"]
        start = float(r["start_s"])
        end = start + float(r["dur_s"])
        out_spans.append({
            "traceId": _otlp_trace_id(r["trace_id"]),
            "spanId": _otlp_span_id(r["span_id"]),
            "parentSpanId": (_otlp_span_id(r["parent_id"])
                             if r.get("parent_id") else ""),
            "name": r["name"],
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": _nanos(start, anchor),
            "endTimeUnixNano": _nanos(end, anchor),
            "attributes": _otlp_attrs(attrs),
        })
    resource_attrs = {"service.name": service_name,
                      "telemetry.sdk.name": "fedamw_tpu.utils.trace",
                      "fedamw.timeline": ("unix" if anchor
                                          else "monotonic-relative")}
    return {"resourceSpans": [{
        "resource": {"attributes": _otlp_attrs(resource_attrs)},
        "scopeSpans": [{
            "scope": {"name": "fedamw_tpu.utils.trace",
                      "version": "TRACE.v1"},
            "spans": out_spans,
        }],
    }]}


def registry_to_otlp(source, service_name: str = "fedamw_tpu") -> dict:
    """A :class:`Registry` (or its ``dump()``) -> an OTLP-shaped
    ``resourceMetrics`` envelope. Counters and gauges export their full
    retained SERIES (one data point per ring sample — the whole point
    of the time-series plane); histograms export their cumulative
    bucketed state as one data point."""
    dump = source.dump() if isinstance(source, Registry) else source
    if not isinstance(dump, dict) or "metrics" not in dump:
        raise ValueError("registry_to_otlp needs a Registry or a "
                         f"{TELEMETRY_SCHEMA} dump dict")
    anchor = dump.get("anchor")
    # one OTLP metric per FAMILY: the label sets of one name merge
    # into one entry's dataPoints (collectors tolerate repeated names,
    # but the protocol's shape is one metric, many attributed points)
    metrics: list[dict] = []
    by_name: dict[str, dict] = {}
    for rec in dump["metrics"]:
        attrs = _otlp_attrs(rec.get("labels") or {})
        m = by_name.get(rec["name"])
        if m is None:
            m = by_name[rec["name"]] = {
                "name": rec["name"],
                "description": rec.get("help") or ""}
            metrics.append(m)
        if rec["kind"] == "histogram":
            body = m.setdefault("histogram", {
                "aggregationTemporality": 2,  # CUMULATIVE
                "dataPoints": []})
            body["dataPoints"].append({
                "attributes": attrs,
                "timeUnixNano": _nanos(
                    rec["series"][-1][0] if rec["series"]
                    else (anchor or {}).get("mono_s", 0.0), anchor),
                "count": str(rec["count"]),
                "sum": _otlp_double(float(rec["sum"])),
                "bucketCounts": [str(n) for n in rec["bucket_counts"]],
                "explicitBounds": list(rec["bounds"]),
            })
        else:
            series = rec["series"] or [[
                (anchor or {}).get("mono_s", 0.0), rec["value"]]]
            points = [{"attributes": attrs,
                       "timeUnixNano": _nanos(t, anchor),
                       "asDouble": _otlp_double(float(v))}
                      for t, v in series]
            if rec["kind"] == "counter":
                body = m.setdefault("sum", {
                    "aggregationTemporality": 2,
                    "isMonotonic": True, "dataPoints": []})
            else:
                body = m.setdefault("gauge", {"dataPoints": []})
            body["dataPoints"].extend(points)
    resource_attrs = {"service.name": service_name,
                      "fedamw.timeline": ("unix" if anchor
                                          else "monotonic-relative")}
    return {"resourceMetrics": [{
        "resource": {"attributes": _otlp_attrs(resource_attrs)},
        "scopeMetrics": [{
            "scope": {"name": "fedamw_tpu.utils.telemetry",
                      "version": TELEMETRY_SCHEMA},
            "metrics": metrics,
        }],
    }]}


# ---------------------------------------------------------------------
# Device-time attribution (jax.profiler correlation)
# ---------------------------------------------------------------------

def parse_profiler_trace(trace_dir: str) -> dict | None:
    """Read the newest Chrome-format ``*.trace.json.gz`` a
    ``jax.profiler`` capture wrote under ``trace_dir`` and sum the busy
    time on DEVICE lanes (processes named ``/device:...`` — TPU/GPU op
    execution; the host lane ``/host:CPU`` is deliberately excluded:
    host thunk time is not device compute).

    Returns ``{"device_busy_s", "device_events", "device_lanes"}`` or
    **None** when the capture holds no device lane — which is exactly
    what a CPU-backend capture looks like, and is the graceful-fallback
    signal :func:`attribute_device_time` turns into ``source="none"``.
    Raises nothing for a missing/corrupt capture either: attribution
    is an optional refinement, never a crash source.
    """
    paths = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                  recursive=True),
        key=os.path.getmtime)
    if not paths:
        return None
    try:
        with gzip.open(paths[-1], "rt") as f:
            trace = json.load(f)
    except (OSError, ValueError):
        return None
    events = trace.get("traceEvents") or []
    device_pids = {
        e.get("pid") for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and str((e.get("args") or {}).get("name", "")).startswith(
            "/device:")}
    if not device_pids:
        return None
    busy_us = 0.0
    n = 0
    for e in events:
        if e.get("ph") == "X" and e.get("pid") in device_pids:
            busy_us += float(e.get("dur") or 0.0)
            n += 1
    return {"device_busy_s": busy_us / 1e6, "device_events": n,
            "device_lanes": len(device_pids)}


def attribute_device_time(dispatch, reps: int = 8,
                          trace_dir: str | None = None) -> dict:
    """Correlate a ``jax.profiler`` capture with host-timed engine
    dispatch to split the blocking ``device_ms`` stage into actual
    device compute vs XLA queue/transfer residency.

    ``dispatch`` is a zero-arg callable running ONE engine dispatch and
    returning its host-blocking seconds (``ServingEngine.
    device_attribution`` wraps ``predict`` this way). The callable runs
    ``reps`` times under one profiler capture; device-lane busy time
    from the capture is divided by the host total:

    - device lanes present (TPU/GPU): ``source="profiler"``,
      ``compute_fraction`` in [0, 1], ``xla_queue_s`` = host blocking
      time not accounted by device busy time.
    - no device lanes (CPU backend), profiler unavailable, or any
      capture failure: ``source="none"`` with the reason — the tested
      graceful fallback; the per-stage split simply stays unsplit.
    """
    import shutil
    import tempfile

    scratch = None
    if trace_dir is None:
        trace_dir = scratch = tempfile.mkdtemp(prefix="fedamw_devattr_")
    host_s = 0.0
    try:
        import jax.profiler as _profiler

        _profiler.start_trace(trace_dir)
        try:
            for _ in range(max(1, int(reps))):
                host_s += float(dispatch())
        finally:
            _profiler.stop_trace()
        parsed = parse_profiler_trace(trace_dir)
    except Exception as e:
        # attribution must never take the serving path down: a broken
        # profiler degrades to the unsplit stage, with the reason named
        return {"source": "none", "reason": f"{type(e).__name__}: {e}",
                "reps": int(reps), "dispatch_s": round(host_s, 6)}
    finally:
        if scratch is not None:
            shutil.rmtree(scratch, ignore_errors=True)
    if parsed is None:
        return {"source": "none",
                "reason": "profiler capture holds no device lane "
                          "(CPU backend)",
                "reps": int(reps), "dispatch_s": round(host_s, 6)}
    busy = min(parsed["device_busy_s"], host_s)
    frac = busy / host_s if host_s > 0 else 0.0
    return {
        "source": "profiler",
        "reps": int(reps),
        "dispatch_s": round(host_s, 6),
        "device_compute_s": round(busy, 6),
        "xla_queue_s": round(max(0.0, host_s - busy), 6),
        "compute_fraction": round(frac, 6),
        "device_events": parsed["device_events"],
        "device_lanes": parsed["device_lanes"],
    }


# ---------------------------------------------------------------------
# Process-global registry (the tracer-configure-path twin)
# ---------------------------------------------------------------------

_global_registry = Registry()
_global_lock = threading.Lock()


def get_registry() -> Registry:
    """The process-global registry the training side records into
    (``algorithms/core.py``, gated behind the global tracer being
    enabled — one ``exp.py --trace_dir`` flag turns on the plane)."""
    return _global_registry


def reset_registry(enabled: bool = True,
                   capacity: int = DEFAULT_CAPACITY) -> Registry:
    """Swap in a fresh process-global registry (benches isolate legs
    with this; tests isolate cases). Returns the new registry."""
    global _global_registry
    with _global_lock:
        _global_registry = Registry(enabled=enabled, capacity=capacity)
        return _global_registry
