"""Unified trace plane: monotonic-clock spans with ids, stdlib-only.

One tracing core for the stack's three timing consumers (the serving
request loop, the training round scans, and the benches), so a slow
request or a slow round localizes to a STAGE instead of disappearing
into one end-to-end number. Deliberately stdlib-only — a serving box
must not grow runtime deps for its observability, same rule as
``serving/metrics.py``.

Design:

- A **span** is one timed interval: ``name``, a ``trace_id`` grouping
  every span of one request/run, its own ``span_id``, an optional
  ``parent_id``, a monotonic ``start_s`` (``time.perf_counter`` basis —
  durations are exact, wall-clock is deliberately absent), ``dur_s``,
  and a flat ``attrs`` dict. A **kind** of ``"annotation"`` marks a
  zero-duration point event (a retry, a deadline verdict) attached to
  the same trace id.
- :class:`Tracer` is a thread-safe bounded collector. Past
  ``max_spans`` it DROPS new spans and counts them (``dropped``) —
  keeping the oldest is the right degradation for request traces,
  where the bench sizes the bound to the stream and a silent
  ring-buffer overwrite would break the "every request id appears
  exactly once" accounting.
- Disabled mode is free: ``Tracer(enabled=False)`` (or the shared
  :data:`NULL_TRACER`) makes ``emit``/``annotate`` immediate returns
  and ``span()`` hand back one process-wide no-op context manager —
  no per-call allocation, pinned by ``tests/test_trace.py``.
- Export is JSONL (one span object per line, ``schema`` in a leading
  header line) via :meth:`Tracer.export_jsonl`;
  :func:`read_jsonl` round-trips it.

The process-global tracer (:func:`configure` / :func:`get_tracer`) is
how the training side opts in without threading a tracer through every
algorithm signature: ``exp.py --trace_dir`` configures it, and
``algorithms/core.py`` emits per-round records when it is enabled —
host-timed around the one fused scan dispatch, with the per-round
duration attributed uniformly (the scan is a single XLA program; the
host cannot see round boundaries, and the records say so via
``attrs["timing"] == "uniform"``).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import threading
import time

#: JSONL header schema tag (bumped on incompatible record changes).
TRACE_SCHEMA = "TRACE.v1"

#: Record keys every exported span carries, in export order.
SPAN_FIELDS = ("name", "kind", "trace_id", "span_id", "parent_id",
               "start_s", "dur_s", "attrs")


class _NullSpan:
    """The shared no-op context manager disabled tracers hand out.

    One process-wide instance (:data:`_NULL_SPAN`): ``span()`` on a
    disabled tracer must not allocate per call — serving's submit path
    runs it per request.
    """

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _LiveSpan:
    """Context manager recording one span on exit (success or raise)."""

    __slots__ = ("_tracer", "name", "trace_id", "parent_id", "attrs",
                 "_t0", "span_id")

    def __init__(self, tracer, name, trace_id, parent_id, attrs):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.span_id = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        if exc_type is not None:
            # a failed stage is the span you want most; never swallow
            self.attrs = dict(self.attrs, error=exc_type.__name__)
        self.span_id = self._tracer.emit(
            self.name, self.trace_id, self._t0, dur,
            parent_id=self.parent_id, **self.attrs)
        return False


class Tracer:
    """Thread-safe bounded span collector with a free disabled mode."""

    def __init__(self, enabled: bool = True, max_spans: int = 100_000):
        if max_spans <= 0:
            raise ValueError(f"max_spans must be positive, got {max_spans}")
        self.enabled = bool(enabled)
        self.max_spans = int(max_spans)
        self._spans: list[dict] = []
        self._dropped = 0
        self._lock = threading.Lock()
        self._ids = itertools.count(1)

    # -- ids ----------------------------------------------------------
    def new_id(self, prefix: str = "t") -> str:
        """A fresh process-unique trace/request id (``prefix-N``).
        Cheap and monotonic; handed out even when disabled, so callers
        (serving's submit) never branch on tracer state for identity."""
        return f"{prefix}-{next(self._ids)}"

    # -- recording ----------------------------------------------------
    def emit(self, name: str, trace_id: str, start_s: float,
             dur_s: float, parent_id: str | None = None,
             kind: str = "span", attrs: dict | None = None,
             **kw) -> str | None:
        """Record one completed span; returns its span id (None when
        disabled or dropped at the bound). Attributes go in ``attrs``
        (the caller's dict is taken as-is — the hot-path spelling; the
        serving loop emits one span per request) or as keyword
        arguments (the convenient spelling); both at once merge, kw
        winning."""
        if not self.enabled:
            return None
        if attrs is None:
            attrs = kw
        elif kw:
            attrs = {**attrs, **kw}
        rec = {
            "name": name,
            "kind": kind,
            "trace_id": trace_id,
            "span_id": None,  # assigned under the lock, below
            "parent_id": parent_id,
            "start_s": float(start_s),
            "dur_s": float(dur_s),
            "attrs": attrs,
        }
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self._dropped += 1
                return None
            rec["span_id"] = f"s-{next(self._ids)}"
            self._spans.append(rec)
        return rec["span_id"]

    def annotate(self, name: str, trace_id: str,
                 parent_id: str | None = None, **attrs) -> str | None:
        """A zero-duration point event (retry, deadline verdict) on an
        existing trace — rendered alongside its spans on export."""
        if not self.enabled:  # skip even the perf_counter call
            return None
        return self.emit(name, trace_id, time.perf_counter(), 0.0,
                         parent_id=parent_id, kind="annotation", **attrs)

    def span(self, name: str, trace_id: str,
             parent_id: str | None = None, **attrs):
        """Context manager timing its body into one span. Disabled
        tracers return the shared no-op instance (zero allocation)."""
        if not self.enabled:
            return _NULL_SPAN
        return _LiveSpan(self, name, trace_id, parent_id, attrs)

    # -- introspection / export ---------------------------------------
    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def records(self) -> list[dict]:
        """Snapshot copy of the collected spans, in emit order."""
        with self._lock:
            return [dict(r) for r in self._spans]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._dropped = 0

    def export_jsonl(self, path: str) -> int:
        """Write ``{schema header}\\n{span}\\n...``; returns the span
        count written (header excluded)."""
        recs = self.records()
        with open(path, "w") as f:
            f.write(json.dumps({"schema": TRACE_SCHEMA,
                                "spans": len(recs),
                                "dropped": self.dropped}) + "\n")
            for r in recs:
                f.write(json.dumps({k: r[k] for k in SPAN_FIELDS}) + "\n")
        return len(recs)


def read_jsonl(path: str) -> tuple[dict, list[dict]]:
    """Inverse of :meth:`Tracer.export_jsonl`:
    ``(header, spans)``. Raises ``ValueError`` on a non-trace file —
    the header line must carry the ``TRACE.`` schema family."""
    with open(path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    if not lines or not str(lines[0].get("schema", "")).startswith("TRACE."):
        raise ValueError(f"{path}: not a trace JSONL (missing "
                         f"{TRACE_SCHEMA!r}-family header line)")
    return lines[0], lines[1:]


#: The shared disabled tracer: emit/annotate are immediate returns and
#: span() is the no-op singleton. Module-level so hot paths can default
#: to it without constructing anything.
NULL_TRACER = Tracer(enabled=False)

_global_tracer: Tracer = NULL_TRACER
_global_lock = threading.Lock()


def configure(enabled: bool = True, max_spans: int = 1_000_000) -> Tracer:
    """Install (and return) the process-global tracer — how ``exp.py
    --trace_dir`` turns on per-round training spans without threading a
    tracer through every algorithm signature. ``configure(False)``
    restores the free :data:`NULL_TRACER`."""
    global _global_tracer
    with _global_lock:
        _global_tracer = (Tracer(enabled=True, max_spans=max_spans)
                          if enabled else NULL_TRACER)
        return _global_tracer


def get_tracer() -> Tracer:
    """The process-global tracer (:data:`NULL_TRACER` until
    :func:`configure`); emitters must treat it as possibly disabled."""
    return _global_tracer
