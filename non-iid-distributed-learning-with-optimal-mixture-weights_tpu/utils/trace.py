"""Unified trace plane: monotonic-clock spans with ids, stdlib-only.

One tracing core for the stack's three timing consumers (the serving
request loop, the training round scans, and the benches), so a slow
request or a slow round localizes to a STAGE instead of disappearing
into one end-to-end number. Deliberately stdlib-only — a serving box
must not grow runtime deps for its observability, same rule as
``serving/metrics.py``.

Design:

- A **span** is one timed interval: ``name``, a ``trace_id`` grouping
  every span of one request/run, its own ``span_id``, an optional
  ``parent_id``, a monotonic ``start_s`` (``time.perf_counter`` basis —
  durations are exact, wall-clock is deliberately absent), ``dur_s``,
  and a flat ``attrs`` dict. A **kind** of ``"annotation"`` marks a
  zero-duration point event (a retry, a deadline verdict) attached to
  the same trace id.
- :class:`Tracer` is a thread-safe bounded collector. Past
  ``max_spans`` it DROPS new spans and counts them (``dropped``) —
  keeping the oldest is the right degradation for request traces,
  where the bench sizes the bound to the stream and a silent
  ring-buffer overwrite would break the "every request id appears
  exactly once" accounting.
- Disabled mode is free: ``Tracer(enabled=False)`` (or the shared
  :data:`NULL_TRACER`) makes ``emit``/``annotate`` immediate returns
  and ``span()`` hand back one process-wide no-op context manager —
  no per-call allocation, pinned by ``tests/test_trace.py``.
- Export is JSONL (one span object per line, ``schema`` in a leading
  header line) via :meth:`Tracer.export_jsonl`;
  :func:`read_jsonl` round-trips it.
- **Streaming** mode (:class:`RotatingJsonlWriter` passed as
  ``Tracer(writer=...)``) is for long-lived serving loops: spans are
  written straight to a rotating JSONL file set instead of
  accumulating in the in-memory collector, so a service that runs for
  days holds O(1) trace memory. Each part file carries the same
  schema header (``read_jsonl`` reads any part); rotation is by span
  count. The continuous-deployment bench leg (``serve_bench.py``
  ``SERVE_TRACE=DIR``) streams through this.

The process-global tracer (:func:`configure` / :func:`get_tracer`) is
how the training side opts in without threading a tracer through every
algorithm signature: ``exp.py --trace_dir`` configures it, and
``algorithms/core.py`` emits per-round records when it is enabled —
host-timed around the one fused scan dispatch, with the per-round
duration attributed uniformly (the scan is a single XLA program; the
host cannot see round boundaries, and the records say so via
``attrs["timing"] == "uniform"``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import json
import os
import threading
import time

#: JSONL header schema tag (bumped on incompatible record changes).
TRACE_SCHEMA = "TRACE.v1"

#: Record keys every exported span carries, in export order.
SPAN_FIELDS = ("name", "kind", "trace_id", "span_id", "parent_id",
               "start_s", "dur_s", "attrs")


class _NullSpan:
    """The shared no-op context manager disabled tracers hand out.

    One process-wide instance (:data:`_NULL_SPAN`): ``span()`` on a
    disabled tracer must not allocate per call — serving's submit path
    runs it per request.
    """

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _LiveSpan:
    """Context manager recording one span on exit (success or raise)."""

    __slots__ = ("_tracer", "name", "trace_id", "parent_id", "attrs",
                 "_t0", "span_id")

    def __init__(self, tracer, name, trace_id, parent_id, attrs):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.span_id = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        if exc_type is not None:
            # a failed stage is the span you want most; never swallow
            self.attrs = dict(self.attrs, error=exc_type.__name__)
        self.span_id = self._tracer.emit(
            self.name, self.trace_id, self._t0, dur,
            parent_id=self.parent_id, **self.attrs)
        return False


class RotatingJsonlWriter:
    """Span sink for long-lived loops: JSONL part files rotated by
    span count, each opening with the ``TRACE.v1`` schema header so
    :func:`read_jsonl` reads any part standalone.

    Rotation keeps every part boundable (ship/delete parts while the
    service keeps running) and the writer itself holds no spans — the
    memory the in-memory collector would otherwise grow without bound.
    Thread-safe: the serving worker and a publisher thread may emit
    concurrently. ``close()`` is idempotent; writing after close
    raises (a silent drop would break the exactly-once accounting the
    serve bench gates on).
    """

    def __init__(self, directory: str, max_spans_per_file: int = 50_000,
                 prefix: str = "trace"):
        if max_spans_per_file <= 0:
            raise ValueError("max_spans_per_file must be positive, got "
                             f"{max_spans_per_file}")
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.max_spans_per_file = int(max_spans_per_file)
        self.prefix = prefix
        self._lock = threading.Lock()
        self._file = None
        # resume numbering PAST any parts already in the directory: a
        # restarted process (the crash case this writer's per-span
        # flush exists for) must never truncate the previous run's
        # trace-00001 — those are exactly the spans worth keeping
        tag = f"{prefix}-"
        existing = [f[len(tag):-len(".jsonl")]
                    for f in os.listdir(directory)
                    if f.startswith(tag) and f.endswith(".jsonl")]
        self._part = max((int(s) for s in existing if s.isdigit()),
                         default=0)
        self._in_part = 0
        self._written = 0
        self._closed = False
        self.paths: list[str] = []

    def _rotate_locked(self) -> None:
        if self._file is not None:
            self._file.close()
        self._part += 1
        self._in_part = 0
        path = os.path.join(
            self.directory, f"{self.prefix}-{self._part:05d}.jsonl")
        self._file = open(path, "w")
        # parts are standalone trace files: same schema family header
        # export_jsonl writes, marked streaming (span count unknowable
        # upfront, and dropped is structurally zero — nothing buffers)
        self._file.write(json.dumps({
            "schema": TRACE_SCHEMA, "streaming": True,
            "part": self._part}) + "\n")
        self.paths.append(path)

    def write(self, rec: dict) -> None:
        """Append one span record (the :data:`SPAN_FIELDS` subset),
        rotating first when the current part is full."""
        line = json.dumps({k: rec[k] for k in SPAN_FIELDS})
        with self._lock:
            if self._closed:
                # a dedicated flag, not `_file is None`: closing
                # BEFORE the first span leaves no file either, and
                # the lazy open below must not silently resurrect a
                # closed writer (the consumer already counted
                # paths/spans_written)
                raise ValueError("RotatingJsonlWriter is closed")
            if self._file is None:
                # graftlint: disable=GL004 rotation must be atomic with the write it precedes; one writer per tracer, so contention is the emitting thread only
                self._rotate_locked()
            if self._in_part >= self.max_spans_per_file:
                # graftlint: disable=GL004 same as above — a racing rotate would double-open part N
                self._rotate_locked()
            # graftlint: disable=GL004 serialized per-span write IS this writer's durability contract (measured ~0.96x in the serve bench's paired trace leg)
            self._file.write(line + "\n")
            # flush per span: this mode exists for processes that die
            # without close() (OOM, preemption) and for shippers
            # tailing the live part — buffered tails would lose the
            # last spans and hand readers a truncated JSON line
            # graftlint: disable=GL004 per-span flush is the crash-durability contract (see comment above)
            self._file.flush()
            self._in_part += 1
            self._written += 1

    @property
    def spans_written(self) -> int:
        with self._lock:
            return self._written

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._file is not None:
                self._file.close()
                self._file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Tracer:
    """Thread-safe bounded span collector with a free disabled mode.

    ``writer`` (a :class:`RotatingJsonlWriter`) switches the tracer to
    streaming: completed spans go straight to the writer's rotating
    JSONL files and the in-memory list stays empty — ``records()``
    returns nothing and :meth:`export_jsonl` refuses (the spans are
    already on disk). ``max_spans``/``dropped`` do not apply; the
    writer counts via ``spans_written``.
    """

    def __init__(self, enabled: bool = True, max_spans: int = 100_000,
                 writer: "RotatingJsonlWriter | None" = None):
        if max_spans <= 0:
            raise ValueError(f"max_spans must be positive, got {max_spans}")
        self.enabled = bool(enabled)
        self.max_spans = int(max_spans)
        self.writer = writer
        self._spans: list[dict] = []
        self._dropped = 0
        self._lock = threading.Lock()
        self._ids = itertools.count(1)

    # -- ids ----------------------------------------------------------
    def new_id(self, prefix: str = "t") -> str:
        """A fresh process-unique trace/request id (``prefix-N``).
        Cheap and monotonic; handed out even when disabled, so callers
        (serving's submit) never branch on tracer state for identity."""
        return f"{prefix}-{next(self._ids)}"

    # -- recording ----------------------------------------------------
    def emit(self, name: str, trace_id: str, start_s: float,
             dur_s: float, parent_id: str | None = None,
             kind: str = "span", attrs: dict | None = None,
             **kw) -> str | None:
        """Record one completed span; returns its span id (None when
        disabled or dropped at the bound). Attributes go in ``attrs``
        (the caller's dict is taken as-is — the hot-path spelling; the
        serving loop emits one span per request) or as keyword
        arguments (the convenient spelling); both at once merge, kw
        winning."""
        if not self.enabled:
            return None
        if attrs is None:
            attrs = kw
        elif kw:
            attrs = {**attrs, **kw}
        rec = {
            "name": name,
            "kind": kind,
            "trace_id": trace_id,
            "span_id": None,  # assigned under the lock, below
            "parent_id": parent_id,
            "start_s": float(start_s),
            "dur_s": float(dur_s),
            "attrs": attrs,
        }
        if self.writer is not None:
            # streaming: the id counter is already thread-safe
            # (itertools.count) and the writer locks internally, so no
            # collector lock is taken — the span never lands in memory
            rec["span_id"] = f"s-{next(self._ids)}"
            try:
                self.writer.write(rec)
            except (ValueError, OSError):
                # a SUPERSEDED tracer whose writer was closed by a
                # reconfigure, or a writer whose disk just filled
                # (ENOSPC on the per-span flush) — either way, degrade
                # like the bounded collector: count the span as
                # dropped instead of raising into the emitting thread
                # (which could be the serving worker, whose death
                # would strand every queued future)
                with self._lock:
                    self._dropped += 1
                return None
            return rec["span_id"]
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self._dropped += 1
                return None
            rec["span_id"] = f"s-{next(self._ids)}"
            self._spans.append(rec)
        return rec["span_id"]

    def annotate(self, name: str, trace_id: str,
                 parent_id: str | None = None, **attrs) -> str | None:
        """A zero-duration point event (retry, deadline verdict) on an
        existing trace — rendered alongside its spans on export."""
        if not self.enabled:  # skip even the perf_counter call
            return None
        return self.emit(name, trace_id, time.perf_counter(), 0.0,
                         parent_id=parent_id, kind="annotation", **attrs)

    def span(self, name: str, trace_id: str,
             parent_id: str | None = None, **attrs):
        """Context manager timing its body into one span. Disabled
        tracers return the shared no-op instance (zero allocation)."""
        if not self.enabled:
            return _NULL_SPAN
        return _LiveSpan(self, name, trace_id, parent_id, attrs)

    # -- introspection / export ---------------------------------------
    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def records(self) -> list[dict]:
        """Snapshot copy of the collected spans, in emit order."""
        with self._lock:
            return [dict(r) for r in self._spans]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._dropped = 0

    def export_jsonl(self, path: str) -> int:
        """Write ``{schema header}\\n{span}\\n...``; returns the span
        count written (header excluded)."""
        if self.writer is not None:
            raise ValueError(
                "streaming tracer: spans were already exported to "
                f"{self.writer.directory!r} as they were emitted "
                "(writer.paths lists the part files)")
        recs = self.records()
        with open(path, "w") as f:
            # the wall/monotonic anchor pair lands in the HEADER only
            # (spans stay wall-clock-free by design): exporters that
            # need epoch timestamps (tools/obs_export.py -> OTLP) map
            # the monotonic span times through it
            f.write(json.dumps({"schema": TRACE_SCHEMA,
                                "spans": len(recs),
                                "dropped": self.dropped,
                                "anchor_unix_s": time.time(),
                                "anchor_mono_s": time.perf_counter()
                                }) + "\n")
            for r in recs:
                f.write(json.dumps({k: r[k] for k in SPAN_FIELDS}) + "\n")
        return len(recs)


def read_jsonl(path: str) -> tuple[dict, list[dict]]:
    """Inverse of :meth:`Tracer.export_jsonl`:
    ``(header, spans)``. Raises ``ValueError`` on a non-trace file —
    the header line must carry the ``TRACE.`` schema family."""
    with open(path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    if not lines or not str(lines[0].get("schema", "")).startswith("TRACE."):
        raise ValueError(f"{path}: not a trace JSONL (missing "
                         f"{TRACE_SCHEMA!r}-family header line)")
    return lines[0], lines[1:]


# ---------------------------------------------------------------------
# Trace-context propagation (the DCN-hop contract, ROADMAP direction 1)
# ---------------------------------------------------------------------

#: Version tag of the serialized context carrier. Distinct from
#: TRACE_SCHEMA: the carrier crosses a process boundary between
#: possibly different builds, so its compatibility is its own contract.
TRACECTX_SCHEMA = "TRACECTX.v1"

#: The string-header spelling's field separator; ids are generated by
#: :meth:`Tracer.new_id` (``prefix-N``) and never contain it.
_CTX_SEP = ";"


@dataclasses.dataclass(frozen=True)
class SpanContext:
    """The minimal cross-process span identity: which trace a remote
    hop belongs to, and which span is its parent. A receiving process
    emits its spans as ``tracer.span(name, ctx.trace_id,
    parent_id=ctx.parent_id)`` — one request, one trace id, spans on
    both sides of the boundary, exactly the "one span per request
    across the DCN hop" contract direction 1 lands on."""

    trace_id: str
    parent_id: str | None = None


def inject_context(trace_id: str, span_id: str | None = None) -> dict:
    """Serialize a span identity for a process boundary: a flat
    JSON-safe dict (``{"schema", "trace_id", "parent_id"}``). The
    CURRENT span's id becomes the remote side's ``parent_id`` — the
    remote spans hang under the local dispatch span."""
    if not trace_id or not isinstance(trace_id, str):
        raise ValueError(f"trace_id must be a non-empty string, got "
                         f"{trace_id!r}")
    for v in (trace_id, span_id):
        if v is not None and _CTX_SEP in v:
            raise ValueError(
                f"id {v!r} contains the carrier separator "
                f"{_CTX_SEP!r} — not a Tracer.new_id-shaped id")
    return {"schema": TRACECTX_SCHEMA, "trace_id": trace_id,
            "parent_id": span_id}


def format_context(carrier: dict) -> str:
    """The one-line header spelling of an injected carrier
    (``TRACECTX.v1;trace_id;parent_id``) for transports that carry
    strings, not dicts. Empty parent serializes as an empty field."""
    if carrier.get("schema") != TRACECTX_SCHEMA:
        raise ValueError(f"not a {TRACECTX_SCHEMA} carrier: "
                         f"{carrier!r}")
    return _CTX_SEP.join((TRACECTX_SCHEMA, carrier["trace_id"],
                          carrier.get("parent_id") or ""))


def extract_context(carrier) -> SpanContext:
    """Inverse of :func:`inject_context` / :func:`format_context`:
    accepts the dict or the string-header spelling, returns a
    :class:`SpanContext`. Malformed carriers raise ``ValueError``
    naming what is wrong — a dropped trace context on a cross-process
    hop must be a loud bug, not a silently-orphaned span tree."""
    if isinstance(carrier, str):
        parts = carrier.split(_CTX_SEP)
        if len(parts) != 3 or parts[0] != TRACECTX_SCHEMA:
            raise ValueError(
                f"malformed trace-context header {carrier!r} "
                f"(expected '{TRACECTX_SCHEMA};trace_id;parent_id')")
        _, trace_id, parent = parts
    elif isinstance(carrier, dict):
        if carrier.get("schema") != TRACECTX_SCHEMA:
            raise ValueError(
                f"carrier schema {carrier.get('schema')!r} is not "
                f"{TRACECTX_SCHEMA}")
        trace_id = carrier.get("trace_id")
        parent = carrier.get("parent_id")
    else:
        raise ValueError(
            f"carrier must be a dict or header string, got "
            f"{type(carrier).__name__}")
    if not trace_id:
        raise ValueError(f"carrier {carrier!r} has no trace_id")
    return SpanContext(trace_id=trace_id, parent_id=parent or None)


#: The shared disabled tracer: emit/annotate are immediate returns and
#: span() is the no-op singleton. Module-level so hot paths can default
#: to it without constructing anything.
NULL_TRACER = Tracer(enabled=False)

_global_tracer: Tracer = NULL_TRACER
_global_lock = threading.Lock()


def configure(enabled: bool = True, max_spans: int = 1_000_000,
              stream_dir: str | None = None,
              rotate_spans: int = 50_000) -> Tracer:
    """Install (and return) the process-global tracer — how ``exp.py
    --trace_dir`` turns on per-round training spans without threading a
    tracer through every algorithm signature. ``configure(False)``
    restores the free :data:`NULL_TRACER`. ``stream_dir`` makes the
    tracer stream spans to a :class:`RotatingJsonlWriter` there (the
    long-lived-loop mode: O(1) trace memory; ``rotate_spans`` bounds
    each part file)."""
    global _global_tracer
    with _global_lock:
        # build the incoming tracer FIRST: if its writer cannot open
        # (unwritable stream_dir), the old tracer must stay fully
        # functional — closing it before a failed swap would leave a
        # process-wide tracer that raises on every emit
        if not enabled:
            new = NULL_TRACER
        else:
            writer = (RotatingJsonlWriter(stream_dir, rotate_spans)
                      if stream_dir else None)
            new = Tracer(enabled=True, max_spans=max_spans,
                         writer=writer)
        old, _global_tracer = _global_tracer, new
        if old.writer is not None:
            # the outgoing streaming tracer's part file would stay
            # open forever otherwise — one leaked fd per reconfigure
            old.writer.close()
        return _global_tracer


def get_tracer() -> Tracer:
    """The process-global tracer (:data:`NULL_TRACER` until
    :func:`configure`); emitters must treat it as possibly disabled."""
    return _global_tracer
