"""Three-way parity: the ACTUAL reference code as a read-only oracle.

Imports ``/root/reference/functions/tools.py`` (never copied, never
modified) and feeds the SAME RFF-mapped digits tensors — produced by
this repo's torch ``prepare_setup`` — through the reference's own
``Centralized``/``Distributed``/``FedAMW_OneShot``/``FedAvg``/
``FedProx``/``FedNova``/``FedAMW`` (``tools.py:240-463``), then runs
this repo's torch and JAX backends on the same partitions/val-split and
compares final test accuracies across seeds. This closes the round-2
gap where "identical final test accuracy" rested on a
same-author-both-sides comparison (VERDICT.md, missing #1).

Repo arms run with ``sequential=True``: the reference passes one model
object through the client loop, so client i+1 starts from client i's
weights (SURVEY.md §2.3.1) — the repo's compat switch reproduces that
semantics; the default-parallel delta is reported separately.

The operating point (digits, J=20, alpha=0.5, D=500, R=30, lr=2.0) is
the non-degenerate anchor: FedAvg/FedProx genuinely learn here
(~9% -> ~85%+), unlike the alpha=0.01 anchor where fixed-p averaging
pins accuracy at the constant-argmax frequency (VERDICT.md, weak #2).

Usage:
  JAX_PLATFORMS=cpu python oracle_parity.py [--seeds 10] [--round 30]
      [--out results_parity/oracle_summary.json]
  python oracle_parity.py --render results_parity/oracle_summary.json
"""

import argparse
import contextlib
import io
import json
import os
import sys
import time

import numpy as np

REFERENCE_ROOT = "/root/reference"

# the anchor hyperparameters (digits registry values except lr, which is
# re-tuned so FedAvg learns at alpha=0.5 — see module docstring)
ANCHOR = dict(
    task="classification",
    dataset="digits", num_partitions=20, alpha=0.5, D=500,
    kernel_par=0.1, lr=2.0, epoch=2, batch_size=32,
    mu=0.0001, lambda_reg=0.0005, lambda_reg_os=0.0005,
    lr_p=5e-6, lr_p_os=0.005,
)
# The MSE-branch anchor (VERDICT r3, missing #3): synthetic_nonlinear is
# the reference's own regression path — tune.py:58-66 builds it via
# load_synthetic_data (utils.py:74-84), and train/test_loop switch to
# nn.MSELoss (tools.py:183-184, 231-234). Registry hyperparameters
# (config.py "synthetic_nonlinear": kernel_par=0.1, lambda_reg=1e-6,
# lambda_prox=7e-7, lr=0.001); for regression the compared metric is
# final test MSE (the reference's comp_accuracy is meaningless on
# (B,1) float targets — its "acc" column reads ~0 for both arms).
# lr is re-tuned 0.001 -> 0.2 like the classification anchor's 2.0: at
# the registry lr the oracle itself barely escapes the var(y)~10
# baseline in a test-sized round budget; at 0.2 CL reaches the 0.04
# label-noise floor and FedAMW (0.07) genuinely beats FedAvg (1.2) —
# the paper's own headline ordering, so parity here is informative.
REG_ANCHOR = dict(
    task="regression",
    dataset="synthetic_nonlinear", num_partitions=10, alpha=0.0, D=200,
    kernel_par=0.1, lr=0.2, epoch=2, batch_size=32,
    mu=7e-7, lambda_reg=1e-6, lambda_reg_os=1e-6,
    lr_p=5e-6, lr_p_os=0.005,
)
# The exp.py-scale anchor (VERDICT r3, next #4): the driver's own
# client count and feature width (J=50, D=2000 — /root/reference/
# exp.py:32,34) at alpha=0.5, where FedAvg genuinely learns (the
# alpha=0.01 default pins fixed-p averaging at the constant-argmax
# frequency; PARITY.md §2 attributes that degeneracy with the oracle).
# lr=2.0 as in the §1 anchor; the sequential oracle is slow at J=50
# (~60 s/seed), so the committed matrix trades rounds for seeds:
# 10 seeds at R=10 — a real paired t-test at a reduced round budget
# (stated in PARITY.md §4).
EXP50_ANCHOR = dict(
    task="classification",
    dataset="digits", num_partitions=50, alpha=0.5, D=2000,
    kernel_par=0.1, lr=2.0, epoch=2, batch_size=32,
    mu=0.0001, lambda_reg=0.0005, lambda_reg_os=0.0005,
    lr_p=5e-6, lr_p_os=0.005,
)
ALGOS = ["CL", "DL", "FedAMW_OneShot", "FedAvg", "FedProx", "FedNova",
         "FedAMW"]


def _metric_key(task):
    return "test_acc" if task == "classification" else "test_loss"


def _load_oracle():
    """Import the reference package read-only, without copying it.

    The path entry is removed again immediately: the reference checkout
    has top-level ``exp.py``/``tune.py`` that would otherwise shadow
    this repo's same-named modules for the rest of the process (e.g. a
    later in-process ``import tune`` would hit the reference's, which
    unconditionally imports NNI). The reference's module-global device
    is pinned to CPU (``tools.py:12`` selects CUDA when available; every
    consumer here compares CPU-to-CPU on CPU tensors).
    """
    import torch

    sys.path.insert(0, REFERENCE_ROOT)
    try:
        import functions.tools as reference_tools
    finally:
        sys.path.remove(REFERENCE_ROOT)
    reference_tools.device = torch.device("cpu")
    return reference_tools


def reference_inputs(setup, val_batch_size=16):
    """A repo ``TorchSetup``'s tensors in the reference's calling
    convention: per-client tensor lists + the pooled shuffled val
    loader (reference ``exp.py:78-99``, batch 16). For regression the
    labels go in as ``(n, 1)`` — the shape the reference's synthetic
    branch feeds ``nn.MSELoss`` (``tune.py:59-66`` reshapes to
    ``(-1, num_classes)`` with ``num_classes=1``); the repo keeps flat
    ``(n,)`` labels and reshapes inside its objective."""
    from torch.utils.data import DataLoader, TensorDataset

    X_train = [setup.X[p] for p in setup.parts]
    y_train = [setup.y[p] for p in setup.parts]
    y_val = setup.y_val
    if setup.task != "classification":
        y_train = [t.reshape(-1, 1) for t in y_train]
        y_val = y_val.reshape(-1, 1)
    validloader = DataLoader(TensorDataset(setup.X_val, y_val),
                             batch_size=val_batch_size, shuffle=True)
    return X_train, y_train, validloader


def reference_y_test(setup):
    """``setup.y_test`` in the reference's calling convention: ``(n, 1)``
    for regression (the shape its ``nn.MSELoss`` expects against the
    model's ``(n, 1)`` output — flat labels would broadcast to
    ``(n, n)``), unchanged for classification."""
    if setup.task != "classification":
        return setup.y_test.reshape(-1, 1)
    return setup.y_test


def _final(res, key="test_acc"):
    return float(np.asarray(res[key]).reshape(-1)[-1])


def _pick(tl, acc, task):
    """Final value of the compared metric (``_metric_key``): test
    accuracy for classification, test MSE for regression (see
    REG_ANCHOR note). Shares ``_final``'s extraction with the repo
    arms so both sides always compare the same quantity."""
    if hasattr(tl, "detach"):
        tl = tl.detach()
    if hasattr(acc, "detach"):
        acc = acc.detach()
    return _final({"test_loss": tl, "test_acc": acc}, _metric_key(task))


def run_oracle(setup, rounds, seed, anchor=None):
    """Run all seven reference algorithms (tools.py:240-463) on the
    repo-produced tensors. Returns {algo: final metric} (acc for
    classification, test MSE for regression)."""
    import torch

    anchor = anchor or ANCHOR
    rt = _load_oracle()
    torch.manual_seed(seed)
    X_train, y_train, validloader = reference_inputs(setup)
    kw = dict(X_test=setup.X_test, y_test=reference_y_test(setup),
              type=setup.task, num_classes=setup.num_classes, D=setup.D,
              batch_size=anchor["batch_size"])
    lr, ep, task = anchor["lr"], anchor["epoch"], setup.task
    out = {}
    sink = io.StringIO()  # test_loop prints every call (tools.py:236)
    with contextlib.redirect_stdout(sink):
        _, tl, acc = rt.Centralized(X_train, y_train, lr=lr,
                                    epoch=ep * rounds, **kw)
        out["CL"] = _pick(tl, acc, task)
        _, tl, acc = rt.Distributed(X_train, y_train, lr=lr,
                                    epoch=ep * rounds, **kw)
        out["DL"] = _pick(tl, acc, task)
        _, tl, acc = rt.FedAMW_OneShot(
            X_train, y_train, validloader=validloader, lr=lr,
            epoch=ep * rounds, lambda_reg_if=True,
            lambda_reg=anchor["lambda_reg_os"], round=rounds,
            lr_p=anchor["lr_p_os"], **kw)
        out["FedAMW_OneShot"] = _pick(tl, acc, task)
        _, tl, acc = rt.FedAvg(X_train, y_train, lr=lr, epoch=ep,
                               round=rounds, **kw)
        out["FedAvg"] = _pick(tl, acc, task)
        _, tl, acc = rt.FedProx(X_train, y_train, lr=lr, epoch=ep,
                                prox=True, mu=anchor["mu"], round=rounds,
                                **kw)
        out["FedProx"] = _pick(tl, acc, task)
        _, tl, acc = rt.FedNova(X_train, y_train, lr=lr, epoch=ep,
                                round=rounds, **kw)
        out["FedNova"] = _pick(tl, acc, task)
        _, tl, acc = rt.FedAMW(X_train, y_train, validloader=validloader,
                               lr=lr, epoch=ep, lambda_reg_if=True,
                               lambda_reg=anchor["lambda_reg"],
                               round=rounds, lr_p=anchor["lr_p"], **kw)
        out["FedAMW"] = _pick(tl, acc, task)
    return out


def run_repo(backend_name, rounds, seed, sequential=True, anchor=None):
    """Run the repo backend on the same partitions/val split.
    Returns {algo: final metric} (acc / test MSE by anchor task)."""
    from fedamw_tpu.data import load_dataset
    from fedamw_tpu.registry import get_backend

    anchor = anchor or ANCHOR
    key = _metric_key(anchor["task"])
    be = get_backend(backend_name)
    rng = np.random.RandomState(seed)
    ds = load_dataset(anchor["dataset"], anchor["num_partitions"],
                      anchor["alpha"], rng=rng)
    setup = be.prepare_setup(ds, D=anchor["D"],
                             kernel_par=anchor["kernel_par"],
                             seed=seed, rng=rng)
    lr, ep, bs = anchor["lr"], anchor["epoch"], anchor["batch_size"]
    common = dict(batch_size=bs, seed=seed, sequential=sequential)
    a = be.ALGORITHMS
    out = {
        "CL": _final(a["Centralized"](setup, lr=lr, epoch=ep * rounds,
                                      **common), key),
        "DL": _final(a["Distributed"](setup, lr=lr, epoch=ep * rounds,
                                      **common), key),
        "FedAMW_OneShot": _final(a["FedAMW_OneShot"](
            setup, lr=lr, epoch=ep * rounds, lambda_reg_if=True,
            lambda_reg=anchor["lambda_reg_os"], round=rounds,
            lr_p=anchor["lr_p_os"], **common), key),
        "FedAvg": _final(a["FedAvg"](setup, lr=lr, epoch=ep,
                                     round=rounds, **common), key),
        "FedProx": _final(a["FedProx"](setup, lr=lr, epoch=ep, prox=True,
                                       mu=anchor["mu"], round=rounds,
                                       **common), key),
        "FedNova": _final(a["FedNova"](setup, lr=lr, epoch=ep,
                                       round=rounds, **common), key),
        "FedAMW": _final(a["FedAMW"](setup, lr=lr, epoch=ep,
                                     lambda_reg_if=True,
                                     lambda_reg=anchor["lambda_reg"],
                                     round=rounds, lr_p=anchor["lr_p"],
                                     **common), key),
    }
    return out


def _build_torch_setup(seed, anchor=None):
    from fedamw_tpu.backends import torch_ref
    from fedamw_tpu.data import load_dataset

    anchor = anchor or ANCHOR
    rng = np.random.RandomState(seed)
    ds = load_dataset(anchor["dataset"], anchor["num_partitions"],
                      anchor["alpha"], rng=rng)
    return torch_ref.prepare_setup(ds, D=anchor["D"],
                                   kernel_par=anchor["kernel_par"],
                                   seed=seed, rng=rng)


def collect(seeds, rounds, out_path, with_parallel=True, anchor=None):
    anchor = anchor or ANCHOR
    summary = {
        "anchor": {**anchor, "round": rounds},
        "task": anchor["task"],
        "seeds": list(seeds),
        "arms": {"reference": [], "torch_seq": [], "jax_seq": []},
    }
    if with_parallel:
        summary["arms"]["jax_parallel"] = []
    for s in seeds:
        t0 = time.time()
        setup = _build_torch_setup(s, anchor)
        summary["arms"]["reference"].append(
            run_oracle(setup, rounds, s, anchor))
        summary["arms"]["torch_seq"].append(
            run_repo("torch", rounds, s, anchor=anchor))
        summary["arms"]["jax_seq"].append(
            run_repo("jax", rounds, s, anchor=anchor))
        if with_parallel:
            summary["arms"]["jax_parallel"].append(
                run_repo("jax", rounds, s, sequential=False,
                         anchor=anchor))
        print(f"[seed {s}] done in {time.time() - t0:.1f}s", flush=True)
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(summary, f, indent=1)
    print(f"summary -> {out_path}")
    return summary


def render(summary):
    """Markdown table: reference oracle vs repo arms, with the
    reference's own paired t-test (functions/utils.py:351-353)."""
    from fedamw_tpu.utils.reporting import check_significance

    task = summary.get("task", "classification")
    regression = task == "regression"
    arms = summary["arms"]
    acc = {arm: {a: np.array([r[a] for r in runs])
                 for a in ALGOS}
           for arm, runs in arms.items()}
    n = len(summary["seeds"])
    a_cfg = summary["anchor"]
    metric = "final test MSE (lower better)" if regression else \
        "final test accuracy"
    lines = [
        "## Parity vs the actual reference code (oracle import"
        + (", regression/MSE branch)" if regression else ")"),
        "",
        f"`oracle_parity.py` imports `/root/reference/functions/tools.py`",
        "read-only and feeds the SAME RFF-mapped tensors (this repo's",
        "torch `prepare_setup` output, identical partitions + val split)",
        "through the reference's own algorithm functions",
        "(`tools.py:240-463`). Repo arms run `sequential=True` to match",
        "the reference's client-contamination semantics (SURVEY.md",
        f"§2.3.1). Anchor: {a_cfg['dataset']}, J={a_cfg['num_partitions']},",
        f"alpha={a_cfg['alpha']}, D={a_cfg['D']}, R={a_cfg['round']},",
        f"lr={a_cfg['lr']}, {n} seeds {summary['seeds']}."
        f" Metric: {metric}.",
    ]
    if regression:
        lines += [
            "This exercises the reference's MSE branches",
            "(`tools.py:183-184, 231-234`) via its own synthetic",
            "regression path (`tune.py:58-66`, `utils.py:74-84`).",
        ]
    else:
        lines += [
            "Anchor chosen so FedAvg/FedProx genuinely learn (no"
            " degenerate rows).",
        ]
    lines += [
        "",
        "| Algorithm | reference | repo-torch (seq) | repo-JAX (seq) |"
        " Δ(jax-ref) | t-test vs ref | parity |",
        "|---|---|---|---|---|---|---|",
    ]
    all_ok = True
    band = 2.0  # accuracy points (classification)
    fmt = "{:.4f}±{:.4f}" if regression else "{:.2f}±{:.2f}"
    for algo in ALGOS:
        r = acc["reference"][algo]
        tq = acc["torch_seq"][algo]
        jq = acc["jax_seq"][algo]
        d = jq.mean() - r.mean()
        if regression:
            # lower is better: negate so check_significance's
            # higher-is-better convention applies
            jax_beats = check_significance(-r, -jq)
            ref_beats = check_significance(-jq, -r)
            # 5% relative, with an absolute floor of half the 0.04
            # label-noise variance: near the noise floor a 5%-of-0.04
            # band would be tighter than seed-to-seed RNG noise
            ok_band = abs(d) <= max(0.05 * abs(r.mean()), 0.02)
            dcol = f"{d:+.4f}"
        else:
            jax_beats = check_significance(r, jq)
            ref_beats = check_significance(jq, r)
            ok_band = abs(d) <= band
            dcol = f"{d:+.2f}"
        winner = ("jax" if jax_beats else
                  "reference" if ref_beats else "none")
        ok = ok_band or winner == "none"
        all_ok &= ok
        lines.append(
            f"| {algo} | {fmt.format(r.mean(), r.std())} | "
            f"{fmt.format(tq.mean(), tq.std())} | "
            f"{fmt.format(jq.mean(), jq.std())} | {dcol} | {winner} | "
            f"{'YES' if ok else 'NO'} |")
    lines.append("")
    lines.append(
        ("Parity = |Δmean| <= max(5% of the reference MSE, 0.02) OR"
         if regression else
         f"Parity = |Δmean| <= {band} accuracy points OR")
        + " the reference's"
        " paired t-test (threshold 1.812) finds no significant winner"
        " in either direction.")
    if "jax_parallel" in acc:
        dfmt = "+.4f" if regression else "+.2f"
        deltas = ", ".join(
            f"{algo} {acc['jax_parallel'][algo].mean() - acc['jax_seq'][algo].mean():{dfmt}}"
            for algo in ALGOS)
        lines.append("")
        unit = "MSE" if regression else "accuracy"
        lines.append(
            "Default-parallel JAX (every client starts from the round's"
            " global weights — the paper's semantics, repo default) vs"
            f" sequential compat, Δmean {unit}: {deltas}. The large"
            " deltas are an operating-point effect, not a defect: the"
            " reference's contamination chain applies J*epoch"
            " consecutive SGD passes to ONE model per round, so at an"
            " lr tuned for that chain, averaging J independent"
            " 2-epoch updates moves far less per round; parallel"
            " semantics needs its own lr/round budget (the paper's"
            " convergence analysis assumes the parallel form).")
    lines.append("")
    lines.append(f"Overall: {'PARITY WITH THE REFERENCE ORACLE' if all_ok else 'FAILURES — see table'}.")
    return "\n".join(lines), all_ok


def degenerate_check(rounds=30, seed=100):
    """The exp.py-defaults anchor (digits, J=50, alpha=0.01, D=2000)
    where PARITY.md §2's FedAvg/FedProx rows sit flat at 8.61: run the
    REFERENCE's own FedAvg there, plus both repo backends in sequential
    and parallel modes, to pin which semantics owns the degeneracy.

    Oracle-verified conclusion (also printed): the flat rows belong to
    the PARALLEL form — the paper's described algorithm and the repo
    default, where the one-class client updates average out — while the
    reference's sequential-contamination artifact (one model chained
    through clients, tools.py:341) lets its code partially escape;
    ``sequential=True`` reproduces that escape on both backends.
    """
    import torch

    from fedamw_tpu.data import load_dataset
    from fedamw_tpu.registry import get_backend

    point = dict(dataset="digits", J=50, alpha=0.01, D=2000,
                 kernel_par=0.1, lr=0.5, epoch=2, batch_size=32)
    out = {"anchor": {**point, "round": rounds, "seed": seed}}

    from fedamw_tpu.backends import torch_ref

    rng = np.random.RandomState(seed)
    ds = load_dataset(point["dataset"], point["J"], point["alpha"],
                      rng=rng)
    tsetup = torch_ref.prepare_setup(ds, D=point["D"],
                                     kernel_par=point["kernel_par"],
                                     seed=seed, rng=rng)
    rt = _load_oracle()
    torch.manual_seed(seed)
    X_train, y_train, _ = reference_inputs(tsetup)
    with contextlib.redirect_stdout(io.StringIO()):
        _, _, acc = rt.FedAvg(
            X_train, y_train, X_test=tsetup.X_test, y_test=tsetup.y_test,
            type="classification", num_classes=tsetup.num_classes,
            D=point["D"], lr=point["lr"], epoch=point["epoch"],
            batch_size=point["batch_size"], round=rounds)
    a = np.asarray(acc)
    out["reference"] = {"first": float(a[0]), "last": float(a[-1])}

    for backend in ("jax", "torch"):
        be = get_backend(backend)
        for sequential in (True, False):
            rng = np.random.RandomState(seed)
            ds = load_dataset(point["dataset"], point["J"],
                              point["alpha"], rng=rng)
            setup = be.prepare_setup(ds, D=point["D"],
                                     kernel_par=point["kernel_par"],
                                     seed=seed, rng=rng)
            res = be.ALGORITHMS["FedAvg"](
                setup, lr=point["lr"], epoch=point["epoch"],
                batch_size=point["batch_size"], round=rounds, seed=seed,
                sequential=sequential)
            acc = np.asarray(res["test_acc"])
            out[f"{backend}_{'seq' if sequential else 'par'}"] = {
                "first": float(acc[0]), "last": float(acc[-1]),
                "ptp": float(np.ptp(acc)),
            }
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seeds", type=int, default=10,
                    help="all committed PARITY.md matrices use 10")
    ap.add_argument("--seed0", type=int, default=100)
    ap.add_argument("--round", type=int, default=30)
    ap.add_argument("--task",
                    choices=["classification", "regression", "exp50"],
                    default="classification",
                    help="regression switches to REG_ANCHOR "
                         "(synthetic_nonlinear, MSE metric); exp50 to "
                         "EXP50_ANCHOR (the driver's J=50/D=2000 scale "
                         "at a non-degenerate alpha)")
    ap.add_argument("--out", type=str, default=None)
    ap.add_argument("--render", type=str, default=None, metavar="JSON",
                    help="render markdown from an existing summary "
                         "instead of running")
    ap.add_argument("--degenerate-check", action="store_true",
                    help="run the exp.py-defaults degeneracy attribution "
                         "check (see degenerate_check), print JSON, and "
                         "write the artifact to --degen-out")
    ap.add_argument("--degen-out", type=str,
                    default="results_parity/degenerate_check.json")
    args = ap.parse_args()
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if args.degenerate_check:
        out = degenerate_check(args.round, args.seed0)
        os.makedirs(os.path.dirname(args.degen_out) or ".", exist_ok=True)
        with open(args.degen_out, "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps(out, indent=1))
        print(f"artifact -> {args.degen_out}", file=sys.stderr)
        return 0
    if args.render:
        with open(args.render) as f:
            summary = json.load(f)
        text, ok = render(summary)
        print(text)
        return 0 if ok else 1
    anchor = {"classification": ANCHOR, "regression": REG_ANCHOR,
              "exp50": EXP50_ANCHOR}[args.task]
    out = args.out or {
        "classification": "results_parity/oracle_summary.json",
        "regression": "results_parity/oracle_regression_summary.json",
        "exp50": "results_parity/oracle_exp50_summary.json",
    }[args.task]
    summary = collect(range(args.seed0, args.seed0 + args.seeds),
                      args.round, out, anchor=anchor)
    text, ok = render(summary)
    print(text)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
