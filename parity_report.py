"""Generate PARITY.md: JAX-vs-torch accuracy parity at the reference
operating point (digits, 50 clients, alpha=0.01, D=2000, R=100 —
reference ``exp.py:31-41``).

Parity criterion per algorithm, two conditions, either suffices:

1. practical equivalence: |mean difference| <= 1.0 accuracy point
   (the paired t-statistic degenerates when both backends produce
   near-identical numbers — a constant 1e-6 gap across seeds yields an
   "infinite" t; the reference's threshold-1.812 test was built to
   separate DIFFERENT algorithms, not arms of the same algorithm);
2. statistical equivalence: the reference's own significance test
   (``functions/utils.py:351-353``, paired-by-seed t > 1.812 — pairing
   is meaningful because the partition stream is numpy-seeded and
   identical across backends) finds NO significant winner in either
   direction.

This makes the "identical final test accuracy" north star concrete:
torch/JAX RNG streams cannot match bitwise (SURVEY.md §2.3.4), so
parity is necessarily statistical.

Usage: python parity_report.py results_parity/jax/exp1_digits.pkl \
           results_parity/torch/exp1_digits.pkl > PARITY.md
"""

import sys

import numpy as np

from fedamw_tpu.utils.reporting import check_significance, load_results

PRACTICAL_BAND = 1.0  # accuracy points


def final_acc(res):
    # (6, R, n_repeats) -> final-round accuracies per algorithm: (6, n_repeats)
    return np.asarray(res["test_acc"])[:, -1, :]


def main(jax_pkl, torch_pkl, note=None):
    import os

    rj, rt = load_results(jax_pkl), load_results(torch_pkl)
    assert rj["name"] == rt["name"]
    aj, at = final_acc(rj), final_acc(rt)
    n = aj.shape[1]
    rounds = rj["epochs"]
    dataset = os.path.basename(jax_pkl).replace("exp1_", "").replace(
        ".pkl", "")

    print("# PARITY — JAX vs torch-CPU at the reference operating point")
    print()
    print(f"dataset `{dataset}`, {rounds} rounds, n_repeats={n} — the")
    print("remaining settings are the exp.py driver defaults (50 clients,")
    print("Dirichlet alpha=0.01, D=2000 RFF, 2 local epochs, batch 32 —")
    print("the reference's constants, `/root/reference/exp.py:31-41` —")
    print("unless the run that produced the pickles overrode them).")
    if note:
        print(note)
    print("Parity per algorithm =")
    print(f"|Δmean| <= {PRACTICAL_BAND} accuracy point (practical")
    print("equivalence) OR the reference's own t-test (threshold 1.812,")
    print("`functions/utils.py:351-353`, paired by seed — the partition")
    print("stream is identical across backends) finds no significant")
    print("winner in either direction. See parity_report.py's docstring")
    print("for why the practical band exists (the paired t degenerates")
    print("on near-identical arms).")
    print()
    print("| Algorithm | JAX acc (mean±std) | torch acc (mean±std) | "
          "Δmean | t-test winner | parity |")
    print("|---|---|---|---|---|---|")
    ok = True
    for i, name in enumerate(rj["name"]):
        jm, js = aj[i].mean(), aj[i].std()
        tm, ts = at[i].mean(), at[i].std()
        jax_beats = check_significance(at[i], aj[i])
        torch_beats = check_significance(aj[i], at[i])
        winner = "jax" if jax_beats else ("torch" if torch_beats else "none")
        par = abs(jm - tm) <= PRACTICAL_BAND or winner == "none"
        ok &= par
        print(f"| {name} | {jm:.2f}±{js:.2f} | {tm:.2f}±{ts:.2f} | "
              f"{jm - tm:+.2f} | {winner} | {'YES' if par else 'NO'} |")
    print()
    # Flag degenerate-but-faithful rows: under extreme label skew the
    # fixed-p average of the client updates cancels and the global model
    # never escapes its initial predictions — the paper's motivating
    # FedAvg/FedProx failure mode, the regime FedAMW's learned mixture
    # weights exist to fix. Every claim in the printed note is verified
    # against the pickles (zero seed variance, identical means, AND a
    # flat test-loss trajectory on both backends) so the note cannot
    # assert a mechanism the run doesn't exhibit.
    tl_j = np.asarray(rj["test_loss"])
    tl_t = np.asarray(rt["test_loss"])
    degenerate = []
    for i, name in enumerate(rj["name"]):
        if name not in ("FedAvg", "FedProx"):
            continue
        flat = np.ptp(tl_j[i]) < 0.1 and np.ptp(tl_t[i]) < 0.1
        frozen = (aj[i].std() == 0 and at[i].std() == 0
                  and abs(aj[i].mean() - at[i].mean()) < 1e-6)
        if flat and frozen:
            degenerate.append(i)
    if degenerate:
        per_algo = "; ".join(
            f"{rj['name'][i]} {aj[i].mean():.2f}±0.00, flat test loss "
            f"JAX {tl_j[i].min():.4f}..{tl_j[i].max():.4f} / torch "
            f"{tl_t[i].min():.4f}..{tl_t[i].max():.4f}"
            for i in degenerate)
        print(f"Note ({per_algo} — each across all rounds and seeds, "
              "identical on both backends): under "
              "this run's label skew the fixed-p average of the client "
              "updates cancels and the global model never escapes its "
              "initial predictions, so accuracy pins at the "
              "constant-argmax class's test frequency with zero seed "
              "variance (the Dirichlet partition stream is fixed, "
              "reference `functions/utils.py:320`). This is the extreme "
              "non-IID failure mode the paper's FedAMW targets — "
              "compare the FedAMW row on the same partitions — "
              "reproduced identically by both backends, not a numerical "
              "artifact. Attribution: the degeneracy belongs to the "
              "PARALLEL client semantics both backends default to (the "
              "paper's described form); the reference's own loop "
              "partially escapes it through its sequential "
              "client-contamination artifact (`tools.py:341`), and "
              "`sequential=True` reproduces that escape on both "
              "backends — oracle-verified by "
              "`oracle_parity.py --degenerate-check` (numbers in "
              "PARITY.md's degeneracy-attribution note).")
        print()
    print(f"Overall: {'ALL SIX ALGORITHMS IN PARITY' if ok else 'PARITY FAILURES — see table'}.")
    print()
    print("Heterogeneity scores (same partition stream, must match closely):")
    print(f"JAX {np.asarray(rj['heterogeneity']).round(4).tolist()} vs "
          f"torch {np.asarray(rt['heterogeneity']).round(4).tolist()}")
    return 0 if ok else 1


if __name__ == "__main__":
    # optional third arg: a sentence appended to the header describing
    # deliberate overrides (e.g. "This table's runs override lr=8.0 …")
    sys.exit(main(sys.argv[1], sys.argv[2],
                  note=sys.argv[3] if len(sys.argv) > 3 else None))
