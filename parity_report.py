"""Generate PARITY.md: JAX-vs-torch accuracy parity at the reference
operating point (digits, 50 clients, alpha=0.01, D=2000, R=100,
n_repeats=3 — reference ``exp.py:31-41``).

Parity criterion per algorithm: the reference's own significance test
(``functions/utils.py:351-353``, paired t > 1.812) applied in BOTH
directions across seed-repeats — parity holds when neither backend
significantly beats the other (the "identical final test accuracy"
north star, made statistical because torch/JAX RNG streams cannot match
bitwise; SURVEY.md §2.3.4).

Usage: python parity_report.py results_parity/jax/exp1_digits.pkl \
           results_parity/torch/exp1_digits.pkl > PARITY.md
"""

import sys

import numpy as np

from fedamw_tpu.utils.reporting import check_significance, load_results


def final_acc(res):
    # (6, R, n_repeats) -> final-round accuracies per algorithm: (6, n_repeats)
    return np.asarray(res["test_acc"])[:, -1, :]


def main(jax_pkl, torch_pkl):
    rj, rt = load_results(jax_pkl), load_results(torch_pkl)
    assert rj["name"] == rt["name"]
    aj, at = final_acc(rj), final_acc(rt)

    print("# PARITY — JAX-TPU vs torch-CPU at the reference operating point")
    print()
    print("digits, 50 clients, Dirichlet alpha=0.01, D=2000 RFF, 100 rounds,")
    print("2 local epochs, batch 32, n_repeats=3 (seeds 100/101/102) — the")
    print("reference driver's constants (`/root/reference/exp.py:31-41`).")
    print("Parity = the reference's own t-test (threshold 1.812,")
    print("`functions/utils.py:351-353`) finds NO significant winner in")
    print("either direction across seed-repeats.")
    print()
    print("| Algorithm | JAX acc (mean±std) | torch acc (mean±std) | "
          "Δmean | parity |")
    print("|---|---|---|---|---|")
    ok = True
    for i, name in enumerate(rj["name"]):
        jm, js = aj[i].mean(), aj[i].std()
        tm, ts = at[i].mean(), at[i].std()
        jax_beats = check_significance(at[i], aj[i])
        torch_beats = check_significance(aj[i], at[i])
        par = not (jax_beats or torch_beats)
        ok &= par
        print(f"| {name} | {jm:.2f}±{js:.2f} | {tm:.2f}±{ts:.2f} | "
              f"{jm - tm:+.2f} | {'YES' if par else 'NO'} |")
    print()
    print(f"Overall: {'ALL SIX ALGORITHMS IN PARITY' if ok else 'PARITY FAILURES — see table'}.")
    print()
    print("Heterogeneity scores (same partition stream, must match closely):")
    print(f"JAX {np.asarray(rj['heterogeneity']).round(4).tolist()} vs "
          f"torch {np.asarray(rt['heterogeneity']).round(4).tolist()}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1], sys.argv[2]))
