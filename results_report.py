"""Render an ``exp1_{dataset}.pkl`` driver artifact into a results table.

The reference emits its paper tables through LaTeX row builders
(``functions/utils.py:355-378``); this renders the same content from
the driver's pickle schema (``exp.py:109-121``, identical to reference
``exp.py:132-143``): per-algorithm final test accuracy (mean ± std over
repeats), the reference's own significance markup (best bold, rows not
significantly worse underlined, threshold 1.812), and the per-repeat
data-heterogeneity scores.

Usage: python results_report.py results/exp1_digits.pkl [--markdown]
"""

import argparse

import numpy as np

from fedamw_tpu.utils.reporting import (check_significance, load_results,
                                        print_acc)


def final_acc(res):
    # (6, R, n_repeats) -> final-round accuracies per algorithm
    return np.asarray(res["test_acc"])[:, -1, :]


def render_markdown(res):
    acc = final_acc(res)
    names = list(res["name"])
    best = int(np.argmax(acc.mean(axis=1)))
    lines = [
        "| Algorithm | final test acc (mean±std over "
        f"{acc.shape[1]} repeats) | vs best |",
        "|---|---|---|",
    ]
    for i, name in enumerate(names):
        row = acc[i]
        if i == best:
            mark = "**best**"
        elif check_significance(row, acc[best]):
            mark = "significantly worse"
        else:
            mark = "not significantly worse"
        lines.append(f"| {name} | {row.mean():.2f}±{row.std():.2f} "
                     f"| {mark} |")
    het = np.asarray(res["heterogeneity"])
    lines.append("")
    lines.append(f"Data heterogeneity per repeat: "
                 f"{np.round(het, 4).tolist()}; rounds={res['epochs']}.")
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("pkl")
    ap.add_argument("--markdown", action="store_true",
                    help="markdown table instead of the LaTeX row")
    args = ap.parse_args()
    res = load_results(args.pkl)
    if args.markdown:
        print(render_markdown(res))
    else:
        # the reference's exact emitter (best bold / underline rule)
        print(" ".join(res["name"]))
        print(print_acc(final_acc(res)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
