"""Render an ``exp1_{dataset}.pkl`` driver artifact into a results table.

The reference emits its paper tables through LaTeX row builders
(``functions/utils.py:355-378``); this renders the same content from
the driver's pickle schema (``exp.py:109-121``, identical to reference
``exp.py:132-143``): per-algorithm final test accuracy (mean ± std over
repeats), the reference's own significance markup (best bold, rows not
significantly worse underlined, threshold 1.812), and the per-repeat
data-heterogeneity scores.

Usage: python results_report.py results/exp1_digits.pkl [--markdown]
"""

import argparse

import numpy as np

from fedamw_tpu.utils.reporting import (check_significance, load_results,
                                        print_acc)


def final_acc(res):
    # (6, R, n_repeats) -> final-round accuracies per algorithm
    return np.asarray(res["test_acc"])[:, -1, :]


def is_regression(res):
    """True when the artifact's meaningful final metric is test_loss
    (MSE, lower better) rather than accuracy.

    Artifacts written since the ``task`` key shipped carry the task
    type explicitly (``exp.py`` records the registry's task_type);
    only legacy pickles fall back to the all-zero-accuracy inference
    (the accuracy metric is classification-only,
    ``fedcore/evaluate.py``) — which a fully-degenerate classification
    run could fool, hence the recorded key (round-4 advisor)."""
    if "task" in res:
        return res["task"] == "regression"
    return bool(np.allclose(np.asarray(res["test_acc"]), 0.0))


def render_markdown(res):
    names = list(res["name"])
    if is_regression(res):
        # lower-is-better: rank by final test MSE; reuse the reference's
        # t-test by negating (check_significance asks "does best beat
        # row", defined on higher-is-better arrays)
        met = np.asarray(res["test_loss"])[:, -1, :]
        means = np.where(np.all(np.isfinite(met), axis=1),
                         met.mean(axis=1), np.inf)
        best = int(np.argmin(means))
        sig = lambda row: check_significance(-row, -met[best])
        head = f"final test MSE (mean±std over {met.shape[1]} repeats)"
        fmt = "{:.4f}±{:.4f}"
    else:
        met = final_acc(res)
        means = np.where(np.all(np.isfinite(met), axis=1),
                         met.mean(axis=1), -np.inf)
        best = int(np.argmax(means))
        sig = lambda row: check_significance(row, met[best])
        head = f"final test acc (mean±std over {met.shape[1]} repeats)"
        fmt = "{:.2f}±{:.2f}"
    lines = [
        f"| Algorithm | {head} | vs best |",
        "|---|---|---|",
    ]
    for i, name in enumerate(names):
        row = met[i]
        if not np.all(np.isfinite(row)):
            # a diverged run can never be best; count the blowups
            bad = int(np.sum(~np.isfinite(row)))
            fin = row[np.isfinite(row)]
            shown = (fmt.format(fin.mean(), fin.std())
                     if fin.size else "—")
            lines.append(f"| {name} | {shown} "
                         f"| diverged (non-finite in {bad}/{row.size} "
                         "repeats) |")
            continue
        if i == best:
            mark = "**best**"
        elif sig(row):
            mark = "significantly worse"
        else:
            mark = "not significantly worse"
        lines.append(f"| {name} | {fmt.format(row.mean(), row.std())} "
                     f"| {mark} |")
    het = np.asarray(res["heterogeneity"])
    lines.append("")
    lines.append(f"Data heterogeneity per repeat: "
                 f"{np.round(het, 4).tolist()}; rounds={res['epochs']}.")
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("pkl")
    ap.add_argument("--markdown", action="store_true",
                    help="markdown table instead of the LaTeX row")
    args = ap.parse_args()
    res = load_results(args.pkl)
    if args.markdown or is_regression(res):
        # the reference's LaTeX emitter assumes accuracy (best=max);
        # regression artifacts always render the markdown MSE table
        print(render_markdown(res))
    else:
        # the reference's exact emitter (best bold / underline rule)
        print(" ".join(res["name"]))
        print(print_acc(final_acc(res)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
