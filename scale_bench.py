"""BASELINE.md scale configs on real hardware (SURVEY hard parts 1 & 6).

Two configurations the reference could never run (its client loop is
sequential Python) but BASELINE.json pins as scale targets:

1. ``covtype``-shaped 2-layer MLP, 1024 Dirichlet(alpha=0.1) clients —
   581,012 examples x 54 features x 7 classes, raw features into
   ``mlp64`` (covtype is not in the reference registry; the raw-feature
   MLP replaces linear+RFF here, which is the point of the config).
2. ``rcv1.binary``-shaped logistic regression, 4096 clients — 20,242
   train examples at d=47,236 / ~0.16% density, RFF-mapped to D=2000
   through the sparse chunked mapper (``ops/rff.py:rff_map_sparse``),
   which never densifies the d-dimensional input.

Both use size-bucketed packing (64 buckets) with ``min_size=0`` (the
reference's min-10 retry is unsatisfiable at this client count,
``functions/utils.py:323``). Real LIBSVM files are not downloadable here
(zero egress), so deterministic shape-matched synthetics stand in; the
arithmetic per update matches the real sets'.

Prints one JSON line per config:
    {"config": ..., "clients": ..., "updates_per_sec": ...,
     "final_acc": ..., "hbm_peak_gb": ..., "wall_s": ...}

Env: SCALE_ROUNDS (default 10), SCALE_BUCKETS (default 64),
SCALE_CONFIGS (comma list, default
"covtype1024,rcv14096,mnistconv512" — the third is an MNIST-shaped
512-client run of the zoo's compact CNN, the MXU-heavy config).

The ``cohort`` leg (SCALE_CONFIGS includes ``cohort1m``; ROADMAP
direction 2) is the million-client streamed round: COHORT_CLIENTS
(default 1,000,000) synthetic clients stream host->device in
COHORT_SHARDS (default 256) double-buffered shards through ONE
compiled shard-tier program (``fedcore.hierarchy`` +
``data.stream``), under a fault plan + ``quarantine:5`` so the
defended path is what gets measured, for COHORT_ROUNDS (default 1)
measured rounds after a 1-round warmup. The record pins
``recompiles_after_warmup == 0`` read from the shard tier's own jit
cache. SCALE_ARTIFACT=PATH additionally writes a ``SCALE.v1``
artifact (validated by ``tools/check_bench_schema.py``) whose
``cohort`` section carries the leg's counters.
"""

import json
import os
import sys
import time

import numpy as np


def hbm_peak_gb():
    import jax

    stats = jax.local_devices()[0].memory_stats() or {}
    peak = stats.get("peak_bytes_in_use")
    return round(peak / 1e9, 3) if peak else None


def run_config(name, ds, model, kernel_type, D, num_clients, rounds,
               buckets, epoch=2, batch_size=32, lr=0.1,
               algorithms=("FedAvg",)):
    from fedamw_tpu import algorithms as algs
    from fedamw_tpu.algorithms import prepare_setup

    setup = prepare_setup(
        ds, D=D, kernel_par=0.1, kernel_type=kernel_type, seed=100,
        rng=np.random.RandomState(100), model=model, buckets=buckets,
    )
    # first-principles FLOPs per client-update (PERFORMANCE.md § MFU;
    # shared definition in utils/flops.py): fwd counted from the real
    # initialized params (so MLP configs are exact); mean over ALL
    # clients incl. zero-size padding (they count as "updates" in
    # updates/s, so excluding them would overstate achieved FLOP/s)
    import jax

    from fedamw_tpu.utils.flops import client_update_flops, \
        fwd_flops_per_sample

    params = setup.model.init(jax.random.PRNGKey(0), setup.D,
                              setup.num_classes)
    n_mean = float(np.mean(np.asarray(setup.sizes)))
    fwd, fwd_basis = fwd_flops_per_sample(
        params, apply_fn=setup.model.apply, d=setup.D,
        with_provenance=True)
    flops_upd = client_update_flops(fwd, epoch, n_mean)
    recs = []
    for alg in algorithms:
        fn = getattr(algs, alg)
        # compile warmup at the measured round count (one scan program)
        fn(setup, lr=lr, epoch=epoch, batch_size=batch_size, round=rounds,
           seed=0, lr_mode="constant")
        t0 = time.perf_counter()
        res = fn(setup, lr=lr, epoch=epoch, batch_size=batch_size,
                 round=rounds, seed=0, lr_mode="constant")
        dt = time.perf_counter() - t0
        rec = {
            "config": name,
            "algorithm": alg,
            "clients": setup.num_clients,
            "updates_per_sec": round(setup.num_clients * rounds / dt, 1),
            "final_acc": round(float(res["test_acc"][-1]), 2),
            "hbm_peak_gb": hbm_peak_gb(),
            "wall_s": round(dt, 3),
            "rounds": rounds,
            "buckets": buckets,
            "flops_per_update": round(flops_upd),
            # counting basis on EVERY record (round-4 advisor): conv
            # rows (xla-cost-model) count elementwise/bias/ReLU work
            # the GEMM rows' matmul-only formula does not, so rows are
            # only comparable within a basis
            "flops_basis": fwd_basis,
            "achieved_gflops": round(
                setup.num_clients * rounds / dt * flops_upd / 1e9, 2),
        }
        if alg != "FedAvg":
            # the shared counter covers the client GEMMs only; FedAMW
            # also runs the p-solver + logit cache, so its true FLOP/s
            # is higher than this field — label rather than mislabel
            rec["flops_note"] = ("client local-SGD GEMMs only; excludes "
                                 "p-solver/logit work")
        if fwd_basis == "gemm-formula-undercount":
            # conv leaves counted by the GEMM formula (runtime without
            # cost_analysis): the artifact itself must say so — the
            # stderr warning does not travel with the JSON
            rec["flops_note"] = (rec.get("flops_note", "") +
                                 "; LOWER BOUND: cost_analysis "
                                 "unavailable, conv work uncounted"
                                 ).lstrip("; ")
        if os.environ.get("SCALE_MEMORY", "1") != "0":
            # AOT compile report: the axon runtime has no live
            # memory_stats(), so the compiler's own buffer assignment is
            # the HBM footprint source of truth (BASELINE.md gap)
            ma = fn(setup, lr=lr, epoch=epoch, batch_size=batch_size,
                    round=rounds, seed=0, lr_mode="constant",
                    analyze_memory=True)
            rec["hbm_compiled_peak_gb"] = round(
                ma.get("peak_memory_in_bytes", 0) / 1e9, 3)
            rec["hbm_args_gb"] = round(
                ma.get("argument_size_in_bytes", 0) / 1e9, 3)
            rec["hbm_temp_gb"] = round(
                ma.get("temp_size_in_bytes", 0) / 1e9, 3)
        print(json.dumps(rec), flush=True)
        recs.append(rec)
    return recs


def covtype_1024(rounds, buckets):
    """581k x 54 x 7-class covtype signature, 2-layer MLP, 1024 clients."""
    from fedamw_tpu.data import FederatedDataset, dirichlet_partition
    from fedamw_tpu.data.synthetic import synthetic_classification

    X, y, Xt, yt = synthetic_classification(464809, 54, 7, seed=11,
                                            test_fraction=0.25)
    parts, _ = dirichlet_partition(y, 1024, alpha=0.1, seed=2020, min_size=0)
    ds = FederatedDataset(
        name="covtype-synth", task_type="classification", num_classes=7,
        d=54, X_train=X, y_train=y, X_test=Xt, y_test=yt, parts=parts,
        source="synthetic",
    )
    return run_config("covtype_mlp_1024", ds, "mlp64", "linear", 54,
                      1024, rounds, buckets)


def mnist_conv_512(rounds, buckets):
    """MNIST signature (60k x 784 flattened 28x28 grayscale, 10-class),
    the zoo's compact CNN (``conv8x16``), 512 Dirichlet(alpha=0.1)
    clients. The conv config is the MXU-heavy member of the scale
    table: each client update runs real convolutions instead of the
    linear flagship's 3-FLOP/byte GEMMs, so this measures the framework
    where arithmetic, not op overhead, should dominate."""
    from fedamw_tpu.data import FederatedDataset, dirichlet_partition
    from fedamw_tpu.data.synthetic import synthetic_classification

    X, y, Xt, yt = synthetic_classification(60000, 784, 10, seed=13,
                                            test_fraction=1 / 6)
    parts, _ = dirichlet_partition(y, 512, alpha=0.1, seed=2020,
                                   min_size=0)
    ds = FederatedDataset(
        name="mnist-synth", task_type="classification", num_classes=10,
        d=784, X_train=X, y_train=y, X_test=Xt, y_test=yt, parts=parts,
        source="synthetic",
    )
    return run_config("mnist_conv_512", ds, "conv8x16", "linear", 784,
                      512, rounds, buckets)


def rcv1_4096(rounds, buckets):
    """rcv1.binary signature: 20,242 train rows, d=47,236 sparse ->
    RFF D=2000, 4096 clients (most hold a handful of samples)."""
    import jax
    import scipy.sparse as sp

    from fedamw_tpu.data import FederatedDataset, dirichlet_partition
    from fedamw_tpu.ops.rff import rff_map_sparse, rff_params

    d, D = 47236, 2000
    n_train, n_test = 20242, 20000
    rng = np.random.RandomState(5)
    Xs = sp.random(n_train + n_test, d, density=0.0016, format="csr",
                   dtype=np.float32, random_state=rng)

    W, b = rff_params(jax.random.PRNGKey(100), d, D, sigma=0.1)
    phi = rff_map_sparse(Xs, W, b)
    del Xs
    # Teacher labels in the mapped feature space: random sparse inputs
    # carry no class structure of their own, so define the boundary a
    # logreg on phi can actually represent — the throughput config
    # should also demonstrate learning, not just speed.
    v = rng.randn(D).astype(np.float32)
    margin = phi @ v
    y_all = (margin > np.median(margin)).astype(np.int32)

    X, Xt = phi[:n_train], phi[n_train:]
    y, yt = y_all[:n_train], y_all[n_train:]
    parts, _ = dirichlet_partition(y, 4096, alpha=0.1, seed=2020, min_size=0)
    ds = FederatedDataset(
        name="rcv1-synth", task_type="classification", num_classes=2,
        d=D, X_train=X, y_train=y, X_test=Xt, y_test=yt, parts=parts,
        source="synthetic",
    )
    # features are pre-mapped (sparse path); kernel_type=linear skips
    # re-RFF. FedAMW is included because extreme non-IID aggregation is
    # the regime the paper's learned mixture weights target.
    return run_config("rcv1_logreg_4096", ds, "linear", "linear", D,
                      4096, rounds, buckets, lr=0.5,
                      algorithms=("FedAvg", "FedAMW"))


def cohort_stream():
    """The million-client streamed cohort round (module docstring).

    The setup is built DIRECTLY (no prepare_setup): at 1M clients the
    per-client Python loops in pack/split are the bottleneck, and the
    leg's point is the streamed round, not the packer. Balanced
    2-sample clients keep the per-shard padded shape tiny, which is
    the honest layout for this leg — the cohort axis, not the sample
    axis, is what scales.
    """
    import jax
    import jax.numpy as jnp

    from fedamw_tpu.algorithms import FedAvg
    from fedamw_tpu.algorithms import core as algo_core
    from fedamw_tpu.algorithms.common import FedSetup
    from fedamw_tpu.models import get_model

    J = int(os.environ.get("COHORT_CLIENTS", "1000000"))
    S = int(os.environ.get("COHORT_SHARDS", "256"))
    rounds = int(os.environ.get("COHORT_ROUNDS", "1"))
    k, D, C = 2, 16, 10
    N = J * k
    rng = np.random.RandomState(7)
    X = rng.randn(N, D).astype(np.float32)
    w_true = rng.randn(D, C).astype(np.float32)
    y = np.argmax(X @ w_true + 0.5 * rng.randn(N, C).astype(np.float32),
                  axis=1).astype(np.int32)
    n_eval = min(4096, N)
    # client rows stay HOST-side numpy: the streamed driver slices
    # them per shard — only the shared feature pool rides HBM in full.
    # The cohort pads up to a multiple of the shard count with inert
    # empty clients (all-zero mask, zero weight) so every shard shares
    # one compiled program — the same mesh-even padding discipline as
    # prepare_setup(client_multiple=...)
    J_pad = -(-J // S) * S
    idx = np.zeros((J_pad, k), np.int32)
    idx[:J] = np.arange(N, dtype=np.int32).reshape(J, k)
    mask = np.zeros((J_pad, k), np.float32)
    mask[:J] = 1.0
    sizes = np.zeros(J_pad, np.int32)
    sizes[:J] = k
    weights = (sizes.astype(np.float64) / sizes.sum()).astype(np.float32)
    setup = FedSetup(
        model=get_model("linear"), task="classification", num_classes=C,
        D=D, X=jnp.asarray(X), y=jnp.asarray(y),
        X_test=jnp.asarray(X[:n_eval]), y_test=jnp.asarray(y[:n_eval]),
        X_val=jnp.asarray(X[:256]), y_val=jnp.asarray(y[:256]),
        idx=idx, mask=mask, sizes=sizes, p_fixed=weights,
    )
    kw = dict(lr=0.2, epoch=1, batch_size=32, seed=0, lr_mode="constant",
              cohort_shards=S, stream_cohort=True,
              faults="drop=0.01,corrupt=0.001:scale:25,seed=0",
              robust_agg="quarantine:5")
    # warmup: compiles the one shard-tier program (and the evaluator)
    FedAvg(setup, round=1, **kw)
    tier = algo_core._LAST_SHARD_TIER
    cc0 = tier._cache_size() if hasattr(tier, "_cache_size") else None
    t0 = time.perf_counter()
    res = FedAvg(setup, round=rounds, **kw)
    dt = time.perf_counter() - t0
    cc1 = tier._cache_size() if cc0 is not None else None
    # when the jit cache cannot be introspected the pin is UNMEASURED:
    # null fails the schema gate loudly rather than fabricating the
    # green 0 the gate exists to verify
    recompiles = int(cc1 - cc0) if cc0 is not None else None
    rec = {
        "config": "cohort_stream",
        "metric": "cohort_updates_per_sec",
        "clients": J,
        "padded_clients": J_pad,
        "shards": S,
        "shard_clients": J_pad // S,
        "streamed": True,
        "rounds": rounds,
        "updates_per_sec": round(J * rounds / dt, 1),
        "wall_s": round(dt, 3),
        "final_acc": round(float(res["test_acc"][-1]), 2),
        "quarantined": int(res["fault_counts"]["quarantined"].sum()),
        "dropped": int(res["fault_counts"]["dropped"].sum()),
        "recompiles_after_warmup": recompiles,
        "hbm_peak_gb": hbm_peak_gb(),
        "platform": jax.default_backend(),
        "devices": jax.local_device_count(),
    }
    print(json.dumps(rec), flush=True)
    return [rec]


def main():
    # the persistent-compile-cache satellite (BENCH_COMPILE_CACHE=DIR,
    # shared with bench.py/serve_bench.py): entered before the first
    # jit dispatch; the artifact records the warm/cold cache state
    from bench_common import compilation_cache_ctx

    with compilation_cache_ctx() as ccache:
        _main(ccache)


def _main(ccache):
    if os.environ.get("JAX_PLATFORMS"):
        # honor the env var under the container's sitecustomize (which
        # force-registers the axon TPU plugin): the config update must
        # land before the first backend query
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    rounds = int(os.environ.get("SCALE_ROUNDS", "10"))
    buckets = int(os.environ.get("SCALE_BUCKETS", "64"))
    configs = os.environ.get("SCALE_CONFIGS",
                             "covtype1024,rcv14096,mnistconv512")
    records, cohort_rec = [], None
    for c in configs.split(","):
        t0 = time.perf_counter()
        if c.strip() == "covtype1024":
            records += covtype_1024(rounds, buckets)
        elif c.strip() == "rcv14096":
            records += rcv1_4096(rounds, buckets)
        elif c.strip() == "mnistconv512":
            records += mnist_conv_512(rounds, buckets)
        elif c.strip() == "cohort1m":
            recs = cohort_stream()
            cohort_rec = recs[0]
            records += recs
        else:
            print(f"# unknown config {c}", file=sys.stderr)
        print(f"# {c}: total {time.perf_counter() - t0:.1f}s "
              f"(incl data gen + compile)", file=sys.stderr)
    artifact = os.environ.get("SCALE_ARTIFACT")
    if artifact:
        if cohort_rec is None:
            # SCALE.v1 REQUIRES the cohort section
            # (tools/check_bench_schema.py), so an artifact written
            # without the cohort leg would fail its own validator —
            # refuse at the source instead of committing a red file
            print("# SCALE_ARTIFACT requires the cohort leg: add "
                  "'cohort1m' to SCALE_CONFIGS (the SCALE.v1 schema's "
                  "cohort section is the thing the artifact "
                  "certifies)", file=sys.stderr)
            raise SystemExit(2)
        import jax

        art = {
            "schema": "SCALE.v1",
            "metric": "updates_per_sec",
            "platform": jax.default_backend(),
            "records": records,
            # the cohort section the schema gate validates: the
            # million-client streamed leg's abort-grade counters
            "cohort": cohort_rec,
            # warm-vs-cold compile-cache state (None = no cache =
            # cold by construction), same contract as the bench
            # drivers' phases.compile_cache
            "compile_cache": ccache.snapshot(),
        }
        with open(artifact, "w") as f:
            json.dump(art, f, indent=1)
        print(f"# artifact -> {artifact}", file=sys.stderr)


if __name__ == "__main__":
    main()
