"""Serving benchmark: checkpoint -> warmed engine -> load generator.

The serve-side sibling of ``bench.py``: it trains a small FedAvg model
(or loads SERVE_CKPT), saves it through ``utils/checkpoint.py`` WITH the
RFF draw, restores it via ``serving.ServingEngine.load`` — the full
production path, not an in-memory shortcut — and measures:

1. **Parity** (abort on failure): engine logits on the raw test set
   must reproduce ``fedcore/evaluate.py``'s accuracy exactly. A serving
   stack that serves different numbers than training evaluated is wrong
   before it is slow.
2. **Per-bucket latency**: p50/p95/p99 and rows/s for every rung of the
   bucket ladder, timed at the engine (no queueing).
3. **Mixed-size stream**: a deterministic request-size mix driven
   through the full ServingService (queue + micro-batcher + deadlines),
   reporting request-level percentiles, throughput, shed counts, and —
   the shape-discipline invariant — **zero recompiles after warmup**,
   read from the jit compile-cache counter.

Output follows the ``bench.py`` driver contract: JSON lines on stdout
with the headline metric LAST, plus a ``BENCH_SERVE_rNN.json`` artifact
(SERVE_OUT overrides the path). The same strict-backend guard applies:
under BENCH_STRICT_TPU=1 a resolved non-TPU backend aborts rc=1 before
measuring anything, so a leaked JAX_PLATFORMS=cpu can never be
harvested green (mirrors bench.py; pinned in
``tests/test_serve_contract.py``).

The mixed stream now runs both untraced — its snapshot, carrying
per-stage (queue / pad / device) latency percentiles, is the headline
source — and through a live ``utils.trace`` Tracer (ISSUE 5), which
must hold every submitted request id exactly once (abort on violation,
like the parity gate). The tracing cost is reported as
``serve_trace_overhead``: best-of-``SERVE_TRACE_REPS`` (default 5)
alternating traced/untraced legs, so a ~tens-of-ms stream's
thread-scheduling noise does not masquerade as overhead. The artifact
grows ``phases`` (build / compile-warmup / timed-run seconds) and a
``trace`` section; recompiles-after-warmup is checked across ALL
streams.

The ISSUE 6 continuous-deployment leg (``loop_bench``) closes the
train->serve loop under load: the trained model is re-published as
SERVE_SWAPS successive registry versions and hot-swapped into the live
engine while a request stream runs — bare swaps timed individually
(install + live-pointer flip, zero recompiles pinned across ALL of
them), then one full shadow-canary promotion (deterministic
per-request-id split, promotion after a live-traffic budget), then a
deliberate parity-gate failure that must ROLL BACK (sign-flipped
weights published under the clean model's eval accuracy). The artifact
grows a ``rollout`` section (swap latency percentiles, in-flight
latency across swaps, canary/drill verdicts, final version +
staleness); with SERVE_TRACE set the loop's spans stream through the
rotating JSONL writer (``utils.trace.RotatingJsonlWriter``) instead of
the in-memory collector — the long-lived-loop mode.

The ISSUE 7 failover leg (``chaos``) proves the replica fleet under
deterministic chaos: the same engine behind 3 replicas (ONE shared
compiled ladder) and a health-gating ``FailoverRouter``, streamed
clean for a baseline tail, then under a scripted ``ChaosPlan`` that
wedges one replica (hedged past) and KILLS two mid-stream. Abort-grade
pins, like parity: every accepted request resolves (success or
explicit DeadlineExceeded — none lost or hung), every request id lands
exactly one span, at least one kill actually fires, and
``compile_count`` stays flat across kills/failovers. The artifact
grows a ``chaos`` section (kills/requeues/hedge-wins counters,
per-replica health, p95 with vs without chaos) and the schema bumps to
BENCH_SERVE.v3.

The ISSUE 9 cold-start leg (``cold_start``, schema BENCH_SERVE.v4)
pins the two replica start modes side by side: compile-warmup start
(fresh engine + warmup, one XLA compile per rung — what every replica
paid until now) vs artifact-load start (``serving/artifacts.py``: the
ladder AOT-exported once via jax.export + native executables, then
``ServingEngine.from_artifact`` deserializing it in milliseconds).
Abort-grade: the artifact path must come up AND serve every rung with
``compile_count == 0``, and must pass the same engine-vs-evaluate
parity gate as the compiled path. The chaos leg is additionally
composed with a MID-STREAM hot weight swap (chaos-under-rollout, the
PR 7 follow-on): zero lost requests, zero recompiles, and the correct
NEW model_version on every post-swap span are abort-grade.

The ISSUE 12 telemetry leg (``telemetry_bench``, schema
BENCH_SERVE.v5) prices the WHOLE observability plane paired: plane-off
(series-disabled registry, no tracer) vs plane-on (registry time
series + per-SLO-class latency family + request tracing + an
installed ``jax.profiler`` device-attribution record), best-of-reps
like the trace leg. Exactly-once spans and zero recompiles stay
abort-grade; the <=5% bound is enforced on committed artifacts by
``tools/check_bench_schema.py``. The artifact section carries the SLO
evaluation (per-class attainment + burn rate) and the device
attribution (the XLA-queue split on device hosts, the honest
``source="none"`` fallback on CPU).

The ISSUE 13 continuous-batching leg (``continuous_batching``, schema
BENCH_SERVE.v6) prices the serving loop's rewrite paired: the
fixed-drain micro-batcher over the hand-picked ladder vs continuous
admission over a ladder LEARNED from the baseline leg's own
``serve_request_rows`` registry series (``serving/ladder.py`` —
bounded program count, explicit pad-waste cost model, recompile budget
charged per installed rung). New rungs are pre-warmed and installed
off the serving thread under live traffic, the learner freezes, and
the paired legs replay one seeded open-loop arrival schedule
(``bench_common.open_loop_offsets``) at ``SERVE_CB_LOAD`` x measured
capacity. Zero recompiles after freeze and exactly-once spans are
abort-grade; the headline mixed stream is ALSO open-loop paced now
(``SERVE_PACE_FACTOR`` x a closed-loop calibration), so its queue
percentiles measure service under load rather than backlog drain.

The ISSUE 14 overload leg (``overload``, schema BENCH_SERVE.v7)
proves the overload CONTROL plane (``serving/control.py``): one
seeded flash-crowd ``LoadSpec`` schedule driven through fixed-N
fleets (1 / min / max replicas, no control) and through the
admission-controlled autoscaled fleet (burn-rate class-aware
shedding — shadow and batch first, interactive never; EDF dispatch
under pressure; burn/shed-rate-driven scale-out with hysteresis),
all over ONE AOT artifact-loaded engine so scale-out rides the PR 9
plane and nothing ever compiles. Abort-grade: the autoscaled fleet
beats every fixed fleet on SLO-good requests per replica-second,
interactive attainment holds its objective while batch sheds, at
least one scale-up fires, zero lost accepted requests, zero
recompiles, exactly-once spans (shed requests included).

The ISSUE 15 pod leg (``pod``, schema BENCH_SERVE.v8) crosses the
process boundary for real: SERVE_POD_WORKERS (default 3) worker
PROCESSES each load the cold-start plane's AOT artifact
(``serving.transport.worker_main``) and serve the length-prefixed
frame protocol; the parent fronts them with ``PodClientEngine`` +
per-worker ``SocketTransport`` replicas behind the same
``FailoverRouter``/``ServingService`` stack, then — under a scripted
``NetChaosPlan`` — partitions one worker's route, SIGKILLs another
mid-stream, and broadcasts a mid-stream ``swap_weights`` version
announce to the pod. Abort-grade: zero lost accepted requests,
exactly-once request spans with the trace context propagated across
the wire (workers stream ``pod_dispatch`` spans whose trace ids must
all be router-sent batch ids), at least one kill and one partition
actually fired, zero recompiles on every surviving worker (read back
via ``stats`` frames), and the agreed post-swap version on every
post-swap span.

Env knobs: SERVE_BUCKETS ("1,8,64,512"), SERVE_D (RFF width, 256),
SERVE_N (train rows, 4096), SERVE_CLIENTS (8), SERVE_TRAIN_ROUNDS (2),
SERVE_ITERS (per-bucket timed calls, 30), SERVE_REQUESTS (mixed-stream
requests, 200), SERVE_MAX_WAIT_MS (2.0), SERVE_SWAPS (hot swaps in the
rollout leg, default 3, floor 2 — the series is N-1 bare timed swaps
plus one shadow canary), SERVE_CHAOS_REQUESTS (chaos-leg stream
length, default max(SERVE_REQUESTS, 120) — long enough that the
scripted per-replica kill indices land mid-stream), SERVE_CKPT (serve
an existing checkpoint dir instead
of training), SERVE_TELEMETRY_REPS (paired telemetry-plane legs,
default 5), SERVE_PACE_FACTOR (headline-stream arrival rate as a
fraction of calibrated capacity, default 0.8), SERVE_CB_REQUESTS
(continuous-batching leg stream length, default max(2 x
SERVE_REQUESTS, 600)), SERVE_CB_LOAD (paired-leg arrival rate as a
fraction of the fixed-drain closed-loop calibration, default 0.35 —
the sub-saturation SLO regime; at saturation both policies converge
to full-ladder batches and the comparison measures queue depth, not
policy), SERVE_CB_REPS (paired continuous-batching reps, best-of per
mode, default 5), SERVE_CB_RUNGS (learned-ladder
program budget, default 6), SERVE_CB_BUDGET (learner recompile
budget, default 6), SERVE_DEVATTR_REPS (profiled dispatches in the
device-attribution probe, default 6), SERVE_OVERLOAD_LOAD (the
overload leg's LoadSpec string; default a seeded flash crowd),
SERVE_OVERLOAD_REPLICA_ROWS_S (modeled per-replica capacity, 1500),
SERVE_OVERLOAD_MIN_REPLICAS (2) / SERVE_OVERLOAD_MAX_REPLICAS (4),
SERVE_OVERLOAD_INT_MS (interactive SLO threshold, 100) /
SERVE_OVERLOAD_INT_OBJECTIVE (0.8),
SERVE_POD_WORKERS (pod-leg worker processes, default 3, floor 2),
SERVE_POD_REQUESTS (pod-leg stream length, default 120),
SERVE_OUT, SERVE_ROUND (artifact suffix, default 1),
SERVE_TRACE (directory: export the traced leg's span records as JSONL
there, and stream the rollout leg's spans there as rotating parts),
SERVE_ARTIFACT_DIR (keep the cold-start leg's exported AOT artifact
there instead of scratch), BENCH_COMPILE_CACHE (directory: persistent
XLA compilation cache for the whole run — warm/cold state recorded in
phases.compile_cache; shared with bench.py/scale_bench.py via
bench_common.compilation_cache_ctx), BENCH_PROFILE_DIR (jax.profiler
capture of the timed section, shared with bench.py via
bench_common.profile_ctx).
"""

import gc
import json
import os
import shutil
import sys
import tempfile
import threading
import time

import numpy as np


def _env_int(name, default):
    return int(os.environ.get(name, str(default)))


def build_checkpoint(ckpt_dir: str, D: int, n: int, clients: int,
                     rounds: int):
    """Train a small FedAvg model on shape-matched synthetic data and
    checkpoint it (params + mixture weights + RFF draw). Returns the
    setup (for the parity cross-check) and the raw test matrix."""
    from fedamw_tpu.algorithms import FedAvg, prepare_setup
    from fedamw_tpu.data import FederatedDataset, dirichlet_partition
    from fedamw_tpu.data.synthetic import synthetic_classification
    from fedamw_tpu.utils.checkpoint import save_checkpoint

    X, y, Xt, yt = synthetic_classification(n, 64, 2, seed=3)
    parts, _ = dirichlet_partition(y, clients, alpha=0.5, seed=2020,
                                   min_size=0)
    ds = FederatedDataset(
        name="serve-synth", task_type="classification", num_classes=2,
        d=64, X_train=X, y_train=y, X_test=Xt, y_test=yt, parts=parts,
        source="synthetic")
    setup = prepare_setup(ds, D=D, kernel_par=0.1, seed=100,
                          rng=np.random.RandomState(100))
    res = FedAvg(setup, lr=0.5, epoch=1, batch_size=32, round=rounds,
                 seed=0, lr_mode="constant", return_state=True)
    save_checkpoint(ckpt_dir, res["params"], p=res["p"],
                    round_idx=rounds, rff=setup.rff)
    return setup, np.asarray(Xt, np.float32)


def check_parity(engine, setup, X_test_raw) -> dict:
    """Engine-vs-evaluate accuracy on the SAME test set: the serving
    path re-maps raw inputs through the checkpointed RFF draw, so an
    exact accuracy match certifies the whole load/fuse/pad pipeline."""
    import jax.numpy as jnp

    from fedamw_tpu.fedcore import make_evaluator

    evaluate = make_evaluator(setup.model.apply, setup.task)
    _, eval_acc = evaluate(
        {k: jnp.asarray(v) for k, v in engine.params.items()},
        setup.X_test, setup.y_test)
    logits = engine.predict(X_test_raw)
    y = np.asarray(setup.y_test)
    engine_acc = 100.0 * float(np.mean(np.argmax(logits, -1) == y))
    return {"engine_acc": round(engine_acc, 6),
            "evaluate_acc": round(float(eval_acc), 6),
            "match": abs(engine_acc - float(eval_acc)) < 1e-4}


def time_bucket(engine, b: int, iters: int, rng) -> dict:
    """Steady-state latency of one ladder rung (exact-fit batches, so
    the number is the compiled program + host roundtrip, no padding)."""
    from fedamw_tpu.serving import LatencyHistogram

    X = rng.randn(b, engine.input_dim).astype(np.float32)
    hist = LatencyHistogram()
    engine.predict(X)  # rung already compiled by warmup; absorb cache hits
    t0 = time.perf_counter()
    for _ in range(iters):
        t1 = time.perf_counter()
        engine.predict(X)
        hist.record(time.perf_counter() - t1)
    dt = time.perf_counter() - t0
    out = hist.percentiles()
    out.update(iters=iters,
               throughput_rows_per_s=round(b * iters / dt, 2))
    return out


def stream_sizes(buckets, n_requests: int, rng) -> list:
    """The deterministic mixed-size recipe: single rows plus every
    rung boundary's neighborhood, permuted — each compiled bucket
    serves real (non-warmup) traffic."""
    sizes = []
    for b in buckets:
        sizes += [1, max(1, b // 2), b]
    return [sizes[i % len(sizes)] for i in rng.permutation(
        max(n_requests, len(sizes)))[:n_requests]]


def mixed_stream(engine, n_requests: int, max_wait_ms: float, rng,
                 tracer=None, metrics=None, slo_classes=None,
                 pace_rps: float | None = None, pace_seed: int = 0,
                 mode: str = "continuous", sizes=None) -> dict:
    """Drive a deterministic mixed-size request stream through the full
    service loop and snapshot its metrics (now including the per-stage
    queue/pad/device percentile families). ``tracer``: a live
    ``utils.trace`` Tracer for the traced leg (every accepted request
    lands one "request" span); None keeps the no-op default.
    ``metrics``: a prepared ``ServeMetrics`` (the telemetry leg passes
    one whose registry is enabled or disabled — the paired plane-on/off
    comparison); ``slo_classes``: a cycle of SLO class labels stamped
    on submits, so the per-class latency family carries real traffic.

    ``pace_rps`` (ISSUE 13 satellite): open-loop SEEDED paced arrivals
    at that mean rate (``bench_common.open_loop_offsets``) — queue
    percentiles then measure service under load. None keeps the
    closed-loop enqueue-everything shape, which measures max
    throughput (what the paired overhead estimators need: under
    pacing both legs would just report the arrival rate). ``mode``:
    the service's batch-formation policy ("continuous" default,
    "drain" = the fixed-micro-batch baseline). ``sizes``: explicit
    request-size list (paired before/after legs share one); default
    derives from the engine's CURRENT ladder via :func:`stream_sizes`.
    """
    from bench_common import open_loop_offsets
    from fedamw_tpu.serving import ServingService

    if sizes is None:
        sizes = stream_sizes(engine.buckets, n_requests, rng)
    payloads = [rng.randn(s, engine.input_dim).astype(np.float32)
                for s in sizes]
    offsets = None
    if pace_rps is not None:
        offsets = open_loop_offsets(np.random.RandomState(pace_seed),
                                    len(payloads), pace_rps)
    # collect BEFORE timing and hold GC off DURING the stream: paired
    # overhead legs run back to back, so monotonically-growing heap
    # garbage would systematically tax whichever leg runs second (a
    # collection pause mid-stream also reads as a fake multi-ms tail
    # in the paced sub-5ms p95 regime). The stream's own garbage is
    # bounded — a few hundred request records — and collected at the
    # next stream's entry.
    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        # the load generator enqueues far faster than the engine
        # drains; max_queue must admit the whole configured stream or
        # a large SERVE_REQUESTS would crash with Overloaded instead
        # of measuring
        with ServingService(engine, max_wait_ms=max_wait_ms,
                            max_queue=max(1024, len(payloads)),
                            tracer=tracer, metrics=metrics,
                            mode=mode) as svc:
            futures = []
            for i, x in enumerate(payloads):
                if offsets is not None:
                    # absolute offsets, not per-gap sleeps: submit-
                    # side overhead never compresses the schedule
                    lag = t0 + offsets[i] - time.perf_counter()
                    if lag > 0:
                        time.sleep(lag)
                futures.append(svc.submit(x, slo_class=(
                    slo_classes[i % len(slo_classes)] if slo_classes
                    else None)))
            for f in futures:
                f.result(timeout=300)
            dt = time.perf_counter() - t0
            snap = svc.metrics.snapshot(engine)
    finally:
        # in a finally: a failed stream must not leave collection off
        # for the rest of the process
        gc.enable()
    # end-to-end wall-clock throughput (the metrics-internal rate spans
    # batch completions only and is None for a single-batch stream)
    snap["throughput_req_per_s"] = round(len(payloads) / dt, 2)
    snap["throughput_rows_per_s"] = round(sum(sizes) / dt, 2)
    snap["mode"] = mode
    snap["arrival_req_per_s"] = (None if pace_rps is None
                                 else round(float(pace_rps), 2))
    return snap


def _wait_live(engine, v, timeout_s: float) -> bool:
    """Poll until ``v`` is the engine's live version (a promote may
    land on the serving worker thread a beat after ``stage``
    returns); True when it took within the timeout."""
    deadline = time.perf_counter() + timeout_s
    while engine.version != v and time.perf_counter() < deadline:
        time.sleep(0.001)
    return engine.version == v


def loop_bench(engine, parity_xy, eval_acc, n_swaps, max_wait_ms, rng,
               trace_dir=None):
    """Continuous deployment under live traffic (see module
    docstring): bare hot swaps timed one by one, a shadow-canary
    promotion, and a parity-failure rollback drill, all against one
    uninterrupted request stream. Returns the artifact ``rollout``
    section. The stream pumps until the rollout script finishes (a
    swap's cost must be measured against in-flight traffic, not an
    idle service); with ``trace_dir`` the spans stream through the
    rotating JSONL writer — the collector-free long-lived-loop mode.
    """
    from fedamw_tpu.serving import (LatencyHistogram, ModelRegistry,
                                    Overloaded, RolloutController,
                                    ServingService)
    from fedamw_tpu.utils.trace import RotatingJsonlWriter, Tracer

    params = {k: np.asarray(v) for k, v in engine.params.items()}
    rff = engine.rff
    if rff is not None:
        rff = (np.asarray(rff[0]), np.asarray(rff[1]))
    registry = ModelRegistry()
    meta = None if eval_acc is None else {"eval_acc": eval_acc}
    # the SAME trained weights re-published as successive training
    # rounds: this leg measures swap/rollout MECHANICS (latency,
    # recompiles, gates), and identical weights make the parity gate
    # exact and the shadow agreement 1.0 by construction. Floor of 2:
    # the series is (n-1) bare timed swaps + 1 shadow canary, and the
    # v2 artifact contract needs at least one timed bare swap for
    # swap_p50_ms
    versions = [registry.publish(params, rff=rff, round_idx=k + 1,
                                 metadata=meta)
                for k in range(max(2, n_swaps))]
    writer = tracer = None
    if trace_dir:
        writer = RotatingJsonlWriter(trace_dir, max_spans_per_file=2000,
                                     prefix="serve_loop")
        tracer = Tracer(writer=writer)
    sizes = [1, 8, max(1, engine.buckets[-1] // 2)]
    payloads = [rng.randn(s, engine.input_dim).astype(np.float32)
                for s in sizes]
    stop = threading.Event()
    pump_errors: list = []

    def pump():
        # bounded in-flight window: resolved results are consumed as
        # the stream runs (a fast backend could otherwise accumulate
        # O(100k) result arrays before a final drain), and any
        # failure is carried out to the main thread
        import collections

        pending: collections.deque = collections.deque()
        i = 0
        try:
            while not stop.is_set() and i < 100_000:
                try:
                    f = svc.submit(payloads[i % len(payloads)])
                except Overloaded:
                    time.sleep(0.001)
                    continue
                pending.append(f)
                i += 1
                if len(pending) >= 512:
                    pending.popleft().result(timeout=300)
            for f in pending:
                f.result(timeout=300)
        except Exception as e:  # surfaced after join, below
            pump_errors.append(e)

    swap_ms = []
    swap_hist = LatencyHistogram()  # one percentile impl, not a copy
    cc0 = engine.compile_count
    with ServingService(engine, max_wait_ms=max_wait_ms,
                        max_queue=4096, tracer=tracer) as svc:
        ctl = RolloutController(svc, registry, mode="shadow",
                                fraction=0.5, min_requests=0,
                                error_budget=0, parity_data=None)
        th = threading.Thread(target=pump, name="loop-pump")
        th.start()
        try:
            # 1) bare hot swaps, timed individually: install the new
            # version's weights + flip the live pointer (min_requests=0
            # promotes inside stage; no parity data -> no gate
            # dispatch in the timing window)
            for v in versions[:-1]:
                t0 = time.perf_counter()
                took = ctl.stage(v) and _wait_live(engine, v, 10)
                dt = time.perf_counter() - t0
                swap_hist.record(dt)
                swap_ms.append(round(dt * 1e3, 3))
                if not took:
                    raise SystemExit(
                        f"# serve_bench aborted: bare swap to version "
                        f"{v} did not take (live={engine.version})")
            # 2) the last version promotes through a REAL shadow
            # canary: deterministic split, candidate dispatched on
            # live traffic, promotion after min_requests clean
            # observations
            ctl.min_requests = 16
            ctl.min_agreement = 0.99
            canary_v = versions[-1]
            t0 = time.perf_counter()
            ok = ctl.stage(canary_v)
            took = ok and _wait_live(engine, canary_v, 60)
            canary_ms = round((time.perf_counter() - t0) * 1e3, 3)
            canary = "promoted" if took else "FAILED"
            # 3) rollback drill: sign-flipped weights published under
            # the clean model's eval accuracy MUST fail the parity
            # gate and leave the canary winner serving. Only after a
            # promoted canary: a timed-out canary is still staged, and
            # staging the drill on top would raise instead of reaching
            # the structured FAILED abort below — clear it first.
            if canary != "promoted" and ok:
                ctl.rollback("canary timed out in loop_bench")
            drill = "skipped"
            if (canary == "promoted" and parity_xy is not None
                    and eval_acc is not None):
                ctl.parity_data = parity_xy
                ctl.min_requests = 0
                bad = registry.publish(
                    {k: -v for k, v in params.items()}, rff=rff,
                    round_idx=len(versions) + 1, metadata=dict(meta))
                live_before = engine.version
                staged = ctl.stage(bad)
                drill = ("rolled_back" if not staged
                         and engine.version == live_before
                         else "FAILED")
                # withdraw the rejected publish: the artifact's final
                # staleness must describe servable models, not the
                # drill's deliberately-bad one
                registry.withdraw(bad)
        finally:
            stop.set()
            th.join(timeout=60)
        if pump_errors:
            raise SystemExit(
                f"# serve_bench aborted: rollout-leg request failed: "
                f"{type(pump_errors[0]).__name__}: {pump_errors[0]}")
        snap = svc.metrics.snapshot(engine)
    if writer is not None:
        writer.close()
    events = [dict(e) for e in ctl.events]
    gate = next((e.get("gate") for e in reversed(events)
                 if e.get("stage") == "parity"), None)
    recompiles = engine.compile_count - cc0
    swap_pcts = swap_hist.percentiles((50, 95))
    section = {
        "mode": "shadow",
        "swaps": len(swap_ms) + int(canary == "promoted"),
        "swap_p50_ms": swap_pcts["p50_ms"],
        "swap_p95_ms": swap_pcts["p95_ms"],
        "swap_max_ms": max(swap_ms) if swap_ms else None,
        "canary": canary,
        "canary_ms": canary_ms,
        "rollback_drill": drill,
        "drill_gate": gate,
        "inflight_p50_ms": snap["p50_ms"],
        "inflight_p95_ms": snap["p95_ms"],
        "requests": snap["requests"],
        "shadow_requests": snap["shadow_requests"],
        "candidate_errors": snap["candidate_errors"],
        "rollbacks": snap["rollbacks"],
        "weight_swaps": snap["weight_swaps"],
        "recompiles_during_swaps": recompiles,
        "final_version": engine.version,
        "staleness_rounds": registry.staleness_rounds(engine.version),
        "trace_parts": len(writer.paths) if writer else 0,
        "trace_spans": writer.spans_written if writer else 0,
    }
    if canary == "FAILED" or drill == "FAILED" or recompiles:
        # rollout gates are abort-grade, like parity: a swap that
        # recompiled or a drill that served bad weights must never
        # emit green-looking numbers
        print(f"# serve_bench aborted: rollout leg failed "
              f"({json.dumps(section)})", file=sys.stderr)
        raise SystemExit(1)
    return section


def chaos_bench(engine, n_requests, max_wait_ms):
    """The ISSUE 7 failover leg: the mixed stream re-run over a
    3-replica fleet (one shared compiled ladder) behind the
    FailoverRouter, first clean, then under a SCRIPTED chaos plan that
    wedges one replica (hedged past) and kills two mid-stream — now
    COMPOSED with a mid-stream hot weight swap (the ISSUE 9
    chaos-under-rollout follow-on): halfway through the chaos stream
    the live version is swapped while replicas are dying around it.
    The acceptance pins are abort-grade, like parity: every accepted
    request must resolve (success or explicit DeadlineExceeded — none
    lost or hung), every request id must land exactly one span, at
    least one scripted kill must actually fire (a chaos leg that never
    exercised failover proves nothing), the compile count must stay
    flat across kills, failovers AND the swap, and every request
    submitted after the swap must carry the NEW model_version on its
    span. Returns the artifact ``chaos`` section (BENCH_SERVE.v4)."""
    from fedamw_tpu.serving import (ChaosPlan, DeadlineExceeded,
                                    FailoverRouter, ReplicaSet,
                                    ServingService)
    from fedamw_tpu.utils.trace import Tracer

    n_replicas = 3
    sizes = [1, 8, max(1, engine.buckets[-1] // 2)]
    rng = np.random.RandomState(13)
    payloads = [rng.randn(s, engine.input_dim).astype(np.float32)
                for s in sizes]
    cc0 = engine.compile_count
    # the swap's weights: the live version re-installed under a new
    # number — this leg measures swap MECHANICS under chaos (correct
    # version on every post-swap span, zero recompiles), and identical
    # weights keep the chaos/clean latency comparison apples-to-apples
    swap_params = {k: np.asarray(v) for k, v in engine.params.items()}
    swap_rff = engine.rff
    if swap_rff is not None:
        swap_rff = (np.asarray(swap_rff[0]), np.asarray(swap_rff[1]))

    def stream(router, tracer=None, swap_at=None):
        """Paced request stream (many small batches, so the scripted
        per-replica dispatch indices land mid-stream, not in one
        giant coalesce); every future is awaited with a hard timeout
        — a hung request surfaces as 'lost', never as a green run.
        ``swap_at``: submit index at which the live weights hot-swap
        mid-stream; request ids submitted after it are returned so
        the caller can pin their spans to the new version."""
        ok = deadline = lost = 0
        submitted, post_swap = [], []
        swap_ver = None
        with ServingService(router, max_wait_ms=max_wait_ms,
                            max_queue=max(1024, n_requests),
                            tracer=tracer) as svc:
            futs = []
            for i in range(n_requests):
                if swap_at is not None and i == swap_at:
                    # the chaos-under-rollout composition: swap while
                    # replicas are being killed around the dispatch
                    swap_ver = router.swap_weights(swap_params,
                                                   rff=swap_rff)
                f = svc.submit(payloads[i % len(payloads)],
                               timeout_s=30.0)
                submitted.append(f.request_id)
                if swap_ver is not None:
                    post_swap.append(f.request_id)
                futs.append(f)
                time.sleep(0.0015)
            for f in futs:
                try:
                    f.result(timeout=60)
                    ok += 1
                except DeadlineExceeded:
                    deadline += 1
                except Exception as e:
                    print(f"# chaos stream: request failed "
                          f"{type(e).__name__}: {e}", file=sys.stderr)
                    lost += 1
            snap = svc.metrics.snapshot(router)
        return snap, ok, deadline, lost, submitted, swap_ver, post_swap

    # clean baseline: same fleet shape, no chaos — the p95 the chaos
    # tail is judged against
    with FailoverRouter(ReplicaSet(engine, n_replicas),
                        policy="round_robin") as clean_router:
        clean_snap, clean_ok, _, clean_lost, _, _, _ = \
            stream(clean_router)

    # scripted chaos, deterministic every run: replica 1 dies on its
    # 3rd dispatch, replica 0 wedges on its 4th (the hedge masks the
    # stall), replica 2 dies on its 6th — two of three replicas killed
    # mid-stream, one survivor carrying the tail. Indices are LOW on
    # purpose: the paced stream forms tens of micro-batches even on a
    # loaded box, and a kill index the stream never reaches would
    # abort the leg (kills_observed < 1 below)
    plan = ChaosPlan.scripted(n_replicas, kills={1: 2, 2: 5},
                              wedges={0: [3]}, wedge_s=0.25,
                              horizon=65536)
    tracer = Tracer(max_spans=4 * n_requests + 64)
    # hedge_floor_ms sits far above any clean dispatch (sub-10ms even
    # on a loaded box) and far below the 250ms wedge stall: ONLY the
    # scripted wedge can cross the hedge threshold, so the leg's
    # hedge/requeue counters — and the kill-cell dispatch indices,
    # which a spurious mirror would otherwise consume — stay
    # deterministic run to run
    with FailoverRouter(ReplicaSet(engine, n_replicas, chaos=plan),
                        policy="round_robin", hedge=True,
                        hedge_min_samples=6,
                        hedge_floor_ms=50.0) as router:
        snap, ok, deadline, lost, submitted, swap_ver, post_swap = \
            stream(router, tracer, swap_at=n_requests // 2)
        fo = snap["failover"]

    req_spans = [r for r in tracer.records() if r["name"] == "request"]
    ids = [r["trace_id"] for r in req_spans]
    spans_once = (sorted(ids) == sorted(submitted)
                  and tracer.dropped == 0)
    # chaos-under-rollout pin: every request submitted AFTER the swap
    # returned must report the NEW version on its span — whichever
    # surviving replica served it, and whether it resolved ok or shed
    # on deadline (the version dimension must never lie under chaos)
    post_ids = set(post_swap)
    post_versions = {r["attrs"].get("model_version")
                     for r in req_spans if r["trace_id"] in post_ids}
    swap_ok = bool(post_swap) and post_versions == {swap_ver}
    recompiles = engine.compile_count - cc0
    section = {
        "replicas": n_replicas,
        "requests": n_requests,
        "resolved_ok": ok,
        "deadline_exceeded": deadline,
        "lost": lost + clean_lost,
        "kills_planned": len(plan.kills_planned()),
        "kills_observed": fo["dead_replicas"],
        "requeues": fo["requeues"],
        "hedges": fo["hedges"],
        "hedge_wins": fo["hedge_wins"],
        "failed_over_requests": sum(
            1 for r in req_spans if r["attrs"].get("failovers", 0)),
        "p95_ms_clean": clean_snap["p95_ms"],
        "p95_ms_chaos": snap["p95_ms"],
        "p50_ms_clean": clean_snap["p50_ms"],
        "p50_ms_chaos": snap["p50_ms"],
        "recompiles_during_chaos": recompiles,
        "spans_exactly_once": spans_once,
        "midstream_swap_version": swap_ver,
        "post_swap_requests": len(post_swap),
        "post_swap_version_ok": swap_ok,
        "hedges_cancelled": fo["hedges_cancelled"],
        "per_replica": fo["replicas"],
    }
    if (section["lost"] or recompiles or not spans_once
            or fo["dead_replicas"] < 1
            or clean_ok != n_requests or not swap_ok):
        # abort-grade, like parity: a lost/hung request, a recompile
        # under failover (or under the mid-stream swap), a lost span,
        # a chaos schedule that never fired, or a post-swap span
        # carrying the wrong model version must not emit green-looking
        # numbers
        print(f"# serve_bench aborted: chaos leg failed "
              f"({json.dumps(section)})", file=sys.stderr)
        raise SystemExit(1)
    return section


def export_artifact_checked(warm_engine, ckpt, buckets, art_dir):
    """Export ``warm_engine``'s ladder as a PR 9 AOT artifact into
    ``art_dir`` and return the manifest. With BENCH_COMPILE_CACHE
    active this process may have loaded cross-process cache entries —
    which corrupts XLA:CPU executable serialization (export_ladder
    self-checks and refuses) — so the export runs the operator CLI in
    a FRESH process instead; the cost then includes interpreter+jax
    startup, which is exactly what an operator's export step costs
    anyway. Shared by the cold-start and overload legs (both start
    replicas from the artifact plane)."""
    from fedamw_tpu.serving.artifacts import export_ladder

    if os.environ.get("BENCH_COMPILE_CACHE"):
        import subprocess

        from fedamw_tpu.serving.artifacts import ArtifactManifest

        env = dict(os.environ)
        env.pop("BENCH_COMPILE_CACHE", None)
        cli = os.path.join(os.path.dirname(os.path.abspath(
            __file__)), "tools", "export_artifacts.py")
        run = subprocess.run(
            [sys.executable, cli, ckpt, art_dir, "--buckets",
             ",".join(str(b) for b in buckets)],
            env=env, capture_output=True, text=True, timeout=300)
        if run.returncode != 0:
            print(f"# serve_bench aborted: artifact export CLI "
                  f"failed: {run.stderr[-1000:]}", file=sys.stderr)
            raise SystemExit(1)
        return ArtifactManifest.load(art_dir)
    return export_ladder(warm_engine, art_dir)


def cold_start_bench(ckpt, buckets, setup, X_test_raw):
    """The ISSUE 9 cold-start leg: the two ways a replica can come up,
    timed side by side from the SAME checkpoint. Compile-warmup start
    — a fresh ``ServingEngine.load`` + ``warmup()``, one XLA compile
    per rung (what every replica paid until now) — vs artifact-load
    start: ``export_ladder`` once (the cost the exporter pays, timed
    separately), then ``ServingEngine.from_artifact`` deserializing
    the pre-compiled ladder. Abort-grade pins: the artifact path must
    come up with ``compile_count == 0`` and KEEP it at 0 after serving
    every rung (a single compile on the load path means the artifact
    did not actually serve), and its logits must reproduce
    ``fedcore/evaluate.py``'s accuracy exactly — the same parity gate
    the compiled path passes. Returns the artifact ``cold_start``
    section (BENCH_SERVE.v4). SERVE_ARTIFACT_DIR keeps the exported
    artifact; otherwise it is scratch."""
    from fedamw_tpu.serving import ServingEngine

    t0 = time.perf_counter()
    cold = ServingEngine.load(ckpt, buckets=buckets)
    compiled = cold.warmup()
    compile_warmup_s = time.perf_counter() - t0

    scratch = None
    art_dir = os.environ.get("SERVE_ARTIFACT_DIR")
    if not art_dir:
        art_dir = scratch = tempfile.mkdtemp(prefix="serve_artifact_")
    try:
        t0 = time.perf_counter()
        manifest = export_artifact_checked(cold, ckpt, buckets, art_dir)
        export_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        art = ServingEngine.from_artifact(art_dir, checkpoint=ckpt)
        art.warmup()  # the no-op: nothing to compile is the point
        load_s = time.perf_counter() - t0

        parity = None
        if setup is not None:
            parity = check_parity(art, setup, X_test_raw)
        # serve every rung once THROUGH the loaded executables: the
        # zero stays zero, or the leg aborts
        rng = np.random.RandomState(11)
        for b in art.buckets:
            art.predict(rng.randn(b, art.input_dim).astype(np.float32))
        cc = art.compile_count
    finally:
        if scratch is not None:
            shutil.rmtree(scratch, ignore_errors=True)
    section = {
        "compile_warmup_s": round(compile_warmup_s, 3),
        "compile_count_compiled": compiled,
        "artifact_export_s": round(export_s, 3),
        "artifact_load_s": round(load_s, 4),
        "artifact_compile_count": cc,
        "speedup_x": (round(compile_warmup_s / load_s, 1)
                      if load_s > 0 else None),
        "rungs": len(manifest.rungs),
        "artifact_bytes": sum(r["bytes"]
                              for r in manifest.rungs.values()),
        "parity": parity,
        "artifact_dir": None if scratch else art_dir,
    }
    if cc != 0 or (parity is not None and not parity["match"]):
        # abort-grade, like parity: an artifact path that compiled
        # anything, or serves different numbers than training
        # evaluated, must never emit green cold-start seconds
        print(f"# serve_bench aborted: cold-start leg failed "
              f"({json.dumps(section)})", file=sys.stderr)
        raise SystemExit(1)
    return section


def telemetry_bench(engine, n_requests, max_wait_ms):
    """The ISSUE 12 unified-telemetry leg: what the WHOLE plane costs,
    measured paired. Plane OFF = a ``ServeMetrics`` whose registry
    runs series-disabled and the no-op tracer (cumulative counters
    only — the pre-ISSUE-12 cost floor); plane ON = live registry
    time series + per-SLO-class latency family + request tracing +
    an installed device-attribution record. Same paired
    best-of-``SERVE_TELEMETRY_REPS`` estimator as the trace leg
    (identical request-size streams per rep; max-throughput shrugs
    off scheduler noise). Abort-grade pins, like parity: every
    submitted request of the winning ON leg lands exactly one span,
    and the compile count stays flat across every leg — the plane
    must observe the ladder, never perturb it. The <=5% overhead
    bound is enforced on COMMITTED artifacts by
    ``tools/check_bench_schema.py`` (v5); a live run past it prints a
    loud warning instead of aborting, so a noisy box cannot flake the
    gate. The sampled ``jax.profiler`` device-attribution probe runs
    once OUTSIDE the paired timing (its cost is reported separately —
    it is an operator action, not a per-request one); on CPU it
    degrades to the honest ``source="none"`` record. Returns the
    artifact ``telemetry_overhead`` section (BENCH_SERVE.v5)."""
    from fedamw_tpu.serving import ServeMetrics
    from fedamw_tpu.utils.telemetry import Registry
    from fedamw_tpu.utils.trace import Tracer

    # floored HERE so the artifact's 'reps' records what actually ran
    # (SERVE_TELEMETRY_REPS=0 must not write a reps=0 the schema gate
    # would rightly reject after a green run)
    reps = max(1, _env_int("SERVE_TELEMETRY_REPS", 5))
    n = max(n_requests, 200)
    cc0 = engine.compile_count
    t0 = time.perf_counter()
    attr = engine.device_attribution(
        reps=_env_int("SERVE_DEVATTR_REPS", 6))
    attr_s = time.perf_counter() - t0
    best_off = best_on = 0.0
    keep = None
    for rep in range(reps):
        # paired legs: each rep reseeds so OFF and ON serve the
        # IDENTICAL request-size stream (same rationale as the trace
        # leg — a shared rng would bias the comparison)
        m_off = ServeMetrics(registry=Registry(enabled=False))
        off = mixed_stream(engine, n, max_wait_ms,
                           np.random.RandomState(300 + rep),
                           metrics=m_off)
        best_off = max(best_off, off["throughput_req_per_s"])
        m_on = ServeMetrics()
        m_on.install_device_attribution(attr)
        t = Tracer(max_spans=4 * n + 64)
        on = mixed_stream(engine, n, max_wait_ms,
                          np.random.RandomState(300 + rep),
                          tracer=t, metrics=m_on,
                          slo_classes=("interactive", "batch"))
        if on["throughput_req_per_s"] >= best_on:
            # keep the winning rep's snapshot + registry + tracer
            # TOGETHER so every artifact field describes one run
            best_on = on["throughput_req_per_s"]
            keep = (on, m_on, t)
    on_snap, m_on, tracer = keep
    # the plane's standard interactive/batch pair + windows
    # (utils.telemetry.DEFAULT_SLO_CLASSES — one definition, not a
    # bench-local copy that could silently diverge)
    slo = m_on.slo()
    req_spans = [r for r in tracer.records() if r["name"] == "request"]
    ids = [r["trace_id"] for r in req_spans]
    spans_once = (len(ids) == n and len(set(ids)) == len(ids)
                  and tracer.dropped == 0)
    recompiles = engine.compile_count - cc0
    overhead = best_off / best_on if best_on else float("inf")
    section = {
        "overhead_x": round(overhead, 3),
        "reps": reps,
        "requests_per_leg": n,
        "plane_off_req_per_s": best_off,
        "plane_on_req_per_s": best_on,
        "plane_on_p50_ms": on_snap["p50_ms"],
        "spans_exactly_once": spans_once,
        "recompiles_during_telemetry": recompiles,
        "registry_instruments": len(m_on.registry.instruments()),
        "registry_points": m_on.registry.points_recorded(),
        "slo": slo,
        "device_attribution": attr,
        "device_attribution_probe_s": round(attr_s, 3),
        "latency_accounting": {
            "seen": on_snap["latency_seen"],
            "sampled": on_snap["latency_sampled"],
            "reservoir_degraded": on_snap["reservoir_degraded"],
        },
    }
    if not spans_once or recompiles:
        # abort-grade, like parity: a lost/duplicated span or a
        # recompile under the full plane must never emit green numbers
        print(f"# serve_bench aborted: telemetry leg failed "
              f"({json.dumps({k: section[k] for k in ('spans_exactly_once', 'recompiles_during_telemetry')})})",
              file=sys.stderr)
        raise SystemExit(1)
    if overhead > 1.05:
        print(f"# WARNING: telemetry plane measured {overhead:.3f}x "
              "(> the 1.05 committed-artifact bound; "
              "tools/check_bench_schema.py will refuse this artifact)",
              file=sys.stderr)
    return section


def overload_bench(ckpt, buckets, max_wait_ms):
    """The ISSUE 14 elastic-serving leg (schema BENCH_SERVE.v7): the
    overload CONTROL plane proven against the fleets it replaces. One
    seeded flash-crowd load shape (``serving.chaos.LoadSpec`` — same
    determinism contract as the chaos plan: every fleet replays the
    IDENTICAL arrival schedule and class mix) is driven through four
    fleets over ONE AOT artifact-loaded engine (scale-out rides the
    PR 9 plane, so ``compile_count`` is zero before, during, and
    after — attaching a replica is microseconds, measured per event):

    - fixed-N fleets (N = 1, 2, max): no admission control, no
      autoscaler — the pre-ISSUE-14 shape. Under the flash crowd the
      small ones melt (interactive and batch blow deadlines
      together); the big one coasts, burning ``N x wall``
      replica-seconds all run.
    - the AUTOSCALED fleet: ``AdmissionController`` (burn-rate
      trigger, queue-residency corroboration, shadow-then-batch shed
      order, interactive never policy-shed) + ``Autoscaler``
      (burn/shed-rate driven scale-out with hysteresis and a
      max-fleet bound) + deadline scheduling in the continuous worker
      (EDF under pressure).

    Per-replica capacity is modeled (``Replica(service_rate_rows_s=)``
    — N replicas serve at most N x rate rows/s), so saturation is a
    property of the SCHEDULE, not of whatever the host's one
    in-process engine happens to do; the flash peak is sized ~2.5x a
    single replica's capacity.

    The headline is **SLO-good requests per replica-second** (classed
    requests answered within their class threshold, over the fleet's
    integrated size x time): the autoscaled fleet must beat EVERY
    fixed fleet — small fleets lose on good requests, big ones on
    replica-seconds. Abort-grade, like parity: the beat itself;
    interactive attainment >= its objective while batch sheds
    (``requests_shed{class=batch}`` > 0); at least one scale-up; zero
    LOST accepted requests in every fleet (shed and deadline are
    typed outcomes, anything else is a loss); zero recompiles; every
    submitted request id — shed ones included — landing exactly one
    span.

    Env knobs: SERVE_OVERLOAD_LOAD (LoadSpec string),
    SERVE_OVERLOAD_REPLICA_ROWS_S (per-replica modeled capacity),
    SERVE_OVERLOAD_MIN/MAX_REPLICAS, SERVE_OVERLOAD_INT_MS /
    SERVE_OVERLOAD_INT_OBJECTIVE (the interactive class's SLO).
    """
    from fedamw_tpu.serving import (AdmissionController, AdmissionShed,
                                    Autoscaler, DeadlineExceeded,
                                    FailoverRouter, LoadSpec, Overloaded,
                                    Replica, ServeMetrics, ServingEngine,
                                    ServingService)
    from fedamw_tpu.utils.telemetry import Registry, SloClass
    from fedamw_tpu.utils.trace import Tracer

    spec = LoadSpec.parse(os.environ.get(
        "SERVE_OVERLOAD_LOAD",
        "shape=flash,base=150,peak=1100,duration=8,at=0.4,width=0.5,"
        "seed=17"))
    rate_rows = float(os.environ.get(
        "SERVE_OVERLOAD_REPLICA_ROWS_S", "1500"))
    n_min = _env_int("SERVE_OVERLOAD_MIN_REPLICAS", 2)
    n_max = _env_int("SERVE_OVERLOAD_MAX_REPLICAS", 4)
    int_ms = float(os.environ.get("SERVE_OVERLOAD_INT_MS", "100"))
    int_obj = float(os.environ.get(
        "SERVE_OVERLOAD_INT_OBJECTIVE", "0.8"))
    classes = (SloClass("interactive", threshold_ms=int_ms,
                        objective=int_obj),
               SloClass("batch", threshold_ms=1000.0, objective=0.5))
    thresholds = {c.name: c.threshold_ms / 1e3 for c in classes}
    offsets = spec.offsets()
    # deterministic class mix, cycled over the seeded arrivals:
    # interactive is half the requests but a quarter of the ROWS —
    # batch (8-row payloads) is the row mass the shed policy trades
    # away to protect it; shadow is the first class to go
    mix = [("interactive", 1, 0.5), ("batch", 8, 3.0),
           ("interactive", 2, 0.5), ("shadow", 1, 1.0),
           ("interactive", 1, 0.5), ("batch", 8, 3.0)]

    # ONE artifact-loaded engine behind every fleet: scale-out is the
    # PR 9 cold-start plane (nothing ever compiles), and the paired
    # fleets measure policy, not engine variance
    warm = ServingEngine.load(ckpt, buckets=buckets)
    warm.warmup()
    scratch = tempfile.mkdtemp(prefix="serve_overload_art_")
    try:
        t0 = time.perf_counter()
        export_artifact_checked(warm, ckpt, buckets, scratch)
        export_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        engine = ServingEngine.from_artifact(scratch, checkpoint=ckpt)
        load_s = time.perf_counter() - t0
        payloads = {
            r: np.random.RandomState(41).randn(
                r, engine.input_dim).astype(np.float32)
            for r in sorted({rows for _, rows, _ in mix})}

        def run_fleet(n0, autoscaled):
            metrics = ServeMetrics(registry=Registry())
            replicas = [Replica(i, engine, None,
                                service_rate_rows_s=rate_rows)
                        for i in range(n0)]
            router = FailoverRouter(replicas, policy="round_robin",
                                    registry=metrics.registry)
            tracer = Tracer(max_spans=4 * len(offsets) + 64)
            admission = autoscaler = None
            if autoscaled:
                admission = AdmissionController(
                    metrics, classes=classes,
                    shed_order=("shadow", "batch"), window_s=0.75,
                    burn_threshold=1.0, min_window_requests=8,
                    queue_floor_ms=int_ms / 2, interval_s=0.02,
                    escalate_ticks=1, relax_ticks=15)
                autoscaler = Autoscaler(
                    router,
                    replica_factory=lambda rid: Replica(
                        rid, engine, None,
                        service_rate_rows_s=rate_rows),
                    metrics=metrics, classes=classes, window_s=0.75,
                    min_replicas=n0, max_replicas=n_max,
                    scale_up_burn=1.0, scale_down_burn=0.25,
                    queue_floor_ms=int_ms / 2, up_ticks=1,
                    down_ticks=12, cooldown_s=0.3,
                    min_window_requests=8)
            recs, futs, submitted = [], [], []
            cc0 = engine.compile_count
            gc.collect()
            with ServingService(router, max_wait_ms=max_wait_ms,
                                max_queue=max(4096, len(offsets)),
                                tracer=tracer, metrics=metrics,
                                admission=admission) as svc:
                if autoscaler is not None:
                    autoscaler.start(interval_s=0.05)
                t0 = time.perf_counter()
                for i, off in enumerate(offsets):
                    lag = t0 + off - time.perf_counter()
                    if lag > 0:
                        # absolute offsets: submit overhead never
                        # compresses the seeded schedule
                        time.sleep(lag)
                    cls, rows_n, timeout = mix[i % len(mix)]
                    rec = {"cls": cls, "t0": time.perf_counter(),
                           "outcome": None, "dt": None}

                    def _done(f, rec=rec):
                        rec["dt"] = time.perf_counter() - rec["t0"]
                        e = f.exception()
                        rec["outcome"] = (
                            "ok" if e is None else
                            "shed" if isinstance(e, AdmissionShed) else
                            "deadline" if isinstance(e, DeadlineExceeded)
                            else "lost")
                    try:
                        f = svc.submit(payloads[rows_n],
                                       timeout_s=timeout,
                                       slo_class=cls)
                    except Overloaded:
                        # max_queue admits the whole schedule; landing
                        # here means the bound was mis-sized — a loss
                        rec["outcome"] = "lost"
                        recs.append(rec)
                        continue
                    submitted.append(f.request_id)
                    f.add_done_callback(_done)
                    recs.append(rec)
                    futs.append(f)
                for f in futs:
                    try:
                        f.result(timeout=120)
                    except Exception:
                        pass  # classified in the callback
                wall = time.perf_counter() - t0
                rs = (autoscaler.replica_seconds() if autoscaler
                      else n0 * wall)
                if autoscaler is not None:
                    autoscaler.stop()
                snap = metrics.snapshot(router)
            counts = {"ok": 0, "shed": 0, "deadline": 0, "lost": 0}
            per_cls: dict = {}
            good = 0
            for rec in recs:
                counts[rec["outcome"] or "lost"] += 1
                cls = rec["cls"]
                c = per_cls.setdefault(cls, {"n": 0, "good": 0})
                c["n"] += 1
                thr = thresholds.get(cls)
                if rec["outcome"] == "ok" and thr is not None \
                        and rec["dt"] <= thr:
                    c["good"] += 1
                    good += 1
            spans = [r for r in tracer.records()
                     if r["name"] == "request"]
            ids = [r["trace_id"] for r in spans]
            section = {
                "replicas_start": n0,
                "replicas_peak": (
                    max((e["size"] for e in autoscaler.events),
                        default=n0) if autoscaler else n0),
                "replica_seconds": round(rs, 3),
                "wall_s": round(wall, 3),
                "requests": len(recs),
                **counts,
                "good": good,
                "good_per_replica_s": round(good / rs, 3),
                "attainment": {
                    cls: round(c["good"] / c["n"], 4)
                    for cls, c in sorted(per_cls.items())},
                "p95_ms": snap["p95_ms"],
                "queue_p95_ms": snap["queue_p95_ms"],
                "shed_by_class": snap["requests_shed_by_class"],
                "recompiles": engine.compile_count - cc0,
                "spans_exactly_once": (
                    sorted(ids) == sorted(submitted)
                    and tracer.dropped == 0),
            }
            if autoscaler is not None:
                section.update(
                    scale_ups=autoscaler.scale_ups,
                    scale_downs=autoscaler.scale_downs,
                    autoscaler_errors=autoscaler.errors,
                    attach_ms=[e["attach_ms"]
                               for e in autoscaler.events
                               if e["action"] == "up"],
                    events=autoscaler.events,
                    admission_level_final=admission.level,
                    admission_evaluations=admission.evaluations)
            return section

        fixed_sizes = sorted({1, n_min, n_max})
        fleets = {f"fixed_{n}": run_fleet(n, autoscaled=False)
                  for n in fixed_sizes}
        fleets["autoscaled"] = run_fleet(n_min, autoscaled=True)
    finally:
        shutil.rmtree(scratch, ignore_errors=True)

    auto = fleets["autoscaled"]
    beats = {
        name: auto["good_per_replica_s"] > rec["good_per_replica_s"]
        for name, rec in fleets.items() if name != "autoscaled"}
    int_ok = auto["attainment"].get("interactive", 0.0) >= int_obj
    batch_shed = int(auto["shed_by_class"].get("batch", 0))
    section = {
        "load": {"shape": spec.shape, "base_rps": spec.base_rps,
                 "peak_rps": spec.peak_rps,
                 "duration_s": spec.duration_s, "seed": spec.seed,
                 "requests": int(len(offsets))},
        "classes": {c.name: {"threshold_ms": c.threshold_ms,
                             "objective": c.objective}
                    for c in classes},
        "replica_rows_per_s": rate_rows,
        "artifact_export_s": round(export_s, 3),
        "artifact_load_s": round(load_s, 4),
        "fleets": fleets,
        "autoscaled_beats_every_fixed": all(beats.values()),
        "beats": beats,
        "interactive_attainment_ok": bool(int_ok),
        "batch_shed": batch_shed,
        "lost_accepted": sum(rec["lost"] for rec in fleets.values()),
        "scale_ups": auto.get("scale_ups", 0),
        "recompiles_during_overload": sum(
            rec["recompiles"] for rec in fleets.values()),
        "spans_exactly_once": all(
            rec["spans_exactly_once"] for rec in fleets.values()),
    }
    if (not section["autoscaled_beats_every_fixed"] or not int_ok
            or batch_shed < 1 or section["lost_accepted"]
            or section["recompiles_during_overload"]
            or not section["spans_exactly_once"]
            or section["scale_ups"] < 1
            or auto.get("autoscaler_errors", 0)):
        # abort-grade, like parity: an elastic fleet that does not
        # beat every fixed fleet on SLO-good work per replica-second,
        # loses an accepted request, compiles anything, drops a span,
        # fails to protect interactive, or never actually scaled must
        # not emit green-looking numbers
        slim = {k: v for k, v in section.items() if k != "fleets"}
        slim["fleet_summary"] = {
            name: {k: rec.get(k) for k in (
                "good_per_replica_s", "replica_seconds", "good",
                "requests", "lost", "attainment")}
            for name, rec in fleets.items()}
        print(f"# serve_bench aborted: overload leg failed "
              f"({json.dumps(slim)})", file=sys.stderr)
        raise SystemExit(1)
    return section


def pod_bench(ckpt, buckets, max_wait_ms):
    """The ISSUE 15 cross-process pod leg (schema BENCH_SERVE.v8):
    the serving plane's first REAL process boundary. ``SERVE_POD_
    WORKERS`` (default 3) worker PROCESSES each load the same PR 9
    AOT artifact (``serving.transport.worker_main`` — zero compiles,
    ever) and serve the length-prefixed frame protocol; the parent
    fronts them with a ``PodClientEngine`` facade + one
    ``SocketTransport`` replica per worker behind the SAME
    ``FailoverRouter``/``ServingService`` stack every in-process leg
    used. Mid-stream, under a SCRIPTED ``NetChaosPlan``:

    - one worker is PARTITIONED (its transport blackholes two
      dispatches — hang, timeout, drop the connection, reconnect),
    - one worker is SIGKILLed (the transport's ``kill_cb`` delivers a
      real SIGKILL, then dispatches into the corpse — connection
      reset, circuit opens, in-flight batch requeues to survivors),
    - and a ``swap_weights`` version-announce broadcasts to the pod,
      so post-swap spans carry the NEW agreed model_version whichever
      surviving worker serves them.

    Abort-grade, like every leg: zero lost accepted requests (every
    future resolves ok or typed), at least one kill AND one partition
    actually fired, exactly-once request spans router-side WITH the
    trace propagated across the wire (each worker streams its
    ``pod_dispatch`` spans to rotating JSONL; their trace ids must
    all be batch ids the router sent — the TRACECTX.v1 consumer),
    zero recompiles on every surviving worker (read back over the
    wire via ``stats`` frames), and the post-swap version pin."""
    import signal
    import subprocess

    from fedamw_tpu.serving import (DeadlineExceeded, FailoverRouter,
                                    NetChaosPlan, PodClientEngine,
                                    Replica, ServingEngine,
                                    ServingService, SocketTransport)
    from fedamw_tpu.utils.trace import Tracer, read_jsonl

    n_workers = max(2, _env_int("SERVE_POD_WORKERS", 3))
    n_requests = _env_int("SERVE_POD_REQUESTS", 120)
    repo = os.path.dirname(os.path.abspath(__file__))
    warm = ServingEngine.load(ckpt, buckets=buckets)
    warm.warmup()
    swap_params = {k: np.asarray(v) for k, v in warm.params.items()}
    swap_rff = warm.rff
    if swap_rff is not None:
        swap_rff = (np.asarray(swap_rff[0]), np.asarray(swap_rff[1]))
    scratch = tempfile.mkdtemp(prefix="serve_pod_")
    art_dir = os.path.join(scratch, "artifact")
    trace_dir = os.path.join(scratch, "worker_trace")
    os.makedirs(trace_dir, exist_ok=True)
    procs, logs = [], []
    try:
        t0 = time.perf_counter()
        export_artifact_checked(warm, ckpt, buckets, art_dir)
        export_s = time.perf_counter() - t0

        # spawn the pod: each worker is a REAL process loading the
        # artifact and publishing its bound port through a port file
        # (spawned in parallel — interpreter+jax startup dominates)
        t0 = time.perf_counter()
        for i in range(n_workers):
            port_file = os.path.join(scratch, f"port{i}")
            code = (
                "import fedamw_tpu\n"
                "from fedamw_tpu.serving.transport import worker_main\n"
                f"worker_main({port_file!r}, artifact_dir={art_dir!r},"
                f" checkpoint={ckpt!r}, worker_id={i},"
                f" trace_dir={trace_dir!r})\n")
            log = open(os.path.join(scratch, f"worker{i}.log"), "w")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", code], cwd=repo,
                stdout=log, stderr=log))
        endpoints = []
        for i in range(n_workers):
            port_file = os.path.join(scratch, f"port{i}")
            deadline = time.perf_counter() + 120
            while not os.path.exists(port_file):
                if procs[i].poll() is not None or \
                        time.perf_counter() > deadline:
                    print(f"# serve_bench aborted: pod worker {i} "
                          f"never came up (rc={procs[i].poll()}); see "
                          f"{scratch}/worker{i}.log", file=sys.stderr)
                    with open(os.path.join(scratch,
                                           f"worker{i}.log")) as f:
                        print(f.read()[-2000:], file=sys.stderr)
                    raise SystemExit(1)
                time.sleep(0.05)
            with open(port_file) as f:
                endpoints.append(("127.0.0.1", int(f.read().strip())))
        spawn_s = time.perf_counter() - t0

        pod = PodClientEngine(endpoints)
        # scripted network chaos, deterministic every run: worker 0's
        # route partitions on its 6th and 9th dispatch (hang, bounded
        # timeout, reconnect), worker 1 is SIGKILLed at its 8th.
        # Indices are LOW on purpose, same reasoning as the chaos leg:
        # the paced stream must actually reach them
        part_at, kill_at = [5, 8], 7
        plan = NetChaosPlan.scripted(
            n_workers, partitions={0: part_at}, kills={1: kill_at},
            horizon=65536, partition_s=0.2)

        def kill_cb(host):
            os.kill(procs[host].pid, signal.SIGKILL)

        transports = [
            SocketTransport(endpoints[i], client=pod, host_index=i,
                            chaos=plan, kill_cb=kill_cb,
                            n_hosts=n_workers)
            for i in range(n_workers)]
        replicas = [Replica(i, pod, transport=transports[i])
                    for i in range(n_workers)]
        tracer = Tracer(max_spans=4 * n_requests + 64)
        sizes = [1, 4, 8]
        rng = np.random.RandomState(23)
        payloads = [rng.randn(s, pod.input_dim).astype(np.float32)
                    for s in sizes]
        ok = deadline_n = lost = 0
        submitted, post_swap = [], []
        swap_ver = None
        t0 = time.perf_counter()
        with FailoverRouter(replicas, policy="round_robin") as router:
            with ServingService(router, max_wait_ms=max_wait_ms,
                                max_queue=max(1024, n_requests),
                                tracer=tracer) as svc:
                futs = []
                for i in range(n_requests):
                    if i == n_requests // 2:
                        # the version-announce broadcast, mid-stream,
                        # AFTER the kill fired: only survivors ack,
                        # and they must agree on the number
                        swap_ver = router.swap_weights(swap_params,
                                                       rff=swap_rff)
                    f = svc.submit(payloads[i % len(payloads)],
                                   timeout_s=30.0)
                    submitted.append(f.request_id)
                    if swap_ver is not None:
                        post_swap.append(f.request_id)
                    futs.append(f)
                    time.sleep(0.0015)
                for f in futs:
                    try:
                        f.result(timeout=60)
                        ok += 1
                    except DeadlineExceeded:
                        deadline_n += 1
                    except Exception as e:
                        print(f"# pod stream: request failed "
                              f"{type(e).__name__}: {e}",
                              file=sys.stderr)
                        lost += 1
                fo = svc.metrics.snapshot(router)["failover"]
        stream_s = time.perf_counter() - t0

        # evidence, over the wire: per-worker stats frames (the
        # killed worker reads back dead), per-transport fault counts
        stats = pod.worker_stats()
        survivors = [m for m in stats if not m.get("dead")]
        dead_workers = [m for m in stats if m.get("dead")]
        faults = {k: sum(t.faults_injected[k] for t in transports)
                  for k in ("partition", "refuse", "lag", "kill")}
        reconnects = sum(t.reconnects for t in transports)

        req_spans = [r for r in tracer.records()
                     if r["name"] == "request"]
        ids = [r["trace_id"] for r in req_spans]
        spans_once = (sorted(ids) == sorted(submitted)
                      and tracer.dropped == 0)
        post_ids = set(post_swap)
        post_versions = {r["attrs"].get("model_version")
                         for r in req_spans if r["trace_id"] in post_ids}
        swap_ok = bool(post_swap) and post_versions == {swap_ver}

        # the cross-process trace: every worker streamed pod_dispatch
        # spans under the TRACECTX the router sent — their trace ids
        # must be batch ids the router-side request spans reference
        batch_ids = {r["attrs"].get("batch") for r in req_spans}
        pod_spans = 0
        alien_ids = 0
        for part in sorted(os.listdir(trace_dir)):
            _, spans = read_jsonl(os.path.join(trace_dir, part))
            for sp in spans:
                if sp["name"] != "pod_dispatch":
                    continue
                pod_spans += 1
                if sp["trace_id"] not in batch_ids:
                    alien_ids += 1
        trace_propagated = pod_spans >= 1 and alien_ids == 0

        section = {
            "workers": n_workers,
            "requests": n_requests,
            "resolved_ok": ok,
            "deadline_exceeded": deadline_n,
            "lost": lost,
            "kills_planned": 1,
            "kills_fired": faults["kill"],
            "partitions_planned": len(part_at),
            "partitions_fired": faults["partition"],
            "workers_dead": len(dead_workers),
            "requeues": fo["requeues"],
            "reconnects": reconnects,
            "artifact_export_s": round(export_s, 3),
            "worker_spawn_s": round(spawn_s, 3),
            "stream_s": round(stream_s, 3),
            "spans_exactly_once": spans_once,
            "midstream_swap_version": swap_ver,
            "swap_acks": pod.last_announce["acks"],
            "post_swap_requests": len(post_swap),
            "post_swap_version_ok": swap_ok,
            "pod_dispatch_spans": pod_spans,
            "trace_propagated": trace_propagated,
            "survivor_recompiles": sum(
                int(m.get("compile_count", 0)) for m in survivors),
            "survivor_dispatches": sum(
                int(m.get("dispatches", 0)) for m in survivors),
            "per_worker": [
                {k: m.get(k) for k in ("worker", "dispatches",
                                       "swaps", "compile_count",
                                       "version", "dead")}
                for m in stats],
        }
        if (lost or not spans_once or faults["kill"] < 1
                or faults["partition"] < 1 or not dead_workers
                or section["survivor_recompiles"]
                or not survivors or not swap_ok
                or not trace_propagated):
            # abort-grade, like parity: a lost request across the
            # wire, a span lost or duplicated, chaos that never
            # fired, a surviving worker that compiled, a post-swap
            # span on the wrong version, or a trace id that failed to
            # cross the hop must not emit green-looking numbers
            print(f"# serve_bench aborted: pod leg failed "
                  f"({json.dumps(section)})", file=sys.stderr)
            raise SystemExit(1)
        return section
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
        for log in logs:
            log.close()
        shutil.rmtree(scratch, ignore_errors=True)


def continuous_batching_bench(ckpt, buckets, max_wait_ms):
    """The ISSUE 13 leg: continuous batching over a traffic-learned
    ladder, measured PAIRED against the fixed-drain baseline it
    replaces, on its own engine (the shared engine's compile counters
    stay untouched). One seeded open-loop arrival schedule, five
    steps:

    1. closed-loop calibration (drain mode, fixed ladder) measures the
       capacity the paced legs are loaded against;
    2. BASELINE reps: fixed ladder + drain-mode batching, open-loop
       paced at ``SERVE_CB_LOAD`` x calibration — their shared live
       registry records the ``serve_request_rows`` histogram series;
    3. a ``LadderLearner`` proposes a rung set from that series
       (bounded program count, explicit pad-waste cost model,
       recompile budget charged per installed rung); the new rungs
       are PRE-WARMED and installed from this thread while a
       continuous-mode service serves a live trickle — re-bucketing
       never compiles on the serving thread — then the learner
       freezes;
    4. CONTINUOUS reps: the same paced schedule and request sizes
       through continuous admission over the learned ladder;
    5. best-of-reps per mode (the paired estimator every overhead leg
       uses: min p95 per mode over ``SERVE_CB_REPS`` alternating
       reps, so a ~hundreds-of-ms stream's scheduler noise does not
       masquerade as policy). BOTH legs of every rep run traced, so
       the policies pay identical observability cost and the winning
       continuous rep doubles as the exactly-once-span evidence.

    Abort-grade pins, like parity: zero recompiles after ladder
    freeze, every request of every continuous rep landing exactly one
    span, and no request failed in any leg. The headline comparison
    (p95 baseline / p95 continuous) is recorded; below 2x it prints a
    loud warning (the committed-capture expectation) but does not
    abort — a loaded box must not flake the contract test on
    scheduler noise. Returns the artifact ``continuous_batching``
    section (BENCH_SERVE.v6)."""
    from fedamw_tpu.serving import (LadderLearner, ServeMetrics,
                                    ServingEngine, ServingService,
                                    apply_proposal)
    from fedamw_tpu.utils.telemetry import Registry
    from fedamw_tpu.utils.trace import Tracer

    n = _env_int("SERVE_CB_REQUESTS",
                 max(2 * _env_int("SERVE_REQUESTS", 200), 600))
    load = float(os.environ.get("SERVE_CB_LOAD", "0.35"))
    reps = max(1, _env_int("SERVE_CB_REPS", 5))
    max_rungs = _env_int("SERVE_CB_RUNGS", 6)
    budget = _env_int("SERVE_CB_BUDGET", 6)

    # TWO engines from one checkpoint: the baseline keeps the fixed
    # ladder for the whole leg, the continuous engine learns — so the
    # paired reps can ALTERNATE modes (a noisy-neighbor slow phase
    # lands on both legs, the same reason the trace/telemetry
    # estimators pair theirs) instead of measuring the modes in
    # disjoint time windows
    eng_base = ServingEngine.load(ckpt, buckets=buckets)
    eng_base.warmup()
    eng_cont = ServingEngine.load(ckpt, buckets=buckets)
    eng_cont.warmup()
    fixed = tuple(eng_base.buckets)
    size_rng = np.random.RandomState(23)
    sizes = stream_sizes(fixed, n, size_rng)

    def leg(engine, mode, pace=None, metrics=None, tracer=None):
        # mixed_stream holds GC off for the timed stream (see there)
        return mixed_stream(engine, n, max_wait_ms,
                            np.random.RandomState(29),
                            tracer=tracer, metrics=metrics,
                            pace_rps=pace, pace_seed=31, mode=mode,
                            sizes=sizes)

    # 1) capacity calibration: closed loop, series-off registry (the
    # calibration must not pollute the learner's evidence)
    cal = leg(eng_base, "drain", metrics=ServeMetrics(
        registry=Registry(enabled=False)))
    rate = round(load * cal["throughput_req_per_s"], 2)

    # 2) the evidence leg: one fixed-drain paced run whose live
    # registry records the request-rows series the learner reads
    m_evidence = ServeMetrics()
    leg(eng_base, "drain", pace=rate, metrics=m_evidence)

    # 3) learn, install on the CONTINUOUS engine (pre-warmed off the
    # serving thread, under live continuous traffic), freeze
    learner = LadderLearner(m_evidence.registry, max_rungs=max_rungs,
                            recompile_budget=budget, min_samples=32)
    proposal = learner.propose(fixed)
    trickle_errors: list = []
    if proposal is not None:
        stop = threading.Event()
        with ServingService(eng_cont, max_wait_ms=max_wait_ms,
                            mode="continuous") as svc:
            def trickle():
                k = 0
                try:
                    while not stop.is_set():
                        svc.submit(size_rng.randn(
                            sizes[k % len(sizes)],
                            eng_cont.input_dim).astype(
                                np.float32)).result(timeout=60)
                        k += 1
                except Exception as e:  # surfaced after join, below
                    trickle_errors.append(e)

            th = threading.Thread(target=trickle, name="cb-trickle")
            th.start()
            try:
                # THIS thread pre-warms and installs each rung while
                # the worker keeps dispatching the old ladder through
                # the live trickle — the off-hot-path re-bucketing the
                # zero-recompile-after-freeze pin certifies
                apply_proposal(eng_cont, proposal, learner)
            finally:
                stop.set()
                th.join(timeout=60)
    learner.freeze()
    cc_freeze = eng_cont.compile_count

    # 4) ALTERNATING paired reps — fixed-drain on the fixed engine,
    # continuous on the learned one, back to back within each rep;
    # best-of-reps per mode. Every continuous rep is traced and every
    # rep's spans are pinned exactly-once.
    base = cont = None
    spans_once = True
    for _ in range(reps):
        snap = leg(eng_base, "drain", pace=rate,
                   metrics=ServeMetrics(),
                   tracer=Tracer(max_spans=4 * n + 64))
        if base is None or snap["p95_ms"] < base["p95_ms"]:
            base = snap
        tracer = Tracer(max_spans=4 * n + 64)
        snap = leg(eng_cont, "continuous", pace=rate,
                   metrics=ServeMetrics(), tracer=tracer)
        ids = [r["trace_id"] for r in tracer.records()
               if r["name"] == "request"]
        spans_once = spans_once and (
            len(ids) == n and len(set(ids)) == len(ids)
            and tracer.dropped == 0)
        if cont is None or snap["p95_ms"] < cont["p95_ms"]:
            cont = snap
    recompiles = eng_cont.compile_count - cc_freeze

    def _sub(snap):
        out = {k: snap[k] for k in (
            "requests", "batches", "mean_batch_rows", "p50_ms",
            "p95_ms", "p99_ms", "queue_depth_peak",
            "throughput_req_per_s", "mode")}
        for stage in ("queue", "pad", "device"):
            for q in ("p50_ms", "p95_ms", "p99_ms"):
                out[f"{stage}_{q}"] = snap[f"{stage}_{q}"]
        return out

    improvement = (round(base["p95_ms"] / cont["p95_ms"], 2)
                   if base["p95_ms"] and cont["p95_ms"] else None)
    section = {
        "requests_per_leg": n,
        "reps": reps,
        "load_factor": load,
        "calibration_req_per_s": cal["throughput_req_per_s"],
        "arrival_req_per_s": rate,
        "baseline": _sub(base),
        "continuous": _sub(cont),
        "ladder": {
            "fixed": list(fixed),
            "learned": list(eng_cont.buckets),
            "installed": list(proposal.install) if proposal else [],
            "retired": list(proposal.retire) if proposal else [],
            "max_rungs": max_rungs,
            "recompile_budget": budget,
            "recompiles_charged": learner.recompiles_spent,
            "frozen": learner.frozen,
            "sample_rows": (proposal.sample_count if proposal else 0),
            "waste_fraction_fixed": (
                proposal.baseline_waste_fraction if proposal else None),
            "waste_fraction_learned": (
                proposal.waste_fraction if proposal else None),
            "skipped_reason": (None if proposal else learner.last_reason),
        },
        "p95_improvement_x": improvement,
        "recompiles_after_freeze": recompiles,
        "spans_exactly_once": spans_once,
    }
    if (recompiles or not spans_once or improvement is None
            or trickle_errors):
        # abort-grade, like parity: a compile after the ladder froze,
        # a lost/duplicated span, a failed in-flight request during
        # install, or a leg with no measurable tail must never emit
        # green-looking improvement numbers
        if trickle_errors:
            section["install_error"] = repr(trickle_errors[0])
        print(f"# serve_bench aborted: continuous-batching leg failed "
              f"({json.dumps(section)})", file=sys.stderr)
        raise SystemExit(1)
    if improvement < 2.0:
        print(f"# WARNING: continuous batching measured only "
              f"{improvement}x p95 vs the fixed-drain baseline (the "
              "committed-capture expectation is >= 2x at high load)",
              file=sys.stderr)
    return section

def main():
    # shared prologue with bench.py (bench_common): re-apply
    # JAX_PLATFORMS over the container's sitecustomize, then the
    # BENCH_STRICT_TPU certification abort on the RESOLVED backend
    from bench_common import (compilation_cache_ctx,
                              reapply_jax_platforms, strict_tpu_abort)

    reapply_jax_platforms()
    import jax

    platform = jax.default_backend()
    strict_tpu_abort("serve_bench", platform)

    from fedamw_tpu.serving import ServingEngine

    buckets = tuple(int(b) for b in os.environ.get(
        "SERVE_BUCKETS", "1,8,64,512").split(","))
    D = _env_int("SERVE_D", 256)
    iters = _env_int("SERVE_ITERS", 30)
    n_requests = _env_int("SERVE_REQUESTS", 200)
    max_wait_ms = float(os.environ.get("SERVE_MAX_WAIT_MS", "2.0"))

    ckpt = os.environ.get("SERVE_CKPT")
    setup = None
    scratch = None  # our own train-and-serve checkpoint, removed on exit
    # the persistent-compile-cache satellite: entered BEFORE the first
    # jit dispatch (jax latches the cache decision at first use), so
    # with BENCH_COMPILE_CACHE set, training build AND every engine
    # compile below go through the cache — phases.compile_cache
    # records cold vs warm
    with compilation_cache_ctx() as ccache:
        t_build0 = time.perf_counter()
        if ckpt:
            engine = ServingEngine.load(ckpt, buckets=buckets)
            print(f"# serving existing checkpoint {ckpt}",
                  file=sys.stderr)
        else:
            ckpt = scratch = tempfile.mkdtemp(prefix="serve_ckpt_")
            setup, X_test_raw = build_checkpoint(
                ckpt, D=D, n=_env_int("SERVE_N", 4096),
                clients=_env_int("SERVE_CLIENTS", 8),
                rounds=_env_int("SERVE_TRAIN_ROUNDS", 2))
            engine = ServingEngine.load(ckpt, buckets=buckets)
        build_s = time.perf_counter() - t_build0
        try:
            _run_bench(engine, setup, X_test_raw if setup is not None
                       else None, ckpt, platform, iters, n_requests,
                       max_wait_ms, build_s, ccache)
        finally:
            if scratch is not None:
                shutil.rmtree(scratch, ignore_errors=True)


def _run_bench(engine, setup, X_test_raw, ckpt, platform, iters,
               n_requests, max_wait_ms, build_s, ccache=None):

    parity = None
    if setup is not None:
        parity = check_parity(engine, setup, X_test_raw)
        print(f"# parity: engine {parity['engine_acc']:.4f} vs "
              f"evaluate {parity['evaluate_acc']:.4f}", file=sys.stderr)
        if not parity["match"]:
            # a serving stack that disagrees with training evaluation
            # must never emit green-looking latency numbers
            print("# serve_bench aborted: serving/evaluate accuracy "
                  "parity FAILED", file=sys.stderr)
            raise SystemExit(1)

    t0 = time.perf_counter()
    warm_compiles = engine.warmup()
    warmup_s = time.perf_counter() - t0
    print(f"# warmup: {warm_compiles} programs "
          f"({len(engine.buckets)} buckets) in {warmup_s:.2f}s",
          file=sys.stderr)

    from bench_common import profile_ctx
    from fedamw_tpu.utils.reporting import format_trace_summary
    from fedamw_tpu.utils.trace import Tracer

    # ISSUE 13: the continuous-batching leg — fixed-drain baseline vs
    # continuous admission over a ladder learned from the baseline's
    # own request-size series, paired on one seeded open-loop
    # schedule; zero recompiles after ladder freeze and exactly-once
    # spans are abort-grade. Runs on its OWN engine, so the shared
    # engine's zero-recompile pin below is untouched by the installs.
    # Runs FIRST of the legs, on a fresh heap: its paired tails live
    # in a sub-5ms regime where the later legs' accumulated garbage
    # (dead engines, artifacts, tracers) turns collection pauses
    # into fake multi-ms p95 samples.
    t_cb0 = time.perf_counter()
    cb = continuous_batching_bench(ckpt, tuple(engine.buckets),
                                   max_wait_ms)
    cb_s = time.perf_counter() - t_cb0
    print(f"# continuous batching: {cb['p95_improvement_x']}x p95 vs "
          f"fixed drain ({cb['baseline']['p95_ms']}ms -> "
          f"{cb['continuous']['p95_ms']}ms at "
          f"{cb['arrival_req_per_s']} req/s; ladder "
          f"{cb['ladder']['fixed']} -> {cb['ladder']['learned']}, "
          f"{cb['ladder']['recompiles_charged']} recompiles charged, "
          f"{cb['recompiles_after_freeze']} after freeze)",
          file=sys.stderr)


    rng = np.random.RandomState(0)
    bucket_latency = {}
    t_timed0 = time.perf_counter()
    with profile_ctx("serve_bench"):
        for b in engine.buckets:
            bucket_latency[str(b)] = rec = time_bucket(engine, b, iters,
                                                       rng)
            print(json.dumps({
                "metric": "serve_bucket_latency",
                "bucket": b, "platform": platform, **rec}))
            print(f"# bucket {b:>5}: p50 {rec['p50_ms']}ms  p99 "
                  f"{rec['p99_ms']}ms  "
                  f"{rec['throughput_rows_per_s']} rows/s",
                  file=sys.stderr)

        # the headline mixed stream is OPEN-LOOP since ISSUE 13: a
        # closed-loop calibration measures capacity, then seeded paced
        # arrivals at SERVE_PACE_FACTOR x that capacity drive the
        # measured stream — queue percentiles now describe service
        # under load, not backlog drain (the old shape enqueued the
        # whole stream first, so queue_depth_peak == requests and the
        # queue family measured a different quantity)
        pace_factor = float(os.environ.get("SERVE_PACE_FACTOR", "0.8"))
        cal = mixed_stream(engine, max(n_requests, 200), max_wait_ms,
                           rng)
        stream = mixed_stream(
            engine, n_requests, max_wait_ms, rng,
            pace_rps=round(pace_factor * cal["throughput_req_per_s"],
                           2))
        stream["calibration_req_per_s"] = cal["throughput_req_per_s"]
        stream["pace_factor"] = pace_factor

        # traced twin of the mixed stream (ISSUE 5): the tracing cost
        # as BEST-of-reps over PAIRED legs. Pairing matters twice:
        # each rep reseeds its rng so the off and on leg serve the
        # IDENTICAL request-size stream (a shared rng would hand the
        # two legs different size mixes — a systematic bias that
        # measured as a fake 1.6x overhead), and max-throughput over
        # reps is the standard steady-state estimator that shrugs off
        # the +-17% thread-scheduling noise of a ~tens-of-ms stream
        reps = _env_int("SERVE_TRACE_REPS", 5)
        # floor the overhead streams at 200 requests: a 40-request
        # stream lasts ~4 ms, inside one scheduler quantum, and its
        # timing is quantization noise whatever the estimator
        n_overhead = max(n_requests, 200)
        best_off, best_on = 0.0, 0.0
        tracer, traced = None, None
        for rep in range(max(1, reps)):
            off_snap = mixed_stream(engine, n_overhead, max_wait_ms,
                                    np.random.RandomState(100 + rep))
            best_off = max(best_off, off_snap["throughput_req_per_s"])
            t = Tracer(max_spans=4 * n_overhead + 64)
            on_snap = mixed_stream(engine, n_overhead, max_wait_ms,
                                   np.random.RandomState(100 + rep),
                                   tracer=t)
            if on_snap["throughput_req_per_s"] >= best_on:
                # keep the WINNING rep's tracer and snapshot together,
                # so the artifact's tracing_on_* fields (throughput,
                # p50) and the exported trace all describe one run
                best_on = on_snap["throughput_req_per_s"]
                tracer, traced = t, on_snap
    timed_s = time.perf_counter() - t_timed0

    # ISSUE 6: the continuous-deployment leg — hot swaps + a shadow
    # canary + a rollback drill against live traffic, swap latency and
    # in-flight tails measured, spans streamed when SERVE_TRACE is set
    t_loop0 = time.perf_counter()
    rollout = loop_bench(
        engine, parity_xy=((X_test_raw, np.asarray(setup.y_test))
                           if setup is not None else None),
        eval_acc=(parity["engine_acc"] if parity is not None else None),
        n_swaps=_env_int("SERVE_SWAPS", 3), max_wait_ms=max_wait_ms,
        rng=np.random.RandomState(7),
        trace_dir=os.environ.get("SERVE_TRACE") or None)
    loop_s = time.perf_counter() - t_loop0
    from fedamw_tpu.utils.reporting import (format_failover_report,
                                            format_rollout_report)

    print(f"# {format_rollout_report(rollout)}", file=sys.stderr)

    # ISSUE 7: the replica-fleet failover leg — the same engine behind
    # N replicas and a health-gating router, first clean, then with
    # replicas scripted to wedge/die mid-stream; zero lost requests and
    # zero recompiles are abort-grade pins
    t_chaos0 = time.perf_counter()
    chaos = chaos_bench(
        engine, n_requests=_env_int("SERVE_CHAOS_REQUESTS",
                                    max(n_requests, 120)),
        max_wait_ms=max_wait_ms)
    chaos_s = time.perf_counter() - t_chaos0
    print(f"# {format_failover_report(chaos)}", file=sys.stderr)

    # ISSUE 9: the cold-start leg — compile-warmup start vs
    # artifact-load start from the same checkpoint, side by side; the
    # artifact path must come up AND serve with compile_count == 0
    t_cold0 = time.perf_counter()
    engine_buckets = tuple(engine.buckets)
    cold = cold_start_bench(ckpt, engine_buckets, setup, X_test_raw)
    cold_s = time.perf_counter() - t_cold0
    print(f"# cold start: compile-warmup {cold['compile_warmup_s']}s "
          f"vs artifact load {cold['artifact_load_s']}s "
          f"({cold['speedup_x']}x; export paid once: "
          f"{cold['artifact_export_s']}s, artifact compile_count "
          f"{cold['artifact_compile_count']})", file=sys.stderr)

    # ISSUE 12: the unified-telemetry leg — the WHOLE plane (registry
    # time series + per-class SLO family + tracing + device
    # attribution) costed against the plane-off floor, paired; the
    # exactly-once-span and zero-recompile pins stay abort-grade
    t_tel0 = time.perf_counter()
    telemetry = telemetry_bench(engine, n_requests=n_requests,
                                max_wait_ms=max_wait_ms)
    telemetry_s = time.perf_counter() - t_tel0
    from fedamw_tpu.utils.reporting import format_overload_report
    print(f"# telemetry plane: {telemetry['overhead_x']}x vs plane-off "
          f"({telemetry['plane_on_req_per_s']} vs "
          f"{telemetry['plane_off_req_per_s']} req/s; "
          f"{telemetry['registry_instruments']} instruments, "
          f"{telemetry['registry_points']} series points; device "
          f"attribution: {telemetry['device_attribution']['source']})",
          file=sys.stderr)

    # ISSUE 14: the overload leg — the burn-rate admission controller
    # + autoscaled fleet against every fixed-N fleet under one seeded
    # flash crowd; the beat, interactive protection, zero lost
    # accepted requests, zero recompiles, and exactly-once spans are
    # abort-grade
    t_ov0 = time.perf_counter()
    overload = overload_bench(ckpt, tuple(engine.buckets), max_wait_ms)
    overload_s = time.perf_counter() - t_ov0
    print(f"# {format_overload_report(overload)}", file=sys.stderr)

    # ISSUE 15: the cross-process pod leg — real worker processes
    # over the frame protocol, one SIGKILLed and one partitioned
    # mid-stream under scripted network chaos, a version announce
    # broadcast to the survivors; zero lost accepted requests,
    # exactly-once spans with the trace propagated across the wire,
    # and zero recompiles on survivors are abort-grade
    t_pod0 = time.perf_counter()
    pod = pod_bench(ckpt, tuple(engine.buckets), max_wait_ms)
    pod_s = time.perf_counter() - t_pod0
    print(f"# pod: {pod['workers']} workers, {pod['requests']} "
          f"requests, {pod['kills_fired']} kill + "
          f"{pod['partitions_fired']} partitions fired, "
          f"{pod['requeues']} requeues, {pod['lost']} lost, "
          f"survivor recompiles {pod['survivor_recompiles']}, "
          f"swap v{pod['midstream_swap_version']} "
          f"({pod['swap_acks']} acks), {pod['pod_dispatch_spans']} "
          f"cross-process spans", file=sys.stderr)

    # the zero-recompile pin now spans EVERY stream — untraced, traced,
    # and the rollout leg's swapped versions: tracing must not perturb
    # the shape discipline, and neither may a weight swap
    recompiles = engine.compile_count - warm_compiles
    print(f"# mixed stream: {stream['requests']} requests in "
          f"{stream['batches']} batches, p50 {stream['p50_ms']}ms "
          f"(queue p50 {stream['queue_p50_ms']}ms / pad "
          f"{stream['pad_p50_ms']}ms / device "
          f"{stream['device_p50_ms']}ms), recompiles after warmup "
          f"(both streams): {recompiles}", file=sys.stderr)

    req_spans = [r for r in tracer.records() if r["name"] == "request"]
    ids = [r["trace_id"] for r in req_spans]
    ids_unique_once = (len(ids) == n_overhead
                       and len(set(ids)) == len(ids)
                       and tracer.dropped == 0)
    print(format_trace_summary("serve mixed-stream", tracer.records()),
          file=sys.stderr)
    if not ids_unique_once:
        # like the parity gate: a trace that lost or duplicated a
        # request must never emit green-looking overhead numbers
        print(f"# serve_bench aborted: {len(ids)} request spans "
              f"({len(set(ids))} unique, {tracer.dropped} dropped) for "
              f"{n_overhead} submitted requests", file=sys.stderr)
        raise SystemExit(1)
    trace_out = None
    if os.environ.get("SERVE_TRACE"):
        os.makedirs(os.environ["SERVE_TRACE"], exist_ok=True)
        trace_out = os.path.join(os.environ["SERVE_TRACE"],
                                 "serve_trace.jsonl")
        tracer.export_jsonl(trace_out)
        print(f"# trace -> {trace_out}", file=sys.stderr)

    overhead = best_off / best_on if best_on else float("inf")
    print(f"# trace overhead (best of {reps} alternating reps): traced "
          f"{best_on} req/s vs untraced {best_off} req/s "
          f"-> {overhead:.3f}x", file=sys.stderr)

    artifact = {
        "metric": "serve_bench",
        # v8: the pod section (cross-process serving over the frame
        # protocol) joins the v7 overload, v6 continuous_batching, v5
        # telemetry_overhead, v4 cold_start, v3 chaos, and v2 rollout
        # sections in the contract — tools/check_bench_schema.py
        # requires each from its version on (earlier artifacts are
        # grandfathered by schema version)
        "schema": "BENCH_SERVE.v8",
        "platform": platform,
        "engine": {
            "buckets": list(engine.buckets),
            "input_dim": engine.input_dim,
            "num_classes": engine.num_classes,
            "rff_fused": engine.rff is not None,
            "checkpoint": ckpt,
        },
        "warmup": {"compile_count": warm_compiles,
                   "seconds": round(warmup_s, 3)},
        "phases": {"build_s": round(build_s, 3),
                   "compile_warmup_s": round(warmup_s, 3),
                   "timed_run_s": round(timed_s, 3),
                   "rollout_s": round(loop_s, 3),
                   "chaos_s": round(chaos_s, 3),
                   "cold_start_s": round(cold_s, 3),
                   "telemetry_s": round(telemetry_s, 3),
                   "continuous_batching_s": round(cb_s, 3),
                   "overload_s": round(overload_s, 3),
                   "pod_s": round(pod_s, 3),
                   # None when BENCH_COMPILE_CACHE is unset (cold by
                   # construction); else dir + entry counts, so a
                   # warm-cache compile_warmup_s can never be read as
                   # a cold capture's
                   "compile_cache": (ccache.snapshot()
                                     if ccache is not None else None)},
        "bucket_latency": bucket_latency,
        "mixed_stream": stream,
        "rollout": rollout,
        "chaos": chaos,
        "cold_start": cold,
        "telemetry_overhead": telemetry,
        "continuous_batching": cb,
        "overload": overload,
        "pod": pod,
        "trace": {
            "request_spans": len(req_spans),
            "unique_request_ids": len(set(ids)),
            "all_ids_unique_once": ids_unique_once,
            "spans_total": len(tracer.records()),
            "dropped": tracer.dropped,
            "exported": trace_out,
        },
        "trace_overhead": {
            "value": round(overhead, 3),
            "reps": reps,
            "tracing_off_req_per_s": best_off,
            "tracing_on_req_per_s": best_on,
            "tracing_on_p50_ms": traced["p50_ms"],
        },
        "recompiles_after_warmup": recompiles,
        "parity": parity,
    }
    out_path = os.environ.get("SERVE_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"BENCH_SERVE_r{_env_int('SERVE_ROUND', 1):02d}.json")
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"# artifact -> {out_path}", file=sys.stderr)

    # the pod line (FIRST of the leg lines — each new leg prepends,
    # so every existing line position the contract test pins is
    # unmoved and the headline stays LAST): the cross-process
    # evidence — a real SIGKILL and a real partition survived on a
    # real wire, nothing lost, nothing compiled, the trace intact
    print(json.dumps({
        "metric": "serve_pod",
        "value": pod["requeues"],
        "unit": "requeues-across-processes",
        "workers": pod["workers"],
        "kills_fired": pod["kills_fired"],
        "partitions_fired": pod["partitions_fired"],
        "lost": pod["lost"],
        "survivor_recompiles": pod["survivor_recompiles"],
        "spans_exactly_once": pod["spans_exactly_once"],
        "trace_propagated": pod["trace_propagated"],
        "swap_version": pod["midstream_swap_version"],
        "platform": platform,
    }))

    # the overload line: the elastic
    # fleet's whole claim — SLO-good work per replica-second vs the
    # best fixed fleet, interactive protected while batch sheds,
    # nothing lost, nothing compiled
    best_fixed = max(
        rec["good_per_replica_s"]
        for name, rec in overload["fleets"].items()
        if name != "autoscaled")
    print(json.dumps({
        "metric": "serve_overload",
        "value": overload["fleets"]["autoscaled"]["good_per_replica_s"],
        "unit": "slo-good-req-per-replica-second",
        "best_fixed": best_fixed,
        "beats_every_fixed": overload["autoscaled_beats_every_fixed"],
        "interactive_attainment":
            overload["fleets"]["autoscaled"]["attainment"].get(
                "interactive"),
        "batch_shed": overload["batch_shed"],
        "scale_ups": overload["scale_ups"],
        "replicas_peak": overload["fleets"]["autoscaled"]
            ["replicas_peak"],
        "lost_accepted": overload["lost_accepted"],
        "recompiles_during_overload":
            overload["recompiles_during_overload"],
        "spans_exactly_once": overload["spans_exactly_once"],
        "platform": platform,
    }))

    # the continuous-batching line: the paired p95
    # improvement over the fixed-drain baseline, the learned ladder,
    # and the zero-recompile-after-freeze pin
    print(json.dumps({
        "metric": "serve_continuous_batching",
        "value": cb["p95_improvement_x"],
        "unit": "x-p95-vs-fixed-drain",
        "baseline_p95_ms": cb["baseline"]["p95_ms"],
        "continuous_p95_ms": cb["continuous"]["p95_ms"],
        "arrival_req_per_s": cb["arrival_req_per_s"],
        "ladder": cb["ladder"]["learned"],
        "recompiles_after_freeze": cb["recompiles_after_freeze"],
        "spans_exactly_once": cb["spans_exactly_once"],
        "platform": platform,
    }))

    # the telemetry-plane line (before the headline, which stays
    # LAST): what the whole observability plane costs, and whether the
    # device split landed
    print(json.dumps({
        "metric": "serve_telemetry_overhead",
        "value": telemetry["overhead_x"],
        "unit": "x-vs-plane-off",
        "plane_on_req_per_s": telemetry["plane_on_req_per_s"],
        "plane_off_req_per_s": telemetry["plane_off_req_per_s"],
        "registry_points": telemetry["registry_points"],
        "slo_classes": len(telemetry["slo"]["classes"]),
        "device_attribution": telemetry["device_attribution"]["source"],
        "platform": platform,
    }))

    # the chaos-leg line (before the headline, which stays LAST): the
    # failover evidence — kills fired, requeues landed, nothing lost,
    # and what chaos cost the tail
    print(json.dumps({
        "metric": "serve_chaos",
        "value": chaos["p95_ms_chaos"],
        "unit": "ms-p95-under-chaos",
        "p95_ms_clean": chaos["p95_ms_clean"],
        "kills": chaos["kills_observed"],
        "requeues": chaos["requeues"],
        "hedge_wins": chaos["hedge_wins"],
        "lost": chaos["lost"],
        "recompiles_during_chaos": chaos["recompiles_during_chaos"],
        "platform": platform,
    }))

    # the rollout-leg line (before the headline, which stays LAST):
    # swap latency is the number an operator sizes a publish cadence by
    print(json.dumps({
        "metric": "serve_rollout",
        "value": rollout["swap_p50_ms"],
        "unit": "ms/swap",
        "swaps": rollout["swaps"],
        "canary": rollout["canary"],
        "rollback_drill": rollout["rollback_drill"],
        "inflight_p95_ms": rollout["inflight_p95_ms"],
        "recompiles_during_swaps": rollout["recompiles_during_swaps"],
        "final_version": rollout["final_version"],
        "platform": platform,
    }))

    # the cold-start line (before the headline, which stays LAST): the
    # number a fleet operator sizes scale-out by — milliseconds to a
    # ready, zero-compile replica vs the compile-warmup seconds it
    # replaces
    print(json.dumps({
        "metric": "serve_cold_start",
        "value": round(cold["artifact_load_s"] * 1e3, 3),
        "unit": "ms-to-ready",
        "compile_warmup_s": cold["compile_warmup_s"],
        "artifact_export_s": cold["artifact_export_s"],
        "speedup_x": cold["speedup_x"],
        "artifact_compile_count": cold["artifact_compile_count"],
        "rungs": cold["rungs"],
        "platform": platform,
    }))

    # the trace-plane cost line (before the headline, which stays LAST)
    print(json.dumps({
        "metric": "serve_trace_overhead",
        "value": round(overhead, 3),
        "unit": "x-vs-untraced",
        "tracing_off_req_per_s": best_off,
        "tracing_on_req_per_s": best_on,
        "request_spans": len(req_spans),
        "platform": platform,
    }))

    # headline LAST (driver contract, as in bench.py): request
    # throughput through the full service path, tails attached
    print(json.dumps({
        "metric": "serve_requests_per_sec",
        "value": stream["throughput_req_per_s"],
        "unit": "requests/s",
        "p50_ms": stream["p50_ms"],
        "p95_ms": stream["p95_ms"],
        "p99_ms": stream["p99_ms"],
        "recompiles_after_warmup": recompiles,
        "buckets": len(engine.buckets),
        "platform": platform,
        "artifact": out_path,
    }))


if __name__ == "__main__":
    main()
