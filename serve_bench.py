"""Serving benchmark: checkpoint -> warmed engine -> load generator.

The serve-side sibling of ``bench.py``: it trains a small FedAvg model
(or loads SERVE_CKPT), saves it through ``utils/checkpoint.py`` WITH the
RFF draw, restores it via ``serving.ServingEngine.load`` — the full
production path, not an in-memory shortcut — and measures:

1. **Parity** (abort on failure): engine logits on the raw test set
   must reproduce ``fedcore/evaluate.py``'s accuracy exactly. A serving
   stack that serves different numbers than training evaluated is wrong
   before it is slow.
2. **Per-bucket latency**: p50/p95/p99 and rows/s for every rung of the
   bucket ladder, timed at the engine (no queueing).
3. **Mixed-size stream**: a deterministic request-size mix driven
   through the full ServingService (queue + micro-batcher + deadlines),
   reporting request-level percentiles, throughput, shed counts, and —
   the shape-discipline invariant — **zero recompiles after warmup**,
   read from the jit compile-cache counter.

Output follows the ``bench.py`` driver contract: JSON lines on stdout
with the headline metric LAST, plus a ``BENCH_SERVE_rNN.json`` artifact
(SERVE_OUT overrides the path). The same strict-backend guard applies:
under BENCH_STRICT_TPU=1 a resolved non-TPU backend aborts rc=1 before
measuring anything, so a leaked JAX_PLATFORMS=cpu can never be
harvested green (mirrors bench.py; pinned in
``tests/test_serve_contract.py``).

Env knobs: SERVE_BUCKETS ("1,8,64,512"), SERVE_D (RFF width, 256),
SERVE_N (train rows, 4096), SERVE_CLIENTS (8), SERVE_TRAIN_ROUNDS (2),
SERVE_ITERS (per-bucket timed calls, 30), SERVE_REQUESTS (mixed-stream
requests, 200), SERVE_MAX_WAIT_MS (2.0), SERVE_CKPT (serve an existing
checkpoint dir instead of training), SERVE_OUT, SERVE_ROUND (artifact
suffix, default 1).
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np


def _env_int(name, default):
    return int(os.environ.get(name, str(default)))


def build_checkpoint(ckpt_dir: str, D: int, n: int, clients: int,
                     rounds: int):
    """Train a small FedAvg model on shape-matched synthetic data and
    checkpoint it (params + mixture weights + RFF draw). Returns the
    setup (for the parity cross-check) and the raw test matrix."""
    from fedamw_tpu.algorithms import FedAvg, prepare_setup
    from fedamw_tpu.data import FederatedDataset, dirichlet_partition
    from fedamw_tpu.data.synthetic import synthetic_classification
    from fedamw_tpu.utils.checkpoint import save_checkpoint

    X, y, Xt, yt = synthetic_classification(n, 64, 2, seed=3)
    parts, _ = dirichlet_partition(y, clients, alpha=0.5, seed=2020,
                                   min_size=0)
    ds = FederatedDataset(
        name="serve-synth", task_type="classification", num_classes=2,
        d=64, X_train=X, y_train=y, X_test=Xt, y_test=yt, parts=parts,
        source="synthetic")
    setup = prepare_setup(ds, D=D, kernel_par=0.1, seed=100,
                          rng=np.random.RandomState(100))
    res = FedAvg(setup, lr=0.5, epoch=1, batch_size=32, round=rounds,
                 seed=0, lr_mode="constant", return_state=True)
    save_checkpoint(ckpt_dir, res["params"], p=res["p"],
                    round_idx=rounds, rff=setup.rff)
    return setup, np.asarray(Xt, np.float32)


def check_parity(engine, setup, X_test_raw) -> dict:
    """Engine-vs-evaluate accuracy on the SAME test set: the serving
    path re-maps raw inputs through the checkpointed RFF draw, so an
    exact accuracy match certifies the whole load/fuse/pad pipeline."""
    import jax.numpy as jnp

    from fedamw_tpu.fedcore import make_evaluator

    evaluate = make_evaluator(setup.model.apply, setup.task)
    _, eval_acc = evaluate(
        {k: jnp.asarray(v) for k, v in engine.params.items()},
        setup.X_test, setup.y_test)
    logits = engine.predict(X_test_raw)
    y = np.asarray(setup.y_test)
    engine_acc = 100.0 * float(np.mean(np.argmax(logits, -1) == y))
    return {"engine_acc": round(engine_acc, 6),
            "evaluate_acc": round(float(eval_acc), 6),
            "match": abs(engine_acc - float(eval_acc)) < 1e-4}


def time_bucket(engine, b: int, iters: int, rng) -> dict:
    """Steady-state latency of one ladder rung (exact-fit batches, so
    the number is the compiled program + host roundtrip, no padding)."""
    from fedamw_tpu.serving import LatencyHistogram

    X = rng.randn(b, engine.input_dim).astype(np.float32)
    hist = LatencyHistogram()
    engine.predict(X)  # rung already compiled by warmup; absorb cache hits
    t0 = time.perf_counter()
    for _ in range(iters):
        t1 = time.perf_counter()
        engine.predict(X)
        hist.record(time.perf_counter() - t1)
    dt = time.perf_counter() - t0
    out = hist.percentiles()
    out.update(iters=iters,
               throughput_rows_per_s=round(b * iters / dt, 2))
    return out


def mixed_stream(engine, n_requests: int, max_wait_ms: float, rng) -> dict:
    """Drive a deterministic mixed-size request stream through the full
    service loop and snapshot its metrics. Sizes mix single rows with
    every rung boundary's neighborhood so each compiled bucket serves
    real (non-warmup) traffic."""
    from fedamw_tpu.serving import ServingService

    sizes = []
    for b in engine.buckets:
        sizes += [1, max(1, b // 2), b]
    sizes = [sizes[i % len(sizes)] for i in rng.permutation(
        max(n_requests, len(sizes)))[:n_requests]]
    payloads = [rng.randn(s, engine.input_dim).astype(np.float32)
                for s in sizes]
    t0 = time.perf_counter()
    # the load generator enqueues far faster than the engine drains;
    # max_queue must admit the whole configured stream or a large
    # SERVE_REQUESTS would crash with Overloaded instead of measuring
    with ServingService(engine, max_wait_ms=max_wait_ms,
                        max_queue=max(1024, n_requests)) as svc:
        futures = [svc.submit(x) for x in payloads]
        for f in futures:
            f.result(timeout=300)
        dt = time.perf_counter() - t0
        snap = svc.metrics.snapshot(engine)
    # end-to-end wall-clock throughput (the metrics-internal rate spans
    # batch completions only and is None for a single-batch stream)
    snap["throughput_req_per_s"] = round(len(payloads) / dt, 2)
    snap["throughput_rows_per_s"] = round(sum(sizes) / dt, 2)
    return snap


def main():
    # shared prologue with bench.py (bench_common): re-apply
    # JAX_PLATFORMS over the container's sitecustomize, then the
    # BENCH_STRICT_TPU certification abort on the RESOLVED backend
    from bench_common import reapply_jax_platforms, strict_tpu_abort

    reapply_jax_platforms()
    import jax

    platform = jax.default_backend()
    strict_tpu_abort("serve_bench", platform)

    from fedamw_tpu.serving import ServingEngine

    buckets = tuple(int(b) for b in os.environ.get(
        "SERVE_BUCKETS", "1,8,64,512").split(","))
    D = _env_int("SERVE_D", 256)
    iters = _env_int("SERVE_ITERS", 30)
    n_requests = _env_int("SERVE_REQUESTS", 200)
    max_wait_ms = float(os.environ.get("SERVE_MAX_WAIT_MS", "2.0"))

    ckpt = os.environ.get("SERVE_CKPT")
    setup = None
    scratch = None  # our own train-and-serve checkpoint, removed on exit
    if ckpt:
        engine = ServingEngine.load(ckpt, buckets=buckets)
        print(f"# serving existing checkpoint {ckpt}", file=sys.stderr)
    else:
        ckpt = scratch = tempfile.mkdtemp(prefix="serve_ckpt_")
        setup, X_test_raw = build_checkpoint(
            ckpt, D=D, n=_env_int("SERVE_N", 4096),
            clients=_env_int("SERVE_CLIENTS", 8),
            rounds=_env_int("SERVE_TRAIN_ROUNDS", 2))
        engine = ServingEngine.load(ckpt, buckets=buckets)
    try:
        _run_bench(engine, setup, X_test_raw if setup is not None
                   else None, ckpt, platform, iters, n_requests,
                   max_wait_ms)
    finally:
        if scratch is not None:
            shutil.rmtree(scratch, ignore_errors=True)


def _run_bench(engine, setup, X_test_raw, ckpt, platform, iters,
               n_requests, max_wait_ms):

    parity = None
    if setup is not None:
        parity = check_parity(engine, setup, X_test_raw)
        print(f"# parity: engine {parity['engine_acc']:.4f} vs "
              f"evaluate {parity['evaluate_acc']:.4f}", file=sys.stderr)
        if not parity["match"]:
            # a serving stack that disagrees with training evaluation
            # must never emit green-looking latency numbers
            print("# serve_bench aborted: serving/evaluate accuracy "
                  "parity FAILED", file=sys.stderr)
            raise SystemExit(1)

    t0 = time.perf_counter()
    warm_compiles = engine.warmup()
    warmup_s = time.perf_counter() - t0
    print(f"# warmup: {warm_compiles} programs "
          f"({len(engine.buckets)} buckets) in {warmup_s:.2f}s",
          file=sys.stderr)

    rng = np.random.RandomState(0)
    bucket_latency = {}
    for b in engine.buckets:
        bucket_latency[str(b)] = rec = time_bucket(engine, b, iters, rng)
        print(json.dumps({
            "metric": "serve_bucket_latency",
            "bucket": b, "platform": platform, **rec}))
        print(f"# bucket {b:>5}: p50 {rec['p50_ms']}ms  p99 "
              f"{rec['p99_ms']}ms  {rec['throughput_rows_per_s']} rows/s",
              file=sys.stderr)

    stream = mixed_stream(engine, n_requests, max_wait_ms, rng)
    recompiles = engine.compile_count - warm_compiles
    print(f"# mixed stream: {stream['requests']} requests in "
          f"{stream['batches']} batches, p50 {stream['p50_ms']}ms, "
          f"recompiles after warmup: {recompiles}", file=sys.stderr)

    artifact = {
        "metric": "serve_bench",
        "schema": "BENCH_SERVE.v1",
        "platform": platform,
        "engine": {
            "buckets": list(engine.buckets),
            "input_dim": engine.input_dim,
            "num_classes": engine.num_classes,
            "rff_fused": engine.rff is not None,
            "checkpoint": ckpt,
        },
        "warmup": {"compile_count": warm_compiles,
                   "seconds": round(warmup_s, 3)},
        "bucket_latency": bucket_latency,
        "mixed_stream": stream,
        "recompiles_after_warmup": recompiles,
        "parity": parity,
    }
    out_path = os.environ.get("SERVE_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"BENCH_SERVE_r{_env_int('SERVE_ROUND', 1):02d}.json")
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"# artifact -> {out_path}", file=sys.stderr)

    # headline LAST (driver contract, as in bench.py): request
    # throughput through the full service path, tails attached
    print(json.dumps({
        "metric": "serve_requests_per_sec",
        "value": stream["throughput_req_per_s"],
        "unit": "requests/s",
        "p50_ms": stream["p50_ms"],
        "p95_ms": stream["p95_ms"],
        "p99_ms": stream["p99_ms"],
        "recompiles_after_warmup": recompiles,
        "buckets": len(engine.buckets),
        "platform": platform,
        "artifact": out_path,
    }))


if __name__ == "__main__":
    main()
