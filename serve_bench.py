"""Serving benchmark: checkpoint -> warmed engine -> load generator.

The serve-side sibling of ``bench.py``: it trains a small FedAvg model
(or loads SERVE_CKPT), saves it through ``utils/checkpoint.py`` WITH the
RFF draw, restores it via ``serving.ServingEngine.load`` — the full
production path, not an in-memory shortcut — and measures:

1. **Parity** (abort on failure): engine logits on the raw test set
   must reproduce ``fedcore/evaluate.py``'s accuracy exactly. A serving
   stack that serves different numbers than training evaluated is wrong
   before it is slow.
2. **Per-bucket latency**: p50/p95/p99 and rows/s for every rung of the
   bucket ladder, timed at the engine (no queueing).
3. **Mixed-size stream**: a deterministic request-size mix driven
   through the full ServingService (queue + micro-batcher + deadlines),
   reporting request-level percentiles, throughput, shed counts, and —
   the shape-discipline invariant — **zero recompiles after warmup**,
   read from the jit compile-cache counter.

Output follows the ``bench.py`` driver contract: JSON lines on stdout
with the headline metric LAST, plus a ``BENCH_SERVE_rNN.json`` artifact
(SERVE_OUT overrides the path). The same strict-backend guard applies:
under BENCH_STRICT_TPU=1 a resolved non-TPU backend aborts rc=1 before
measuring anything, so a leaked JAX_PLATFORMS=cpu can never be
harvested green (mirrors bench.py; pinned in
``tests/test_serve_contract.py``).

The mixed stream now runs both untraced — its snapshot, carrying
per-stage (queue / pad / device) latency percentiles, is the headline
source — and through a live ``utils.trace`` Tracer (ISSUE 5), which
must hold every submitted request id exactly once (abort on violation,
like the parity gate). The tracing cost is reported as
``serve_trace_overhead``: best-of-``SERVE_TRACE_REPS`` (default 5)
alternating traced/untraced legs, so a ~tens-of-ms stream's
thread-scheduling noise does not masquerade as overhead. The artifact
grows ``phases`` (build / compile-warmup / timed-run seconds) and a
``trace`` section; recompiles-after-warmup is checked across ALL
streams.

Env knobs: SERVE_BUCKETS ("1,8,64,512"), SERVE_D (RFF width, 256),
SERVE_N (train rows, 4096), SERVE_CLIENTS (8), SERVE_TRAIN_ROUNDS (2),
SERVE_ITERS (per-bucket timed calls, 30), SERVE_REQUESTS (mixed-stream
requests, 200), SERVE_MAX_WAIT_MS (2.0), SERVE_CKPT (serve an existing
checkpoint dir instead of training), SERVE_OUT, SERVE_ROUND (artifact
suffix, default 1), SERVE_TRACE (directory: export the traced leg's
span records as JSONL there), BENCH_PROFILE_DIR (jax.profiler capture
of the timed section, shared with bench.py via
bench_common.profile_ctx).
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np


def _env_int(name, default):
    return int(os.environ.get(name, str(default)))


def build_checkpoint(ckpt_dir: str, D: int, n: int, clients: int,
                     rounds: int):
    """Train a small FedAvg model on shape-matched synthetic data and
    checkpoint it (params + mixture weights + RFF draw). Returns the
    setup (for the parity cross-check) and the raw test matrix."""
    from fedamw_tpu.algorithms import FedAvg, prepare_setup
    from fedamw_tpu.data import FederatedDataset, dirichlet_partition
    from fedamw_tpu.data.synthetic import synthetic_classification
    from fedamw_tpu.utils.checkpoint import save_checkpoint

    X, y, Xt, yt = synthetic_classification(n, 64, 2, seed=3)
    parts, _ = dirichlet_partition(y, clients, alpha=0.5, seed=2020,
                                   min_size=0)
    ds = FederatedDataset(
        name="serve-synth", task_type="classification", num_classes=2,
        d=64, X_train=X, y_train=y, X_test=Xt, y_test=yt, parts=parts,
        source="synthetic")
    setup = prepare_setup(ds, D=D, kernel_par=0.1, seed=100,
                          rng=np.random.RandomState(100))
    res = FedAvg(setup, lr=0.5, epoch=1, batch_size=32, round=rounds,
                 seed=0, lr_mode="constant", return_state=True)
    save_checkpoint(ckpt_dir, res["params"], p=res["p"],
                    round_idx=rounds, rff=setup.rff)
    return setup, np.asarray(Xt, np.float32)


def check_parity(engine, setup, X_test_raw) -> dict:
    """Engine-vs-evaluate accuracy on the SAME test set: the serving
    path re-maps raw inputs through the checkpointed RFF draw, so an
    exact accuracy match certifies the whole load/fuse/pad pipeline."""
    import jax.numpy as jnp

    from fedamw_tpu.fedcore import make_evaluator

    evaluate = make_evaluator(setup.model.apply, setup.task)
    _, eval_acc = evaluate(
        {k: jnp.asarray(v) for k, v in engine.params.items()},
        setup.X_test, setup.y_test)
    logits = engine.predict(X_test_raw)
    y = np.asarray(setup.y_test)
    engine_acc = 100.0 * float(np.mean(np.argmax(logits, -1) == y))
    return {"engine_acc": round(engine_acc, 6),
            "evaluate_acc": round(float(eval_acc), 6),
            "match": abs(engine_acc - float(eval_acc)) < 1e-4}


def time_bucket(engine, b: int, iters: int, rng) -> dict:
    """Steady-state latency of one ladder rung (exact-fit batches, so
    the number is the compiled program + host roundtrip, no padding)."""
    from fedamw_tpu.serving import LatencyHistogram

    X = rng.randn(b, engine.input_dim).astype(np.float32)
    hist = LatencyHistogram()
    engine.predict(X)  # rung already compiled by warmup; absorb cache hits
    t0 = time.perf_counter()
    for _ in range(iters):
        t1 = time.perf_counter()
        engine.predict(X)
        hist.record(time.perf_counter() - t1)
    dt = time.perf_counter() - t0
    out = hist.percentiles()
    out.update(iters=iters,
               throughput_rows_per_s=round(b * iters / dt, 2))
    return out


def mixed_stream(engine, n_requests: int, max_wait_ms: float, rng,
                 tracer=None) -> dict:
    """Drive a deterministic mixed-size request stream through the full
    service loop and snapshot its metrics (now including the per-stage
    queue/pad/device percentile families). Sizes mix single rows with
    every rung boundary's neighborhood so each compiled bucket serves
    real (non-warmup) traffic. ``tracer``: a live ``utils.trace``
    Tracer for the traced leg (every accepted request lands one
    "request" span); None keeps the no-op default."""
    from fedamw_tpu.serving import ServingService

    sizes = []
    for b in engine.buckets:
        sizes += [1, max(1, b // 2), b]
    sizes = [sizes[i % len(sizes)] for i in rng.permutation(
        max(n_requests, len(sizes)))[:n_requests]]
    payloads = [rng.randn(s, engine.input_dim).astype(np.float32)
                for s in sizes]
    t0 = time.perf_counter()
    # the load generator enqueues far faster than the engine drains;
    # max_queue must admit the whole configured stream or a large
    # SERVE_REQUESTS would crash with Overloaded instead of measuring
    with ServingService(engine, max_wait_ms=max_wait_ms,
                        max_queue=max(1024, n_requests),
                        tracer=tracer) as svc:
        futures = [svc.submit(x) for x in payloads]
        for f in futures:
            f.result(timeout=300)
        dt = time.perf_counter() - t0
        snap = svc.metrics.snapshot(engine)
    # end-to-end wall-clock throughput (the metrics-internal rate spans
    # batch completions only and is None for a single-batch stream)
    snap["throughput_req_per_s"] = round(len(payloads) / dt, 2)
    snap["throughput_rows_per_s"] = round(sum(sizes) / dt, 2)
    return snap


def main():
    # shared prologue with bench.py (bench_common): re-apply
    # JAX_PLATFORMS over the container's sitecustomize, then the
    # BENCH_STRICT_TPU certification abort on the RESOLVED backend
    from bench_common import reapply_jax_platforms, strict_tpu_abort

    reapply_jax_platforms()
    import jax

    platform = jax.default_backend()
    strict_tpu_abort("serve_bench", platform)

    from fedamw_tpu.serving import ServingEngine

    buckets = tuple(int(b) for b in os.environ.get(
        "SERVE_BUCKETS", "1,8,64,512").split(","))
    D = _env_int("SERVE_D", 256)
    iters = _env_int("SERVE_ITERS", 30)
    n_requests = _env_int("SERVE_REQUESTS", 200)
    max_wait_ms = float(os.environ.get("SERVE_MAX_WAIT_MS", "2.0"))

    ckpt = os.environ.get("SERVE_CKPT")
    setup = None
    scratch = None  # our own train-and-serve checkpoint, removed on exit
    t_build0 = time.perf_counter()
    if ckpt:
        engine = ServingEngine.load(ckpt, buckets=buckets)
        print(f"# serving existing checkpoint {ckpt}", file=sys.stderr)
    else:
        ckpt = scratch = tempfile.mkdtemp(prefix="serve_ckpt_")
        setup, X_test_raw = build_checkpoint(
            ckpt, D=D, n=_env_int("SERVE_N", 4096),
            clients=_env_int("SERVE_CLIENTS", 8),
            rounds=_env_int("SERVE_TRAIN_ROUNDS", 2))
        engine = ServingEngine.load(ckpt, buckets=buckets)
    build_s = time.perf_counter() - t_build0
    try:
        _run_bench(engine, setup, X_test_raw if setup is not None
                   else None, ckpt, platform, iters, n_requests,
                   max_wait_ms, build_s)
    finally:
        if scratch is not None:
            shutil.rmtree(scratch, ignore_errors=True)


def _run_bench(engine, setup, X_test_raw, ckpt, platform, iters,
               n_requests, max_wait_ms, build_s):

    parity = None
    if setup is not None:
        parity = check_parity(engine, setup, X_test_raw)
        print(f"# parity: engine {parity['engine_acc']:.4f} vs "
              f"evaluate {parity['evaluate_acc']:.4f}", file=sys.stderr)
        if not parity["match"]:
            # a serving stack that disagrees with training evaluation
            # must never emit green-looking latency numbers
            print("# serve_bench aborted: serving/evaluate accuracy "
                  "parity FAILED", file=sys.stderr)
            raise SystemExit(1)

    t0 = time.perf_counter()
    warm_compiles = engine.warmup()
    warmup_s = time.perf_counter() - t0
    print(f"# warmup: {warm_compiles} programs "
          f"({len(engine.buckets)} buckets) in {warmup_s:.2f}s",
          file=sys.stderr)

    from bench_common import profile_ctx
    from fedamw_tpu.utils.reporting import format_trace_summary
    from fedamw_tpu.utils.trace import Tracer

    rng = np.random.RandomState(0)
    bucket_latency = {}
    t_timed0 = time.perf_counter()
    with profile_ctx("serve_bench"):
        for b in engine.buckets:
            bucket_latency[str(b)] = rec = time_bucket(engine, b, iters,
                                                       rng)
            print(json.dumps({
                "metric": "serve_bucket_latency",
                "bucket": b, "platform": platform, **rec}))
            print(f"# bucket {b:>5}: p50 {rec['p50_ms']}ms  p99 "
                  f"{rec['p99_ms']}ms  "
                  f"{rec['throughput_rows_per_s']} rows/s",
                  file=sys.stderr)

        stream = mixed_stream(engine, n_requests, max_wait_ms, rng)

        # traced twin of the mixed stream (ISSUE 5): the tracing cost
        # as BEST-of-reps over PAIRED legs. Pairing matters twice:
        # each rep reseeds its rng so the off and on leg serve the
        # IDENTICAL request-size stream (a shared rng would hand the
        # two legs different size mixes — a systematic bias that
        # measured as a fake 1.6x overhead), and max-throughput over
        # reps is the standard steady-state estimator that shrugs off
        # the +-17% thread-scheduling noise of a ~tens-of-ms stream
        reps = _env_int("SERVE_TRACE_REPS", 5)
        # floor the overhead streams at 200 requests: a 40-request
        # stream lasts ~4 ms, inside one scheduler quantum, and its
        # timing is quantization noise whatever the estimator
        n_overhead = max(n_requests, 200)
        best_off, best_on = 0.0, 0.0
        tracer, traced = None, None
        for rep in range(max(1, reps)):
            off_snap = mixed_stream(engine, n_overhead, max_wait_ms,
                                    np.random.RandomState(100 + rep))
            best_off = max(best_off, off_snap["throughput_req_per_s"])
            t = Tracer(max_spans=4 * n_overhead + 64)
            on_snap = mixed_stream(engine, n_overhead, max_wait_ms,
                                   np.random.RandomState(100 + rep),
                                   tracer=t)
            if on_snap["throughput_req_per_s"] >= best_on:
                # keep the WINNING rep's tracer and snapshot together,
                # so the artifact's tracing_on_* fields (throughput,
                # p50) and the exported trace all describe one run
                best_on = on_snap["throughput_req_per_s"]
                tracer, traced = t, on_snap
    timed_s = time.perf_counter() - t_timed0

    # the zero-recompile pin now spans BOTH streams: tracing must not
    # perturb the shape discipline (host-side timestamps only)
    recompiles = engine.compile_count - warm_compiles
    print(f"# mixed stream: {stream['requests']} requests in "
          f"{stream['batches']} batches, p50 {stream['p50_ms']}ms "
          f"(queue p50 {stream['queue_p50_ms']}ms / pad "
          f"{stream['pad_p50_ms']}ms / device "
          f"{stream['device_p50_ms']}ms), recompiles after warmup "
          f"(both streams): {recompiles}", file=sys.stderr)

    req_spans = [r for r in tracer.records() if r["name"] == "request"]
    ids = [r["trace_id"] for r in req_spans]
    ids_unique_once = (len(ids) == n_overhead
                       and len(set(ids)) == len(ids)
                       and tracer.dropped == 0)
    print(format_trace_summary("serve mixed-stream", tracer.records()),
          file=sys.stderr)
    if not ids_unique_once:
        # like the parity gate: a trace that lost or duplicated a
        # request must never emit green-looking overhead numbers
        print(f"# serve_bench aborted: {len(ids)} request spans "
              f"({len(set(ids))} unique, {tracer.dropped} dropped) for "
              f"{n_overhead} submitted requests", file=sys.stderr)
        raise SystemExit(1)
    trace_out = None
    if os.environ.get("SERVE_TRACE"):
        os.makedirs(os.environ["SERVE_TRACE"], exist_ok=True)
        trace_out = os.path.join(os.environ["SERVE_TRACE"],
                                 "serve_trace.jsonl")
        tracer.export_jsonl(trace_out)
        print(f"# trace -> {trace_out}", file=sys.stderr)

    overhead = best_off / best_on if best_on else float("inf")
    print(f"# trace overhead (best of {reps} alternating reps): traced "
          f"{best_on} req/s vs untraced {best_off} req/s "
          f"-> {overhead:.3f}x", file=sys.stderr)

    artifact = {
        "metric": "serve_bench",
        "schema": "BENCH_SERVE.v1",
        "platform": platform,
        "engine": {
            "buckets": list(engine.buckets),
            "input_dim": engine.input_dim,
            "num_classes": engine.num_classes,
            "rff_fused": engine.rff is not None,
            "checkpoint": ckpt,
        },
        "warmup": {"compile_count": warm_compiles,
                   "seconds": round(warmup_s, 3)},
        "phases": {"build_s": round(build_s, 3),
                   "compile_warmup_s": round(warmup_s, 3),
                   "timed_run_s": round(timed_s, 3)},
        "bucket_latency": bucket_latency,
        "mixed_stream": stream,
        "trace": {
            "request_spans": len(req_spans),
            "unique_request_ids": len(set(ids)),
            "all_ids_unique_once": ids_unique_once,
            "spans_total": len(tracer.records()),
            "dropped": tracer.dropped,
            "exported": trace_out,
        },
        "trace_overhead": {
            "value": round(overhead, 3),
            "reps": reps,
            "tracing_off_req_per_s": best_off,
            "tracing_on_req_per_s": best_on,
            "tracing_on_p50_ms": traced["p50_ms"],
        },
        "recompiles_after_warmup": recompiles,
        "parity": parity,
    }
    out_path = os.environ.get("SERVE_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"BENCH_SERVE_r{_env_int('SERVE_ROUND', 1):02d}.json")
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"# artifact -> {out_path}", file=sys.stderr)

    # the trace-plane cost line (before the headline, which stays LAST)
    print(json.dumps({
        "metric": "serve_trace_overhead",
        "value": round(overhead, 3),
        "unit": "x-vs-untraced",
        "tracing_off_req_per_s": best_off,
        "tracing_on_req_per_s": best_on,
        "request_spans": len(req_spans),
        "platform": platform,
    }))

    # headline LAST (driver contract, as in bench.py): request
    # throughput through the full service path, tails attached
    print(json.dumps({
        "metric": "serve_requests_per_sec",
        "value": stream["throughput_req_per_s"],
        "unit": "requests/s",
        "p50_ms": stream["p50_ms"],
        "p95_ms": stream["p95_ms"],
        "p99_ms": stream["p99_ms"],
        "recompiles_after_warmup": recompiles,
        "buckets": len(engine.buckets),
        "platform": platform,
        "artifact": out_path,
    }))


if __name__ == "__main__":
    main()
