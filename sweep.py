"""Standalone hyperparameter sweep — the NNI-free twin of config.yml.

NNI is not installed on this box, so this driver replays the reference
tuning setup (`/root/reference/config.yml`: TPE over lr_p x lambda_reg,
trial = `tune.py`) without the daemon: it samples trials from the SAME
search grid, calls ``tune.main`` in-process (sharing the jit cache
across trials — the round-scan program recompiles only when a
jit-static knob like lr_p changes), and writes a ranked TUNING.md.
With NNI installed, `nnictl create --config config.yml` remains the
full TPE path; this script is the zero-dependency fallback and the
generator of the committed tuning artifact.

Usage: python sweep.py [--dataset digits] [--trials 12] [--round 50]
                       [--seed 7] [--out TUNING.md]
"""

import argparse
import os
import time


# the reference search space, config.yml:12-17 (verbatim grids)
LR_P_GRID = [0.5, 0.1, 0.01, 0.005, 0.001, 0.0005, 0.0001,
             0.00005, 0.00001, 0.000005, 0.000001]
LAMBDA_REG_GRID = [0.1, 0.01, 0.005, 0.001, 0.0005, 0.0001,
                   0.00005, 0.00001, 0.000005, 0.000001, 0.0000001]


def run_sweep(dataset, trials, rounds, seed, backend="jax", trial_seed=1):
    import numpy as np

    import tune

    rng = np.random.RandomState(seed)
    grid = [(lp, lam) for lp in LR_P_GRID for lam in LAMBDA_REG_GRID]
    picks = [grid[i] for i in rng.choice(len(grid), size=min(trials, len(grid)),
                                         replace=False)]
    results = []
    for i, (lr_p, lam) in enumerate(picks):
        params = vars(tune.get_params())
        # pin the trial training seed explicitly: --seed is a shared flag,
        # so without this the sweep's grid-sampling seed would leak into
        # the trials via parse_known_args (the NNI flow runs tune.py at
        # its default seed=1).
        params.update(dataset=dataset, lr_p=lr_p, lambda_reg=lam,
                      round=rounds, backend=backend, seed=trial_seed)
        t0 = time.perf_counter()
        metrics = {}
        acc = tune.main(params, metrics_out=metrics)
        dt = time.perf_counter() - t0
        results.append({"lr_p": lr_p, "lambda_reg": lam,
                        "acc": acc, "loss": metrics["loss"],
                        "wall_s": dt})
        print(f"[trial {i + 1}/{len(picks)}] lr_p={lr_p} lambda_reg={lam} "
              f"-> acc {acc:.2f} loss {metrics['loss']:.5f} ({dt:.1f}s)",
              flush=True)
    from fedamw_tpu.config import get_parameter

    if get_parameter(dataset).get("task_type") == "regression":
        # acc is 0.0 on regression tasks (fedcore/evaluate.py) — rank
        # by final MSE ascending; a diverged (non-finite) trial sorts
        # last. The reference's NNI flow maximized the acc report even
        # for its regression dataset (/root/reference/tune.py:135), so
        # its TPE was blind there; this ranking is the repair.
        import math

        return sorted(results,
                      key=lambda r: (not math.isfinite(r["loss"]),
                                     r["loss"]))
    return sorted(results, key=lambda r: -r["acc"])


def write_report(results, dataset, rounds, seed, out, trial_seed=1):
    from fedamw_tpu.config import get_parameter

    # the trial loss is the task's own objective — label it honestly
    # (CE for classification, MSE for regression)
    loss_label = ("final MSE"
                  if get_parameter(dataset).get("task_type") == "regression"
                  else "final CE")
    lines = [
        "# TUNING — FedAMW hyperparameter sweep (standalone)",
        "",
        f"`sweep.py --dataset {dataset} --trials {len(results)} "
        f"--round {rounds} --seed {seed} --trial_seed {trial_seed} "
        f"--out {out}` — random search over the",
        "reference TPE grid (`/root/reference/config.yml:12-17`; NNI is",
        "not installed here, so this is the zero-dependency twin of the",
        "`nnictl` flow — `tune.py` is the trial entry in both). 50",
        "clients, Dirichlet alpha=0.01, D=2000 RFF, the registry's",
        "remaining hyperparameters.",
        "",
        f"| rank | lr_p | lambda_reg | final acc | {loss_label} | trial wall (s) |",
        "|---|---|---|---|---|---|",
    ]
    for i, r in enumerate(results):
        lines.append(f"| {i + 1} | {r['lr_p']} | {r['lambda_reg']} | "
                     f"{r['acc']:.2f} | {r.get('loss', float('nan')):.5f} "
                     f"| {r['wall_s']:.1f} |")
    lines += [
        "",
        "The rows above rank this run's sampled trials only. Historical",
        "note: the `digits` registry block (`config.py`) carries the",
        "rank-1 values of the committed digits sweep (adopted in commit",
        "06c7e94), and the parity artifacts (`results_parity/`,",
        "PARITY.md) were regenerated under them. The reference's own",
        "per-dataset blocks were produced the same way at larger trial",
        "counts.",
        "",
    ]
    with open(out, "w") as f:
        f.write("\n".join(lines))
    print(f"report -> {out}")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dataset", type=str, default="digits")
    ap.add_argument("--trials", type=int, default=12)
    ap.add_argument("--round", type=int, default=50)
    ap.add_argument("--seed", type=int, default=7,
                    help="grid-sampling seed (NOT the trial training seed)")
    ap.add_argument("--trial_seed", type=int, default=1,
                    help="training seed passed to every trial "
                         "(tune.py's default, matching the NNI flow)")
    ap.add_argument("--backend", type=str, default="jax")
    ap.add_argument("--out", type=str, default="TUNING.md")
    args = ap.parse_args()
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    results = run_sweep(args.dataset, args.trials, args.round, args.seed,
                        args.backend, trial_seed=args.trial_seed)
    write_report(results, args.dataset, args.round, args.seed, args.out,
                 trial_seed=args.trial_seed)


if __name__ == "__main__":
    main()
