"""End-to-end algorithm tests: small synthetic federations on CPU.

Raw (identity-mapped) digits features converge in a handful of rounds,
which keeps these fast; one test exercises the full RFF path. Short runs
use ``lr_mode='constant'`` — the reference's compounding decay schedule
zeroes the lr almost immediately at tiny round counts.
"""

import numpy as np
import pytest

from fedamw_tpu.algorithms import (
    ALGORITHMS,
    Centralized,
    Distributed,
    FedAMW,
    FedAMW_OneShot,
    FedAvg,
    FedNova,
    FedProx,
    prepare_setup,
)
from fedamw_tpu.data import load_dataset


@pytest.fixture(scope="module")
def setup():
    ds = load_dataset("digits", num_partitions=4, alpha=0.5)
    return prepare_setup(ds, kernel_type="linear", seed=100,
                         rng=np.random.RandomState(100))


class TestRoundBased:
    def test_fedavg_learns(self, setup):
        res = FedAvg(setup, lr=0.5, epoch=2, batch_size=32, round=8, seed=0,
                     lr_mode="constant")
        assert res["test_acc"].shape == (8,)
        assert res["train_loss"].shape == (8,)
        assert res["test_acc"][-1] > 85.0
        assert res["test_loss"][-1] < res["test_loss"][0]

    def test_fedavg_reference_schedule_decays(self, setup):
        res = FedAvg(setup, lr=0.5, epoch=1, round=8, seed=0,
                     lr_mode="reference")
        # decay at t=4 (/10) and t=6 (/1000): late rounds barely move
        late_delta = abs(res["test_acc"][-1] - res["test_acc"][-2])
        assert late_delta < 1.0

    def test_fedprox_runs(self, setup):
        res = FedProx(setup, lr=0.5, epoch=2, round=6, prox=True, mu=0.01,
                      seed=0, lr_mode="constant")
        assert res["test_acc"][-1] > 80.0

    def test_fednova_runs(self, setup):
        res = FedNova(setup, lr=0.5, epoch=2, round=6, seed=0,
                      lr_mode="constant")
        assert res["test_acc"][-1] > 80.0

    def test_fedamw_learns_p(self, setup):
        res = FedAMW(setup, lr=0.5, epoch=2, round=6, lambda_reg_if=True,
                     lambda_reg=5e-5, lr_p=0.01, seed=0, lr_mode="constant")
        assert res["test_acc"].shape == (6,)
        assert res["test_acc"][-1] > 80.0

    def test_seed_determinism(self, setup):
        a = FedAvg(setup, lr=0.5, epoch=1, round=3, seed=4, lr_mode="constant")
        b = FedAvg(setup, lr=0.5, epoch=1, round=3, seed=4, lr_mode="constant")
        np.testing.assert_allclose(a["test_acc"], b["test_acc"])

    def test_sequential_mode_differs(self, setup):
        par = FedAvg(setup, lr=0.5, epoch=1, round=2, seed=0, lr_mode="constant")
        seq = FedAvg(setup, lr=0.5, epoch=1, round=2, seed=0,
                     lr_mode="constant", sequential=True)
        assert not np.allclose(par["test_acc"], seq["test_acc"])


class TestOneShot:
    def test_centralized_upper_bound(self, setup):
        res = Centralized(setup, lr=0.5, epoch=8, batch_size=32, seed=0)
        assert res["test_acc"].ndim == 0
        assert float(res["test_acc"]) > 90.0

    def test_distributed(self, setup):
        res = Distributed(setup, lr=0.5, epoch=8, batch_size=32, seed=0)
        assert float(res["test_acc"]) > 70.0

    def test_fedamw_oneshot(self, setup):
        res = FedAMW_OneShot(setup, lr=0.5, epoch=8, round=5,
                             lambda_reg_if=True, lambda_reg=5e-4,
                             lr_p=0.05, seed=0)
        assert res["test_acc"].shape == (5,)
        assert res["test_acc"][-1] > 70.0
        # no p[0]^t aliasing: accuracy must not collapse over iterations
        assert res["test_acc"][-1] >= res["test_acc"][0] - 10.0


def test_bucketed_matches_unbucketed():
    # heavy skew: bucketing must change performance, not results
    ds = load_dataset("digits", num_partitions=8, alpha=0.1)
    kw = dict(kernel_type="linear", seed=100)
    plain = prepare_setup(ds, rng=np.random.RandomState(100), **kw)
    bucketed = prepare_setup(ds, rng=np.random.RandomState(100), buckets=3, **kw)
    assert len(bucketed.n_maxes) == 3
    # padded volume shrinks
    assert sum(c * m for c, m in zip(bucketed.bucket_counts, bucketed.n_maxes)) \
        < plain.num_clients * plain.n_maxes[0]
    run = dict(lr=0.5, epoch=2, round=5, seed=0, lr_mode="constant")
    a = FedAvg(plain, **run)
    b = FedAvg(bucketed, **run)
    # same dataset, same algorithm; differs only through shuffle RNG
    assert abs(a["test_acc"][-1] - b["test_acc"][-1]) < 4.0
    amw = FedAMW(bucketed, lr=0.5, epoch=2, round=4, lambda_reg_if=True,
                 lambda_reg=5e-5, lr_p=0.001, seed=0, lr_mode="constant")
    assert amw["test_acc"][-1] > 70.0


def test_rff_path_end_to_end():
    ds = load_dataset("digits", num_partitions=4, alpha=0.5)
    setup = prepare_setup(ds, D=256, kernel_par=1.0, seed=100,
                          rng=np.random.RandomState(100))
    res = FedAvg(setup, lr=2.0, epoch=2, round=12, seed=0, lr_mode="constant")
    assert res["test_acc"][-1] > 40.0
    assert res["test_acc"][-1] > res["test_acc"][0]


def test_registry_complete():
    assert set(ALGORITHMS) == {
        "Centralized", "Distributed", "FedAMW_OneShot",
        "FedAvg", "FedProx", "FedNova", "FedAMW",
    }


def test_regression_task():
    ds = load_dataset("synthetic_nonlinear", num_partitions=4, alpha=1.0)
    setup = prepare_setup(ds, D=64, kernel_par=0.1, seed=1,
                          rng=np.random.RandomState(1))
    res = FedAvg(setup, lr=0.05, epoch=1, round=3, seed=0, lr_mode="constant")
    assert res["test_loss"].shape == (3,)
    assert np.all(np.isfinite(res["test_loss"]))
    assert res["test_loss"][-1] < res["test_loss"][0]


def test_analyze_memory_reports_compiled_footprint():
    """analyze_memory=True returns the AOT compiler's device-memory
    report for the whole fused training program instead of running it
    (the axon runtime exposes no live memory_stats(); BASELINE.md)."""
    import numpy as np

    from fedamw_tpu.algorithms import FedAvg, prepare_setup
    from fedamw_tpu.data import load_dataset

    ds = load_dataset("digits", num_partitions=4, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=0,
                          rng=np.random.RandomState(0))
    ma = FedAvg(setup, lr=0.5, epoch=1, round=2, seed=0,
                lr_mode="constant", analyze_memory=True)
    assert ma["argument_size_in_bytes"] > 0
    # arguments must dominate: the resident feature matrix is the big
    # buffer, and temp must stay the same order (no accidental
    # per-round duplication of X inside the scan)
    X_bytes = setup.X.size * setup.X.dtype.itemsize
    assert ma["argument_size_in_bytes"] >= X_bytes
    assert ma["temp_size_in_bytes"] < 50 * X_bytes
