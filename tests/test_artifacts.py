"""The cold-start plane: AOT serving artifacts and their typed
compatibility contract (ISSUE 9).

Load-bearing guarantees:

- **Manifest round-trip**: ``export_ladder`` writes an
  ``ArtifactManifest`` whose on-disk JSON reloads field-for-field, and
  ``load_ladder`` on the same host validates it clean.
- **Typed incompatibility**: a manifest mismatched on ANY contract
  field — jaxlib version, platform, machine features, dtype, buckets
  (a rung file withheld), weight signature — raises
  :class:`ArtifactIncompatible` naming the field. NEVER a warning:
  this is the explicit replacement for the XLA:CPU AOT loader's
  machine-feature log line (MULTICHIP_r05).
- **from_artifact parity**: the artifact-loaded engine reproduces the
  compiled-path engine's logits bitwise on every rung, comes up with
  ``compile_count == 0``, keeps it at 0 across a mixed-size stream
  (``warmup()`` is a no-op), and chunks oversized batches identically.
- **Zero-recompile swap on the artifact path**: weights are
  exported-call arguments, so ``swap_weights``/``install_weights``/
  versioned dispatch work unchanged on an artifact-loaded engine with
  the compile count pinned at 0 — there is no jit cache to miss.
- **Watcher publishing** (satellite): ``CheckpointWatcher(
  artifact_dir=...)`` exports an artifact beside every published
  vNNNN checkpoint; an export failure counts in ``errors`` without
  unwinding the publish.
- **Retention** (ISSUE 10 satellite): ``prune_artifacts`` bounds the
  export directory like ``ModelRegistry.prune`` bounds the registry —
  oldest first, protected (live/candidate) versions never dropped —
  and ``CheckpointWatcher(artifact_keep=, artifact_protect=)`` runs it
  after each export, always keeping the ladder that just landed.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from fedamw_tpu.serving import (ArtifactIncompatible, ArtifactManifest,
                                CheckpointWatcher, ModelRegistry,
                                ServingEngine, export_ladder,
                                load_ladder)
from fedamw_tpu.serving.artifacts import (host_fingerprint,
                                          load_portable,
                                          validate_weights)
from fedamw_tpu.utils.checkpoint import save_checkpoint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
D, C = 12, 3
BUCKETS = (1, 4, 8)


def make_engine(rff=True, seed=1, buckets=BUCKETS):
    rng = np.random.RandomState(seed)
    kw = {}
    if rff:
        kw["rff"] = (rng.randn(6, D).astype(np.float32),
                     rng.randn(D).astype(np.float32))
    e = ServingEngine({"w": rng.randn(C, D).astype(np.float32)},
                      buckets=buckets, **kw)
    e.warmup()
    return e


def host_weights(engine):
    params = {k: np.asarray(v) for k, v in engine.params.items()}
    rff = engine.rff
    if rff is not None:
        rff = (np.asarray(rff[0]), np.asarray(rff[1]))
    return params, rff


def _tamper(art_dir, mutate):
    """Edit the manifest JSON in place through ``mutate(obj)``."""
    path = os.path.join(art_dir, "manifest.json")
    with open(path) as f:
        obj = json.load(f)
    mutate(obj)
    with open(path, "w") as f:
        json.dump(obj, f)


# -- manifest ----------------------------------------------------------

def test_manifest_round_trips_field_for_field(tmp_path):
    engine = make_engine()
    m = export_ladder(engine, str(tmp_path), model_version=7,
                      round_idx=42)
    m2 = ArtifactManifest.load(str(tmp_path))
    assert m2 == m  # frozen dataclass equality: every field survived
    assert m2.model_version == 7 and m2.round_idx == 42
    assert m2.buckets == list(BUCKETS)
    assert m2.host == host_fingerprint()
    assert sorted(m2.rungs) == [str(b) for b in sorted(BUCKETS)]
    for rec in m2.rungs.values():
        assert rec["bytes"] > 0
        assert os.path.exists(os.path.join(str(tmp_path),
                                           rec["stablehlo"]))
        assert os.path.exists(os.path.join(str(tmp_path),
                                           rec["executable"]))


def test_load_ladder_clean_on_exporting_host(tmp_path):
    engine = make_engine()
    export_ladder(engine, str(tmp_path))
    manifest, rungs = load_ladder(str(tmp_path))
    assert sorted(rungs) == sorted(BUCKETS)
    # the loaded rung IS the program: callable on (x, params, rff)
    params, rff, _ = engine._resolve(None)
    X = np.random.RandomState(0).randn(4, engine.input_dim).astype(
        np.float32)
    out = np.asarray(rungs[4](X, params, rff))
    np.testing.assert_array_equal(out, engine.predict(X))


@pytest.mark.parametrize("field, mutate", [
    ("jaxlib_version",
     lambda o: o["host"].__setitem__("jaxlib_version", "9.9.9")),
    ("jax_version",
     lambda o: o["host"].__setitem__("jax_version", "0.0.1")),
    ("platform",
     lambda o: o["host"].__setitem__("platform", "tpu")),
    ("device_kind",
     lambda o: o["host"].__setitem__("device_kind", "TPU v4")),
    ("machine",
     lambda o: o["host"].__setitem__("machine", "armv7l")),
    ("dtype", lambda o: o.__setitem__("dtype", "bfloat16")),
    ("n_devices", lambda o: o.__setitem__("n_devices", 8)),
    ("calling_convention_version",
     lambda o: o.__setitem__("calling_convention_version", 99999)),
])
def test_each_host_field_mismatch_raises_typed(tmp_path, field, mutate):
    """Each contract field individually: tampering it (and nothing
    else) must raise ArtifactIncompatible NAMING that field — never a
    warning, never a silent load."""
    engine = make_engine()
    export_ladder(engine, str(tmp_path))
    _tamper(str(tmp_path), mutate)
    params, rff = host_weights(engine)
    with pytest.raises(ArtifactIncompatible) as ei:
        ServingEngine.from_artifact(str(tmp_path), params=params,
                                    rff=rff)
    assert any(field == f for f, _, _ in ei.value.mismatches), \
        f"{field} not named in {ei.value.mismatches}"


def test_cpu_feature_mismatch_raises_typed(tmp_path):
    """The machine-features axis the XLA:CPU AOT loader only WARNS
    about: a fingerprint recorded by the exporter that differs from
    the running host is a typed refusal here."""
    engine = make_engine()
    export_ladder(engine, str(tmp_path))
    m = ArtifactManifest.load(str(tmp_path))
    if m.host["cpu_features"] is None:
        pytest.skip("host CPU features not fingerprintable here")
    _tamper(str(tmp_path),
            lambda o: o["host"].__setitem__("cpu_features", "deadbeef"))
    with pytest.raises(ArtifactIncompatible) as ei:
        load_ladder(str(tmp_path))
    assert any(f == "cpu_features" for f, _, _ in ei.value.mismatches)


def test_unknown_schema_major_refused_typed(tmp_path):
    """A future SERVE_ARTIFACT.v2 may rename or re-type fields, so an
    unknown major is refused BEFORE field parsing — typed, naming the
    schema — and a same-major manifest with a missing field surfaces
    as a typed malformed-manifest refusal, never a bare TypeError."""
    engine = make_engine()
    export_ladder(engine, str(tmp_path))
    _tamper(str(tmp_path),
            lambda o: o.__setitem__("schema", "SERVE_ARTIFACT.v2"))
    with pytest.raises(ArtifactIncompatible) as ei:
        ArtifactManifest.load(str(tmp_path))
    assert any(f == "schema" for f, _, _ in ei.value.mismatches)
    export_ladder(engine, str(tmp_path))  # restore
    _tamper(str(tmp_path), lambda o: o.pop("param_sig"))
    with pytest.raises(ArtifactIncompatible) as ei:
        load_ladder(str(tmp_path))
    assert any("malformed" in str(a)
               for _, a, _ in ei.value.mismatches)


def test_bucket_tamper_and_missing_rung_raise_typed(tmp_path):
    engine = make_engine()
    export_ladder(engine, str(tmp_path))
    # a manifest claiming a rung whose file is absent: typed, named
    _tamper(str(tmp_path), lambda o: o["rungs"].__setitem__(
        "64", {"stablehlo": "rung_64.stablehlo",
               "executable": "rung_64.xla", "bytes": 1}))
    with pytest.raises(ArtifactIncompatible) as ei:
        load_ladder(str(tmp_path))
    assert any("rung[64]" == f for f, _, _ in ei.value.mismatches)


def test_damaged_manifest_and_executable_raise_typed(tmp_path):
    engine = make_engine()
    export_ladder(engine, str(tmp_path))
    # truncate one executable: deserialization failure is typed too
    exe = os.path.join(str(tmp_path), "rung_4.xla")
    with open(exe, "wb") as f:
        f.write(b"\x80corrupt")
    with pytest.raises(ArtifactIncompatible):
        load_ladder(str(tmp_path))
    # and a directory with no manifest at all
    with pytest.raises(ArtifactIncompatible):
        load_ladder(str(tmp_path / "nowhere"))


def test_weight_signature_mismatch_raises_typed(tmp_path):
    engine = make_engine()
    export_ladder(engine, str(tmp_path))
    params, rff = host_weights(engine)
    rng = np.random.RandomState(9)
    # wrong leaf shape
    with pytest.raises(ArtifactIncompatible) as ei:
        ServingEngine.from_artifact(
            str(tmp_path),
            params={"w": rng.randn(C, D + 1).astype(np.float32)},
            rff=rff)
    assert any(f.startswith("param[") for f, _, _ in ei.value.mismatches)
    # wrong leaf dtype (the per-field dtype half of the contract)
    with pytest.raises(ArtifactIncompatible):
        ServingEngine.from_artifact(
            str(tmp_path),
            params={"w": params["w"].astype(np.float64)}, rff=rff)
    # rff-ness flipped: structurally different program
    with pytest.raises(ArtifactIncompatible) as ei:
        ServingEngine.from_artifact(str(tmp_path), params=params,
                                    rff=None)
    assert any(f == "rff_fused" for f, _, _ in ei.value.mismatches)
    # validate_weights alone names extra/missing keys
    with pytest.raises(ArtifactIncompatible) as ei:
        validate_weights(ArtifactManifest.load(str(tmp_path)),
                         {"w": params["w"], "b1": params["w"]}, rff)
    assert any(f == "param_keys" for f, _, _ in ei.value.mismatches)


# -- from_artifact parity + zero compiles ------------------------------

def test_from_artifact_parity_and_zero_compiles(tmp_path):
    engine = make_engine()
    export_ladder(engine, str(tmp_path))
    params, rff = host_weights(engine)
    art = ServingEngine.from_artifact(str(tmp_path), params=params,
                                      rff=rff)
    assert art.compile_count == 0
    assert art.warmup() == 0  # the no-op: nothing to compile
    assert art.compile_count == 0
    assert art.buckets == engine.buckets
    rng = np.random.RandomState(3)
    # every rung boundary + single rows + an oversized chunked batch
    for n in [1, 2, 4, 5, 8, 3, 1, 20]:
        X = rng.randn(n, engine.input_dim).astype(np.float32)
        np.testing.assert_array_equal(art.predict(X),
                                      engine.predict(X))
    assert art.compile_count == 0  # served everything, compiled nothing
    assert art.artifact_manifest is not None


def test_from_artifact_via_checkpoint_dir(tmp_path):
    """The production path: weights come from the checkpoint, programs
    from the artifact — export once, serve any round."""
    rng = np.random.RandomState(5)
    params = {"w": rng.randn(C, D).astype(np.float32)}
    rff = (rng.randn(6, D).astype(np.float32),
           rng.randn(D).astype(np.float32))
    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(ckpt, params, p=np.ones(2) / 2, round_idx=3,
                    rff=rff)
    engine = ServingEngine.load(ckpt, buckets=BUCKETS)
    engine.warmup()
    art_dir = str(tmp_path / "artifact")
    export_ladder(engine, art_dir, round_idx=3)
    art = ServingEngine.from_artifact(art_dir, checkpoint=ckpt)
    X = rng.randn(7, engine.input_dim).astype(np.float32)
    np.testing.assert_array_equal(art.predict(X), engine.predict(X))
    assert art.compile_count == 0
    with pytest.raises(ValueError, match="not both"):
        ServingEngine.from_artifact(art_dir, checkpoint=ckpt,
                                    params=params)
    with pytest.raises(ValueError, match="weight source"):
        ServingEngine.from_artifact(art_dir)


def test_artifact_engine_zero_recompile_swap(tmp_path):
    """The hot-swap invariant survives the artifact path: weights are
    exported-call arguments, so install/swap/versioned dispatch reuse
    the loaded executables with the compile count pinned at ZERO."""
    engine = make_engine()
    export_ladder(engine, str(tmp_path))
    params, rff = host_weights(engine)
    art = ServingEngine.from_artifact(str(tmp_path), params=params,
                                      rff=rff)
    rng = np.random.RandomState(7)
    X = rng.randn(5, art.input_dim).astype(np.float32)
    base = art.predict(X)
    # stage a candidate, dispatch it PINNED, then promote
    w2 = {"w": rng.randn(C, D).astype(np.float32)}
    art.install_weights(1, w2, rff=rff)
    cand = art.predict(X, version=1)
    assert not np.array_equal(cand, base)
    art.swap_weights(version=1)
    np.testing.assert_array_equal(art.predict(X), cand)
    # install-and-flip spelling too
    v = art.swap_weights({"w": -w2["w"]}, rff=rff)
    assert art.version == v
    assert art.compile_count == 0  # across ALL of it
    # swap-compat checks still guard the artifact engine
    with pytest.raises(ValueError, match="swap-incompatible"):
        art.swap_weights({"w": rng.randn(C, D + 2).astype(np.float32)},
                         rff=rff)


def test_portable_rung_round_trips_and_matches(tmp_path):
    """The jax.export half: the portable StableHLO rung deserializes
    and reproduces the engine bitwise (under one fresh jit compile) —
    the cross-host currency a new host class re-materializes from."""
    import jax

    engine = make_engine()
    export_ladder(engine, str(tmp_path))
    exported = load_portable(str(tmp_path), 4)
    assert jax.default_backend() in exported.platforms
    params, rff, _ = engine._resolve(None)
    X = np.random.RandomState(1).randn(4, engine.input_dim).astype(
        np.float32)
    out = np.asarray(jax.jit(exported.call)(X, params, rff))
    np.testing.assert_array_equal(out, engine.predict(X))
    with pytest.raises(ArtifactIncompatible):
        load_portable(str(tmp_path), 4096)  # no such rung


def test_export_refuses_mesh_engines(tmp_path):
    engine = make_engine()
    engine.mesh = object()  # an exported program bakes in devices
    with pytest.raises(ValueError, match="single-device"):
        export_ladder(engine, str(tmp_path))


def test_pre_mapped_engine_exports_without_rff(tmp_path):
    """The no-RFF layout (pre-mapped features) round-trips too — rff
    absence is structural and recorded as such."""
    engine = make_engine(rff=False)
    m = export_ladder(engine, str(tmp_path))
    assert m.rff_sig is None
    params, _ = host_weights(engine)
    art = ServingEngine.from_artifact(str(tmp_path), params=params)
    X = np.random.RandomState(2).randn(3, D).astype(np.float32)
    np.testing.assert_array_equal(art.predict(X), engine.predict(X))
    assert art.compile_count == 0


# -- watcher + CLI (satellites) ----------------------------------------

def _publish_ckpt(dirpath, seed=11):
    rng = np.random.RandomState(seed)
    save_checkpoint(str(dirpath), {"w": rng.randn(C, D).astype(
        np.float32)}, p=np.ones(2) / 2, round_idx=seed)


def test_watcher_publishes_artifacts_beside_checkpoints(tmp_path):
    watch = tmp_path / "ckpts"
    art_root = tmp_path / "artifacts"
    watch.mkdir()
    _publish_ckpt(watch / "v0001", seed=1)
    _publish_ckpt(watch / "v0002", seed=2)
    reg = ModelRegistry()
    w = CheckpointWatcher(reg, str(watch), artifact_dir=str(art_root),
                          artifact_buckets=(1, 4))
    assert w.poll_once() == [1, 2]
    assert [n for n, _ in w.artifacts] == ["v0001", "v0002"]
    assert w.errors == 0
    # each artifact cold-starts an engine against ITS checkpoint
    for name, art_dir in w.artifacts:
        eng = ServingEngine.from_artifact(
            art_dir, checkpoint=str(watch / name))
        assert eng.compile_count == 0
        assert eng.buckets == (1, 4)
        m = ArtifactManifest.load(art_dir)
        assert m.model_version == dict(w.published)[name]


def test_prune_artifacts_keeps_protected_and_newest(tmp_path):
    """Retention beside ModelRegistry.prune (ISSUE 10 satellite):
    oldest exported dirs drop down to ``keep``, protected versions
    (ints or dirnames) NEVER drop even when that leaves more than
    ``keep``, non-vNNNN entries are untouched, and a missing dir is a
    normal startup state."""
    from fedamw_tpu.serving import prune_artifacts

    art = tmp_path / "artifacts"
    for i in range(1, 7):
        (art / f"v{i:04d}").mkdir(parents=True)
    (art / "not_a_version").mkdir()
    removed = prune_artifacts(str(art), keep=3, protect=(2, "v0003"))
    assert removed == ["v0001", "v0004", "v0005"]  # oldest first
    assert sorted(os.listdir(art)) == [
        "not_a_version", "v0002", "v0003", "v0006"]
    # idempotent at the bound; keep larger than population is a no-op
    assert prune_artifacts(str(art), keep=3) == []
    assert prune_artifacts(str(tmp_path / "never_exported"), 1) == []
    with pytest.raises(ValueError, match="keep must be >= 0"):
        prune_artifacts(str(art), keep=-1)
    # a BARE-string protect names one dir, never iterates per char
    # (protected entries count toward keep, same as ModelRegistry)
    assert prune_artifacts(str(art), keep=1, protect="v0002") == \
        ["v0003", "v0006"]
    assert sorted(os.listdir(art)) == ["not_a_version", "v0002"]


def test_watcher_artifact_retention_never_drops_protected(tmp_path):
    """``CheckpointWatcher(artifact_keep=N)``: each successful export
    prunes the export dir to N, always keeping the just-exported
    ladder, plus whatever ``artifact_protect()`` pins (the
    live/candidate versions a rollout controller is serving)."""
    watch = tmp_path / "ckpts"
    art_root = tmp_path / "artifacts"
    watch.mkdir()
    for i in (1, 2, 3):
        _publish_ckpt(watch / f"v{i:04d}", seed=i)
    reg = ModelRegistry()
    protected: list = ["v0001"]  # pretend v0001 is still live
    w = CheckpointWatcher(reg, str(watch), artifact_dir=str(art_root),
                          artifact_buckets=(1,), artifact_keep=1,
                          artifact_protect=lambda: tuple(protected))
    assert w.poll_once() == [1, 2, 3]
    assert w.errors == 0
    # keep=1 would hold only the newest, but v0001 is pinned live
    assert sorted(os.listdir(art_root)) == ["v0001", "v0003"]
    assert w.artifacts_pruned == ["v0002"]
    # the pinned artifact still cold-starts its checkpoint
    eng = ServingEngine.from_artifact(str(art_root / "v0001"),
                                      checkpoint=str(watch / "v0001"))
    assert eng.compile_count == 0
    # a later poll with the pin RELEASED lets v0001 age out
    protected.clear()
    _publish_ckpt(watch / "v0004", seed=4)
    assert w.poll_once() == [4]
    assert sorted(os.listdir(art_root)) == ["v0004"]
    assert w.artifacts_pruned == ["v0002", "v0001", "v0003"]


def test_watcher_artifact_keep_validations(tmp_path):
    """keep=0 would delete the export that just landed — refused at
    construction; a raising protect callable counts in errors and
    never takes the publish or the export down."""
    watch = tmp_path / "ckpts"
    watch.mkdir()
    _publish_ckpt(watch / "v0001")
    with pytest.raises(ValueError, match="artifact_keep"):
        CheckpointWatcher(ModelRegistry(), str(watch),
                          artifact_dir=str(tmp_path / "a"),
                          artifact_keep=0)

    def broken_protect():
        raise RuntimeError("controller gone")

    w = CheckpointWatcher(ModelRegistry(), str(watch),
                          artifact_dir=str(tmp_path / "a"),
                          artifact_buckets=(1,), artifact_keep=1,
                          artifact_protect=broken_protect)
    assert w.poll_once() == [1]  # publish stands
    assert [n for n, _ in w.artifacts] == ["v0001"]  # export stands
    assert w.errors == 1 and w.artifacts_pruned == []


def test_watcher_artifact_failure_counts_not_fatal(tmp_path):
    """An unexportable checkpoint (here: artifact_dir is an unwritable
    path) must count in errors WITHOUT unwinding the publish."""
    watch = tmp_path / "ckpts"
    watch.mkdir()
    _publish_ckpt(watch / "v0001")
    blocked = tmp_path / "blocked"
    blocked.write_text("a file where a directory must go")
    reg = ModelRegistry()
    w = CheckpointWatcher(reg, str(watch),
                          artifact_dir=str(blocked / "sub"),
                          artifact_buckets=(1,))
    assert w.poll_once() == [1]  # the publish stands
    assert w.errors == 1 and w.artifacts == []


def test_export_artifacts_cli_exports_and_checks(tmp_path):
    ckpt = tmp_path / "ckpt"
    rng = np.random.RandomState(4)
    rff = (rng.randn(6, D).astype(np.float32),
           rng.randn(D).astype(np.float32))
    save_checkpoint(str(ckpt), {"w": rng.randn(C, D).astype(
        np.float32)}, p=np.ones(2) / 2, round_idx=5, rff=rff)
    out_dir = tmp_path / "artifact"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "export_artifacts.py"),
         str(ckpt), str(out_dir), "--buckets", "1,4", "--check"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    summary = json.loads(out.stdout.strip().splitlines()[-1])
    assert summary["rungs"] == 2 and summary["bytes"] > 0
    assert summary["round_idx"] == 5
    assert summary["check"]["compile_count"] == 0
    assert summary["check"]["parity"] == "bitwise"
    # and the artifact the CLI wrote serves in-process too
    eng = ServingEngine.from_artifact(str(out_dir),
                                      checkpoint=str(ckpt))
    assert eng.compile_count == 0
