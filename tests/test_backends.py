"""Torch backend, registry gate, and cross-backend statistical parity."""

import numpy as np
import pytest

from fedamw_tpu.data import load_dataset
from fedamw_tpu.registry import get_algorithm, get_backend


@pytest.fixture(scope="module")
def ds():
    return load_dataset("digits", num_partitions=4, alpha=0.5)


@pytest.fixture(scope="module")
def torch_setup(ds):
    return get_backend("torch").prepare_setup(
        ds, kernel_type="linear", seed=100, rng=np.random.RandomState(100)
    )


class TestRegistry:
    def test_both_backends_complete(self):
        names = {"Centralized", "Distributed", "FedAMW_OneShot",
                 "FedAvg", "FedProx", "FedNova", "FedAMW"}
        assert set(get_backend("jax").ALGORITHMS) == names
        assert set(get_backend("torch").ALGORITHMS) == names

    def test_unknown_backend(self):
        with pytest.raises(ValueError, match="unknown backend"):
            get_backend("tensorflow")

    def test_unknown_algorithm(self):
        with pytest.raises(ValueError, match="unknown algorithm"):
            get_algorithm("FedSGD", "jax")


class TestTorchBackend:
    def test_fedavg_learns(self, torch_setup):
        res = get_algorithm("FedAvg", "torch")(
            torch_setup, lr=0.5, epoch=2, round=6, seed=0, lr_mode="constant"
        )
        assert res["test_acc"].shape == (6,)
        assert res["test_acc"][-1] > 85.0

    def test_centralized(self, torch_setup):
        res = get_algorithm("Centralized", "torch")(
            torch_setup, lr=0.5, epoch=8, seed=0
        )
        assert float(res["test_acc"]) > 90.0

    def test_fedamw(self, torch_setup):
        res = get_algorithm("FedAMW", "torch")(
            torch_setup, lr=0.5, epoch=2, round=4, lambda_reg_if=True,
            lambda_reg=5e-5, lr_p=0.01, seed=0, lr_mode="constant"
        )
        assert res["test_acc"][-1] > 75.0

    def test_fednova_and_oneshot(self, torch_setup):
        nova = get_algorithm("FedNova", "torch")(
            torch_setup, lr=0.5, epoch=2, round=4, seed=0, lr_mode="constant"
        )
        assert nova["test_acc"][-1] > 75.0
        osr = get_algorithm("FedAMW_OneShot", "torch")(
            torch_setup, lr=0.5, epoch=8, round=3, lambda_reg_if=True,
            lambda_reg=5e-4, lr_p=0.05, seed=0
        )
        assert osr["test_acc"].shape == (3,)
        assert osr["test_acc"][-1] > 70.0

    def test_empty_client_inert(self, ds):
        import torch

        setup = get_backend("torch").prepare_setup(
            ds, kernel_type="linear", seed=1, rng=np.random.RandomState(1)
        )
        setup.parts.append(torch.zeros(0, dtype=torch.long))
        setup.sizes = np.append(setup.sizes, 0)
        res = get_algorithm("FedNova", "torch")(
            setup, lr=0.5, epoch=1, round=2, seed=0, lr_mode="constant"
        )
        assert np.all(np.isfinite(res["test_acc"]))

    def test_sequential_differs(self, torch_setup):
        par = get_algorithm("FedAvg", "torch")(
            torch_setup, lr=0.5, epoch=1, round=2, seed=0, lr_mode="constant")
        seq = get_algorithm("FedAvg", "torch")(
            torch_setup, lr=0.5, epoch=1, round=2, seed=0, lr_mode="constant",
            sequential=True)
        assert not np.allclose(par["test_acc"], seq["test_acc"])


class TestCrossBackendParity:
    """Statistical parity: same data, same semantics, different RNG
    streams -> final accuracy must agree within noise (SURVEY.md §2.3.4:
    bitwise torch/JAX RNG parity is impossible; the parity target is
    statistical)."""

    def test_fedavg_parity(self, ds):
        jb, tb = get_backend("jax"), get_backend("torch")
        kw = dict(kernel_type="linear", seed=100)
        js = jb.prepare_setup(ds, rng=np.random.RandomState(100), **kw)
        ts = tb.prepare_setup(ds, rng=np.random.RandomState(100), **kw)
        run = dict(lr=0.5, epoch=2, round=6, lr_mode="constant")
        ja = [jb.ALGORITHMS["FedAvg"](js, seed=s, **run)["test_acc"][-1]
              for s in (0, 1)]
        ta = [tb.ALGORITHMS["FedAvg"](ts, seed=s, **run)["test_acc"][-1]
              for s in (0, 1)]
        assert abs(np.mean(ja) - np.mean(ta)) < 4.0

    def test_centralized_parity(self, ds):
        jb, tb = get_backend("jax"), get_backend("torch")
        kw = dict(kernel_type="linear", seed=100)
        js = jb.prepare_setup(ds, rng=np.random.RandomState(100), **kw)
        ts = tb.prepare_setup(ds, rng=np.random.RandomState(100), **kw)
        ja = float(jb.ALGORITHMS["Centralized"](js, lr=0.5, epoch=10, seed=0)["test_acc"])
        ta = float(tb.ALGORITHMS["Centralized"](ts, lr=0.5, epoch=10, seed=0)["test_acc"])
        assert abs(ja - ta) < 4.0
