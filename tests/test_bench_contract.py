"""The driver contract of bench.py and __graft_entry__.py.

The round driver consumes bench.py's stdout (JSON lines, headline
metric LAST) and runs ``dryrun_multichip`` for the multi-chip
correctness artifact — both must keep working regardless of refactors,
and both must survive an unreachable accelerator (the remote-tunnel
outage that nulled the round-2 artifacts). Tiny shapes keep this
test-sized; the persistent compile cache reaches the subprocesses via
the JAX_COMPILATION_CACHE_DIR env var conftest exports, so reruns are
cheap.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_emits_driver_contract_json():
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        BENCH_CLIENTS="8", BENCH_ROUNDS="2", BENCH_D="64",
        BENCH_TORCH_ROUNDS="1", BENCH_AMW_TORCH_ROUNDS="1",
        BENCH_REF_ROUNDS="1", BENCH_AMW_REF_ROUNDS="1",
    )
    # ambient knobs that would flip the asserted defended-leg /
    # reputation-leg / trace-leg shape (a developer shell may export
    # them)
    for k in ("BENCH_NO_DEFENDED", "BENCH_DEFENDED",
              "BENCH_DEFENDED_AGG", "BENCH_DEFENDED_FAULTS",
              "BENCH_NO_REPUTATION", "BENCH_REPUTATION_AGG",
              "BENCH_REPUTATION_FAULTS", "BENCH_NO_TRACE",
              "BENCH_TRACE_OVERHEAD"):
        env.pop(k, None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=570,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [json.loads(ln) for ln in out.stdout.splitlines() if ln]
    assert len(lines) == 6
    # headline LAST (the driver records the final line), and its
    # kill-safety duplicate printed BEFORE the defended leg's runs
    assert lines[-1]["metric"] == "client_updates_per_sec"
    assert lines[1] == lines[-1]
    assert lines[0]["metric"] == "fedamw_client_updates_per_sec"
    for rec in (lines[0], lines[-1]):
        assert rec["unit"] == "client-updates/s"
        assert rec["value"] > 0
        assert rec["vs_baseline"] > 0
        assert rec["platform"] == "cpu"
        assert rec["baseline_arm"] in ("reference-loop", "torch-backend")
        # "xla", a pallas layout, or a FedAMW "kernel+psolver" pair label
        assert rec["impl"] == "xla" or rec["impl"].startswith("pallas")
    # the headline carries the phase-attributed wall-clock of the
    # winning leg (ISSUE 5 bench contract)
    phases = lines[-1]["phases"]
    for k in ("build_s", "compile_warmup_s", "timed_run_s"):
        assert phases[k] > 0
    # the defended-round leg (ISSUE 3): fault plane + defense overhead
    # vs the faulted plain mean, on the same plan
    dfd = lines[2]
    assert dfd["metric"] == "defended_round_overhead"
    assert dfd["value"] > 0
    assert dfd["unit"] == "x-vs-faulted-mean"
    assert dfd["defended_updates_per_sec"] > 0
    assert dfd["faulted_mean_updates_per_sec"] > 0
    assert "mkrum" in dfd["robust_agg"]
    assert dfd["platform"] == "cpu"
    # the reputation-round leg (ISSUE 4): the stateful cross-round
    # defense (rep EWMA + auto-tuned z threshold) vs the same faulted
    # plain mean
    rep = lines[3]
    assert rep["metric"] == "reputation_round_overhead"
    assert rep["value"] > 0
    assert rep["unit"] == "x-vs-faulted-mean"
    assert rep["reputation_updates_per_sec"] > 0
    assert rep["faulted_mean_updates_per_sec"] > 0
    assert "rep" in rep["robust_agg"]
    assert rep["platform"] == "cpu"
    # the trace-plane cost leg (ISSUE 5): tracing on vs off, on the
    # same compiled program
    trc = lines[4]
    assert trc["metric"] == "trace_overhead"
    assert trc["value"] > 0
    assert trc["unit"] == "x-vs-untraced"
    assert trc["traced_updates_per_sec"] > 0
    assert trc["untraced_updates_per_sec"] > 0
    # one train_scan span + one round record per round, per traced run
    # (warmup + timed = 2 runs of BENCH_ROUNDS=2 -> 2 * (1 + 2))
    assert trc["spans_recorded"] == 6
    assert trc["platform"] == "cpu"
    # driver-captured roofline fields (PERFORMANCE.md § MFU)
    assert lines[-1]["flops_per_update"] > 0
    assert lines[-1]["achieved_gflops"] > 0


def test_bench_cpu_fallback_contract():
    """The unattended fallback path (what the driver captures with the
    tunnel down): headline printed FIRST for kill-safety AND LAST for
    the parse contract, reference/torch FedAMW arms skipped, a JAX-only
    FedAMW datapoint with a warm cache — and the reputation leg, whose
    contract promises the metric on BOTH the full and fallback paths.
    BENCH_FORCE_FALLBACK skips the 180 s probe, which is also what
    makes this path testable."""
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu", BENCH_FORCE_FALLBACK="1",
        BENCH_FALLBACK_AMW="1",
        BENCH_CLIENTS="8", BENCH_D="64",
        BENCH_TORCH_ROUNDS="1",
    )
    # ambient knobs that would flip the asserted code path (documented
    # in BASELINE.md for real runs; a developer shell may export them)
    for k in ("BENCH_ROUNDS", "BENCH_CPU_FALLBACK_FULL",
              "BENCH_REF_ROUNDS", "BENCH_NO_PALLAS",
              "BENCH_NO_REFERENCE", "BENCH_DEFENDED",
              "BENCH_NO_DEFENDED", "BENCH_NO_REPUTATION",
              "BENCH_REPUTATION_AGG", "BENCH_REPUTATION_FAULTS",
              "BENCH_NO_TRACE", "BENCH_TRACE_OVERHEAD"):
        env.pop(k, None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=570,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "reference arm skipped in CPU fallback" in out.stderr
    # the defended and trace-overhead legs defer to headline
    # kill-safety in fallback (both opt back in via env)
    assert "defended leg skipped in CPU fallback" in out.stderr
    assert "trace-overhead leg skipped in CPU fallback" in out.stderr
    lines = [json.loads(ln) for ln in out.stdout.splitlines() if ln]
    assert len(lines) == 4
    assert lines[0] == lines[-1]  # kill-safety duplicate of the headline
    assert lines[-1]["metric"] == "client_updates_per_sec"
    assert lines[-1]["platform"] == "cpu"
    assert lines[-1]["baseline_arm"] == "torch-backend"
    assert lines[1]["metric"] == "fedamw_client_updates_per_sec"
    assert "vs_baseline" not in lines[1]  # no baseline arm in fallback
    # the reputation leg runs in fallback too (both-paths contract)
    assert lines[2]["metric"] == "reputation_round_overhead"
    assert lines[2]["value"] > 0
    assert "rep" in lines[2]["robust_agg"]


def test_bench_fallback_defended_headline_kill_safety():
    """BENCH_DEFENDED=1 in the CPU fallback with the FedAMW leg
    disabled: the headline must print BEFORE the defended leg's four
    training runs (same kill-safety duplicate as the FedAMW leg), so a
    driver-side wall-clock kill mid-leg never leaves zero JSON lines
    (the BENCH_r02-null failure mode)."""
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu", BENCH_FORCE_FALLBACK="1",
        BENCH_FALLBACK_AMW="0", BENCH_DEFENDED="1",
        BENCH_CLIENTS="8", BENCH_D="64",
        BENCH_TORCH_ROUNDS="1",
    )
    for k in ("BENCH_ROUNDS", "BENCH_CPU_FALLBACK_FULL",
              "BENCH_REF_ROUNDS", "BENCH_NO_DEFENDED",
              "BENCH_DEFENDED_AGG", "BENCH_DEFENDED_FAULTS",
              "BENCH_NO_REPUTATION", "BENCH_REPUTATION_AGG",
              "BENCH_REPUTATION_FAULTS", "BENCH_NO_TRACE",
              "BENCH_TRACE_OVERHEAD"):
        env.pop(k, None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=570,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [json.loads(ln) for ln in out.stdout.splitlines() if ln]
    assert len(lines) == 4
    assert lines[0] == lines[-1]  # kill-safety duplicate
    assert lines[0]["metric"] == "client_updates_per_sec"
    assert lines[1]["metric"] == "defended_round_overhead"
    assert lines[2]["metric"] == "reputation_round_overhead"


def test_bench_strict_tpu_refuses_cpu_backend():
    """BENCH_STRICT_TPU certifies TPU evidence: with the resolved
    backend CPU (a leaked JAX_PLATFORMS=cpu — honored by bench.py's
    own config update), strict mode must abort BEFORE measuring
    anything, or the window harvest could mark a CPU capture green
    (tpu_window.sh relies on this; the probe alone cannot see an
    in-process platform downgrade)."""
    for leak in ({"JAX_PLATFORMS": "cpu"}, {"BENCH_FORCE_FALLBACK": "1"}):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.update(BENCH_STRICT_TPU="1", **leak)
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=300,
        )
        assert out.returncode == 1, leak
        assert "BENCH_STRICT_TPU set but the resolved backend" in out.stderr
        assert not out.stdout.strip()  # no metric lines to mis-harvest


def test_bench_sweep_only_contract():
    """BENCH_SWEEP_ONLY (tpu_window.sh step 5/5) must emit exactly the
    env-gated sweep JSON lines — bucket and unroll — and skip every
    other leg, so the window's sweep step never re-times what earlier
    steps harvested."""
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu", BENCH_NO_PROBE="1", BENCH_SWEEP_ONLY="1",
        BENCH_SWEEP_BUCKETS="4,8", BENCH_SWEEP_UNROLL="1,8",
        BENCH_CLIENTS="8", BENCH_D="64", BENCH_ROUNDS="2",
    )
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=570,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [json.loads(ln) for ln in out.stdout.splitlines() if ln]
    assert len(lines) == 2
    rec, urec = lines
    assert rec["metric"] == "bucket_sweep_updates_per_sec"
    assert set(rec["buckets"]) == {"4", "8"}
    assert rec["value"] == max(rec["buckets"].values())
    assert rec["platform"] == "cpu"
    assert urec["metric"] == "unroll_sweep_updates_per_sec"
    assert set(urec["unrolls"]) == {"1", "8"}
    assert urec["value"] == max(urec["unrolls"].values())
    assert urec["default_unroll"] == 8
    # no other legs ran (their stderr banners are absent)
    assert "torch-cpu" not in out.stderr
    assert "reference-loop" not in out.stderr


def test_serve_bench_rollout_leg_respects_swap_knob(tmp_path):
    """The serve driver's ISSUE 6 rollout leg (the serve-side sibling
    of the env-gated bench legs above): SERVE_SWAPS sizes the hot-swap
    series, the serve_rollout JSON line precedes the headline (which
    stays LAST for the driver's final-line parse), and the swap
    zero-recompile pin holds at a non-default swap count. The full
    rollout-leg contract is pinned in test_serve_contract.py; this
    pins the driver-facing knob."""
    out_path = str(tmp_path / "BENCH_SERVE_knob.json")
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", SERVE_OUT=out_path,
               SERVE_BUCKETS="1,8,32", SERVE_D="64", SERVE_N="1024",
               SERVE_TRAIN_ROUNDS="1", SERVE_ITERS="3",
               SERVE_REQUESTS="40", SERVE_SWAPS="5",
               SERVE_TRACE_REPS="1")
    env.pop("BENCH_STRICT_TPU", None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "serve_bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=570,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [json.loads(ln) for ln in out.stdout.splitlines() if ln]
    assert lines[-1]["metric"] == "serve_requests_per_sec"
    roll = [l for l in lines if l["metric"] == "serve_rollout"]
    assert len(roll) == 1
    assert roll[0]["swaps"] == 5  # 4 bare + 1 shadow canary
    assert roll[0]["recompiles_during_swaps"] == 0
    assert roll[0]["canary"] == "promoted"
    assert lines[-1]["recompiles_after_warmup"] == 0
    with open(out_path) as f:
        art = json.load(f)
    assert art["rollout"]["swaps"] == 5
    assert art["rollout"]["final_version"] == 5


def test_dryrun_multichip_succeeds_without_backend_query():
    """`python -c "import __graft_entry__ as g; g.dryrun_multichip(4)"`
    completes via the respawn-first path (no respawn-skip vars set).
    What this pins is the mechanics — the parent must reach the respawn
    without needing a JAX backend query, and the child must pin the
    virtual CPU mesh. The hang scenario itself (parent backend query
    blocking on this container's force-registered remote plugin with
    the tunnel down — MULTICHIP_r02 rc=124) only manifests under that
    sitecustomize, so it is covered by construction, not simulated
    here."""
    env = dict(os.environ)
    env.pop("_GRAFT_DRYRUN_RESPAWNED", None)
    env.pop("GRAFT_DRYRUN_REAL", None)
    out = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(4)"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=570,
    )
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    assert "dryrun_multichip(4): OK" in out.stdout


def test_bench_jax_best_leg_policy(monkeypatch):
    """The in-process contract of bench_jax_best: the baseline leg must
    run with both impl env vars pinned to xla (pinning keeps the
    accuracy cross-check valid under any 'auto' default), the FedAMW
    candidate list must include the mixed xla+pallas pair (the isolated
    p-solver measurement the round-5 auto-revert is waiting on), the
    fastest accuracy-matching pair must win, and the caller's env must
    be restored."""
    import bench as bench_mod

    calls = []
    speed = {
        ("xla", "xla"): 100.0,
        ("pallas", "pallas"): 140.0,
        ("xla", "pallas"): 160.0,
        ("pallas_col", "pallas_nt"): 90.0,
    }

    def fake_bench_jax(ds, D, rounds, algorithm="FedAvg", **kw):
        pair = (os.environ["FEDAMW_KERNEL"], os.environ["FEDAMW_PSOLVER"])
        calls.append(pair)
        return speed[pair], 97.5, 1.0

    monkeypatch.setattr(bench_mod, "bench_jax", fake_bench_jax)
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("FEDAMW_KERNEL", "caller-sentinel")
    monkeypatch.delenv("FEDAMW_PSOLVER", raising=False)
    monkeypatch.delenv("BENCH_NO_PALLAS", raising=False)

    ups, acc, dt, impl = bench_mod.bench_jax_best(
        None, 64, 2, algorithm="FedAMW")
    assert calls[0] == ("xla", "xla")  # pinned baseline leg
    assert ("xla", "pallas") in calls  # isolated p-solver leg measured
    assert impl == "xla+pallas" and ups == 160.0
    # caller env restored exactly
    assert os.environ["FEDAMW_KERNEL"] == "caller-sentinel"
    assert "FEDAMW_PSOLVER" not in os.environ

    # accuracy-mismatched candidates are discarded even when faster
    calls.clear()

    def fake_bad_acc(ds, D, rounds, algorithm="FedAvg", **kw):
        pair = (os.environ["FEDAMW_KERNEL"], os.environ["FEDAMW_PSOLVER"])
        calls.append(pair)
        if pair == ("xla", "xla"):
            return 100.0, 97.5, 1.0
        return 500.0, 42.0, 1.0

    monkeypatch.setattr(bench_mod, "bench_jax", fake_bad_acc)
    ups, acc, dt, impl = bench_mod.bench_jax_best(
        None, 64, 2, algorithm="FedAMW")
    assert impl == "xla" and ups == 100.0

    # FedAvg: p-solver never runs -> only the diagonal epoch-kernel
    # candidates, no mixed pairs, label is the kernel name alone
    calls.clear()
    monkeypatch.setattr(bench_mod, "bench_jax", fake_bench_jax)
    ups, acc, dt, impl = bench_mod.bench_jax_best(
        None, 64, 2, algorithm="FedAvg")
    assert calls[0] == ("xla", "xla")
    assert ("xla", "pallas") not in calls
    assert impl == "pallas" and ups == 140.0
