"""bfloat16 feature storage: accuracy within tolerance of float32.

``prepare_setup(feature_dtype=jnp.bfloat16)`` halves the feature
matrices' HBM footprint and gather traffic; compute stays float32.
These pin that the option (a) actually stores bf16, (b) lands within a
small accuracy band of the f32 run, and (c) composes with bucketing.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from fedamw_tpu.algorithms import FedAMW, FedAvg, prepare_setup
from fedamw_tpu.data import load_dataset


@pytest.fixture(scope="module")
def ds():
    return load_dataset("digits", num_partitions=8, alpha=0.5)


def _setup(ds, dtype, **kw):
    # raw features (kernel_type="linear") learn fast on digits, making
    # the "it actually learned" guard meaningful in few rounds
    return prepare_setup(ds, kernel_type="linear", seed=100,
                         rng=np.random.RandomState(100),
                         feature_dtype=dtype, **kw)


def test_bf16_storage_dtypes(ds):
    s = _setup(ds, jnp.bfloat16)
    assert s.X.dtype == jnp.bfloat16
    assert s.X_test.dtype == jnp.bfloat16
    assert s.X_val.dtype == jnp.bfloat16
    assert s.y.dtype != jnp.bfloat16


def test_bf16_fedavg_accuracy_close_to_f32(ds):
    kw = dict(lr=0.5, epoch=1, round=5, seed=0, lr_mode="constant")
    acc32 = FedAvg(_setup(ds, None), **kw)["test_acc"][-1]
    acc16 = FedAvg(_setup(ds, jnp.bfloat16), **kw)["test_acc"][-1]
    assert abs(float(acc32) - float(acc16)) < 3.0
    assert float(acc16) > 50.0  # it actually learned


def test_bf16_fedamw_bucketed(ds):
    s = _setup(ds, jnp.bfloat16, buckets=2)
    res = FedAMW(s, lr=0.5, epoch=1, round=2, lambda_reg=1e-4,
                 lr_p=1e-3, seed=0, lr_mode="constant")
    assert np.all(np.isfinite(res["test_loss"]))
