"""Replay every committed campaign regression (ISSUE 16).

``campaigns/regressions/*.json`` holds minimal repros shrunk from
scenario-fuzzing campaign failures (``tools/run_campaign.py``). Each
file records the scenario spec that USED to violate the invariant
codes in ``fixed_codes`` — committing one asserts the bug is fixed,
and this collector replays them all forever: a repro that fails again
here is a regression of the original fix, with the shrunk spec as the
ready-made reproduction command.

Promotion workflow (README "Scenario campaigns"): a campaign failure
is auto-shrunk, the minimal repro lands in ``campaigns/regressions/``,
the bug gets fixed, the repro file gets committed with the fix, and
tier-1 replays it from then on. Files are tiny (one spec string + the
shrink trace), so the whole directory stays tier-1.
"""

import glob
import os

import pytest

from fedamw_tpu.scenario import PropertyOracle, load_regression

pytestmark = pytest.mark.scenario

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REG_DIR = os.path.join(REPO, "campaigns", "regressions")
REG_FILES = sorted(glob.glob(os.path.join(REG_DIR, "*.json")))


def test_regression_directory_is_nonempty():
    # the collector below parametrizes over files; an accidentally
    # emptied directory would silently pass, so pin that at least the
    # announce-gap repro (the PR 16 founding regression) is present
    assert REG_FILES, f"no committed regressions under {REG_DIR}"


@pytest.mark.parametrize(
    "path", REG_FILES, ids=[os.path.basename(p) for p in REG_FILES])
def test_committed_regression_replays_clean(path):
    rec = load_regression(path)
    verdict = PropertyOracle().run(rec["spec"])
    assert verdict.ok, (
        f"{os.path.basename(path)} regressed: the shrunk repro "
        f"{rec['spec']!r} violates {verdict.codes()} again "
        f"(originally fixed: {rec['fixed_codes']}) — "
        f"{verdict.violations}")
