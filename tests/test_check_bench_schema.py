"""tools/check_bench_schema.py — the artifact-contract gate (ISSUE 5).

Tier-1 on purpose: the round driver parses the committed BENCH_* /
BENCH_SERVE_* / MULTICHIP_* artifacts, and a malformed one must fail
the suite, not surface as a null harvest rows later. Also pins the
negative cases (the tool must actually REJECT contract violations —
a validator that accepts everything is worse than none) and the
no-match guard.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import check_bench_schema as cbs  # noqa: E402


def test_every_committed_artifact_validates():
    rc = cbs.main(["--root", REPO, "--expect-some"])
    assert rc == 0


def _write(tmp_path, name, obj):
    path = tmp_path / name
    path.write_text(json.dumps(obj))
    return str(path)


def test_rejects_headline_not_last(tmp_path):
    good_head = {"metric": "client_updates_per_sec", "value": 1.0,
                 "unit": "client-updates/s", "platform": "cpu"}
    tail = (json.dumps(good_head) + "\n"
            + json.dumps({"metric": "some_other_leg"}) + "\n")
    p = _write(tmp_path, "BENCH_r09.json",
               {"n": 9, "rc": 0, "tail": tail, "parsed": good_head})
    errs = cbs.validate_file(p)
    assert any("headline-metric-last" in e for e in errs)


def test_rejects_missing_platform_on_modern_capture(tmp_path):
    head = {"metric": "client_updates_per_sec", "value": 2.0,
            "unit": "client-updates/s"}
    p = _write(tmp_path, "BENCH_r09.json",
               {"n": 9, "rc": 0, "tail": json.dumps(head),
                "parsed": head})
    errs = cbs.validate_file(p)
    assert any("platform" in e for e in errs)
    # capture 1 predates the label and is grandfathered by number
    p1 = _write(tmp_path, "BENCH_r01x.json",
                {"n": 1, "rc": 0, "tail": json.dumps(head),
                 "parsed": head})
    assert cbs.validate_file(p1) == []


def test_rejects_green_rc_with_null_parsed_and_allows_red(tmp_path):
    p = _write(tmp_path, "BENCH_r09.json",
               {"n": 9, "rc": 0, "tail": "", "parsed": None})
    assert cbs.validate_file(p)
    p2 = _write(tmp_path, "BENCH_r10.json",
                {"n": 10, "rc": 1, "tail": "# aborted", "parsed": None})
    assert cbs.validate_file(p2) == []  # the honest aborted shape (r02)


def test_rejects_serve_artifact_drift(tmp_path):
    art = {"metric": "serve_bench", "schema": "BENCH_SERVE.v1",
           "platform": "cpu",
           "bucket_latency": {"1": {"p50_ms": 0.1, "p99_ms": 0.2}},
           "mixed_stream": {"requests": 10},
           "recompiles_after_warmup": 0}
    p = _write(tmp_path, "BENCH_SERVE_r09.json", art)
    assert cbs.validate_file(p) == []
    for key, bad in (("schema", "BENCH.v1"), ("platform", ""),
                     ("bucket_latency", {}),
                     ("mixed_stream", {"requests": 0}),
                     ("recompiles_after_warmup", None)):
        broken = dict(art, **{key: bad})
        p = _write(tmp_path, "BENCH_SERVE_r09.json", broken)
        assert cbs.validate_file(p), f"accepted broken {key}"


GOOD_ROLLOUT = {"mode": "shadow", "swaps": 3, "swap_p50_ms": 1.2,
                "swap_p95_ms": 2.0, "inflight_p95_ms": 9.5,
                "canary": "promoted", "rollback_drill": "rolled_back",
                "recompiles_during_swaps": 0, "final_version": 3,
                "staleness_rounds": 0}


def test_serve_v2_requires_rollout_section(tmp_path):
    """From schema v2 on, the continuous-deployment leg's 'rollout'
    section is contract; v1 artifacts (r01) are grandfathered by
    schema version — strict for everything that could carry it."""
    art = {"metric": "serve_bench", "schema": "BENCH_SERVE.v2",
           "platform": "cpu",
           "bucket_latency": {"1": {"p50_ms": 0.1, "p99_ms": 0.2}},
           "mixed_stream": {"requests": 10},
           "recompiles_after_warmup": 0}
    p = _write(tmp_path, "BENCH_SERVE_r09.json", art)
    errs = cbs.validate_file(p)
    assert any("rollout" in e for e in errs)
    good = dict(art, rollout=dict(GOOD_ROLLOUT))
    assert cbs.validate_file(
        _write(tmp_path, "BENCH_SERVE_r09.json", good)) == []
    # v1 stays valid without the section (the committed r01 shape)
    v1 = dict(art, schema="BENCH_SERVE.v1")
    assert cbs.validate_file(
        _write(tmp_path, "BENCH_SERVE_r09.json", v1)) == []
    # an unparseable version suffix must NOT skip the v2 rules silently
    weird = dict(art, schema="BENCH_SERVE.v2-rc1")
    errs = cbs.validate_file(
        _write(tmp_path, "BENCH_SERVE_r09.json", weird))
    assert any("unparseable schema version" in e for e in errs)


def test_serve_v2_rejects_rollout_drift(tmp_path):
    base = {"metric": "serve_bench", "schema": "BENCH_SERVE.v2",
            "platform": "cpu",
            "bucket_latency": {"1": {"p50_ms": 0.1, "p99_ms": 0.2}},
            "mixed_stream": {"requests": 10},
            "recompiles_after_warmup": 0}
    for key, bad in (("swaps", 0), ("swap_p50_ms", None),
                     ("inflight_p95_ms", "fast"),
                     ("recompiles_during_swaps", None),
                     ("canary", ""), ("rollback_drill", "FAILED"),
                     ("staleness_rounds", None)):
        rollout = dict(GOOD_ROLLOUT, **{key: bad})
        p = _write(tmp_path, "BENCH_SERVE_r09.json",
                   dict(base, rollout=rollout))
        assert cbs.validate_file(p), f"accepted broken rollout {key}"
    # a canary that FAILED must never land green in a committed file
    p = _write(tmp_path, "BENCH_SERVE_r09.json",
               dict(base, rollout=dict(GOOD_ROLLOUT, canary="FAILED")))
    assert any("FAILED" in e for e in cbs.validate_file(p))


GOOD_CHAOS = {"replicas": 3, "requests": 120, "resolved_ok": 118,
              "deadline_exceeded": 2, "lost": 0, "kills_planned": 2,
              "kills_observed": 2, "requeues": 2, "hedges": 1,
              "hedge_wins": 1, "p95_ms_clean": 3.1, "p95_ms_chaos": 3.6,
              "recompiles_during_chaos": 0, "spans_exactly_once": True}


def _serve_art(schema="BENCH_SERVE.v3", **extra):
    art = {"metric": "serve_bench", "schema": schema,
           "platform": "cpu",
           "bucket_latency": {"1": {"p50_ms": 0.1, "p99_ms": 0.2}},
           "mixed_stream": {"requests": 10},
           "recompiles_after_warmup": 0,
           "rollout": dict(GOOD_ROLLOUT)}
    art.update(extra)
    return art


def test_serve_v3_requires_chaos_section(tmp_path):
    """From schema v3 on, the replica-fleet failover leg's 'chaos'
    section is contract; v2 artifacts predate it and stay valid."""
    p = _write(tmp_path, "BENCH_SERVE_r09.json", _serve_art())
    errs = cbs.validate_file(p)
    assert any("'chaos' section" in e for e in errs)
    good = _serve_art(chaos=dict(GOOD_CHAOS))
    assert cbs.validate_file(
        _write(tmp_path, "BENCH_SERVE_r09.json", good)) == []
    # v2 stays valid without the section (pre-ISSUE-7 shape)
    v2 = _serve_art(schema="BENCH_SERVE.v2")
    assert cbs.validate_file(
        _write(tmp_path, "BENCH_SERVE_r09.json", v2)) == []


def test_serve_v3_rejects_chaos_drift(tmp_path):
    for key, bad in (("kills_observed", None), ("requeues", -1),
                     ("hedge_wins", None), ("requests", 0),
                     ("p95_ms_clean", None), ("p95_ms_chaos", "slow"),
                     ("spans_exactly_once", False)):
        chaos = dict(GOOD_CHAOS, **{key: bad})
        p = _write(tmp_path, "BENCH_SERVE_r09.json",
                   _serve_art(chaos=chaos))
        assert cbs.validate_file(p), f"accepted broken chaos {key}"
    # the abort-grade pins, re-checked at the gate: lost requests and
    # failover recompiles must never land in a committed artifact
    for key, bad, needle in (("lost", 3, "lost"),
                             ("recompiles_during_chaos", 1,
                              "never recompile"),
                             ("kills_observed", 0, "proves nothing")):
        p = _write(tmp_path, "BENCH_SERVE_r09.json",
                   _serve_art(chaos=dict(GOOD_CHAOS, **{key: bad})))
        assert any(needle in e for e in cbs.validate_file(p))


GOOD_COLD = {"compile_warmup_s": 2.4, "compile_count_compiled": 4,
             "artifact_export_s": 1.1, "artifact_load_s": 0.012,
             "artifact_compile_count": 0, "speedup_x": 200.0,
             "rungs": 4, "artifact_bytes": 120000,
             "parity": {"match": True}}

#: v4 chaos carries the mid-stream-swap pins on top of the v3 shape
GOOD_CHAOS_V4 = dict(GOOD_CHAOS, midstream_swap_version=4,
                     post_swap_requests=60, post_swap_version_ok=True,
                     hedges_cancelled=0)


def _serve_art_v4(**extra):
    art = _serve_art(schema="BENCH_SERVE.v4",
                     chaos=dict(GOOD_CHAOS_V4),
                     cold_start=dict(GOOD_COLD))
    art.update(extra)
    return art


def test_serve_v4_requires_cold_start_section(tmp_path):
    """From schema v4 on, the AOT-artifact leg's 'cold_start' section
    is contract; v3 artifacts predate it and stay valid."""
    assert cbs.validate_file(
        _write(tmp_path, "BENCH_SERVE_r09.json", _serve_art_v4())) == []
    art = _serve_art_v4()
    del art["cold_start"]
    errs = cbs.validate_file(_write(tmp_path, "BENCH_SERVE_r09.json",
                                    art))
    assert any("'cold_start' section" in e for e in errs)
    # v3 stays valid without the section (pre-ISSUE-9 shape)
    v3 = _serve_art(schema="BENCH_SERVE.v3", chaos=dict(GOOD_CHAOS))
    assert cbs.validate_file(
        _write(tmp_path, "BENCH_SERVE_r09.json", v3)) == []


def test_serve_v4_rejects_cold_start_drift(tmp_path):
    # both start modes must be present and timed
    for key, bad in (("compile_warmup_s", None),
                     ("compile_warmup_s", 0),
                     ("artifact_load_s", None),
                     ("artifact_load_s", 0),
                     ("artifact_export_s", "fast"),
                     ("rungs", 0)):
        cold = dict(GOOD_COLD, **{key: bad})
        p = _write(tmp_path, "BENCH_SERVE_r09.json",
                   _serve_art_v4(cold_start=cold))
        assert cbs.validate_file(p), f"accepted broken cold {key}={bad}"
    # the abort-grade pin, re-checked at the gate: a compiled start
    # wearing the AOT label must never land green
    p = _write(tmp_path, "BENCH_SERVE_r09.json", _serve_art_v4(
        cold_start=dict(GOOD_COLD, artifact_compile_count=3)))
    assert any("compile NOTHING" in e for e in cbs.validate_file(p))


def test_serve_v4_rejects_midstream_swap_drift(tmp_path):
    """The chaos-under-rollout pins ride the v4 chaos section: the
    swap must actually precede some requests, and every post-swap span
    must have carried the new version."""
    p = _write(tmp_path, "BENCH_SERVE_r09.json", _serve_art_v4(
        chaos=dict(GOOD_CHAOS_V4, post_swap_requests=0)))
    assert any("post_swap_requests" in e for e in cbs.validate_file(p))
    p = _write(tmp_path, "BENCH_SERVE_r09.json", _serve_art_v4(
        chaos=dict(GOOD_CHAOS_V4, post_swap_version_ok=False)))
    assert any("post_swap_version_ok" in e
               for e in cbs.validate_file(p))
    # v3 artifacts never carried the swap fields: still valid there
    v3 = _serve_art(schema="BENCH_SERVE.v3", chaos=dict(GOOD_CHAOS))
    assert cbs.validate_file(
        _write(tmp_path, "BENCH_SERVE_r09.json", v3)) == []


GOOD_TELEMETRY = {
    "overhead_x": 0.99, "reps": 5, "requests_per_leg": 200,
    "plane_off_req_per_s": 5000.0, "plane_on_req_per_s": 5050.0,
    "spans_exactly_once": True, "recompiles_during_telemetry": 0,
    "registry_instruments": 17, "registry_points": 1200,
    "slo": {"schema": "SLO.v1", "classes": {
        "interactive": {"objective": 0.99, "threshold_ms": 50.0,
                        "windows": {"60s": {"total": 100, "good": 99,
                                            "attainment": 0.99,
                                            "burn_rate": 1.0}}}}},
    "device_attribution": {"source": "none",
                           "reason": "profiler capture holds no "
                                     "device lane (CPU backend)"},
}


def _serve_art_v5(**extra):
    art = _serve_art(schema="BENCH_SERVE.v5",
                     chaos=dict(GOOD_CHAOS_V4),
                     cold_start=dict(GOOD_COLD),
                     telemetry_overhead=dict(GOOD_TELEMETRY))
    art.update(extra)
    return art


def test_serve_v5_requires_telemetry_section(tmp_path):
    """From schema v5 on, the unified-telemetry leg's
    'telemetry_overhead' section is contract; v4 artifacts predate it
    and stay valid."""
    assert cbs.validate_file(
        _write(tmp_path, "BENCH_SERVE_r09.json", _serve_art_v5())) == []
    art = _serve_art_v5()
    del art["telemetry_overhead"]
    errs = cbs.validate_file(_write(tmp_path, "BENCH_SERVE_r09.json",
                                    art))
    assert any("'telemetry_overhead' section" in e for e in errs)
    # v4 stays valid without the section (pre-ISSUE-12 shape)
    v4 = _serve_art_v4()
    assert cbs.validate_file(
        _write(tmp_path, "BENCH_SERVE_r09.json", v4)) == []


def test_serve_v5_rejects_telemetry_drift(tmp_path):
    for key, bad in (("overhead_x", None), ("overhead_x", 0),
                     ("reps", 0), ("plane_on_req_per_s", None),
                     ("plane_off_req_per_s", 0),
                     ("spans_exactly_once", False),
                     ("recompiles_during_telemetry", 2),
                     ("slo", {}), ("slo", {"classes": {}}),
                     ("device_attribution", None),
                     ("device_attribution", {})):
        tel = dict(GOOD_TELEMETRY, **{key: bad})
        p = _write(tmp_path, "BENCH_SERVE_r09.json",
                   _serve_art_v5(telemetry_overhead=tel))
        assert cbs.validate_file(p), \
            f"accepted broken telemetry {key}={bad}"
    # the <=5% bound IS the leg's claim: a costlier plane in a
    # committed artifact must not land green
    p = _write(tmp_path, "BENCH_SERVE_r09.json", _serve_art_v5(
        telemetry_overhead=dict(GOOD_TELEMETRY, overhead_x=1.2)))
    assert any("1.05 bound" in e for e in cbs.validate_file(p))
    # a non-profiler attribution must name its reason (the honest CPU
    # fallback shape)...
    p = _write(tmp_path, "BENCH_SERVE_r09.json", _serve_art_v5(
        telemetry_overhead=dict(GOOD_TELEMETRY,
                                device_attribution={"source": "none"})))
    assert any("reason" in e for e in cbs.validate_file(p))
    # ...and a profiler one must carry the split fields
    p = _write(tmp_path, "BENCH_SERVE_r09.json", _serve_art_v5(
        telemetry_overhead=dict(
            GOOD_TELEMETRY,
            device_attribution={"source": "profiler"})))
    errs = cbs.validate_file(p)
    assert any("device_compute_s" in e for e in errs)
    assert any("compute_fraction" in e for e in errs)
    # a complete profiler attribution validates
    good_attr = {"source": "profiler", "device_compute_s": 0.04,
                 "xla_queue_s": 0.01, "compute_fraction": 0.8}
    p = _write(tmp_path, "BENCH_SERVE_r09.json", _serve_art_v5(
        telemetry_overhead=dict(GOOD_TELEMETRY,
                                device_attribution=good_attr)))
    assert cbs.validate_file(p) == []


GOOD_CB_LEG = {
    "requests": 400, "batches": 180, "mean_batch_rows": 140.0,
    "p50_ms": 1.4, "p95_ms": 3.1, "p99_ms": 4.0,
    "queue_depth_peak": 9, "throughput_req_per_s": 1300.0,
}

GOOD_CONTINUOUS = {
    "requests_per_leg": 400, "reps": 3, "load_factor": 0.45,
    "calibration_req_per_s": 2900.0, "arrival_req_per_s": 1305.0,
    "baseline": dict(GOOD_CB_LEG, p95_ms=6.5, mode="drain"),
    "continuous": dict(GOOD_CB_LEG, mode="continuous"),
    "ladder": {"fixed": [1, 8, 64, 512],
               "learned": [1, 8, 32, 64, 256, 512],
               "installed": [32, 256], "retired": [],
               "max_rungs": 6, "recompile_budget": 6,
               "recompiles_charged": 2, "frozen": True,
               "sample_rows": 1200, "waste_fraction_fixed": 0.61,
               "waste_fraction_learned": 0.12},
    "p95_improvement_x": 2.1,
    "recompiles_after_freeze": 0,
    "spans_exactly_once": True,
}


def _serve_art_v6(**extra):
    art = _serve_art(schema="BENCH_SERVE.v6",
                     chaos=dict(GOOD_CHAOS_V4),
                     cold_start=dict(GOOD_COLD),
                     telemetry_overhead=dict(GOOD_TELEMETRY),
                     continuous_batching=dict(GOOD_CONTINUOUS))
    art.update(extra)
    return art


def test_serve_v6_requires_continuous_batching_section(tmp_path):
    """From schema v6 on, the learned-ladder continuous-batching
    leg's section is contract; v5 artifacts predate it and stay
    valid."""
    assert cbs.validate_file(
        _write(tmp_path, "BENCH_SERVE_r09.json", _serve_art_v6())) == []
    art = _serve_art_v6()
    del art["continuous_batching"]
    errs = cbs.validate_file(_write(tmp_path, "BENCH_SERVE_r09.json",
                                    art))
    assert any("'continuous_batching' section" in e for e in errs)
    # v5 stays valid without the section (pre-ISSUE-13 shape)
    v5 = _serve_art_v5()
    assert cbs.validate_file(
        _write(tmp_path, "BENCH_SERVE_r09.json", v5)) == []


def test_serve_v6_rejects_continuous_batching_drift(tmp_path):
    # both paired legs, measured, with a recorded improvement
    for key, bad in (("baseline", None),
                     ("continuous", None),
                     ("baseline", dict(GOOD_CB_LEG, p95_ms=0)),
                     ("continuous", dict(GOOD_CB_LEG, requests=0)),
                     ("p95_improvement_x", None),
                     ("p95_improvement_x", 0),
                     ("ladder", {}),
                     ("ladder", {"learned": []})):
        cb = dict(GOOD_CONTINUOUS)
        if bad is None and key in ("baseline", "continuous"):
            del cb[key]
        else:
            cb[key] = bad
        p = _write(tmp_path, "BENCH_SERVE_r09.json",
                   _serve_art_v6(continuous_batching=cb))
        assert cbs.validate_file(p), \
            f"accepted broken continuous_batching {key}={bad!r}"
    # the abort-grade pins, re-checked at the gate: a post-freeze
    # compile or a lost span must never land in a committed artifact
    p = _write(tmp_path, "BENCH_SERVE_r09.json", _serve_art_v6(
        continuous_batching=dict(GOOD_CONTINUOUS,
                                 recompiles_after_freeze=1)))
    assert any("never compile on the hot path" in e
               for e in cbs.validate_file(p))
    p = _write(tmp_path, "BENCH_SERVE_r09.json", _serve_art_v6(
        continuous_batching=dict(GOOD_CONTINUOUS,
                                 spans_exactly_once=False)))
    assert any("spans_exactly_once" in e for e in cbs.validate_file(p))


def _overload_fleet(good_per_rs, **extra):
    rec = {"replicas_start": 2, "replicas_peak": 2,
           "replica_seconds": 16.0, "wall_s": 8.0, "requests": 5000,
           "ok": 4800, "shed": 0, "deadline": 200, "lost": 0,
           "good": 3000, "good_per_replica_s": good_per_rs,
           "attainment": {"interactive": 0.95, "batch": 0.8},
           "p95_ms": 40.0, "queue_p95_ms": 30.0, "shed_by_class": {},
           "recompiles": 0, "spans_exactly_once": True}
    rec.update(extra)
    return rec


GOOD_OVERLOAD = {
    "load": {"shape": "flash", "base_rps": 150.0, "peak_rps": 1100.0,
             "duration_s": 8.0, "seed": 17, "requests": 5000},
    "classes": {"interactive": {"threshold_ms": 100.0,
                                "objective": 0.8},
                "batch": {"threshold_ms": 1000.0, "objective": 0.5}},
    "replica_rows_per_s": 1500.0,
    "artifact_export_s": 0.2, "artifact_load_s": 0.02,
    "fleets": {
        "fixed_1": _overload_fleet(70.0, replicas_start=1,
                                   replicas_peak=1,
                                   replica_seconds=8.0),
        "fixed_4": _overload_fleet(134.0, replicas_start=4,
                                   replicas_peak=4,
                                   replica_seconds=32.0),
        "autoscaled": _overload_fleet(
            170.0, replicas_peak=4, scale_ups=2, scale_downs=1,
            shed_by_class={"batch": 400, "shadow": 100}),
    },
    "autoscaled_beats_every_fixed": True,
    "beats": {"fixed_1": True, "fixed_4": True},
    "interactive_attainment_ok": True,
    "batch_shed": 400,
    "lost_accepted": 0,
    "scale_ups": 2,
    "recompiles_during_overload": 0,
    "spans_exactly_once": True,
}


def _serve_art_v7(**extra):
    art = _serve_art_v6(schema="BENCH_SERVE.v7",
                        overload=json.loads(
                            json.dumps(GOOD_OVERLOAD)))
    art.update(extra)
    return art


def test_serve_v7_requires_overload_section(tmp_path):
    """From schema v7 on, the elastic-serving leg's 'overload'
    section is contract; v6 artifacts predate it and stay valid."""
    assert cbs.validate_file(
        _write(tmp_path, "BENCH_SERVE_r09.json", _serve_art_v7())) == []
    art = _serve_art_v7()
    del art["overload"]
    errs = cbs.validate_file(_write(tmp_path, "BENCH_SERVE_r09.json",
                                    art))
    assert any("'overload' section" in e for e in errs)
    # v6 stays valid without the section (pre-ISSUE-14 shape)
    v6 = _serve_art_v6()
    assert cbs.validate_file(
        _write(tmp_path, "BENCH_SERVE_r09.json", v6)) == []


def test_serve_v7_rejects_overload_drift(tmp_path):
    # the comparison must be present and measured for every fleet
    ov = json.loads(json.dumps(GOOD_OVERLOAD))
    del ov["fleets"]["autoscaled"]
    p = _write(tmp_path, "BENCH_SERVE_r09.json",
               _serve_art_v7(overload=ov))
    assert any("autoscaled" in e for e in cbs.validate_file(p))
    for key, bad, needle in (
            ("requests", 0, "positive request count"),
            ("replica_seconds", 0, "replica_seconds"),
            ("good_per_replica_s", None, "good_per_replica_s"),
            ("lost", 3, "lost")):
        ov = json.loads(json.dumps(GOOD_OVERLOAD))
        ov["fleets"]["fixed_1"][key] = bad
        p = _write(tmp_path, "BENCH_SERVE_r09.json",
                   _serve_art_v7(overload=ov))
        assert any(needle in e for e in cbs.validate_file(p)), \
            f"accepted broken overload fleet {key}={bad!r}"
    # the abort-grade pins, re-checked at the gate — including the
    # beat, NUMERICALLY: an artifact whose autoscaled fleet does not
    # strictly exceed every fixed fleet must not land green even if
    # its boolean says otherwise
    ov = json.loads(json.dumps(GOOD_OVERLOAD))
    ov["fleets"]["autoscaled"]["good_per_replica_s"] = 100.0
    p = _write(tmp_path, "BENCH_SERVE_r09.json",
               _serve_art_v7(overload=ov))
    assert any("must beat" in e for e in cbs.validate_file(p))
    for key, bad, needle in (
            ("autoscaled_beats_every_fixed", False,
             "autoscaled_beats_every_fixed"),
            ("interactive_attainment_ok", False,
             "interactive_attainment_ok"),
            ("batch_shed", 0, "batch_shed"),
            ("lost_accepted", 2, "lost_accepted"),
            ("recompiles_during_overload", 1, "never compile"),
            ("spans_exactly_once", False, "spans_exactly_once")):
        ov = json.loads(json.dumps(GOOD_OVERLOAD))
        ov[key] = bad
        p = _write(tmp_path, "BENCH_SERVE_r09.json",
                   _serve_art_v7(overload=ov))
        assert any(needle in e for e in cbs.validate_file(p)), \
            f"accepted broken overload {key}={bad!r}"
    # an autoscaler that never scaled proves nothing
    ov = json.loads(json.dumps(GOOD_OVERLOAD))
    ov["fleets"]["autoscaled"]["scale_ups"] = 0
    p = _write(tmp_path, "BENCH_SERVE_r09.json",
               _serve_art_v7(overload=ov))
    assert any("scale_ups" in e for e in cbs.validate_file(p))


GOOD_POD = {
    "workers": 3, "requests": 120, "resolved_ok": 118,
    "deadline_exceeded": 2, "lost": 0,
    "kills_planned": 1, "kills_fired": 1,
    "partitions_planned": 2, "partitions_fired": 1,
    "workers_dead": 1, "requeues": 2, "reconnects": 4,
    "artifact_export_s": 0.2, "worker_spawn_s": 3.0,
    "stream_s": 0.5, "spans_exactly_once": True,
    "midstream_swap_version": 1, "swap_acks": 2,
    "post_swap_requests": 60, "post_swap_version_ok": True,
    "pod_dispatch_spans": 22, "trace_propagated": True,
    "survivor_recompiles": 0, "survivor_dispatches": 15,
    "per_worker": [],
}


def _serve_art_v8(**extra):
    art = _serve_art_v7(schema="BENCH_SERVE.v8",
                        pod=json.loads(json.dumps(GOOD_POD)))
    art.update(extra)
    return art


def test_serve_v8_requires_pod_section(tmp_path):
    """From schema v8 on, the cross-process serving leg's 'pod'
    section is contract; v7 artifacts predate it and stay valid."""
    assert cbs.validate_file(
        _write(tmp_path, "BENCH_SERVE_r09.json", _serve_art_v8())) == []
    art = _serve_art_v8()
    del art["pod"]
    errs = cbs.validate_file(_write(tmp_path, "BENCH_SERVE_r09.json",
                                    art))
    assert any("'pod' section" in e for e in errs)
    # v7 stays valid without the section (pre-ISSUE-15 shape)
    v7 = _serve_art_v7()
    assert cbs.validate_file(
        _write(tmp_path, "BENCH_SERVE_r09.json", v7)) == []


def test_serve_v8_rejects_pod_drift(tmp_path):
    # the abort-grade pins, re-checked at the gate: a one-process
    # "pod", chaos that never fired, a lost request, a broken trace
    # hop, or a compiled survivor must never land in a committed
    # artifact
    for key, bad, needle in (
            ("workers", 1, "not a pod"),
            ("requests", 0, "positive"),
            ("kills_fired", 0, "never killed"),
            ("partitions_fired", 0, "never partitioned"),
            ("lost", 2, "lost"),
            ("spans_exactly_once", False, "spans_exactly_once"),
            ("trace_propagated", False, "TRACECTX"),
            ("survivor_recompiles", 3, "never compile")):
        pod = json.loads(json.dumps(GOOD_POD))
        pod[key] = bad
        p = _write(tmp_path, "BENCH_SERVE_r09.json",
                   _serve_art_v8(pod=pod))
        assert any(needle in e for e in cbs.validate_file(p)), \
            f"accepted broken pod {key}={bad!r}"


def test_rejects_multichip_ok_rc_disagreement(tmp_path):
    p = _write(tmp_path, "MULTICHIP_r09.json",
               {"n_devices": 8, "rc": 124, "ok": True, "tail": "OK"})
    errs = cbs.validate_file(p)
    assert any("disagrees" in e for e in errs)
    p2 = _write(tmp_path, "MULTICHIP_r10.json",
                {"n_devices": 8, "rc": 0, "ok": True,
                 "tail": "dryrun_multichip(8): OK"})
    assert cbs.validate_file(p2) == []


def test_rejects_non_json_and_unknown_family(tmp_path):
    bad = tmp_path / "BENCH_r09.json"
    bad.write_text("{not json")
    assert cbs.validate_file(str(bad))
    other = _write(tmp_path, "WHATEVER_r01.json", {})
    assert cbs.validate_file(other)


def test_expect_some_fails_on_empty_root(tmp_path):
    assert cbs.main(["--root", str(tmp_path), "--expect-some"]) == 1
    assert cbs.main(["--root", str(tmp_path)]) == 0


def _good_scale():
    return {
        "schema": "SCALE.v1",
        "metric": "updates_per_sec",
        "platform": "cpu",
        "records": [{"config": "cohort_stream", "wall_s": 4.8}],
        "cohort": {
            "clients": 1_000_000, "shards": 256, "shard_clients": 3907,
            "rounds": 1, "streamed": True, "updates_per_sec": 2e5,
            "wall_s": 4.8, "recompiles_after_warmup": 0,
        },
    }


def test_scale_v1_validates_and_requires_cohort_section(tmp_path):
    assert cbs.validate_file(
        _write(tmp_path, "SCALE_r09.json", _good_scale())) == []
    art = _good_scale()
    del art["cohort"]
    errs = cbs.validate_file(_write(tmp_path, "SCALE_r09.json", art))
    assert any("cohort" in e for e in errs)
    # an unparseable version must not silently skip the cohort rules
    art = _good_scale()
    art["schema"] = "SCALE.v1-rc1"
    errs = cbs.validate_file(_write(tmp_path, "SCALE_r09.json", art))
    assert any("unparseable schema version" in e for e in errs)


def test_scale_rejects_cohort_drift(tmp_path):
    # a recompile during the streamed sweep must never land green
    art = _good_scale()
    art["cohort"]["recompiles_after_warmup"] = 2
    errs = cbs.validate_file(_write(tmp_path, "SCALE_r09.json", art))
    assert any("recompiles_after_warmup" in e for e in errs)
    # a one-shard "cohort" never exercised the two-tier fold
    art = _good_scale()
    art["cohort"]["shards"] = 1
    errs = cbs.validate_file(_write(tmp_path, "SCALE_r09.json", art))
    assert any("shards" in e for e in errs)
    # an unstreamed leg is not the thing this section certifies
    art = _good_scale()
    art["cohort"]["streamed"] = False
    errs = cbs.validate_file(_write(tmp_path, "SCALE_r09.json", art))
    assert any("streamed" in e for e in errs)
    # throughput/wall time must be positive numbers
    art = _good_scale()
    art["cohort"]["updates_per_sec"] = 0
    errs = cbs.validate_file(_write(tmp_path, "SCALE_r09.json", art))
    assert any("updates_per_sec" in e for e in errs)
    # the records list itself is part of the contract
    art = _good_scale()
    art["records"] = []
    errs = cbs.validate_file(_write(tmp_path, "SCALE_r09.json", art))
    assert any("records" in e for e in errs)
    # family check: a non-SCALE schema in a SCALE_ file
    art = _good_scale()
    art["schema"] = "BENCH_SERVE.v3"
    errs = cbs.validate_file(_write(tmp_path, "SCALE_r09.json", art))
    assert any("SCALE. family" in e for e in errs)


def _good_graftlint():
    return {
        "schema": "GRAFTLINT.v1",
        "package": "pkg",
        "rules": {"GL001": {"title": "t", "catches": "c",
                            "runtime_twin": "r"}},
        "counts": {"GL001": 0},
        "findings": [],
        "baselined": [],
        "suppressed": [
            {"rule": "GL003", "path": "serving/engine.py", "line": 9,
             "message": "m", "context": "c", "fingerprint": "ab12",
             "reason": "deliberate sync, argued inline"}],
        "clean": True,
    }


def test_graftlint_artifact_validates_and_rejects_drift(tmp_path):
    errs = cbs.validate_file(_write(tmp_path, "GRAFTLINT_r01.json",
                                    _good_graftlint()))
    assert errs == []
    # a committed lint artifact carrying findings is the silent-red
    # landing the gate exists to stop
    art = _good_graftlint()
    art["findings"] = [{"rule": "GL001", "path": "x.py", "line": 1,
                        "message": "m", "fingerprint": "cd34"}]
    art["clean"] = False
    errs = cbs.validate_file(_write(tmp_path, "GRAFTLINT_r01.json", art))
    assert any("must be clean" in e for e in errs)
    # a suppression without its mandatory reason
    art = _good_graftlint()
    art["suppressed"][0].pop("reason")
    errs = cbs.validate_file(_write(tmp_path, "GRAFTLINT_r01.json", art))
    assert any("without a reason" in e for e in errs)
    # a self-contradicting artifact: counts say 7, findings say none
    art = _good_graftlint()
    art["counts"] = {"GL001": 7}
    errs = cbs.validate_file(_write(tmp_path, "GRAFTLINT_r01.json", art))
    assert any("disagrees with" in e for e in errs)
    # a partial (--rules) run must not wear a full run's counts table
    art = _good_graftlint()
    art["rules_run"] = ["GL001", "GL004"]
    errs = cbs.validate_file(_write(tmp_path, "GRAFTLINT_r01.json", art))
    assert any("rules_run" in e for e in errs)
    # family + version discipline, same as every other artifact
    art = _good_graftlint()
    art["schema"] = "SCALE.v1"
    errs = cbs.validate_file(_write(tmp_path, "GRAFTLINT_r01.json", art))
    assert any("GRAFTLINT. family" in e for e in errs)
    art = _good_graftlint()
    art["schema"] = "GRAFTLINT.v1-rc1"
    errs = cbs.validate_file(_write(tmp_path, "GRAFTLINT_r01.json", art))
    assert any("unparseable schema version" in e for e in errs)


# -- CAMPAIGN.v1 (ISSUE 16: the scenario-fuzzing campaign artifact) ---

def _campaign_art(**over):
    verdict = {"spec": "seed=1,rounds=2,clients=4,replicas=2,"
                       "requests=12,faults=0.2,chaos=0,load=0,net=0,"
                       "swaps=0,kills=0,scales=0",
               "digest": "ab" * 32, "codes": [], "ok": True,
               "counts": {"served": 12}}
    art = {"schema": "CAMPAIGN.v1", "seed": 3, "budget": 2,
           "scenarios": 2, "failures": 0, "truncated": False,
           "digest": "cd" * 32, "verdicts": [dict(verdict),
                                             dict(verdict)],
           "violations": [], "wall_s": 0.5}
    art.update(over)
    return art


def test_campaign_v1_minimal_artifact_validates(tmp_path):
    p = _write(tmp_path, "CAMPAIGN_x.json", _campaign_art())
    assert cbs.validate_file(p) == []
    # truncated short campaigns are honest and pass
    p2 = _write(tmp_path, "CAMPAIGN_y.json",
                _campaign_art(scenarios=1, truncated=True,
                              verdicts=_campaign_art()["verdicts"][:1]))
    assert cbs.validate_file(p2) == []


def test_campaign_rejects_committed_failures(tmp_path):
    bad_v = dict(_campaign_art()["verdicts"][0],
                 codes=["RECOMPILE"], ok=False)
    art = _campaign_art(
        failures=1,
        verdicts=[_campaign_art()["verdicts"][0], bad_v],
        violations=[{"index": 1, "verdict": bad_v}])
    p = _write(tmp_path, "CAMPAIGN_x.json", art)
    errs = cbs.validate_file(p)
    assert any("must be clean" in e for e in errs)


def test_campaign_rejects_malformed_digest(tmp_path):
    for digest in ("", "xyz", "AB" * 32, "ab" * 31):
        p = _write(tmp_path, "CAMPAIGN_x.json",
                   _campaign_art(digest=digest))
        assert any("sha256" in e for e in cbs.validate_file(p))


def test_campaign_rejects_silent_truncation(tmp_path):
    art = _campaign_art(scenarios=1,
                        verdicts=_campaign_art()["verdicts"][:1])
    p = _write(tmp_path, "CAMPAIGN_x.json", art)
    errs = cbs.validate_file(p)
    assert any("without truncated=true" in e for e in errs)
    # and a count that exceeds the budget is impossible
    art2 = _campaign_art(scenarios=3, budget=2)
    p2 = _write(tmp_path, "CAMPAIGN_x.json", art2)
    assert any("exceeds budget" in e for e in cbs.validate_file(p2))


def test_campaign_rejects_ok_codes_disagreement(tmp_path):
    art = _campaign_art()
    art["verdicts"][1] = dict(art["verdicts"][1],
                              codes=["LOST_REQUEST"], ok=True)
    p = _write(tmp_path, "CAMPAIGN_x.json", art)
    errs = cbs.validate_file(p)
    assert any("disagrees with codes" in e for e in errs)
    # the inverse disagreement is red, so it must ALSO carry a
    # violation record
    art2 = _campaign_art()
    art2["verdicts"][1] = dict(art2["verdicts"][1], ok=False)
    p2 = _write(tmp_path, "CAMPAIGN_x.json", art2)
    errs2 = cbs.validate_file(p2)
    assert any("disagrees with codes" in e for e in errs2)
    assert any("red verdict" in e for e in errs2)


# -- CAMPAIGN.v2 (ISSUE 18: the coverage-guided hunt artifact) --------

def _hunt_art(**over):
    base = _campaign_art()["verdicts"]
    v0 = dict(base[0], origin={"kind": "grid", "index": 3},
              signature=["faults", "kill"])
    v1 = dict(base[1], origin={"kind": "mutation", "parent": 0,
                               "stream": "events", "attempt": 1},
              signature=["faults", "kill", "mutant"])
    art = _campaign_art(schema="CAMPAIGN.v2", verdicts=[v0, v1],
                        coverage={"faults": 2, "kill": 2, "mutant": 1},
                        wall_budget_s=None)
    art.update(over)
    return art


def test_campaign_v2_hunt_artifact_validates(tmp_path):
    assert cbs.validate_file(
        _write(tmp_path, "CAMPAIGN_x.json", _hunt_art())) == []
    # a capped hunt records its cap as a positive number
    assert cbs.validate_file(_write(
        tmp_path, "CAMPAIGN_x.json", _hunt_art(wall_budget_s=120.5))) \
        == []
    # v1 artifacts predate the hunt accounting and stay valid bare
    assert cbs.validate_file(
        _write(tmp_path, "CAMPAIGN_x.json", _campaign_art())) == []


def test_campaign_v2_requires_hunt_accounting(tmp_path):
    art = _hunt_art()
    del art["coverage"]
    errs = cbs.validate_file(_write(tmp_path, "CAMPAIGN_x.json", art))
    assert any("'coverage'" in e for e in errs)
    p = _write(tmp_path, "CAMPAIGN_x.json",
               _hunt_art(coverage={"faults": -1}))
    assert any("non-negative" in e for e in cbs.validate_file(p))
    art = _hunt_art()
    del art["wall_budget_s"]
    errs = cbs.validate_file(_write(tmp_path, "CAMPAIGN_x.json", art))
    assert any("wall_budget_s" in e for e in errs)
    for bad in (0, -2, "fast"):
        p = _write(tmp_path, "CAMPAIGN_x.json",
                   _hunt_art(wall_budget_s=bad))
        assert any("wall_budget_s" in e for e in cbs.validate_file(p)), \
            f"accepted wall_budget_s={bad!r}"


def test_campaign_v2_requires_verdict_provenance(tmp_path):
    # a v2 verdict without its origin/signature cannot be replayed
    art = _hunt_art()
    del art["verdicts"][0]["origin"]
    errs = cbs.validate_file(_write(tmp_path, "CAMPAIGN_x.json", art))
    assert any("'origin'" in e for e in errs)
    art = _hunt_art()
    del art["verdicts"][1]["signature"]
    errs = cbs.validate_file(_write(tmp_path, "CAMPAIGN_x.json", art))
    assert any("'signature'" in e for e in errs)
    art = _hunt_art()
    art["verdicts"][0]["origin"] = {"kind": "wishful"}
    errs = cbs.validate_file(_write(tmp_path, "CAMPAIGN_x.json", art))
    assert any("'grid' or 'mutation'" in e for e in errs)
    art = _hunt_art()
    art["verdicts"][0]["origin"] = {"kind": "grid"}
    errs = cbs.validate_file(_write(tmp_path, "CAMPAIGN_x.json", art))
    assert any("pool 'index'" in e for e in errs)


def test_campaign_v2_rejects_ill_founded_mutation_lineage(tmp_path):
    # a mutant whose parent ran LATER (or is itself) is a lineage the
    # seed could never re-derive — the hand-edit this gate exists for
    for parent in (1, 5, -1, None):
        art = _hunt_art()
        art["verdicts"][1]["origin"]["parent"] = parent
        errs = cbs.validate_file(_write(tmp_path, "CAMPAIGN_x.json",
                                        art))
        assert any("EARLIER verdict" in e for e in errs), \
            f"accepted mutation parent={parent!r}"
    art = _hunt_art()
    del art["verdicts"][1]["origin"]["stream"]
    errs = cbs.validate_file(_write(tmp_path, "CAMPAIGN_x.json", art))
    assert any("'stream'" in e for e in errs)
    art = _hunt_art()
    art["verdicts"][1]["origin"]["attempt"] = 0
    errs = cbs.validate_file(_write(tmp_path, "CAMPAIGN_x.json", art))
    assert any("'attempt'" in e for e in errs)


def test_campaign_rejects_bad_shrink_trace(tmp_path):
    bad_v = dict(_campaign_art()["verdicts"][0],
                 codes=["RECOMPILE"], ok=False)
    base = dict(failures=1,
                verdicts=[_campaign_art()["verdicts"][0], bad_v])
    # shrunk without its spec/codes/trace
    art = _campaign_art(**base, violations=[
        {"index": 1, "verdict": bad_v, "shrunk": {"spec": "seed=1"}}])
    p = _write(tmp_path, "CAMPAIGN_x.json", art)
    assert any("spec/codes/trace" in e for e in cbs.validate_file(p))
    # trace steps missing action/spec/kept
    art2 = _campaign_art(**base, violations=[
        {"index": 1, "verdict": bad_v,
         "shrunk": {"spec": "seed=1", "codes": ["RECOMPILE"],
                    "trace": [{"action": "drop:faults"}]}}])
    p2 = _write(tmp_path, "CAMPAIGN_x.json", art2)
    assert any("action/spec/kept" in e for e in cbs.validate_file(p2))
