"""Optional model checkpointing: save/load roundtrip + algorithm state.

The reference persists only metric matrices (``exp.py:132-143``); the
framework adds opt-in ``(global_params, p, round)`` checkpoints
(``utils/checkpoint.py``). These tests pin the roundtrip and that
``return_state=True`` hands back the exact final model the metrics
were computed from.
"""

import numpy as np

from fedamw_tpu.algorithms import FedAMW, FedAvg, prepare_setup
from fedamw_tpu.data import load_dataset
from fedamw_tpu.fedcore import make_evaluator
from fedamw_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


def test_roundtrip(tmp_path):
    params = {"w": np.arange(12, dtype=np.float32).reshape(3, 4)}
    p = np.array([0.25, 0.75], np.float32)
    where = save_checkpoint(str(tmp_path / "ck"), params, p=p, round_idx=7)
    state = load_checkpoint(str(tmp_path / "ck"))
    np.testing.assert_array_equal(np.asarray(state["params"]["w"]),
                                  params["w"])
    np.testing.assert_array_equal(np.asarray(state["p"]), p)
    assert int(state["round"]) == 7
    assert isinstance(where, str)


def test_return_state_matches_reported_metrics(tmp_path):
    ds = load_dataset("digits", num_partitions=6, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=3,
                          rng=np.random.RandomState(3))
    res = FedAvg(setup, lr=0.5, epoch=1, round=3, seed=0,
                 lr_mode="constant", return_state=True)
    evaluate = make_evaluator(setup.model.apply, setup.task)
    tl, ta = evaluate(res["params"], setup.X_test, setup.y_test)
    np.testing.assert_allclose(float(ta), res["test_acc"][-1], atol=1e-4)
    # fixed-weight algorithms report p_fixed as the final mixture
    np.testing.assert_allclose(np.asarray(res["p"]),
                               np.asarray(setup.p_fixed), atol=0)

    # and the state survives a disk roundtrip
    save_checkpoint(str(tmp_path / "fedavg"), res["params"], p=res["p"])
    state = load_checkpoint(str(tmp_path / "fedavg"))
    tl2, ta2 = evaluate(
        {k: np.asarray(v) for k, v in state["params"].items()},
        setup.X_test, setup.y_test)
    np.testing.assert_allclose(float(ta2), float(ta), atol=1e-5)


def test_fedamw_returns_learned_p():
    ds = load_dataset("digits", num_partitions=6, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=3,
                          rng=np.random.RandomState(3))
    res = FedAMW(setup, lr=0.5, epoch=1, round=2, lambda_reg=1e-4,
                 lr_p=1e-2, seed=0, lr_mode="constant", return_state=True)
    # learned p must have moved off the sample-count init
    assert not np.allclose(np.asarray(res["p"]),
                           np.asarray(setup.p_fixed))
