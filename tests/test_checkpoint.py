"""Optional model checkpointing: save/load roundtrip + algorithm state.

The reference persists only metric matrices (``exp.py:132-143``); the
framework adds opt-in ``(global_params, p, round)`` checkpoints
(``utils/checkpoint.py``). These tests pin the roundtrip and that
``return_state=True`` hands back the exact final model the metrics
were computed from.
"""

import numpy as np

from fedamw_tpu.algorithms import FedAMW, FedAvg, prepare_setup
from fedamw_tpu.data import load_dataset
from fedamw_tpu.fedcore import make_evaluator
from fedamw_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


def test_roundtrip(tmp_path):
    params = {"w": np.arange(12, dtype=np.float32).reshape(3, 4)}
    p = np.array([0.25, 0.75], np.float32)
    where = save_checkpoint(str(tmp_path / "ck"), params, p=p, round_idx=7)
    state = load_checkpoint(str(tmp_path / "ck"))
    np.testing.assert_array_equal(np.asarray(state["params"]["w"]),
                                  params["w"])
    np.testing.assert_array_equal(np.asarray(state["p"]), p)
    assert int(state["round"]) == 7
    assert isinstance(where, str)


def test_return_state_matches_reported_metrics(tmp_path):
    ds = load_dataset("digits", num_partitions=6, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=3,
                          rng=np.random.RandomState(3))
    res = FedAvg(setup, lr=0.5, epoch=1, round=3, seed=0,
                 lr_mode="constant", return_state=True)
    evaluate = make_evaluator(setup.model.apply, setup.task)
    tl, ta = evaluate(res["params"], setup.X_test, setup.y_test)
    np.testing.assert_allclose(float(ta), res["test_acc"][-1], atol=1e-4)
    # fixed-weight algorithms report p_fixed as the final mixture
    np.testing.assert_allclose(np.asarray(res["p"]),
                               np.asarray(setup.p_fixed), atol=0)

    # and the state survives a disk roundtrip
    save_checkpoint(str(tmp_path / "fedavg"), res["params"], p=res["p"])
    state = load_checkpoint(str(tmp_path / "fedavg"))
    tl2, ta2 = evaluate(
        {k: np.asarray(v) for k, v in state["params"].items()},
        setup.X_test, setup.y_test)
    np.testing.assert_allclose(float(ta2), float(ta), atol=1e-5)


def test_layout_switch_never_shadows_fresh_state(tmp_path, monkeypatch):
    """An orbax save followed by a pickle-fallback save to the SAME dir
    (orbax broken on the rerun) must load the FRESH state: the stale
    orbax layout is removed, not left to shadow the pickle — serving
    would otherwise restore the old round's params with no error."""
    import sys

    from fedamw_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    old = {"w": np.zeros((2, 3), np.float32)}
    new = {"w": np.ones((2, 3), np.float32)}
    where1 = save_checkpoint(str(tmp_path / "ck"), old)
    assert "orbax" in where1  # precondition: first save took orbax
    monkeypatch.setitem(sys.modules, "orbax", None)
    monkeypatch.setitem(sys.modules, "orbax.checkpoint", None)
    where2 = save_checkpoint(str(tmp_path / "ck"), new)
    assert "state.pkl" in where2
    monkeypatch.undo()  # load with orbax importable again
    state = load_checkpoint(str(tmp_path / "ck"))
    np.testing.assert_array_equal(np.asarray(state["params"]["w"]),
                                  new["w"])


def test_corrupt_pickle_raises_typed_error_with_path(tmp_path):
    """A truncated/corrupt state.pkl raises CheckpointError naming the
    offending file — not the storage layer's bare EOFError/
    UnpicklingError (useless on a box serving dozens of checkpoints).
    A missing checkpoint stays FileNotFoundError."""
    import pickle

    import pytest

    from fedamw_tpu.utils.checkpoint import CheckpointError

    ck = tmp_path / "ck"
    ck.mkdir()
    good = pickle.dumps({"params": {"w": np.zeros((2, 2), np.float32)}})
    (ck / "state.pkl").write_bytes(good[: len(good) // 2])  # truncated
    with pytest.raises(CheckpointError, match="state.pkl"):
        load_checkpoint(str(ck))
    try:
        load_checkpoint(str(ck))
    except CheckpointError as e:
        assert e.path.endswith("state.pkl")

    (ck / "state.pkl").write_bytes(b"\x80garbage not a pickle")
    with pytest.raises(CheckpointError):
        load_checkpoint(str(ck))

    with pytest.raises(FileNotFoundError):
        load_checkpoint(str(tmp_path / "nowhere"))


def test_corrupt_orbax_tree_raises_typed_error(tmp_path):
    """A half-written orbax layout (interrupted save) is a typed
    CheckpointError too, and it names the orbax dir."""
    import pytest

    from fedamw_tpu.utils.checkpoint import CheckpointError

    ck = tmp_path / "ck"
    (ck / "orbax").mkdir(parents=True)  # empty dir: no valid tree
    with pytest.raises(CheckpointError, match="orbax"):
        load_checkpoint(str(ck))


def test_serving_engine_surfaces_checkpoint_error(tmp_path):
    """ServingEngine.load propagates the typed error for a damaged
    checkpoint and raises its own CheckpointError for a state with no
    'params' — the operator gets 'which file is broken', never a
    KeyError mid-construction."""
    import pickle

    import pytest

    from fedamw_tpu.serving import ServingEngine
    from fedamw_tpu.utils.checkpoint import CheckpointError

    ck = tmp_path / "ck"
    ck.mkdir()
    (ck / "state.pkl").write_bytes(b"not a pickle at all")
    with pytest.raises(CheckpointError, match="state.pkl"):
        ServingEngine.load(str(ck))

    with open(ck / "state.pkl", "wb") as f:
        pickle.dump({"p": np.ones(3, np.float32)}, f)  # no 'params'
    with pytest.raises(CheckpointError, match="params"):
        ServingEngine.load(str(ck))


def test_fedamw_returns_learned_p():
    ds = load_dataset("digits", num_partitions=6, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=3,
                          rng=np.random.RandomState(3))
    res = FedAMW(setup, lr=0.5, epoch=1, round=2, lambda_reg=1e-4,
                 lr_p=1e-2, seed=0, lr_mode="constant", return_state=True)
    # learned p must have moved off the sample-count init
    assert not np.allclose(np.asarray(res["p"]),
                           np.asarray(setup.p_fixed))


def test_resume_reproduces_uninterrupted_run():
    """prefix (rounds [0,3) of a 6-horizon) + checkpoint + resume
    (rounds [3,6)) == the uninterrupted 6-round run, exactly: every
    per-round stream (shuffle keys, LR schedule, participation keys) is
    generated for the full horizon and sliced."""
    import numpy as np

    from fedamw_tpu.algorithms import FedAvg, prepare_setup
    from fedamw_tpu.data import load_dataset

    ds = load_dataset("digits", num_partitions=6, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=9,
                          rng=np.random.RandomState(9))
    kw = dict(lr=0.5, epoch=1, batch_size=32, seed=0,
              lr_mode="reference")  # horizon-dependent schedule: the
    # strictest case (a 3-round run would decay at t=1.5, not t=3)

    full = FedAvg(setup, round=6, return_state=True, **kw)
    prefix = FedAvg(setup, round=6, stop_round=3, return_state=True, **kw)
    resumed = FedAvg(setup, round=6, start_round=3,
                     resume_from={"params": prefix["params"]},
                     return_state=True, **kw)

    np.testing.assert_array_equal(
        np.asarray(resumed["test_acc"]), np.asarray(full["test_acc"])[3:])
    np.testing.assert_array_equal(
        np.asarray(resumed["train_loss"]),
        np.asarray(full["train_loss"])[3:])
    np.testing.assert_array_equal(np.asarray(resumed["params"]["w"]),
                                  np.asarray(full["params"]["w"]))


def test_resume_roundtrips_through_checkpoint_files(tmp_path):
    """The same equivalence through save_checkpoint/load_checkpoint on
    disk (either orbax or pickle layout)."""
    import numpy as np

    from fedamw_tpu.algorithms import FedAvg, prepare_setup
    from fedamw_tpu.data import load_dataset
    from fedamw_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    ds = load_dataset("digits", num_partitions=4, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=3,
                          rng=np.random.RandomState(3))
    kw = dict(lr=0.5, epoch=1, batch_size=32, seed=1, lr_mode="constant")

    full = FedAvg(setup, round=4, return_state=True, **kw)
    prefix = FedAvg(setup, round=4, stop_round=2, return_state=True, **kw)
    save_checkpoint(str(tmp_path / "ck"), prefix["params"], p=prefix["p"],
                    round_idx=2)
    state = load_checkpoint(str(tmp_path / "ck"))
    resumed = FedAvg(setup, round=4, start_round=int(state["round"]),
                     resume_from=state, **kw)
    np.testing.assert_allclose(
        np.asarray(resumed["test_acc"]),
        np.asarray(full["test_acc"])[2:], atol=1e-5)


def test_resume_validates_window():
    import numpy as np
    import pytest

    from fedamw_tpu.algorithms import FedAvg, prepare_setup
    from fedamw_tpu.data import load_dataset

    ds = load_dataset("digits", num_partitions=4, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=3,
                          rng=np.random.RandomState(3))
    with pytest.raises(ValueError, match="start_round"):
        FedAvg(setup, round=4, start_round=2)  # no resume_from
    with pytest.raises(ValueError, match="stop_round"):
        FedAvg(setup, round=4, stop_round=5)


def test_fedamw_resume_continues_mixture_weights():
    """FedAMW exact resume: params, the learned p, AND the p-optimizer
    momentum buffer ('p_opt' from return_state=True) continue from the
    checkpoint, so prefix + resume == the uninterrupted run, like the
    FedAvg test above."""
    import numpy as np

    from fedamw_tpu.algorithms import FedAMW, prepare_setup
    from fedamw_tpu.data import load_dataset

    ds = load_dataset("digits", num_partitions=4, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=5,
                          rng=np.random.RandomState(5))
    kw = dict(lr=0.5, epoch=1, batch_size=32, lambda_reg=1e-4, lr_p=1e-3,
              seed=1, lr_mode="constant")

    full = FedAMW(setup, round=4, return_state=True, **kw)
    prefix = FedAMW(setup, round=4, stop_round=2, return_state=True, **kw)
    resumed = FedAMW(setup, round=4, start_round=2,
                     resume_from={"params": prefix["params"],
                                  "p": prefix["p"],
                                  "p_opt": prefix["p_opt"]},
                     return_state=True, **kw)
    # resumed p must continue from the prefix's p, not reinit to n_j/n
    assert not np.allclose(np.asarray(resumed["p"]),
                           np.asarray(setup.p_fixed))
    np.testing.assert_array_equal(np.asarray(resumed["test_acc"]),
                                  np.asarray(full["test_acc"])[2:])
    np.testing.assert_array_equal(np.asarray(resumed["train_loss"]),
                                  np.asarray(full["train_loss"])[2:])
    np.testing.assert_array_equal(np.asarray(resumed["p"]),
                                  np.asarray(full["p"]))


def test_fedamw_resume_without_p_opt_warns_and_approximates():
    """Resuming from a checkpoint lacking 'p_opt' (e.g. one written
    before round 3) warns and restarts the momentum buffer — still a
    valid continuation, just approximate."""
    import numpy as np
    import pytest

    from fedamw_tpu.algorithms import FedAMW, prepare_setup
    from fedamw_tpu.data import load_dataset

    ds = load_dataset("digits", num_partitions=4, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=5,
                          rng=np.random.RandomState(5))
    kw = dict(lr=0.5, epoch=1, batch_size=32, lambda_reg=1e-4, lr_p=1e-3,
              seed=1, lr_mode="constant")
    full = FedAMW(setup, round=4, return_state=True, **kw)
    prefix = FedAMW(setup, round=4, stop_round=2, return_state=True, **kw)
    with pytest.warns(UserWarning, match="p_opt"):
        resumed = FedAMW(setup, round=4, start_round=2,
                         resume_from={"params": prefix["params"],
                                      "p": prefix["p"]}, **kw)
    np.testing.assert_allclose(np.asarray(resumed["test_acc"])[-1],
                               np.asarray(full["test_acc"])[-1], atol=2.0)


def test_fedopt_resume_carries_server_state(tmp_path):
    """FedAvg + server_opt='adam' exact resume: the Adam moments and
    bias-correction count travel through the checkpoint as the
    'server_opt' leaf tuple (ADVICE r2: without this, resume silently
    reinitialized the server optimizer)."""
    import numpy as np
    import pytest

    from fedamw_tpu.algorithms import FedAvg, prepare_setup
    from fedamw_tpu.data import load_dataset
    from fedamw_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    ds = load_dataset("digits", num_partitions=4, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=7,
                          rng=np.random.RandomState(7))
    kw = dict(lr=0.5, epoch=1, batch_size=32, seed=2, lr_mode="constant",
              server_opt="adam", server_lr=0.1)

    full = FedAvg(setup, round=4, return_state=True, **kw)
    prefix = FedAvg(setup, round=4, stop_round=2, return_state=True, **kw)
    save_checkpoint(str(tmp_path / "ck"), prefix["params"],
                    p=prefix["p"], round_idx=2,
                    extra={"server_opt": prefix["server_opt"],
                           "server_opt_kind": prefix["server_opt_kind"]})
    state = load_checkpoint(str(tmp_path / "ck"))
    resumed = FedAvg(setup, round=4, start_round=int(state["round"]),
                     resume_from=state, **kw)
    np.testing.assert_allclose(np.asarray(resumed["test_acc"]),
                               np.asarray(full["test_acc"])[2:], atol=1e-5)
    np.testing.assert_allclose(np.asarray(resumed["train_loss"]),
                               np.asarray(full["train_loss"])[2:],
                               atol=1e-6)

    # and without the state: a warning + approximate continuation
    with pytest.warns(UserWarning, match="server_opt"):
        FedAvg(setup, round=4, start_round=2,
               resume_from={"params": prefix["params"]}, **kw)

    # config drift must be rejected, not silently reinterpreted:
    # adam and yogi states share a leaf structure, so without the kind
    # tag yogi would happily consume adam's moments
    assert state.get("server_opt_kind") == "adam"
    with pytest.raises(ValueError, match="server_opt"):
        FedAvg(setup, round=4, start_round=2, resume_from=state,
               **{**kw, "server_opt": "yogi"})
