"""serving/control.py — the overload control plane (ISSUE 14).

Load-bearing contracts:

- **Load-shape grammar determinism**: a ``LoadSpec`` expands to a
  bitwise-identical arrival schedule for the same seed (the serving
  twin of the chaos plan's pin) — the overload bench replays ONE
  flash crowd across fleets, not statistically-similar ones.
- **Hand-computed burn-rate fixtures**: known latency samples under an
  injectable clock drive the admission controller's escalate/relax
  machine and the autoscaler's up/down machine deterministically —
  trigger = burn > threshold, queue-percentile corroboration gates
  it, hysteresis (ticks / dead band / cooldown) prevents flapping.
- **Class-aware shedding**: shadow sheds first, then batch;
  interactive is NEVER policy-shed; rejections resolve futures with
  the typed ``AdmissionShed`` (not the deadline path), counted per
  class and annotated ``shed`` on the span.
- **Elastic fleet**: ``FailoverRouter.add_replica/remove_replica``
  grow/shrink routing at runtime; the autoscaler scales up under a
  flash crowd, never past ``max_replicas``, scales down only after
  sustained quiet and only replicas it added, and its
  replica-seconds integral is hand-checkable.
- **Deadline scheduling**: under pressure the continuous worker
  dispatches soonest-deadline-first (``batcher.edf_order``); the
  clean-load path is byte-identical FIFO.
- **Interactive protection under sustained overload** (real time): a
  throttled fleet at ~2x capacity with the controller attached keeps
  interactive attainment above batch while batch sheds, and loses
  nothing.
"""

import threading
import time

import numpy as np
import pytest

from fedamw_tpu.serving import (AdmissionController, AdmissionShed,
                                Autoscaler, FailoverRouter, LoadSpec,
                                Replica, ReplicaSet, ServeMetrics,
                                ServingEngine, ServingService,
                                admission_shed_rate, edf_order)
from fedamw_tpu.serving.metrics import (QUEUE_RESIDENCY_METRIC,
                                        SHED_CLASS_METRIC)
from fedamw_tpu.utils.telemetry import Registry, SloClass, SloEvaluator
from fedamw_tpu.utils.trace import Tracer

pytestmark = pytest.mark.control

D, C = 16, 3

CLASSES = (SloClass("interactive", threshold_ms=50.0, objective=0.99),
           SloClass("batch", threshold_ms=500.0, objective=0.95))


def make_engine(buckets=(1, 8, 32)):
    rng = np.random.RandomState(1)
    e = ServingEngine({"w": rng.randn(C, D).astype(np.float32)},
                      buckets=buckets)
    e.warmup()
    return e


class Clock:
    """Injectable monotonic clock: tests advance time by hand."""

    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def make_plane(clk):
    """A metrics bundle on a fake-clock registry — every series
    timestamp below is hand-placed."""
    return ServeMetrics(registry=Registry(clock=clk))


def feed(m, n_bad, n_good, cls="batch", queue_s=0.4, bad_s=0.9,
         good_s=0.005):
    """Record ``n_bad`` over-threshold + ``n_good`` under-threshold
    latencies for ``cls`` plus queue residency — one hand-computed
    burn-rate evidence batch at the registry clock's current time."""
    n = n_bad + n_good
    m.record_batch(n, n, latencies=[bad_s] * n_bad + [good_s] * n_good,
                   stage_seconds={"queue": [queue_s] * n},
                   slo_classes=[cls] * n)


# -- LoadSpec: grammar + determinism ----------------------------------

def test_load_spec_parse_full_grammar():
    s = LoadSpec.parse("shape=flash,base=200,peak=1600,duration=6,"
                       "at=0.35,width=0.25,seed=17")
    assert (s.shape, s.base_rps, s.peak_rps) == ("flash", 200.0, 1600.0)
    assert (s.duration_s, s.at, s.width, s.seed) == (6.0, 0.35, 0.25, 17)
    # bare defaults
    s2 = LoadSpec.parse("")
    assert s2 == LoadSpec()
    assert LoadSpec.parse("shape=overload,peak=900").shape == "overload"


@pytest.mark.parametrize("bad, match", [
    ("boom=1", "unknown load spec key"),
    ("shape", "not key=value"),
    ("peak=lots", "peak=lots"),
    ("shape=square", "must be one of"),
    ("base=0", "positive rate"),
    ("base=500,peak=100", ">= base_rps"),
    ("duration=0", "must be positive"),
    ("at=1.5", r"in \[0, 1\]"),
    ("shape=flash,at=0.9,width=0.3", r"at \+ width <= 1"),
])
def test_load_spec_rejects_malformed(bad, match):
    with pytest.raises(ValueError, match=match):
        LoadSpec.parse(bad)


def test_load_shapes_rate_curves():
    d = 10.0
    flash = LoadSpec(shape="flash", base_rps=100, peak_rps=1000,
                     duration_s=d, at=0.4, width=0.2)
    assert flash.rate(0.0) == 100 and flash.rate(3.9) == 100
    assert flash.rate(4.0) == 1000 and flash.rate(5.9) == 1000
    assert flash.rate(6.0) == 100
    assert flash.rate(-1) == 0.0 and flash.rate(d) == 0.0
    over = LoadSpec(shape="overload", base_rps=100, peak_rps=1000,
                    duration_s=d, at=0.5)
    ramp = [over.rate(t) for t in (0.0, 1.0, 2.5, 4.0)]
    assert ramp == sorted(ramp) and ramp[0] == 100  # monotone ramp
    assert over.rate(5.0) == over.rate(9.9) == 1000  # sustained hold
    di = LoadSpec(shape="diurnal", base_rps=100, peak_rps=1000,
                  duration_s=d)
    assert di.rate(0.0) == pytest.approx(100)
    assert di.rate(5.0) == pytest.approx(1000)  # peak mid-cycle
    assert 100 < di.rate(2.5) < 1000


def test_load_offsets_same_seed_same_curve():
    """The determinism pin: same seed => bitwise-identical offered
    load; different seed => a different schedule."""
    spec = LoadSpec(shape="flash", base_rps=100, peak_rps=800,
                    duration_s=4.0, at=0.5, width=0.25, seed=7)
    a, b = spec.offsets(), spec.offsets()
    np.testing.assert_array_equal(a, b)
    c = LoadSpec(shape="flash", base_rps=100, peak_rps=800,
                 duration_s=4.0, at=0.5, width=0.25, seed=8).offsets()
    assert len(a) != len(c) or (a[:len(c)] != c[:len(a)]).any()
    assert np.all(np.diff(a) >= 0)  # sorted arrivals
    assert a[0] >= 0 and a[-1] < 4.0
    # the flash window actually carries the peak: arrival density in
    # [2.0, 3.0) dwarfs the base-rate window [0.0, 1.0)
    in_flash = int(np.sum((a >= 2.0) & (a < 3.0)))
    in_base = int(np.sum(a < 1.0))
    assert in_flash > 3 * in_base


# -- the burn-rate evidence (hand-computed) ---------------------------

def test_burn_rates_hand_computed():
    clk = Clock()
    m = make_plane(clk)
    # batch: 4 bad of 20 => attainment 0.8, err 0.2, budget 0.05,
    # burn 4.0; interactive: no traffic => None, never 100%
    feed(m, n_bad=4, n_good=16, cls="batch")
    ev = SloEvaluator(m.registry, classes=CLASSES, windows_s=(60.0,))
    rec = ev.burn_rates(now=clk())
    assert rec["batch"]["total"] == 20 and rec["batch"]["good"] == 16
    assert rec["batch"]["attainment"] == pytest.approx(0.8)
    assert rec["batch"]["burn_rate"] == pytest.approx(4.0)
    assert rec["interactive"]["burn_rate"] is None
    # the window ages the evidence out
    clk.t += 120
    rec = ev.burn_rates(now=clk())
    assert rec["batch"]["burn_rate"] is None


def test_deadline_shed_counts_slo_bad_regardless_of_wait():
    """Survivorship-bias guard: a deadline-shed request lands on its
    class's deadline-miss counter and the evaluator folds it into
    attainment as SLO-BAD — a miss is bad whatever it waited, so the
    burn signal sees overload even when callers run deadlines TIGHTER
    than the class threshold (a waited-time latency sample would have
    read such a death as 'good')."""
    clk = Clock()
    m = make_plane(clk)
    # batch threshold is 500ms; these requests died at 50ms — still
    # SLO-bad, every one of them
    for _ in range(10):
        m.record_shed("deadline", slo_class="batch")
    ev = SloEvaluator(m.registry, classes=CLASSES, windows_s=(60.0,))
    rec = ev.burn_rates(now=clk())
    assert rec["batch"]["total"] == 10 and rec["batch"]["good"] == 0
    assert rec["batch"]["missed"] == 10
    assert rec["batch"]["attainment"] == 0.0
    assert m.shed_deadline == 10
    # misses COMPOSE with served samples: 10 missed + 10 served-good
    # => attainment 0.5, burn 10 (budget 0.05)
    feed(m, n_bad=0, n_good=10, good_s=0.005)
    rec = ev.burn_rates(now=clk())
    assert rec["batch"]["total"] == 20 and rec["batch"]["good"] == 10
    assert rec["batch"]["attainment"] == pytest.approx(0.5)
    assert rec["batch"]["burn_rate"] == pytest.approx(10.0)
    # evaluate() shares the same window arithmetic (one definition)
    full = ev.evaluate(now=clk())
    assert full["classes"]["batch"]["windows"]["60s"] == rec["batch"]
    # admission sheds deliberately do NOT count as misses (the
    # controller's own shedding must not feed back into its trigger)
    m.record_admission_shed("batch")
    assert ev.burn_rates(now=clk())["batch"]["missed"] == 10
    # ...and the miss evidence ages out with the window
    clk.t += 120
    assert ev.burn_rates(now=clk())["batch"]["burn_rate"] is None


def test_admission_shed_counters_and_rate():
    clk = Clock()
    m = make_plane(clk)
    for _ in range(6):
        m.record_admission_shed("batch")
    m.record_admission_shed("shadow")
    snap = m.snapshot()
    assert snap["shed_admission"] == 7 and m.shed_admission == 7
    assert snap["requests_shed_by_class"] == {"batch": 6, "shadow": 1}
    assert m.registry.lookup(SHED_CLASS_METRIC,
                             labels={"class": "batch"}).value == 6
    assert admission_shed_rate(m.registry, 10.0,
                               now=clk()) == pytest.approx(0.7)
    clk.t += 100  # rate ages out with the window
    assert admission_shed_rate(m.registry, 10.0, now=clk()) == 0.0


def test_queue_residency_family_records():
    clk = Clock()
    m = make_plane(clk)
    m.record_batch(4, 4, latencies=[0.01] * 4,
                   stage_seconds={"queue": [0.2, 0.3, 0.4, 0.5]})
    hist = m.registry.lookup(QUEUE_RESIDENCY_METRIC)
    assert hist is not None and hist.count == 4
    assert hist.percentile(95, window_s=60.0,
                           now=clk()) == pytest.approx(0.5)


# -- AdmissionController ----------------------------------------------

def make_controller(m, **kw):
    kw.setdefault("classes", CLASSES)
    kw.setdefault("shed_order", ("shadow", "batch"))
    kw.setdefault("window_s", 5.0)
    kw.setdefault("interval_s", 0.05)
    kw.setdefault("escalate_ticks", 2)
    kw.setdefault("relax_ticks", 3)
    kw.setdefault("min_window_requests", 10)
    return AdmissionController(m, **kw)


def test_controller_validates():
    m = make_plane(Clock())
    with pytest.raises(ValueError, match="shed_order"):
        AdmissionController(m, classes=CLASSES, shed_order=())
    with pytest.raises(ValueError, match="protected"):
        AdmissionController(m, classes=CLASSES,
                            shed_order=("interactive", "batch"))
    with pytest.raises(ValueError, match="positive"):
        make_controller(m, window_s=0)
    with pytest.raises(ValueError, match=">= 1"):
        make_controller(m, escalate_ticks=0)


def test_controller_escalates_one_class_at_a_time():
    """The hand-computed shed fixture: batch burn 4.0 with 400ms queue
    residency corroborating => shadow sheds after escalate_ticks,
    batch after another escalate_ticks, interactive NEVER."""
    clk = Clock()
    m = make_plane(clk)
    ctl = make_controller(m)
    feed(m, n_bad=8, n_good=12)
    assert ctl.decide(clk())["triggered"] == ["batch"]
    assert ctl.level == 0  # one tick is not escalation
    ctl.decide(clk())
    assert ctl.level == 1 and ctl.shed_classes() == ("shadow",)
    assert not ctl.admit("shadow", now=clk.t)
    assert ctl.admit("batch", now=clk.t)
    ctl.decide(clk())
    ctl.decide(clk())
    assert ctl.level == 2 and ctl.shed_classes() == ("batch", "shadow")
    assert not ctl.admit("batch", now=clk.t)
    assert ctl.admit("interactive", now=clk.t)  # protected, always
    for _ in range(10):  # escalation is BOUNDED by the shed order
        ctl.decide(clk())
    assert ctl.level == 2


def test_controller_burn_without_queue_never_sheds():
    """The corroboration gate: slow-but-served traffic with an empty
    queue is not overload — burn alone must not shed."""
    clk = Clock()
    m = make_plane(clk)
    ctl = make_controller(m)
    feed(m, n_bad=8, n_good=12, queue_s=0.001)  # 1ms queue residency
    for _ in range(6):
        d = ctl.decide(clk())
    assert d["triggered"] == ["batch"] and not d["corroborated"]
    assert ctl.level == 0 and ctl.admit("shadow", now=clk.t)


def test_controller_thin_evidence_never_sheds():
    clk = Clock()
    m = make_plane(clk)
    ctl = make_controller(m, min_window_requests=30)
    feed(m, n_bad=8, n_good=12)  # 20 < 30: not enough evidence
    for _ in range(4):
        ctl.decide(clk())
    assert ctl.level == 0


def test_controller_relaxes_slowly_with_hysteresis():
    clk = Clock()
    m = make_plane(clk)
    ctl = make_controller(m)
    feed(m, n_bad=8, n_good=12)
    for _ in range(4):
        ctl.decide(clk())
    assert ctl.level == 2
    clk.t += 10  # the bad window ages out entirely
    feed(m, n_bad=0, n_good=20, queue_s=0.001)
    ctl.decide(clk())
    ctl.decide(clk())
    assert ctl.level == 2  # 2 clean ticks < relax_ticks: still shed
    ctl.decide(clk())
    assert ctl.level == 1  # relax one LEVEL per relax_ticks
    for _ in range(3):
        ctl.decide(clk())
    assert ctl.level == 0 and ctl.shed_classes() == ()
    assert ctl.admit("batch", now=clk.t)


def test_admit_caches_by_interval():
    """admit() is the submit-path call: at most one evaluation per
    interval_s, everything between is a cached set lookup."""
    clk = Clock()
    m = make_plane(clk)
    ctl = make_controller(m, interval_s=1.0)
    for _ in range(50):
        ctl.admit("batch", now=clk.t)
    assert ctl.evaluations == 1
    clk.t += 1.1
    ctl.admit("batch", now=clk.t)
    assert ctl.evaluations == 2


# -- elastic fleet: router add/remove ---------------------------------

def test_router_add_replica_routes_and_validates():
    engine = make_engine()
    router = FailoverRouter(ReplicaSet(engine, 1), policy="round_robin")
    assert router.fleet_size() == 1
    rid = router.add_replica(Replica(1, engine))
    assert rid == 1 and router.fleet_size() == 2
    X = np.random.RandomState(0).randn(2, D).astype(np.float32)
    router.predict(X)
    router.predict(X)  # round robin reaches the new replica
    assert router.replicas[1].dispatches == 1
    assert router.replica_stats()["fleet_size"] == 2
    with pytest.raises(ValueError, match="already in the fleet"):
        router.add_replica(Replica(1, engine))
    other = make_engine()
    with pytest.raises(ValueError, match="ONE engine"):
        router.add_replica(Replica(2, other))


def test_router_remove_replica_retires_from_routing():
    engine = make_engine()
    router = FailoverRouter(ReplicaSet(engine, 3), policy="round_robin")
    router.remove_replica(1)
    assert router.fleet_size() == 2
    X = np.random.RandomState(0).randn(1, D).astype(np.float32)
    for _ in range(4):
        router.predict(X)
    assert router.replicas[0].dispatches + \
        router.replicas[1].dispatches == 4
    stats = router.replica_stats()
    assert stats["removed_replicas"] == 1
    assert set(stats["replicas"]) == {"0", "2"}
    with pytest.raises(KeyError):
        router.remove_replica(7)
    router.remove_replica(0)
    with pytest.raises(ValueError, match="last replica"):
        router.remove_replica(2)


def test_replica_service_rate_models_capacity():
    """The capacity model: a throttled replica's dispatches wait for
    the replica to come free — back-to-back work takes at least
    rows/rate end to end."""
    engine = make_engine()
    with pytest.raises(ValueError, match="positive rows/s"):
        Replica(0, engine, service_rate_rows_s=-1)
    rep = Replica(0, engine, service_rate_rows_s=200.0)
    X = np.random.RandomState(0).randn(8, D).astype(np.float32)
    t0 = time.perf_counter()
    rep.predict(X)  # reserves 40ms; returns without waiting
    rep.predict(X)  # waits for the replica to free: >= ~40ms
    rep.predict(X)  # >= ~80ms cumulative wait
    assert time.perf_counter() - t0 >= 0.08
    # rate=None replicas stay bit-identical to a bare engine call
    free = Replica(1, engine)
    np.testing.assert_array_equal(free.predict(X), engine.predict(X))


# -- Autoscaler --------------------------------------------------------

def make_scaler(router, m, clk, **kw):
    engine = router.engine
    kw.setdefault("classes", CLASSES)
    kw.setdefault("window_s", 5.0)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("up_ticks", 2)
    kw.setdefault("down_ticks", 3)
    kw.setdefault("cooldown_s", 1.0)
    kw.setdefault("scale_down_burn", 0.25)
    kw.setdefault("min_window_requests", 10)
    return Autoscaler(router, lambda rid: Replica(rid, engine), m,
                      clock=clk, **kw)


def test_autoscaler_validates():
    engine = make_engine()
    router = FailoverRouter(ReplicaSet(engine, 1))
    m = make_plane(Clock())
    with pytest.raises(ValueError, match="hysteresis"):
        make_scaler(router, m, Clock(), scale_down_burn=1.5)
    with pytest.raises(ValueError, match="min_replicas"):
        make_scaler(router, m, Clock(), min_replicas=0)
    with pytest.raises(ValueError, match=">= 1"):
        make_scaler(router, m, Clock(), up_ticks=0)


def test_autoscaler_scales_up_under_flash_crowd():
    """The flash-crowd pin, clock-driven: clean traffic holds, the
    burn spike scales up after up_ticks (cooldown gating each step)
    up to max_replicas and never past."""
    clk = Clock()
    m = make_plane(clk)
    engine = make_engine()
    router = FailoverRouter(ReplicaSet(engine, 1),
                            policy="round_robin")
    asc = make_scaler(router, m, clk, max_replicas=3)
    feed(m, n_bad=0, n_good=20, queue_s=0.001)
    for _ in range(5):
        assert asc.tick(clk())["action"] == "hold"
    assert router.fleet_size() == 1
    # the crowd arrives: burn 4.0, 400ms queue residency
    clk.t += 1
    feed(m, n_bad=8, n_good=12)
    assert asc.tick(clk())["action"] == "hold"  # tick 1 of up_ticks=2
    rec = asc.tick(clk())
    assert rec["action"] == "up" and router.fleet_size() == 2
    assert rec["attach_ms"] >= 0 and rec["replica_id"] == 1
    # cooldown holds the next step
    clk.t += 0.2
    asc.tick(clk())
    asc.tick(clk())
    assert router.fleet_size() == 2
    clk.t += 1.0  # cooldown over; evidence still burning
    asc.tick(clk())
    asc.tick(clk())
    assert router.fleet_size() == 3 and asc.scale_ups == 2
    clk.t += 1.0  # max-fleet bound: never past max_replicas
    for _ in range(6):
        asc.tick(clk())
    assert router.fleet_size() == 3
    assert [e["action"] for e in asc.events] == ["up", "up"]


def test_autoscaler_shed_rate_alone_scales_up():
    """Policy-shed traffic IS unserved demand: once the controller
    sheds, the served remainder looks healthy — the shed-rate signal
    must scale the fleet without waiting for burn or queue to re-age."""
    clk = Clock()
    m = make_plane(clk)
    engine = make_engine()
    router = FailoverRouter(ReplicaSet(engine, 1))
    asc = make_scaler(router, m, clk, up_ticks=1)
    m.record_admission_shed("batch")
    rec = asc.tick(clk())
    assert rec["action"] == "up" and rec["shed_rate"] > 0
    assert router.fleet_size() == 2


def test_autoscaler_scales_down_with_hysteresis_and_floor():
    clk = Clock()
    m = make_plane(clk)
    engine = make_engine()
    router = FailoverRouter(ReplicaSet(engine, 1))
    asc = make_scaler(router, m, clk, up_ticks=1, down_ticks=3)
    feed(m, n_bad=8, n_good=12)
    asc.tick(clk())
    clk.t += 2
    feed(m, n_bad=8, n_good=12)
    asc.tick(clk())
    assert router.fleet_size() == 3
    # quiet: the bad window ages out entirely, no sheds, no queue
    clk.t += 20
    assert asc.tick(clk())["action"] == "hold"  # quiet tick 1
    asc.tick(clk())
    assert router.fleet_size() == 3  # 2 quiet ticks < down_ticks
    rec = asc.tick(clk())
    assert rec["action"] == "down" and router.fleet_size() == 2
    assert rec["replica_id"] == 2  # last added goes first
    clk.t += 2  # cooldown, then the remaining added replica
    for _ in range(3):
        asc.tick(clk())
    assert router.fleet_size() == 1 and asc.scale_downs == 2
    # the floor: the founding replica is never the autoscaler's to take
    clk.t += 5
    for _ in range(8):
        asc.tick(clk())
    assert router.fleet_size() == 1


def test_autoscaler_dead_band_holds():
    """Burn between the down and up thresholds is the hysteresis dead
    band: no action, ever — the no-flap pin."""
    clk = Clock()
    m = make_plane(clk)
    engine = make_engine()
    router = FailoverRouter(ReplicaSet(engine, 1))
    asc = make_scaler(router, m, clk, up_ticks=1, down_ticks=2,
                      scale_up_burn=1.0, scale_down_burn=0.25)
    # batch: 1 bad of 20 => burn 1.0 — NOT > up threshold, not < 0.25
    feed(m, n_bad=1, n_good=19)
    for _ in range(10):
        assert asc.tick(clk())["action"] == "hold"
    assert asc.events == [] and router.fleet_size() == 1


def test_autoscaler_replica_seconds_integral():
    clk = Clock()
    m = make_plane(clk)
    engine = make_engine()
    router = FailoverRouter(ReplicaSet(engine, 2))
    asc = make_scaler(router, m, clk, up_ticks=1)
    clk.t += 10  # 2 replicas for 10s
    assert asc.replica_seconds(clk()) == pytest.approx(20.0)
    feed(m, n_bad=8, n_good=12)
    asc.tick(clk())  # -> 3 replicas at t+10
    clk.t += 5  # 3 replicas for 5s
    assert asc.replica_seconds(clk()) == pytest.approx(35.0)


def test_overload_rejection_is_class_attributed():
    """A max_queue rejection is a door shed like an admission shed:
    it must land on the per-class shed family (the autoscaler's
    capacity-shortfall signal), not vanish into a classless counter
    while the survivors read healthy."""
    engine = make_engine()
    with ServingService(engine, max_queue=0) as svc:
        x = np.random.RandomState(0).randn(1, D).astype(np.float32)
        from fedamw_tpu.serving import Overloaded

        with pytest.raises(Overloaded):
            svc.submit(x, slo_class="interactive")
        snap = svc.metrics.snapshot(engine)
    assert snap["shed_overload"] == 1
    assert snap["requests_shed_by_class"] == {"interactive": 1}
    assert admission_shed_rate(svc.metrics.registry, 60.0) > 0


def test_autoscaler_forgets_externally_removed_replica():
    """An operator removing the autoscaler's replica out from under
    it must not wedge scale-in forever: the KeyError prunes the stale
    id and the next quiet period removes the remaining added one."""
    clk = Clock()
    m = make_plane(clk)
    engine = make_engine()
    router = FailoverRouter(ReplicaSet(engine, 1))
    asc = make_scaler(router, m, clk, up_ticks=1, down_ticks=1,
                      cooldown_s=0.0)
    feed(m, n_bad=8, n_good=12)
    asc.tick(clk())
    clk.t += 2
    feed(m, n_bad=8, n_good=12)
    asc.tick(clk())
    assert router.fleet_size() == 3
    router.remove_replica(2)  # the operator takes the last-added one
    clk.t += 20  # quiet: everything aged out
    rec = asc.tick(clk())
    assert rec["action"] == "error" and asc.errors == 1
    rec = asc.tick(clk())  # the stale id is forgotten: shrink works
    assert rec["action"] == "down" and rec["replica_id"] == 1
    assert router.fleet_size() == 1


def test_autoscaler_factory_error_counted_not_fatal():
    clk = Clock()
    m = make_plane(clk)
    engine = make_engine()
    router = FailoverRouter(ReplicaSet(engine, 1))

    def boom(rid):
        raise RuntimeError("artifact missing")

    asc = Autoscaler(router, boom, m, classes=CLASSES, window_s=5.0,
                     up_ticks=1, scale_down_burn=0.25, clock=clk,
                     min_window_requests=10)
    feed(m, n_bad=8, n_good=12)
    rec = asc.tick(clk())
    assert rec["action"] == "error" and asc.errors == 1
    assert router.fleet_size() == 1


# -- deadline scheduling (EDF) ----------------------------------------

class _R:
    def __init__(self, deadline, t_submit):
        self.deadline = deadline
        self.t_submit = t_submit


def test_edf_order_pure():
    a = _R(5.0, 1.0)
    b = _R(2.0, 2.0)
    c = _R(None, 0.5)
    d = _R(2.0, 1.5)
    out = edf_order([a, b, c, d])
    # soonest deadline first; FIFO among equals; no-deadline last
    assert out == [d, b, a, c]
    # all-deadline-free: byte-identical FIFO (the clean-load path)
    e, f = _R(None, 1.0), _R(None, 2.0)
    assert edf_order([e, f]) == [e, f]
    assert edf_order([f, e]) == [e, f]


class _SlowFirstEngine:
    """Engine front whose FIRST dispatch stalls — the window in which
    the EDF test queues its out-of-order-deadline requests."""

    def __init__(self, engine, stall_s=0.25):
        self._engine = engine
        self._stall = stall_s
        self._calls = 0
        self.buckets = (1, 2)
        self.input_dim = engine.input_dim

    def predict(self, X, **kw):
        self._calls += 1
        if self._calls == 1:
            time.sleep(self._stall)
        return self._engine.predict(X, **kw)


def test_service_dispatches_soonest_deadline_first_under_pressure():
    """Three queued requests against a 2-row ladder: the worker must
    serve the two soonest deadlines and defer the most patient, in
    deadline order — not arrival order."""
    engine = make_engine()
    front = _SlowFirstEngine(engine)
    order, lock = [], threading.Lock()

    def tag(name):
        def cb(fut):
            with lock:
                order.append(name)
        return cb

    x = np.random.RandomState(0).randn(1, D).astype(np.float32)
    with ServingService(front, max_queue=64) as svc:
        first = svc.submit(x, timeout_s=30.0)
        first.add_done_callback(tag("first"))
        time.sleep(0.05)  # the worker is inside the stalled dispatch
        # arrival order is the REVERSE of deadline order
        for name, to in (("patient", 20.0), ("mid", 10.0),
                         ("urgent", 5.0)):
            svc.submit(x, timeout_s=to).add_done_callback(tag(name))
        time.sleep(0.02)
        deadline = time.time() + 10
        while len(order) < 4 and time.time() < deadline:
            time.sleep(0.01)
    # dispatch 2 carries [urgent, mid] (2-row cap), "patient" defers
    assert order[0] == "first"
    assert order.index("urgent") < order.index("patient")
    assert order.index("mid") < order.index("patient")


def test_edf_aging_bounds_deferral_of_deadline_free_requests():
    """Starvation guard: pure EDF sorts a deadline-FREE request last
    every cycle, and a sustained deadline'd stream would defer it
    forever. Aging (EDF_MAX_DEFERRALS) exempts it to the front after
    a bounded number of deferrals — it must resolve well before the
    deadline'd tail, not after it."""
    engine = make_engine(buckets=(1,))  # one row per dispatch
    order, lock = [], threading.Lock()

    def tag(name):
        def cb(fut):
            with lock:
                order.append(name)
        return cb

    x = np.random.RandomState(0).randn(1, D).astype(np.float32)
    with ServingService(engine, max_queue=256) as svc:
        # a pre-queued pressure train, then the deadline-free request,
        # then MORE deadline'd traffic behind it: every cycle's EDF
        # window holds a sooner deadline than "free"'s (none)
        for i in range(10):
            svc.submit(x, timeout_s=30.0).add_done_callback(
                tag(f"a{i}"))
        free = svc.submit(x)  # no deadline: pure EDF would starve it
        free.add_done_callback(tag("free"))
        for i in range(15):
            svc.submit(x, timeout_s=30.0).add_done_callback(
                tag(f"b{i}"))
        free.result(timeout=30)
        deadline = time.time() + 20
        while len(order) < 26 and time.time() < deadline:
            time.sleep(0.01)
    assert len(order) == 26
    # bounded deferral: "free" dispatched within EDF_MAX_DEFERRALS-ish
    # cycles of the deadline'd traffic overtaking it — NOT last
    assert order.index("free") < order.index("b10")


# -- the typed shed outcome through the service -----------------------

class _StubAdmission:
    """Duck-typed controller: sheds exactly the named classes —
    isolates the service wiring from the controller's dynamics."""

    def __init__(self, shed):
        self.shed = set(shed)

    def admit(self, slo_class, now=None):
        return slo_class not in self.shed


def test_admission_shed_resolves_future_typed_with_span():
    engine = make_engine()
    tracer = Tracer()
    with ServingService(engine, tracer=tracer,
                        admission=_StubAdmission({"batch"})) as svc:
        x = np.random.RandomState(0).randn(2, D).astype(np.float32)
        shed_fut = svc.submit(x, slo_class="batch")
        ok_fut = svc.submit(x, slo_class="interactive")
        # the shed future is ALREADY resolved, with the typed error —
        # not Overloaded, not DeadlineExceeded
        with pytest.raises(AdmissionShed, match="batch"):
            shed_fut.result(timeout=0)
        ok_fut.result(timeout=30)
        snap = svc.metrics.snapshot(engine)
    assert snap["shed_admission"] == 1
    assert snap["requests_shed_by_class"] == {"batch": 1}
    assert snap["shed_deadline"] == 0  # NOT the deadline path
    assert snap["requests"] == 1  # the interactive one served
    # exactly one span per submitted id — the shed one included, with
    # the shed annotation naming class and policy
    spans = {s["trace_id"]: s for s in tracer.records()
             if s["name"] == "request"}
    assert set(spans) == {shed_fut.request_id, ok_fut.request_id}
    assert spans[shed_fut.request_id]["attrs"]["outcome"] == "shed"
    assert spans[ok_fut.request_id]["attrs"]["outcome"] == "ok"
    ann = [s for s in tracer.records() if s["name"] == "shed"]
    assert len(ann) == 1
    assert ann[0]["trace_id"] == shed_fut.request_id
    assert ann[0]["attrs"]["slo_class"] == "batch"
    assert ann[0]["attrs"]["policy"] == "admission"


def test_interactive_protected_under_sustained_overload():
    """The end-to-end protection pin (real time): a throttled fleet
    offered ~2x its capacity with the controller attached — batch
    sheds (policy, counted per class), interactive attainment stays
    far above batch's, nothing is lost, every accepted request
    resolves typed."""
    engine = make_engine()
    metrics = ServeMetrics(registry=Registry())
    classes = (SloClass("interactive", threshold_ms=150.0,
                        objective=0.8),
               SloClass("batch", threshold_ms=400.0, objective=0.5))
    ctl = AdmissionController(
        metrics, classes=classes, shed_order=("batch",),
        window_s=0.5, burn_threshold=1.0, min_window_requests=6,
        queue_floor_ms=40.0, interval_s=0.01, escalate_ticks=1,
        relax_ticks=40)
    router = FailoverRouter(
        ReplicaSet(engine, 1, service_rate_rows_s=400.0),
        policy="round_robin", registry=metrics.registry)
    spec = LoadSpec(shape="overload", base_rps=40, peak_rps=160,
                    duration_s=2.0, at=0.3, seed=5)
    offsets = spec.offsets()
    rng = np.random.RandomState(3)
    pay = {1: rng.randn(1, D).astype(np.float32),
           8: rng.randn(8, D).astype(np.float32)}
    mix = [("interactive", 1, 0.4), ("batch", 8, 1.5)]
    outcomes = {"interactive": [], "batch": []}
    with ServingService(router, metrics=metrics, max_queue=4096,
                        admission=ctl) as svc:
        t0 = time.perf_counter()
        futs = []
        for i, off in enumerate(offsets):
            lag = t0 + off - time.perf_counter()
            if lag > 0:
                time.sleep(lag)
            cls, rows, to = mix[i % len(mix)]
            futs.append((cls, time.perf_counter(),
                         svc.submit(pay[rows], timeout_s=to,
                                    slo_class=cls)))
        for cls, t_sub, f in futs:
            try:
                f.result(timeout=60)
                outcomes[cls].append("ok")
            except AdmissionShed:
                outcomes[cls].append("shed")
            except Exception as e:
                outcomes[cls].append(type(e).__name__)
        snap = metrics.snapshot(router)
    allowed = {"ok", "shed", "DeadlineExceeded"}
    assert all(o in allowed
               for recs in outcomes.values() for o in recs)  # no loss
    # batch was policy-shed; interactive never was
    assert snap["requests_shed_by_class"].get("batch", 0) >= 1
    assert "interactive" not in snap["requests_shed_by_class"]
    ok_rate = {cls: recs.count("ok") / len(recs)
               for cls, recs in outcomes.items()}
    # the protected class keeps serving while batch is traded away
    assert ok_rate["interactive"] > ok_rate["batch"]
    assert ok_rate["interactive"] >= 0.8


# -- per-class deadline defaults (ISSUE 15 satellite) ------------------

class _WedgedEngine:
    """Engine whose dispatch stalls far past any class deadline —
    what a class-implied timeout must protect callers from."""

    def __init__(self, stall_s=5.0):
        self.buckets = (1, 8)
        self.input_dim = D
        self.num_classes = C
        self.version = 0
        self.compile_count = 0
        self.stall_s = stall_s

    def predict(self, X, version=None, record_timings=True):
        time.sleep(self.stall_s)
        return np.zeros((np.atleast_2d(X).shape[0], C), np.float32)


def test_slo_class_owns_a_default_timeout():
    # explicit wins; unset derives 4x the threshold — the vocabulary
    # owns the number either way
    c = SloClass("interactive", threshold_ms=50.0, objective=0.9,
                 default_timeout_s=0.75)
    assert c.timeout_s() == 0.75
    d = SloClass("batch", threshold_ms=500.0, objective=0.9)
    assert d.timeout_s() == pytest.approx(2.0)
    with pytest.raises(ValueError, match="default_timeout_s"):
        SloClass("x", threshold_ms=10.0, default_timeout_s=0.0)


def test_class_deadline_applies_without_hand_picked_timeout():
    """The satellite's whole point: a submit that names its class but
    no timeout gets the class deadline — observable as a
    DeadlineExceeded against a wedged engine, where the pre-ISSUE-15
    behavior would hang the caller for the full stall."""
    from fedamw_tpu.serving import DeadlineExceeded

    classes = (SloClass("interactive", threshold_ms=50.0,
                        objective=0.9, default_timeout_s=0.2),)
    engine = _WedgedEngine(stall_s=1.0)
    with ServingService(engine, slo_classes=classes) as svc:
        x = np.zeros((1, D), np.float32)
        # head request occupies the engine for the full stall...
        head = svc.submit(x, slo_class="interactive")
        time.sleep(0.1)  # let the worker dequeue it and wedge
        # ...so the second ages in the queue past its CLASS deadline
        # (0.2s) — no timeout_s hand-picked anywhere
        fut = svc.submit(x, slo_class="interactive")
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=30)
        assert head.result(timeout=30).shape == (1, C)


def test_explicit_timeout_wins_over_class_default():
    classes = (SloClass("interactive", threshold_ms=50.0,
                        objective=0.9, default_timeout_s=0.05),)
    engine = _WedgedEngine(stall_s=0.3)
    with ServingService(engine, slo_classes=classes) as svc:
        x = np.zeros((1, D), np.float32)
        # the caller's explicit, LONGER deadline overrides the tiny
        # class default: the request survives the stall
        out = svc.submit(x, slo_class="interactive",
                         timeout_s=30.0).result(timeout=30)
        assert out.shape == (1, C)


def test_unknown_class_and_no_vocabulary_stay_deadline_free():
    # outside the vocabulary (and with no vocabulary at all), nothing
    # changes: no implied deadline, the pre-ISSUE-15 behavior
    classes = (SloClass("interactive", threshold_ms=50.0,
                        objective=0.9, default_timeout_s=0.05),)
    engine = _WedgedEngine(stall_s=0.3)
    with ServingService(engine, slo_classes=classes) as svc:
        x = np.zeros((1, D), np.float32)
        out = svc.submit(x, slo_class="bulk").result(timeout=30)
        assert out.shape == (1, C)
    with ServingService(engine) as svc:
        out = svc.submit(x, slo_class="interactive").result(timeout=30)
        assert out.shape == (1, C)
