import numpy as np
import pytest

from fedamw_tpu.data import (
    canonicalize_labels,
    load_dataset,
    load_svmlight,
    pack_partitions,
    split_train_val,
    synthetic_classification,
)


class TestCanonicalizeLabels:
    def test_binary_pm1(self):
        y = canonicalize_labels(np.array([-1.0, 1.0, -1.0, 1.0]), "a9a")
        np.testing.assert_array_equal(y, [0, 1, 0, 1])
        assert y.dtype == np.int32

    def test_binary_12(self):
        y = canonicalize_labels(np.array([1.0, 2.0, 2.0]), "whatever")
        np.testing.assert_array_equal(y, [0, 1, 1])

    def test_multiclass_shift(self):
        y = canonicalize_labels(np.array([1.0, 3.0, 7.0]), "satimage")
        np.testing.assert_array_equal(y, [0, 2, 6])

    def test_regression_minmax_100(self):
        y = canonicalize_labels(np.array([2.0, 4.0, 6.0]), "abalone")
        np.testing.assert_allclose(y, [0.0, 50.0, 100.0])
        assert y.dtype == np.float32

    def test_regression_test_split_suffix(self):
        # '.t' files must canonicalize like their train split (the torch
        # reference mangles regression test labels here).
        y = canonicalize_labels(np.array([2.0, 4.0, 6.0]), "cadata.t")
        np.testing.assert_allclose(y, [0.0, 50.0, 100.0])
        assert y.dtype == np.float32


def test_svmlight_roundtrip(tmp_path):
    path = tmp_path / "toy"
    path.write_text("3 1:0.5 4:1.5\n1 2:2.0\n2 1:-1.0 4:0.25\n")
    X, y = load_svmlight("toy", str(tmp_path))
    assert X.shape == (3, 4)
    np.testing.assert_allclose(X[0], [0.5, 0, 0, 1.5])
    np.testing.assert_array_equal(y, [2, 0, 1])  # shifted multiclass


class TestPack:
    def test_shapes_and_mask(self):
        parts = [np.array([3, 1, 4]), np.array([5]), np.array([9, 2])]
        pack = pack_partitions(parts)
        assert pack.idx.shape == (3, 3)
        np.testing.assert_array_equal(pack.sizes, [3, 1, 2])
        np.testing.assert_array_equal(pack.mask.sum(axis=1), [3, 1, 2])
        np.testing.assert_array_equal(pack.idx[1], [5, 0, 0])

    def test_weights(self):
        pack = pack_partitions([np.arange(3), np.arange(1)])
        np.testing.assert_allclose(pack.weights, [0.75, 0.25])

    def test_pad_clients(self):
        pack = pack_partitions([np.arange(3), np.arange(2)], pad_clients_to=4)
        assert pack.num_clients == 4
        assert pack.mask[2:].sum() == 0
        assert pack.weights[2:].sum() == 0

    def test_n_max_too_small(self):
        with pytest.raises(ValueError):
            pack_partitions([np.arange(5)], n_max=3)


def test_split_train_val_partition():
    rng = np.random.RandomState(0)
    parts = [np.arange(0, 40), np.arange(40, 100)]
    train_parts, val_idx = split_train_val(parts, 0.2, rng)
    assert len(val_idx) == 8 + 12
    combined = np.sort(np.concatenate(train_parts + [val_idx]))
    np.testing.assert_array_equal(combined, np.arange(100))
    # val comes only from each client's own shard
    assert set(val_idx[:8]).issubset(set(range(40)))


def test_synthetic_classification_signature():
    X, y, Xt, yt = synthetic_classification(1000, 36, 6, seed=1)
    assert X.shape == (1000, 36) and Xt.shape == (250, 36)
    assert set(np.unique(y)).issubset(set(range(6)))
    # learnable: clusters separate classes better than chance
    assert X.dtype == np.float32 and y.dtype == np.int32


def test_load_dataset_digits():
    ds = load_dataset("digits", num_partitions=5, alpha=0.5)
    assert ds.source == "sklearn"
    assert ds.d == 64 and ds.num_classes == 10
    assert ds.num_partitions == 5
    assert min(len(p) for p in ds.parts) >= 10
    total = sum(len(p) for p in ds.parts)
    assert total == len(ds.y_train)


def test_load_dataset_synthetic_fallback():
    ds = load_dataset("satimage", num_partitions=4, alpha=1.0)
    assert ds.source == "synthetic"
    assert ds.d == 36 and ds.num_classes == 6


def test_generate_synthetic_lognormal_sizes():
    from fedamw_tpu.data import generate_synthetic

    X, y, Xt, yt, dh, mh = generate_synthetic(
        0.5, 0.5, 4, 0, 3, rng=np.random.RandomState(0)
    )
    assert X.shape[0] == 3 and X.shape[2] == 4
    assert y.shape == X.shape[:2]


def test_load_dataset_iid():
    ds = load_dataset("digits", num_partitions=4, alpha=-1,
                      rng=np.random.RandomState(5))
    sizes = [len(p) for p in ds.parts]
    assert max(sizes) - min(sizes) <= 1
