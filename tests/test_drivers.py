"""Driver smoke tests: exp.py and tune.py run end-to-end as subprocesses."""

import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, cwd):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + \
        " --xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO
    return subprocess.run(
        [sys.executable] + args, cwd=cwd, env=env,
        capture_output=True, text=True, timeout=600,
    )


@pytest.mark.parametrize("backend", ["jax", "torch"])
def test_exp_driver(tmp_path, backend):
    out = _run(
        [os.path.join(REPO, "exp.py"), "--dataset", "digits",
         "--backend", backend, "--D", "128", "--num_partitions", "4",
         "--round", "3", "--local_epoch", "1",
         "--result_dir", str(tmp_path)],
        cwd=str(tmp_path),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    with open(tmp_path / "exp1_digits.pkl", "rb") as f:
        data = pickle.load(f)
    # reference result schema (exp.py:132-143)
    assert data["name"] == ["CL", "DL", "FedAMW_OneShot", "FedAvg",
                            "FedProx", "FedAMW"]
    assert data["train_loss"].shape == (6, 3, 1)
    assert data["test_acc"].shape == (6, 3, 1)
    assert data["heterogeneity"].shape == (1,)
    assert np.all(np.isfinite(data["test_acc"]))


def test_exp_driver_publish_every_segments_equal_full_run(tmp_path):
    """--publish_every N (ISSUE 6): the segmented publishing loop's
    stitched metrics equal the uninterrupted run's, a servable
    checkpoint lands at every boundary (round marker, eval_acc for
    the rollout parity gate, the RFF draw), and the versions are
    registry-ingestible."""
    common = [os.path.join(REPO, "exp.py"), "--dataset", "digits",
              "--D", "128", "--num_partitions", "4", "--round", "4",
              "--local_epoch", "1"]
    plain = _run(common + ["--result_dir", str(tmp_path / "plain")],
                 cwd=str(tmp_path))
    assert plain.returncode == 0, plain.stderr[-2000:]
    pub = _run(common + ["--result_dir", str(tmp_path / "pub"),
                         "--save_models", str(tmp_path / "models"),
                         "--publish_every", "2"],
               cwd=str(tmp_path))
    assert pub.returncode == 0, pub.stderr[-2000:]
    with open(tmp_path / "plain" / "exp1_digits.pkl", "rb") as f:
        want = pickle.load(f)
    with open(tmp_path / "pub" / "exp1_digits.pkl", "rb") as f:
        got = pickle.load(f)
    # segmented == uninterrupted, for every algorithm and metric
    np.testing.assert_array_equal(got["test_acc"], want["test_acc"])
    np.testing.assert_array_equal(got["train_loss"], want["train_loss"])
    # one publishable version per boundary, self-contained for serving
    for name in ("FedAvg", "FedProx", "FedAMW"):
        base = tmp_path / "models" / f"digits_{name}_repeat0"
        assert (base / "v0002").is_dir() and (base / "v0004").is_dir()
    from fedamw_tpu.serving import ModelRegistry

    reg = ModelRegistry()
    v1 = reg.publish_checkpoint(
        str(tmp_path / "models" / "digits_FedAvg_repeat0" / "v0002"))
    v2 = reg.publish_checkpoint(
        str(tmp_path / "models" / "digits_FedAvg_repeat0" / "v0004"))
    assert reg.get(v1).round_idx == 2 and reg.get(v2).round_idx == 4
    assert reg.get(v2).eval_acc is not None
    assert reg.get(v2).rff is not None
    assert reg.staleness_rounds(v1) == 2


def test_exp_driver_publish_every_validation():
    out = _run([os.path.join(REPO, "exp.py"), "--dataset", "digits",
                "--publish_every", "2"], cwd=REPO)
    assert out.returncode != 0
    assert "--save_models" in out.stderr
    out = _run([os.path.join(REPO, "exp.py"), "--dataset", "digits",
                "--publish_every", "2", "--save_models", "/tmp/x",
                "--faults", "drop=0.1"], cwd=REPO)
    assert out.returncode != 0
    assert "clean path" in out.stderr


def test_tune_driver_standalone(tmp_path):
    out = _run(
        [os.path.join(REPO, "tune.py"), "--dataset", "digits",
         "--D", "128", "--round", "3", "--local_epoch", "1",
         "--lr_p", "0.001", "--lambda_reg", "0.00005"],
        cwd=str(tmp_path),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "FedAMW final" in out.stdout


def test_exp_driver_extension_flags(tmp_path):
    """--participation/--server_opt apply to FedAvg/FedProx only;
    FedAMW runs the reference protocol and the run must complete with
    the same result schema."""
    out = _run(
        [os.path.join(REPO, "exp.py"), "--dataset", "digits",
         "--backend", "jax", "--D", "128", "--num_partitions", "4",
         "--round", "3", "--local_epoch", "1",
         "--participation", "0.6", "--server_opt", "adam",
         "--server_lr", "0.1", "--result_dir", str(tmp_path)],
        cwd=str(tmp_path),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "extensions on FedAvg/FedProx" in out.stdout
    with open(tmp_path / "exp1_digits.pkl", "rb") as f:
        data = pickle.load(f)
    assert data["test_acc"].shape == (6, 3, 1)


def test_exp_driver_defense_and_feature_dtype(tmp_path):
    """One jax driver run exercising the ISSUE 3 surfaces together:
    --faults + --robust_agg (defense telemetry printed per algorithm)
    and --feature_dtype + --save_models (the narrow-feature marker
    reaches the serving checkpoint, closing the ROADMAP plumbing
    item)."""
    ck = tmp_path / "models"
    out = _run(
        [os.path.join(REPO, "exp.py"), "--dataset", "digits",
         "--backend", "jax", "--D", "128", "--num_partitions", "4",
         "--round", "2", "--local_epoch", "1",
         "--faults", "corrupt=0.25:scale:20,seed=3",
         "--robust_agg", "quarantine:5+mkrum:3",
         "--feature_dtype", "bfloat16",
         "--save_models", str(ck), "--result_dir", str(tmp_path)],
        cwd=str(tmp_path),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "faults:" in out.stdout            # fault report line
    assert "defense [quarantine:5.0+mkrum:3]" in out.stdout
    assert "krum picks" in out.stdout
    with open(tmp_path / "exp1_digits.pkl", "rb") as f:
        data = pickle.load(f)
    assert np.all(np.isfinite(data["test_acc"]))
    # the checkpoint is self-contained for bf16-parity serving
    from fedamw_tpu.utils.checkpoint import load_checkpoint
    state = load_checkpoint(str(ck / "digits_FedAMW_repeat0"))
    assert str(state["feature_dtype"]) == "bfloat16"
    assert "rff_W" in state


def test_exp_driver_feature_dtype_rejected_on_torch():
    out = _run([os.path.join(REPO, "exp.py"), "--dataset", "digits",
                "--backend", "torch", "--feature_dtype", "bfloat16"],
               cwd=REPO)
    assert out.returncode != 0
    assert "--feature_dtype is a jax-backend extension" in out.stderr


def test_results_report_regression_mode():
    """Regression artifacts (acc==0 everywhere; fedcore/evaluate.py)
    are rendered as a final-test-MSE table with best = LOWEST loss and
    the reference t-test applied on the negated (higher-is-better)
    values — the classification path stays argmax-on-accuracy."""
    import results_report as rr

    names = ["CL", "DL", "FedAMW_OneShot", "FedAvg", "FedProx", "FedAMW"]
    rng = np.random.RandomState(0)
    loss = np.abs(rng.randn(6, 4, 5)) + 1.0
    loss[5] = 0.01  # FedAMW: clearly lowest MSE
    res = {
        "name": names,
        "train_loss": loss,
        "test_loss": loss,
        "test_acc": np.zeros((6, 4, 5)),
        "heterogeneity": np.zeros(5),
        "epochs": 4,
    }
    assert rr.is_regression(res)
    md = rr.render_markdown(res)
    assert "final test MSE" in md
    best_rows = [ln for ln in md.splitlines() if "**best**" in ln]
    assert len(best_rows) == 1 and best_rows[0].startswith("| FedAMW ")
    # a clearly-worse constant row is flagged by the t-test
    dl_row = [ln for ln in md.splitlines() if ln.startswith("| DL ")][0]
    assert "significantly worse" in dl_row

    # the recorded task key (round-4 advisor) beats metric inference:
    # a fully-degenerate classification run (all-zero accuracy) must
    # render as an accuracy table, not a regression MSE table
    res["task"] = "classification"
    assert not rr.is_regression(res)
    assert "final test acc" in rr.render_markdown(res)
    res["task"] = "regression"
    assert rr.is_regression(res)
    del res["task"]

    res["test_acc"] = np.full((6, 4, 5), 50.0)
    res["test_acc"][0] = 99.0  # CL best on accuracy
    assert not rr.is_regression(res)
    md = rr.render_markdown(res)
    assert "final test acc" in md
    best_rows = [ln for ln in md.splitlines() if "**best**" in ln]
    assert len(best_rows) == 1 and best_rows[0].startswith("| CL ")


def test_exp_driver_sharded_matches_unsharded(tmp_path):
    """--shard N runs the driver's client axis over an N-device mesh
    (the test env is an 8-device virtual CPU mesh) and must reproduce
    the unsharded run: losses to float noise; accuracies may flip by
    single test samples when 1e-5-level logit noise crosses a decision
    boundary (digits test split here is 180 samples -> one flip is
    0.56 acc points)."""
    common = [os.path.join(REPO, "exp.py"), "--dataset", "digits",
              "--D", "128", "--num_partitions", "12", "--round", "3",
              "--local_epoch", "1"]
    outs = {}
    for name, extra in (("sharded", ["--shard", "8"]), ("plain", [])):
        d = tmp_path / name
        d.mkdir()
        out = _run(common + ["--result_dir", str(d)] + extra, cwd=str(d))
        assert out.returncode == 0, out.stderr[-2000:]
        with open(d / "exp1_digits.pkl", "rb") as f:
            outs[name] = pickle.load(f)
        if name == "sharded":
            assert "sharded over 8 devices" in out.stdout
    for k in ("train_loss", "test_loss"):
        np.testing.assert_allclose(outs["sharded"][k], outs["plain"][k],
                                   atol=1e-3)
    np.testing.assert_allclose(outs["sharded"]["test_acc"],
                               outs["plain"]["test_acc"], atol=1.5)


def test_exp_driver_shard_flag_validation():
    out = _run([os.path.join(REPO, "exp.py"), "--dataset", "digits",
                "--shard", "8", "--backend", "torch"], cwd=REPO)
    assert out.returncode != 0 and "--shard requires" in out.stderr
    out = _run([os.path.join(REPO, "exp.py"), "--dataset", "digits",
                "--shard", "8", "--sequential"], cwd=REPO)
    assert out.returncode != 0 and "incompatible" in out.stderr


def test_exp_driver_resume(tmp_path):
    """--resume: repeat-level preemption durability. A 1-repeat run
    leaves a config-signed partial; rerunning with --n_repeats 2
    --resume skips the finished repeat and the final artifact is
    bit-exact vs an uninterrupted 2-repeat run (repeats are
    independent — each reseeds from seed+t). A config mismatch is an
    error, not a silent mix."""
    common = [os.path.join(REPO, "exp.py"), "--dataset", "digits",
              "--D", "96", "--num_partitions", "6", "--round", "2",
              "--local_epoch", "1"]
    d1, d2 = tmp_path / "a", tmp_path / "b"
    d1.mkdir(), d2.mkdir()
    out = _run(common + ["--n_repeats", "1", "--result_dir", str(d1)],
               cwd=str(d1))
    assert out.returncode == 0, out.stderr[-2000:]
    assert (d1 / "exp1_digits.partial.pkl").exists()
    out = _run(common + ["--n_repeats", "2", "--resume",
                         "--result_dir", str(d1)], cwd=str(d1))
    assert out.returncode == 0, out.stderr[-2000:]
    assert "continuing at repeat 1" in out.stdout
    assert "[repeat 0]" not in out.stdout  # finished repeat skipped
    out = _run(common + ["--n_repeats", "2", "--result_dir", str(d2)],
               cwd=str(d2))
    assert out.returncode == 0, out.stderr[-2000:]
    with open(d1 / "exp1_digits.pkl", "rb") as f:
        resumed = pickle.load(f)
    with open(d2 / "exp1_digits.pkl", "rb") as f:
        straight = pickle.load(f)
    for k in ("train_loss", "test_loss", "test_acc", "heterogeneity"):
        np.testing.assert_array_equal(resumed[k], straight[k])
    # config mismatch refuses to mix
    out = _run(common[:3] + ["--D", "64"] + common[5:]
               + ["--n_repeats", "2", "--resume", "--result_dir", str(d1)],
               cwd=str(d1))
    assert out.returncode != 0
    assert "different configuration" in out.stderr


def test_exp_driver_fresh_run_backs_up_partial(tmp_path):
    """A run WITHOUT --resume must not clobber an existing partial (the
    durable progress of a preempted run): it is set aside as .bak with
    a warning."""
    common = [os.path.join(REPO, "exp.py"), "--dataset", "digits",
              "--D", "96", "--num_partitions", "6", "--round", "2",
              "--local_epoch", "1", "--n_repeats", "1",
              "--result_dir", str(tmp_path)]
    out = _run(common, cwd=str(tmp_path))
    assert out.returncode == 0, out.stderr[-2000:]
    partial = tmp_path / "exp1_digits.partial.pkl"
    assert partial.exists()
    with open(partial, "rb") as f:
        saved = f.read()
    out = _run(common, cwd=str(tmp_path))
    assert out.returncode == 0, out.stderr[-2000:]
    assert "cannot clobber" in out.stderr
    with open(tmp_path / "exp1_digits.partial.pkl.bak", "rb") as f:
        assert f.read() == saved

def test_exp_driver_model_extension(tmp_path):
    """--model runs the reference experiment flow with any zoo member
    (jax-only extension; the torch twin is the linear parity oracle)."""
    out = _run([os.path.join(REPO, "exp.py"), "--dataset", "digits",
                "--model", "mlp16", "--backend", "torch"], cwd=REPO)
    assert out.returncode != 0 and "jax-backend extension" in out.stderr
    out = _run([os.path.join(REPO, "exp.py"), "--dataset", "digits",
                "--D", "64", "--num_partitions", "4", "--round", "2",
                "--local_epoch", "1", "--model", "mlp16",
                "--result_dir", str(tmp_path)], cwd=str(tmp_path))
    assert out.returncode == 0, out.stderr[-2000:]
    assert "forcing kernel_type='linear'" in out.stdout
    with open(tmp_path / "exp1_digits.pkl", "rb") as f:
        data = pickle.load(f)
    assert data["test_acc"].shape == (6, 2, 1)
    assert np.all(np.isfinite(data["train_loss"]))
