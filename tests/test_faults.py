"""The fault plane (fedcore.faults) + robust aggregation (fedcore.robust).

Load-bearing contracts (ISSUE 2 acceptance):

- same seed => identical FaultPlan (deterministic injection);
- faults=None is the untouched default graph (pinned upstream by the
  oracle-regression suite); a ZERO-RATE spec routes through the fault
  graph and still reproduces the clean params/eval metrics bitwise;
- a NaN/Inf-corrupted client is quarantined and the run equals the same
  run with that client cleanly dropped — array-equal, not approximate;
- an all-faulty round leaves the global model unchanged;
- FedAMW accepts partial participation: the p-solver runs masked, the
  learned p carries exactly zero mass on absent/quarantined clients,
  and under FEDAMW_P_GUARD=simplex p stays on the MASKED simplex;
- fault injection adds no recompiles to the round trainer (plan rows
  are scanned inputs; jit cache counter pinned, same mechanism as
  tests/test_serve_contract.py).
"""

import dataclasses

import numpy as np
import pytest

from fedamw_tpu.algorithms import (FedAMW, FedAMW_OneShot, FedAvg,
                                   FedNova, core, prepare_setup)
from fedamw_tpu.data import load_dataset
from fedamw_tpu.fedcore.faults import (FaultPlan, FaultSpec,
                                       resolve_fault_plan)
from fedamw_tpu.fedcore.robust import (RobustSpec, clip_update_norms,
                                       coordinatewise_median,
                                       coordinatewise_trimmed_mean,
                                       parse_robust_spec,
                                       sanitize_updates)

pytestmark = pytest.mark.faults


@pytest.fixture(scope="module")
def setup8():
    ds = load_dataset("digits", num_partitions=8, alpha=0.5)
    return prepare_setup(ds, kernel_type="linear", seed=3,
                         rng=np.random.RandomState(3))


KW = dict(lr=0.5, epoch=1, round=3, seed=0, lr_mode="constant")
AMW_KW = dict(**KW, lambda_reg=1e-4, lr_p=1e-3)


def target_plan(R, J, kind, j, frac=0.5, fill=np.nan):
    """A plan hitting exactly client ``j`` every round with one fault
    kind — the surgical tool the equivalence tests need (spec-built
    plans hit random clients)."""
    z = np.zeros((R, J), np.float32)
    drop, straggle, corrupt = z.copy(), z.copy(), z.copy()
    scale = np.ones((R, J), np.float32)
    poison, fillm = z.copy(), z.copy()
    if kind == "drop":
        drop[:, j] = 1
    elif kind == "straggle":
        straggle[:, j] = 1
        scale[:, j] = frac
    elif kind == "sign":
        corrupt[:, j] = 1
        scale[:, j] = -1.0
    else:  # poison (nan/inf)
        corrupt[:, j] = 1
        poison[:, j] = 1
        fillm[:, j] = fill
    return FaultPlan(drop, straggle, corrupt, scale, poison, fillm)


# -- plan determinism and spec parsing --------------------------------

def test_same_seed_identical_plan():
    spec = FaultSpec(drop=0.2, straggle=0.1, corrupt=0.15,
                     corrupt_mode="nan", seed=11)
    a = FaultPlan.build(spec, rounds=20, num_clients=16)
    b = FaultPlan.build(spec, rounds=20, num_clients=16)
    for name in ("drop", "straggle", "corrupt", "scale", "poison",
                 "fill"):
        np.testing.assert_array_equal(
            getattr(a, name), getattr(b, name), err_msg=name)
    # and a different seed actually moves the plan
    c = FaultPlan.build(dataclasses.replace(spec, seed=12), 20, 16)
    assert not np.array_equal(a.drop, c.drop)


def test_plan_roles_are_exclusive_and_rate_shaped():
    spec = FaultSpec(drop=0.3, straggle=0.3, corrupt=0.3, seed=0)
    plan = FaultPlan.build(spec, rounds=50, num_clients=40)
    total = plan.drop + plan.straggle + plan.corrupt
    assert total.max() <= 1.0  # one role per (round, client) cell
    # LLN at n=2000 cells: each empirical rate lands near 0.3
    for m in (plan.drop, plan.straggle, plan.corrupt):
        assert 0.25 < m.mean() < 0.35


def test_spec_parse_full_syntax():
    s = FaultSpec.parse("drop=0.1, straggle=0.2:0.25, "
                        "corrupt=0.05:scale:7.5, seed=9")
    assert s == FaultSpec(drop=0.1, straggle=0.2, straggle_frac=0.25,
                          corrupt=0.05, corrupt_mode="scale",
                          corrupt_scale=7.5, seed=9)


@pytest.mark.parametrize("bad", [
    "drop=1.5", "drop=0.6,straggle=0.6", "straggle=0.1:0",
    "corrupt=0.1:bogus", "corrupt=0.1:scale:inf", "typo=1",
    "drop", "drop=abc",
])
def test_spec_parse_rejects_malformed(bad):
    with pytest.raises(ValueError):
        FaultSpec.parse(bad)


def test_spec_parse_errors_name_the_token():
    with pytest.raises(ValueError, match="token 'drop=unknown'"):
        # a value containing 'unknown' must still get the token-naming
        # wrapper, not be misrouted as an unknown-key error
        FaultSpec.parse("drop=unknown")
    with pytest.raises(ValueError, match="unknown fault spec key"):
        FaultSpec.parse("frobnicate=1")


def test_resolve_rejects_mismatched_plan():
    plan = FaultPlan.build(FaultSpec(drop=0.1), rounds=4, num_clients=8)
    with pytest.raises(ValueError, match="horizon"):
        resolve_fault_plan(plan, rounds=5, num_clients=8)
    assert resolve_fault_plan(None, 5, 8) is None


# -- robust primitives ------------------------------------------------

def test_sanitize_quarantines_nonfinite():
    g = {"w": np.zeros((3, 2), np.float32)}
    stacked = {"w": np.stack([np.full((3, 2), 1.0, np.float32),
                              np.full((3, 2), np.nan, np.float32),
                              np.full((3, 2), 2.0, np.float32)])}
    losses = np.asarray([0.5, 0.1, np.inf], np.float32)
    clean, losses_c, ok = sanitize_updates(g, stacked, losses)
    np.testing.assert_array_equal(np.asarray(ok), [1.0, 0.0, 0.0])
    clean_w = np.asarray(clean["w"])
    np.testing.assert_array_equal(clean_w[0], 1.0)  # untouched
    np.testing.assert_array_equal(clean_w[1], 0.0)  # -> global params
    # a quarantined client is excluded WHOLESALE: client 1's loss was
    # finite, but its params were poisoned, so its loss is zeroed too
    np.testing.assert_array_equal(np.asarray(losses_c), [0.5, 0.0, 0.0])


def test_clip_update_norms_bounds_only_offenders():
    g = {"w": np.zeros((1, 4), np.float32)}
    stacked = {"w": np.stack([np.asarray([[3.0, 4.0, 0, 0]], np.float32),
                              np.asarray([[0.3, 0.4, 0, 0]], np.float32)])}
    out = np.asarray(clip_update_norms(g, stacked, 1.0)["w"])
    np.testing.assert_allclose(np.linalg.norm(out[0]), 1.0, rtol=1e-6)
    np.testing.assert_array_equal(out[1], stacked["w"][1])  # compliant


def test_coordinatewise_median_masks_absent():
    x = {"w": np.asarray([[1.0], [100.0], [2.0], [3.0]], np.float32)}
    present = np.asarray([1.0, 0.0, 1.0, 1.0], np.float32)
    out = float(np.asarray(coordinatewise_median(x, present)["w"])[0])
    assert out == 2.0  # median of {1, 2, 3}; the absent 100 never votes


def test_trimmed_mean_drops_extremes_and_falls_back():
    vals = np.asarray([[v] for v in (0.0, 1.0, 2.0, 3.0, 100.0)],
                      np.float32)
    x = {"w": vals}
    present = np.ones(5, np.float32)
    out = float(np.asarray(
        coordinatewise_trimmed_mean(x, present, 1)["w"])[0])
    np.testing.assert_allclose(out, 2.0)  # mean of {1, 2, 3}
    # 2 present clients cannot trim 1 from each end -> masked mean
    present2 = np.asarray([1, 1, 0, 0, 0], np.float32)
    out2 = float(np.asarray(
        coordinatewise_trimmed_mean(x, present2, 1)["w"])[0])
    np.testing.assert_allclose(out2, 0.5)


@pytest.mark.parametrize("spec, want", [
    ("mean", RobustSpec()),
    ("median", RobustSpec(agg="median")),
    ("trim:2", RobustSpec(agg="trim", trim=2)),
    ("clip:5", RobustSpec(clip=5.0)),
    ("clip:5+trim:1", RobustSpec(agg="trim", trim=1, clip=5.0)),
    ("CLIP:2.5 + median", RobustSpec(agg="median", clip=2.5)),
])
def test_parse_robust_spec(spec, want):
    assert parse_robust_spec(spec) == want


@pytest.mark.parametrize("bad", ["trim", "trim:0", "clip:0", "clip:nan",
                                 "clip:inf", "median+trim:1", "krum",
                                 "median+mean", "trim:2+mean",
                                 "clip:5+clip:0.1"])
def test_parse_robust_spec_rejects(bad):
    """Includes the silent-fallback spellings: 'median+mean' must not
    quietly run the plain average the user opted out of, and duplicate
    clip radii must not last-win."""
    with pytest.raises(ValueError):
        parse_robust_spec(bad)


# -- end-to-end: injection, quarantine, equivalences ------------------

def test_zero_rate_spec_matches_clean_run(setup8):
    clean = FedAvg(setup8, return_state=True, **KW)
    zero = FedAvg(setup8, faults="drop=0.0,seed=0", return_state=True,
                  **KW)
    # the fault graph with an all-clean plan reproduces the clean run:
    # params and eval metrics bitwise (clean clients pass through the
    # injection untouched via `where`); train_loss to float tolerance
    # (its weight rescale fuses into the reduction differently)
    np.testing.assert_array_equal(np.asarray(zero["params"]["w"]),
                                  np.asarray(clean["params"]["w"]))
    np.testing.assert_array_equal(zero["test_acc"], clean["test_acc"])
    np.testing.assert_array_equal(zero["test_loss"], clean["test_loss"])
    np.testing.assert_allclose(zero["train_loss"], clean["train_loss"],
                               rtol=1e-5)
    assert all(v.sum() == 0 for v in zero["fault_counts"].values())


@pytest.mark.parametrize("algo, kw", [(FedAvg, KW), (FedAMW, AMW_KW)])
def test_nan_client_quarantined_equals_clean_drop(setup8, algo, kw):
    """The headline robustness contract: a NaN-corrupted client is
    quarantined, the run stays finite, and every array the run
    produces equals the same run with that client cleanly dropped —
    quarantine IS exclusion, not approximation."""
    R, J = KW["round"], setup8.num_clients
    nan_run = algo(setup8, faults=target_plan(R, J, "nan", 2),
                   return_state=True, **kw)
    drop_run = algo(setup8, faults=target_plan(R, J, "drop", 2),
                    return_state=True, **kw)
    for key in ("train_loss", "test_loss", "test_acc"):
        assert np.all(np.isfinite(nan_run[key])), key
        np.testing.assert_array_equal(nan_run[key], drop_run[key],
                                      err_msg=key)
    np.testing.assert_array_equal(np.asarray(nan_run["params"]["w"]),
                                  np.asarray(drop_run["params"]["w"]))
    np.testing.assert_array_equal(np.asarray(nan_run["p"]),
                                  np.asarray(drop_run["p"]))
    # the quarantine caught the poisoned client every round...
    np.testing.assert_array_equal(
        nan_run["fault_counts"]["quarantined"], np.full(R, 1))
    # ...and the faulty run actually differs from the clean one
    clean = algo(setup8, **kw)
    assert not np.allclose(clean["test_loss"], nan_run["test_loss"])


def test_inf_poison_also_quarantined(setup8):
    R, J = KW["round"], setup8.num_clients
    res = FedAvg(setup8, faults=target_plan(R, J, "nan", 1, fill=np.inf),
                 **KW)
    assert np.all(np.isfinite(res["train_loss"]))
    assert res["fault_counts"]["quarantined"].sum() == R


@pytest.mark.parametrize("kind", ["drop", "nan"])
def test_all_clients_faulty_round_leaves_model_unchanged(setup8, kind):
    J = setup8.num_clients
    zeros, ones = np.zeros((1, J), np.float32), np.ones((1, J), np.float32)
    if kind == "drop":
        plan = FaultPlan(ones, zeros, zeros, ones, zeros, zeros)
    else:  # every client reports NaN -> every client quarantined
        plan = FaultPlan(zeros, zeros, ones, ones, ones,
                         np.full((1, J), np.nan, np.float32))
    res = FedAvg(setup8, faults=plan, round=1, return_state=True,
                 **{k: v for k, v in KW.items() if k != "round"})
    init = core._derive_params(setup8.model.init, KW["seed"],
                               setup8.D, setup8.num_classes)
    np.testing.assert_array_equal(np.asarray(res["params"]["w"]),
                                  np.asarray(init["w"]))
    assert np.all(np.isfinite(res["test_loss"]))


def test_straggler_shrinks_the_update(setup8):
    """A straggler's report pulls the aggregate LESS than its full
    update: the faulted round's params differ from clean, stay finite,
    and land between a full drop and the clean run."""
    R, J = KW["round"], setup8.num_clients
    clean = FedAvg(setup8, return_state=True, **KW)
    strag = FedAvg(setup8, faults=target_plan(R, J, "straggle", 0,
                                              frac=0.25),
                   return_state=True, **KW)
    assert np.all(np.isfinite(strag["test_loss"]))
    assert not np.array_equal(np.asarray(strag["params"]["w"]),
                              np.asarray(clean["params"]["w"]))
    assert strag["fault_counts"]["straggled"].sum() == R


def test_fednova_accepts_faults(setup8):
    res = FedNova(setup8, faults="drop=0.25,corrupt=0.25:nan,seed=5",
                  **KW)
    assert np.all(np.isfinite(res["test_loss"]))
    counts = res["fault_counts"]
    assert counts["quarantined"].sum() == counts["corrupted"].sum()


def test_sign_flip_defended_by_median_and_clip(setup8):
    """Finite corruption (sign flip) sails through the quarantine by
    design; the opt-in robust aggregators are the defense."""
    R, J = KW["round"], setup8.num_clients
    plan = target_plan(R, J, "sign", 0)
    for agg in ("median", "clip:1+trim:1"):
        res = FedAvg(setup8, faults=plan, robust_agg=agg, **KW)
        assert np.all(np.isfinite(res["test_loss"])), agg
        assert res["fault_counts"]["corrupted"].sum() == R
        assert res["fault_counts"]["quarantined"].sum() == 0


def test_robust_agg_without_faults_runs(setup8):
    res = FedAvg(setup8, robust_agg="trim:1", **KW)
    assert np.all(np.isfinite(res["test_loss"]))
    assert "fault_counts" not in res  # no plan, no fault report


# -- FedAMW partial participation / masked p --------------------------

def test_fedamw_accepts_partial_participation(setup8):
    full = FedAMW(setup8, **AMW_KW)
    dflt = FedAMW(setup8, participation=1.0, **AMW_KW)
    np.testing.assert_array_equal(full["test_acc"], dflt["test_acc"])
    half = FedAMW(setup8, participation=0.5, **AMW_KW)
    assert np.all(np.isfinite(half["test_loss"]))
    assert not np.allclose(full["train_loss"], half["train_loss"])


def test_fedamw_dropout_zero_mass_and_masked_simplex(setup8,
                                                     monkeypatch):
    """A client dropped every round earns exactly zero mixture mass,
    and under the simplex guard the learned p lives on the MASKED
    simplex: zero on invalid clients, the rest summing to 1."""
    R, J = AMW_KW["round"], setup8.num_clients
    plan = target_plan(R, J, "drop", 3)
    res = FedAMW(setup8, faults=plan, return_state=True, **AMW_KW)
    assert float(np.asarray(res["p"])[3]) == 0.0  # unguarded too

    monkeypatch.setenv("FEDAMW_P_GUARD", "simplex")
    guarded = FedAMW(setup8, faults=plan, return_state=True, **AMW_KW)
    p = np.asarray(guarded["p"])
    assert p[3] == 0.0
    assert np.all(p >= 0)
    np.testing.assert_allclose(p.sum(), 1.0, atol=1e-5)
    assert np.all(np.isfinite(guarded["test_loss"]))


# -- zero-recompile + resume contracts --------------------------------

def test_fault_plan_change_adds_no_recompile(setup8):
    """The plan rows are DATA (scanned inputs), not program structure:
    two runs under different plans share one trainer and one compiled
    XLA program — the bench-grade zero-recompile contract, read from
    the jit cache counter like tests/test_serve_contract.py."""
    FedAvg(setup8, faults="drop=0.4,corrupt=0.1:nan,seed=0", **KW)
    fn = core._LAST_TRAIN_FN
    size0 = fn._cache_size() if hasattr(fn, "_cache_size") else None
    FedAvg(setup8, faults="drop=0.1,straggle=0.3:0.5,seed=99", **KW)
    assert core._LAST_TRAIN_FN is fn  # same memoized trainer
    if size0 is not None:
        assert fn._cache_size() == size0  # same compiled program


def test_faults_resume_replays_identical_plan(setup8):
    """Prefix + resume == the uninterrupted faulty run: plan rows are
    generated for the FULL horizon and sliced, exactly like the LR
    schedule and key streams."""
    kw = dict(lr=0.5, epoch=1, batch_size=32, seed=0,
              lr_mode="reference", faults="drop=0.3,corrupt=0.2:nan,seed=3")
    full = FedAvg(setup8, round=4, return_state=True, **kw)
    prefix = FedAvg(setup8, round=4, stop_round=2, return_state=True,
                    **kw)
    resumed = FedAvg(setup8, round=4, start_round=2,
                     resume_from={"params": prefix["params"]},
                     return_state=True, **kw)
    np.testing.assert_array_equal(resumed["test_acc"],
                                  np.asarray(full["test_acc"])[2:])
    np.testing.assert_array_equal(np.asarray(resumed["params"]["w"]),
                                  np.asarray(full["params"]["w"]))
    np.testing.assert_array_equal(
        resumed["fault_counts"]["quarantined"],
        full["fault_counts"]["quarantined"][2:])


# -- surface checks ---------------------------------------------------

def test_oneshot_algorithms_reject_faults(setup8):
    from fedamw_tpu.algorithms import Centralized, Distributed
    for fn in (Centralized, Distributed, FedAMW_OneShot):
        with pytest.raises(ValueError, match="faults"):
            fn(setup8, epoch=1, faults="drop=0.1")
        with pytest.raises(ValueError, match="faults"):
            fn(setup8, epoch=1, robust_agg="median")


def test_fault_counts_and_report(setup8):
    res = FedAvg(setup8, faults="drop=0.5,seed=2", **KW)
    counts = res["fault_counts"]
    valid = (np.asarray(setup8.sizes) > 0)
    plan = FaultPlan.build(FaultSpec(drop=0.5, seed=2), KW["round"],
                           setup8.num_clients)
    np.testing.assert_array_equal(
        counts["dropped"], (plan.drop * valid).sum(1).astype(int))

    from fedamw_tpu.utils.reporting import (fault_summary,
                                            format_fault_report)
    s = fault_summary(counts)
    assert s["total_dropped"] == counts["dropped"].sum()
    assert s["rounds"] == KW["round"]
    line = format_fault_report("FedAvg", counts)
    assert "FedAvg" in line and f"{s['total_dropped']} dropped" in line
