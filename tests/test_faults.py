"""The fault plane (fedcore.faults) + robust aggregation (fedcore.robust).

Load-bearing contracts (ISSUE 2 acceptance):

- same seed => identical FaultPlan (deterministic injection);
- faults=None is the untouched default graph (pinned upstream by the
  oracle-regression suite); a ZERO-RATE spec routes through the fault
  graph and still reproduces the clean params/eval metrics bitwise;
- a NaN/Inf-corrupted client is quarantined and the run equals the same
  run with that client cleanly dropped — array-equal, not approximate;
- an all-faulty round leaves the global model unchanged;
- FedAMW accepts partial participation: the p-solver runs masked, the
  learned p carries exactly zero mass on absent/quarantined clients,
  and under FEDAMW_P_GUARD=simplex p stays on the MASKED simplex;
- fault injection adds no recompiles to the round trainer (plan rows
  are scanned inputs; jit cache counter pinned, same mechanism as
  tests/test_serve_contract.py).
"""

import dataclasses

import numpy as np
import pytest

from fedamw_tpu.algorithms import (FedAMW, FedAMW_OneShot, FedAvg,
                                   FedNova, core, prepare_setup)
from fedamw_tpu.data import load_dataset
from fedamw_tpu.fedcore.faults import (FaultPlan, FaultSpec,
                                       resolve_fault_plan)
from fedamw_tpu.fedcore.robust import (RobustSpec, clip_update_norms,
                                       coordinatewise_median,
                                       coordinatewise_trimmed_mean,
                                       geometric_median, krum_aggregate,
                                       krum_select, make_robust_aggregator,
                                       parse_robust_spec,
                                       sanitize_updates,
                                       zscore_quarantine)

pytestmark = pytest.mark.faults


@pytest.fixture(scope="module")
def setup8():
    ds = load_dataset("digits", num_partitions=8, alpha=0.5)
    return prepare_setup(ds, kernel_type="linear", seed=3,
                         rng=np.random.RandomState(3))


KW = dict(lr=0.5, epoch=1, round=3, seed=0, lr_mode="constant")
AMW_KW = dict(**KW, lambda_reg=1e-4, lr_p=1e-3)


def target_plan(R, J, kind, j, frac=0.5, fill=np.nan):
    """A plan hitting exactly client ``j`` every round with one fault
    kind — the surgical tool the equivalence tests need (spec-built
    plans hit random clients)."""
    z = np.zeros((R, J), np.float32)
    drop, straggle, corrupt = z.copy(), z.copy(), z.copy()
    scale = np.ones((R, J), np.float32)
    poison, fillm = z.copy(), z.copy()
    if kind == "drop":
        drop[:, j] = 1
    elif kind == "straggle":
        straggle[:, j] = 1
        scale[:, j] = frac
    elif kind == "sign":
        corrupt[:, j] = 1
        scale[:, j] = -1.0
    else:  # poison (nan/inf)
        corrupt[:, j] = 1
        poison[:, j] = 1
        fillm[:, j] = fill
    return FaultPlan(drop, straggle, corrupt, scale, poison, fillm)


# -- plan determinism and spec parsing --------------------------------

def test_same_seed_identical_plan():
    spec = FaultSpec(drop=0.2, straggle=0.1, corrupt=0.15,
                     corrupt_mode="nan", seed=11)
    a = FaultPlan.build(spec, rounds=20, num_clients=16)
    b = FaultPlan.build(spec, rounds=20, num_clients=16)
    for name in ("drop", "straggle", "corrupt", "scale", "poison",
                 "fill"):
        np.testing.assert_array_equal(
            getattr(a, name), getattr(b, name), err_msg=name)
    # and a different seed actually moves the plan
    c = FaultPlan.build(dataclasses.replace(spec, seed=12), 20, 16)
    assert not np.array_equal(a.drop, c.drop)


def test_plan_roles_are_exclusive_and_rate_shaped():
    spec = FaultSpec(drop=0.3, straggle=0.3, corrupt=0.3, seed=0)
    plan = FaultPlan.build(spec, rounds=50, num_clients=40)
    total = plan.drop + plan.straggle + plan.corrupt
    assert total.max() <= 1.0  # one role per (round, client) cell
    # LLN at n=2000 cells: each empirical rate lands near 0.3
    for m in (plan.drop, plan.straggle, plan.corrupt):
        assert 0.25 < m.mean() < 0.35


def test_spec_parse_full_syntax():
    s = FaultSpec.parse("drop=0.1, straggle=0.2:0.25, "
                        "corrupt=0.05:scale:7.5, seed=9")
    assert s == FaultSpec(drop=0.1, straggle=0.2, straggle_frac=0.25,
                          corrupt=0.05, corrupt_mode="scale",
                          corrupt_scale=7.5, seed=9)


def test_spec_parse_lie_and_plan_report():
    """The lie mode (ISSUE 4): a lying cell does FULL work (scale 1,
    update untouched) but its REPORTED fraction is lie_frac — the
    FedNova tau inflation attack. The plan's report row carries the
    claim; honest cells derive their report from the straggle row."""
    s = FaultSpec.parse("lie=0.3:0.01, straggle=0.2:0.5, seed=4")
    assert s.lie == 0.3 and s.lie_frac == 0.01
    with pytest.raises(ValueError, match="lie_frac"):
        FaultSpec(lie=0.1, lie_frac=0.0)
    with pytest.raises(ValueError, match="sum"):
        FaultSpec(drop=0.5, straggle=0.3, lie=0.3)
    plan = FaultPlan.build(s, rounds=8, num_clients=12)
    assert plan.lie.sum() > 0
    # mutually exclusive roles, full work on lying cells
    assert ((plan.lie + plan.straggle + plan.drop
             + plan.corrupt).max() <= 1.0)
    np.testing.assert_array_equal(plan.scale[plan.lie > 0], 1.0)
    np.testing.assert_array_equal(plan.report[plan.lie > 0],
                                  np.float32(0.01))
    np.testing.assert_array_equal(plan.report[plan.straggle > 0], 0.5)
    clean = (plan.lie == 0) & (plan.straggle == 0)
    np.testing.assert_array_equal(plan.report[clean], 1.0)
    # rows() ships the REPORTED fraction as the tau_frac row
    tau = np.asarray(plan.rows(0, 8)[4])
    np.testing.assert_array_equal(tau, plan.report)
    # a lie mask WITHOUT the claimed fractions must refuse loudly: the
    # derived report would be 1.0 on lying cells (a clean plan) while
    # fault_counts still labeled them "lied"
    with pytest.raises(ValueError, match="report"):
        FaultPlan(plan.drop, plan.straggle, plan.corrupt, plan.scale,
                  plan.poison, plan.fill, lie=plan.lie)


def test_rep_parse_error_names_the_malformed_field():
    """'rep:0.9:abc' is a FLOOR problem — the decay is valid and the
    error must not point the operator at it."""
    with pytest.raises(ValueError, match="floor"):
        parse_robust_spec("rep:0.9:abc")
    with pytest.raises(ValueError, match="decay"):
        parse_robust_spec("rep:abc:0.2")


@pytest.mark.parametrize("bad", [
    "drop=1.5", "drop=0.6,straggle=0.6", "straggle=0.1:0",
    "corrupt=0.1:bogus", "corrupt=0.1:scale:inf", "typo=1",
    "drop", "drop=abc",
])
def test_spec_parse_rejects_malformed(bad):
    with pytest.raises(ValueError):
        FaultSpec.parse(bad)


def test_spec_parse_errors_name_the_token():
    with pytest.raises(ValueError, match="token 'drop=unknown'"):
        # a value containing 'unknown' must still get the token-naming
        # wrapper, not be misrouted as an unknown-key error
        FaultSpec.parse("drop=unknown")
    with pytest.raises(ValueError, match="unknown fault spec key"):
        FaultSpec.parse("frobnicate=1")


def test_resolve_rejects_mismatched_plan():
    plan = FaultPlan.build(FaultSpec(drop=0.1), rounds=4, num_clients=8)
    with pytest.raises(ValueError, match="horizon"):
        resolve_fault_plan(plan, rounds=5, num_clients=8)
    assert resolve_fault_plan(None, 5, 8) is None


# -- robust primitives ------------------------------------------------

def test_sanitize_quarantines_nonfinite():
    g = {"w": np.zeros((3, 2), np.float32)}
    stacked = {"w": np.stack([np.full((3, 2), 1.0, np.float32),
                              np.full((3, 2), np.nan, np.float32),
                              np.full((3, 2), 2.0, np.float32)])}
    losses = np.asarray([0.5, 0.1, np.inf], np.float32)
    clean, losses_c, ok = sanitize_updates(g, stacked, losses)
    np.testing.assert_array_equal(np.asarray(ok), [1.0, 0.0, 0.0])
    clean_w = np.asarray(clean["w"])
    np.testing.assert_array_equal(clean_w[0], 1.0)  # untouched
    np.testing.assert_array_equal(clean_w[1], 0.0)  # -> global params
    # a quarantined client is excluded WHOLESALE: client 1's loss was
    # finite, but its params were poisoned, so its loss is zeroed too
    np.testing.assert_array_equal(np.asarray(losses_c), [0.5, 0.0, 0.0])


def test_clip_update_norms_bounds_only_offenders():
    g = {"w": np.zeros((1, 4), np.float32)}
    stacked = {"w": np.stack([np.asarray([[3.0, 4.0, 0, 0]], np.float32),
                              np.asarray([[0.3, 0.4, 0, 0]], np.float32)])}
    out = np.asarray(clip_update_norms(g, stacked, 1.0)["w"])
    np.testing.assert_allclose(np.linalg.norm(out[0]), 1.0, rtol=1e-6)
    np.testing.assert_array_equal(out[1], stacked["w"][1])  # compliant


def test_coordinatewise_median_masks_absent():
    x = {"w": np.asarray([[1.0], [100.0], [2.0], [3.0]], np.float32)}
    present = np.asarray([1.0, 0.0, 1.0, 1.0], np.float32)
    out = float(np.asarray(coordinatewise_median(x, present)["w"])[0])
    assert out == 2.0  # median of {1, 2, 3}; the absent 100 never votes


def test_trimmed_mean_drops_extremes_and_falls_back():
    vals = np.asarray([[v] for v in (0.0, 1.0, 2.0, 3.0, 100.0)],
                      np.float32)
    x = {"w": vals}
    present = np.ones(5, np.float32)
    out = float(np.asarray(
        coordinatewise_trimmed_mean(x, present, 1)["w"])[0])
    np.testing.assert_allclose(out, 2.0)  # mean of {1, 2, 3}
    # 2 present clients cannot trim 1 from each end -> masked mean
    present2 = np.asarray([1, 1, 0, 0, 0], np.float32)
    out2 = float(np.asarray(
        coordinatewise_trimmed_mean(x, present2, 1)["w"])[0])
    np.testing.assert_allclose(out2, 0.5)


@pytest.mark.parametrize("spec, want", [
    ("mean", RobustSpec()),
    ("median", RobustSpec(agg="median")),
    ("trim:2", RobustSpec(agg="trim", trim=2)),
    ("clip:5", RobustSpec(clip=5.0)),
    ("clip:5+trim:1", RobustSpec(agg="trim", trim=1, clip=5.0)),
    ("CLIP:2.5 + median", RobustSpec(agg="median", clip=2.5)),
    ("krum", RobustSpec(agg="krum")),
    ("mkrum:4", RobustSpec(agg="mkrum", mkrum_m=4)),
    ("geomed", RobustSpec(agg="geomed", geomed_iters=8)),
    ("geomed:3", RobustSpec(agg="geomed", geomed_iters=3)),
    ("quarantine:2.5", RobustSpec(zscore=2.5)),
    ("quarantine", RobustSpec(zscore=3.0)),
    ("quarantine:3+mkrum:6",
     RobustSpec(agg="mkrum", mkrum_m=6, zscore=3.0)),
    ("clip:5+quarantine:2+geomed:4",
     RobustSpec(agg="geomed", geomed_iters=4, clip=5.0, zscore=2.0)),
    ("quarantine:auto", RobustSpec(zscore_auto=True)),
    ("rep", RobustSpec(rep_decay=0.9, rep_floor=0.2)),
    ("rep:0.5", RobustSpec(rep_decay=0.5, rep_floor=0.2)),
    ("rep:0.5:0.1", RobustSpec(rep_decay=0.5, rep_floor=0.1)),
    ("rep:0.9:0", RobustSpec(rep_decay=0.9, rep_floor=0.0)),
    ("rep:0.9+quarantine:3.5",
     RobustSpec(zscore=3.5, rep_decay=0.9, rep_floor=0.2)),
    ("rep:0.8:0.25+quarantine:auto+mkrum:4",
     RobustSpec(agg="mkrum", mkrum_m=4, zscore_auto=True,
                rep_decay=0.8, rep_floor=0.25)),
])
def test_parse_robust_spec(spec, want):
    assert parse_robust_spec(spec) == want


@pytest.mark.parametrize("bad", ["trim", "trim:0", "clip:0", "clip:nan",
                                 "clip:inf", "median+trim:1",
                                 "median+mean", "trim:2+mean",
                                 "clip:5+clip:0.1", "krum:2", "mkrum",
                                 "mkrum:0", "geomed:0", "geomed:x",
                                 "quarantine:0", "quarantine:nan",
                                 "quarantine:inf", "krum+mkrum:2",
                                 "quarantine:2+quarantine:3", "bogus",
                                 "rep:0", "rep:1", "rep:nan", "rep:x",
                                 "rep:0.9:1", "rep:0.9:-0.1",
                                 "rep:0.9:0.2:7", "rep+rep:0.5",
                                 "quarantine:auto+quarantine:3",
                                 "quarantine:aut0"])
def test_parse_robust_spec_rejects(bad):
    """Includes the silent-fallback spellings: 'median+mean' must not
    quietly run the plain average the user opted out of, and duplicate
    clip radii / quarantine thresholds must not last-win."""
    with pytest.raises(ValueError):
        parse_robust_spec(bad)


# every accepted spelling the suite knows about — the canonical
# round-trip sweep below AND the conftest-level guard
# (FEDAMW_SPEC_ROUNDTRIP_CHECK, enabled suite-wide) both walk it
ACCEPTED_SPELLINGS = [
    "mean", "median", "trim:1", "trim:3", "clip", "clip:5",
    "clip:0.5+median", "clip:5+trim:1", "CLIP:2.5 + median",
    "krum", "mkrum:1", "mkrum:4", "geomed", "geomed:3",
    "quarantine", "quarantine:2.5", "quarantine:3+mkrum:6",
    "clip:5+quarantine:2+geomed:4", "mkrum:2+clip:1+quarantine:1.5",
    # the stateful tokens (ISSUE 4): cross-round reputation and the
    # auto-tuned quarantine threshold, alone and composed
    "rep", "rep:0.5", "rep:0.9:0.3", "REP:0.5 : 0.1",
    "quarantine:auto", "QUARANTINE:AUTO", "rep:0.9+quarantine:auto",
    "rep:0.9:0.2+quarantine:3.5",
    "clip:5+quarantine:auto+rep:0.8:0.25+mkrum:4",
]


@pytest.mark.parametrize("spelling", ACCEPTED_SPELLINGS)
def test_robust_spec_canonical_round_trip(spelling):
    """parse(canonical(parse(s))) == parse(s) and canonical() is a
    fixed point — otherwise an accepted spelling and its canonical
    form would key DIFFERENT entries in the trainer jit cache
    (core._cached_round_trainer memoizes on the canonical string) and
    silently recompile per spelling."""
    spec = parse_robust_spec(spelling)
    canon = spec.canonical()
    assert parse_robust_spec(canon) == spec
    assert parse_robust_spec(canon).canonical() == canon


def test_roundtrip_guard_is_armed_in_suite():
    """conftest exports FEDAMW_SPEC_ROUNDTRIP_CHECK=1, so EVERY
    parse_robust_spec call anywhere in the suite (fixtures, trainers,
    drivers) verifies the round-trip contract — a new token with a
    drifting canonical spelling fails loudly at first parse."""
    import os

    from fedamw_tpu.fedcore.robust import SPEC_ROUNDTRIP_ENV
    assert os.environ.get(SPEC_ROUNDTRIP_ENV)


# -- defense primitives: z-quarantine, krum, geomed -------------------

def test_zscore_quarantine_flags_scaled_outlier():
    """A 10x-norm outlier z-scores far beyond any sane threshold under
    the median/MAD test (the classical mean/std z is BOUNDED by
    (n-1)/sqrt(n) ~ 2.2 here — it could never fire at Z=3; the robust
    z is why quarantine:3 works at federated client counts)."""
    rng = np.random.RandomState(0)
    J, P = 6, 8
    g = {"w": np.zeros((P,), np.float32)}
    deltas = rng.randn(J, P).astype(np.float32)
    deltas /= np.linalg.norm(deltas, axis=1, keepdims=True)
    deltas *= (1.0 + 0.1 * rng.randn(J, 1).astype(np.float32))
    deltas[0] *= 10.0
    stacked = {"w": deltas}
    ok, z = zscore_quarantine(g, stacked, np.ones(J, np.float32), 3.0)
    ok, z = np.asarray(ok), np.asarray(z)
    assert ok[0] == 0.0 and z[0] > 3.0
    np.testing.assert_array_equal(ok[1:], 1.0)


def test_zscore_quarantine_is_upper_tail_only():
    """A small-norm update (a straggler's truncated work) must NOT be
    quarantined — its pull on the aggregate is bounded by its norm,
    and the straggler-exact FedNova path exists to weight it, not
    discard it. Only the large-norm tail quarantines."""
    rng = np.random.RandomState(7)
    J, P = 8, 10
    g = {"w": np.zeros((P,), np.float32)}
    deltas = rng.randn(J, P).astype(np.float32)
    deltas /= np.linalg.norm(deltas, axis=1, keepdims=True)
    deltas *= (1.0 + 0.05 * rng.randn(J, 1).astype(np.float32))
    deltas[0] *= 0.25   # straggler: frac=0.25 of the work
    deltas[1] *= 10.0   # attacker: 10x norm
    ok, z = zscore_quarantine(g, {"w": deltas},
                              np.ones(J, np.float32), 5.0)
    ok = np.asarray(ok)
    assert ok[0] == 1.0  # the straggler survives
    assert ok[1] == 0.0  # the attacker does not
    assert float(np.asarray(z)[0]) == 0.0  # below-median scores 0


def test_fednova_straggler_survives_quarantine(setup8):
    """The pairing the straggler-exact tau was built for: FedNova with
    real stragglers AND quarantine:5 — the stragglers' partial work is
    kept (zero z-quarantines) and normalized exactly, not discarded."""
    R, J = KW["round"], setup8.num_clients
    res = FedNova(setup8, faults=target_plan(R, J, "straggle", 2,
                                             frac=0.25),
                  robust_agg="quarantine:5", **KW)
    assert np.all(np.isfinite(res["test_loss"]))
    assert res["fault_counts"]["straggled"].sum() == R
    assert res["defense"]["z_quarantined"].sum() == 0


def test_majority_straggle_round_spares_honest_clients(setup8):
    """The work-fraction normalization contract: with a MAJORITY of
    clients straggling, the raw-norm median would sit at the straggler
    norm and the honest full-work clients would look like upper-tail
    outliers. Scoring full-work-equivalent norms (norms / tau_frac,
    the fraction FedNova already assumes clients report) keeps every
    honest client in the round."""
    R, J = KW["round"], setup8.num_clients
    z = np.zeros((R, J), np.float32)
    straggle = z.copy()
    scale = np.ones((R, J), np.float32)
    straggle[:, :J - 2] = 1          # 6 of 8 straggle...
    scale[:, :J - 2] = 0.25          # ...at a quarter of the work
    plan = FaultPlan(z, straggle, z.copy(), scale, z.copy(), z.copy())
    res = FedAvg(setup8, faults=plan, robust_agg="quarantine:5", **KW)
    assert np.all(np.isfinite(res["test_loss"]))
    assert res["fault_counts"]["straggled"].sum() == R * (J - 2)
    assert res["defense"]["z_quarantined"].sum() == 0


def test_zscore_quarantine_work_frac_normalizes(setup8):
    """Unit-level: under work_frac every 0.25x-work straggler scores
    as its full-work-equivalent self (z ~ 0, even when stragglers are
    the majority), while a 20x attacker reporting full work still
    quarantines."""
    rng = np.random.RandomState(11)
    J, P = 6, 12
    g = {"w": np.zeros((P,), np.float32)}
    deltas = rng.randn(J, P).astype(np.float32)
    deltas /= np.linalg.norm(deltas, axis=1, keepdims=True)
    work = np.ones(J, np.float32)
    # clients 0-3 straggle at 0.25; client 4 honest; client 5 scales 20x
    deltas[:4] *= 0.25
    work[:4] = 0.25
    deltas[5] *= 20.0
    ok, z = zscore_quarantine(g, {"w": deltas},
                              np.ones(J, np.float32), 5.0,
                              work_frac=work)
    ok = np.asarray(ok)
    np.testing.assert_array_equal(ok[:5], 1.0)  # stragglers + honest
    assert ok[5] == 0.0                          # attacker


def test_zscore_quarantine_ignores_absent_and_uniform():
    g = {"w": np.zeros((4,), np.float32)}
    stacked = {"w": np.stack([np.full(4, 1.0), np.full(4, 1.0),
                              np.full(4, 100.0), np.full(4, 1.0)]
                             ).astype(np.float32)}
    # the 100x client is ABSENT: it must neither be scored nor pollute
    # the median/MAD of the present set
    present = np.asarray([1, 1, 0, 1], np.float32)
    ok, z = zscore_quarantine(g, stacked, present, 3.0)
    np.testing.assert_array_equal(np.asarray(ok), [1, 1, 1, 1])
    assert float(np.asarray(z)[2]) == 0.0
    # numerically identical present updates: z is exactly 0 everywhere
    # (the spread floor), not noise amplified into quarantines
    ok2, z2 = zscore_quarantine(
        g, {"w": np.ones((4, 4), np.float32)}, present, 3.0)
    np.testing.assert_array_equal(np.asarray(z2), 0.0)


def test_krum_select_excludes_the_outlier():
    rng = np.random.RandomState(1)
    J, P = 8, 10
    g = {"w": np.zeros(P, np.float32)}
    honest = rng.randn(P).astype(np.float32)
    x = honest[None] + 0.05 * rng.randn(J, P).astype(np.float32)
    x[3] = -5.0 * honest  # far from the honest cluster
    sel = np.asarray(krum_select(g, {"w": x},
                                 np.ones(J, np.float32), J - 1))
    assert sel[3] == 0.0 and sel.sum() == J - 1
    # classic krum (m=1) picks ONE honest client
    sel1 = np.asarray(krum_select(g, {"w": x},
                                  np.ones(J, np.float32), 1))
    assert sel1.sum() == 1 and sel1[3] == 0.0
    # absent clients can never be selected
    present = np.ones(J, np.float32)
    present[0] = 0.0
    sel2 = np.asarray(krum_select(g, {"w": x}, present, J))
    assert sel2[0] == 0.0


def test_krum_scores_deltas_not_raw_params():
    """The float32 contract behind _flat_deltas: with a LARGE shared
    global model (norm ~1e2) and tiny honest deltas (~1e-2), the
    Gram-expanded pairwise distances on raw stacked params would be
    pure rounding noise (~1e-3 absolute, an order above the true
    ~1e-4 distances). Scoring deltas keeps a modest outlier reliably
    excluded."""
    rng = np.random.RandomState(6)
    J, P = 8, 50
    big = (10.0 * rng.randn(P)).astype(np.float32)  # ||g|| ~ 70
    g = {"w": big}
    d = rng.randn(J, P).astype(np.float32)
    d /= np.linalg.norm(d, axis=1, keepdims=True) * 100.0  # ~1e-2
    d[5] *= -8.0  # modest outlier, far only at DELTA scale
    sel = np.asarray(krum_select(g, {"w": big[None] + d},
                                 np.ones(J, np.float32), J - 1))
    assert sel[5] == 0.0 and sel.sum() == J - 1


def test_krum_small_round_falls_back_to_present():
    """With fewer than 3 present clients the Krum score has no
    defensive content; every present client is selected (masked-mean
    fallback, mirroring trimmed-mean's small-n behavior)."""
    g = {"w": np.zeros(1, np.float32)}
    x = {"w": np.asarray([[0.0], [1.0], [50.0]], np.float32)}
    present = np.asarray([1, 0, 1], np.float32)
    sel = np.asarray(krum_select(g, x, present, 1))
    np.testing.assert_array_equal(sel, present)


def test_geomed_matches_median_against_outlier():
    """The geometric median of a tight cluster + one far outlier lands
    in the cluster, and the Weiszfeld residual telemetry shrinks to
    ~0 by the default iteration count."""
    rng = np.random.RandomState(2)
    J, P = 9, 6
    center = rng.randn(P).astype(np.float32)
    x = center[None] + 0.01 * rng.randn(J, P).astype(np.float32)
    x[4] = center + 1000.0
    out, residual = geometric_median({"w": x}, np.ones(J, np.float32),
                                     iters=12)
    assert np.linalg.norm(np.asarray(out["w"]) - center) < 0.1
    assert float(residual) < 1e-2
    # absent clients never vote: mask the outlier out and the result
    # stays in the cluster with everyone else present
    present = np.ones(J, np.float32)
    present[4] = 0.0
    out2, _ = geometric_median({"w": x}, present, iters=12)
    assert np.linalg.norm(np.asarray(out2["w"]) - center) < 0.1


# -- aggregator contracts (ISSUE 3 satellite) -------------------------

CONTRACT_SPECS = ("mean", "median", "trim:1", "krum", "mkrum:4",
                  "geomed", "geomed:16")


def test_clean_round_every_aggregator_near_weighted_mean():
    """On a clean all-present round with a tight honest cluster, every
    aggregator is a consistent estimator of the same center: each
    lands within the cluster spread of the weighted mean."""
    rng = np.random.RandomState(3)
    J, P = 8, 20
    base = rng.randn(P).astype(np.float32)
    stacked = {"w": (base[None]
                     + 0.01 * rng.randn(J, P)).astype(np.float32)}
    w = np.full(J, 1.0 / J, np.float32)
    present = np.ones(J, np.float32)
    from fedamw_tpu.fedcore.aggregate import weighted_average
    g = {"w": np.zeros(P, np.float32)}
    want = np.asarray(weighted_average(stacked, w)["w"])
    for spec in CONTRACT_SPECS:
        agg = make_robust_aggregator(parse_robust_spec(spec))
        out, _aux = agg(g, stacked, w, present)
        np.testing.assert_allclose(np.asarray(out["w"]), want,
                                   atol=0.05, err_msg=spec)


def test_sign_flip_attackers_defended_norm_bounded_mean_diverges():
    """f=3 of 10 clients report a scaled sign flip: the plain mean is
    dragged far from the honest center while every defended
    aggregator stays within the honest cluster."""
    rng = np.random.RandomState(4)
    J, P, f = 10, 30, 3
    honest = rng.randn(P).astype(np.float32)
    honest /= np.linalg.norm(honest) / 5.0
    x = honest[None] + 0.05 * rng.randn(J, P).astype(np.float32)
    x[:f] = -30.0 * honest[None] + 0.05 * rng.randn(f, P)
    stacked = {"w": x.astype(np.float32)}
    w = np.full(J, 1.0 / J, np.float32)
    present = np.ones(J, np.float32)
    from fedamw_tpu.fedcore.aggregate import weighted_average
    mean_err = np.linalg.norm(
        np.asarray(weighted_average(stacked, w)["w"]) - honest)
    assert mean_err > 10.0  # the undefended mean diverges
    g = {"w": np.zeros(P, np.float32)}
    for spec in ("median", "trim:3", "krum", "mkrum:5", "geomed"):
        agg = make_robust_aggregator(parse_robust_spec(spec))
        out, _aux = agg(g, stacked, w, present)
        err = np.linalg.norm(np.asarray(out["w"]) - honest)
        assert err < 1.0, (spec, err, mean_err)


def test_krum_aggregate_returns_selection_telemetry():
    rng = np.random.RandomState(5)
    x = {"w": rng.randn(6, 4).astype(np.float32)}
    out, selected = krum_aggregate({"w": np.zeros(4, np.float32)}, x,
                                   np.ones(6, np.float32), 3)
    assert np.asarray(selected).sum() == 3
    picked = np.asarray(selected) > 0
    np.testing.assert_allclose(
        np.asarray(out["w"]), np.asarray(x["w"])[picked].mean(0),
        rtol=1e-5)


# -- straggler-exact FedNova ------------------------------------------

def test_fednova_tau_frac_rescales_effective_weights():
    from fedamw_tpu.fedcore.aggregate import fednova_effective_weights
    sizes = np.asarray([100.0, 200.0, 0.0, 50.0], np.float32)
    p = np.asarray([0.3, 0.4, 0.0, 0.3], np.float32)
    frac = np.asarray([1.0, 0.5, 1.0, 1.0], np.float32)
    full = np.asarray(fednova_effective_weights(sizes, p, 2, 32))
    exact = np.asarray(fednova_effective_weights(sizes, p, 2, 32,
                                                 tau_frac=frac))
    # manual FedNova: tau scaled by the work actually done
    tau = sizes * 2 / 32 * frac
    tau_eff = float(np.sum(tau * p))
    want = np.where(tau > 0, p * tau_eff / np.where(tau > 0, tau, 1.0),
                    0.0)
    np.testing.assert_allclose(exact, want, rtol=1e-6)
    # the straggler's PER-STEP weight grows (fewer local steps ->
    # larger normalized weight), and padded clients stay inert
    assert exact[1] > full[1]
    assert exact[2] == 0.0
    # an all-ones fraction is bitwise the full-work weights
    ones = np.asarray(fednova_effective_weights(
        sizes, p, 2, 32, tau_frac=np.ones(4, np.float32)))
    np.testing.assert_array_equal(ones, full)


def test_fault_plan_rows_carry_tau_frac():
    """rows() exposes the per-round work fraction: straggle_frac on
    straggling cells, 1.0 elsewhere — including corrupt cells, whose
    scale is an adversarial multiplier, NOT work done."""
    spec = FaultSpec(straggle=0.4, straggle_frac=0.25, corrupt=0.3,
                     corrupt_mode="scale", corrupt_scale=7.0, seed=1)
    plan = FaultPlan.build(spec, rounds=6, num_clients=10)
    rows = plan.rows(0, 6)
    assert len(rows) == 5
    tau_frac = np.asarray(rows[4])
    np.testing.assert_array_equal(tau_frac[plan.straggle > 0], 0.25)
    np.testing.assert_array_equal(tau_frac[plan.straggle == 0], 1.0)
    assert plan.corrupt.sum() > 0  # the distinction above was exercised


def test_fednova_full_work_straggler_is_bitwise_clean(setup8):
    """straggle_frac=1.0 means the full local work was done: the
    injection is a bitwise no-op AND the straggler-exact tau path
    multiplies by exactly 1.0, so the faulted FedNova run equals the
    clean one array-for-array — pinning that the tau_frac wiring
    cannot perturb a clean round."""
    R, J = KW["round"], setup8.num_clients
    clean = FedNova(setup8, return_state=True, **KW)
    faulted = FedNova(setup8, faults=target_plan(R, J, "straggle", 2,
                                                 frac=1.0),
                      return_state=True, **KW)
    np.testing.assert_array_equal(np.asarray(faulted["params"]["w"]),
                                  np.asarray(clean["params"]["w"]))
    np.testing.assert_array_equal(faulted["test_acc"],
                                  clean["test_acc"])
    assert faulted["fault_counts"]["straggled"].sum() == R


def test_fednova_straggler_exact_tau_changes_the_aggregate(setup8):
    """A true straggler (frac<1) must flow through the tau-exact
    normalization: the run stays finite and differs from clean."""
    R, J = KW["round"], setup8.num_clients
    clean = FedNova(setup8, return_state=True, **KW)
    strag = FedNova(setup8, faults=target_plan(R, J, "straggle", 2,
                                               frac=0.25),
                    return_state=True, **KW)
    assert np.all(np.isfinite(strag["test_loss"]))
    assert not np.array_equal(np.asarray(strag["params"]["w"]),
                              np.asarray(clean["params"]["w"]))


# -- end-to-end: injection, quarantine, equivalences ------------------

def test_zero_rate_spec_matches_clean_run(setup8):
    clean = FedAvg(setup8, return_state=True, **KW)
    zero = FedAvg(setup8, faults="drop=0.0,seed=0", return_state=True,
                  **KW)
    # the fault graph with an all-clean plan reproduces the clean run:
    # params and eval metrics bitwise (clean clients pass through the
    # injection untouched via `where`); train_loss to float tolerance
    # (its weight rescale fuses into the reduction differently)
    np.testing.assert_array_equal(np.asarray(zero["params"]["w"]),
                                  np.asarray(clean["params"]["w"]))
    np.testing.assert_array_equal(zero["test_acc"], clean["test_acc"])
    np.testing.assert_array_equal(zero["test_loss"], clean["test_loss"])
    np.testing.assert_allclose(zero["train_loss"], clean["train_loss"],
                               rtol=1e-5)
    assert all(v.sum() == 0 for v in zero["fault_counts"].values())


@pytest.mark.parametrize("algo, kw", [(FedAvg, KW), (FedAMW, AMW_KW)])
def test_nan_client_quarantined_equals_clean_drop(setup8, algo, kw):
    """The headline robustness contract: a NaN-corrupted client is
    quarantined, the run stays finite, and every array the run
    produces equals the same run with that client cleanly dropped —
    quarantine IS exclusion, not approximation."""
    R, J = KW["round"], setup8.num_clients
    nan_run = algo(setup8, faults=target_plan(R, J, "nan", 2),
                   return_state=True, **kw)
    drop_run = algo(setup8, faults=target_plan(R, J, "drop", 2),
                    return_state=True, **kw)
    for key in ("train_loss", "test_loss", "test_acc"):
        assert np.all(np.isfinite(nan_run[key])), key
        np.testing.assert_array_equal(nan_run[key], drop_run[key],
                                      err_msg=key)
    np.testing.assert_array_equal(np.asarray(nan_run["params"]["w"]),
                                  np.asarray(drop_run["params"]["w"]))
    np.testing.assert_array_equal(np.asarray(nan_run["p"]),
                                  np.asarray(drop_run["p"]))
    # the quarantine caught the poisoned client every round...
    np.testing.assert_array_equal(
        nan_run["fault_counts"]["quarantined"], np.full(R, 1))
    # ...and the faulty run actually differs from the clean one
    clean = algo(setup8, **kw)
    assert not np.allclose(clean["test_loss"], nan_run["test_loss"])


def test_inf_poison_also_quarantined(setup8):
    R, J = KW["round"], setup8.num_clients
    res = FedAvg(setup8, faults=target_plan(R, J, "nan", 1, fill=np.inf),
                 **KW)
    assert np.all(np.isfinite(res["train_loss"]))
    assert res["fault_counts"]["quarantined"].sum() == R


@pytest.mark.parametrize("kind", ["drop", "nan"])
def test_all_clients_faulty_round_leaves_model_unchanged(setup8, kind):
    J = setup8.num_clients
    zeros, ones = np.zeros((1, J), np.float32), np.ones((1, J), np.float32)
    if kind == "drop":
        plan = FaultPlan(ones, zeros, zeros, ones, zeros, zeros)
    else:  # every client reports NaN -> every client quarantined
        plan = FaultPlan(zeros, zeros, ones, ones, ones,
                         np.full((1, J), np.nan, np.float32))
    res = FedAvg(setup8, faults=plan, round=1, return_state=True,
                 **{k: v for k, v in KW.items() if k != "round"})
    init = core._derive_params(setup8.model.init, KW["seed"],
                               setup8.D, setup8.num_classes)
    np.testing.assert_array_equal(np.asarray(res["params"]["w"]),
                                  np.asarray(init["w"]))
    assert np.all(np.isfinite(res["test_loss"]))


def test_straggler_shrinks_the_update(setup8):
    """A straggler's report pulls the aggregate LESS than its full
    update: the faulted round's params differ from clean, stay finite,
    and land between a full drop and the clean run."""
    R, J = KW["round"], setup8.num_clients
    clean = FedAvg(setup8, return_state=True, **KW)
    strag = FedAvg(setup8, faults=target_plan(R, J, "straggle", 0,
                                              frac=0.25),
                   return_state=True, **KW)
    assert np.all(np.isfinite(strag["test_loss"]))
    assert not np.array_equal(np.asarray(strag["params"]["w"]),
                              np.asarray(clean["params"]["w"]))
    assert strag["fault_counts"]["straggled"].sum() == R


def test_fednova_accepts_faults(setup8):
    res = FedNova(setup8, faults="drop=0.25,corrupt=0.25:nan,seed=5",
                  **KW)
    assert np.all(np.isfinite(res["test_loss"]))
    counts = res["fault_counts"]
    assert counts["quarantined"].sum() == counts["corrupted"].sum()


def test_sign_flip_defended_by_median_and_clip(setup8):
    """Finite corruption (sign flip) sails through the non-finite
    quarantine by design (and through the NORM z-test too — a sign
    flip is norm-preserving); the opt-in robust aggregators are the
    defense."""
    R, J = KW["round"], setup8.num_clients
    plan = target_plan(R, J, "sign", 0)
    for agg in ("median", "clip:1+trim:1", "krum", "mkrum:4",
                "geomed:4"):
        res = FedAvg(setup8, faults=plan, robust_agg=agg, **KW)
        assert np.all(np.isfinite(res["test_loss"])), agg
        assert res["fault_counts"]["corrupted"].sum() == R
        assert res["fault_counts"]["quarantined"].sum() == 0


def test_scored_quarantine_catches_scale_attack(setup8):
    """A finite 25x-scaled update slips the non-finite quarantine but
    the delta-norm z-test flags it every round; the defense telemetry
    reports the catch and the quarantined client's weight renormalizes
    away exactly like a drop (array-equal to the clean-drop run)."""
    R, J = KW["round"], setup8.num_clients
    plan = target_plan(R, J, "sign", 2)
    # a scale corruption: reuse the sign-plan plumbing with scale=25
    plan.scale[:, 2] = 25.0
    # Z=5: honest digits clients top out near z~3.3 (real Dirichlet
    # heterogeneity), the 25x attacker lands at z>50 — 5 splits them
    # with a wide margin on both sides
    res = FedAvg(setup8, faults=plan, robust_agg="quarantine:5",
                 return_state=True, **KW)
    assert np.all(np.isfinite(res["test_loss"]))
    d = res["defense"]
    assert d["robust_agg"] == "quarantine:5.0"
    np.testing.assert_array_equal(d["z_quarantined"], np.full(R, 1))
    assert float(np.max(d["z_max"])) > 5.0
    drop = FedAvg(setup8, faults=target_plan(R, J, "drop", 2),
                  return_state=True, **KW)
    np.testing.assert_array_equal(np.asarray(res["params"]["w"]),
                                  np.asarray(drop["params"]["w"]))
    np.testing.assert_array_equal(res["test_acc"], drop["test_acc"])


def test_scored_quarantine_spares_clean_rounds(setup8):
    """quarantine:Z without faults: no honest digits client should
    z-score past a loose threshold, so the run is bitwise the clean
    run (same weights, same present set) and telemetry shows zero."""
    clean = FedAvg(setup8, return_state=True, **KW)
    res = FedAvg(setup8, robust_agg="quarantine:50", return_state=True,
                 **KW)
    assert res["defense"]["z_quarantined"].sum() == 0
    np.testing.assert_array_equal(np.asarray(res["params"]["w"]),
                                  np.asarray(clean["params"]["w"]))
    assert "fault_counts" not in res  # no plan, no fault report


def test_defended_aggregators_emit_telemetry(setup8):
    """mkrum's selection counts and geomed's Weiszfeld residuals reach
    the result's defense record with the documented shapes."""
    R, J = KW["round"], setup8.num_clients
    res = FedAvg(setup8, robust_agg="mkrum:4", **KW)
    d = res["defense"]
    assert d["krum_selected"].shape == (R, J)
    np.testing.assert_array_equal(d["krum_selected"].sum(1),
                                  np.full(R, 4))
    np.testing.assert_array_equal(d["krum_pick_counts"],
                                  d["krum_selected"].sum(0))
    res = FedAvg(setup8, robust_agg="geomed:6", **KW)
    d = res["defense"]
    assert d["geomed_residual"].shape == (R,)
    assert np.all(np.isfinite(d["geomed_residual"]))


def test_robust_agg_without_faults_runs(setup8):
    res = FedAvg(setup8, robust_agg="trim:1", **KW)
    assert np.all(np.isfinite(res["test_loss"]))
    assert "fault_counts" not in res  # no plan, no fault report


# -- FedAMW partial participation / masked p --------------------------

def test_fedamw_accepts_partial_participation(setup8):
    full = FedAMW(setup8, **AMW_KW)
    dflt = FedAMW(setup8, participation=1.0, **AMW_KW)
    np.testing.assert_array_equal(full["test_acc"], dflt["test_acc"])
    half = FedAMW(setup8, participation=0.5, **AMW_KW)
    assert np.all(np.isfinite(half["test_loss"]))
    assert not np.allclose(full["train_loss"], half["train_loss"])


def test_fedamw_dropout_zero_mass_and_masked_simplex(setup8,
                                                     monkeypatch):
    """A client dropped every round earns exactly zero mixture mass,
    and under the simplex guard the learned p lives on the MASKED
    simplex: zero on invalid clients, the rest summing to 1."""
    R, J = AMW_KW["round"], setup8.num_clients
    plan = target_plan(R, J, "drop", 3)
    res = FedAMW(setup8, faults=plan, return_state=True, **AMW_KW)
    assert float(np.asarray(res["p"])[3]) == 0.0  # unguarded too

    monkeypatch.setenv("FEDAMW_P_GUARD", "simplex")
    guarded = FedAMW(setup8, faults=plan, return_state=True, **AMW_KW)
    p = np.asarray(guarded["p"])
    assert p[3] == 0.0
    assert np.all(p >= 0)
    np.testing.assert_allclose(p.sum(), 1.0, atol=1e-5)
    assert np.all(np.isfinite(guarded["test_loss"]))


def test_fedamw_mkrum_zero_mass_on_attacker_and_beats_mean(setup8):
    """The ISSUE 3 acceptance contract: under a persistent sign-flip
    attacker, FedAMW + mkrum quarantines the attacker out of the
    mixture (selection folds into the present mask BEFORE the p-solve,
    so the attacker's learned mass is exactly zero and its picks stay
    at zero) and ends with better validation accuracy than FedAMW +
    mean on the same seed and plan.

    lr_p is deliberately SLOW (1e-4) here: at hot mixture rates the
    unconstrained p-solver is itself a defense — it learns a NEGATIVE
    weight for the sign-flipped client, re-flipping the poison back
    into signal, and FedAMW+mean can even beat clean (measured: p[2]
    -> -0.65 at lr_p=1e-3). The defense plane is for the regimes where
    p cannot adapt within the horizon (slow lr_p, the simplex guard,
    or attacks on the solve itself) — README 'Choosing a robust
    aggregator'."""
    J = setup8.num_clients
    kw = dict(lr=0.5, epoch=1, round=3, seed=0, lr_mode="constant",
              lambda_reg=1e-4, lr_p=1e-4)
    R = kw["round"]
    plan = target_plan(R, J, "sign", 2)
    defended = FedAMW(setup8, faults=plan, robust_agg=f"mkrum:{J - 1}",
                      return_state=True, **kw)
    assert np.all(np.isfinite(defended["test_loss"]))
    p = np.asarray(defended["p"])
    assert float(p[2]) == 0.0  # exactly zero learned mass
    picks = defended["defense"]["krum_pick_counts"]
    assert picks[2] == 0  # never selected
    assert picks.sum() == R * (J - 1)
    undefended = FedAMW(setup8, faults=plan, return_state=True, **kw)
    assert (float(defended["test_acc"][-1])
            > float(undefended["test_acc"][-1]))
    # the attacker keeps nonzero mass in the undefended run — the
    # defended zero is the selection's doing, not the solver's
    assert float(np.asarray(undefended["p"])[2]) != 0.0


# -- zero-recompile + resume contracts --------------------------------

def test_fault_plan_change_adds_no_recompile(setup8):
    """The plan rows are DATA (scanned inputs), not program structure:
    two runs under different plans share one trainer and one compiled
    XLA program — the bench-grade zero-recompile contract, read from
    the jit cache counter like tests/test_serve_contract.py."""
    FedAvg(setup8, faults="drop=0.4,corrupt=0.1:nan,seed=0", **KW)
    fn = core._LAST_TRAIN_FN
    size0 = fn._cache_size() if hasattr(fn, "_cache_size") else None
    FedAvg(setup8, faults="drop=0.1,straggle=0.3:0.5,seed=99", **KW)
    assert core._LAST_TRAIN_FN is fn  # same memoized trainer
    if size0 is not None:
        assert fn._cache_size() == size0  # same compiled program


@pytest.mark.parametrize("agg", ["krum", "mkrum:3", "geomed:4",
                                 "quarantine:3",
                                 "clip:5+quarantine:3+mkrum:6",
                                 "rep:0.5:0.2", "quarantine:auto",
                                 "rep:0.9:0.2+quarantine:auto",
                                 "rep:0.8:0.1+quarantine:4+mkrum:6"])
def test_new_defense_tokens_compile_one_round_program(setup8, agg):
    """ISSUE 3/4 acceptance: every new spec token — including the
    STATEFUL ones, whose cross-round reputation / auto-threshold state
    rides the scan carry as fixed-shape leaves — compiles exactly one
    round program across varying per-round fault plans: the defense is
    program STRUCTURE, the plan (and the carried state) is data."""
    FedAvg(setup8, faults="corrupt=0.3:sign,seed=1", robust_agg=agg,
           **KW)
    fn = core._LAST_TRAIN_FN
    size0 = fn._cache_size() if hasattr(fn, "_cache_size") else None
    FedAvg(setup8, faults="corrupt=0.1:scale:9,drop=0.2,seed=77",
           robust_agg=agg, **KW)
    assert core._LAST_TRAIN_FN is fn
    if size0 is not None:
        assert fn._cache_size() == size0
    # equivalent spellings share the SAME memoized trainer (canonical
    # spec keys the cache): e.g. 'geomed' == 'geomed:8'
    if agg == "geomed:4":
        FedAvg(setup8, faults="corrupt=0.1:sign,seed=3",
               robust_agg="GEOMED:4", **KW)
        assert core._LAST_TRAIN_FN is fn


def test_faults_resume_replays_identical_plan(setup8):
    """Prefix + resume == the uninterrupted faulty run: plan rows are
    generated for the FULL horizon and sliced, exactly like the LR
    schedule and key streams."""
    kw = dict(lr=0.5, epoch=1, batch_size=32, seed=0,
              lr_mode="reference", faults="drop=0.3,corrupt=0.2:nan,seed=3")
    full = FedAvg(setup8, round=4, return_state=True, **kw)
    prefix = FedAvg(setup8, round=4, stop_round=2, return_state=True,
                    **kw)
    resumed = FedAvg(setup8, round=4, start_round=2,
                     resume_from={"params": prefix["params"]},
                     return_state=True, **kw)
    np.testing.assert_array_equal(resumed["test_acc"],
                                  np.asarray(full["test_acc"])[2:])
    np.testing.assert_array_equal(np.asarray(resumed["params"]["w"]),
                                  np.asarray(full["params"]["w"]))
    np.testing.assert_array_equal(
        resumed["fault_counts"]["quarantined"],
        full["fault_counts"]["quarantined"][2:])


# -- surface checks ---------------------------------------------------

def test_oneshot_algorithms_reject_faults(setup8):
    from fedamw_tpu.algorithms import Centralized, Distributed
    for fn in (Centralized, Distributed, FedAMW_OneShot):
        with pytest.raises(ValueError, match="faults"):
            fn(setup8, epoch=1, faults="drop=0.1")
        with pytest.raises(ValueError, match="faults"):
            fn(setup8, epoch=1, robust_agg="median")


def test_fault_counts_and_report(setup8):
    res = FedAvg(setup8, faults="drop=0.5,seed=2", **KW)
    counts = res["fault_counts"]
    valid = (np.asarray(setup8.sizes) > 0)
    plan = FaultPlan.build(FaultSpec(drop=0.5, seed=2), KW["round"],
                           setup8.num_clients)
    np.testing.assert_array_equal(
        counts["dropped"], (plan.drop * valid).sum(1).astype(int))

    from fedamw_tpu.utils.reporting import (fault_summary,
                                            format_fault_report)
    s = fault_summary(counts)
    assert s["total_dropped"] == counts["dropped"].sum()
    assert s["rounds"] == KW["round"]
    line = format_fault_report("FedAvg", counts)
    assert "FedAvg" in line and f"{s['total_dropped']} dropped" in line


def test_defense_summary_and_report(setup8):
    R, J = KW["round"], setup8.num_clients
    plan = target_plan(R, J, "sign", 1)
    plan.scale[:, 1] = 30.0
    res = FedAvg(setup8, faults=plan,
                 robust_agg="quarantine:5+mkrum:6", **KW)
    from fedamw_tpu.utils.reporting import (defense_summary,
                                            format_defense_report)
    d = res["defense"]
    s = defense_summary(d)
    assert s["robust_agg"] == "quarantine:5.0+mkrum:6"
    assert s["total_z_quarantined"] == d["z_quarantined"].sum() == R
    assert s["max_z"] > 5.0
    assert s["krum_least_picked"][1] <= s["krum_most_picked"][1]
    line = format_defense_report("FedAvg", d)
    assert "FedAvg defense" in line
    assert "z-quarantined" in line and "krum picks" in line

    res_g = FedAvg(setup8, robust_agg="geomed:4", **KW)
    line_g = format_defense_report("FedAvg", res_g["defense"])
    assert "weiszfeld residual" in line_g

    # padded (sizes==0) clients are masked out of the per-client pick
    # stats: a padding column with 0 picks must not be named "least
    # picked" / counted as "never selected"
    fake = {"robust_agg": "mkrum:2",
            "krum_pick_counts": np.asarray([3, 1, 2, 0]),
            "client_valid": np.asarray([1, 1, 1, 0])}
    sf = defense_summary(fake)
    assert sf["krum_least_picked"] == (1, 1)
    assert sf["krum_never_picked"] == 0
