import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedamw_tpu.fedcore import (
    client_logits,
    fednova_effective_weights,
    make_client_round,
    make_evaluator,
    make_local_update,
    make_p_solver,
    weighted_average,
)
from fedamw_tpu.models import linear_model


def _torch_full_batch_sgd(w0, X, y, lr, epochs, mu, lam, task):
    """Trusted torch re-statement of the reference train_loop objective
    (tools.py:193-211) with batch_size >= n, so no shuffle dependence."""
    import torch

    w = torch.tensor(np.array(w0), requires_grad=True)
    anchor = torch.tensor(np.array(w0))
    Xt = torch.tensor(np.array(X))
    if task == "classification":
        yt = torch.tensor(np.array(y), dtype=torch.long)
        crit = torch.nn.CrossEntropyLoss()
    else:
        yt = torch.tensor(np.array(y)).reshape(-1, 1)
        crit = torch.nn.MSELoss()
    last_loss = None
    for _ in range(epochs):
        out = Xt @ w.T
        loss = crit(out, yt) + mu * (w - anchor).norm(2) + lam * torch.norm(w, "fro")
        (g,) = torch.autograd.grad(loss, w)
        last_loss = float(loss)
        w = (w - lr * g).detach().requires_grad_()
    return w.detach().numpy(), last_loss


@pytest.fixture
def small_problem():
    rng = np.random.RandomState(0)
    n, d, C = 24, 6, 3
    X = rng.randn(n, d).astype(np.float32)
    y = rng.randint(0, C, n).astype(np.int32)
    model = linear_model()
    w0 = model.init(jax.random.PRNGKey(0), d, C)
    return X, y, model, w0


class TestLocalUpdateParity:
    @pytest.mark.parametrize(
        "mu,lam", [(0.0, 0.0), (0.3, 0.0), (0.0, 0.2), (0.3, 0.2)]
    )
    def test_full_batch_matches_torch(self, small_problem, mu, lam):
        X, y, model, w0 = small_problem
        n = len(y)
        lu = make_local_update(model.apply, "classification", 3, n, n)
        idx = jnp.arange(n, dtype=jnp.int32)
        mask = jnp.ones(n)
        new_p, loss, _ = lu(
            w0, jnp.array(X), jnp.array(y), idx, mask,
            jax.random.PRNGKey(5), 0.1, mu, lam,
        )
        want_w, want_loss = _torch_full_batch_sgd(
            w0["w"], X, y, 0.1, 3, mu, lam, "classification"
        )
        np.testing.assert_allclose(np.array(new_p["w"]), want_w, atol=1e-5)
        # returned loss is the last epoch's (pre-step) objective
        assert float(loss) == pytest.approx(want_loss, abs=1e-5)

    def test_regression_full_batch(self, small_problem):
        X, _, model, _ = small_problem
        n = X.shape[0]
        yreg = (X @ np.ones(X.shape[1])).astype(np.float32)
        w0 = {"w": jnp.zeros((1, X.shape[1]))}
        lu = make_local_update(model.apply, "regression", 2, n, n)
        new_p, loss, acc = lu(
            w0, jnp.array(X), jnp.array(yreg),
            jnp.arange(n, dtype=jnp.int32), jnp.ones(n),
            jax.random.PRNGKey(0), 0.01, 0.0, 0.0,
        )
        want_w, want_loss = _torch_full_batch_sgd(
            np.zeros((1, X.shape[1]), np.float32), X, yreg, 0.01, 2, 0.0, 0.0,
            "regression",
        )
        np.testing.assert_allclose(np.array(new_p["w"]), want_w, atol=1e-5)
        assert float(loss) == pytest.approx(want_loss, abs=1e-5)
        assert float(acc) == 0.0

    def test_padding_is_inert(self, small_problem):
        X, y, model, w0 = small_problem
        n = len(y)
        n_max = n + 8
        lu = make_local_update(model.apply, "classification", 2, 8, n_max)
        mask = jnp.concatenate([jnp.ones(n), jnp.zeros(8)])
        # identical real rows, two different garbage paddings
        idx_a = jnp.concatenate([jnp.arange(n), jnp.zeros(8, jnp.int32)]).astype(jnp.int32)
        idx_b = jnp.concatenate([jnp.arange(n), jnp.full(8, n - 1, jnp.int32)]).astype(jnp.int32)
        out_a = lu(w0, jnp.array(X), jnp.array(y), idx_a, mask,
                   jax.random.PRNGKey(3), 0.1, 0.1, 0.1)
        out_b = lu(w0, jnp.array(X), jnp.array(y), idx_b, mask,
                   jax.random.PRNGKey(3), 0.1, 0.1, 0.1)
        np.testing.assert_allclose(
            np.array(out_a[0]["w"]), np.array(out_b[0]["w"]), atol=1e-6
        )
        assert float(out_a[1]) == pytest.approx(float(out_b[1]), abs=1e-6)

    def test_empty_client_is_identity(self, small_problem):
        X, y, model, w0 = small_problem
        lu = make_local_update(model.apply, "classification", 2, 8, 16)
        new_p, loss, acc = lu(
            w0, jnp.array(X), jnp.array(y),
            jnp.zeros(16, jnp.int32), jnp.zeros(16),
            jax.random.PRNGKey(0), 0.1, 0.0, 0.0,
        )
        np.testing.assert_allclose(np.array(new_p["w"]), np.array(w0["w"]))
        assert float(loss) == 0.0


class TestClientRound:
    def _pack(self, X, y, parts, n_max):
        J = len(parts)
        idx = np.zeros((J, n_max), np.int32)
        mask = np.zeros((J, n_max), np.float32)
        for j, p in enumerate(parts):
            idx[j, : len(p)] = p
            mask[j, : len(p)] = 1.0
        return jnp.array(idx), jnp.array(mask)

    def test_parallel_equals_individual(self, small_problem):
        X, y, model, w0 = small_problem
        parts = [np.arange(0, 10), np.arange(10, 24)]
        idx, mask = self._pack(X, y, parts, 14)
        keys = jax.random.split(jax.random.PRNGKey(9), 2)
        rf = make_client_round(model.apply, "classification", 2, 4, 14)
        stacked, losses, accs = rf(
            w0, jnp.array(X), jnp.array(y), idx, mask, keys, 0.1, 0.0, 0.0
        )
        lu = make_local_update(model.apply, "classification", 2, 4, 14)
        for j in range(2):
            pj, lj, aj = lu(
                w0, jnp.array(X), jnp.array(y), idx[j], mask[j], keys[j],
                0.1, 0.0, 0.0,
            )
            np.testing.assert_allclose(
                np.array(stacked["w"][j]), np.array(pj["w"]), atol=1e-6
            )
            assert float(losses[j]) == pytest.approx(float(lj), abs=1e-6)

    def test_sequential_contamination(self, small_problem):
        X, y, model, w0 = small_problem
        parts = [np.arange(0, 12), np.arange(12, 24)]
        idx, mask = self._pack(X, y, parts, 12)
        keys = jax.random.split(jax.random.PRNGKey(9), 2)
        rf_seq = make_client_round(
            model.apply, "classification", 2, 12, 12, sequential=True
        )
        stacked, _, _ = rf_seq(
            w0, jnp.array(X), jnp.array(y), idx, mask, keys, 0.1, 0.0, 0.0
        )
        # client 0 starts from the global params...
        lu = make_local_update(model.apply, "classification", 2, 12, 12)
        p0, _, _ = lu(w0, jnp.array(X), jnp.array(y), idx[0], mask[0], keys[0],
                      0.1, 0.0, 0.0)
        np.testing.assert_allclose(np.array(stacked["w"][0]), np.array(p0["w"]),
                                   atol=1e-6)
        # ...and client 1 starts from client 0's result (the reference quirk)
        p1, _, _ = lu(p0, jnp.array(X), jnp.array(y), idx[1], mask[1], keys[1],
                      0.1, 0.0, 0.0)
        np.testing.assert_allclose(np.array(stacked["w"][1]), np.array(p1["w"]),
                                   atol=1e-6)


class TestAggregate:
    def test_weighted_average_closed_form(self):
        stacked = {"w": jnp.stack([jnp.full((2, 2), 1.0), jnp.full((2, 2), 3.0)])}
        p = jnp.array([0.25, 0.75])
        out = weighted_average(stacked, p)
        np.testing.assert_allclose(np.array(out["w"]), np.full((2, 2), 2.5))

    def test_fednova_weights(self):
        sizes = jnp.array([100, 300])
        p = jnp.array([0.25, 0.75])
        w = fednova_effective_weights(sizes, p, epochs=2, batch_size=32)
        tau = np.array([100 * 2 / 32, 300 * 2 / 32])
        tau_eff = (tau * np.array([0.25, 0.75])).sum()
        np.testing.assert_allclose(
            np.array(w), np.array([0.25, 0.75]) * tau_eff / tau, rtol=1e-6
        )

    def test_fednova_weights_padded_clients_inert(self):
        # padded clients (size 0, p 0) must not produce NaNs (0/0)
        sizes = jnp.array([100, 80, 0, 0])
        p = jnp.array([100 / 180, 80 / 180, 0.0, 0.0])
        w = fednova_effective_weights(sizes, p, epochs=2, batch_size=32)
        assert np.all(np.isfinite(np.array(w)))
        np.testing.assert_allclose(np.array(w[2:]), 0.0)

    def test_client_logits_matches_reference_einsum(self):
        model = linear_model()
        J, C, D, n = 3, 4, 5, 7
        rng = np.random.RandomState(0)
        W = rng.randn(J, C, D).astype(np.float32)
        X = rng.randn(n, D).astype(np.float32)
        out = client_logits(model.apply, {"w": jnp.array(W)}, jnp.array(X))
        want = np.einsum("jcd,nd->njc", W, X)
        np.testing.assert_allclose(np.array(out), want, atol=1e-5)


class TestPSolver:
    def test_momentum_matches_torch(self):
        """One full-coverage batch per epoch -> deterministic; check the
        SGD-momentum recurrence against torch (tools.py:423)."""
        import torch

        rng = np.random.RandomState(0)
        n_val, J, C = 8, 2, 3
        logits = rng.randn(n_val, J, C).astype(np.float32)
        y = rng.randint(0, C, n_val).astype(np.int32)
        p0 = np.array([0.5, 0.5], np.float32)

        solve, init_opt = make_p_solver(
            "classification", n_val, batch_size=n_val, lr_p=0.1, momentum=0.9
        )
        p, opt, loss, acc = solve(
            jnp.array(logits), jnp.array(y), jnp.array(p0), init_opt(jnp.array(p0)),
            jax.random.PRNGKey(0), 3,
        )

        pt = torch.tensor(p0, requires_grad=True)
        opt_t = torch.optim.SGD([pt], lr=0.1, momentum=0.9)
        lt = torch.tensor(logits)
        yt = torch.tensor(y, dtype=torch.long)
        for _ in range(3):
            opt_t.zero_grad()
            out = torch.einsum("bjc,j->bc", lt, pt)
            torch.nn.CrossEntropyLoss()(out, yt).backward()
            opt_t.step()
        np.testing.assert_allclose(np.array(p), pt.detach().numpy(), atol=1e-5)

    def test_p_moves_toward_good_client(self):
        rng = np.random.RandomState(1)
        n_val, C = 64, 4
        y = rng.randint(0, C, n_val).astype(np.int32)
        good = np.eye(C, dtype=np.float32)[y] * 10.0
        bad = rng.randn(n_val, C).astype(np.float32)
        logits = np.stack([good, bad], axis=1)  # (n, J=2, C)
        p0 = jnp.array([0.5, 0.5])
        solve, init_opt = make_p_solver(
            "classification", n_val, batch_size=16, lr_p=0.05, momentum=0.9
        )
        p, _, loss, acc = solve(
            jnp.array(logits), jnp.array(y), p0, init_opt(p0),
            jax.random.PRNGKey(0), 20,
        )
        assert float(p[0]) > float(p[1])
        assert float(acc) > 90.0


def test_evaluator_matches_torch(small_problem):
    import torch

    X, y, model, w0 = small_problem
    ev = make_evaluator(model.apply, "classification")
    loss, acc = ev(w0, jnp.array(X), jnp.array(y))
    out = torch.tensor(np.array(X)) @ torch.tensor(np.array(w0["w"])).T
    want = torch.nn.CrossEntropyLoss()(out, torch.tensor(np.array(y), dtype=torch.long))
    assert float(loss) == pytest.approx(float(want), abs=1e-5)
    want_acc = 100.0 * float(
        (out.argmax(1) == torch.tensor(np.array(y))).float().mean()
    )
    assert float(acc) == pytest.approx(want_acc, abs=1e-4)


def test_scan_unroll_env_override(monkeypatch):
    """FEDAMW_SCAN_UNROLL tunes the client-SGD scan unroll (the window
    harvest's hardware sweep) and is part of the trainer cache key so a
    program compiled under one setting is never reused under another."""
    from fedamw_tpu.algorithms.core import _kernel_env
    from fedamw_tpu.fedcore.client import SGD_SCAN_UNROLL, scan_unroll

    monkeypatch.delenv("FEDAMW_SCAN_UNROLL", raising=False)
    assert scan_unroll() == SGD_SCAN_UNROLL
    base_key = _kernel_env()
    monkeypatch.setenv("FEDAMW_SCAN_UNROLL", "4")
    assert scan_unroll() == 4
    assert _kernel_env() != base_key
