"""FedOpt server optimizers (extension — Reddi et al. 2021,
arXiv:2003.00295; the reference always overwrites the global model with
the weighted average, ``tools.py:350``).

The server update is one optimizer step on the pseudo-gradient
``g_t = w_t - aggregate_t``. Invariant: ``server_opt="sgd"`` with
``server_lr=1.0`` IS the reference rule.
"""

import numpy as np
import pytest

from fedamw_tpu.algorithms import FedAMW, FedAvg, prepare_setup
from fedamw_tpu.backends import torch_ref
from fedamw_tpu.data import load_dataset


@pytest.fixture(scope="module")
def setup6():
    ds = load_dataset("digits", num_partitions=6, alpha=0.5)
    return prepare_setup(ds, kernel_type="linear", seed=3,
                         rng=np.random.RandomState(3))


@pytest.fixture(scope="module")
def tsetup6():
    ds = load_dataset("digits", num_partitions=6, alpha=0.5)
    return torch_ref.prepare_setup(ds, kernel_type="linear", seed=3,
                                   rng=np.random.RandomState(3))


KW = dict(lr=0.5, epoch=1, batch_size=32, round=4, seed=0,
          lr_mode="constant")


def test_server_sgd_lr1_is_reference_rule_jax(setup6):
    vanilla = FedAvg(setup6, **KW)
    sgd1 = FedAvg(setup6, server_opt="sgd", server_lr=1.0, **KW)
    np.testing.assert_allclose(np.asarray(sgd1["test_acc"]),
                               np.asarray(vanilla["test_acc"]), atol=1e-4)
    np.testing.assert_allclose(np.asarray(sgd1["test_loss"]),
                               np.asarray(vanilla["test_loss"]), atol=1e-5)


def test_server_sgd_lr1_is_reference_rule_torch(tsetup6):
    vanilla = torch_ref.FedAvg(tsetup6, **KW)
    sgd1 = torch_ref.FedAvg(tsetup6, server_opt="sgd", server_lr=1.0, **KW)
    np.testing.assert_allclose(np.asarray(sgd1["test_acc"]),
                               np.asarray(vanilla["test_acc"]), atol=1e-4)


@pytest.mark.parametrize("backend_fedavg", ["jax", "torch"])
def test_fedadam_learns_and_differs(backend_fedavg, setup6, tsetup6):
    fn, s = ((FedAvg, setup6) if backend_fedavg == "jax"
             else (torch_ref.FedAvg, tsetup6))
    vanilla = fn(s, **KW)
    adam = fn(s, server_opt="adam", server_lr=0.1, **KW)
    assert np.all(np.isfinite(np.asarray(adam["test_loss"])))
    assert not np.allclose(np.asarray(adam["test_acc"]),
                           np.asarray(vanilla["test_acc"]))
    assert np.asarray(adam["test_acc"])[-1] > 50.0  # still learns


@pytest.mark.parametrize("opt", ["adam", "yogi", "adagrad"])
def test_fedopt_matches_across_backends_on_fixed_stream(opt):
    """Each optimizer's formulas must agree exactly across backends:
    drive both update rules with the same pseudo-gradient sequence
    (the torch mirror replicates optax's math, accumulator inits, and
    bias corrections)."""
    import jax.numpy as jnp
    import optax
    import torch

    rng = np.random.RandomState(0)
    grads = [rng.randn(3, 5).astype(np.float32) for _ in range(6)]

    tx = {"adam": optax.adam(0.1, b1=0.9, b2=0.99, eps=1e-3),
          "yogi": optax.yogi(0.1, b1=0.9, b2=0.99, eps=1e-3),
          "adagrad": optax.adagrad(0.1)}[opt]
    w_j = jnp.zeros((3, 5))
    st = tx.init(w_j)
    for g in grads:
        up, st = tx.update(jnp.asarray(g), st, w_j)
        w_j = optax.apply_updates(w_j, up)

    init = {"yogi": 1e-6, "adagrad": 0.1}.get(opt, 0.0)
    w_t = torch.zeros(3, 5)
    m = torch.full((3, 5), init)
    v = torch.full((3, 5), init)
    b1, b2, eps = 0.9, 0.99, 1e-3
    for t, g in enumerate(grads):
        gt = torch.tensor(g)
        if opt == "adagrad":
            v = v + gt * gt
            inv = torch.where(v > 0, torch.rsqrt(v + 1e-7),
                              torch.zeros_like(v))
            w_t = w_t - 0.1 * gt * inv
            continue
        m = b1 * m + (1 - b1) * gt
        if opt == "yogi":
            g2 = gt * gt
            v = v - (1 - b2) * torch.sign(v - g2) * g2
        else:
            v = b2 * v + (1 - b2) * gt * gt
        m_hat = m / (1 - b1 ** (t + 1))
        v_hat = v / (1 - b2 ** (t + 1))
        w_t = w_t - 0.1 * m_hat / (torch.sqrt(v_hat) + eps)
    np.testing.assert_allclose(np.asarray(w_j), w_t.numpy(),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("backend", ["jax", "torch"])
def test_fedamw_rejects_server_opt(backend, setup6, tsetup6):
    fn, s = ((FedAMW, setup6) if backend == "jax"
             else (torch_ref.FedAMW, tsetup6))
    with pytest.raises(ValueError, match="server_opt"):
        fn(s, round=2, server_opt="adam")


def test_invalid_server_opt_rejected(setup6):
    with pytest.raises(ValueError, match="server_opt"):
        FedAvg(setup6, round=2, server_opt="rmsprop")


@pytest.mark.parametrize("opt,slr", [("yogi", 0.1), ("adagrad", 0.5)])
@pytest.mark.parametrize("backend_fedavg", ["jax", "torch"])
def test_fedyogi_adagrad_run_e2e(opt, slr, backend_fedavg, setup6, tsetup6):
    # adagrad's monotone accumulator shrinks steps fast, so it needs a
    # larger server_lr to clear the bar in 4 rounds
    fn, s = ((FedAvg, setup6) if backend_fedavg == "jax"
             else (torch_ref.FedAvg, tsetup6))
    res = fn(s, server_opt=opt, server_lr=slr, **KW)
    assert np.all(np.isfinite(np.asarray(res["test_loss"])))
    assert np.asarray(res["test_acc"])[-1] > 50.0
