"""tools/graftlint — the static-analysis gate (ISSUE 10).

Four layers, all tier-1:

- **Per-rule fixtures**: for each of GL001-GL006, a minimal offender
  that MUST flag and a near-miss that MUST NOT — the rule's contract,
  pinned as code (a linter whose rules drift silently is worse than
  none; these are its own regression pins).
- **Suppression / baseline round-trip**: inline ``# graftlint:
  disable=`` requires a reason; the baseline file round-trips
  fingerprints and an EMPTY baseline (what this repo commits) gates
  every finding.
- **The repo-wide gate**: the shipped package lints CLEAN — zero
  unsuppressed findings — so a new trace hazard / lock violation /
  swallowed exception fails ``pytest -m 'not slow'``.
- **Mutation checks** (the acceptance criterion): re-introducing a
  fixed bug or stripping a committed suppression in a copy of the REAL
  package turns the gate red — proof the gate is live, not
  vacuously green.
"""

import json
import os
import shutil
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.graftlint import (ALL_RULES, RULES, SCHEMA,  # noqa: E402
                             default_package_root, run_lint)
from tools.graftlint.cli import main as cli_main  # noqa: E402
from tools.graftlint.cli import report_json  # noqa: E402
from tools.graftlint.suppress import (apply_baseline,  # noqa: E402
                                      load_baseline, parse_disables,
                                      save_baseline)

pytestmark = pytest.mark.graftlint

PKG = default_package_root()


def lint_src(tmp_path, source, name="mod.py", rules=None):
    """Lint one snippet as a tiny package; returns (findings,
    suppressed)."""
    path = tmp_path / name
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return run_lint(str(tmp_path), rules=rules)


def rules_of(findings):
    return sorted({f.rule for f in findings})


# -- GL001: trace hazards ---------------------------------------------

def test_gl001_flags_python_if_on_traced_value(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """)
    assert rules_of(findings) == ["GL001"]
    assert "if" in findings[0].message


def test_gl001_flags_concretizers_and_scan_body(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import jax
        import numpy as np

        def run(xs):
            def body(carry, x):
                k = float(carry)
                h = np.asarray(x)
                j = x.item()
                return carry, x
            return jax.lax.scan(body, 0.0, xs)
    """)
    msgs = " | ".join(f.message for f in findings)
    assert rules_of(findings) == ["GL001"]
    assert len(findings) == 3
    assert "float(" in msgs and "np.asarray" in msgs and ".item()" in msgs


def test_gl001_follows_package_calls_with_traced_args(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import jax

        def helper(v):
            while v > 1:
                v = v - 1
            return v

        @jax.jit
        def f(x):
            return helper(x)
    """)
    assert rules_of(findings) == ["GL001"]
    assert "while" in findings[0].message


def test_gl001_near_misses_stay_silent(tmp_path):
    # is-None tests, static attrs (.shape/.ndim), len(), static
    # argnames, and branching on a helper's TRACE-TIME-STATIC return
    # are all how shape-stable jax code is supposed to look
    findings, _ = lint_src(tmp_path, """
        import jax
        from functools import partial

        def resolve(params, forced):
            if forced:
                return "pallas"
            return "xla"

        @partial(jax.jit, static_argnames=("mode",))
        def f(x, mode, y=None):
            if mode == "fast":
                x = x * 2
            if y is not None:
                x = x + y
            if x.ndim == 2 and x.shape[0] > 4:
                x = x[:4]
            if len(x) > 2:
                x = x * 1.0
            impl = resolve(x, False)
            if impl.startswith("pallas"):
                x = x + 1
            return x
    """)
    assert findings == []


# -- GL002: recompile hazards in hot paths ----------------------------

def test_gl002_flags_fresh_jit_in_hot_path(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import jax

        class ServingEngine:
            def predict(self, X):
                fn = jax.jit(lambda v: v * 2)
                return fn(X)
    """, name="serving/engine.py")
    assert "GL002" in rules_of(findings)
    assert "fresh `jax.jit`" in findings[0].message


def test_gl002_flags_shape_keyed_cache_in_hot_path(tmp_path):
    findings, _ = lint_src(tmp_path, """
        class ServingEngine:
            def _run(self, X):
                self._cache[X.shape] = 1
                self._seen.add(X.dtype)
                return X
    """, name="serving/engine.py")
    assert rules_of(findings) == ["GL002"]
    assert len(findings) == 2


def test_gl002_near_misses_stay_silent(tmp_path):
    # jit at construction time, and shapes in ERROR MESSAGES (raise
    # paths are not hot), are the blessed patterns
    findings, _ = lint_src(tmp_path, """
        import jax

        class ServingEngine:
            def __init__(self):
                self._predict = jax.jit(lambda v: v)

            def predict(self, X):
                if X.ndim != 2:
                    raise ValueError(f"bad shape {X.shape}")
                return self._predict(X)
    """, name="serving/engine.py")
    assert findings == []


# -- GL003: host sync in hot paths ------------------------------------

def test_gl003_flags_device_sync_in_hot_path(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import numpy as np

        class ServingEngine:
            def _run(self, X):
                out = self._predict(X)
                out.block_until_ready()
                return np.asarray(out)
    """, name="serving/engine.py")
    assert rules_of(findings) == ["GL003"]
    assert len(findings) == 2


def test_gl002_flags_shape_keyed_cache_in_ladder_learner(tmp_path):
    """The ISSUE 13 hot-path extension: the ladder learner's read path
    is polled against live traffic, and a shape-keyed cache there is
    the exact recompile-hazard pattern the learned ladder exists to
    remove — GL002 must catch it."""
    findings, _ = lint_src(tmp_path, """
        class LadderLearner:
            def propose(self, current, X):
                self._cache[X.shape] = current
                self._seen.add(X.dtype)
                return current
    """, name="serving/ladder.py")
    assert rules_of(findings) == ["GL002"]
    assert len(findings) == 2


def test_gl002_ladder_learner_near_miss_stays_silent(tmp_path):
    # the REAL learner's shape: integer row-count samples from the
    # registry series, no array shapes anywhere near a cache key —
    # and shapes in raise messages stay blessed
    findings, _ = lint_src(tmp_path, """
        class LadderLearner:
            def observed_sizes(self):
                return [int(v) for v in self.registry.values()]

            def propose(self, current, X=None):
                sizes = self.observed_sizes()
                if X is not None and X.ndim != 2:
                    raise ValueError(f"bad payload {X.shape}")
                return tuple(sorted(set(sizes)))
    """, name="serving/ladder.py")
    assert findings == []


def test_gl003_flags_host_sync_in_admission_loop(tmp_path):
    """The ISSUE 13 hot-path extension: the continuous-admission loop
    runs once per dispatch on the worker thread — a device sync inside
    it is a per-batch stall GL003 must catch."""
    findings, _ = lint_src(tmp_path, """
        import numpy as np

        def admit(q, seed, engine):
            out = engine.predict(seed)
            out.block_until_ready()
            return np.asarray(out)
    """, name="serving/batcher.py")
    assert rules_of(findings) == ["GL003"]
    assert len(findings) == 2


def test_gl003_admission_loop_near_miss_stays_silent(tmp_path):
    # the REAL admit: queue ops and row arithmetic only — no device
    # values in sight (np work on the request PAYLOADS is host->host)
    findings, _ = lint_src(tmp_path, """
        import queue

        def admit(q, seed, max_rows):
            batch = list(seed) if isinstance(seed, list) else [seed]
            rows = sum(r.rows for r in batch)
            while rows < max_rows:
                try:
                    nxt = q.get_nowait()
                except queue.Empty:
                    break
                if rows + nxt.rows > max_rows:
                    return batch, nxt
                batch.append(nxt)
                rows += nxt.rows
            return batch, None
    """, name="serving/batcher.py")
    assert findings == []


def test_gl003_flags_host_sync_in_admission_decision(tmp_path):
    """The ISSUE 14 hot-path extension: the admission decision runs on
    EVERY submit — a device sync inside it taxes the admission path
    itself, exactly what GL003 exists to catch."""
    findings, _ = lint_src(tmp_path, """
        import numpy as np

        class AdmissionController:
            def admit(self, slo_class, engine, x):
                out = engine.predict(x)
                out.block_until_ready()
                return np.asarray(out)
    """, name="serving/control.py")
    assert rules_of(findings) == ["GL003"]
    assert len(findings) == 2


def test_gl002_flags_shape_keyed_cache_in_autoscaler_tick(tmp_path):
    """The autoscaler tick polls against live traffic — a shape-keyed
    cache there is the recompile-hazard pattern GL002 exists to
    catch, same as the ladder learner's read path."""
    findings, _ = lint_src(tmp_path, """
        class Autoscaler:
            def tick(self, X):
                self._cache[X.shape] = 1
                self._seen.add(X.dtype)
                return X

        class AdmissionController:
            def _evaluate(self, now, X):
                self._plans[X.shape] = now
    """, name="serving/control.py")
    assert rules_of(findings) == ["GL002"]
    assert len(findings) == 3


def test_control_plane_near_misses_stay_silent(tmp_path):
    # the REAL shapes: pure registry reads, cached-set lookups, and
    # counter arithmetic — no device values, no array-shape keys
    # anywhere near the decision (shapes in raise messages stay
    # blessed)
    findings, _ = lint_src(tmp_path, """
        class AdmissionController:
            def admit(self, slo_class, now=None):
                now = self.clock() if now is None else now
                with self._lock:
                    if now - self._last_eval >= self.interval_s:
                        self._evaluate(now)
                    return slo_class not in self._shed

            def _evaluate(self, now):
                burns = self._evaluator.burn_rates(self.window_s,
                                                   now=now)
                hot = [n for n, rec in burns.items()
                       if rec["burn_rate"] is not None
                       and rec["burn_rate"] > self.burn_threshold]
                if hot:
                    self._level = min(self._level + 1,
                                      len(self.shed_order))
                self._shed = frozenset(self.shed_order[:self._level])

        class Autoscaler:
            def tick(self, now, X=None):
                if X is not None and X.ndim != 2:
                    raise ValueError(f"bad evidence shape {X.shape}")
                size = self.router.fleet_size()
                if size < self.max_replicas and self._hot >= 2:
                    self.router.add_replica(self.factory(size))
                return size
    """, name="serving/control.py")
    assert findings == []


def test_gl003_near_misses_stay_silent(tmp_path):
    # converting the INPUT (host->host) is fine; so is converting a
    # dispatch result outside the hot-path set
    findings, _ = lint_src(tmp_path, """
        import numpy as np

        class ServingEngine:
            def _run(self, X):
                X = np.asarray(X, dtype=np.float32)
                return self._predict(X)

            def debug_dump(self, X):
                out = self._predict(X)
                return np.asarray(out)
    """, name="serving/engine.py")
    assert findings == []


# -- GL004: lock discipline -------------------------------------------

def test_gl004_flags_blocking_under_lock(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def slow(self):
                with self._lock:
                    time.sleep(0.1)

            def io(self):
                with self._lock:
                    with open("/tmp/x") as f:
                        return f.read()
    """)
    assert rules_of(findings) == ["GL004"]
    assert len(findings) >= 2


def test_gl004_flags_blocking_through_local_call(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def _write_out(self):
                with open("/tmp/x", "w") as f:
                    f.write("hi")

            def publish(self):
                with self._lock:
                    self._write_out()
    """)
    assert rules_of(findings) == ["GL004"]
    assert any("_write_out" in f.message for f in findings)


def test_gl004_flags_nonreentrant_reacquire_not_rlock(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import threading

        class Bad:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self):
                with self._lock:
                    with self._lock:
                        pass

        class Fine:
            def __init__(self):
                self._lock = threading.RLock()

            def f(self):
                with self._lock:
                    with self._lock:
                        pass
    """)
    assert rules_of(findings) == ["GL004"]
    assert len(findings) == 1
    assert "not reentrant" in findings[0].message


def test_gl004_flags_blocking_in_acquire_release_region(tmp_path):
    """The .acquire()/.release() spelling (ISSUE 12 satellite): a bare
    acquire opens a held region to the matching release — including
    the canonical acquire(); try: ...; finally: release() shape —
    and blocking inside it flags exactly like a with-body. Findings
    anchor at the ACQUIRE line, so one argued suppression covers the
    region."""
    findings, _ = lint_src(tmp_path, """
        import threading
        import time

        _MODULE_LOCK = threading.Lock()

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def plain(self):
                self._lock.acquire()
                time.sleep(0.1)
                self._lock.release()

            def guarded(self):
                _MODULE_LOCK.acquire()
                try:
                    with open("/tmp/x", "w") as f:
                        f.write("hi")
                finally:
                    _MODULE_LOCK.release()
    """)
    assert rules_of(findings) == ["GL004"]
    assert len(findings) == 2
    # anchored at the acquire lines (one suppression point per region)
    assert all("acquire()/release() region" in f.message
               for f in findings)
    assert all("acquire" in f.context for f in findings)


def test_gl004_acquire_release_region_reacquire_and_rlock(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import threading

        class Bad:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self):
                self._lock.acquire()
                try:
                    with self._lock:
                        pass
                finally:
                    self._lock.release()

        class Fine:
            def __init__(self):
                self._lock = threading.RLock()

            def f(self):
                self._lock.acquire()
                try:
                    with self._lock:
                        pass
                finally:
                    self._lock.release()
    """)
    assert rules_of(findings) == ["GL004"]
    assert len(findings) == 1
    assert "not reentrant" in findings[0].message


def test_gl004_region_survives_conditional_early_release(tmp_path):
    """A conditional release (early-exit branch) must not END the held
    region: the fall-through path still holds the lock, and blocking
    after the branch flags. Work INSIDE the released branch is skipped
    (path-ambiguous — a linter must not claim it)."""
    findings, _ = lint_src(tmp_path, """
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self, err):
                self._lock.acquire()
                if err:
                    self._lock.release()
                    time.sleep(9)  # NOT under the lock: must not flag
                    return
                time.sleep(0.1)  # fall-through: still held -> flags
                self._lock.release()
    """)
    assert rules_of(findings) == ["GL004"]
    assert len(findings) == 1
    assert "(line 15" in findings[0].message  # the fall-through sleep


def test_gl004_acquire_release_near_misses_stay_silent(tmp_path):
    # cheap state flips between acquire and release, and blocking
    # AFTER the release, are the blessed shapes — exactly what the
    # with-spelling's near-miss pins
    findings, _ = lint_src(tmp_path, """
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def flip(self):
                self._lock.acquire()
                self.state = "on"
                self._lock.release()
                time.sleep(0.01)

            def guarded(self):
                self._lock.acquire()
                try:
                    self.n += 1
                finally:
                    self._lock.release()
    """)
    assert findings == []


def test_gl004_near_misses_stay_silent(tmp_path):
    # blocking OUTSIDE the lock, and pure state flips under it, are
    # exactly the pattern the serving stack uses
    findings, _ = lint_src(tmp_path, """
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self):
                with self._lock:
                    x = 1
                time.sleep(0.01)
                return x
    """)
    assert findings == []


def test_gl004_flags_lock_across_blocking_socket(tmp_path):
    """The ISSUE 15 vocabulary extension: a lock held across socket
    connect/send/recv stalls every contending thread by a network
    round-trip — the exact hazard the cross-process transport
    introduces, and the one its argued exchange-region suppression
    exists for."""
    findings, _ = lint_src(tmp_path, """
        import socket
        import threading

        class T:
            def __init__(self):
                self._lock = threading.Lock()

            def dial(self, addr):
                with self._lock:
                    self._sock = socket.create_connection(addr)

            def exchange(self, payload):
                with self._lock:
                    self._sock.sendall(payload)
                    return self._sock.recv(4096)

            def serve(self):
                with self._lock:
                    conn, _ = self._listener.accept()
                return conn
    """)
    assert rules_of(findings) == ["GL004"]
    assert len(findings) == 4
    msgs = " | ".join(f.message for f in findings)
    assert "socket connect" in msgs and "socket send" in msgs
    assert "socket recv" in msgs and "socket accept" in msgs


def test_gl004_socket_near_misses_stay_silent(tmp_path):
    # socket I/O OUTSIDE the lock — the counter-then-exchange shape
    # the real SocketTransport uses for its backoff state — is the
    # blessed pattern
    findings, _ = lint_src(tmp_path, """
        import socket
        import threading

        class T:
            def __init__(self):
                self._lock = threading.Lock()

            def dispatch(self, addr, payload):
                with self._lock:
                    k = self._dispatches
                    self._dispatches = k + 1
                sock = socket.create_connection(addr)
                sock.sendall(payload)
                return sock.recv(4096)
    """)
    assert findings == []


def test_gl003_flags_host_sync_in_transport_serve_loop(tmp_path):
    """The ISSUE 15 hot-path extension: the worker-side dispatch
    handler runs once per pod request — a device sync on the hosted
    engine's result is a per-request stall GL003 must catch."""
    findings, _ = lint_src(tmp_path, """
        import numpy as np

        class PodWorker:
            def _handle_dispatch(self, header, payload):
                X = self._decode(header, payload)
                out = self.engine.predict(X)
                out.block_until_ready()
                return np.asarray(out).tobytes()
    """, name="serving/transport.py")
    assert rules_of(findings) == ["GL003"]
    assert len(findings) == 2


def test_gl003_transport_near_miss_stays_silent(tmp_path):
    # the REAL handler's shape: frame decode, one engine dispatch,
    # .tobytes() on the (already-host) result — no converter on the
    # dispatch result, no explicit sync
    findings, _ = lint_src(tmp_path, """
        class PodWorker:
            def _handle_dispatch(self, header, payload):
                X = self._decode(header, payload)
                out = self.engine.predict(X)
                resp = {"rows": int(out.shape[0])}
                return resp, out.tobytes()

        class SocketTransport:
            def dispatch(self, X):
                with self._lock:
                    k = self._dispatches
                    self._dispatches = k + 1
                return self._exchange(X, k)
    """, name="serving/transport.py")
    assert findings == []


def test_issue18_byzantine_sync_surface_is_hot(tmp_path):
    """The ISSUE 18 hot-path extension: announce/sync verification and
    the rejoin resync run on pod serve threads, the client's announce
    holds the pod-wide swap lock, and the hunt scheduler's pricing
    loop is wall-budget-accounted — all named hot, so a host sync
    there fails the gate."""
    from tools.graftlint.astscope import HOT_PATHS
    assert {"PodClientEngine.swap_weights", "PodWorker.resync",
            "PodWorker._handle_swap", "PodWorker._handle_sync"} \
        <= HOT_PATHS["serving/transport.py"]
    assert "run_search" in HOT_PATHS["scenario/search.py"]
    findings, _ = lint_src(tmp_path, """
        class PodWorker:
            def _handle_swap(self, header, payload):
                out = self.engine.predict(self._decode(payload))
                out.block_until_ready()
                return {"kind": "swapped"}, b""
    """, name="serving/transport.py")
    assert rules_of(findings) == ["GL003"]


# -- GL005: impure traced code ----------------------------------------

def test_gl005_flags_host_rng_and_wallclock_in_traced_code(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import time

        import jax
        import numpy as np

        @jax.jit
        def f(x):
            noise = np.random.randn(4)
            t0 = time.time()
            return x + noise.sum() + t0
    """)
    assert rules_of(findings) == ["GL005"]
    assert len(findings) == 2


def test_gl005_near_misses_stay_silent(tmp_path):
    # jax.random with a threaded key IS the blessed randomness, and
    # host rng/clocks outside traced scope are ordinary host code
    findings, _ = lint_src(tmp_path, """
        import time

        import jax
        import numpy as np

        @jax.jit
        def f(x, key):
            return x + jax.random.normal(key, x.shape)

        def host_driver():
            seed = np.random.randint(0, 2 ** 31)
            return seed, time.time()
    """)
    assert findings == []


# -- GL006: exception hygiene on serving threads ----------------------

def test_gl006_flags_swallowing_handler_in_serving_module(tmp_path):
    findings, _ = lint_src(tmp_path, """
        class Worker:
            def _loop(self):
                try:
                    self.step()
                except Exception:
                    pass
    """, name="serving/loop.py")
    assert rules_of(findings) == ["GL006"]


def test_gl006_flags_bare_except_in_thread_target(tmp_path):
    # thread targets OUTSIDE serving/ are in scope too (the watcher
    # pattern); resolution follows Thread(target=self._run)
    findings, _ = lint_src(tmp_path, """
        import threading

        class W:
            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                try:
                    self.poll()
                except:
                    return
    """)
    assert rules_of(findings) == ["GL006"]
    assert "bare" in findings[0].message


def test_gl006_accounted_handlers_stay_silent(tmp_path):
    # typed excepts, counted failures, forwarded exceptions, and
    # re-raises are the four blessed shapes (service/_poll_once/
    # replica requeue all use one of them)
    findings, _ = lint_src(tmp_path, """
        class Worker:
            def _loop(self):
                try:
                    self.step()
                except ValueError:
                    pass

            def _poll(self):
                try:
                    self.step()
                except Exception:
                    self.errors += 1

            def _serve(self, fut):
                try:
                    self.step()
                except Exception as e:
                    fut.set_exception(e)

            def _guard(self):
                try:
                    self.step()
                except Exception:
                    self.metrics.record_rollback()
                    raise
    """, name="serving/loop.py")
    assert findings == []


# -- resolution edge cases (review pins) ------------------------------

def test_relative_import_in_package_init_resolves(tmp_path):
    """``from .impl import helper`` inside ``sub/__init__.py`` must
    land on ``sub/impl.py`` (the containing package, not one level
    up) — trace propagation through package re-export modules depends
    on it."""
    (tmp_path / "sub").mkdir()
    (tmp_path / "sub" / "impl.py").write_text(textwrap.dedent("""
        def helper(v):
            if v > 0:
                return v
            return -v
    """))
    (tmp_path / "sub" / "__init__.py").write_text(textwrap.dedent("""
        import jax

        from .impl import helper

        @jax.jit
        def traced(x):
            return helper(x)
    """))
    findings, _ = run_lint(str(tmp_path))
    assert [f.rule for f in findings] == ["GL001"]
    assert findings[0].path == "sub/impl.py"


def test_builtin_map_is_not_a_trace_entry(tmp_path):
    """Plain builtin ``map``/``filter`` must not classify as
    ``jax.lax.map`` and mint false traced roots."""
    findings, _ = lint_src(tmp_path, """
        def pick(x):
            if x:
                return 1
            return 0

        def host_code(xs):
            return list(map(pick, xs))
    """)
    assert findings == []


def test_identical_context_findings_get_distinct_fingerprints(
        tmp_path):
    """Two textually identical violations in one file must carry
    distinct fingerprints — one baseline entry must not silence
    both sites."""
    findings, _ = lint_src(tmp_path, """
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def a(self):
                with self._lock:
                    time.sleep(0.1)

            def b(self):
                with self._lock:
                    time.sleep(0.1)
    """)
    assert len(findings) == 2
    assert findings[0].context == findings[1].context
    assert findings[0].fingerprint != findings[1].fingerprint


def test_cli_missing_or_empty_root_fails_loudly(tmp_path, capsys):
    """A typo'd path must never report clean (exit 2, 'no Python
    modules') — the silent-green landing the review caught."""
    rc = cli_main([str(tmp_path / "no_such_dir")])
    assert rc == 2
    assert "no Python modules" in capsys.readouterr().err
    empty = tmp_path / "empty"
    empty.mkdir()
    assert cli_main([str(empty)]) == 2


# -- suppression / baseline round-trip --------------------------------

def test_suppression_requires_reason(tmp_path):
    src = """
        import jax

        @jax.jit
        def f(x):
            if x > 0:  # graftlint: disable=GL001 {reason}
                return x
            return -x
    """
    findings, suppressed = lint_src(tmp_path, src.format(
        reason="trace-time constant branch, proven by pin X"))
    assert findings == [] and len(suppressed) == 1
    assert suppressed[0].reason.startswith("trace-time constant")

    findings, suppressed = lint_src(tmp_path / "two",
                                    src.format(reason=""))
    # reasonless: does NOT suppress, and says so
    assert suppressed == [] and len(findings) == 1
    assert "no reason given" in findings[0].message


def test_suppression_line_above_and_wrong_rule(tmp_path):
    findings, suppressed = lint_src(tmp_path, """
        import jax

        @jax.jit
        def f(x):
            # graftlint: disable=GL001 constant branch by contract
            if x > 0:
                return x
            # graftlint: disable=GL005 wrong rule id does not suppress
            if x > 1:
                return x
            return -x
    """)
    assert len(suppressed) == 1 and suppressed[0].rule == "GL001"
    assert len(findings) == 1 and findings[0].rule == "GL001"


def test_parse_disables_grammar():
    assert parse_disables("x  # graftlint: disable=GL001 why") == \
        (("GL001",), "why")
    assert parse_disables(
        "x  # graftlint: disable=GL001,GL004 two rules") == \
        (("GL001", "GL004"), "two rules")
    assert parse_disables("x  # a normal comment") is None


def test_baseline_round_trip(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """)
    assert len(findings) == 1
    bl_path = str(tmp_path / "baseline.json")
    save_baseline(findings, bl_path)
    fps = load_baseline(bl_path)
    new, old = apply_baseline(findings, fps)
    assert new == [] and len(old) == 1  # baselined: reported, not fatal
    new, old = apply_baseline(findings, set())  # the committed shape
    assert len(new) == 1 and old == []
    # fingerprints are line-number-free: an edit ABOVE the finding
    # must not orphan the baseline entry
    assert findings[0].fingerprint == \
        findings[0].__class__(rule=findings[0].rule,
                              path=findings[0].path, line=999,
                              message="other",
                              context=findings[0].context).fingerprint


def test_malformed_baseline_raises(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({"not_fingerprints": []}))
    with pytest.raises(ValueError, match="malformed baseline"):
        load_baseline(str(p))


def test_committed_baseline_is_empty():
    # the adoption escape hatch stays closed in THIS repo: every
    # pre-existing finding was fixed or argued inline, so the gate
    # runs at full strength
    assert load_baseline() == set()


# -- CLI + JSON schema ------------------------------------------------

def test_cli_json_clean_run_and_schema_gate(tmp_path, capsys):
    (tmp_path / "ok.py").write_text("x = 1\n")
    rc = cli_main([str(tmp_path), "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["schema"] == SCHEMA == "GRAFTLINT.v1"
    assert out["clean"] is True and out["findings"] == []
    assert set(out["counts"]) == set(ALL_RULES)
    assert out["rules_run"] == sorted(ALL_RULES)
    assert set(out["rules"]) == set(RULES)
    # a --rules subset is honest about its coverage: the counts table
    # covers exactly what ran, and rules_run records it
    rc = cli_main([str(tmp_path), "--rules", "GL004", "--format",
                   "json"])
    sub = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert sub["rules_run"] == ["GL004"]
    assert set(sub["counts"]) == {"GL004"}
    # the check_bench_schema gate accepts what graftlint emits
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import check_bench_schema as cbs

    art = tmp_path / "GRAFTLINT_selftest.json"
    art.write_text(json.dumps(out))
    assert cbs.validate_file(str(art)) == []


def test_cli_text_failing_run_and_dirty_artifact_rejected(
        tmp_path, capsys):
    (tmp_path / "bad.py").write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """))
    rc = cli_main([str(tmp_path)])
    text = capsys.readouterr()
    assert rc == 1
    assert "GL001" in text.out and "bad.py" in text.out
    rc = cli_main([str(tmp_path), "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["clean"] is False
    # a DIRTY artifact must never land committed: the schema gate
    # re-rejects it even though it is structurally valid JSON
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import check_bench_schema as cbs

    art = tmp_path / "GRAFTLINT_dirty.json"
    art.write_text(json.dumps(out))
    assert any("must be clean" in e for e in cbs.validate_file(
        str(art)))


def test_cli_unknown_rule_is_an_error(tmp_path):
    (tmp_path / "ok.py").write_text("x = 1\n")
    assert cli_main([str(tmp_path), "--rules", "GL999"]) == 2


# -- the repo-wide tier-1 gate ----------------------------------------

def test_package_gate_zero_unsuppressed_findings():
    """THE gate: the shipped package lints clean. A new traced-branch,
    hot-path sync, lock violation, or swallowed exception anywhere in
    the package fails tier-1 right here."""
    findings, suppressed = run_lint(PKG)
    assert findings == [], "\n".join(
        f"{f.path}:{f.line}: {f.rule} {f.message}" for f in findings)
    # every committed suppression is an ARGUED one
    assert all(f.reason for f in suppressed)
    # and the suppression set is the audited one — a new suppression
    # is a reviewed decision, not a drive-by (update this count with
    # the justification in the diff). 9th (ISSUE 12): artifacts.py's
    # _EXPORT_LOCK acquire/release region — newly VISIBLE to GL004's
    # acquire-spelling analysis, and argued (a process-wide export
    # serializes blocking work by design; never the serving hot path).
    # 10th + 11th (ISSUE 15): transport.py's SocketTransport exchange
    # region — the I/O lock deliberately held across the socket
    # round-trip (one in-flight exchange per connection IS the frame
    # protocol; interleaved frames from a second thread would corrupt
    # both) — and PodClientEngine's swap-announce region (the whole
    # pick->broadcast->commit is one critical section: two
    # interleaved announces would serve different weights under one
    # agreed version number); both flagged by GL004's new
    # blocking-socket vocabulary and argued at their acquire lines
    assert len(suppressed) == 11


# -- mutation checks: the gate is live --------------------------------

@pytest.fixture()
def pkg_copy(tmp_path):
    dst = tmp_path / "pkg"
    shutil.copytree(PKG, dst, ignore=shutil.ignore_patterns(
        "__pycache__", "*.pyc"))
    return dst


def _edit(path, old, new):
    text = path.read_text()
    assert old in text, f"mutation anchor missing in {path.name}"
    path.write_text(text.replace(old, new, 1))


def test_mutation_stripped_suppressions_refire(pkg_copy):
    """Deleting the committed inline disables re-fires their rules —
    the suppressions are load-bearing, not decorative."""
    for rel, rule in (("serving/engine.py", "GL002"),
                      ("serving/engine.py", "GL003"),
                      ("serving/registry.py", "GL004"),
                      ("serving/artifacts.py", "GL004"),
                      ("utils/trace.py", "GL004")):
        path = pkg_copy / rel
        text = path.read_text()
        lines = [ln for ln in text.splitlines()
                 if f"graftlint: disable={rule}" not in ln]
        assert len(lines) < len(text.splitlines())
        path.write_text("\n".join(lines) + "\n")
    findings, _ = run_lint(str(pkg_copy))
    fired = rules_of(findings)
    assert "GL002" in fired and "GL003" in fired and "GL004" in fired


def test_mutation_reverted_gl006_fixes_refire(pkg_copy):
    """Re-introducing the swallowed-exception bugs this PR fixed turns
    the gate red again."""
    _edit(pkg_copy / "serving" / "service.py",
          "self.metrics.record_staleness_error()\n            return 0",
          "return 0")
    _edit(pkg_copy / "serving" / "metrics.py",
          "self.record_staleness_error()",
          "pass")
    findings, _ = run_lint(str(pkg_copy), rules=("GL006",))
    paths = {f.path for f in findings}
    assert paths == {"serving/service.py", "serving/metrics.py"}


def test_mutation_injected_hazards_fail_the_gate(pkg_copy):
    """One injected offender per rule, dropped into the real package
    tree, turns the gate red with exactly that rule — GL001-GL006 are
    each proven live against the shipped code, not just toy fixtures."""
    (pkg_copy / "fedcore" / "_gl_mutation.py").write_text(textwrap.dedent("""
        import time

        import jax
        import numpy as np

        @jax.jit
        def _mut_gl001(x):
            if x > 0:
                return x
            return -x

        @jax.jit
        def _mut_gl005(x):
            return x + np.random.randn(4).sum() + time.time()
    """))
    _edit(pkg_copy / "serving" / "engine.py",
          "        weights = self._resolve(version)",
          "        _fresh = jax.jit(lambda v: v)  # injected GL002\n"
          "        weights = self._resolve(version)")
    (pkg_copy / "serving" / "_gl_mutation.py").write_text(
        textwrap.dedent("""
        import threading
        import time

        import numpy as np


        class _MutHot:
            def _work(self):
                try:
                    self.step()
                except Exception:
                    pass

            def _locked(self):
                with self._lock:
                    time.sleep(0.5)

            def _region(self):
                # the acquire()/release() spelling must fire too
                self._lock.acquire()
                time.sleep(0.5)
                self._lock.release()
        """))
    findings, _ = run_lint(str(pkg_copy))
    fired = rules_of(findings)
    for rule in ("GL001", "GL002", "GL004", "GL005", "GL006"):
        assert rule in fired, f"{rule} did not fire on its mutation"


def test_mutation_gl003_sync_in_real_hot_path(pkg_copy):
    """A block_until_ready dropped into the REAL ServingEngine._run
    dispatch fails the gate as GL003."""
    _edit(pkg_copy / "serving" / "engine.py",
          "            out = self._predict(x, params, rff)",
          "            out = self._predict(x, params, rff)\n"
          "            out.block_until_ready()")
    findings, _ = run_lint(str(pkg_copy), rules=("GL003",))
    assert [f.rule for f in findings] == ["GL003"]
    assert findings[0].path == "serving/engine.py"
