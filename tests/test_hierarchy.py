"""The million-client cohort plane (ISSUE 8): sharded client axis,
two-tier hierarchical aggregation, streamed shards.

Load-bearing contracts:

- **Sharded == unsharded**: for FedAvg/FedProx/FedNova/FedAMW, the
  in-graph two-tier reduction reproduces the flat path's aggregates to
  float re-association tolerance, and every quarantine/gating DECISION
  is bit-identical (the per-client evidence never changes — only the
  final weighted sum is re-associated).
- **Zero recompiles across shard counts AND fault plans**: the shard
  count is a traced scalar and the plan rows are scanned inputs, so
  one compiled program covers the whole ``--cohort_shards`` sweep
  (the fault plane's zero-recompile contract extends to the
  hierarchy).
- **Reputation carry round-trip**: the ``O(J)`` reputation vector
  rides the sharded carry unchanged — prefix + checkpoint + resume
  under ``cohort_shards`` reproduces the uninterrupted sharded run.
- **Streamed shards**: the host-loop tier (one compiled shard-tier
  program, double-buffered host->device shards) reproduces the flat
  clean run within tolerance, keeps the defended path (shard-local
  evidence), and is bounded by host RAM, not HBM — the 1M-client leg
  lives in ``scale_bench.py`` (``cohort`` section of SCALE_r01.json).
"""

import numpy as np
import pytest

from fedamw_tpu.algorithms import (FedAMW, FedAvg, FedNova, FedProx,
                                   prepare_setup)
from fedamw_tpu.algorithms import core
from fedamw_tpu.data import CohortShardStream, load_dataset
from fedamw_tpu.fedcore.hierarchy import (MAX_COHORT_SHARDS,
                                          resolve_cohort_shards,
                                          shard_histogram, shard_ids,
                                          two_tier_weighted_average)
from fedamw_tpu.fedcore.aggregate import (segment_weighted_sums,
                                          weighted_average)
from fedamw_tpu.parallel import validate_cohort_alignment

pytestmark = pytest.mark.faults

KW = dict(lr=0.5, epoch=1, batch_size=32, round=3, seed=0,
          lr_mode="constant")
FAULTS = "drop=0.2,corrupt=0.1:scale:25,seed=3"


@pytest.fixture(scope="module")
def setup8():
    ds = load_dataset("digits", num_partitions=8, alpha=0.5)
    return prepare_setup(ds, kernel_type="linear", seed=3,
                         rng=np.random.RandomState(3))


# -- shard assignment / reductions (unit tier) ------------------------

def test_shard_ids_contiguous_and_balanced():
    ids = np.asarray(shard_ids(8, 4))
    np.testing.assert_array_equal(ids, [0, 0, 1, 1, 2, 2, 3, 3])
    # non-divisible cohorts stay contiguous and off-by-at-most-one
    ids = np.asarray(shard_ids(10, 3))
    assert (np.diff(ids) >= 0).all() and ids[0] == 0 and ids[-1] == 2
    counts = np.bincount(ids, minlength=3)
    assert counts.max() - counts.min() <= 1
    # one shard = the flat assignment
    assert np.asarray(shard_ids(5, 1)).sum() == 0


def test_resolve_cohort_shards_validation():
    assert resolve_cohort_shards(0, 8) == 0
    assert resolve_cohort_shards(4, 8) == 4
    with pytest.raises(ValueError, match=">= 0"):
        resolve_cohort_shards(-1, 8)
    with pytest.raises(ValueError, match="exceeds the cohort"):
        resolve_cohort_shards(9, 8)
    with pytest.raises(ValueError, match="MAX_COHORT_SHARDS"):
        resolve_cohort_shards(MAX_COHORT_SHARDS + 1,
                              10 * MAX_COHORT_SHARDS)
    # streamed sharding has no static partial-buffer cap
    assert resolve_cohort_shards(
        MAX_COHORT_SHARDS + 1, 10 * MAX_COHORT_SHARDS,
        streamed=True) == MAX_COHORT_SHARDS + 1


def test_two_tier_matches_flat_weighted_average():
    rng = np.random.RandomState(0)
    J = 12
    stacked = {"w": rng.randn(J, 5, 3).astype(np.float32),
               "b": rng.randn(J, 3).astype(np.float32)}
    w = rng.rand(J).astype(np.float32)
    flat = weighted_average(stacked, w)
    for s in (1, 3, 4, 12):
        ids = shard_ids(J, s)
        two = two_tier_weighted_average(stacked, w, ids)
        for k in stacked:
            np.testing.assert_allclose(np.asarray(two[k]),
                                       np.asarray(flat[k]), rtol=2e-6,
                                       atol=1e-6)


def test_segment_weighted_sums_partials_fold_exactly():
    rng = np.random.RandomState(1)
    J = 8
    stacked = {"w": rng.randn(J, 4).astype(np.float32)}
    w = rng.rand(J).astype(np.float32)
    ids = shard_ids(J, 4)
    parts = segment_weighted_sums(stacked, w, ids, MAX_COHORT_SHARDS)
    assert parts["w"].shape == (MAX_COHORT_SHARDS, 4)
    # each partial is its own shard's weighted sum; rows past the
    # shard count are exactly zero
    for s in range(4):
        sl = slice(2 * s, 2 * s + 2)
        np.testing.assert_allclose(
            np.asarray(parts["w"][s]),
            (w[sl, None] * stacked["w"][sl]).sum(0), rtol=1e-6)
    assert not np.asarray(parts["w"][4:]).any()


def test_shard_histogram_counts_per_shard():
    ids = shard_ids(8, 4)
    h = np.asarray(shard_histogram(np.ones(8, np.float32), ids))
    np.testing.assert_array_equal(h[:4], [2, 2, 2, 2])
    assert h[4:].sum() == 0


def test_validate_cohort_alignment():
    validate_cohort_alignment(8, 4)   # whole shards per device
    validate_cohort_alignment(7, 1)   # single device: anything goes
    with pytest.raises(ValueError, match="align"):
        validate_cohort_alignment(6, 4)


# -- sharded == unsharded (the equivalence sweep) ---------------------

@pytest.mark.parametrize("algo,extra", [
    (FedAvg, {}),
    (FedProx, dict(prox=True, mu=0.1)),
    (FedNova, {}),
    (FedAMW, dict(lambda_reg=1e-4, lr_p=1e-4)),
])
def test_sharded_matches_unsharded_clean(setup8, algo, extra):
    flat = algo(setup8, **KW, **extra)
    sh = algo(setup8, cohort_shards=4, **KW, **extra)
    np.testing.assert_allclose(sh["test_loss"], flat["test_loss"],
                               rtol=5e-5, atol=1e-6)
    np.testing.assert_allclose(sh["train_loss"], flat["train_loss"],
                               rtol=5e-5, atol=1e-6)
    h = sh["hierarchy"]
    assert h["cohort_shards"] == 4
    assert h["shard_present"].shape == (KW["round"], 4)
    assert (h["shard_present"].sum(axis=1)
            == setup8.num_clients).all()


@pytest.mark.parametrize("algo,extra", [
    (FedAvg, {}),
    (FedNova, {}),
    (FedAMW, dict(lambda_reg=1e-4, lr_p=1e-4)),
])
def test_sharded_decisions_bitwise_identical_under_faults(setup8, algo,
                                                          extra):
    """Same cohort, same faults: the sharded run's quarantine and
    gating DECISIONS equal the flat run's exactly — evidence is
    per-client (shard-local by construction) and only the final
    reduction is re-associated."""
    kw = dict(KW, faults=FAULTS, robust_agg="quarantine:5")
    flat = algo(setup8, **kw, **extra)
    sh = algo(setup8, cohort_shards=4, **kw, **extra)
    np.testing.assert_array_equal(
        sh["defense"]["z_quarantined"], flat["defense"]["z_quarantined"])
    np.testing.assert_array_equal(
        sh["fault_counts"]["quarantined"],
        flat["fault_counts"]["quarantined"])
    np.testing.assert_allclose(sh["test_loss"], flat["test_loss"],
                               rtol=5e-5, atol=1e-6)


def test_sharded_reputation_gating_identical(setup8):
    """The stateful plane: the carried reputation trajectory and its
    hard-gate verdicts are bit-identical under sharding (the O(J)
    carry rides the sharded scan unchanged)."""
    kw = dict(KW, faults="corrupt=0.25:sign,seed=1",
              robust_agg="rep:0.5:0.2")
    flat = FedAvg(setup8, **kw)
    sh = FedAvg(setup8, cohort_shards=2, **kw)
    np.testing.assert_array_equal(sh["defense"]["rep_gated"],
                                  flat["defense"]["rep_gated"])
    np.testing.assert_allclose(sh["defense"]["reputation"],
                               flat["defense"]["reputation"],
                               rtol=1e-5, atol=1e-6)


def test_order_statistic_aggregators_still_run_sharded(setup8):
    """median/krum fold globally by definition — the hierarchy keeps
    their flat reduction (documented), and the run stays equal to the
    flat one (selection masks identical)."""
    kw = dict(KW, faults="corrupt=0.2:sign,seed=2", robust_agg="mkrum:5")
    flat = FedAvg(setup8, **kw)
    sh = FedAvg(setup8, cohort_shards=4, **kw)
    np.testing.assert_array_equal(sh["defense"]["krum_selected"],
                                  flat["defense"]["krum_selected"])
    np.testing.assert_allclose(sh["test_loss"], flat["test_loss"],
                               rtol=5e-5, atol=1e-6)


# -- zero recompiles across fault plans and shard counts --------------

def test_shard_count_change_adds_no_recompile(setup8):
    """The shard count is DATA (a traced scalar), the plan rows are
    scanned inputs: one trainer, one compiled program across the whole
    (fault plan x shard count) sweep."""
    FedAvg(setup8, cohort_shards=2, faults=FAULTS,
           robust_agg="quarantine:5", **KW)
    fn = core._LAST_TRAIN_FN
    size0 = fn._cache_size() if hasattr(fn, "_cache_size") else None
    for shards, faults in ((4, FAULTS), (8, "drop=0.1,seed=9"),
                           (1, "corrupt=0.3:sign,seed=4")):
        FedAvg(setup8, cohort_shards=shards, faults=faults,
               robust_agg="quarantine:5", **KW)
        assert core._LAST_TRAIN_FN is fn  # same memoized trainer
        if size0 is not None:
            assert fn._cache_size() == size0  # same compiled program


def test_hierarchy_off_keeps_the_flat_trainer(setup8):
    """cohort_shards=0 is the exact flat graph: it shares the
    memoized trainer (and compiled program) with a run that never
    heard of the hierarchy — the flag is program structure only when
    ON."""
    FedAvg(setup8, **KW)
    fn = core._LAST_TRAIN_FN
    FedAvg(setup8, cohort_shards=0, **KW)
    assert core._LAST_TRAIN_FN is fn


# -- reputation carry round-trip across shards ------------------------

def test_sharded_rep_resume_roundtrip(setup8):
    """Prefix + checkpoint + resume under cohort_shards == the
    uninterrupted sharded run, reputation carry included (the O(J)
    vector resumes through the sharded trainer unchanged)."""
    kw = dict(lr=0.5, epoch=1, batch_size=32, seed=0,
              lr_mode="reference", cohort_shards=4,
              faults="corrupt=0.25:sign,seed=1",
              robust_agg="rep:0.5:0.2")
    full = FedAvg(setup8, round=4, return_state=True, **kw)
    prefix = FedAvg(setup8, round=4, stop_round=2, return_state=True,
                    **kw)
    resumed = FedAvg(setup8, round=4, start_round=2,
                     resume_from={"params": prefix["params"],
                                  "reputation": prefix["reputation"]},
                     return_state=True, **kw)
    np.testing.assert_array_equal(resumed["test_acc"],
                                  np.asarray(full["test_acc"])[2:])
    np.testing.assert_array_equal(np.asarray(resumed["reputation"]),
                                  np.asarray(full["reputation"]))


# -- streamed shards --------------------------------------------------

def test_streamed_matches_flat_clean(setup8):
    flat = FedAvg(setup8, **KW)
    st = FedAvg(setup8, cohort_shards=4, stream_cohort=True, **KW)
    np.testing.assert_allclose(st["test_acc"], flat["test_acc"],
                               atol=1e-4)
    np.testing.assert_allclose(st["train_loss"], flat["train_loss"],
                               rtol=5e-5, atol=1e-6)
    assert st["streamed"] == {
        "cohort_shards": 4, "shard_clients": 2,
        "present": pytest.approx([8.0] * KW["round"]),
    }


def test_streamed_nova_matches_flat(setup8):
    flat = FedNova(setup8, **KW)
    st = FedNova(setup8, cohort_shards=2, stream_cohort=True, **KW)
    np.testing.assert_allclose(st["test_acc"], flat["test_acc"],
                               atol=1e-4)


def test_streamed_defended_round_quarantines(setup8):
    """The streamed tier keeps the defended path: shard-local
    non-finite + z quarantine evidence folds into the global counters
    (a 25x attacker is an upper outlier inside its own shard too)."""
    st = FedAvg(setup8, cohort_shards=2, stream_cohort=True,
                faults=FAULTS, robust_agg="quarantine:5", **KW)
    flat = FedAvg(setup8, faults=FAULTS, robust_agg="quarantine:5",
                  **KW)
    # role counts are plan facts — identical by construction
    np.testing.assert_array_equal(st["fault_counts"]["dropped"],
                                  flat["fault_counts"]["dropped"])
    np.testing.assert_array_equal(st["fault_counts"]["corrupted"],
                                  flat["fault_counts"]["corrupted"])
    # the runtime verdicts catch the attackers (stats are shard-local,
    # so exact equality with the flat run is not contractual)
    assert (st["fault_counts"]["quarantined"]
            >= flat["fault_counts"]["corrupted"]).all()
    assert np.isfinite(st["test_loss"]).all()


def test_streamed_zero_recompile_across_rounds_and_plans(setup8):
    """ONE shard-tier program serves every shard of every round of
    every same-config run — fault plans and round counts are data.
    Changing the shard COUNT changes the per-shard static shape (the
    streamed mode's one shape axis), costing exactly one more program
    — never one per shard or per round."""
    FedAvg(setup8, cohort_shards=2, stream_cohort=True, faults=FAULTS,
           robust_agg="quarantine:5", **KW)
    tier = core._LAST_SHARD_TIER
    size0 = tier._cache_size() if hasattr(tier, "_cache_size") else None
    FedAvg(setup8, cohort_shards=2, stream_cohort=True,
           faults="drop=0.3,seed=11", robust_agg="quarantine:5",
           **dict(KW, round=5))
    assert core._LAST_SHARD_TIER is tier  # same memoized tier
    if size0 is not None:
        assert tier._cache_size() == size0  # plans/rounds are data
    FedAvg(setup8, cohort_shards=4, stream_cohort=True, faults=FAULTS,
           robust_agg="quarantine:5", **KW)
    assert core._LAST_SHARD_TIER is tier
    if size0 is not None:
        # a new shard SHAPE is one new program, not one per shard/round
        assert tier._cache_size() == size0 + 1


def test_streamed_surface_is_guarded(setup8):
    with pytest.raises(ValueError, match="learned"):
        FedAMW(setup8, cohort_shards=2, stream_cohort=True, **KW)
    with pytest.raises(ValueError, match="cohort_shards"):
        FedAvg(setup8, stream_cohort=True, **KW)
    with pytest.raises(ValueError, match="global statistics"):
        FedAvg(setup8, cohort_shards=2, stream_cohort=True,
               robust_agg="rep:0.9:0.2", **KW)
    with pytest.raises(ValueError, match="sequential"):
        FedAvg(setup8, cohort_shards=2, stream_cohort=True,
               sequential=True, **KW)


def test_cohort_shard_stream_double_buffers_all_shards():
    J, n_max = 8, 3
    idx = np.arange(J * n_max, dtype=np.int32).reshape(J, n_max)
    mask = np.ones((J, n_max), np.float32)
    sizes = np.full(J, n_max, np.int32)
    p = np.full(J, 1.0 / J, np.float32)
    stream = CohortShardStream(4, idx=idx, mask=mask, sizes=sizes,
                               p_fixed=p)
    keys = np.arange(J * 2, dtype=np.uint32).reshape(J, 2)
    rows = np.arange(J, dtype=np.float32)
    fault_rows = (rows, rows + 1, rows + 2, rows + 3, rows + 4)
    seen = []
    for s, shard in stream.round_shards(keys, fault_rows=fault_rows):
        assert shard["idx"].shape == (2, n_max)
        assert shard["keys"].shape == (2, 2)
        assert len(shard["fault_rows"]) == 5
        np.testing.assert_array_equal(np.asarray(shard["idx"]),
                                      idx[2 * s:2 * s + 2])
        np.testing.assert_array_equal(
            np.asarray(shard["fault_rows"][0]), rows[2 * s:2 * s + 2])
        seen.append(s)
    assert seen == [0, 1, 2, 3]


def test_cohort_shard_stream_rejects_ragged_split():
    idx = np.zeros((10, 2), np.int32)
    with pytest.raises(ValueError, match="client_multiple"):
        CohortShardStream(4, idx=idx, mask=np.zeros((10, 2)),
                          sizes=np.zeros(10), p_fixed=np.zeros(10))
    with pytest.raises(ValueError, match=">= 1"):
        CohortShardStream(0, idx=idx, mask=np.zeros((10, 2)),
                          sizes=np.zeros(10), p_fixed=np.zeros(10))
