"""IDX / CIFAR-binary readers and their data_tf parity.

Fixtures are crafted in-memory files, not downloads (zero-egress box).
Parity target: the reference's ``data_tf`` (``functions/utils.py:67-72``)
applied through torchvision's PIL->numpy view — MNIST row-major 784,
CIFAR10 HWC 3072, pixels mapped ``x/255`` then ``(x-0.5)/0.5``.
"""

import gzip
import os
import struct

import numpy as np
import pytest

from fedamw_tpu.data import load_dataset
from fedamw_tpu.data.images import (
    data_tf,
    load_cifar10,
    load_mnist,
    read_idx,
)


def write_idx(path, arr, compress=False):
    codes = {np.uint8: 0x08, np.int32: 0x0C, np.float32: 0x0D}
    code = codes[arr.dtype.type]
    header = struct.pack(">HBB", 0, code, arr.ndim)
    header += struct.pack(f">{arr.ndim}I", *arr.shape)
    payload = arr.astype(arr.dtype.newbyteorder(">")).tobytes()
    opener = gzip.open if compress else open
    with opener(path, "wb") as f:
        f.write(header + payload)


@pytest.fixture
def mnist_dir(tmp_path):
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (20, 28, 28)).astype(np.uint8)
    labels = rng.randint(0, 10, 20).astype(np.uint8)
    timgs = rng.randint(0, 256, (8, 28, 28)).astype(np.uint8)
    tlabels = rng.randint(0, 10, 8).astype(np.uint8)
    write_idx(str(tmp_path / "train-images-idx3-ubyte"), imgs)
    write_idx(str(tmp_path / "train-labels-idx1-ubyte"), labels)
    # test split gzipped: both forms must parse
    write_idx(str(tmp_path / "t10k-images-idx3-ubyte.gz"), timgs,
              compress=True)
    write_idx(str(tmp_path / "t10k-labels-idx1-ubyte.gz"), tlabels,
              compress=True)
    return tmp_path, imgs, labels, timgs, tlabels


@pytest.fixture
def cifar_dir(tmp_path):
    rng = np.random.RandomState(1)
    d = tmp_path / "cifar-10-batches-bin"
    d.mkdir()
    all_chw, all_labels = [], []
    for i in range(1, 6):
        labels = rng.randint(0, 10, 4).astype(np.uint8)
        chw = rng.randint(0, 256, (4, 3, 32, 32)).astype(np.uint8)
        rec = np.concatenate(
            [labels[:, None], chw.reshape(4, -1)], axis=1
        ).astype(np.uint8)
        rec.tofile(str(d / f"data_batch_{i}.bin"))
        all_chw.append(chw)
        all_labels.append(labels)
    tlabels = rng.randint(0, 10, 4).astype(np.uint8)
    tchw = rng.randint(0, 256, (4, 3, 32, 32)).astype(np.uint8)
    np.concatenate([tlabels[:, None], tchw.reshape(4, -1)], axis=1).astype(
        np.uint8
    ).tofile(str(d / "test_batch.bin"))
    return (tmp_path, np.concatenate(all_chw),
            np.concatenate(all_labels), tchw, tlabels)


def test_idx_roundtrip(tmp_path):
    arr = np.arange(24, dtype=np.uint8).reshape(2, 3, 4)
    write_idx(str(tmp_path / "x"), arr)
    np.testing.assert_array_equal(read_idx(str(tmp_path / "x")), arr)


def test_idx_rejects_garbage(tmp_path):
    p = tmp_path / "bad"
    p.write_bytes(b"not an idx file at all")
    with pytest.raises(ValueError, match="IDX"):
        read_idx(str(p))


def test_data_tf_formula():
    x = np.array([[0, 255, 127]], dtype=np.uint8)
    out = data_tf(x)
    # (x/255 - 0.5) / 0.5, reference utils.py:67-72
    np.testing.assert_allclose(
        out, [[-1.0, 1.0, (127 / 255 - 0.5) / 0.5]], atol=1e-6
    )
    assert out.dtype == np.float32


def test_load_mnist_parity(mnist_dir):
    path, imgs, labels, timgs, tlabels = mnist_dir
    X, y, Xt, yt = load_mnist(str(path))
    assert X.shape == (20, 784) and Xt.shape == (8, 784)
    np.testing.assert_array_equal(y, labels.astype(np.int32))
    np.testing.assert_array_equal(yt, tlabels.astype(np.int32))
    # row-major flatten of the raw image, then the data_tf map
    expect = (imgs.reshape(20, -1).astype(np.float32) / 255 - 0.5) / 0.5
    np.testing.assert_allclose(X, expect, atol=1e-6)


def test_load_cifar10_parity(cifar_dir):
    path, chw, labels, tchw, tlabels = cifar_dir
    X, y, Xt, yt = load_cifar10(str(path))
    assert X.shape == (20, 3072) and Xt.shape == (4, 3072)
    np.testing.assert_array_equal(y, labels.astype(np.int32))
    # reference order: PIL->numpy is HWC, flattened
    hwc = chw.transpose(0, 2, 3, 1).reshape(20, -1).astype(np.float32)
    np.testing.assert_allclose(X, (hwc / 255 - 0.5) / 0.5, atol=1e-6)


def test_load_dataset_resolves_mnist_files(mnist_dir):
    path = mnist_dir[0]
    ds = load_dataset("mnist", num_partitions=2, alpha=-1,
                      data_dir=str(path), rng=np.random.RandomState(0))
    assert ds.source == "file"
    assert ds.d == 784 and ds.num_classes == 10
    assert len(ds.parts) == 2


def test_load_dataset_mnist_falls_back_without_files(tmp_path):
    ds = load_dataset("mnist", num_partitions=2, alpha=-1,
                      data_dir=str(tmp_path), rng=np.random.RandomState(0),
                      min_size=0)
    assert ds.source == "synthetic"
    assert ds.d == 784  # registry signature preserved
