"""The learned-ladder plane (ISSUE 13): rung learning properties,
recompile-budget accounting, atomic install/retire on a live engine,
and continuous-batching admission.

The load-bearing guarantees: (1) ``learn_ladder`` is optimal under its
explicit pad-waste cost model — rung count within the program budget,
monotone rungs, the top rung covering the observed max, and sampled
waste never above the hand-picked ``1/8/64/512/4096`` ladder's when
the budget allows at least as many rungs; (2) the recompile budget is
a hard pin — each installed rung is charged, an exhausted learner is
FROZEN and proposes nothing, and overdrawing raises; (3)
``install_rung`` pre-warms on the CALLER's thread and publishes
atomically, so concurrent live traffic sees zero hot-path compiles
and a consistent ladder at every dispatch; (4) the continuous
admission policy (``batcher.admit``) never waits, never splits a
request, and hands the over-budget request back as the holdover.
"""

import queue as queue_mod
import threading
import time

import numpy as np
import pytest

from fedamw_tpu.serving import (LadderLearner, ServeMetrics,
                                ServingEngine, ServingService, admit,
                                apply_proposal, ladder_waste,
                                learn_ladder)
from fedamw_tpu.utils.telemetry import Registry

FIXED = (1, 8, 64, 512, 4096)


def _engine(buckets=(1, 8, 64), d=16, C=3, seed=6):
    rng = np.random.RandomState(seed)
    return ServingEngine({"w": rng.randn(C, d).astype(np.float32)},
                         buckets=buckets)


# -- learn_ladder properties ------------------------------------------

def _random_sizes(rng, n=400):
    pool = [1, 2, 3, 7, 9, 17, 33, 50, 100, 250, 300, 700, 1500]
    probs = rng.dirichlet(np.ones(len(pool)))
    return [int(s) for s in rng.choice(pool, size=n, p=probs)]


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
@pytest.mark.parametrize("budget", [1, 2, 4, 6, 10])
def test_learned_ladder_properties(seed, budget):
    """Rung count within the program budget, strictly monotone rungs,
    top rung == observed max — for arbitrary samples and budgets."""
    sizes = _random_sizes(np.random.RandomState(seed))
    rungs = learn_ladder(sizes, budget)
    assert 1 <= len(rungs) <= budget
    assert list(rungs) == sorted(set(rungs))  # strictly increasing
    assert rungs[-1] == max(sizes)  # every sampled request fits


@pytest.mark.parametrize("seed", range(6))
def test_learned_waste_never_above_fixed_ladder(seed):
    """With a budget at least the fixed ladder's rung count, the
    DP-learned ladder's sampled pad waste is <= the hand-picked
    ``1/8/64/512/4096`` ladder's — the optimality property that makes
    learning worth its recompiles."""
    sizes = _random_sizes(np.random.RandomState(100 + seed))
    rungs = learn_ladder(sizes, max_rungs=len(FIXED))
    assert ladder_waste(sizes, rungs)["waste_rows"] <= \
        ladder_waste(sizes, FIXED)["waste_rows"]


def test_learn_ladder_is_optimal_against_brute_force():
    import itertools

    sizes = [1, 2, 2, 5, 9, 9, 9, 14, 30, 30]
    cand = sorted(set(sizes))
    for budget in (1, 2, 3, 4):
        best = min(
            ladder_waste(sizes, c)["waste_rows"]
            for k in range(1, budget + 1)
            for c in itertools.combinations(cand, k)
            if c[-1] == max(sizes))
        got = learn_ladder(sizes, budget)
        assert ladder_waste(sizes, got)["waste_rows"] == best


def test_program_cost_prices_rungs_explicitly():
    """The explicit cost model: with a high enough per-program price,
    the learner stops minting rungs for marginal padding savings."""
    sizes = [1] * 50 + [2] * 2 + [64] * 50
    free = learn_ladder(sizes, 3, program_cost=0.0)
    priced = learn_ladder(sizes, 3, program_cost=1000.0)
    assert len(priced) < len(free)
    assert priced[-1] == free[-1] == 64


def test_ladder_waste_chunks_oversized_at_top_rung():
    """Sizes above the top rung chunk there (full chunks are exact,
    only the remainder pads) — mirroring ServingEngine.predict."""
    w = ladder_waste([10], (4, 8))  # 8 + pad(2 -> 4): 2 waste rows
    assert w["waste_rows"] == 2 and w["padded_rows"] == 12
    assert ladder_waste([16], (4, 8))["waste_rows"] == 0
    with pytest.raises(ValueError, match="positive"):
        ladder_waste([0], (4, 8))
    with pytest.raises(ValueError, match="at least one"):
        learn_ladder([], 3)


# -- learner: evidence, budget accounting, freeze ---------------------

def _metrics_with_traffic(sizes):
    m = ServeMetrics()
    for s in sizes:
        m.record_batch(n_requests=1, n_rows=s, latencies=[1e-4],
                       rows_per_request=[s])
    return m


def test_learner_reads_request_rows_series_and_proposes():
    sizes = [1, 3, 3, 5, 24, 24] * 20
    m = _metrics_with_traffic(sizes)
    learner = LadderLearner(m.registry, max_rungs=4,
                            recompile_budget=8, min_samples=32)
    assert sorted(set(learner.observed_sizes())) == [1, 3, 5, 24]
    prop = learner.propose((1, 8, 64))
    assert prop is not None
    assert prop.rungs[-1] == 24 and len(prop.rungs) <= 4
    assert prop.sample_count == len(sizes)
    # the explicit cost evidence: learning must beat the current
    # ladder on the very sample it learned from
    assert prop.waste_fraction < prop.baseline_waste_fraction
    assert prop.recompiles_charged == len(prop.install)
    assert set(prop.install).isdisjoint((1, 8, 64))
    assert set(prop.retire) <= {1, 8, 64}


def test_learner_needs_evidence_and_respects_min_samples():
    m = _metrics_with_traffic([1, 8])
    learner = LadderLearner(m.registry, min_samples=64)
    assert learner.propose((1, 8)) is None
    assert "min_samples" in learner.last_reason
    # a series-disabled registry records no evidence at all
    m_off = ServeMetrics(registry=Registry(enabled=False))
    m_off.record_batch(n_requests=1, n_rows=4, latencies=[1e-4],
                       rows_per_request=[4])
    assert LadderLearner(m_off.registry,
                         min_samples=1).observed_sizes() == []


def test_recompile_budget_is_a_hard_pin():
    """Each install charges the budget; overdraw raises; an exhausted
    learner is frozen and proposes nothing ever again."""
    m = _metrics_with_traffic([1, 3, 3, 5, 24, 24] * 20)
    learner = LadderLearner(m.registry, max_rungs=4, recompile_budget=2,
                            min_samples=32)
    prop = learner.propose((1, 8, 64))
    if prop is not None:
        # affordable proposal: spend it and the learner freezes
        assert len(prop.install) <= 2
        learner.charge(len(prop.install))
    else:
        # unaffordable: the reason names the budget
        assert "budget" in learner.last_reason
        learner.charge(2)
    assert learner.recompiles_spent == 2
    assert learner.budget_remaining == 0
    assert learner.frozen is True
    assert learner.propose((1, 8, 64)) is None
    assert "frozen" in learner.last_reason
    with pytest.raises(RuntimeError, match="budget exhausted"):
        learner.charge(1)


def test_freeze_is_explicit_and_final():
    m = _metrics_with_traffic([1, 3, 24] * 20)
    learner = LadderLearner(m.registry, min_samples=16)
    assert learner.frozen is False
    learner.freeze()
    assert learner.frozen is True
    assert learner.propose((1, 8)) is None


def test_learner_declines_when_current_ladder_already_optimal():
    sizes = [1, 8, 64] * 30
    m = _metrics_with_traffic(sizes)
    learner = LadderLearner(m.registry, max_rungs=3, min_samples=32)
    assert learner.propose((1, 8, 64)) is None
    assert learner.last_reason is not None


# -- engine: atomic rung install/retire -------------------------------

def test_install_rung_prewarms_and_serves_without_hot_compile():
    engine = _engine(buckets=(1, 8))
    warm = engine.warmup()
    assert warm == 2
    engine.install_rung(4)
    assert engine.buckets == (1, 4, 8)
    cc = engine.compile_count
    assert cc == 3  # the install's ONE charged compile, paid upfront
    rng = np.random.RandomState(0)
    out = engine.predict(rng.randn(3, 16).astype(np.float32))
    assert out.shape == (3, 3)
    assert engine.compile_count == cc  # pre-warmed: dispatch is free
    # duplicates and nonsense are refused
    with pytest.raises(ValueError, match="already a ladder rung"):
        engine.install_rung(4)
    with pytest.raises(ValueError, match="positive"):
        engine.install_rung(0)


def test_retire_rung_keeps_programs_and_floor():
    engine = _engine(buckets=(1, 8, 64))
    engine.warmup()
    cc = engine.compile_count
    engine.retire_rung(8)
    assert engine.buckets == (1, 64)
    rng = np.random.RandomState(1)
    # former rung-8 traffic pads up to 64 with zero recompiles (the
    # compiled program for 8 stays cached but unused)
    engine.predict(rng.randn(5, 16).astype(np.float32))
    assert engine.compile_count == cc
    with pytest.raises(KeyError):
        engine.retire_rung(8)
    engine.retire_rung(1)
    with pytest.raises(ValueError, match="last rung"):
        engine.retire_rung(64)


def test_install_rung_on_artifact_engine_requires_aot():
    """The cold-start plane's zero-compile contract survives
    re-bucketing: an artifact-loaded engine refuses a compiling
    install and accepts an AOT-supplied rung executable."""
    engine = _engine(buckets=(1, 8))
    engine._aot = {}  # artifact-loaded marker (from_artifact sets it)
    with pytest.raises(ValueError, match="aot="):
        engine.install_rung(4)

    calls = []

    def fake_rung(x, params, rff):
        calls.append(int(x.shape[0]))
        return engine._predict(x, params, rff)

    engine.install_rung(4, aot=fake_rung)
    assert engine.buckets == (1, 4, 8)
    rng = np.random.RandomState(2)
    engine.predict(rng.randn(3, 16).astype(np.float32))
    assert calls == [4]  # served through the supplied executable


def test_offthread_install_race_with_live_traffic():
    """The pre-warm race pin: rungs install from another thread while
    the service dispatches live traffic continuously — every request
    resolves correctly, the ladder is consistent at every dispatch,
    and the only compiles are the installs' own charged pre-warms
    (zero on the serving hot path after the final install)."""
    engine = _engine(buckets=(1, 8, 64))
    engine.warmup()
    rng = np.random.RandomState(3)
    payloads = [rng.randn(k, 16).astype(np.float32)
                for k in (1, 3, 5, 8, 13, 40)]
    want = [engine.predict(x) for x in payloads]
    stop = threading.Event()
    errors: list = []
    served = [0]

    def pump(svc):
        k = 0
        try:
            while not stop.is_set():
                i = k % len(payloads)
                out = svc.submit(payloads[i]).result(timeout=60)
                np.testing.assert_array_equal(out, want[i])
                served[0] += 1
                k += 1
        except Exception as e:
            errors.append(e)

    with ServingService(engine, mode="continuous") as svc:
        th = threading.Thread(target=pump, args=(svc,))
        th.start()
        time.sleep(0.02)
        for b in (4, 16, 32):
            engine.install_rung(b)  # pre-warm + atomic publish, HERE
        cc_after_installs = engine.compile_count
        engine.retire_rung(64)
        time.sleep(0.05)  # live traffic over the learned ladder
        stop.set()
        th.join(timeout=60)
    assert errors == []
    assert served[0] > 0
    assert engine.buckets == (1, 4, 8, 16, 32)
    # 3 warmup + 3 installs, and NOTHING after: the post-install
    # traffic (including former rung-64 sizes padding to 8+32 chunks
    # or 40 -> chunked) never compiled on the hot path
    assert cc_after_installs == 6
    assert engine.compile_count == 6


def test_apply_proposal_charges_learner_and_updates_engine():
    engine = _engine(buckets=(1, 8, 64))
    engine.warmup()
    m = _metrics_with_traffic([1, 3, 3, 5, 24, 24] * 20)
    learner = LadderLearner(m.registry, max_rungs=4, recompile_budget=8,
                            min_samples=32)
    prop = learner.propose(engine.buckets)
    assert prop is not None
    ladder = apply_proposal(engine, prop, learner)
    assert ladder == engine.buckets == prop.rungs
    assert learner.recompiles_spent == len(prop.install)


# -- continuous admission (batcher.admit) -----------------------------

def test_admit_takes_queued_never_waits_and_hands_back_holdover():
    q = queue_mod.Queue()
    for k in (4, 3):
        q.put(np.zeros((k, 8), np.float32))
    t0 = time.perf_counter()
    batch, held = admit(q, np.zeros((2, 8), np.float32), max_rows=8)
    took = time.perf_counter() - t0
    # 2 + 4 fit; the 3-row request would exceed 8 -> holdover (same
    # contract as drain), and nothing ever lingered
    assert [b.shape[0] for b in batch] == [2, 4]
    assert held is not None and held.shape[0] == 3
    assert took < 0.05
    # empty queue: solo dispatch immediately, no holdover
    t0 = time.perf_counter()
    batch, held = admit(q, np.zeros((1, 8), np.float32), max_rows=8)
    assert [b.shape[0] for b in batch] == [1] and held is None
    assert time.perf_counter() - t0 < 0.05


def test_service_modes_validated_and_drain_still_selectable():
    engine = _engine()
    with pytest.raises(ValueError, match="mode"):
        ServingService(engine, mode="bogus")
    rng = np.random.RandomState(4)
    for mode in ("continuous", "drain"):
        with ServingService(engine, mode=mode, max_wait_ms=1.0) as svc:
            x = rng.randn(3, 16).astype(np.float32)
            np.testing.assert_array_equal(
                svc.submit(x).result(timeout=30), engine.predict(x))


def test_worker_picks_up_installed_rungs_mid_stream():
    """The worker re-reads the ladder per batch: a rung installed
    mid-stream raises the admission cap without a service restart."""
    engine = _engine(buckets=(1, 8))
    engine.warmup()
    rng = np.random.RandomState(5)
    with ServingService(engine, mode="continuous") as svc:
        svc.submit(rng.randn(2, 16).astype(np.float32)).result(
            timeout=30)
        engine.install_rung(32)
        out = svc.submit(rng.randn(20, 16).astype(np.float32)).result(
            timeout=30)
        assert out.shape == (20, 3)
    snap = svc.metrics.snapshot(engine)
    assert snap["requests"] == 2


def test_request_rows_series_lands_in_registry():
    """The PR 12 signal the learner consumes: every served request's
    row count is a sample on the serve_request_rows histogram series,
    and every dispatch's total on serve_batch_rows."""
    engine = _engine()
    m = ServeMetrics()
    rng = np.random.RandomState(7)
    with ServingService(engine, metrics=m) as svc:
        for k in (1, 4, 9):
            svc.submit(rng.randn(k, 16).astype(np.float32)).result(
                timeout=30)
    req = m.registry.lookup("serve_request_rows")
    batch = m.registry.lookup("serve_batch_rows")
    assert req is not None and batch is not None
    assert sorted(int(v) for _, v in req.series_state()[0]) == [1, 4, 9]
    assert req.count == 3
    assert batch.count == m.batches


# -- code-review regression pins --------------------------------------

def test_predict_latched_ladder_survives_concurrent_retire():
    """predict latches ONE ladder snapshot for the whole call: a rung
    retired mid-dispatch must keep serving through its cached program
    (retire_rung's documented guarantee), never raise on a batch the
    latched ladder covers."""
    engine = _engine(buckets=(1, 8, 64))
    engine.warmup()
    cc = engine.compile_count
    weights = engine._resolve(None)
    ladder = engine.buckets  # the in-flight dispatch's snapshot
    engine.retire_rung(64)
    timings = {"pad_s": 0.0, "dispatch_s": 0.0}
    out = engine._run(np.zeros((40, 16), np.float32), weights, timings,
                      ladder)
    assert out.shape == (40, 3)
    assert timings["bucket"] == 64  # the retired rung, still compiled
    assert engine.compile_count == cc


def test_apply_proposal_rounds_rungs_on_mesh_engines():
    """Mesh engines round rungs to device multiples: a proposed rung
    that rounds onto an existing one installs (and charges) nothing,
    and a current rung that is a proposed rung's rounded image is
    never retired — the proposal's coverage survives the rounding."""
    from fedamw_tpu.parallel import make_serving_mesh
    from fedamw_tpu.serving.ladder import LadderProposal

    rng = np.random.RandomState(6)
    engine = ServingEngine({"w": rng.randn(3, 16).astype(np.float32)},
                           buckets=(1, 8, 64),
                           mesh=make_serving_mesh())
    assert engine.buckets == (8, 64)  # rung 1 rounded up to 8 shards
    engine.warmup()
    prop = LadderProposal(
        rungs=(5, 30, 64), install=(5, 30), retire=(8,),
        sample_count=100, observed_max=64, waste_fraction=0.1,
        baseline_waste_fraction=0.5, recompiles_charged=2)
    m = _metrics_with_traffic([1])
    learner = LadderLearner(m.registry, recompile_budget=4,
                            min_samples=1)
    ladder = apply_proposal(engine, prop, learner)
    # 5 rounds onto the existing rung 8 (skipped, uncharged); 30
    # rounds to a NEW rung 32 (installed, charged once); 8 is rung
    # 5's rounded image, so the retire is skipped
    assert ladder == (8, 32, 64)
    assert learner.recompiles_spent == 1


def test_rung_aware_carry_never_dispatches_past_the_top_rung():
    """A rung-cut tail stacking with a holdover can make the carried
    seed exceed the rung budget; the worker must trim the batch back
    to it so the engine never chunks a coalesced service batch (which
    would split a request across dispatches)."""
    dispatched: list = []

    class _Recorder(ServingEngine):
        def predict(self, X, version=None, record_timings=True):
            dispatched.append(int(np.atleast_2d(X).shape[0]))
            return super().predict(X, version=version,
                                   record_timings=record_timings)

    engine = _Recorder({"w": np.random.RandomState(8).randn(
        3, 16).astype(np.float32)}, buckets=(1, 8))
    engine.warmup()
    dispatched.clear()
    rng = np.random.RandomState(9)
    svc = ServingService(engine, mode="continuous", rung_aware=True)
    svc._thread = object()  # queue a burst before the worker starts
    futs = [svc.submit(rng.randn(5, 16).astype(np.float32))
            for _ in range(4)]
    svc._thread = None
    with svc:
        for f in futs:
            assert f.result(timeout=30).shape == (5, 3)
    # every service-level dispatch stayed within the top rung (the
    # engine's own chunking path was never entered)
    assert dispatched and max(dispatched) <= 8


def test_install_rung_refuses_aot_on_jit_engine():
    """A jit engine dispatches through its own cache — a supplied
    executable would be silently discarded while the caller pays the
    compile it exported to avoid; refused loudly instead."""
    engine = _engine(buckets=(1, 8))
    with pytest.raises(ValueError, match="artifact-loaded"):
        engine.install_rung(4, aot=lambda x, p, r: x)
    assert engine.buckets == (1, 8)  # nothing installed


def test_record_batch_rejects_misaligned_slo_classes():
    m = ServeMetrics()
    with pytest.raises(ValueError, match="align"):
        m.record_batch(n_requests=3, n_rows=3,
                       latencies=[1e-3, 2e-3, 3e-3],
                       slo_classes=["interactive"])
