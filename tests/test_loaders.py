"""Minibatch loaders (reference ``load_data``, ``utils.py:86-121``) and
``error_estimate`` (``tools.py:64-79``) — the reference's dead-code
surface, reproduced for completeness."""

import numpy as np
import pytest

from fedamw_tpu.data import MinibatchLoader, load_data
from fedamw_tpu.ops import error_estimate


def test_minibatch_loader_covers_all_rows_once_per_epoch():
    X = np.arange(20, dtype=np.float32).reshape(10, 2)
    y = np.arange(10, dtype=np.int32)
    loader = MinibatchLoader(X, y, batch_size=3, shuffle=True, seed=0)
    assert len(loader) == 4  # ceil(10/3): last partial batch kept
    seen = np.concatenate([yb for _, yb in loader])
    np.testing.assert_array_equal(np.sort(seen), y)
    # X rows travel with their labels through the shuffle
    for xb, yb in loader:
        np.testing.assert_array_equal(xb, X[yb])


def test_minibatch_loader_reshuffles_each_epoch():
    y = np.arange(64, dtype=np.int32)
    X = y.astype(np.float32).reshape(-1, 1)
    loader = MinibatchLoader(X, y, batch_size=64, shuffle=True, seed=3)
    first = next(iter(loader))[1].copy()
    second = next(iter(loader))[1].copy()
    assert not np.array_equal(first, second)
    ordered = MinibatchLoader(X, y, batch_size=64, shuffle=False)
    np.testing.assert_array_equal(next(iter(ordered))[1], y)


def test_load_data_svmlight_branch(tmp_path):
    lines = [f"{i % 3} 1:{i / 10.0} 2:{1.0 - i / 10.0}" for i in range(25)]
    (tmp_path / "toy").write_text("\n".join(lines) + "\n")
    train, validate, test, d, num_classes = load_data(
        "toy", batch_size=4, data_dir=str(tmp_path), seed=0)
    assert d == 2 and num_classes == 3
    assert validate is test  # reference returns testloader twice
    n_train = sum(len(yb) for _, yb in train)
    n_test = sum(len(yb) for _, yb in test)
    assert n_train == 20 and n_test == 5  # 80/20 split
    assert len(test) == 1  # single full-set test batch


def test_load_data_regression_num_classes(tmp_path):
    lines = [f"{i / 5.0} 1:{i}" for i in range(10)]
    (tmp_path / "abalone").write_text("\n".join(lines) + "\n")
    _, _, _, _, num_classes = load_data("abalone", data_dir=str(tmp_path))
    assert num_classes == 1


def test_load_data_mnist_branch(tmp_path):
    from tests.test_images import write_idx

    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, size=(30, 28, 28), dtype=np.uint8)
    labels = rng.randint(0, 10, size=30, dtype=np.uint8)
    write_idx(str(tmp_path / "train-images-idx3-ubyte"), imgs)
    write_idx(str(tmp_path / "train-labels-idx1-ubyte"), labels)
    write_idx(str(tmp_path / "t10k-images-idx3-ubyte"), imgs[:7])
    write_idx(str(tmp_path / "t10k-labels-idx1-ubyte"), labels[:7])

    train, validate, test, d, num_classes = load_data(
        "mnist", batch_size=8, data_dir=str(tmp_path), seed=0)
    assert d == 784 and num_classes == 10
    # reference: 6000-row validation split; fixture has fewer rows, so
    # train gets the remainder (possibly zero) — sizes must still add up
    n_val = sum(len(yb) for _, yb in validate)
    n_train = sum(len(yb) for _, yb in train)
    assert n_val + n_train == 30
    n_test = sum(len(yb) for _, yb in test)
    assert n_test == 7


def test_error_estimate_multiclass():
    logits = np.array([[2.0, 0.0, 0.0], [0.0, 2.0, 0.0], [0.0, 0.0, 2.0],
                       [2.0, 0.0, 0.0]], np.float32)
    target = np.array([0, 1, 2, 1])
    mse, err = error_estimate(logits, target, "multiclass")
    assert err == pytest.approx(0.25)
    onehot = np.eye(3, dtype=np.float32)[target]
    assert mse == pytest.approx(float(np.mean((logits - onehot) ** 2)))


def test_error_estimate_regression_and_bad_type():
    out = np.array([1.0, 2.0, 3.0], np.float32)
    tgt = np.array([1.0, 2.0, 5.0], np.float32)
    mse, mse2 = error_estimate(out, tgt, "regression")
    assert mse == mse2 == pytest.approx(4.0 / 3.0)
    with pytest.raises(ValueError):
        error_estimate(out, tgt, "nope")
