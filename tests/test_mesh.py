"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedamw_tpu.algorithms import FedAvg, prepare_setup
from fedamw_tpu.data import load_dataset
from fedamw_tpu.fedcore import (
    client_logits,
    make_client_round,
    make_evaluator,
    make_p_solver,
    weighted_average,
)
from fedamw_tpu.parallel import make_mesh, shard_client_keys, shard_setup


@pytest.fixture(scope="module")
def setup8():
    ds = load_dataset("digits", num_partitions=8, alpha=0.5)
    return prepare_setup(ds, kernel_type="linear", seed=100,
                         rng=np.random.RandomState(100))


def test_mesh_has_8_devices():
    mesh = make_mesh()
    assert mesh.devices.size == 8


def test_sharded_round_matches_unsharded(setup8):
    mesh = make_mesh()
    sharded = shard_setup(setup8, mesh)
    n_max = int(setup8.idx.shape[1])
    rf = jax.jit(make_client_round(setup8.model.apply, setup8.task, 1, 32, n_max))
    params = setup8.model.init(jax.random.PRNGKey(0), setup8.D, setup8.num_classes)
    keys = jax.random.split(jax.random.PRNGKey(1), 8)

    args = (jnp.float32(0.5), jnp.float32(0.0), jnp.float32(0.0))
    stacked_u, losses_u, _ = rf(params, setup8.X, setup8.y, setup8.idx,
                                setup8.mask, keys, *args)
    stacked_s, losses_s, _ = rf(params, sharded.X, sharded.y, sharded.idx,
                                sharded.mask, shard_client_keys(keys, mesh),
                                *args)
    np.testing.assert_allclose(np.asarray(losses_s), np.asarray(losses_u),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(stacked_s["w"]),
                               np.asarray(stacked_u["w"]), atol=1e-5)
    # the stacked client params actually live sharded over the mesh
    shard_devs = {d for s in stacked_s["w"].addressable_shards
                  for d in [s.device]}
    assert len(shard_devs) == 8


def test_sharded_aggregation_reduces_over_ici(setup8):
    mesh = make_mesh()
    sharded = shard_setup(setup8, mesh)
    n_max = int(setup8.idx.shape[1])
    rf = make_client_round(setup8.model.apply, setup8.task, 1, 32, n_max)
    evaluate = make_evaluator(setup8.model.apply, setup8.task)
    params = setup8.model.init(jax.random.PRNGKey(0), setup8.D, setup8.num_classes)
    keys = shard_client_keys(jax.random.split(jax.random.PRNGKey(1), 8), mesh)

    @jax.jit
    def round_step(params):
        stacked, losses, _ = rf(params, sharded.X, sharded.y, sharded.idx,
                                sharded.mask, keys, jnp.float32(0.5),
                                jnp.float32(0.0), jnp.float32(0.0))
        p = sharded.sizes.astype(jnp.float32)
        p = p / jnp.sum(p)
        g = weighted_average(stacked, p)
        return g, evaluate(g, sharded.X_test, sharded.y_test)

    g, (tl, ta) = round_step(params)
    assert np.isfinite(float(tl))
    assert float(ta) > 30.0  # one round of learning happened


def test_full_fedavg_on_sharded_setup(setup8):
    mesh = make_mesh()
    sharded = shard_setup(setup8, mesh)
    res = FedAvg(sharded, lr=0.5, epoch=1, round=4, seed=0, lr_mode="constant")
    res_u = FedAvg(setup8, lr=0.5, epoch=1, round=4, seed=0, lr_mode="constant")
    np.testing.assert_allclose(res["test_acc"], res_u["test_acc"], atol=1e-4)


def test_shard_setup_rejects_uneven(setup8):
    mesh = make_mesh()
    ds = load_dataset("digits", num_partitions=5, alpha=0.5)
    bad = prepare_setup(ds, kernel_type="linear", seed=1,
                        rng=np.random.RandomState(1))
    with pytest.raises(ValueError, match="divisible"):
        shard_setup(bad, mesh)


def test_padded_clients_for_mesh():
    ds = load_dataset("digits", num_partitions=5, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=1,
                          rng=np.random.RandomState(1), pad_clients_to=8)
    mesh = make_mesh()
    sharded = shard_setup(setup, mesh)
    res = FedAvg(sharded, lr=0.5, epoch=1, round=3, seed=0, lr_mode="constant")
    assert res["test_acc"][-1] > 60.0


# --- bucketing x mesh composition -----------------------------------------


@pytest.fixture(scope="module")
def bucketed20():
    """20 clients in 3 size buckets, each bucket padded to a multiple of
    8 — the packing the 1024/4096-client scale configs rely on."""
    ds = load_dataset("digits", num_partitions=20, alpha=0.3)
    return prepare_setup(ds, kernel_type="linear", seed=100,
                         rng=np.random.RandomState(100),
                         buckets=3, client_multiple=8)


def test_bucketed_setup_is_mesh_even(bucketed20):
    assert bucketed20.bucket_idx is not None
    for b in bucketed20.bucket_idx:
        assert b.shape[0] % 8 == 0
    # padded slots exist (20 clients never split 3-ways into 8-multiples)
    assert bucketed20.num_clients > 20
    assert int((np.asarray(bucketed20.sizes) > 0).sum()) == 20
    # inert padding carries zero weight
    p = np.asarray(bucketed20.p_fixed)
    assert np.all(p[np.asarray(bucketed20.sizes) == 0] == 0)
    np.testing.assert_allclose(p.sum(), 1.0, atol=1e-6)


def test_bucketed_fedavg_sharded_matches_unsharded(bucketed20):
    mesh = make_mesh()
    sharded = shard_setup(bucketed20, mesh)
    kw = dict(lr=0.5, epoch=1, round=4, seed=0, lr_mode="constant")
    res_u = FedAvg(bucketed20, **kw)
    res_s = FedAvg(sharded, **kw)
    np.testing.assert_allclose(res_s["test_acc"], res_u["test_acc"],
                               atol=1e-4)
    np.testing.assert_allclose(res_s["train_loss"], res_u["train_loss"],
                               atol=1e-5)


def test_bucketed_fedamw_sharded_matches_unsharded(bucketed20):
    from fedamw_tpu.algorithms import FedAMW

    mesh = make_mesh()
    sharded = shard_setup(bucketed20, mesh)
    kw = dict(lr=0.5, epoch=1, round=3, lambda_reg=1e-4, lr_p=1e-3,
              seed=0, lr_mode="constant")
    res_u = FedAMW(bucketed20, **kw)
    res_s = FedAMW(sharded, **kw)
    np.testing.assert_allclose(res_s["test_acc"], res_u["test_acc"],
                               atol=1e-4)


def test_bucketed_fedamw_padding_is_inert(bucketed20):
    """Learned mixture weights must stay exactly zero on padded clients
    (otherwise padded and unpadded runs diverge semantically)."""
    import jax.numpy as jnp

    from fedamw_tpu.fedcore import make_p_solver

    J = bucketed20.num_clients
    n_val = int(bucketed20.X_val.shape[0])
    solve, init_opt = make_p_solver(bucketed20.task, n_val, 16, 1e-2,
                                    momentum=0.9)
    valid = (np.asarray(bucketed20.sizes) > 0).astype(np.float32)
    p0 = jnp.asarray(bucketed20.p_fixed)
    rng = np.random.RandomState(0)
    logits = jnp.asarray(
        rng.randn(n_val, J, bucketed20.num_classes).astype(np.float32))
    p, _, _, _ = solve(logits, bucketed20.y_val, p0, init_opt(p0),
                       jax.random.PRNGKey(0), 2,
                       client_valid=jnp.asarray(valid))
    p = np.asarray(p)
    assert np.all(p[valid == 0] == 0.0)
    assert np.any(p[valid == 1] != np.asarray(p0)[valid == 1])


def test_shard_setup_rejects_uneven_bucket():
    ds = load_dataset("digits", num_partitions=10, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=1,
                          rng=np.random.RandomState(1), buckets=3)
    with pytest.raises(ValueError, match="divisible"):
        shard_setup(setup, make_mesh())


def test_participation_sharded_matches_unsharded(setup8):
    """Partial participation draws its Bernoulli mask inside the round
    scan; under a sharded client axis the mask, the renormalized
    weights, and the no-op-round logic must reproduce the unsharded run
    exactly."""
    mesh = make_mesh()
    sharded = shard_setup(setup8, mesh)
    kw = dict(lr=0.5, epoch=1, round=5, seed=0, lr_mode="constant",
              participation=0.5)
    res_u = FedAvg(setup8, **kw)
    res_s = FedAvg(sharded, **kw)
    np.testing.assert_allclose(res_s["train_loss"], res_u["train_loss"],
                               atol=1e-5)
    np.testing.assert_allclose(res_s["test_acc"], res_u["test_acc"],
                               atol=1e-4)


def test_fedopt_sharded_matches_unsharded(setup8):
    """The FedAdam server step runs on replicated params after the
    client-axis reduction; sharding must not change it."""
    mesh = make_mesh()
    sharded = shard_setup(setup8, mesh)
    kw = dict(lr=0.5, epoch=1, round=4, seed=0, lr_mode="constant",
              server_opt="adam", server_lr=0.1)
    res_u = FedAvg(setup8, **kw)
    res_s = FedAvg(sharded, **kw)
    np.testing.assert_allclose(res_s["test_loss"], res_u["test_loss"],
                               atol=1e-4)
    np.testing.assert_allclose(res_s["test_acc"], res_u["test_acc"],
                               atol=1e-3)


def test_oneshot_sharded_matches_unsharded(setup8):
    """FedAMW_OneShot's long local phase runs through the same bucketed
    round kernel; sharding the client axis must not change the one-shot
    mixture learning that follows."""
    from fedamw_tpu.algorithms import FedAMW_OneShot

    mesh = make_mesh()
    sharded = shard_setup(setup8, mesh)
    kw = dict(lr=0.5, epoch=2, round=3, lambda_reg=1e-4, lr_p=1e-3,
              seed=0)
    res_u = FedAMW_OneShot(setup8, **kw)
    res_s = FedAMW_OneShot(sharded, **kw)
    np.testing.assert_allclose(res_s["test_acc"], res_u["test_acc"],
                               atol=1e-3)
    np.testing.assert_allclose(res_s["test_loss"], res_u["test_loss"],
                               atol=1e-4)
