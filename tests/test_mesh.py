"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedamw_tpu.algorithms import FedAvg, prepare_setup
from fedamw_tpu.data import load_dataset
from fedamw_tpu.fedcore import (
    client_logits,
    make_client_round,
    make_evaluator,
    make_p_solver,
    weighted_average,
)
from fedamw_tpu.parallel import make_mesh, shard_client_keys, shard_setup


@pytest.fixture(scope="module")
def setup8():
    ds = load_dataset("digits", num_partitions=8, alpha=0.5)
    return prepare_setup(ds, kernel_type="linear", seed=100,
                         rng=np.random.RandomState(100))


def test_mesh_has_8_devices():
    mesh = make_mesh()
    assert mesh.devices.size == 8


def test_sharded_round_matches_unsharded(setup8):
    mesh = make_mesh()
    sharded = shard_setup(setup8, mesh)
    n_max = int(setup8.idx.shape[1])
    rf = jax.jit(make_client_round(setup8.model.apply, setup8.task, 1, 32, n_max))
    params = setup8.model.init(jax.random.PRNGKey(0), setup8.D, setup8.num_classes)
    keys = jax.random.split(jax.random.PRNGKey(1), 8)

    args = (jnp.float32(0.5), jnp.float32(0.0), jnp.float32(0.0))
    stacked_u, losses_u, _ = rf(params, setup8.X, setup8.y, setup8.idx,
                                setup8.mask, keys, *args)
    stacked_s, losses_s, _ = rf(params, sharded.X, sharded.y, sharded.idx,
                                sharded.mask, shard_client_keys(keys, mesh),
                                *args)
    np.testing.assert_allclose(np.asarray(losses_s), np.asarray(losses_u),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(stacked_s["w"]),
                               np.asarray(stacked_u["w"]), atol=1e-5)
    # the stacked client params actually live sharded over the mesh
    shard_devs = {d for s in stacked_s["w"].addressable_shards
                  for d in [s.device]}
    assert len(shard_devs) == 8


def test_sharded_aggregation_reduces_over_ici(setup8):
    mesh = make_mesh()
    sharded = shard_setup(setup8, mesh)
    n_max = int(setup8.idx.shape[1])
    rf = make_client_round(setup8.model.apply, setup8.task, 1, 32, n_max)
    evaluate = make_evaluator(setup8.model.apply, setup8.task)
    params = setup8.model.init(jax.random.PRNGKey(0), setup8.D, setup8.num_classes)
    keys = shard_client_keys(jax.random.split(jax.random.PRNGKey(1), 8), mesh)

    @jax.jit
    def round_step(params):
        stacked, losses, _ = rf(params, sharded.X, sharded.y, sharded.idx,
                                sharded.mask, keys, jnp.float32(0.5),
                                jnp.float32(0.0), jnp.float32(0.0))
        p = sharded.sizes.astype(jnp.float32)
        p = p / jnp.sum(p)
        g = weighted_average(stacked, p)
        return g, evaluate(g, sharded.X_test, sharded.y_test)

    g, (tl, ta) = round_step(params)
    assert np.isfinite(float(tl))
    assert float(ta) > 30.0  # one round of learning happened


def test_full_fedavg_on_sharded_setup(setup8):
    mesh = make_mesh()
    sharded = shard_setup(setup8, mesh)
    res = FedAvg(sharded, lr=0.5, epoch=1, round=4, seed=0, lr_mode="constant")
    res_u = FedAvg(setup8, lr=0.5, epoch=1, round=4, seed=0, lr_mode="constant")
    np.testing.assert_allclose(res["test_acc"], res_u["test_acc"], atol=1e-4)


def test_shard_setup_rejects_uneven(setup8):
    mesh = make_mesh()
    ds = load_dataset("digits", num_partitions=5, alpha=0.5)
    bad = prepare_setup(ds, kernel_type="linear", seed=1,
                        rng=np.random.RandomState(1))
    with pytest.raises(ValueError, match="divisible"):
        shard_setup(bad, mesh)


def test_padded_clients_for_mesh():
    ds = load_dataset("digits", num_partitions=5, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=1,
                          rng=np.random.RandomState(1), pad_clients_to=8)
    mesh = make_mesh()
    sharded = shard_setup(setup, mesh)
    res = FedAvg(sharded, lr=0.5, epoch=1, round=3, seed=0, lr_mode="constant")
    assert res["test_acc"][-1] > 60.0
