"""Multi-host init smoke: 2-process CPU ``jax.distributed``.

Covers the DCN tier of the communication backend
(``parallel/mesh.py:initialize_multihost``): two spawned processes join
one JAX runtime via the coordination service, build a GLOBAL mesh
spanning both, and run the framework's aggregation collective — the
weighted average over the client axis — across the process boundary.
On real pods the same three args come from the environment and the
reduction rides DCN; here the transport is local grpc, which exercises
the identical code path (SURVEY §5: the reference imports
torch.distributed and never calls it — this capability is new).
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np

_CHILD = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = ""  # one local device per process
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.environ["FEDAMW_REPO"])
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fedamw_tpu.parallel import initialize_multihost, make_mesh

    addr, pid = sys.argv[1], int(sys.argv[2])
    n = initialize_multihost(coordinator_address=addr, num_processes=2,
                             process_id=pid)
    assert n == 2, f"global device count {n}"
    assert jax.process_count() == 2
    mesh = make_mesh()  # global mesh spanning both processes

    # the framework's server step: weighted average of stacked client
    # params over the sharded client axis -> all-reduce across hosts.
    # Client pid's (3,) params live on this process; p = (0.25, 0.75).
    sh = NamedSharding(mesh, P("clients", None))
    local = jax.device_put(
        jnp.full((1, 3), float(pid + 1)), jax.local_devices()[0])
    stacked = jax.make_array_from_single_device_arrays((2, 3), sh, [local])
    p = jax.device_put(jnp.array([0.25, 0.75]), NamedSharding(mesh, P()))
    agg = jax.jit(
        lambda w, p: jnp.tensordot(p, w, axes=1),
        out_shardings=NamedSharding(mesh, P()),
    )(stacked, p)
    got = float(agg[0])
    assert abs(got - 1.75) < 1e-6, got  # 0.25*1 + 0.75*2
    print(f"OK pid={pid} agg={got}", flush=True)
""")


_TRAIN_CHILD = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.environ["FEDAMW_REPO"])
    import numpy as np

    from fedamw_tpu.parallel import initialize_multihost, make_mesh, \\
        shard_setup

    addr, pid = sys.argv[1], int(sys.argv[2])
    n = initialize_multihost(coordinator_address=addr, num_processes=2,
                             process_id=pid)
    assert n == 4, n  # 2 hosts x 2 devices: a DCN x ICI layout in miniature

    from fedamw_tpu.algorithms import FedAMW, FedAvg, prepare_setup
    from fedamw_tpu.data import load_dataset

    ds = load_dataset("digits", num_partitions=6, alpha=0.5,
                      rng=np.random.RandomState(7))
    setup = prepare_setup(ds, D=64, kernel_par=0.1, seed=7,
                          rng=np.random.RandomState(7), buckets=2,
                          client_multiple=4)
    setup = shard_setup(setup, make_mesh())
    res = FedAvg(setup, lr=0.5, epoch=1, batch_size=16, round=2, seed=0,
                 lr_mode="constant")
    res2 = FedAMW(setup, lr=0.5, epoch=1, batch_size=16, round=2,
                  lambda_reg=1e-4, lr_p=1e-3, seed=0, lr_mode="constant")
    print(f"MHTRAIN pid={pid} "
          f"fedavg={float(res['test_acc'][-1]):.6f} "
          f"fedamw={float(res2['test_acc'][-1]):.6f}", flush=True)
""")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_init_and_cross_host_aggregation(tmp_path):
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    addr = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["FEDAMW_REPO"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), addr, str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for pr in procs:
            out, _ = pr.communicate(timeout=120)
            outs.append(out)
    finally:
        for pr in procs:
            pr.kill()
    for pid, (pr, out) in enumerate(zip(procs, outs)):
        assert pr.returncode == 0, f"child {pid} failed:\n{out[-2000:]}"
        assert f"OK pid={pid}" in out
    accs = [line for out in outs for line in out.splitlines()
            if line.startswith("OK")]
    assert len(accs) == 2
    np.testing.assert_allclose(
        [float(a.split("agg=")[1]) for a in accs], [1.75, 1.75])


def test_two_process_full_training_matches_single_process(tmp_path):
    """The FULL training path — bucketed vmapped local SGD, FedAMW's
    p-solver over cached logits, weighted aggregation, eval — jitted
    over a 4-device global mesh spanning 2 processes (2 local devices
    each: DCN x ICI in miniature). Both ranks must report identical
    metrics, and they must match the same program on a single-process
    4-device mesh (the pjit promise: placement changes, the program
    doesn't)."""
    script = tmp_path / "train_child.py"
    script.write_text(_TRAIN_CHILD)
    addr = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["FEDAMW_REPO"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), addr, str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for pr in procs:
            out, _ = pr.communicate(timeout=420)
            outs.append(out)
    finally:
        for pr in procs:
            pr.kill()
    lines = {}
    for pid, (pr, out) in enumerate(zip(procs, outs)):
        assert pr.returncode == 0, f"child {pid} failed:\n{out[-3000:]}"
        (line,) = [ln for ln in out.splitlines()
                   if ln.startswith("MHTRAIN")]
        lines[pid] = line.split(" ", 2)[2]
    assert lines[0] == lines[1]  # SPMD: every rank sees the same metrics

    # single-process reference: same setup on 4 of this process's 8
    # virtual devices (identical logical mesh)
    from fedamw_tpu.algorithms import FedAMW, FedAvg, prepare_setup
    from fedamw_tpu.data import load_dataset
    from fedamw_tpu.parallel import make_mesh, shard_setup

    ds = load_dataset("digits", num_partitions=6, alpha=0.5,
                      rng=np.random.RandomState(7))
    setup = prepare_setup(ds, D=64, kernel_par=0.1, seed=7,
                          rng=np.random.RandomState(7), buckets=2,
                          client_multiple=4)
    setup = shard_setup(setup, make_mesh(4))
    res = FedAvg(setup, lr=0.5, epoch=1, batch_size=16, round=2, seed=0,
                 lr_mode="constant")
    res2 = FedAMW(setup, lr=0.5, epoch=1, batch_size=16, round=2,
                  lambda_reg=1e-4, lr_p=1e-3, seed=0, lr_mode="constant")
    got = dict(part.split("=") for part in lines[0].split())
    np.testing.assert_allclose(float(got["fedavg"]),
                               float(res["test_acc"][-1]), atol=1e-4)
    np.testing.assert_allclose(float(got["fedamw"]),
                               float(res2["test_acc"][-1]), atol=1e-4)


def test_two_process_exp_driver(tmp_path):
    """The experiment driver end to end across two processes
    (--multihost): both hosts run the SAME command, the client axis
    shards over the 2x2 global mesh, exactly process 0 writes the
    result pickle in the reference schema — and that pickle EQUALS the
    single-process run of the same command (round-4 verdict #5: the
    DCN tier must carry the whole driver, not one collective, and
    placement must not change the math)."""
    addr = f"127.0.0.1:{_free_port()}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outdirs = [tmp_path / f"p{pid}" for pid in range(2)]
    procs = []
    for pid in range(2):
        outdirs[pid].mkdir()
        env = dict(os.environ)
        env.update(JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=2",
                   PYTHONPATH=repo)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(repo, "exp.py"),
             "--dataset", "digits", "--D", "64", "--num_partitions", "6",
             "--round", "2", "--local_epoch", "1", "--multihost",
             "--coordinator", addr, "--num_processes", "2",
             "--process_id", str(pid),
             "--result_dir", str(outdirs[pid])],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=str(outdirs[pid]),
        ))
    outs = []
    try:
        for pr in procs:
            out, _ = pr.communicate(timeout=420)
            outs.append(out)
    finally:
        for pr in procs:
            pr.kill()
    for pid, (pr, out) in enumerate(zip(procs, outs)):
        assert pr.returncode == 0, f"process {pid} failed:\n{out[-3000:]}"
        assert "multihost: process" in out and "4 global devices" in out
    # one writer: process 0's pickle exists in the reference schema,
    # process 1 wrote nothing
    import pickle as _pickle
    with open(outdirs[0] / "exp1_digits.pkl", "rb") as f:
        data = _pickle.load(f)
    assert data["test_acc"].shape == (6, 2, 1)
    assert np.all(np.isfinite(data["train_loss"]))
    assert not (outdirs[1] / "exp1_digits.pkl").exists()

    # single-process reference: the same command without --multihost on
    # a 4-device single-process mesh (--shard 4 — the identical logical
    # mesh, so pjit's promise is placement-only). The multihost pickle
    # must reproduce it to float tolerance on every metric surface.
    soloDir = tmp_path / "solo"
    soloDir.mkdir()
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=4",
               PYTHONPATH=repo)
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "exp.py"),
         "--dataset", "digits", "--D", "64", "--num_partitions", "6",
         "--round", "2", "--local_epoch", "1", "--shard", "4",
         "--result_dir", str(soloDir)],
        capture_output=True, text=True, env=env, cwd=str(soloDir),
        timeout=420,
    )
    assert out.returncode == 0, (out.stdout + out.stderr)[-3000:]
    with open(soloDir / "exp1_digits.pkl", "rb") as f:
        solo = _pickle.load(f)
    for k in ("train_loss", "test_loss", "test_acc", "heterogeneity"):
        np.testing.assert_allclose(data[k], solo[k], rtol=1e-5,
                                   atol=1e-5, err_msg=k)
