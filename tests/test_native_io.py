"""Native C++ svmlight parser vs sklearn ground truth."""

import numpy as np
import pytest

sk = pytest.importorskip("sklearn.datasets")


def _random_svmlight_file(path, n=200, d=40, seed=0, density=0.2):
    rng = np.random.RandomState(seed)
    lines = []
    for _ in range(n):
        label = rng.choice([-1.0, 1.0])
        nnz = rng.binomial(d, density)
        idxs = np.sort(rng.choice(d, size=max(nnz, 1), replace=False)) + 1
        feats = " ".join(f"{i}:{rng.randn():.6f}" for i in idxs)
        lines.append(f"{label:g} {feats}")
    path.write_text("\n".join(lines) + "\n")


def test_native_matches_sklearn(tmp_path):
    from fedamw_tpu import native_io

    path = tmp_path / "rand.svm"
    _random_svmlight_file(path, n=200, d=40)
    X_native, y_native = native_io.load_svmlight(str(path))

    X_sk, y_sk = sk.load_svmlight_file(str(path))
    X_sk = np.asarray(X_sk.todense(), dtype=np.float32)

    assert X_native.shape == X_sk.shape
    np.testing.assert_allclose(X_native, X_sk, rtol=1e-6)
    np.testing.assert_allclose(y_native, y_sk)


def test_native_handles_comments_and_blanks(tmp_path):
    from fedamw_tpu import native_io

    path = tmp_path / "messy.svm"
    path.write_text("# header comment\n\n2 1:0.5 3:1.25\n\n1 2:-2.0\n")
    X, y = native_io.load_svmlight(str(path))
    assert X.shape == (2, 3)
    np.testing.assert_allclose(X[0], [0.5, 0.0, 1.25])
    np.testing.assert_allclose(X[1], [0.0, -2.0, 0.0])
    np.testing.assert_allclose(y, [2.0, 1.0])


def test_native_missing_file():
    from fedamw_tpu import native_io

    with pytest.raises(OSError):
        native_io.load_svmlight("/tmp/definitely_not_here.svm")


def test_data_layer_uses_native(tmp_path):
    # load_svmlight in the data layer should transparently use the
    # native parser and produce canonicalized labels
    from fedamw_tpu.data import load_svmlight

    path = tmp_path / "toy"
    path.write_text("3 1:0.5 4:1.5\n1 2:2.0\n2 1:-1.0 4:0.25\n")
    X, y = load_svmlight("toy", str(tmp_path), use_native=True)
    assert X.shape == (3, 4)
    np.testing.assert_array_equal(y, [2, 0, 1])
