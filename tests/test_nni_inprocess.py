"""The ``HAS_NNI=True`` branch of tune.py, executed IN-PROCESS.

``tests/test_nni_merge.py`` runs the branch in a subprocess (fake nni
package on PYTHONPATH); this companion injects a fake ``nni`` via
``sys.modules`` and drives ``tune.py`` with ``runpy`` under
``run_name="__main__"`` so the real tuner code path — ``import nni``
succeeding, ``nni.get_next_parameter()``, ``merge_parameter`` precedence
over argparse defaults, and ``nni.report_final_result`` (``tune.py:
18-24, 101-115``; reference flow ``/root/reference/tune.py:170-177``) —
executes inside the test process where its coverage is directly
observable (VERDICT r3, missing #4).
"""

import os
import runpy
import sys
import types

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TUNER_PARAMS = {"lr_p": 0.04321, "lambda_reg": 0.00777}


def _fake_nni(reported):
    """An in-memory nni package mirroring the two entry points tune.py
    uses, with real-NNI merge semantics (overwrite Namespace attrs,
    reject unknown keys)."""
    nni = types.ModuleType("nni")
    nni.get_next_parameter = lambda: dict(TUNER_PARAMS)
    nni.report_final_result = reported.append

    utils = types.ModuleType("nni.utils")

    def merge_parameter(args, tuner_params):
        for k, v in tuner_params.items():
            if not hasattr(args, k):
                raise ValueError(f"unknown tuner param {k!r}")
            cur = getattr(args, k)
            setattr(args, k, type(cur)(v) if cur is not None else v)
        return args

    utils.merge_parameter = merge_parameter
    nni.utils = utils
    return nni, utils


def test_has_nni_true_branch_runs_in_process(monkeypatch, capsys):
    reported = []
    nni, utils = _fake_nni(reported)
    monkeypatch.setitem(sys.modules, "nni", nni)
    monkeypatch.setitem(sys.modules, "nni.utils", utils)
    # small-but-real trial: torch backend (no jit warmup), digits at the
    # driver's hard-coded J=50/alpha=0.01, one round
    monkeypatch.setattr(sys, "argv", [
        "tune.py", "--backend", "torch", "--dataset", "digits",
        "--D", "32", "--round", "1", "--local_epoch", "1",
    ])
    ns = runpy.run_path(os.path.join(REPO, "tune.py"),
                        run_name="__main__")

    assert ns["HAS_NNI"] is True  # the real import-gate took the NNI arm
    out = capsys.readouterr().out
    # tuner-proposed values overwrote the argparse defaults (keyed match
    # in the printed merged-params dict, not a bare-substring match)
    assert f"'lr_p': {TUNER_PARAMS['lr_p']}" in out
    assert f"'lambda_reg': {TUNER_PARAMS['lambda_reg']}" in out
    # ...and non-tuned flags kept their CLI values
    assert "'backend': 'torch'" in out
    # the final metric crossed back through nni.report_final_result
    assert len(reported) == 1
    acc = float(reported[0])
    assert np.isfinite(acc) and 0.0 <= acc <= 100.0
    assert f"acc={acc:.5f}" in out
