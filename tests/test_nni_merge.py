"""The NNI merge path of tune.py, exercised with a vendored fake nni.

NNI is not installed on this box, so the ``merge_parameter`` precedence
branch (``tune.py:98-106``; reference ``tune.py:173-175``) would never
execute. A minimal fake ``nni`` package on PYTHONPATH activates it and
proves tuner-proposed parameters win over argparse defaults, and that
the final accuracy flows back through ``nni.report_final_result``.
"""

import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def write_fake_nni(root, tuner_params, report_path):
    pkg = root / "nni"
    pkg.mkdir()
    (pkg / "__init__.py").write_text(textwrap.dedent(f"""
        import json

        def get_next_parameter():
            return json.loads({json.dumps(json.dumps(tuner_params))})

        def report_final_result(value):
            with open({str(report_path)!r}, "w") as f:
                f.write(repr(float(value)))
    """))
    # real NNI's merge_parameter overwrites Namespace attrs in place
    (pkg / "utils.py").write_text(textwrap.dedent("""
        def merge_parameter(args, tuner_params):
            for k, v in tuner_params.items():
                if not hasattr(args, k):
                    raise ValueError(f"unknown tuner param {k!r}")
                setattr(args, k, type(getattr(args, k))(v)
                        if getattr(args, k) is not None else v)
            return args
    """))


def test_tuner_params_override_argparse_defaults(tmp_path):
    report = tmp_path / "reported.txt"
    write_fake_nni(tmp_path, {"lr_p": 0.01234, "lambda_reg": 0.00567},
                   report)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + \
        " --xla_force_host_platform_device_count=8"
    # fake nni shadows the (absent) real one; repo stays importable
    env["PYTHONPATH"] = f"{tmp_path}{os.pathsep}{REPO}"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tune.py"),
         "--dataset", "digits", "--D", "64", "--round", "2"],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=600,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    # the printed merged-params dict shows the tuner values won
    assert "0.01234" in out.stdout
    assert "0.00567" in out.stdout
    # and the final metric crossed back through report_final_result
    assert report.exists()
    reported = float(report.read_text())
    assert 0.0 <= reported <= 100.0
    assert f"acc={reported:.5f}" in out.stdout
