"""Execute config.yml's LITERAL trialCommand under a fake NNI daemon.

``tests/test_nni_merge.py`` runs a trial subprocess with
hand-chosen flags; this test closes the remaining gap (VERDICT r2,
missing #4): parse ``config.yml`` exactly as ``nnictl`` would, sample a
point from its declared search space, and run the trialCommand string
verbatim (reference flow: ``/root/reference/config.yml:25`` ->
``tune.py:170-177``). On this box ``satimage`` resolves to the
shape-matched synthetic fallback, so the literal command (D=2000,
R=100, 50 clients) runs in about a minute on the virtual-CPU mesh.
"""

import os
import shlex
import subprocess
import sys

import yaml

from test_nni_merge import write_fake_nni

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_literal_trialcommand_executes_and_reports(tmp_path):
    with open(os.path.join(REPO, "config.yml")) as f:
        cfg = yaml.safe_load(f)

    # the search space must be addressable by tune.py's flag surface
    space = cfg["searchSpace"]
    assert set(space) == {"lr_p", "lambda_reg"}
    for spec in space.values():
        assert spec["_type"] == "choice" and spec["_value"]

    # one TPE-style sample: a deterministic grid point from _value
    tuner_params = {k: spec["_value"][2] for k, spec in space.items()}
    report = tmp_path / "reported.txt"
    write_fake_nni(tmp_path, tuner_params, report)

    argv = shlex.split(cfg["trialCommand"])
    assert argv[0] == "python3" and argv[1] == "tune.py"
    # same interpreter, literal flags; cwd=REPO as nnictl's trial would
    argv = [sys.executable, os.path.join(REPO, argv[1]), *argv[2:]]

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + \
        " --xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = f"{tmp_path}{os.pathsep}{REPO}"
    out = subprocess.run(argv, cwd=REPO, env=env, capture_output=True,
                         text=True, timeout=570)
    assert out.returncode == 0, out.stderr[-2000:]
    assert report.exists(), out.stdout[-2000:]
    reported = float(report.read_text())
    assert 0.0 <= reported <= 100.0
    assert f"acc={reported:.5f}" in out.stdout
    # the sampled tuner values reached the merged-params dict (keyed
    # form: a bare value substring could match another flag's default)
    assert f"'lr_p': {tuner_params['lr_p']}" in out.stdout
    assert f"'lambda_reg': {tuner_params['lambda_reg']}" in out.stdout
