import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedamw_tpu.models import get_model, linear_model, mlp_model
from fedamw_tpu.ops import (
    Meter,
    ce_per_example,
    comp_accuracy,
    l2_norm_safe,
    lr_schedule_array,
    masked_accuracy,
    masked_mean,
    mse_per_example,
    prox_penalty,
    rff_map,
    rff_params,
    ridge_penalty,
    training_loss,
    update_learning_rate,
)


class TestRFF:
    def test_shapes_and_range(self):
        key = jax.random.PRNGKey(0)
        W, b = rff_params(key, 5, 64, sigma=0.5)
        assert W.shape == (5, 64) and b.shape == (1, 64)
        X = jax.random.normal(jax.random.PRNGKey(1), (7, 5))
        phi = rff_map(X, W, b)
        assert phi.shape == (7, 64)
        assert jnp.all(jnp.abs(phi) <= 1.0 / np.sqrt(64) + 1e-6)

    def test_kernel_approximation(self):
        # E[phi(x) . phi(y)] = 0.5 * exp(-sigma^2 ||x-y||^2 / 2) with the
        # reference's 1/sqrt(D) normalization (tools.py:27).
        sigma, D = 0.7, 60000
        W, b = rff_params(jax.random.PRNGKey(2), 3, D, sigma)
        x = jnp.array([[0.3, -0.1, 0.5]])
        y = jnp.array([[-0.2, 0.4, 0.1]])
        approx = float((rff_map(x, W, b) @ rff_map(y, W, b).T).squeeze())
        exact = 0.5 * np.exp(-(sigma**2) * float(jnp.sum((x - y) ** 2)) / 2)
        assert abs(approx - exact) < 0.01

    def test_sigma_is_std(self):
        W, _ = rff_params(jax.random.PRNGKey(3), 100, 2000, sigma=0.3)
        assert abs(float(W.std()) - 0.3) < 0.005


class TestLosses:
    def test_ce_matches_torch(self):
        import torch

        logits = np.random.RandomState(0).randn(8, 5).astype(np.float32)
        labels = np.random.RandomState(1).randint(0, 5, 8)
        want = torch.nn.CrossEntropyLoss()(
            torch.tensor(logits), torch.tensor(labels)
        ).item()
        got = float(masked_mean(ce_per_example(jnp.array(logits), jnp.array(labels)),
                                jnp.ones(8)))
        assert abs(got - want) < 1e-5

    def test_mse_matches_torch(self):
        import torch

        preds = np.random.RandomState(0).randn(6, 1).astype(np.float32)
        targets = np.random.RandomState(1).randn(6).astype(np.float32)
        want = torch.nn.MSELoss()(
            torch.tensor(preds), torch.tensor(targets).reshape(6, 1)
        ).item()
        got = float(masked_mean(mse_per_example(jnp.array(preds), jnp.array(targets)),
                                jnp.ones(6)))
        assert abs(got - want) < 1e-5

    def test_masked_mean_ignores_padding(self):
        v = jnp.array([1.0, 2.0, 100.0])
        m = jnp.array([1.0, 1.0, 0.0])
        assert float(masked_mean(v, m)) == pytest.approx(1.5)
        assert float(masked_mean(v, jnp.zeros(3))) == 0.0

    def test_prox_matches_torch_norm(self):
        import torch

        w = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        a = np.random.RandomState(1).randn(3, 4).astype(np.float32)
        want = torch.norm(torch.tensor(w) - torch.tensor(a), 2).item()
        got = float(prox_penalty({"w": jnp.array(w)}, {"w": jnp.array(a)}))
        assert abs(got - want) < 1e-5

    def test_prox_grad_zero_at_anchor(self):
        w = {"w": jnp.ones((3, 4))}
        g = jax.grad(lambda p: prox_penalty(p, w))(w)
        assert jnp.all(jnp.isfinite(g["w"]))
        assert float(jnp.abs(g["w"]).max()) == 0.0

    def test_ridge_skips_biases(self):
        params = {"w1": jnp.full((2, 2), 3.0), "b1": jnp.full((7,), 100.0)}
        assert float(ridge_penalty(params)) == pytest.approx(6.0)

    def test_training_loss_combination(self):
        model = linear_model()
        params = model.init(jax.random.PRNGKey(0), 4, 3)
        anchor = jax.tree.map(lambda w: w + 1.0, params)
        x = jnp.ones((2, 4))
        y = jnp.array([0, 2])
        m = jnp.ones(2)
        base, _ = training_loss(params, anchor, model.apply, x, y, m,
                                "classification", 0.0, 0.0)
        with_pen, _ = training_loss(params, anchor, model.apply, x, y, m,
                                    "classification", 0.5, 0.25)
        expected = float(base) + 0.5 * float(prox_penalty(params, anchor)) \
            + 0.25 * float(ridge_penalty(params))
        assert float(with_pen) == pytest.approx(expected, rel=1e-5)

    def test_l2_norm_safe_zero(self):
        assert float(l2_norm_safe(jnp.zeros(5))) == 0.0
        g = jax.grad(lambda x: l2_norm_safe(x))(jnp.zeros(5))
        assert jnp.all(g == 0.0)


class TestMetrics:
    def test_masked_accuracy(self):
        logits = jnp.array([[2.0, 1.0], [0.0, 3.0], [5.0, 0.0]])
        labels = jnp.array([0, 1, 1])
        acc = float(masked_accuracy(logits, labels, jnp.ones(3)))
        assert acc == pytest.approx(100.0 * 2 / 3)
        acc2 = float(masked_accuracy(logits, labels, jnp.array([1.0, 1.0, 0.0])))
        assert acc2 == pytest.approx(100.0)

    def test_comp_accuracy_matches_reference_semantics(self):
        rng = np.random.RandomState(0)
        out = rng.randn(20, 6)
        target = rng.randint(0, 6, 20)
        top1, top3 = comp_accuracy(out, target, topk=(1, 3))
        want1 = 100.0 * np.mean(np.argmax(out, 1) == target)
        assert top1 == pytest.approx(want1)
        assert top3 >= top1

    def test_meter(self):
        m = Meter(ptag="Loss")
        m.update(1.0, n=2)
        m.update(4.0, n=1)
        assert m.avg == pytest.approx(2.0)
        assert m.count == 3


class TestSchedule:
    def test_reference_compounding(self):
        lrs = lr_schedule_array(1.0, 100, "reference")
        assert lrs[0] == 1.0 and lrs[49] == 1.0
        assert lrs[50] == pytest.approx(0.1)
        assert lrs[74] == pytest.approx(0.1)
        assert lrs[75] == pytest.approx(0.001)  # compounded, not 0.01
        assert lrs[99] == pytest.approx(0.001)

    def test_paper_mode(self):
        lrs = lr_schedule_array(1.0, 100, "paper")
        assert lrs[75] == pytest.approx(0.01)

    def test_matches_reference_recurrence(self):
        # simulate the reference's reassignment loop via the
        # reference-surface single-step function
        for T in (1, 2, 3, 4, 7, 100):
            lr = 0.5
            expect = []
            for t in range(T):
                lr = update_learning_rate(t, lr, T)
                expect.append(lr)
            np.testing.assert_allclose(
                lr_schedule_array(0.5, T, "reference"), expect, rtol=1e-6
            )


class TestModels:
    def test_linear_forward(self):
        model = linear_model()
        params = model.init(jax.random.PRNGKey(0), 10, 3)
        assert params["w"].shape == (3, 10)
        bound = np.sqrt(6.0 / 13)
        assert float(jnp.abs(params["w"]).max()) <= bound
        out = model.apply(params, jnp.ones((5, 10)))
        assert out.shape == (5, 3)

    def test_mlp_forward(self):
        model = mlp_model(hidden=16)
        params = model.init(jax.random.PRNGKey(0), 10, 3)
        out = model.apply(params, jnp.ones((5, 10)))
        assert out.shape == (5, 3)

    def test_get_model(self):
        assert get_model("linear").name == "linear"
        assert get_model("mlp32").name == "mlp32"

    def test_deep_mlp_spec_and_forward(self):
        model = get_model("mlp32x16")
        assert model.name == "mlp32x16"
        params = model.init(jax.random.PRNGKey(0), 10, 3)
        assert params["w1"].shape == (32, 10)
        assert params["w2"].shape == (16, 32)
        assert params["w3"].shape == (3, 16)
        out = model.apply(params, jnp.ones((5, 10)))
        assert out.shape == (5, 3)

    def test_single_hidden_mlp_params_unchanged_by_depth_support(self):
        # the deep-stack generalization must not move the existing
        # 2-layer model's initialization (same split, same shapes)
        from fedamw_tpu.models import xavier_uniform

        model = mlp_model(hidden=16)
        params = model.init(jax.random.PRNGKey(7), 10, 3)
        assert set(params) == {"w1", "b1", "w2"}
        k1, _ = jax.random.split(jax.random.PRNGKey(7))
        np.testing.assert_array_equal(
            np.asarray(params["w1"]),
            np.asarray(xavier_uniform(k1, (16, 10))))

    def test_deep_mlp_federates(self):
        # any-depth pytree model must run through the full FedAvg path
        # (stacking, aggregation, eval are pytree-generic)
        from fedamw_tpu.algorithms import FedAvg, prepare_setup
        from fedamw_tpu.data import load_dataset

        ds = load_dataset("digits", num_partitions=4, alpha=0.5,
                          rng=np.random.RandomState(3))
        setup = prepare_setup(ds, kernel_type="linear", seed=3,
                              rng=np.random.RandomState(3),
                              model="mlp32x16")
        res = FedAvg(setup, lr=0.5, epoch=1, round=3, seed=0,
                     lr_mode="constant")
        assert np.all(np.isfinite(np.asarray(res["test_loss"])))
        assert res["test_acc"][-1] > 15.0  # learns past chance


class TestConvModel:
    def test_conv_forward_shapes(self):
        from fedamw_tpu.models import conv_model

        model = conv_model(channels=(4, 8))
        params = model.init(jax.random.PRNGKey(0), 64, 10)  # 8x8 digits
        assert params["k1"].shape == (3, 3, 1, 4)
        assert params["k2"].shape == (3, 3, 4, 8)
        # two stride-2 convs: 8 -> 4 -> 2; head fan-in 2*2*8
        assert params["w"].shape == (10, 32)
        out = model.apply(params, jnp.ones((5, 64)))
        assert out.shape == (5, 10)

    def test_conv_spec_and_registry(self):
        assert get_model("conv").name == "conv8x16"
        assert get_model("conv4").name == "conv4"
        assert get_model("conv4x8").name == "conv4x8"

    def test_conv_rejects_non_square_features(self):
        from fedamw_tpu.models import conv_model

        with pytest.raises(ValueError, match="perfect square"):
            conv_model((4,)).init(jax.random.PRNGKey(0), 60, 10)

    def test_conv_federates_and_learns(self):
        """The CNN drops into the generic federated path (identity
        feature map on raw 8x8 digits) and beats chance within a few
        FedAvg rounds — aggregation, the client kernel's autodiff path,
        and evaluation are all pytree-generic."""
        import numpy as np

        from fedamw_tpu.algorithms import FedAvg, prepare_setup
        from fedamw_tpu.data import load_dataset

        ds = load_dataset("digits", num_partitions=8, alpha=0.5)
        setup = prepare_setup(ds, kernel_type="linear", seed=5,
                              rng=np.random.RandomState(5),
                              model="conv4x8")
        res = FedAvg(setup, lr=0.3, epoch=2, batch_size=32, round=8,
                     seed=0, lr_mode="constant")
        acc = float(np.asarray(res["test_acc"])[-1])
        assert acc > 60.0, acc  # 10 classes, chance = 10%; measured 80


@pytest.mark.skipif(jax.default_backend() != "cpu",
                    reason="pins XLA's CPU cost-model output; "
                           "accelerator backends count fusion-level")
def test_conv_flops_use_xla_cost_model():
    """Conv kernels are 4-D and do work proportional to their output
    spatial size — parameter shapes alone undercount them (only the
    linear head would register). With apply_fn/d the count comes from
    XLA's cost model; GEMM-only models keep the documented 2·in·out
    formula bit-for-bit (committed artifact continuity)."""
    from fedamw_tpu.models import get_model
    from fedamw_tpu.utils.flops import fwd_flops_per_sample

    m = get_model("conv8x16")
    p = m.init(jax.random.PRNGKey(0), 784, 10)
    head_only = fwd_flops_per_sample(p)
    full = fwd_flops_per_sample(p, apply_fn=m.apply, d=784)
    assert head_only == 2 * 784 * 10  # the (10, 7*7*16) head alone
    # hand estimate (interior positions): conv1 2*9*1*8*14*14 = 28,224
    # + conv2 2*9*8*16*7*7 = 112,896 + head 15,680 = 156,800; XLA's
    # SAME-padding edge handling counts slightly fewer
    assert 100_000 < full <= 160_000, full

    lm = get_model("linear")
    lp = lm.init(jax.random.PRNGKey(0), 2000, 2)
    assert (fwd_flops_per_sample(lp)
            == fwd_flops_per_sample(lp, apply_fn=lm.apply, d=2000)
            == 2 * 2000 * 2)
    # provenance names the basis actually used on every path (round-4
    # advisor: emitters stamp it on each record, so the two
    # non-comparable counting bases can never be conflated silently)
    assert fwd_flops_per_sample(
        lp, with_provenance=True) == (2 * 2000 * 2, "gemm-formula")
    assert fwd_flops_per_sample(
        p, apply_fn=m.apply, d=784,
        with_provenance=True)[1] == "xla-cost-model"
    assert fwd_flops_per_sample(
        p, with_provenance=True)[1] == "gemm-formula-undercount"


def test_conv_fedamw_learned_mixture():
    """FedAMW's learned-mixture machinery (per-client logit cache,
    p-SGD, weighted aggregation) is pytree-generic: it runs the CNN
    unchanged and p stays finite/non-degenerate."""
    from fedamw_tpu.algorithms import FedAMW, prepare_setup
    from fedamw_tpu.data import load_dataset

    ds = load_dataset("digits", num_partitions=6, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=3,
                          rng=np.random.RandomState(3), model="conv4x8")
    res = FedAMW(setup, lr=0.3, epoch=2, batch_size=32, round=8,
                 lambda_reg=1e-4, lr_p=1e-3, seed=0, lr_mode="constant",
                 return_state=True)
    acc = float(np.asarray(res["test_acc"])[-1])
    p = np.asarray(res["p"])
    assert np.all(np.isfinite(p)) and p.shape == (6,)
    assert float(np.std(p)) > 0.0  # the mixture actually moved
    assert acc > 40.0, acc  # 10-class chance is 10%; measured 62
