"""Opt-in mixture-weight guard (round-4 verdict #8).

The reference learns p UNCONSTRAINED (``functions/tools.py:417-423``)
and the framework keeps that as the default — TUNING_regression.md
shows the faithful consequence: 4/16 regression sweep trials diverge
to NaN at lr_p >= 0.005. FEDAMW_P_GUARD (or make_p_solver's p_guard
argument) opts into projected-SGD stability for users off the tuned
registry without touching reference semantics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedamw_tpu.fedcore.aggregate import (make_p_solver, project_simplex,
                                          resolve_p_guard)


def _project_simplex_np(v):
    """Reference implementation (Held et al. / Duchi et al. 2008)."""
    u = np.sort(v)[::-1]
    css = np.cumsum(u)
    rho = np.nonzero(u + (1.0 - css) / np.arange(1, len(v) + 1) > 0)[0][-1]
    theta = (css[rho] - 1.0) / (rho + 1.0)
    return np.maximum(v - theta, 0.0)


def test_resolve_p_guard(monkeypatch):
    monkeypatch.delenv("FEDAMW_P_GUARD", raising=False)
    assert resolve_p_guard("auto") == "none"  # reference default
    assert resolve_p_guard("simplex") == "simplex"
    assert resolve_p_guard("clip:2.5") == "clip:2.5"
    monkeypatch.setenv("FEDAMW_P_GUARD", "simplex")
    assert resolve_p_guard("auto") == "simplex"
    with pytest.raises(ValueError):
        resolve_p_guard("simplx")
    # a malformed or sign-flipping clip radius fails HERE, naming the
    # env var — not later as a bare float() crash or silent negation
    for bad in ("clip:-1", "clip:abc", "clip:0"):
        with pytest.raises(ValueError, match="FEDAMW_P_GUARD"):
            resolve_p_guard(bad)


def test_resolve_p_guard_rejects_non_finite_radius():
    """ADVICE r5 regression: `radius <= 0` is False for NaN, so
    'clip:nan' used to pass validation and the guard multiplied every
    mixture weight by NaN — the exact divergence the guard exists to
    prevent. 'clip:inf' was a silent no-op guard. Both must fail
    loudly, naming the env var; float-parseable spellings included."""
    for bad in ("clip:nan", "clip:NaN", "clip:inf", "clip:Inf",
                "clip:-inf", "clip:infinity"):
        with pytest.raises(ValueError, match="FEDAMW_P_GUARD"):
            resolve_p_guard(bad)
    # the fix must not over-reject: ordinary finite radii still resolve
    assert resolve_p_guard("clip:1e-3") == "clip:1e-3"


def test_guard_refuses_pallas_kernel(monkeypatch):
    """An active guard + an explicit Pallas p-solver pin must refuse
    loudly: the fused kernel implements the unconstrained reference
    update, and silently running XLA under a pallas pin would poison
    bench provenance (every 'pallas' leg would measure XLA)."""
    monkeypatch.delenv("FEDAMW_P_GUARD", raising=False)
    with pytest.raises(ValueError, match="p_guard"):
        make_p_solver("classification", 48, 16, 1e-2, 0.9,
                      kernel_impl="pallas_interpret", p_guard="simplex")
    monkeypatch.setenv("FEDAMW_P_GUARD", "clip:2")
    with pytest.raises(ValueError, match="p_guard"):
        make_p_solver("classification", 48, 16, 1e-2, 0.9,
                      kernel_impl="pallas_interpret")


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_project_simplex_matches_reference(seed):
    v = np.random.RandomState(seed).randn(17).astype(np.float32) * 3
    got = np.asarray(project_simplex(jnp.asarray(v)))
    np.testing.assert_allclose(got, _project_simplex_np(v), rtol=1e-5,
                               atol=1e-6)
    assert got.min() >= 0 and abs(got.sum() - 1.0) < 1e-5
    # a point already on the simplex is a fixed point
    w = np.abs(v) / np.abs(v).sum()
    np.testing.assert_allclose(
        np.asarray(project_simplex(jnp.asarray(w))), w, rtol=1e-5,
        atol=1e-6)


def test_project_simplex_respects_valid_mask():
    v = jnp.asarray([0.5, 0.9, -0.2, 3.0, 3.0], jnp.float32)
    valid = jnp.asarray([1.0, 1.0, 1.0, 0.0, 0.0])
    got = np.asarray(project_simplex(v, valid))
    # padded entries stay exactly 0; the valid subset carries mass 1
    np.testing.assert_array_equal(got[3:], np.zeros(2))
    np.testing.assert_allclose(got[:3].sum(), 1.0, rtol=1e-6)
    np.testing.assert_allclose(got[:3],
                               _project_simplex_np(np.asarray(v[:3])),
                               rtol=1e-5, atol=1e-6)


def _diverging_problem():
    """A p-solver setting where the unconstrained reference update
    blows up: large-magnitude regression logits + a hot lr_p (the
    TUNING_regression.md cliff, shrunk to test size — the MSE gradient
    is ~A·p, so a step size past 2/λmax(A) doubles p every step)."""
    rng = np.random.RandomState(7)
    n_val, J = 64, 8
    logits = jnp.asarray(rng.randn(n_val, J, 1).astype(np.float32) * 40)
    y = jnp.asarray(rng.randn(n_val).astype(np.float32))
    p0 = jnp.ones(J, jnp.float32) / J
    return n_val, logits, y, p0


@pytest.mark.parametrize("guard", ["simplex", "clip"])
def test_guard_keeps_diverging_trial_finite(guard):
    n_val, logits, y, p0 = _diverging_problem()
    key = jax.random.PRNGKey(0)

    s0, i0 = make_p_solver("regression", n_val, 16, 5e-3, 0.9,
                           p_guard="none")
    p_un = np.asarray(s0(logits, y, p0, i0(p0), key, 30)[0])
    assert not np.all(np.isfinite(p_un)) or np.abs(p_un).max() > 1e6, (
        "precondition: the unguarded trial must diverge for this test "
        f"to mean anything (got max|p|={np.abs(p_un).max():.3g})")

    sg, ig = make_p_solver("regression", n_val, 16, 5e-3, 0.9,
                           p_guard=guard)
    p_g = np.asarray(sg(logits, y, p0, ig(p0), key, 30)[0])
    assert np.all(np.isfinite(p_g))
    if guard == "simplex":
        assert p_g.min() >= 0 and abs(p_g.sum() - 1.0) < 1e-4
    else:
        assert float(np.sqrt((p_g ** 2).sum())) <= 1.0 + 1e-5


def test_guard_keeps_real_sweep_trial_finite(monkeypatch):
    """The regression-sweep divergence cliff (TUNING_regression.md:
    unconstrained p diverges on synthetic_nonlinear at hot lr_p), end
    to end through FedAMW: unguarded it blows up, FEDAMW_P_GUARD=
    simplex keeps every metric finite.

    The original sweep row (lr_p=0.005, lambda_reg=1e-05, nan at R=50,
    reproduced at R=10 when this test shipped) stopped diverging at
    R=10 somewhere before PR 4 (measured: finite through lr_p=0.01,
    nan from lr_p=0.02) — the cliff moved, it did not close. lr_p is
    pinned at 2e-2, past today's edge, so the test keeps exercising
    the divergence the guard exists for; the precondition assert below
    still fails loudly if the cliff ever moves past it again."""
    from fedamw_tpu.algorithms import FedAMW, prepare_setup
    from fedamw_tpu.config import get_parameter
    from fedamw_tpu.data import load_dataset

    params = get_parameter("synthetic_nonlinear")
    rng = np.random.RandomState(7)
    ds = load_dataset("synthetic_nonlinear", 50, 0.01, rng=rng)
    setup = prepare_setup(ds, D=2000, kernel_par=params["kernel_par"],
                          kernel_type=params["kernel_type"], seed=7,
                          rng=rng)
    kw = dict(lr=params["lr"], epoch=2, round=10, lambda_reg=1e-5,
              lr_p=2e-2, seed=0, lr_mode="reference")
    monkeypatch.delenv("FEDAMW_P_GUARD", raising=False)
    tl_un = np.asarray(FedAMW(setup, **kw)["test_loss"])
    assert not np.all(np.isfinite(tl_un)), (
        "precondition: the sweep trial no longer diverges unguarded — "
        "re-pick the operating point so this test still exercises the "
        "cliff")
    monkeypatch.setenv("FEDAMW_P_GUARD", "simplex")
    res_g = FedAMW(setup, **kw)
    for k in ("train_loss", "test_loss"):
        assert np.all(np.isfinite(np.asarray(res_g[k]))), k


def test_guard_off_is_bitexact_reference_path():
    """p_guard='none' must not perturb the default solver (the guard is
    strictly additive)."""
    n_val, J, C = 48, 5, 2
    rng = np.random.RandomState(3)
    logits = jnp.asarray(rng.randn(n_val, J, C).astype(np.float32))
    y = jnp.asarray(rng.randint(0, C, n_val).astype(np.int32))
    p0 = jnp.ones(J, jnp.float32) / J
    key = jax.random.PRNGKey(5)
    s1, i1 = make_p_solver("classification", n_val, 16, 1e-2, 0.9)
    s2, i2 = make_p_solver("classification", n_val, 16, 1e-2, 0.9,
                           p_guard="none")
    np.testing.assert_array_equal(
        np.asarray(s1(logits, y, p0, i1(p0), key, 3)[0]),
        np.asarray(s2(logits, y, p0, i2(p0), key, 3)[0]))


def test_guard_env_reaches_fedamw_e2e(monkeypatch):
    """FEDAMW_P_GUARD threads through the cached trainer factories into
    a full FedAMW run (the env snapshot is part of the cache key, so a
    guarded program is never reused unguarded and vice versa)."""
    from fedamw_tpu.algorithms import FedAMW, prepare_setup
    from fedamw_tpu.data import load_dataset

    ds = load_dataset("digits", num_partitions=5, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=2,
                          rng=np.random.RandomState(2))
    kw = dict(lr=0.5, epoch=1, round=3, lambda_reg=1e-4, lr_p=1e-2,
              seed=0, lr_mode="constant", return_state=True)
    monkeypatch.delenv("FEDAMW_P_GUARD", raising=False)
    res_un = FedAMW(setup, **kw)
    monkeypatch.setenv("FEDAMW_P_GUARD", "simplex")
    res_g = FedAMW(setup, **kw)
    p_g = np.asarray(res_g["p"])
    assert p_g.min() >= -1e-6 and abs(p_g.sum() - 1.0) < 1e-4
    # the guarded run took a genuinely different trajectory than the
    # unconstrained default (if these match, the env never reached the
    # solver — e.g. a stale cached program)
    assert not np.allclose(p_g, np.asarray(res_un["p"]))
