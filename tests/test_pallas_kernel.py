"""Fused Pallas epoch kernel vs the XLA scan kernel — must agree.

Runs the Pallas kernel in interpreter mode (no TPU needed) against the
autodiff-based XLA kernel on identical inputs: same shuffles, same
4-way penalty combinations, masked partial batches, empty clients,
both tasks, and under vmap over the client axis. The hand-derived
gradients in pallas_kernel.py are only correct if these match tightly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedamw_tpu.fedcore.client import make_client_round, make_local_update

N, D, C, B = 300, 256, 3, 32


def _data(task, seed=0):
    rng = np.random.RandomState(seed)
    X = jnp.asarray(rng.randn(N, D).astype(np.float32))
    if task == "classification":
        y = jnp.asarray(rng.randint(0, C, N).astype(np.int32))
    else:
        y = jnp.asarray(rng.randn(N).astype(np.float32))
    w0 = {"w": jnp.asarray(rng.randn(C, D).astype(np.float32) * 0.1)}
    return X, y, w0


def _client(n, seed=1):
    rng = np.random.RandomState(seed)
    idx = jnp.asarray(rng.choice(N, size=max(n, 1), replace=False)
                      .astype(np.int32))
    n_max = 64
    pad = n_max - idx.shape[0]
    idx = jnp.concatenate([idx, jnp.zeros(pad, jnp.int32)])
    mask = jnp.concatenate([jnp.ones(max(n, 1), jnp.float32) * (n > 0),
                            jnp.zeros(pad, jnp.float32)])
    return idx, mask, n_max


@pytest.mark.parametrize("impl", ["pallas_interpret",
                                  "pallas_col_interpret"])
@pytest.mark.parametrize("task", ["classification", "regression"])
@pytest.mark.parametrize("mu,lam", [(0.0, 0.0), (0.05, 0.0),
                                    (0.0, 0.01), (0.05, 0.01)])
def test_pallas_matches_xla_single_client(task, mu, lam, impl):
    X, y, w0 = _data(task)
    idx, mask, n_max = _client(50)
    key = jax.random.PRNGKey(7)
    args = (X, y, idx, mask, key, jnp.float32(0.1), jnp.float32(mu),
            jnp.float32(lam))

    # the XLA kernel needs a real apply_fn; the pallas one derives it
    from fedamw_tpu.models import linear_model

    lu_x = make_local_update(linear_model().apply, task, 2, B, n_max,
                             kernel_impl="xla")
    lu_p = make_local_update(None, task, 2, B, n_max,
                             kernel_impl=impl)
    wx, lx, ax = lu_x(w0, *args)
    wp, lp, ap = lu_p(w0, *args)
    np.testing.assert_allclose(np.asarray(wp["w"]), np.asarray(wx["w"]),
                               atol=2e-5, rtol=1e-5)
    np.testing.assert_allclose(float(lp), float(lx), atol=1e-4)
    np.testing.assert_allclose(float(ap), float(ax), atol=1e-3)


def test_pallas_empty_client_is_inert():
    X, y, w0 = _data("classification")
    idx, mask, n_max = _client(0)
    lu_p = make_local_update(None, "classification", 2, B, n_max,
                             kernel_impl="pallas_interpret")
    wp, lp, ap = lu_p(w0, X, y, idx, mask, jax.random.PRNGKey(0),
                      jnp.float32(0.1), jnp.float32(0.0), jnp.float32(0.0))
    np.testing.assert_array_equal(np.asarray(wp["w"]), np.asarray(w0["w"]))
    assert float(lp) == 0.0


@pytest.mark.parametrize("impl", ["pallas_interpret",
                                  "pallas_col_interpret"])
def test_pallas_matches_xla_vmapped_round(impl):
    from fedamw_tpu.models import linear_model

    task = "classification"
    X, y, w0 = _data(task)
    J, n_max = 6, 64
    rng = np.random.RandomState(3)
    idx = jnp.asarray(rng.randint(0, N, size=(J, n_max)).astype(np.int32))
    mask = jnp.asarray((rng.rand(J, n_max) < 0.8).astype(np.float32))
    keys = jax.random.split(jax.random.PRNGKey(11), J)
    args = (X, y, idx, mask, keys, jnp.float32(0.2), jnp.float32(0.01),
            jnp.float32(0.001))

    rf_x = jax.jit(make_client_round(linear_model().apply, task, 2, B,
                                     n_max, kernel_impl="xla"))
    rf_p = jax.jit(make_client_round(linear_model().apply, task, 2, B,
                                     n_max, kernel_impl=impl))
    sx, lx, ax = rf_x(w0, *args)
    sp, lp, ap = rf_p(w0, *args)
    np.testing.assert_allclose(np.asarray(sp["w"]), np.asarray(sx["w"]),
                               atol=2e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(lp), np.asarray(lx), atol=1e-4)
    np.testing.assert_allclose(np.asarray(ap), np.asarray(ax), atol=1e-3)
