"""Fused Pallas p-solver pinned against the XLA solver.

Same shuffle stream (both paths draw batches via ``epoch_batches`` from
the same key), same masked-mean loss, same SGD(momentum) recurrence —
so the two implementations must agree to float tolerance on every
output, including the carried optax momentum state.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedamw_tpu.fedcore.aggregate import make_p_solver, resolve_psolver_impl


def _mk(task, n_val, J, C, seed=0):
    rng = np.random.RandomState(seed)
    logits = jnp.asarray(rng.randn(n_val, J, C).astype(np.float32))
    if task == "classification":
        y = jnp.asarray(rng.randint(0, C, n_val).astype(np.int32))
    else:
        y = jnp.asarray(rng.randn(n_val).astype(np.float32))
    p = jnp.asarray(rng.rand(J).astype(np.float32))
    p = p / p.sum()
    return logits, y, p


@pytest.mark.parametrize("impl", ["pallas_interpret",
                                  "pallas_nt_interpret"])
@pytest.mark.parametrize("task,C", [("classification", 3),
                                    ("classification", 2),
                                    ("regression", 1)])
@pytest.mark.parametrize("momentum", [0.9, 0.0])
def test_pallas_solver_matches_xla(task, C, momentum, impl):
    n_val, J, B = 53, 7, 16  # last batch partial (53 = 3*16 + 5)
    logits, y, p0 = _mk(task, n_val, J, C)
    key = jax.random.PRNGKey(42)

    sx, ix = make_p_solver(task, n_val, B, 5e-3, momentum,
                           kernel_impl="xla")
    sp, ip = make_p_solver(task, n_val, B, 5e-3, momentum,
                           kernel_impl=impl)
    px, ox, lx, ax = sx(logits, y, p0, ix(p0), key, 3)
    pp, op, lp, ap = sp(logits, y, p0, ip(p0), key, 3)

    np.testing.assert_allclose(np.asarray(pp), np.asarray(px),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(float(lp), float(lx), rtol=2e-5)
    np.testing.assert_allclose(float(ap), float(ax), rtol=2e-5)
    # momentum state round-trips through the kernel in optax form
    for a, b in zip(jax.tree_util.tree_leaves(op),
                    jax.tree_util.tree_leaves(ox)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_pallas_solver_client_valid_freezes_padding():
    n_val, J, C, B = 48, 6, 2, 16
    logits, y, p0 = _mk("classification", n_val, J, C, seed=3)
    cv = jnp.asarray([1.0, 1.0, 1.0, 1.0, 0.0, 0.0])
    p0 = p0 * cv / jnp.sum(p0 * cv)  # padded clients start at exactly 0

    sp, ip = make_p_solver("classification", n_val, B, 1e-2, 0.9,
                           kernel_impl="pallas_interpret")
    pp, _, _, _ = sp(logits, y, p0, ip(p0), jax.random.PRNGKey(0), 4,
                     client_valid=cv)
    np.testing.assert_array_equal(np.asarray(pp)[4:], np.zeros(2))
    assert not np.allclose(np.asarray(pp)[:4], np.asarray(p0)[:4])

    sx, ix = make_p_solver("classification", n_val, B, 1e-2, 0.9,
                           kernel_impl="xla")
    px, _, _, _ = sx(logits, y, p0, ix(p0), jax.random.PRNGKey(0), 4,
                     client_valid=cv)
    np.testing.assert_allclose(np.asarray(pp), np.asarray(px),
                               rtol=2e-5, atol=2e-6)


def test_pallas_solver_huge_buffer_falls_back(monkeypatch):
    """Past the epoch-gather byte budget the pallas build must route to
    the XLA per-step path instead of materializing the buffer."""
    import fedamw_tpu.fedcore.client as client_mod

    monkeypatch.setattr(client_mod, "EPOCH_GATHER_BYTES_LIMIT", 1)
    n_val, J, C, B = 48, 4, 2, 16
    logits, y, p0 = _mk("classification", n_val, J, C, seed=1)
    sp, ip = make_p_solver("classification", n_val, B, 1e-2, 0.9,
                           kernel_impl="pallas_interpret")
    sx, ix = make_p_solver("classification", n_val, B, 1e-2, 0.9,
                           kernel_impl="xla")
    pp = sp(logits, y, p0, ip(p0), jax.random.PRNGKey(7), 2)[0]
    px = sx(logits, y, p0, ix(p0), jax.random.PRNGKey(7), 2)[0]
    # with the fallback active the results are the XLA path's exactly
    np.testing.assert_array_equal(np.asarray(pp), np.asarray(px))


def test_resolve_psolver_impl(monkeypatch):
    assert resolve_psolver_impl("xla") == "xla"
    assert resolve_psolver_impl("pallas") == "pallas"
    monkeypatch.setenv("FEDAMW_PSOLVER", "pallas")
    assert resolve_psolver_impl("auto") == "pallas"
    monkeypatch.setenv("FEDAMW_PSOLVER", "pallas_nt")
    assert resolve_psolver_impl("auto") == "pallas_nt"
    monkeypatch.setenv("FEDAMW_PSOLVER", "xla")
    assert resolve_psolver_impl("auto") == "xla"
    monkeypatch.delenv("FEDAMW_PSOLVER")
    # with no override, auto resolves to xla on EVERY backend (round-5
    # revert of the round-4 pallas-on-TPU flip — the hardware evidence
    # for the kernel was a red log; see resolve_psolver_impl)
    assert resolve_psolver_impl("auto") == "xla"


def test_fedamw_e2e_pallas_psolver_matches_xla(monkeypatch):
    """End-to-end FedAMW with the env-selected Pallas p-solver must
    match the XLA run (and the trainer cache must not leak programs
    across env settings — the env snapshot is part of the cache key)."""
    from fedamw_tpu.algorithms import FedAMW, prepare_setup
    from fedamw_tpu.data import load_dataset

    ds = load_dataset("digits", num_partitions=5, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=2,
                          rng=np.random.RandomState(2))
    kw = dict(lr=0.5, epoch=1, round=2, lambda_reg=1e-4, lr_p=1e-3,
              seed=0, lr_mode="constant")
    monkeypatch.setenv("FEDAMW_PSOLVER", "xla")
    res_x = FedAMW(setup, **kw)
    monkeypatch.setenv("FEDAMW_PSOLVER", "pallas_interpret")
    res_p = FedAMW(setup, **kw)
    np.testing.assert_allclose(res_p["test_acc"], res_x["test_acc"],
                               atol=1e-3)
    np.testing.assert_allclose(res_p["test_loss"], res_x["test_loss"],
                               atol=1e-4)
