"""Hardware validation for the fused Pallas kernels.

These tests compile and run the Mosaic kernels on a REAL TPU backend
and pin them against the XLA implementations. They are skipped in the
default test environment (conftest.py forces an 8-device virtual CPU
mesh); run them on a TPU-attached box with:

    FEDAMW_TEST_PLATFORM=tpu python -m pytest tests/test_pallas_tpu.py -q

Interpret-mode numerical parity lives in test_pallas_kernel.py /
test_pallas_psolver.py; this file answers the remaining question —
"does Mosaic actually lower and produce the same numbers on hardware?"
(Round-2 history: the epoch kernel passed interpret tests but failed to
lower on a v5e until the block layouts and reductions were reshaped;
see PERFORMANCE.md.)
"""

import jax
import numpy as np
import pytest

from fedamw_tpu.fedcore.client import _TPU_BACKENDS

pytestmark = pytest.mark.skipif(
    jax.default_backend() not in _TPU_BACKENDS,
    reason="needs a real TPU backend (FEDAMW_TEST_PLATFORM=tpu)",
)


# File order is window-priority order: a short tunnel window that dies
# mid-tier still certifies the tests that ran. The p-solver comparisons
# lead — they are the round-5 flip-back gate (the auto default reverted
# to xla on a red round-4 log); the epoch-kernel lowering checks and
# the e2e run follow.


@pytest.mark.parametrize("impl", ["pallas", "pallas_nt"])
@pytest.mark.parametrize("task,C", [("classification", 2),
                                    ("regression", 1)])
def test_psolver_kernel_lowers_and_matches_xla(task, C, impl):
    from fedamw_tpu.fedcore.aggregate import make_p_solver

    n_val, J, B = 253, 64, 16
    rng = np.random.RandomState(1)
    import jax.numpy as jnp

    logits = jnp.asarray(rng.randn(n_val, J, C).astype(np.float32))
    if task == "classification":
        y = jnp.asarray(rng.randint(0, C, n_val).astype(np.int32))
    else:
        y = jnp.asarray(rng.randn(n_val).astype(np.float32))
    p0 = jnp.ones(J, jnp.float32) / J
    key = jax.random.PRNGKey(3)

    sx, ix = make_p_solver(task, n_val, B, 5e-3, 0.9, kernel_impl="xla")
    sp, ip = make_p_solver(task, n_val, B, 5e-3, 0.9, kernel_impl=impl)
    # Precision-pinned comparison (round-4 advisor): run BOTH arms at
    # matmul precision HIGHEST and require the divergence to CLOSE.
    # All the kernel's contractions carry precision=None, which
    # lax.dot_general canonicalizes from default_matmul_precision at
    # trace time — inside the Mosaic kernel body too — so the context
    # manager pins both programs to f32-grade passes. At HIGHEST the
    # two arms compute the same math with the same arithmetic, so a
    # residual gap is a kernel BUG, not rounding: this is the gate the
    # round-4 loosened rtol=2e-2/atol=2e-3 check could not provide
    # (and what the red round-4 log, max|diff| 4.6e-4 at default
    # precision across the four parametrizations, could not settle).
    px = np.asarray(sx(logits, y, p0, ix(p0), key, 3)[0])
    pp = np.asarray(sp(logits, y, p0, ip(p0), key, 3)[0])
    with jax.default_matmul_precision("highest"):
        px_hi = np.asarray(sx(logits, y, p0, ix(p0), key, 3)[0])
        pp_hi = np.asarray(sp(logits, y, p0, ip(p0), key, 3)[0])
    np.testing.assert_allclose(pp_hi, px_hi, rtol=1e-4, atol=1e-5)
    # Secondary, default-precision envelope. The control gap comes
    # from the TRUSTED arm only — the XLA program's own
    # default-vs-HIGHEST drift measures the bf16-tiling rounding scale
    # of these shapes (deriving it from the Pallas arm too would let a
    # default-path-only kernel bug license its own drift via an
    # inflated |pp - pp_hi|). Floored at 2e-3 (≈4x the worst round-4
    # measured drift, 4.6e-4) so an f32-lowered XLA control on these
    # tiny dims cannot collapse the envelope to ~0 and red-gate pure
    # rounding differences. Kernel-correctness lives in the HIGHEST
    # gate above; this only catches gross default-path breakage.
    gap = float(np.max(np.abs(px - px_hi)))
    err = float(np.max(np.abs(pp - px)))
    assert err <= max(4.0 * gap, 2e-3), (
        f"default-precision drift {err:.3e} exceeds envelope "
        f"(4x XLA control gap {gap:.3e}, floor 2e-3)"
    )


@pytest.mark.parametrize("layout", ["row", "col"])
def test_epoch_kernel_lowers_and_matches_interpret(layout):
    """Both layouts: "row" is the default; "col" is the transpose-free
    fallback for the row kernel's in-kernel w.T/dz.T relayouts (the one
    audited residual Mosaic risk) — if row fails to lower here, col is
    the drop-in (FEDAMW_KERNEL=pallas_col)."""
    import jax.numpy as jnp

    from fedamw_tpu.fedcore.pallas_kernel import make_pallas_epoch

    C, D, B, S = 2, 2000, 32, 7
    rng = np.random.RandomState(0)
    epoch = make_pallas_epoch("classification", C, D, B, S,
                              layout=layout)
    w0 = jnp.asarray(rng.randn(C, D).astype(np.float32) * 0.01)
    Xe = jnp.asarray(rng.randn(S, B, D).astype(np.float32))
    ye = jnp.asarray(rng.randint(0, C, (S, B)).astype(np.int32))
    bv = jnp.ones((S, B), jnp.float32)
    bv = bv.at[-1, 20:].set(0.0)  # partial last batch
    scal = jnp.asarray([0.1, 0.01, 0.001], jnp.float32)
    w, met = jax.jit(epoch)(w0, w0, Xe, ye, bv, scal)
    w, met = np.asarray(w), np.asarray(met)

    ref = make_pallas_epoch("classification", C, D, B, S, interpret=True,
                            layout=layout)
    w_i, met_i = jax.jit(ref)(w0, w0, Xe, ye, bv, scal)
    np.testing.assert_allclose(w, np.asarray(w_i), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(met, np.asarray(met_i), rtol=1e-4)


def test_fedamw_e2e_with_pallas_kernels(monkeypatch):
    """Full FedAMW run with both fused kernels selected via env."""
    from fedamw_tpu.algorithms import FedAMW, prepare_setup
    from fedamw_tpu.data import load_dataset

    ds = load_dataset("digits", num_partitions=8, alpha=0.5)
    setup = prepare_setup(ds, kernel_type="linear", seed=4,
                          rng=np.random.RandomState(4))
    kw = dict(lr=0.5, epoch=1, round=3, lambda_reg=1e-4, lr_p=1e-3,
              seed=0, lr_mode="constant")
    monkeypatch.setenv("FEDAMW_KERNEL", "xla")
    monkeypatch.setenv("FEDAMW_PSOLVER", "xla")
    res_x = FedAMW(setup, **kw)
    monkeypatch.setenv("FEDAMW_KERNEL", "pallas")
    monkeypatch.setenv("FEDAMW_PSOLVER", "pallas")
    res_p = FedAMW(setup, **kw)
    np.testing.assert_allclose(np.asarray(res_p["test_acc"]),
                               np.asarray(res_x["test_acc"]), atol=0.5)


def test_auto_defaults_on_tpu_backend(monkeypatch):
    """Round-5 policy, asserted on the real backend: with no env
    overrides BOTH kernels auto-resolve to XLA — the p-solver's brief
    round-4 pallas-on-TPU default was reverted because its only
    committed hardware log was red (see resolve_psolver_impl). The
    Pallas kernels stay explicit opt-ins until a window lands green
    hardware parity plus an isolated mixed-pair bench win."""
    from fedamw_tpu.fedcore.aggregate import resolve_psolver_impl
    from fedamw_tpu.fedcore.client import resolve_kernel_impl

    monkeypatch.delenv("FEDAMW_PSOLVER", raising=False)
    monkeypatch.delenv("FEDAMW_KERNEL", raising=False)
    assert resolve_psolver_impl("auto") == "xla"
    linear_params = {"w": np.zeros((2, 8), np.float32)}
    assert resolve_kernel_impl("auto", linear_params, True) == "xla"
    # explicit pallas request still honored for the epoch kernel
    assert resolve_kernel_impl("pallas", linear_params, True) == "pallas"
