"""Partial client participation (extension — the reference trains every
client every round, ``tools.py:340``).

Per round a Bernoulli mask picks the participating clients; aggregation
weights renormalize over them (subset carries the full original mass);
an all-absent round leaves the global model unchanged; FedAMW rejects
the option (its learned mixture weights assume full participation).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from fedamw_tpu.algorithms import FedAMW, FedAvg, FedNova, prepare_setup
from fedamw_tpu.backends import torch_ref
from fedamw_tpu.data import load_dataset
from fedamw_tpu.fedcore import participation_weights


def test_participation_weights_preserve_mass():
    w = jnp.asarray([0.5, 0.3, 0.2])
    part = jnp.asarray([1.0, 0.0, 1.0])
    out = np.asarray(participation_weights(w, part))
    assert out[1] == 0.0
    np.testing.assert_allclose(out.sum(), 1.0, atol=1e-6)
    # relative weights among participants preserved
    np.testing.assert_allclose(out[0] / out[2], 0.5 / 0.2, rtol=1e-5)


def test_participation_weights_all_absent_is_zero():
    w = jnp.asarray([0.6, 0.4])
    out = np.asarray(participation_weights(w, jnp.zeros(2)))
    np.testing.assert_array_equal(out, np.zeros(2))


@pytest.fixture(scope="module")
def setup8():
    ds = load_dataset("digits", num_partitions=8, alpha=0.5)
    return prepare_setup(ds, kernel_type="linear", seed=3,
                         rng=np.random.RandomState(3))


def test_full_participation_matches_default(setup8):
    kw = dict(lr=0.5, epoch=1, round=3, seed=0, lr_mode="constant")
    a = FedAvg(setup8, **kw)
    b = FedAvg(setup8, participation=1.0, **kw)
    np.testing.assert_array_equal(a["test_acc"], b["test_acc"])


def test_partial_participation_runs_and_differs(setup8):
    kw = dict(lr=0.5, epoch=1, round=4, seed=0, lr_mode="constant")
    full = FedAvg(setup8, **kw)
    half = FedAvg(setup8, participation=0.5, **kw)
    assert np.all(np.isfinite(half["test_loss"]))
    assert not np.allclose(full["train_loss"], half["train_loss"])
    assert half["test_acc"][-1] > 30.0  # still learns


def test_fednova_partial_participation(setup8):
    """FedNova composes with participation through the shared round
    skeleton: the tau-scaled weights renormalize over the participating
    subset (mass-preserving, like FedAvg's)."""
    kw = dict(lr=0.5, epoch=1, round=4, seed=0, lr_mode="constant")
    full = FedNova(setup8, **kw)
    half = FedNova(setup8, participation=0.5, **kw)
    assert np.all(np.isfinite(np.asarray(half["test_loss"])))
    assert not np.allclose(full["train_loss"], half["train_loss"])
    assert half["test_acc"][-1] > 30.0


def test_fedamw_accepts_partial_participation(setup8):
    """FedAMW used to hard-reject participation<1; the fault-plane PR
    lifted that — the p-solver runs masked over the present clients
    (tests/test_faults.py pins the masked-p semantics in depth)."""
    kw = dict(lr=0.5, epoch=1, round=3, seed=0, lr_mode="constant",
              lambda_reg=1e-4, lr_p=1e-3)
    full = FedAMW(setup8, **kw)
    half = FedAMW(setup8, participation=0.5, **kw)
    assert np.all(np.isfinite(half["test_loss"]))
    assert not np.allclose(full["train_loss"], half["train_loss"])


def test_torch_fedamw_rejects_partial_participation():
    ds = load_dataset("digits", num_partitions=4, alpha=0.5)
    s = torch_ref.prepare_setup(ds, kernel_type="linear", seed=3,
                                rng=np.random.RandomState(3))
    with pytest.raises(ValueError, match="full participation"):
        torch_ref.FedAMW(s, participation=0.5, round=2)


@pytest.mark.parametrize("bad", [0.0, -0.5, 1.5])
def test_participation_out_of_range_rejected(setup8, bad):
    with pytest.raises(ValueError, match="participation"):
        FedAvg(setup8, participation=bad, round=2)


def test_torch_backend_participation():
    ds = load_dataset("digits", num_partitions=6, alpha=0.5)
    s = torch_ref.prepare_setup(ds, kernel_type="linear", seed=3,
                                rng=np.random.RandomState(3))
    kw = dict(lr=0.5, epoch=1, round=3, seed=0, lr_mode="constant")
    full = torch_ref.FedAvg(s, **kw)
    half = torch_ref.FedAvg(s, participation=0.5, **kw)
    assert np.all(np.isfinite(half["test_loss"]))
    assert not np.allclose(full["train_loss"], half["train_loss"])


def test_torch_empty_client_cannot_wipe_model(monkeypatch):
    """A Bernoulli round that selects ONLY a zero-size client must be a
    no-op, not an all-zero weighted average that erases the global model
    (the empty client's aggregation weight is 0; the gate must check
    weight mass, not participant headcount)."""
    from fedamw_tpu.data.datasets import FederatedDataset

    rng = np.random.RandomState(0)
    X = rng.randn(60, 5).astype(np.float32)
    y = (rng.rand(60) > 0.5).astype(np.int32)
    parts = [np.arange(0, 30), np.arange(30, 60),
             np.array([], dtype=np.int64)]  # client 2 is empty
    ds = FederatedDataset(
        name="toy", task_type="classification", num_classes=2, d=5,
        X_train=X, y_train=y, X_test=X[:20], y_test=y[:20], parts=parts,
        source="synthetic")
    s = torch_ref.prepare_setup(ds, kernel_type="linear", seed=0,
                                rng=np.random.RandomState(0))
    assert float(s.p_fixed[2]) == 0.0
    # Force every round to "select" only the empty client: with the
    # valid-mask fix the mask is all-zero -> no-op rounds; without it
    # the first aggregate would zero the model and accuracy would pin
    # at a constant-argmax value with zero train signal.
    import torch as _torch
    real_rand = _torch.rand

    def fake_rand(*sizes, **kw):
        if sizes == (3,):  # the participation mask draw
            return _torch.tensor([1.0, 1.0, 0.0])
        return real_rand(*sizes, **kw)

    monkeypatch.setattr(_torch, "rand", fake_rand)
    res = torch_ref.FedAvg(s, lr=0.5, epoch=1, round=3, seed=0,
                           lr_mode="constant", participation=0.5)
    assert np.all(res["train_loss"] == 0.0)  # no participants -> no loss
    assert np.all(np.isfinite(res["test_loss"]))
    # the model was never replaced by the all-zero average: a zero
    # weight matrix has exactly 50% accuracy on argmax ties; the
    # Xavier-initialized model evaluates identically every round and
    # its loss must stay at the initial value, not collapse to ln(2)
    # of a zeroed model producing uniform logits of exactly 0
    first = res["test_loss"][0]
    assert np.allclose(res["test_loss"], first)


@pytest.mark.parametrize("backend", ["jax", "torch"])
def test_oneshot_algorithms_reject_partial_participation(backend, setup8):
    """One-shot algorithms must refuse participation<1 loudly, not
    swallow it via **_ and silently run full participation."""
    if backend == "jax":
        from fedamw_tpu.algorithms import Centralized, Distributed
        from fedamw_tpu.algorithms import FedAMW_OneShot as OS
        s = setup8
    else:
        ds = load_dataset("digits", num_partitions=4, alpha=0.5)
        s = torch_ref.prepare_setup(ds, kernel_type="linear", seed=3,
                                    rng=np.random.RandomState(3))
        Centralized, Distributed, OS = (torch_ref.Centralized,
                                        torch_ref.Distributed,
                                        torch_ref.FedAMW_OneShot)
    for fn in (Centralized, Distributed, OS):
        with pytest.raises(ValueError, match="full participation"):
            fn(s, epoch=1, participation=0.5)


@pytest.mark.parametrize("backend", ["jax", "torch"])
def test_sequential_rejects_partial_participation(backend, setup8):
    """sequential-compat + partial participation have no defined joint
    semantics (an absent client has no place in the contamination
    chain); both backends must refuse the combination identically."""
    if backend == "jax":
        fn, s = FedAvg, setup8
    else:
        ds = load_dataset("digits", num_partitions=4, alpha=0.5)
        s = torch_ref.prepare_setup(ds, kernel_type="linear", seed=3,
                                    rng=np.random.RandomState(3))
        fn = torch_ref.FedAvg
    with pytest.raises(ValueError, match="sequential"):
        fn(s, round=2, sequential=True, participation=0.5)
