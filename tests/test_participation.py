"""Partial client participation (extension — the reference trains every
client every round, ``tools.py:340``).

Per round a Bernoulli mask picks the participating clients; aggregation
weights renormalize over them (subset carries the full original mass);
an all-absent round leaves the global model unchanged; FedAMW rejects
the option (its learned mixture weights assume full participation).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from fedamw_tpu.algorithms import FedAMW, FedAvg, prepare_setup
from fedamw_tpu.backends import torch_ref
from fedamw_tpu.data import load_dataset
from fedamw_tpu.fedcore import participation_weights


def test_participation_weights_preserve_mass():
    w = jnp.asarray([0.5, 0.3, 0.2])
    part = jnp.asarray([1.0, 0.0, 1.0])
    out = np.asarray(participation_weights(w, part))
    assert out[1] == 0.0
    np.testing.assert_allclose(out.sum(), 1.0, atol=1e-6)
    # relative weights among participants preserved
    np.testing.assert_allclose(out[0] / out[2], 0.5 / 0.2, rtol=1e-5)


def test_participation_weights_all_absent_is_zero():
    w = jnp.asarray([0.6, 0.4])
    out = np.asarray(participation_weights(w, jnp.zeros(2)))
    np.testing.assert_array_equal(out, np.zeros(2))


@pytest.fixture(scope="module")
def setup8():
    ds = load_dataset("digits", num_partitions=8, alpha=0.5)
    return prepare_setup(ds, kernel_type="linear", seed=3,
                         rng=np.random.RandomState(3))


def test_full_participation_matches_default(setup8):
    kw = dict(lr=0.5, epoch=1, round=3, seed=0, lr_mode="constant")
    a = FedAvg(setup8, **kw)
    b = FedAvg(setup8, participation=1.0, **kw)
    np.testing.assert_array_equal(a["test_acc"], b["test_acc"])


def test_partial_participation_runs_and_differs(setup8):
    kw = dict(lr=0.5, epoch=1, round=4, seed=0, lr_mode="constant")
    full = FedAvg(setup8, **kw)
    half = FedAvg(setup8, participation=0.5, **kw)
    assert np.all(np.isfinite(half["test_loss"]))
    assert not np.allclose(full["train_loss"], half["train_loss"])
    assert half["test_acc"][-1] > 30.0  # still learns


def test_fedamw_rejects_partial_participation(setup8):
    with pytest.raises(ValueError, match="full participation"):
        FedAMW(setup8, participation=0.5, round=2)


def test_torch_fedamw_rejects_partial_participation():
    ds = load_dataset("digits", num_partitions=4, alpha=0.5)
    s = torch_ref.prepare_setup(ds, kernel_type="linear", seed=3,
                                rng=np.random.RandomState(3))
    with pytest.raises(ValueError, match="full participation"):
        torch_ref.FedAMW(s, participation=0.5, round=2)


@pytest.mark.parametrize("bad", [0.0, -0.5, 1.5])
def test_participation_out_of_range_rejected(setup8, bad):
    with pytest.raises(ValueError, match="participation"):
        FedAvg(setup8, participation=bad, round=2)


def test_torch_backend_participation():
    ds = load_dataset("digits", num_partitions=6, alpha=0.5)
    s = torch_ref.prepare_setup(ds, kernel_type="linear", seed=3,
                                rng=np.random.RandomState(3))
    kw = dict(lr=0.5, epoch=1, round=3, seed=0, lr_mode="constant")
    full = torch_ref.FedAvg(s, **kw)
    half = torch_ref.FedAvg(s, participation=0.5, **kw)
    assert np.all(np.isfinite(half["test_loss"]))
    assert not np.allclose(full["train_loss"], half["train_loss"])
