import numpy as np
import pytest

from fedamw_tpu.data import dirichlet_partition, uniform_partition


def _reference_transcription(labels, n_parts, alpha, seed):
    """Direct transcription of the reference partitioner using the global
    RNG (``functions/utils.py:314-349``), used only to pin exact parity of
    our RandomState-based implementation."""
    labels = np.asarray(labels)
    K = len(set(labels.tolist()))
    N = len(labels)
    np.random.seed(seed)
    min_size = 0
    while min_size < 10:
        idx_batch = [[] for _ in range(n_parts)]
        for k in range(K):
            idx_k = np.where(labels == k)[0]
            np.random.shuffle(idx_k)
            proportions = np.random.dirichlet(np.repeat(alpha, n_parts))
            proportions = np.array(
                [p * (len(idx_j) < N / n_parts) for p, idx_j in zip(proportions, idx_batch)]
            ) + 1 / len(idx_k)
            proportions = proportions / proportions.sum()
            proportions = (np.cumsum(proportions) * len(idx_k)).astype(int)[:-1]
            idx_batch = [
                idx_j + idx.tolist()
                for idx_j, idx in zip(idx_batch, np.split(idx_k, proportions))
            ]
            min_size = min([len(idx_j) for idx_j in idx_batch])
    for j in range(n_parts):
        np.random.shuffle(idx_batch[j])
    return idx_batch


@pytest.fixture
def labels():
    rng = np.random.RandomState(3)
    return rng.randint(0, 6, size=2000)


def test_exact_cover(labels):
    parts, _ = dirichlet_partition(labels, 8, 0.1, seed=2020)
    all_idx = np.concatenate(parts)
    assert len(all_idx) == len(labels)
    assert len(np.unique(all_idx)) == len(labels)


def test_min_size_honored(labels):
    parts, _ = dirichlet_partition(labels, 8, 0.01, seed=2020)
    assert min(len(p) for p in parts) >= 10


def test_deterministic(labels):
    a, _ = dirichlet_partition(labels, 8, 0.1, seed=2020)
    b, _ = dirichlet_partition(labels, 8, 0.1, seed=2020)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


@pytest.mark.parametrize("alpha", [0.01, 0.1, 1.0])
def test_bitwise_parity_with_reference_rng(labels, alpha):
    ours, _ = dirichlet_partition(labels, 8, alpha, seed=2020)
    ref = _reference_transcription(labels, 8, alpha, seed=2020)
    assert len(ours) == len(ref)
    for o, r in zip(ours, ref):
        np.testing.assert_array_equal(o, np.asarray(r))


def test_class_counts(labels):
    parts, counts = dirichlet_partition(labels, 4, 0.5, seed=2020)
    for j, p in enumerate(parts):
        assert sum(counts[j].values()) == len(p)


def test_uniform_partition_covers():
    parts = uniform_partition(103, 5, np.random.RandomState(0))
    idx = np.concatenate(parts)
    assert sorted(idx.tolist()) == list(range(103))
    assert max(len(p) for p in parts) - min(len(p) for p in parts) <= 1


def test_min_size_zero_single_pass():
    # scale configs: 2 classes x many clients can never satisfy min 10;
    # min_size=0 must run exactly one assignment pass and return
    y = np.random.RandomState(0).randint(0, 2, 5000)
    parts, _ = dirichlet_partition(y, 128, 0.1, seed=2020, min_size=0)
    assert len(parts) == 128
    assert sum(len(p) for p in parts) == 5000


def test_bounded_retries_raise():
    y = np.random.RandomState(0).randint(0, 2, 5000)
    with pytest.raises(RuntimeError, match="min_size"):
        dirichlet_partition(y, 128, 0.1, seed=2020, min_size=10, max_retries=3)


def test_skew_increases_as_alpha_shrinks(labels):
    def skew(alpha):
        parts, _ = dirichlet_partition(labels, 8, alpha, seed=2020)
        sizes = np.array([len(p) for p in parts], float)
        return sizes.std() / sizes.mean()

    assert skew(0.01) > skew(100.0)
