"""The ACTUAL reference code as a read-only parity oracle (fast tier).

``oracle_parity.py`` is the full 10-seed harness behind PARITY.md §1;
this test pins the capability in CI at a small operating point: import
``/root/reference/functions/tools.py`` (never copied), feed it the SAME
RFF-mapped tensors as the repo's torch backend, and require agreement.
Skips when the reference checkout is absent (other machines).
"""

import os

import numpy as np
import pytest

import oracle_parity

pytestmark = pytest.mark.skipif(
    not os.path.isdir(oracle_parity.REFERENCE_ROOT),
    reason="reference checkout not mounted",
)

ROUNDS = 8
SEED = 100


@pytest.fixture(scope="module")
def arms():
    # smaller than the PARITY.md anchor so the sequential oracle loop
    # stays test-sized; same digits/alpha=0.5 regime where FedAvg learns
    anchor = dict(oracle_parity.ANCHOR, num_partitions=8, D=128)
    setup = oracle_parity._build_torch_setup(SEED, anchor)
    ref = oracle_parity.run_oracle(setup, ROUNDS, SEED, anchor)
    repo = oracle_parity.run_repo("torch", ROUNDS, SEED, anchor=anchor)
    return ref, repo


def test_oracle_import_does_not_shadow_repo_modules():
    """The reference checkout has top-level exp.py/tune.py; loading the
    oracle must not leave /root/reference on sys.path where a later
    in-process ``import tune`` (sweep.py does this) would resolve to the
    reference's NNI-importing driver instead of this repo's."""
    import sys

    oracle_parity._load_oracle()
    assert oracle_parity.REFERENCE_ROOT not in sys.path
    import tune

    assert os.path.dirname(os.path.abspath(tune.__file__)) != \
        oracle_parity.REFERENCE_ROOT


def test_oracle_runs_all_seven_and_learns(arms):
    ref, _ = arms
    assert set(ref) == set(oracle_parity.ALGOS)
    assert all(np.isfinite(v) for v in ref.values())
    # non-degenerate: the reference genuinely learns at this anchor
    # (digits majority-class frequency is ~10%)
    assert ref["FedAvg"] > 40.0
    assert ref["FedAMW"] > 40.0


def test_repo_torch_matches_oracle(arms):
    """Same tensors, same sequential semantics, independent
    implementations; single seed, so the band covers shuffle/init RNG
    noise (the 10-seed statistical test lives in PARITY.md §1)."""
    ref, repo = arms
    for algo in oracle_parity.ALGOS:
        # FedAMW_OneShot: the reference has the aliasing bug (client 0's
        # stored weights get re-scaled by p[0] every p-iteration,
        # tools.py:318-320 — compounding to p[0]^t), which the repo
        # deliberately does NOT reproduce. At J=8 effectively deleting
        # client 0 from the ensemble is material, so the bug itself
        # creates a real gap; at the PARITY.md anchor (J=20, 10 seeds)
        # the arms still agree statistically.
        band = 25.0 if algo == "FedAMW_OneShot" else 12.0
        assert abs(ref[algo] - repo[algo]) <= band, (
            f"{algo}: oracle {ref[algo]:.2f} vs repo {repo[algo]:.2f}")
