"""The reference's MSE branch as a read-only parity oracle (fast tier).

Companion to ``tests/test_reference_oracle.py`` for the regression task:
the reference's synthetic regression path (``tune.py:58-66`` →
``load_synthetic_data``, ``utils.py:74-84``) routes ``train_loop``/
``test_loop`` through ``nn.MSELoss`` (``tools.py:183-184, 231-234``).
This pins that branch against the repo's torch backend at a test-sized
operating point; the 5-seed statistical matrix lives in PARITY.md §3
(``oracle_parity.py --task regression``). Skips when the reference
checkout is absent (other machines).
"""

import os

import numpy as np
import pytest

import oracle_parity

pytestmark = pytest.mark.skipif(
    not os.path.isdir(oracle_parity.REFERENCE_ROOT),
    reason="reference checkout not mounted",
)

ROUNDS = 6
SEED = 100


@pytest.fixture(scope="module")
def arms():
    # smaller than the PARITY.md §3 anchor so the sequential oracle loop
    # stays test-sized; same lr=0.2 regime where the oracle genuinely
    # learns (CL approaches the 0.04 label-noise floor)
    anchor = dict(oracle_parity.REG_ANCHOR, num_partitions=8, D=128)
    setup = oracle_parity._build_torch_setup(SEED, anchor)
    ref = oracle_parity.run_oracle(setup, ROUNDS, SEED, anchor)
    repo = oracle_parity.run_repo("torch", ROUNDS, SEED, anchor=anchor)
    return ref, repo


def test_oracle_regression_learns(arms):
    """The reference itself learns at this anchor: MSE drops far below
    the var(y) ~ 10 predict-zero baseline, and the mixture algorithms
    beat plain averaging (the paper's headline ordering)."""
    ref, _ = arms
    assert set(ref) == set(oracle_parity.ALGOS)
    assert all(np.isfinite(v) for v in ref.values())
    assert ref["CL"] < 1.0
    assert ref["FedAMW"] < ref["FedAvg"]


def test_repo_torch_matches_oracle_mse(arms):
    """Same tensors, same sequential semantics, independent
    implementations; single seed, so the band covers shuffle/init RNG
    noise. FedAMW_OneShot gets a wider band for the reference's p[0]^t
    aliasing bug (tools.py:318-320), deliberately not reproduced."""
    ref, repo = arms
    for algo in oracle_parity.ALGOS:
        band = 1.0 if algo == "FedAMW_OneShot" else 0.5
        assert abs(ref[algo] - repo[algo]) <= band, (
            f"{algo}: oracle {ref[algo]:.4f} vs repo {repo[algo]:.4f}")
