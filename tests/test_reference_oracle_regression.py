"""The reference's MSE branch as a read-only parity oracle (fast tier).

Companion to ``tests/test_reference_oracle.py`` for the regression task:
the reference's synthetic regression path (``tune.py:58-66`` →
``load_synthetic_data``, ``utils.py:74-84``) routes ``train_loop``/
``test_loop`` through ``nn.MSELoss`` (``tools.py:183-184, 231-234``).
This pins that branch against the repo's torch backend at a test-sized
operating point; the 10-seed statistical matrix lives in PARITY.md §3
(``oracle_parity.py --task regression``). Skips when the reference
checkout is absent (other machines).
"""

import os

import numpy as np
import pytest

import oracle_parity

pytestmark = pytest.mark.skipif(
    not os.path.isdir(oracle_parity.REFERENCE_ROOT),
    reason="reference checkout not mounted",
)

ROUNDS = 6
SEED = 100


@pytest.fixture(scope="module")
def arms():
    # smaller than the PARITY.md §3 anchor so the sequential oracle loop
    # stays test-sized; same lr=0.2 regime where the oracle genuinely
    # learns (CL approaches the 0.04 label-noise floor)
    anchor = dict(oracle_parity.REG_ANCHOR, num_partitions=8, D=128)
    setup = oracle_parity._build_torch_setup(SEED, anchor)
    ref = oracle_parity.run_oracle(setup, ROUNDS, SEED, anchor)
    repo = oracle_parity.run_repo("torch", ROUNDS, SEED, anchor=anchor)
    return ref, repo


def test_oracle_regression_learns(arms):
    """The reference itself learns at this anchor: MSE drops far below
    the var(y) ~ 10 predict-zero baseline, and the mixture algorithms
    beat plain averaging (the paper's headline ordering)."""
    ref, _ = arms
    assert set(ref) == set(oracle_parity.ALGOS)
    assert all(np.isfinite(v) for v in ref.values())
    assert ref["CL"] < 1.0
    assert ref["FedAMW"] < ref["FedAvg"]


def test_default_lr_p_divergence_is_faithful():
    """At the tuner CLI's default ``lr_p=0.1`` the regression p-solver
    blows up (NaN by round 2) on the REFERENCE's own FedAMW — so the
    repo reproducing that blow-up is parity, not a bug (PARITY.md §3
    "known faithful divergence"; the NNI search space sweeps lr_p down
    to 5e-6 precisely because of this)."""
    import contextlib
    import io

    import torch

    # the tuner's exact operating point: J=50 (tune.py hard-codes it);
    # at smaller J the p-gradient happens to stay bounded
    anchor = dict(oracle_parity.REG_ANCHOR, num_partitions=50, D=64,
                  lr=0.001, lr_p=0.1, epoch=1)
    setup = oracle_parity._build_torch_setup(1, anchor)

    rt = oracle_parity._load_oracle()
    torch.manual_seed(1)
    X_train, y_train, validloader = oracle_parity.reference_inputs(setup)
    with contextlib.redirect_stdout(io.StringIO()):
        _, tl, _ = rt.FedAMW(
            X_train, y_train, X_test=setup.X_test,
            y_test=oracle_parity.reference_y_test(setup),
            type="regression",
            num_classes=1, D=anchor["D"], lr=anchor["lr"],
            epoch=anchor["epoch"], batch_size=anchor["batch_size"],
            lambda_reg_if=True, lambda_reg=anchor["lambda_reg"],
            round=2, lr_p=anchor["lr_p"], validloader=validloader)
    assert not np.isfinite(float(np.asarray(tl)[-1]))

    # both repo backends reproduce the blow-up (PARITY.md §3 claims
    # "BOTH this repo's backends" — pin each so a later p-solver guard
    # can't silently diverge from the reference here)
    from fedamw_tpu.data import load_dataset
    from fedamw_tpu.registry import get_backend

    amw_kw = dict(lr=anchor["lr"], epoch=anchor["epoch"],
                  batch_size=anchor["batch_size"], lambda_reg_if=True,
                  lambda_reg=anchor["lambda_reg"], round=2,
                  lr_p=anchor["lr_p"], seed=1, sequential=True)
    for backend in ("torch", "jax"):
        be = get_backend(backend)
        rng = np.random.RandomState(1)
        ds = load_dataset(anchor["dataset"], anchor["num_partitions"],
                          anchor["alpha"], rng=rng)
        bsetup = be.prepare_setup(ds, D=anchor["D"],
                                  kernel_par=anchor["kernel_par"],
                                  seed=1, rng=rng)
        res = be.ALGORITHMS["FedAMW"](bsetup, **amw_kw)
        assert not np.isfinite(float(np.asarray(res["test_loss"])[-1])), \
            backend


def test_repo_torch_matches_oracle_mse(arms):
    """Same tensors, same sequential semantics, independent
    implementations; single seed, so the band covers shuffle/init RNG
    noise. FedAMW_OneShot gets a wider band for the reference's p[0]^t
    aliasing bug (tools.py:318-320), deliberately not reproduced."""
    ref, repo = arms
    for algo in oracle_parity.ALGOS:
        band = 1.0 if algo == "FedAMW_OneShot" else 0.5
        assert abs(ref[algo] - repo[algo]) <= band, (
            f"{algo}: oracle {ref[algo]:.4f} vs repo {repo[algo]:.4f}")
