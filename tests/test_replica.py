"""Replica-fleet failover under deterministic chaos (ISSUE 7).

Load-bearing contracts:

- **Chaos determinism**: the same ``ChaosSpec`` seed expands to the
  identical ``ChaosPlan`` role matrix, and driving the same plan with
  the same dispatch sequence yields the identical kill schedule,
  requeue counts, and per-replica routed totals — the serving twin of
  ``tests/test_faults.py``'s seeded-fault pins.
- **Dead-replica requeue**: a replica killed mid-dispatch has its
  in-flight batch re-dispatched against a survivor within the original
  request deadline — every accepted request resolves (success or an
  explicit typed error), none lost or hung, with ZERO recompiles
  (N replicas share ONE compiled bucket ladder).
- **Health gating**: consecutive failures open a circuit; after the
  cooldown one half-open probe re-earns traffic; killed replicas stay
  dead. With survivors the router fails TRANSIENTLY (the service's
  retry layer re-enters); with nobody left it fails fast.
- **Hedged dispatch**: a dispatch exceeding the latency-percentile
  hedge threshold is mirrored to the next-healthiest replica and the
  first result wins.
- **Exactly-once spans**: under mid-stream replica death every
  accepted request id lands exactly one "request" span, carrying
  ``replica_id``/``failovers``.
- **CheckpointWatcher** (satellite): vNNNN checkpoint dirs are
  published in round order, damaged entries retried (never marked
  seen), bounded poll interval, clean shutdown.
"""

import os
import threading
import time

import numpy as np
import pytest

from fedamw_tpu.serving import (ChaosFault, ChaosPlan, ChaosSpec,
                                CheckpointWatcher, FailoverRouter,
                                ModelRegistry, NoReplicasAvailable,
                                Replica, ReplicaDead, ReplicaSet,
                                ReplicaUnavailable, ServingEngine,
                                ServingService, resolve_chaos_plan)
from fedamw_tpu.serving.chaos import CLEAN, FLAKY, KILL, SLOW, WEDGE
from fedamw_tpu.serving.service import _is_transient
from fedamw_tpu.utils.trace import Tracer

D, C = 16, 3


def make_engine(buckets=(1, 8, 32)):
    rng = np.random.RandomState(1)
    e = ServingEngine({"w": rng.randn(C, D).astype(np.float32)},
                      buckets=buckets)
    e.warmup()
    return e


def rows(n, seed=5):
    return np.random.RandomState(seed).randn(n, D).astype(np.float32)


# -- chaos spec / plan -------------------------------------------------

def test_chaos_spec_parse_full_grammar():
    s = ChaosSpec.parse(
        "kill=0.01,wedge=0.02:0.5,flaky=0.05,slow=0.1:4.0,seed=7")
    assert (s.kill, s.wedge, s.wedge_s) == (0.01, 0.02, 0.5)
    assert (s.flaky, s.slow, s.slow_mult, s.seed) == (0.05, 0.1, 4.0, 7)
    # shape knobs are optional: bare rates keep the defaults
    s2 = ChaosSpec.parse("wedge=0.1,slow=0.2")
    assert s2.wedge_s == 0.25 and s2.slow_mult == 3.0 and s2.seed == 0
    assert ChaosSpec.parse("") == ChaosSpec()


@pytest.mark.parametrize("bad, match", [
    ("boom=1", "unknown chaos spec key"),
    ("kill", "not key=value"),
    ("kill=lots", "kill=lots"),
    ("kill=1.5", r"must be in \[0, 1\]"),
    ("kill=0.6,flaky=0.6", "sum to <= 1"),
    ("wedge=0.1:0", "positive stall"),
    ("slow=0.1:0.5", ">= 1"),
])
def test_chaos_spec_rejects_malformed(bad, match):
    with pytest.raises(ValueError, match=match):
        ChaosSpec.parse(bad)


def test_chaos_plan_build_is_seed_deterministic():
    spec = ChaosSpec(kill=0.02, wedge=0.05, flaky=0.1, slow=0.1, seed=9)
    a = ChaosPlan.build(spec, 4, horizon=512)
    b = ChaosPlan.build(spec, 4, horizon=512)
    np.testing.assert_array_equal(a.roles, b.roles)
    # every role actually lands at these rates, and a different seed
    # is a different schedule
    for code in (KILL, WEDGE, FLAKY, SLOW):
        assert (a.roles == code).any()
    c = ChaosPlan.build(
        ChaosSpec(kill=0.02, wedge=0.05, flaky=0.1, slow=0.1, seed=10),
        4, horizon=512)
    assert (a.roles != c.roles).any()


def test_chaos_plan_scripted_placement_and_queries():
    plan = ChaosPlan.scripted(3, kills={1: 4}, wedges={0: [2]},
                              flaky={2: [0, 1]}, slow={0: [5]},
                              horizon=8)
    assert plan.role(1, 4) == KILL and plan.kill_at(1) == 4
    assert plan.kill_at(0) is None and plan.kills_planned() == {1: 4}
    assert plan.role(0, 2) == WEDGE and plan.role(2, 0) == FLAKY
    assert plan.role(0, 5) == SLOW
    assert plan.role(0, 0) == CLEAN
    assert plan.role(0, 10_000) == CLEAN  # past the horizon: clean
    with pytest.raises(ValueError, match="two roles"):
        ChaosPlan.scripted(2, kills={0: 1}, flaky={0: [1]})
    with pytest.raises(ValueError, match="out of range"):
        ChaosPlan.scripted(2, kills={5: 0})
    with pytest.raises(ValueError, match="outside the horizon"):
        ChaosPlan.scripted(2, kills={0: 9}, horizon=4)


def test_resolve_chaos_plan_accepts_every_surface():
    assert resolve_chaos_plan(None, 3) is None
    p = resolve_chaos_plan("kill=0.5,seed=3", 2, horizon=16)
    assert isinstance(p, ChaosPlan) and p.n_replicas == 2
    q = resolve_chaos_plan(ChaosSpec(flaky=0.2), 3, horizon=8)
    assert q.horizon == 8
    assert resolve_chaos_plan(q, 3) is q  # prebuilt passes through
    with pytest.raises(ValueError, match="rebuild the plan"):
        resolve_chaos_plan(q, 5)
    with pytest.raises(TypeError, match="chaos must be"):
        resolve_chaos_plan(42, 3)


# -- replica dispatch boundary ----------------------------------------

def test_replica_clean_dispatch_is_bitwise_engine_output():
    engine = make_engine()
    rep = Replica(0, engine, plan=None)
    X = rows(4)
    np.testing.assert_array_equal(rep.predict(X), engine.predict(X))
    assert rep.dispatches == 1


def test_replica_kill_is_permanent():
    engine = make_engine()
    plan = ChaosPlan.scripted(1, kills={0: 1}, horizon=8)
    rep = Replica(0, engine, plan)
    rep.predict(rows(2))  # dispatch 0: clean
    with pytest.raises(ReplicaDead):
        rep.predict(rows(2))  # dispatch 1: the kill
    assert rep.dead
    with pytest.raises(ReplicaDead):  # and forever after
        rep.predict(rows(2))


def test_replica_flaky_and_wedge_are_transient_to_the_service():
    engine = make_engine()
    plan = ChaosPlan.scripted(1, flaky={0: [0]}, wedges={0: [1]},
                              wedge_s=0.02, horizon=8)
    rep = Replica(0, engine, plan)
    with pytest.raises(ChaosFault) as ei:
        rep.predict(rows(1))
    # ChaosFault IS a ConnectionError: the service's transient
    # classifier treats injected chaos exactly like a real tunnel blip
    assert isinstance(ei.value, ConnectionError)
    assert _is_transient(ei.value)
    t0 = time.perf_counter()
    with pytest.raises(ChaosFault, match="wedged"):
        rep.predict(rows(1))
    assert time.perf_counter() - t0 >= 0.02  # the stall, then the drop
    rep.predict(rows(1))  # dispatch 2: clean again


def test_replica_set_validates_and_iterates():
    engine = make_engine()
    rs = ReplicaSet(engine, 3, chaos="flaky=0.1,seed=2", horizon=32)
    assert len(rs) == 3 and [r.replica_id for r in rs] == [0, 1, 2]
    assert rs[1].engine is engine and rs.plan.n_replicas == 3
    with pytest.raises(ValueError, match="at least one replica"):
        ReplicaSet(engine, 0)


# -- router: routing, health, failover --------------------------------

def test_router_requires_one_shared_engine():
    e1, e2 = make_engine(), make_engine()
    with pytest.raises(ValueError, match="share ONE engine"):
        FailoverRouter([Replica(0, e1), Replica(1, e2)])
    with pytest.raises(ValueError, match="policy"):
        FailoverRouter(ReplicaSet(e1, 2), policy="random")


def test_router_requeues_dead_replicas_batch_to_survivor():
    engine = make_engine()
    plan = ChaosPlan.scripted(3, kills={0: 0}, horizon=32)
    router = FailoverRouter(ReplicaSet(engine, 3, chaos=plan),
                            policy="round_robin")
    X = rows(4)
    out = router.predict(X)  # replica 0 dies under it; 1 answers
    np.testing.assert_array_equal(out, engine.predict(X))
    timing = router.pop_timings()
    assert timing["replica"] == 1 and timing["failovers"] == 1
    stats = router.replica_stats()
    assert stats["requeues"] == 1 and stats["dead_replicas"] == 1
    assert stats["replicas"]["0"]["state"] == "dead"
    assert stats["replicas"]["0"]["requeued"] == 1
    assert stats["replicas"]["1"]["ok"] == 1


def test_router_same_plan_same_schedule_same_totals():
    """ISSUE 7 determinism pin: same ChaosPlan + same dispatch
    sequence => identical kill schedule, requeue counts, and final
    per-replica routed totals, across independent fleets."""
    engine = make_engine()
    spec = ChaosSpec(kill=0.03, flaky=0.1, seed=11)

    def drive():
        plan = resolve_chaos_plan(spec, 3, horizon=64)
        router = FailoverRouter(ReplicaSet(engine, 3, chaos=plan),
                                policy="round_robin",
                                failure_threshold=100)
        kills_seen = {}
        for k in range(40):
            try:
                router.predict(rows(2, seed=k))
            except (ReplicaUnavailable, NoReplicasAvailable):
                pass
            for r in router.replicas:
                if r.dead and r.replica_id not in kills_seen:
                    kills_seen[r.replica_id] = r.dispatches - 1
        stats = router.replica_stats()
        return (kills_seen, stats["requeues"],
                {k: v["routed"] for k, v in stats["replicas"].items()})

    a, b = drive(), drive()
    assert a == b
    # the observed kill schedule IS the plan's (plan facts, available
    # before anything runs)
    plan = resolve_chaos_plan(spec, 3, horizon=64)
    for rid, at in a[0].items():
        assert plan.kill_at(rid) == at


def test_router_circuit_opens_then_half_open_probe_recovers():
    engine = make_engine()
    plan = ChaosPlan.scripted(1, flaky={0: [0, 1]}, horizon=16)
    router = FailoverRouter(ReplicaSet(engine, 1, chaos=plan),
                            failure_threshold=2, cooldown_s=0.05)
    h = router._health[0]
    for _ in range(2):  # two transient failures open the circuit
        with pytest.raises(ReplicaUnavailable):
            router.predict(rows(1))
    assert h.state == "open"
    # while open (cooldown pending) nothing routes — and the failure
    # is TRANSIENT (a ConnectionError): the service retries, the
    # replica's dispatch counter is NOT consumed
    before = router.replicas[0].dispatches
    with pytest.raises(ReplicaUnavailable) as ei:
        router.predict(rows(1))
    assert isinstance(ei.value, ConnectionError)
    assert router.replicas[0].dispatches == before
    time.sleep(0.06)  # cooldown elapses: one half-open probe allowed
    out = router.predict(rows(1))  # dispatch 2 is clean -> closes
    assert out.shape == (1, C) and h.state == "closed"
    assert router.replica_stats()["replicas"]["0"]["ok"] == 1


def test_half_open_admits_exactly_one_probe():
    """The half-open window admits ONE in-flight probe: concurrent
    dispatches (hedge mirrors especially) must not pile onto a
    maybe-still-broken replica before the probe's outcome lands."""
    from fedamw_tpu.serving.replica import ReplicaHealth

    h = ReplicaHealth(failure_threshold=1, cooldown_s=0.05,
                      ewma_alpha=0.2)
    t0 = 100.0
    h.on_failure(t0)
    assert h.state == "open" and not h.available(t0 + 0.01)
    assert h.available(t0 + 0.06)  # cooldown elapsed: half-open
    h.on_probe()  # the router routed the probe
    assert not h.available(t0 + 0.06)  # window closed while in flight
    h.on_failure(t0 + 0.07)  # probe failed: fresh cooldown
    assert h.state == "open" and not h.available(t0 + 0.08)
    assert h.available(t0 + 0.13)  # next cooldown, next probe
    h.on_probe()
    h.on_success(0.001)  # probe succeeded: re-earned traffic
    assert h.state == "closed" and h.available(t0 + 0.14)


def test_router_all_dead_fails_fast_not_transient():
    engine = make_engine()
    plan = ChaosPlan.scripted(2, kills={0: 0, 1: 0}, horizon=8)
    router = FailoverRouter(ReplicaSet(engine, 2, chaos=plan))
    with pytest.raises(NoReplicasAvailable) as ei:
        router.predict(rows(2))
    # fail FAST: with nobody left a retry only burns the deadline
    assert not _is_transient(ei.value)
    with pytest.raises(NoReplicasAvailable):
        router.predict(rows(2))


def test_router_deadline_bounds_the_failover_walk():
    engine = make_engine()
    router = FailoverRouter(ReplicaSet(engine, 2))
    with pytest.raises(ReplicaUnavailable, match="deadline"):
        router.predict(rows(1), deadline=time.perf_counter() - 1.0)
    # nothing was dispatched: the walk stopped before routing
    assert all(r.dispatches == 0 for r in router.replicas)


def test_router_hedges_wedged_dispatch_and_mirror_wins():
    engine = make_engine()
    # replica 0 wedges on its 3rd dispatch (after the hedge histogram
    # has enough clean samples to arm the percentile threshold)
    plan = ChaosPlan.scripted(2, wedges={0: [2]}, wedge_s=0.5,
                              horizon=64)
    with FailoverRouter(ReplicaSet(engine, 2, chaos=plan),
                        policy="round_robin", hedge=True,
                        hedge_min_samples=4, hedge_factor=2.0,
                        hedge_floor_ms=1.0) as router:
        for k in range(4):  # r0 d0, r1 d0, r0 d1, r1 d1: all clean
            router.predict(rows(2, seed=k))
        assert router._hedge_timeout_s() is not None
        X = rows(3, seed=99)
        t0 = time.perf_counter()
        out = router.predict(X)  # r0 d2 wedges -> mirrored to r1
        dt = time.perf_counter() - t0
        np.testing.assert_array_equal(out, engine.predict(X))
        assert dt < 0.45  # the mirror answered; nobody rode out 0.5s
        assert router.hedges == 1 and router.hedge_wins == 1
        timing = router.pop_timings()
        assert timing["hedged"] is True and timing["replica"] == 1


def test_hedge_both_fail_excludes_mirror_from_requeue_walk():
    """When the primary AND its hedge mirror both fail, the failover
    walk must exclude (and account) BOTH — re-dispatching the batch to
    the mirror that just failed it would burn deadline on a known-bad
    replica."""
    engine = make_engine()
    # r1 wedges on its 2nd dispatch; the mirror (r2) is flaky on its
    # 2nd — both fail the same batch, r0 must carry it
    plan = ChaosPlan.scripted(3, wedges={1: [1]}, flaky={2: [1]},
                              wedge_s=0.5, horizon=64)
    with FailoverRouter(ReplicaSet(engine, 3, chaos=plan),
                        policy="round_robin", hedge=True,
                        hedge_min_samples=4, hedge_factor=2.0,
                        hedge_floor_ms=1.0) as router:
        for k in range(4):  # r0 d0, r1 d0, r2 d0, r0 d1: all clean
            router.predict(rows(2, seed=k))
        assert router._hedge_timeout_s() is not None
        X = rows(3, seed=99)
        out = router.predict(X)  # r1 wedges -> mirror r2 flaky -> r0
        np.testing.assert_array_equal(out, engine.predict(X))
        stats = router.replica_stats()
        assert router.hedges == 1 and router.hedge_wins == 0
        assert stats["requeues"] == 2  # both failures accounted
        assert stats["replicas"]["1"]["requeued"] == 1
        assert stats["replicas"]["2"]["requeued"] == 1
        # the mirror was NOT re-attempted after failing the batch
        assert router.replicas[2].dispatches == 2
        assert router.pop_timings()["replica"] == 0


def _wait_for(cond, timeout_s=5.0):
    """Poll until ``cond()`` (the abandoned hedge loser finishes on a
    pool thread; its discarded accounting lands asynchronously)."""
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < timeout_s:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


def test_primary_win_cancels_mirror_failure_health_untouched():
    """The ISSUE 9 hedge-cancellation follow-on: when the PRIMARY
    resolves first, the losing mirror's dispatch is marked cancelled —
    its eventual FAILURE is discarded without opening the mirror's
    circuit or touching its EWMA, counted under ``hedges_cancelled``/
    per-replica ``cancelled`` instead of ``failed``/``requeued``."""
    engine = make_engine()
    # r0 is slow (not failing) on its 3rd dispatch — long enough to
    # cross the hedge threshold, fast enough to beat the mirror; the
    # mirror r1 wedges 0.6s on its 3rd dispatch and then fails
    plan = ChaosPlan.scripted(2, slow={0: [2]}, wedges={1: [2]},
                              slow_mult=400.0, wedge_s=0.6, horizon=64)
    with FailoverRouter(ReplicaSet(engine, 2, chaos=plan),
                        policy="round_robin", hedge=True,
                        hedge_min_samples=4, hedge_factor=2.0,
                        hedge_floor_ms=1.0) as router:
        for k in range(4):  # r0 d0, r1 d0, r0 d1, r1 d1: all clean
            router.predict(rows(2, seed=k))
        assert router._hedge_timeout_s() is not None
        ewma_before = router.replica_stats()["replicas"]["1"]["ewma_ms"]
        X = rows(3, seed=99)
        out = router.predict(X)  # r0 slow -> mirrored to r1 -> r0 wins
        np.testing.assert_array_equal(out, engine.predict(X))
        assert router.hedges == 1 and router.hedge_wins == 0
        assert router.hedges_cancelled == 1
        # the discarded mirror outcome lands on a pool thread later
        assert _wait_for(lambda: router.replica_stats()
                         ["replicas"]["1"]["cancelled"] == 1)
        stats = router.replica_stats()
        assert stats["hedges_cancelled"] == 1
        r1 = stats["replicas"]["1"]
        # the wedge-then-fail was DISCARDED: no failure, no requeue,
        # circuit closed, EWMA exactly what the clean dispatches left
        assert r1["failed"] == 0 and r1["requeued"] == 0
        assert r1["state"] == "closed"
        assert r1["ewma_ms"] == ewma_before
        assert stats["requeues"] == 0
        timing = router.pop_timings()
        assert timing["replica"] == 0 and timing["hedged"] is True


class _SleepyReplica(Replica):
    """Chaos-free replica with an exact per-dispatch stall AFTER the
    (successful) engine call — slow, never failing: what the
    cancelled-success case needs and rate/mult chaos cannot script
    deterministically (one plan-wide slow_mult would make primary and
    mirror photo-finish)."""

    def __init__(self, replica_id, engine, sleeps):
        super().__init__(replica_id, engine, None)
        self._sleeps = dict(sleeps)  # dispatch index -> seconds

    def predict(self, X, version=None, record_timings=True):
        k = self.dispatches
        out = super().predict(X, version=version,
                              record_timings=record_timings)
        s = self._sleeps.get(k, 0.0)
        if s:
            time.sleep(s)
        return out


def test_primary_win_cancels_mirror_success_no_ewma_sample():
    """A cancelled mirror that SUCCEEDS is discarded the same way: no
    ok count, no EWMA sample, no hedge win — a race it was only
    drafted into must not distort its health either way."""
    engine = make_engine()
    # on the hedged dispatch (each replica's 3rd): primary r0 stalls
    # 60ms — past the ~1-2ms hedge threshold, far under the mirror's
    # 600ms — so the primary wins with a wide deterministic margin
    # and the mirror's SUCCESS lands half a second after cancellation
    reps = [_SleepyReplica(0, engine, {2: 0.06}),
            _SleepyReplica(1, engine, {2: 0.6})]
    with FailoverRouter(reps, policy="round_robin", hedge=True,
                        hedge_min_samples=4, hedge_factor=2.0,
                        hedge_floor_ms=1.0) as router:
        for k in range(4):
            router.predict(rows(2, seed=k))
        assert router._hedge_timeout_s() is not None
        before = router.replica_stats()["replicas"]["1"]
        X = rows(3, seed=42)
        out = router.predict(X)
        np.testing.assert_array_equal(out, engine.predict(X))
        assert router.hedges == 1
        assert router.hedges_cancelled == 1
        assert _wait_for(lambda: router.replica_stats()
                         ["replicas"]["1"]["cancelled"] == 1)
        after = router.replica_stats()["replicas"]["1"]
        assert router.hedge_wins == 0
        assert after["ok"] == before["ok"]  # success discarded
        assert after["ewma_ms"] == before["ewma_ms"]


def test_cancelled_failure_releases_half_open_probe_slot():
    """A half-open replica drafted as a hedge mirror whose CANCELLED
    dispatch fails must get its probe slot back: the cancelled branch
    skips on_failure (which normally clears the in-flight probe), and
    leaking the slot would bench a live replica forever."""
    engine = make_engine()
    plan = ChaosPlan.scripted(2, flaky={1: [0, 1]}, horizon=16)
    router = FailoverRouter(ReplicaSet(engine, 2, chaos=plan),
                            failure_threshold=1, cooldown_s=0.01)
    h = router._health[1]
    X = rows(2)
    with pytest.raises(ChaosFault):  # r1 d0 flaky: circuit opens
        router._attempt(router.replicas[1], X, None, False)
    assert h.state == "open"
    time.sleep(0.02)
    assert h.available(time.perf_counter())  # cooldown -> half-open
    h.on_probe()  # the pick consumed the single probe slot
    assert not h.available(time.perf_counter())
    cancel = threading.Event()
    cancel.set()  # the primary already won this race
    with pytest.raises(ChaosFault):  # r1 d1 flaky, CANCELLED
        router._attempt(router.replicas[1], X, None, False, cancel)
    # outcome discarded — failures unchanged, circuit state kept —
    # but the probe slot is free again: the replica stays routable
    assert h.failures == 1
    assert h.available(time.perf_counter())
    assert router.replica_stats()["replicas"]["1"]["cancelled"] == 1


def test_cancelled_mirror_kill_still_marks_dead():
    """Cancellation discards the HEALTH observation, not the fact of
    death: a chaos kill landing on a cancelled mirror still marks the
    replica dead (it is gone for every future dispatch), while the
    failed/requeued counters stay clean."""
    engine = make_engine()
    plan = ChaosPlan.scripted(2, slow={0: [2]}, kills={1: 2},
                              slow_mult=400.0, horizon=64)
    with FailoverRouter(ReplicaSet(engine, 2, chaos=plan),
                        policy="round_robin", hedge=True,
                        hedge_min_samples=4, hedge_factor=2.0,
                        hedge_floor_ms=1.0) as router:
        for k in range(4):
            router.predict(rows(2, seed=k))
        X = rows(3, seed=7)
        out = router.predict(X)  # r0 slow -> mirror r1 killed instantly
        np.testing.assert_array_equal(out, engine.predict(X))
        # the kill raises immediately — usually BEFORE the slow
        # primary returns, in which case it counts as a genuine
        # failure (cancel was not yet set); either way the replica is
        # dead and nothing was requeued (the primary answered)
        assert _wait_for(lambda: router.replica_stats()
                         ["replicas"]["1"]["state"] == "dead")
        stats = router.replica_stats()
        assert stats["replicas"]["1"]["requeued"] == 0
        assert stats["requeues"] == 0


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def test_adaptive_hedge_threshold_tracks_live_window():
    """The ISSUE 14 satellite (ROADMAP carried item): with
    ``hedge_window_s`` set, the hedge threshold is the percentile of
    the TRAILING WINDOW's dispatch latencies — it re-arms when the
    live distribution shifts, where the all-time reservoir would keep
    hedging against history — and falls back to the reservoir while
    the window is thin."""
    from fedamw_tpu.utils.telemetry import Registry

    engine = make_engine()
    with pytest.raises(ValueError, match="needs a .*registry"):
        FailoverRouter(ReplicaSet(engine, 2), hedge=True,
                       hedge_window_s=10.0)
    clk = _Clock()
    reg = Registry(clock=clk)
    router = FailoverRouter(ReplicaSet(engine, 2), hedge=True,
                            policy="round_robin", registry=reg,
                            hedge_min_samples=4, hedge_factor=2.0,
                            hedge_floor_ms=0.01, hedge_window_s=10.0)
    # 6 real (fast, sub-ms) dispatches land in BOTH the reservoir and
    # the fleet window series (stamped at the fake clock's now)
    for k in range(6):
        router.predict(rows(2, seed=k))
    fast = router._hedge_timeout_s()
    assert fast is not None and fast < 0.05
    # the latency regime SHIFTS: the fast evidence ages out of the
    # window and the recent window is slow — the adaptive threshold
    # must track the live distribution (~2x the new p95), not history
    clk.t += 100.0
    for _ in range(8):
        router._fleet_hist.observe(0.05)
    adaptive = router._hedge_timeout_s()
    assert adaptive == pytest.approx(0.1, rel=0.05)
    # the all-time reservoir still remembers only fast dispatches: the
    # legacy (non-windowed) threshold would be ~100x smaller — the
    # exact staleness adaptive mode exists to fix
    assert adaptive > 10 * fast
    # thin window (everything aged out) => fall back to the reservoir
    # rather than disarming tail protection
    clk.t += 100.0
    thin = router._hedge_timeout_s()
    assert thin is not None and thin == pytest.approx(fast, rel=0.5)


def test_adaptive_hedge_still_masks_a_wedge():
    """Behavioral twin of the fixed-knob hedge test: with the
    threshold armed from the rolling window, a wedged dispatch is
    still mirrored and the mirror still wins."""
    from fedamw_tpu.utils.telemetry import Registry

    engine = make_engine()
    plan = ChaosPlan.scripted(2, wedges={0: [2]}, wedge_s=0.5,
                              horizon=64)
    reg = Registry()
    with FailoverRouter(ReplicaSet(engine, 2, chaos=plan),
                        policy="round_robin", hedge=True,
                        hedge_min_samples=4, hedge_factor=2.0,
                        hedge_floor_ms=1.0, registry=reg,
                        hedge_window_s=60.0) as router:
        for k in range(4):
            router.predict(rows(2, seed=k))
        # the threshold armed from the WINDOW (4 samples >= min), and
        # the fleet series actually carries the dispatches
        assert router._fleet_hist.count == 4
        assert router._hedge_timeout_s() is not None
        X = rows(3, seed=99)
        out = router.predict(X)  # r0 wedges -> mirrored to r1
        np.testing.assert_array_equal(out, engine.predict(X))
        assert router.hedges == 1 and router.hedge_wins == 1


def test_untimed_dispatch_attributes_pinned_version():
    """Hedged-mode attempts run untimed (record_timings=False) and so
    skip the engine's timing slot — the fallback attribution must
    still report the version the dispatch PINNED (a rollout candidate
    split), not whatever is live."""
    engine = make_engine()
    rng = np.random.RandomState(7)
    engine.install_weights(1, {"w": rng.randn(C, D).astype(np.float32)})
    router = FailoverRouter(ReplicaSet(engine, 2))
    _, timing = router._attempt(router.replicas[0], rows(2), 1, False)
    assert timing["version"] == 1  # pinned, even though live is 0
    _, timing = router._attempt(router.replicas[0], rows(2), None, False)
    assert timing["version"] == engine.version  # None -> live


def test_router_passthrough_surfaces_shared_engine():
    engine = make_engine()
    router = FailoverRouter(ReplicaSet(engine, 3))
    assert router.buckets == engine.buckets
    assert router.input_dim == engine.input_dim
    assert router.num_classes == engine.num_classes
    assert router.version == engine.version
    assert router.compile_count == engine.compile_count
    # one warmup serves every replica and consumes no chaos cells
    cc = engine.compile_count
    assert router.warmup() == cc
    assert all(r.dispatches == 0 for r in router.replicas)


# -- service integration: the acceptance pins --------------------------

def _run_chaos_stream(n_requests=40, kills={0: 1}, timeout_s=30.0,
                      n_replicas=3):
    """Drive a request stream through the full service over a chaos
    fleet; returns everything the pins assert on."""
    engine = make_engine()
    cc0 = engine.compile_count
    plan = ChaosPlan.scripted(n_replicas, kills=kills, horizon=4096)
    router = FailoverRouter(ReplicaSet(engine, n_replicas, chaos=plan),
                            policy="round_robin")
    tracer = Tracer()
    rng = np.random.RandomState(0)
    submitted, results = [], []
    with ServingService(router, max_wait_ms=1.0, tracer=tracer) as svc:
        futs = []
        for _ in range(n_requests):
            f = svc.submit(rng.randn(4, D).astype(np.float32),
                           timeout_s=timeout_s)
            submitted.append(f.request_id)
            futs.append(f)
            time.sleep(0.001)  # a stream, not one giant coalesce
        for f in futs:
            try:
                results.append(("ok", f.result(timeout=60)))
            except Exception as e:
                results.append((type(e).__name__, None))
        snap = svc.metrics.snapshot(router)
    return dict(engine=engine, router=router, tracer=tracer,
                submitted=submitted, results=results, snap=snap,
                recompiles=engine.compile_count - cc0)


def test_midstream_kill_no_request_lost_zero_recompiles():
    """The acceptance criteria pin: kill= injected mid-stream on a
    3-replica set — every accepted request resolves, the killed
    replica's in-flight batch re-dispatches to a survivor within the
    original deadline (it resolves OK, not DeadlineExceeded), and
    compile_count stays flat (shared ladder, zero recompiles)."""
    r = _run_chaos_stream(n_requests=40, kills={0: 1})
    # every accepted request resolved — and since survivors were
    # healthy, every one resolved with a RESULT within its deadline
    assert len(r["results"]) == 40
    assert all(kind == "ok" for kind, _ in r["results"])
    assert r["recompiles"] == 0
    fo = r["snap"]["failover"]
    assert fo["dead_replicas"] == 1 and fo["requeues"] >= 1
    assert fo["replicas"]["0"]["state"] == "dead"
    # the requeued batch went to a survivor
    assert fo["replicas"]["1"]["ok"] + fo["replicas"]["2"]["ok"] > 0
    assert r["snap"]["compile_count"] == len(r["engine"].buckets)


def test_exactly_once_spans_under_replica_death():
    """Satellite pin: every accepted request id lands exactly one
    "request" span under mid-stream replica death, and the spans carry
    the failover dimensions."""
    r = _run_chaos_stream(n_requests=40, kills={0: 1, 2: 5})
    spans = [s for s in r["tracer"].records() if s["name"] == "request"]
    ids = [s["trace_id"] for s in spans]
    assert sorted(ids) == sorted(r["submitted"])  # exactly once, all
    assert len(set(ids)) == len(ids) == 40
    assert r["tracer"].dropped == 0
    for s in spans:
        assert "replica_id" in s["attrs"]
        assert s["attrs"]["replica_id"] in (0, 1, 2)
        assert s["attrs"]["failovers"] >= 0
    # the kill actually hit a served batch: some span crossed a failover
    assert max(s["attrs"]["failovers"] for s in spans) >= 1


def test_all_replicas_dead_requests_fail_typed_not_hang():
    """No survivors: every accepted request resolves with a typed
    error (nothing hangs), and still lands exactly one span."""
    r = _run_chaos_stream(n_requests=8, kills={0: 0, 1: 0, 2: 0},
                          timeout_s=10.0)
    assert len(r["results"]) == 8
    assert all(kind == "NoReplicasAvailable" for kind, _ in r["results"])
    spans = [s for s in r["tracer"].records() if s["name"] == "request"]
    assert sorted(s["trace_id"] for s in spans) == sorted(r["submitted"])
    assert all(s["attrs"]["outcome"] == "error" for s in spans)
    assert r["recompiles"] == 0


def test_flaky_chaos_rides_the_service_retry_layer():
    """A flaky (transient) dispatch composes with the PR 2 service
    retry: the request still succeeds, the retry is counted, and the
    replica recovers (no kill, no dead state)."""
    engine = make_engine()
    plan = ChaosPlan.scripted(1, flaky={0: [0]}, horizon=64)
    router = FailoverRouter(ReplicaSet(engine, 1, chaos=plan),
                            failure_threshold=3)
    with ServingService(router, max_wait_ms=1.0,
                        retry_backoff_ms=1.0) as svc:
        out = svc.predict(rows(2), timeout_s=30)
        snap = svc.metrics.snapshot(router)
    assert out.shape == (2, C)
    assert snap["retries"] >= 1  # the flaky dispatch was retried
    assert snap["failover"]["dead_replicas"] == 0
    assert snap["failover"]["replicas"]["0"]["state"] == "closed"


# -- checkpoint watcher (satellite) ------------------------------------

def _write_ckpt(path, seed=0, round_idx=1):
    from fedamw_tpu.utils.checkpoint import save_checkpoint

    rng = np.random.RandomState(seed)
    save_checkpoint(str(path),
                    {"w": rng.randn(C, D).astype(np.float32)},
                    round_idx=round_idx)


def test_watcher_publishes_in_round_order_and_dedupes(tmp_path):
    _write_ckpt(tmp_path / "v0002", seed=2, round_idx=2)
    _write_ckpt(tmp_path / "v0001", seed=1, round_idx=1)
    (tmp_path / "not_a_version").mkdir()
    reg = ModelRegistry()
    w = CheckpointWatcher(reg, str(tmp_path), poll_interval_s=0.02)
    out = w.poll_once()
    assert len(out) == 2
    # ingested in ROUND order (the numeric suffix), so staleness
    # accounting stays monotone: v0001 first
    assert [name for name, _ in w.published] == ["v0001", "v0002"]
    assert reg.get(out[0]).round_idx == 1
    assert reg.get(out[1]).round_idx == 2
    assert w.poll_once() == [] and len(reg) == 2  # seen: no re-publish
    assert w.errors == 0


def test_watcher_retries_damaged_entry_until_it_loads(tmp_path):
    (tmp_path / "v0001").mkdir()  # a checkpoint "mid-write": no state
    _write_ckpt(tmp_path / "v0002", seed=2, round_idx=2)
    reg = ModelRegistry()
    w = CheckpointWatcher(reg, str(tmp_path), poll_interval_s=0.02)
    # the damaged entry STOPS the poll: v0002 waits behind it, else
    # the recovered v0001 would later take a higher registry version
    # and latest() would regress to the round-1 model
    assert w.poll_once() == [] and w.errors == 1
    assert len(reg) == 0
    _write_ckpt(tmp_path / "v0001", round_idx=1)  # the write completes
    out = w.poll_once()  # retried, never marked seen — then v0002
    assert len(out) == 2
    assert [name for name, _ in w.published] == ["v0001", "v0002"]
    assert reg.latest().round_idx == 2


def test_watcher_daemon_lifecycle_and_clean_shutdown(tmp_path):
    reg = ModelRegistry()
    seen = []
    with pytest.raises(ValueError, match="poll_interval_s"):
        CheckpointWatcher(reg, str(tmp_path), poll_interval_s=0.0)
    with CheckpointWatcher(
            reg, str(tmp_path / "later"), poll_interval_s=0.02,
            on_publish=lambda v, p: seen.append(v)) as w:
        with pytest.raises(RuntimeError, match="already started"):
            w.start()
        # the directory does not exist yet (training starts later):
        # a normal startup state, not an error
        time.sleep(0.05)
        assert w.errors == 0 and w.polls >= 1
        (tmp_path / "later").mkdir()
        _write_ckpt(tmp_path / "later" / "v0003", round_idx=3)
        deadline = time.time() + 5
        while not w.published and time.time() < deadline:
            time.sleep(0.01)
        assert [n for n, _ in w.published] == ["v0003"]
        assert seen == [w.published[0][1]]
    assert w._thread is None  # joined
    w.stop()  # idempotent


def test_watcher_on_publish_errors_counted_not_fatal(tmp_path):
    _write_ckpt(tmp_path / "v0001", round_idx=1)
    _write_ckpt(tmp_path / "v0002", round_idx=2)
    reg = ModelRegistry()

    def boom(v, path):
        raise RuntimeError("subscriber bug")

    w = CheckpointWatcher(reg, str(tmp_path), poll_interval_s=0.02,
                          on_publish=boom)
    out = w.poll_once()
    # the callback's failure never blocks ingestion: both published,
    # both errors counted
    assert len(out) == 2 and len(reg) == 2 and w.errors == 2
