import numpy as np

from fedamw_tpu.utils import Logger, check_significance, print_acc, print_time


def test_check_significance():
    best = np.array([90.0, 91.0, 92.0, 90.5, 91.5])
    clearly_worse = best - 5.0 + np.random.RandomState(0).randn(5) * 0.1
    assert check_significance(clearly_worse, best)
    assert not check_significance(best, best)  # zero diff -> not significant
    # constant positive gap (zero variance): reference computes inf -> True
    assert check_significance(best - 5.0, best)


def test_print_acc_marks_best_bold():
    m = np.array([[90.0, 91.0], [80.0, 81.0]])
    row = print_acc(m)
    assert "\\textbf{90.50$\\pm$0.50}" in row
    assert row.count("&") == 2


def test_print_acc_underlines_insignificant():
    m = np.array([[90.0, 91.0], [89.9, 91.2]])
    row = print_acc(m)
    assert "\\underline{" in row


def test_print_time_marks_fastest():
    m = np.array([[10.0, 12.0], [5.0, 6.0]])
    row = print_time(m)
    assert "\\textbf{5.50}" in row


def test_logger(tmp_path):
    path = tmp_path / "log.txt"
    lg = Logger(str(path))
    lg.write("hello\n")
    assert path.read_text() == "hello\n"
