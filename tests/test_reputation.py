"""The stateful cross-round reputation plane (ISSUE 4).

Load-bearing contracts:

- the `rep[:decay[:floor]]` / `quarantine:auto` grammar parses,
  canonicalizes, and composes (the spelling sweep lives in
  tests/test_faults.py; the conftest round-trip guard covers every
  parse here too);
- directional scores separate a norm-preserving sign flip (invisible
  to ANY norm test) from honest non-IID heterogeneity at O(JP);
- the self-REPORTED work fraction is trust-clamped: a client claiming
  frac=0.01 while doing full-norm work gets its claim bumped by the
  norm cross-check (and replaced by the cohort median as its
  reputation drops), so it gains no FedNova tau advantage;
- reputation DYNAMICS: a persistent sign-flipper's reputation decays
  geometrically to the floor and STAYS gated; an honest client
  transiently corrupted by the fault plan regains weight within
  O(1/(1-decay)) rounds of the corruption ending;
- `quarantine:auto` starts at the hand-tuned Z=5 operating point,
  catches a 25x scale attack exactly like the static threshold
  (array-equal to the clean-drop run), and never fires on a clean run;
- telemetry (reputation trajectories, gate counts, clamped-frac
  counts, threshold trajectory) reaches res["defense"] and the
  reporting layer.

The zero-recompile contract for the new tokens is pinned in
tests/test_faults.py::test_new_defense_tokens_compile_one_round_program.
"""

import numpy as np
import pytest

from fedamw_tpu.algorithms import FedAMW, FedAvg, FedNova, prepare_setup
from fedamw_tpu.data import load_dataset
from fedamw_tpu.fedcore.aggregate import fednova_effective_weights
from fedamw_tpu.fedcore.faults import FaultPlan
from fedamw_tpu.fedcore.robust import (KRUM_DESEL_EROSION,
                                       REP_DECAY_DEFAULT,
                                       REP_FLOOR_DEFAULT, Z_AUTO_MAX,
                                       Z_AUTO_MIN, directional_scores,
                                       parse_robust_spec,
                                       reputation_update,
                                       trust_bounded_work_frac)

pytestmark = [pytest.mark.faults, pytest.mark.reputation]


@pytest.fixture(scope="module")
def setup_iid():
    """Near-IID digits (alpha=100): client deltas cluster tightly, so
    the directional signal is crisp — the regime where the gating
    dynamics contracts are sharp. (Under extreme heterogeneity the
    one-round cosine signal weakens and `rep` degrades to soft
    down-weighting around the floor — README 'Cross-round
    reputation'.)"""
    ds = load_dataset("digits", num_partitions=8, alpha=100.0)
    return prepare_setup(ds, kernel_type="linear", seed=3,
                         rng=np.random.RandomState(3))


@pytest.fixture(scope="module")
def setup_het():
    """The heterogeneous cohort the rest of the fault suite uses."""
    ds = load_dataset("digits", num_partitions=8, alpha=0.5)
    return prepare_setup(ds, kernel_type="linear", seed=3,
                         rng=np.random.RandomState(3))


KW = dict(lr=0.5, epoch=1, seed=0, lr_mode="constant")


def sign_plan(R, J, j, rounds_active=None):
    """Sign-flip client ``j`` on ``rounds_active`` (default: all)."""
    z = np.zeros((R, J), np.float32)
    drop, straggle, corrupt = z.copy(), z.copy(), z.copy()
    scale = np.ones((R, J), np.float32)
    act = slice(None) if rounds_active is None else rounds_active
    corrupt[act, j] = 1
    scale[act, j] = -1.0
    return FaultPlan(drop, straggle, corrupt, scale, poison=z.copy(),
                     fill=z.copy())


def lie_plan(R, J, j, claim=0.01):
    """Client ``j`` does FULL work every round but REPORTS ``claim``
    as its work fraction (the FedNova tau inflation attack)."""
    z = np.zeros((R, J), np.float32)
    report = np.ones((R, J), np.float32)
    report[:, j] = claim
    lie = z.copy()
    lie[:, j] = 1.0
    return FaultPlan(z, z.copy(), z.copy(), np.ones((R, J), np.float32),
                     z.copy(), z.copy(), report=report, lie=lie)


# -- grammar ----------------------------------------------------------

def test_rep_defaults_and_canonical():
    spec = parse_robust_spec("rep")
    assert spec.rep_decay == REP_DECAY_DEFAULT
    assert spec.rep_floor == REP_FLOOR_DEFAULT
    assert spec.stateful and not spec.is_default
    assert spec.canonical() == "rep:0.9:0.2"
    auto = parse_robust_spec("quarantine:auto")
    assert auto.zscore_auto and auto.zscore is None and auto.stateful
    assert auto.canonical() == "quarantine:auto"
    both = parse_robust_spec("rep:0.5:0.1+quarantine:auto+mkrum:4")
    assert both.canonical() == "quarantine:auto+rep:0.5:0.1+mkrum:4"
    # the memoryless specs stay memoryless
    assert not parse_robust_spec("quarantine:5").stateful
    assert not parse_robust_spec("mkrum:4").stateful


# -- directional scores -----------------------------------------------

def test_directional_scores_flag_sign_flip_not_heterogeneity():
    rng = np.random.RandomState(0)
    J, P = 8, 40
    g = {"w": np.zeros(P, np.float32)}
    base = rng.randn(P).astype(np.float32)
    x = base[None] + 0.1 * rng.randn(J, P).astype(np.float32)
    x[2] = -x[2]  # norm-preserving flip
    cos = np.asarray(directional_scores(
        g, {"w": x}, np.ones(J, np.float32)))
    assert cos[2] < -0.8
    assert np.all(np.delete(cos, 2) > 0.8)
    # an absent client's garbage never pollutes the median direction
    x2 = x.copy()
    x2[5] = 1e6 * rng.randn(P).astype(np.float32)
    present = np.ones(J, np.float32)
    present[5] = 0.0
    cos2 = np.asarray(directional_scores(g, {"w": x2}, present))
    assert cos2[2] < -0.8 and np.all(cos2[[0, 1, 3, 4, 6, 7]] > 0.8)


# -- trust-bounded work fraction --------------------------------------

def test_trust_bounded_work_frac_clamps_liar_spares_straggler():
    present = np.ones(6, np.float32)
    norms = np.asarray([1.0, 1.05, 0.95, 1.0, 0.25, 1.0], np.float32)
    #                   honest x4 ............ straggler  liar
    frac = np.asarray([1, 1, 1, 1, 0.25, 0.01], np.float32)
    rep = np.ones(6, np.float32)
    trusted, n = trust_bounded_work_frac(norms, frac, present, rep)
    trusted = np.asarray(trusted)
    # the liar's full-norm work implies ~full-work: bumped to ~0.5
    # (norm / (FRAC_MARGIN * median full-work-equivalent norm))
    assert trusted[5] > 0.4
    # the honest straggler's claim is proportional to its norm — kept
    np.testing.assert_allclose(trusted[4], 0.25, atol=1e-6)
    np.testing.assert_allclose(trusted[:4], frac[:4], atol=1e-6)
    assert int(n) == 1
    # zero reputation: the claim is replaced by the cohort median
    rep0 = rep.copy()
    rep0[5] = 0.0
    t0 = np.asarray(trust_bounded_work_frac(norms, frac, present,
                                            rep0)[0])
    np.testing.assert_allclose(t0[5], 1.0, atol=1e-6)
    # absent clients pass their claim through untouched
    absent = present.copy()
    absent[5] = 0.0
    ta = np.asarray(trust_bounded_work_frac(norms, frac, absent,
                                            rep)[0])
    np.testing.assert_allclose(ta[5], 0.01, atol=1e-9)


def test_lie_gains_no_fednova_advantage_after_clamp():
    """The unit-level attack closure: claiming frac=0.01 at full work
    inflates the FedNova per-step weight ~100x; after the trust clamp
    the inflation collapses to the FRAC_MARGIN slack (~2x), and with
    reputation at zero it vanishes entirely."""
    J = 6
    sizes = np.full(J, 100.0, np.float32)
    p = np.full(J, 1.0 / J, np.float32)
    norms = np.ones(J, np.float32)
    frac = np.ones(J, np.float32)
    frac[5] = 0.01
    w_lie = np.asarray(fednova_effective_weights(sizes, p, 2, 32,
                                                 tau_frac=frac))
    assert w_lie[5] / w_lie[0] > 50.0  # the undefended inflation
    trusted, _ = trust_bounded_work_frac(
        norms, frac, np.ones(J, np.float32), np.ones(J, np.float32))
    w_t = np.asarray(fednova_effective_weights(sizes, p, 2, 32,
                                               tau_frac=trusted))
    assert w_t[5] / w_t[0] < 3.0  # clamped to the margin slack
    rep0 = np.ones(J, np.float32)
    rep0[5] = 0.0
    t0, _ = trust_bounded_work_frac(norms, frac,
                                    np.ones(J, np.float32), rep0)
    w_0 = np.asarray(fednova_effective_weights(sizes, p, 2, 32,
                                               tau_frac=t0))
    np.testing.assert_allclose(w_0[5] / w_0[0], 1.0, rtol=1e-5)


# -- reputation update dynamics (unit) --------------------------------

def test_reputation_update_decay_and_recovery_rates():
    J = 4
    ones = np.ones(J, np.float32)
    rep = ones.copy()
    cos = np.asarray([0.9, 0.85, -0.9, 0.88], np.float32)
    # three rounds of a flipped client: geometric decay at `decay`
    for t in range(3):
        rep = np.asarray(reputation_update(rep, ones, ones, cos, ones,
                                           None, 3.0, 0.5))
    assert rep[2] == pytest.approx(0.125, abs=0.02)
    np.testing.assert_allclose(rep[[0, 1, 3]], 1.0, atol=0.02)
    # recovery: full evidence pulls rep back within O(1/(1-decay))
    good = np.abs(cos)
    for t in range(2):
        rep = np.asarray(reputation_update(rep, ones, ones, good, ones,
                                           None, 3.0, 0.5))
    assert rep[2] > 0.75
    # an absent client's reputation is frozen either way
    reported = np.asarray([1, 1, 1, 0], np.float32)
    before = rep.copy()
    rep2 = np.asarray(reputation_update(rep, reported, reported, cos,
                                        reported, None, 3.0, 0.5))
    assert rep2[3] == before[3]
    # a non-finite reporter (scoreable=0) earns zero evidence
    scoreable = np.asarray([0, 1, 1, 1], np.float32)
    rep3 = np.asarray(reputation_update(ones, ones, scoreable, good,
                                        ones, None, 3.0, 0.5))
    assert rep3[0] == pytest.approx(0.5, abs=1e-5)


# -- end-to-end dynamics ----------------------------------------------

def test_persistent_flipper_converges_to_floor_and_stays_gated(
        setup_iid):
    R, J = 10, setup_iid.num_clients
    res = FedAvg(setup_iid, faults=sign_plan(R, J, 2),
                 robust_agg="rep:0.5:0.2", round=R, **KW)
    d = res["defense"]
    rep = d["reputation"]
    assert np.all(np.isfinite(res["test_loss"]))
    # geometric decay to (numerically) zero, never back above floor
    assert rep[2, 2] < 0.2
    assert np.all(rep[2:, 2] < 0.2)
    assert rep[-1, 2] < 0.01
    # gated from the round reputation crossed the floor, every round
    np.testing.assert_array_equal(d["rep_gated"][2:], 1)
    # honest clients keep (near-)full trust and are never gated
    honest = np.delete(rep[-1], 2)
    assert honest.min() > 0.5
    assert d["rep_gated"].max() <= 1


def test_transient_corruption_recovers_within_memory_horizon(
        setup_iid):
    """An honest client corrupted for rounds 0-2 only must regain
    weight within O(1/(1-decay)) rounds of the corruption ending:
    with decay=0.5 (memory ~2 rounds), reputation is back above the
    gate floor within 2 rounds and near full trust by the horizon."""
    R, J = 10, setup_iid.num_clients
    res = FedAvg(setup_iid,
                 faults=sign_plan(R, J, 2, rounds_active=slice(0, 3)),
                 robust_agg="rep:0.5:0.2", round=R, **KW)
    rep = res["defense"]["reputation"][:, 2]
    assert rep[2] < 0.2          # distrusted while corrupted
    assert rep[4] > 0.2          # back above the floor in <= 2 rounds
    assert rep[-1] > 0.9         # near-full trust by the horizon
    # and the gate actually lifted: no rep-gating in the tail
    np.testing.assert_array_equal(
        res["defense"]["rep_gated"][5:], 0)


def test_fedamw_rep_gate_zeroes_learned_mass(setup_iid):
    """Reputation gates the present mask BEFORE the p-solve (same
    mechanism as krum selection / dropout), so a gated client's
    learned mixture weight is masked to exactly zero and stays there
    while gated."""
    R, J = 8, setup_iid.num_clients
    kw = dict(lambda_reg=1e-4, lr_p=1e-3, round=R, **KW)
    res = FedAMW(setup_iid, faults=sign_plan(R, J, 2),
                 robust_agg="rep:0.5:0.2", return_state=True, **kw)
    assert np.all(np.isfinite(res["test_loss"]))
    assert res["defense"]["reputation"][-1, 2] < 0.2
    assert float(np.asarray(res["p"])[2]) == 0.0
    # the undefended run keeps nonzero mass on the attacker — the
    # zero is the gate's doing, not the solver's
    und = FedAMW(setup_iid, faults=sign_plan(R, J, 2),
                 return_state=True, **kw)
    assert float(np.asarray(und["p"])[2]) != 0.0


def test_lie_attack_clamped_and_defended_run_tracks_clean(setup_het):
    """The e2e attack closure on FedNova: a full-work client claiming
    frac=0.01 drags the undefended run far from clean (its per-step
    weight is ~100x); under `rep` the claim is clamped every round
    (frac_clamped telemetry) and the defended trajectory stays close
    to the clean one."""
    R, J = 6, setup_het.num_clients
    plan = lie_plan(R, J, 2)
    clean = FedNova(setup_het, return_state=True, round=R, **KW)
    lied = FedNova(setup_het, faults=plan, return_state=True, round=R,
                   **KW)
    defended = FedNova(setup_het, faults=plan,
                       robust_agg="rep:0.5:0.2", return_state=True,
                       round=R, **KW)
    assert np.all(np.isfinite(defended["test_loss"]))
    np.testing.assert_array_equal(
        defended["defense"]["frac_clamped"], np.full(R, 1))
    np.testing.assert_array_equal(
        lied["fault_counts"]["lied"], np.full(R, 1))
    cw = np.asarray(clean["params"]["w"])
    err_lied = np.linalg.norm(np.asarray(lied["params"]["w"]) - cw)
    err_dfd = np.linalg.norm(np.asarray(defended["params"]["w"]) - cw)
    assert err_dfd < err_lied


# -- quarantine:auto --------------------------------------------------

def test_quarantine_auto_catches_scale_attack_like_static(setup_het):
    """A 25x-scaled client z-scores far beyond the auto threshold
    every round; the quarantine folds into the same present mask, so
    the run is array-equal to the same run with that client cleanly
    dropped — and the threshold telemetry stays inside the clip
    band."""
    R, J = 3, setup_het.num_clients
    plan = sign_plan(R, J, 2)
    plan.scale[:, 2] = 25.0
    res = FedAvg(setup_het, faults=plan, robust_agg="quarantine:auto",
                 return_state=True, round=R, **KW)
    d = res["defense"]
    assert d["robust_agg"] == "quarantine:auto"
    np.testing.assert_array_equal(d["z_quarantined"], np.full(R, 1))
    thr = np.asarray(d["z_threshold"], float)
    assert thr[0] == pytest.approx(5.0)  # the hand-tuned start
    assert np.all((thr >= Z_AUTO_MIN) & (thr <= Z_AUTO_MAX))
    z = np.zeros((R, J), np.float32)
    drop = z.copy()
    drop[:, 2] = 1
    dropped = FedAvg(setup_het,
                     faults=FaultPlan(drop, z, z.copy(),
                                      np.ones((R, J), np.float32),
                                      z.copy(), z.copy()),
                     return_state=True, round=R, **KW)
    np.testing.assert_array_equal(np.asarray(res["params"]["w"]),
                                  np.asarray(dropped["params"]["w"]))
    np.testing.assert_array_equal(res["test_acc"], dropped["test_acc"])


def test_quarantine_auto_spares_clean_run(setup_het):
    """No faults: the adaptive threshold must never fire on honest
    heterogeneity (digits tops out near z ~ 3.3; the threshold starts
    at 5 and its running clean-quantile basis keeps it above the
    observed max), leaving the run bitwise the clean run."""
    R = 6
    clean = FedAvg(setup_het, return_state=True, round=R, **KW)
    res = FedAvg(setup_het, robust_agg="quarantine:auto",
                 return_state=True, round=R, **KW)
    assert res["defense"]["z_quarantined"].sum() == 0
    np.testing.assert_array_equal(np.asarray(res["params"]["w"]),
                                  np.asarray(clean["params"]["w"]))
    thr = np.asarray(res["defense"]["z_threshold"], float)
    assert np.all(thr > np.asarray(res["defense"]["z_max"]).max())


# -- telemetry / reporting --------------------------------------------

def test_defense_report_carries_reputation_and_threshold(setup_het):
    from fedamw_tpu.utils.reporting import (defense_summary,
                                            format_defense_report,
                                            format_fault_report)

    R, J = 6, setup_het.num_clients
    res = FedAvg(setup_het, faults=lie_plan(R, J, 2),
                 robust_agg="rep:0.5:0.2+quarantine:auto", round=R,
                 **KW)
    d = res["defense"]
    assert d["reputation"].shape == (R, J)
    s = defense_summary(d)
    assert s["robust_agg"] == "quarantine:auto+rep:0.5:0.2"
    assert 0.0 <= s["rep_final_mean"] <= 1.0
    assert s["total_frac_clamped"] >= R  # the liar, every round
    assert s["z_threshold_first"] == pytest.approx(5.0)
    line = format_defense_report("FedAvg", d)
    assert "reputation:" in line and "auto z threshold" in line
    assert "work-fraction claims clamped" in line
    fline = format_fault_report("FedAvg", res["fault_counts"])
    assert "lied-frac" in fline
    # fault_summary tolerates pre-PR-4 records without a "lied" key
    from fedamw_tpu.utils.reporting import fault_summary
    legacy = {k: v for k, v in res["fault_counts"].items()
              if k != "lied"}
    assert "total_lied" not in fault_summary(legacy)


# -- checkpoint persistence (ISSUE 6 satellite) -----------------------

def test_reputation_roundtrips_through_checkpoint(tmp_path, setup_het):
    """Prefix + checkpoint (reputation included) + resume == the
    uninterrupted run, bitwise — including the reputation trajectory
    itself. Without persistence, a resumed run would restart the
    sign-flipper at full trust; with it, the flipper stays distrusted
    across the boundary."""
    from fedamw_tpu.utils.checkpoint import (load_checkpoint,
                                             save_checkpoint)

    R, J = 6, setup_het.num_clients
    plan = sign_plan(R, J, 2)
    kw = dict(faults=plan, robust_agg="rep:0.5:0.2",
              return_state=True, **KW)
    full = FedAvg(setup_het, round=R, **kw)
    prefix = FedAvg(setup_het, round=R, stop_round=3, **kw)
    # the flipper is already below full trust at the boundary
    assert prefix["reputation"][2] < 1.0
    save_checkpoint(str(tmp_path / "ck"), prefix["params"],
                    round_idx=3, reputation=prefix["reputation"])
    state = load_checkpoint(str(tmp_path / "ck"))
    np.testing.assert_array_equal(
        np.asarray(state["reputation"], np.float32),
        np.asarray(prefix["reputation"], np.float32))
    resumed = FedAvg(setup_het, round=R, start_round=3,
                     resume_from=state, **kw)
    np.testing.assert_array_equal(np.asarray(resumed["test_acc"]),
                                  np.asarray(full["test_acc"])[3:])
    np.testing.assert_array_equal(
        np.asarray(resumed["defense"]["reputation"]),
        np.asarray(full["defense"]["reputation"])[3:])
    np.testing.assert_array_equal(np.asarray(resumed["reputation"]),
                                  np.asarray(full["reputation"]))


def test_auto_threshold_zq_roundtrips_through_checkpoint(tmp_path,
                                                         setup_het):
    """ISSUE 7 satellite: the `quarantine:auto` threshold estimate
    (the carried clean-z quantile `zq`) persists through checkpoints
    alongside reputation — prefix + save_checkpoint(defense_state=
    {'zq': ...}) + resume reproduces the uninterrupted run's threshold
    trajectory bitwise. Before this, a resumed auto-threshold run
    re-tuned from the Z=5 start (the ROADMAP carried follow-on)."""
    from fedamw_tpu.utils.checkpoint import (load_checkpoint,
                                             save_checkpoint)

    R = 6
    kw = dict(robust_agg="quarantine:auto", return_state=True, **KW)
    full = FedAvg(setup_het, round=R, **kw)
    prefix = FedAvg(setup_het, round=R, stop_round=3, **kw)
    # the estimate has moved off its Z_AUTO_INIT start by the boundary
    # (otherwise this test could pass without any carry at all)
    from fedamw_tpu.fedcore.robust import Z_AUTO_INIT
    assert np.float32(prefix["zq"]) != np.float32(Z_AUTO_INIT)
    save_checkpoint(str(tmp_path / "ck"), prefix["params"], round_idx=3,
                    defense_state={"zq": prefix["zq"]})
    state = load_checkpoint(str(tmp_path / "ck"))
    # the stored estimate round-trips bitwise through either layout
    np.testing.assert_array_equal(
        np.asarray(state["defense_state"]["zq"], np.float32),
        np.asarray(prefix["zq"], np.float32))
    resumed = FedAvg(setup_het, round=R, start_round=3,
                     resume_from=state, **kw)
    # the stitched threshold trajectory IS the uninterrupted one
    np.testing.assert_array_equal(
        np.asarray(resumed["defense"]["z_threshold"]),
        np.asarray(full["defense"]["z_threshold"])[3:])
    np.testing.assert_array_equal(np.asarray(resumed["zq"]),
                                  np.asarray(full["zq"]))
    np.testing.assert_array_equal(np.asarray(resumed["test_acc"]),
                                  np.asarray(full["test_acc"])[3:])


def test_resume_auto_without_zq_warns_and_retunes_from_start(
        setup_het):
    """The legacy-checkpoint path: resuming a quarantine:auto run from
    a state without 'zq' re-tunes from the Z=5 operating point — loud
    (a warning naming the fix), not silent."""
    R = 6
    kw = dict(robust_agg="quarantine:auto", return_state=True, **KW)
    prefix = FedAvg(setup_het, round=R, stop_round=3, **kw)
    with pytest.warns(UserWarning, match="zq"):
        resumed = FedAvg(setup_het, round=R, start_round=3,
                         resume_from={"params": prefix["params"]}, **kw)
    # restarted estimate: the first resumed threshold is back at the
    # hand-tuned start, ABOVE the prefix's already-tightened carry
    thr0 = float(np.asarray(resumed["defense"]["z_threshold"])[0])
    assert thr0 == pytest.approx(5.0)
    assert thr0 > float(np.asarray(
        prefix["defense"]["z_threshold"])[-1])


def test_resume_rejects_non_scalar_zq(setup_het):
    prefix = FedAvg(setup_het, round=4, stop_round=2,
                    robust_agg="quarantine:auto", return_state=True,
                    **KW)
    with pytest.raises(ValueError, match="scalar"):
        FedAvg(setup_het, round=4, start_round=2,
               resume_from={"params": prefix["params"],
                            "zq": np.ones(3, np.float32)},
               robust_agg="quarantine:auto", **KW)


def test_resume_without_reputation_warns_and_restarts_trust(setup_het):
    """The legacy-checkpoint path: resuming a rep-defended run from a
    state without 'reputation' restarts everyone at full trust — loud
    (a warning naming the fix), not silent."""
    R, J = 6, setup_het.num_clients
    plan = sign_plan(R, J, 2)
    kw = dict(faults=plan, robust_agg="rep:0.5:0.2",
              return_state=True, **KW)
    prefix = FedAvg(setup_het, round=R, stop_round=3, **kw)
    with pytest.warns(UserWarning, match="reputation"):
        resumed = FedAvg(setup_het, round=R, start_round=3,
                         resume_from={"params": prefix["params"]}, **kw)
    # restarted trust: round-3 reputation re-decays from 1.0, so the
    # flipper is MORE trusted than in the carried prefix state
    assert resumed["defense"]["reputation"][0][2] > \
        prefix["reputation"][2]


def test_resume_rejects_cohort_size_mismatch(setup_het):
    R = 4
    prefix = FedAvg(setup_het, round=R, stop_round=2,
                    robust_agg="rep", return_state=True, **KW)
    with pytest.raises(ValueError, match="cohort"):
        FedAvg(setup_het, round=R, start_round=2,
               resume_from={"params": prefix["params"],
                            "reputation": np.ones(3, np.float32)},
               robust_agg="rep", **KW)


def test_rep_soft_only_mode_downweights_without_gating(setup_het):
    """floor=0 is soft-only: nobody is ever hard-gated, but the
    flipper's reputation (and so its relative weight) still sinks —
    the run differs from the undefended one and stays finite."""
    R, J = 6, setup_het.num_clients
    plan = sign_plan(R, J, 2)
    res = FedAvg(setup_het, faults=plan, robust_agg="rep:0.5:0",
                 return_state=True, round=R, **KW)
    assert np.all(np.isfinite(res["test_loss"]))
    d = res["defense"]
    assert d["rep_gated"].sum() == 0
    rep = d["reputation"][-1]
    assert rep[2] < np.delete(rep, 2).min()
    und = FedAvg(setup_het, faults=plan, return_state=True, round=R,
                 **KW)
    assert not np.array_equal(np.asarray(res["params"]["w"]),
                              np.asarray(und["params"]["w"]))


# -- quarantine:auto bounded threshold drift (PR 8 satellite) ----------

def _threshold_trajectory(basis_fn, honest_z, rounds, park=0.98):
    """Simulate the carried-zq recursion (algorithms.core.guard_faults'
    exact EWMA/clip arithmetic, host-side) under a PATIENT attacker
    that parks its z at ``park`` x the CURRENT threshold every round —
    always clean, always the clean max. ``basis_fn(z, clean, zq)`` is
    the per-round threshold basis under test."""
    from fedamw_tpu.fedcore.robust import Z_AUTO_BETA, Z_AUTO_INIT, \
        Z_AUTO_MARGIN
    zq, thresholds = Z_AUTO_INIT, []
    for _ in range(rounds):
        thr = float(np.clip(Z_AUTO_MARGIN * zq, Z_AUTO_MIN, Z_AUTO_MAX))
        thresholds.append(thr)
        z = np.append(honest_z, park * thr).astype(np.float32)
        clean = (z <= thr).astype(np.float32)
        q = float(basis_fn(z, clean, zq))
        zq = (1.0 - Z_AUTO_BETA) * zq + Z_AUTO_BETA * q
    return np.asarray(thresholds)


def test_patient_attacker_cannot_ratchet_trimmed_threshold():
    """The attack trajectory the ROADMAP carried follow-on names: a
    just-under-threshold attacker is the clean MAX by construction, so
    the OLD untrimmed max basis lets it drag the running estimate —
    and the threshold — all the way to Z_AUTO_MAX, widening its own
    headroom every round. Under the rise-capped basis its UPWARD pull
    is bounded by the gap over the honest runner-up, so the threshold
    never exceeds its start and settles no higher than
    Z_AUTO_MARGIN * Z_AUTO_TRIM_GAP x the honest max."""
    from fedamw_tpu.fedcore.robust import (Z_AUTO_MARGIN,
                                           Z_AUTO_TRIM_GAP,
                                           _masked_vector_quantile,
                                           trimmed_clean_basis)
    honest = np.array([0.5, 0.9, 1.3, 1.8, 2.2], np.float32)
    untrimmed = _threshold_trajectory(
        lambda z, c, _zq: _masked_vector_quantile(
            np.asarray(z), np.asarray(c), 1.0), honest, rounds=200)
    trimmed = _threshold_trajectory(trimmed_clean_basis, honest,
                                    rounds=200)
    # the drift: the untrimmed threshold ratchets to the hard cap
    assert untrimmed[-1] == Z_AUTO_MAX
    # the bound: the attacker can never RAISE the capped threshold —
    # it holds at its starting operating point instead of ratcheting,
    # and the attacker never earns one point of extra headroom
    assert trimmed.max() <= trimmed[0] + 1e-4
    assert np.all(np.diff(trimmed[50:]) <= 1e-6)  # no late ratchet
    # recovery: once the attacker leaves, the honest folds tighten the
    # threshold toward the contract's honest ceiling
    from fedamw_tpu.fedcore.robust import Z_AUTO_BETA
    zq = trimmed[-1] / Z_AUTO_MARGIN
    for _ in range(100):
        q = float(trimmed_clean_basis(
            honest, np.ones_like(honest), zq))
        zq = (1.0 - Z_AUTO_BETA) * zq + Z_AUTO_BETA * q
    settled = np.clip(Z_AUTO_MARGIN * zq, Z_AUTO_MIN, Z_AUTO_MAX)
    bound = Z_AUTO_MARGIN * Z_AUTO_TRIM_GAP * float(honest.max())
    assert settled <= bound + 1e-3


def test_trimmed_basis_honest_cohort_untouched():
    """A clean max at or below the carried estimate (or within the
    gap of its runner-up) passes through RAW — honest cohorts keep the
    pre-trim threshold dynamics; the cap bites only on a separated
    top score trying to pull the estimate UP."""
    from fedamw_tpu.fedcore.robust import (Z_AUTO_TRIM_GAP,
                                           trimmed_clean_basis)
    z = np.array([0.5, 1.6, 2.0, 2.4], np.float32)
    clean = np.ones(4, np.float32)
    assert float(trimmed_clean_basis(z, clean, 10 / 3)) == \
        pytest.approx(2.4)
    # a separated top trying to RAISE the estimate is capped at
    # max(gap x runner-up, the carried estimate)
    z_sep = np.array([0.5, 1.0, 1.2, 4.0], np.float32)
    assert float(trimmed_clean_basis(z_sep, clean, 1.0)) == \
        pytest.approx(Z_AUTO_TRIM_GAP * 1.2)
    assert float(trimmed_clean_basis(z_sep, clean, 3.0)) == \
        pytest.approx(3.0)  # never below the carried estimate
    # ...but the basis follows the raw max DOWN freely (one-sided cap)
    assert float(trimmed_clean_basis(z, clean, 3.0)) == \
        pytest.approx(2.4)
    # quarantined (non-clean) entries never enter the basis
    mask = np.array([1, 1, 1, 0], np.float32)
    assert float(trimmed_clean_basis(z_sep, mask, 1.0)) == \
        pytest.approx(1.2)
    # a single clean score has no runner-up to trim against
    one = np.array([0, 0, 0, 1], np.float32)
    assert float(trimmed_clean_basis(z_sep, one, 1.0)) == \
        pytest.approx(4.0)


def test_auto_threshold_trim_is_wired_into_the_round_scan(setup_het):
    """Wiring pin (measured): the s=2 scale attacker's round-0 z
    (~3.5) lands UNDER the initial Z=5 threshold — the one clean round
    of a would-be patient attack. The rise-capped basis refuses to
    fold that separated score upward (cap = max(gap x honest
    runner-up ~1.5, the carried 10/3)), so the round-1 threshold
    cannot exceed 5.0; the untrimmed max basis would fold the
    attacker's 3.5 and RAISE it (1.5 * (0.9*10/3 + 0.1*3.5) ~ 5.03).
    The trajectory staying at/below 5 with a near-threshold clean max
    on record is therefore the cap demonstrably running inside the
    jitted scan."""
    R, J = 12, setup_het.num_clients
    z = np.zeros((R, J), np.float32)
    corrupt = z.copy()
    corrupt[:, 2] = 1
    scale = np.ones((R, J), np.float32)
    scale[:, 2] = 2.0
    plan = FaultPlan(z, z.copy(), corrupt, scale, z.copy(), z.copy())
    res = FedAvg(setup_het, faults=plan, robust_agg="quarantine:auto",
                 round=R, lr=0.5, epoch=1, seed=0, lr_mode="constant")
    d = res["defense"]
    # round 0: the attacker is clean (just under the hand-tuned start)
    assert 3.0 < d["z_max"][0] < 5.0
    assert d["z_threshold"][0] == pytest.approx(5.0)
    # the near-threshold clean score never RAISES the threshold
    # (untrimmed: round 1 lands at ~5.03 > 5)
    assert d["z_threshold"][1] <= 5.0 + 1e-5
    assert np.asarray(d["z_threshold"]).max() <= 5.0 + 1e-5
    # and the honest folds still tighten it downward afterwards
    assert d["z_threshold"][-1] < 4.0


# -- krum selection as reputation evidence (ISSUE 18) -----------------

def test_reputation_update_krum_channel_math():
    """The selection channel is exact: a deselected CANDIDATE keeps
    KRUM_DESEL_EROSION of its evidence, selected candidates and
    non-candidates are untouched, and omitting the channel is the
    pre-ISSUE-18 update bitwise."""
    J = 4
    ones = np.ones(J, np.float32)
    good = np.full(J, 0.9, np.float32)
    sel = np.asarray([1, 0, 0, 1], np.float32)
    cand = np.asarray([1, 1, 0, 0], np.float32)
    rep = np.asarray(reputation_update(ones, ones, ones, good, ones,
                                       None, 3.0, 0.5, sel=sel,
                                       sel_cand=cand))
    # client 1: deselected candidate -> evidence 1 - EROSION = 0.5,
    # rep = 0.5 * 1 + 0.5 * 0.5
    assert rep[1] == pytest.approx(
        0.5 + 0.5 * (1.0 - KRUM_DESEL_EROSION), abs=1e-5)
    # selected candidate (0) and both non-candidates (2, 3) keep full
    # evidence — deselection only means something to considered clients
    np.testing.assert_allclose(rep[[0, 2, 3]], 1.0, atol=1e-5)
    plain = np.asarray(reputation_update(ones, ones, ones, good, ones,
                                         None, 3.0, 0.5))
    np.testing.assert_array_equal(
        np.asarray(reputation_update(ones, ones, ones, good, ones,
                                     None, 3.0, 0.5, sel=None)), plain)


def test_krum_verdicts_feed_reputation_one_round_delayed(setup_iid):
    """Fixed path e2e: under `rep+mkrum` the aggregator's selection
    verdict becomes next round's evidence. Round 0 reputation is
    IDENTICAL to the mkrum-free run (the carry starts with no verdict
    — the one-round delay), the flipper is deselected every round, and
    its reputation decays to the floor and stays gated."""
    R, J = 8, setup_iid.num_clients
    plan = sign_plan(R, J, 2)
    with_k = FedAvg(setup_iid, faults=plan,
                    robust_agg="rep:0.5:0.2+mkrum:7", round=R, **KW)
    plain = FedAvg(setup_iid, faults=plan, robust_agg="rep:0.5:0.2",
                   round=R, **KW)
    dk = with_k["defense"]
    assert np.all(np.isfinite(with_k["test_loss"]))
    # the distance selector rejects the sign flip from round 0 on
    np.testing.assert_array_equal(dk["krum_selected"][:, 2], 0)
    # one-round delay: round 0's EWMA ran before any verdict existed
    np.testing.assert_allclose(dk["reputation"][0],
                               plain["defense"]["reputation"][0],
                               atol=1e-6)
    # decay to the floor, honest clients keep near-full trust (mkrum:7
    # deselects exactly one client — the flipper — so no honest client
    # ever pays the erosion)
    assert dk["reputation"][-1, 2] < 0.2
    assert np.delete(dk["reputation"][-1], 2).min() > 0.5
    np.testing.assert_array_equal(
        np.delete(dk["krum_selected"], 2, axis=1), 1)


def test_krum_verdicts_feed_reputation_on_learned_path(setup_iid):
    """Learned path e2e: FedAMW's present-mask krum fold records the
    same verdict stream — the flipper is deselected, its reputation
    decays below the floor, and its learned mixture mass is exactly
    zero (selection AND the rep gate both fold into the mask the
    p-solve sees)."""
    R, J = 8, setup_iid.num_clients
    res = FedAMW(setup_iid, faults=sign_plan(R, J, 2),
                 robust_agg="rep:0.5:0.2+mkrum:7", lambda_reg=1e-4,
                 lr_p=1e-3, return_state=True, round=R, **KW)
    d = res["defense"]
    assert np.all(np.isfinite(res["test_loss"]))
    np.testing.assert_array_equal(d["krum_selected"][:, 2], 0)
    assert d["reputation"][-1, 2] < 0.2
    assert float(np.asarray(res["p"])[2]) == 0.0
