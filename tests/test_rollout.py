"""The online train->serve loop (ISSUE 6): versioned registry, hot
weight swap, shadow/A-B rollout.

Load-bearing contracts:

- **Zero-recompile hot swap**: ``compile_count`` stays flat across >= 3
  ``swap_weights`` under live traffic (the bucket ladder is compiled
  once; weights are jit arguments), and swap-incompatible weights are
  refused BEFORE anything changes.
- **Deterministic split**: shadow/A-B assignment is a pure function of
  the request id (crc32, stable across processes), monotone in the
  fraction.
- **Gated traffic**: a candidate takes traffic only after the offline
  parity gate passes (``engine_acc == evaluate_acc``); a gate failure
  retires the candidate and the prior version never stops serving
  (rollback pin). The live-traffic error budget rolls a flaky
  candidate back, with A/B callers transparently answered from the
  live version.
- **Observability**: every request span carries ``model_version`` and
  ``staleness_rounds``; the metrics snapshot carries the swap/canary
  counters and per-version served split.
- **Atomicity**: a swap is atomic w.r.t. batch dispatch — under
  concurrent submit + rapid swaps every result is EXACTLY one
  installed version's output (params and rff can never mix), and a
  retried request re-resolves the live version (a request queued
  against version k must not dispatch against a half-swapped engine).
"""

import threading
import time

import numpy as np
import pytest

from fedamw_tpu.serving import (ModelRegistry, RolloutController,
                                ServingEngine, ServingService,
                                assigned_to_candidate, split_key)
from fedamw_tpu.utils.trace import Tracer

D, C = 16, 3


def base_params(scale=1.0, seed=0):
    rng = np.random.RandomState(seed)
    return {"w": (scale * rng.randn(C, D)).astype(np.float32)}


def make_engine(buckets=(1, 8, 32), rff=False, **kw):
    rng = np.random.RandomState(1)
    r = None
    if rff:
        r = (rng.randn(8, D).astype(np.float32),
             rng.randn(D).astype(np.float32))
        kw.setdefault("params", {"w": rng.randn(C, D).astype(np.float32)})
    params = kw.pop("params", base_params())
    e = ServingEngine(params, rff=r, buckets=buckets, **kw)
    e.warmup()
    return e


# -- registry ---------------------------------------------------------

def test_registry_publish_get_latest_staleness():
    reg = ModelRegistry()
    assert reg.latest() is None and len(reg) == 0
    v1 = reg.publish(base_params(), round_idx=2,
                     metadata={"eval_acc": 91.25})
    v2 = reg.publish(base_params(2.0), round_idx=7)
    assert v2 == v1 + 1 and reg.versions() == [v1, v2]
    assert reg.latest().version == v2
    assert reg.get(v1).eval_acc == 91.25 and reg.get(v2).eval_acc is None
    # staleness: rounds the newest publish is ahead of a version
    assert reg.staleness_rounds(v1) == 5
    assert reg.staleness_rounds(v2) == 0
    assert reg.staleness_rounds(999) == 0  # unknown stays 0, not huge
    with pytest.raises(KeyError, match="not in registry"):
        reg.get(999)
    # withdrawing a gate-rejected publish stops it counting toward
    # everyone else's staleness
    assert reg.withdraw(v2) is True and reg.withdraw(v2) is False
    assert reg.staleness_rounds(v1) == 0


def test_registry_publish_checkpoint_carries_markers(tmp_path):
    from fedamw_tpu.utils.checkpoint import save_checkpoint

    rng = np.random.RandomState(3)
    rff = (rng.randn(8, D).astype(np.float32),
           rng.randn(D).astype(np.float32))
    save_checkpoint(str(tmp_path / "ck"), base_params(), p=np.ones(4) / 4,
                    round_idx=6, rff=rff, extra={"eval_acc": 88.5})
    reg = ModelRegistry()
    v = reg.publish_checkpoint(str(tmp_path / "ck"))
    entry = reg.get(v)
    assert entry.round_idx == 6 and entry.eval_acc == 88.5
    assert entry.source.startswith("checkpoint:")
    np.testing.assert_array_equal(entry.rff[0], rff[0])
    # the published params serve: straight into an engine (raw width
    # comes from the checkpointed draw: rff_W is (d_raw, D_features))
    engine = ServingEngine(entry.params, rff=entry.rff, buckets=(8,))
    assert engine.input_dim == rff[0].shape[0]


def test_registry_prune_keeps_protected():
    reg = ModelRegistry()
    vs = [reg.publish(base_params(), round_idx=k) for k in range(5)]
    removed = reg.prune(keep=2, protect=(vs[0],))
    assert vs[0] in reg and vs[-1] in reg
    assert len(reg) == 2 + 1 - 1  # keep=2 total, protected survives
    for v in removed:
        assert v not in reg


# -- hot swap ---------------------------------------------------------

def test_swap_zero_recompile_and_output_flip():
    engine = make_engine()
    cc = engine.compile_count
    X = np.random.RandomState(5).randn(4, D).astype(np.float32)
    out0 = engine.predict(X)
    for k in (2.0, 3.0, 4.0):  # >= 3 swaps, compile count pinned flat
        v = engine.swap_weights(base_params(k))
        np.testing.assert_allclose(engine.predict(X), k * out0,
                                   rtol=1e-5)
        assert engine.version == v
    assert engine.compile_count == cc
    assert engine.swap_count == 3
    # install-and-flip REPLACES: a swap-per-round loop holds ONE
    # version on device, not every generation it ever served
    assert engine.versions_installed == [engine.version]


def test_swap_rejects_incompatible_and_leaves_live_serving():
    engine = make_engine()
    X = np.random.RandomState(5).randn(2, D).astype(np.float32)
    want = engine.predict(X)
    with pytest.raises(ValueError, match="swap-incompatible"):
        engine.swap_weights({"w": np.zeros((C, D + 1), np.float32)})
    with pytest.raises(ValueError, match="structure differs"):
        engine.swap_weights({"w": want, "extra": want})
    with pytest.raises(ValueError, match="rff-ness"):
        engine.swap_weights(base_params(), rff=(
            np.zeros((8, D), np.float32), np.zeros(D, np.float32)))
    np.testing.assert_array_equal(engine.predict(X), want)
    assert engine.swap_count == 0


def test_auto_version_swap_never_clobbers_staged_candidate():
    """swap_weights(params) auto-versions past EVERY installed slot —
    a staged rollout candidate must survive a direct swap landing
    next to it."""
    engine = make_engine()
    X = np.random.RandomState(5).randn(2, D).astype(np.float32)
    out0 = engine.predict(X)
    engine.install_weights(1, base_params(5.0))  # staged candidate
    v = engine.swap_weights(base_params(2.0))
    assert v == 2  # past the staged slot, never onto it
    np.testing.assert_allclose(engine.predict(X, version=1), 5 * out0,
                               rtol=1e-5)
    np.testing.assert_allclose(engine.predict(X), 2 * out0, rtol=1e-5)


def test_router_slot_is_singular_and_detachable():
    engine = make_engine()
    reg = ModelRegistry()
    cand = reg.publish(base_params(2.0), round_idx=1)
    with ServingService(engine, max_wait_ms=0.5) as svc:
        a = RolloutController(svc, reg, mode="shadow", fraction=0.5,
                              min_requests=10 ** 6)
        assert a.stage(cand)
        # a second controller must not silently orphan A's rollout
        with pytest.raises(ValueError, match="already has a router"):
            RolloutController(svc, reg, mode="shadow", fraction=0.5)
        a.detach()  # rolls back the in-flight candidate, frees slot
        assert cand not in engine.versions_installed
        assert svc.router is None
        b = RolloutController(svc, reg, mode="shadow", fraction=0.5,
                              min_requests=0)
        assert b.stage(cand) and engine.version == cand


def test_min_agreement_is_shadow_only():
    """ab mode has no paired live outputs to measure agreement on —
    configuring the floor there must refuse loudly, not silently
    never enforce."""
    engine = make_engine()
    with ServingService(engine, max_wait_ms=0.5) as svc:
        with pytest.raises(ValueError, match="shadow-mode"):
            RolloutController(svc, ModelRegistry(), mode="ab",
                              min_agreement=0.9)


def test_parity_gate_dispatch_never_pollutes_worker_timings():
    """The controller's parity-gate predict runs on another thread;
    with record_timings=False it must not land in the pop_timings
    slot the serving worker attributes spans from."""
    engine = make_engine()
    X = np.random.RandomState(5).randn(4, D).astype(np.float32)
    engine.predict(X)  # worker-style call: populates the slot
    engine.install_weights(9, base_params(3.0))
    engine.predict(X, version=9, record_timings=False)
    t = engine.pop_timings()
    assert t is not None and t["version"] == engine.version  # not 9
    assert engine.pop_timings() is None


def test_install_retire_and_explicit_version_dispatch():
    engine = make_engine()
    X = np.random.RandomState(5).randn(3, D).astype(np.float32)
    out0 = engine.predict(X)
    engine.install_weights(7, base_params(2.0))
    # staged, not live: default dispatch unchanged, explicit reaches it
    np.testing.assert_array_equal(engine.predict(X), out0)
    np.testing.assert_allclose(engine.predict(X, version=7), 2 * out0,
                               rtol=1e-5)
    with pytest.raises(ValueError, match="live"):
        engine.retire(engine.version)
    # a staged (possibly gated) slot must not be silently replaced
    with pytest.raises(ValueError, match="already installed"):
        engine.install_weights(7, base_params(9.0))
    engine.retire(7)
    with pytest.raises(KeyError, match="not installed"):
        engine.predict(X, version=7)
    with pytest.raises(KeyError, match="not installed"):
        engine.retire(7)  # double-retire is a bug, not a no-op
    engine.install_weights(7, base_params(9.0))  # retire -> re-stage ok


# -- deterministic split ----------------------------------------------

def test_split_assignment_is_deterministic_and_monotone():
    ids = [f"req-{i}" for i in range(2000)]
    a1 = [assigned_to_candidate(i, 0.3) for i in ids]
    a2 = [assigned_to_candidate(i, 0.3) for i in ids]
    assert a1 == a2  # pure function of the id
    # monotone ramp: everyone at 0.3 is still assigned at 0.6
    a_wide = [assigned_to_candidate(i, 0.6) for i in ids]
    assert all(w for n, w in zip(a1, a_wide) if n)
    # edges and rough calibration
    assert not any(assigned_to_candidate(i, 0.0) for i in ids)
    assert all(assigned_to_candidate(i, 1.0) for i in ids)
    frac = np.mean(a1)
    assert 0.25 < frac < 0.35
    assert all(0.0 <= split_key(i) < 1.0 for i in ids)


def test_partition_preserves_order_and_covers_batch():
    from fedamw_tpu.serving import partition

    hit, miss = partition(list(range(10)), lambda x: x % 3 == 0)
    assert hit == [0, 3, 6, 9] and miss == [1, 2, 4, 5, 7, 8]
    assert partition([], lambda x: True) == ([], [])


def test_format_rollout_report_reads_like_a_verdict():
    from fedamw_tpu.utils.reporting import format_rollout_report

    line = format_rollout_report({
        "mode": "shadow", "swaps": 3, "swap_p50_ms": 0.4,
        "swap_max_ms": 5.6, "canary": "promoted", "canary_ms": 118.8,
        "rollback_drill": "rolled_back", "inflight_p95_ms": 9.5,
        "recompiles_during_swaps": 0, "final_version": 3,
        "staleness_rounds": 1})
    assert "3 swaps" in line and "canary promoted" in line
    assert "drill rolled_back" in line and "recompiles 0" in line
    assert "serving v3" in line


# -- rollout: gates, canary, rollback ---------------------------------

def _labels_for(engine, X):
    return np.argmax(engine.predict(X), -1)


def test_parity_gate_failure_rolls_back_and_live_keeps_serving():
    engine = make_engine()
    rng = np.random.RandomState(9)
    X = rng.randn(64, D).astype(np.float32)
    y = _labels_for(engine, X)  # live model scores 100 on its own labels
    reg = ModelRegistry()
    # sign-flipped weights published under the clean model's accuracy:
    # the gate must catch the lie before any traffic reaches them
    bad = reg.publish(base_params(-1.0), round_idx=1,
                      metadata={"eval_acc": 100.0})
    with ServingService(engine, max_wait_ms=0.5) as svc:
        ctl = RolloutController(svc, reg, mode="shadow", fraction=0.5,
                                min_requests=0, parity_data=(X, y))
        live_before = engine.version
        assert ctl.stage(bad) is False
        # prior version serving, candidate fully retired
        assert engine.version == live_before
        assert bad not in engine.versions_installed
        out = svc.predict(X[:4])
        np.testing.assert_array_equal(out, engine.predict(X[:4]))
    assert ctl.events[-1]["event"] == "rollback"
    assert ctl.events[-1]["gate"]["match"] is False
    assert svc.metrics.rollbacks == 1
    assert ctl.split() is None


def test_shadow_canary_promotes_after_budget_and_answers_from_live():
    engine = make_engine()
    rng = np.random.RandomState(11)
    X = rng.randn(64, D).astype(np.float32)
    y = _labels_for(engine, X)
    reg = ModelRegistry()
    # 2x weights: same argmax (gate passes, agreement 1.0), different
    # logits (so "answered from live" is distinguishable bitwise)
    cand = reg.publish(base_params(2.0), round_idx=3,
                       metadata={"eval_acc": 100.0})
    payload = X[:4]
    live_out = engine.predict(payload)
    with ServingService(engine, max_wait_ms=0.5) as svc:
        ctl = RolloutController(svc, reg, mode="shadow", fraction=1.0,
                                min_requests=10, error_budget=0,
                                min_agreement=0.99, parity_data=(X, y))
        assert ctl.stage(cand) is True
        assert engine.version != cand  # staged, not yet live
        pre = [svc.submit(payload) for _ in range(10)]
        for f in pre:
            # shadow phase: every caller answered from the LIVE version
            # even though its request was mirrored to the candidate
            out = f.result(timeout=30)
            if engine.version != cand:  # before the flip lands
                np.testing.assert_array_equal(out, live_out)
        deadline = time.perf_counter() + 30
        while engine.version != cand and time.perf_counter() < deadline:
            time.sleep(0.005)
        assert engine.version == cand  # canary promoted
        post = svc.predict(payload)
        np.testing.assert_allclose(post, 2 * live_out, rtol=1e-5)
        snap = svc.metrics.snapshot(engine)
    assert snap["model_version"] == cand
    assert snap["weight_swaps"] == 1
    assert snap["shadow_requests"] >= 10
    assert snap["candidate_errors"] == 0 and snap["rollbacks"] == 0
    assert ctl.events[-1]["event"] == "promoted"
    assert ctl.events[-1]["agreement"] == 1.0


class _CandidateFails(ServingEngine):
    """Candidate-version dispatches raise; live dispatches serve."""

    fail_version = None

    def predict(self, X, version=None):
        if version is not None and version == self.fail_version:
            raise RuntimeError("candidate weights exploded")
        return super().predict(X, version=version)


def test_error_budget_rollback_with_live_fallback_in_ab_mode():
    rng = np.random.RandomState(1)
    engine = _CandidateFails(base_params(), buckets=(1, 8, 32))
    engine.warmup()
    reg = ModelRegistry()
    cand = reg.publish(base_params(2.0), round_idx=1)
    engine.fail_version = cand
    payload = rng.randn(2, D).astype(np.float32)
    live_out = engine.predict(payload)
    with ServingService(engine, max_wait_ms=0.5) as svc:
        ctl = RolloutController(svc, reg, mode="ab", fraction=1.0,
                                min_requests=1000, error_budget=3)
        assert ctl.stage(cand) is True
        futs = [svc.submit(payload) for _ in range(8)]
        for f in futs:
            # every A/B caller transparently falls back to the live
            # version — a broken canary never surfaces as an error
            np.testing.assert_array_equal(f.result(timeout=30),
                                          live_out)
        deadline = time.perf_counter() + 30
        while ctl.split() is not None and time.perf_counter() < deadline:
            time.sleep(0.005)
        snap = svc.metrics.snapshot(engine)
    assert ctl.split() is None  # rolled back, not promoted
    assert engine.version != cand
    assert cand not in engine.versions_installed
    assert snap["candidate_errors"] > 3
    assert snap["rollbacks"] == 1
    assert ctl.events[-1]["event"] == "rollback"
    assert "error budget" in ctl.events[-1]["reason"]


def test_ab_mode_serves_candidate_slice_by_request_id():
    engine = make_engine()
    rng = np.random.RandomState(13)
    reg = ModelRegistry()
    cand = reg.publish(base_params(2.0), round_idx=1)
    payload = rng.randn(2, D).astype(np.float32)
    live_out = engine.predict(payload)
    with ServingService(engine, max_wait_ms=0.5) as svc:
        ctl = RolloutController(svc, reg, mode="ab", fraction=0.5,
                                min_requests=10 ** 6)  # never promotes
        assert ctl.stage(cand) is True
        futs = [svc.submit(payload) for _ in range(40)]
        for f in futs:
            out = f.result(timeout=30)
            if assigned_to_candidate(f.request_id, 0.5):
                np.testing.assert_allclose(out, 2 * live_out, rtol=1e-5)
            else:
                np.testing.assert_array_equal(out, live_out)
        snap = svc.metrics.snapshot(engine)
    by_ver = snap["requests_by_version"]
    assert set(by_ver) == {str(engine.version), str(cand)}
    assert sum(by_ver.values()) == 40
    ctl.rollback("test done")


def test_stage_gate_exception_retires_candidate_and_allows_retry():
    """A parity gate that cannot RUN (malformed parity data here; a
    transient backend blip in production) must not leak the installed
    candidate — the same version number must be re-stageable once the
    problem clears."""
    engine = make_engine()
    rng = np.random.RandomState(9)
    reg = ModelRegistry()
    cand = reg.publish(base_params(2.0), round_idx=1,
                       metadata={"eval_acc": 100.0})
    bad_width = rng.randn(8, D + 3).astype(np.float32)
    with ServingService(engine, max_wait_ms=0.5) as svc:
        ctl = RolloutController(svc, reg, mode="shadow", fraction=0.5,
                                min_requests=10 ** 6,
                                parity_data=(bad_width, np.zeros(8)))
        with pytest.raises(ValueError, match="expected"):
            ctl.stage(cand)
        assert cand not in engine.versions_installed  # no leak
        assert ctl.split() is None
        # retry with usable parity data: the slot was cleaned up, so
        # staging the SAME version must not raise "already installed"
        # (2x weights share the live argmax, so the gate passes)
        X = rng.randn(64, D).astype(np.float32)
        ctl.parity_data = (X, _labels_for(engine, X))
        assert ctl.stage(cand) is True
        ctl.rollback("test done")


def test_snapshot_staleness_tracks_registry_after_swaps_stop():
    """The falling-behind signal: once promoted, a service that never
    swaps again must still watch its staleness grow as training
    publishes new rounds."""
    engine = make_engine()
    rng = np.random.RandomState(11)
    X = rng.randn(64, D).astype(np.float32)
    y = _labels_for(engine, X)
    reg = ModelRegistry()
    cand = reg.publish(base_params(2.0), round_idx=3,
                       metadata={"eval_acc": 100.0})
    with ServingService(engine, max_wait_ms=0.5) as svc:
        ctl = RolloutController(svc, reg, mode="shadow", fraction=0.5,
                                min_requests=0, parity_data=(X, y))
        assert ctl.stage(cand) and engine.version == cand
        assert svc.metrics.snapshot(engine)["staleness_rounds"] == 0
        reg.publish(base_params(3.0), round_idx=10)  # training moves on
        snap = svc.metrics.snapshot(engine)
    assert snap["staleness_rounds"] == 7  # live at read time, not swap


def test_registry_seeded_engine_reports_staleness_before_any_swap(
        tmp_path):
    """The never-swapped window: an engine seeded with its REGISTRY
    version (the documented load(version=) flow) watches itself fall
    behind as training publishes, before any rollout ever runs."""
    from fedamw_tpu.utils.checkpoint import save_checkpoint

    save_checkpoint(str(tmp_path / "ck"), base_params(), round_idx=2,
                    extra={"eval_acc": 50.0})
    reg = ModelRegistry()
    live_v = reg.publish_checkpoint(str(tmp_path / "ck"))
    engine = ServingEngine.load(str(tmp_path / "ck"), buckets=(1, 8),
                                version=live_v)
    engine.warmup()
    with ServingService(engine, max_wait_ms=0.5) as svc:
        RolloutController(svc, reg, mode="shadow", fraction=0.5,
                          min_requests=10 ** 6)
        assert svc.metrics.snapshot(engine)["staleness_rounds"] == 0
        reg.publish(base_params(2.0), round_idx=9)
        snap = svc.metrics.snapshot(engine)
    assert snap["model_version"] == live_v
    assert snap["staleness_rounds"] == 7  # behind, with zero swaps


def test_snapshot_counts_broken_staleness_lookup():
    """GL006 regression (graftlint): a raising ``staleness_of`` keeps
    degrading to the swap-time value — but the failure is COUNTED
    (``staleness_errors``), never silently swallowed; a dead registry
    hookup must not read as a permanently-current service."""
    from fedamw_tpu.serving import ServeMetrics

    m = ServeMetrics()
    m.record_swap(version=3, staleness_rounds=2)

    def broken(_version):
        raise KeyError("registry lost the version")

    m.staleness_of = broken
    snap = m.snapshot()
    assert snap["staleness_rounds"] == 2  # swap-time value survives
    assert snap["staleness_errors"] == 1
    assert m.snapshot()["staleness_errors"] == 2  # counts per lookup
    m.staleness_of = lambda v: 9  # recovered source wins again
    snap = m.snapshot()
    assert snap["staleness_rounds"] == 9
    assert snap["staleness_errors"] == 2  # no new error


def test_span_staleness_counts_broken_router_lookup():
    """GL006 regression (graftlint): a router whose
    ``staleness_rounds`` raises must not take the request span down —
    the span reports staleness 0 and the failure lands in
    ``staleness_errors``."""
    engine = make_engine()
    rng = np.random.RandomState(13)
    X = rng.randn(4, D).astype(np.float32)
    tracer = Tracer(enabled=True)

    class _BrokenRouter:
        def split(self):
            return None

        def staleness_rounds(self, version):
            raise RuntimeError("registry connection lost")

    with ServingService(engine, max_wait_ms=0.5, tracer=tracer) as svc:
        svc.router = _BrokenRouter()
        out = svc.predict(X)
    assert out.shape == (4, C)
    spans = [r for r in tracer.records() if r["kind"] == "span"
             and r["name"] == "request"]
    assert len(spans) == 1  # the span still landed
    assert spans[0]["attrs"]["staleness_rounds"] == 0
    assert svc.metrics.staleness_errors >= 1


def test_second_concurrent_stage_is_refused():
    engine = make_engine()
    reg = ModelRegistry()
    v1 = reg.publish(base_params(2.0), round_idx=1)
    v2 = reg.publish(base_params(3.0), round_idx=2)
    with ServingService(engine, max_wait_ms=0.5) as svc:
        ctl = RolloutController(svc, reg, mode="shadow", fraction=0.5,
                                min_requests=10 ** 6)
        assert ctl.stage(v1)
        with pytest.raises(RuntimeError, match="in flight"):
            ctl.stage(v2)
        ctl.rollback("test done")
        assert ctl.stage(v2)  # slot free again after rollback
        ctl.rollback("test done")


def test_continuous_promote_loop_bounds_installed_versions():
    """The headline long-lived scenario: one stage->promote per
    published round. The engine must hold at most live + one prior
    (for revert) on device — never every version it ever served."""
    engine = make_engine()
    reg = ModelRegistry()
    X = np.random.RandomState(5).randn(2, D).astype(np.float32)
    with ServingService(engine, max_wait_ms=0.5) as svc:
        ctl = RolloutController(svc, reg, mode="shadow", fraction=0.5,
                                min_requests=0)  # direct verified deploy
        for k in range(1, 6):
            v = reg.publish(base_params(float(k + 1)), round_idx=k)
            assert ctl.stage(v)
            assert engine.version == v
            assert len(engine.versions_installed) <= 2
        out = svc.predict(X)
    # prior kept for revert, everything older retired
    assert engine.versions_installed == [4, 5]
    np.testing.assert_allclose(out, engine.predict(X, version=5))
    prev = ctl.revert()
    assert prev == 4 and engine.version == 4
    # the reverted-away version is retired (the memory bound holds
    # through reverts) and the one-shot prior slot is consumed
    assert engine.versions_installed == [4]
    with pytest.raises(RuntimeError, match="prior"):
        ctl.revert()


def test_swap_explicit_version_refuses_installed_slot():
    engine = make_engine()
    engine.install_weights(3, base_params(5.0))
    with pytest.raises(ValueError, match="already installed"):
        engine.swap_weights(base_params(2.0), version=3)
    # the staged slot is untouched and auto-assign still works
    X = np.random.RandomState(5).randn(2, D).astype(np.float32)
    base_out = engine.predict(X)
    np.testing.assert_allclose(engine.predict(X, version=3),
                               5 * base_out, rtol=1e-5)
    assert engine.swap_weights(base_params(2.0)) == 4


# -- observability: version/staleness on every span -------------------

def test_every_request_span_carries_version_and_staleness():
    engine = make_engine()
    rng = np.random.RandomState(17)
    X = rng.randn(64, D).astype(np.float32)
    y = _labels_for(engine, X)
    reg = ModelRegistry()
    reg.publish(base_params(), round_idx=1)  # makes v0 stale by publish
    cand = reg.publish(base_params(2.0), round_idx=4,
                       metadata={"eval_acc": 100.0})
    tracer = Tracer()
    payload = X[:2]
    with ServingService(engine, max_wait_ms=0.5, tracer=tracer) as svc:
        ctl = RolloutController(svc, reg, mode="shadow", fraction=0.5,
                                min_requests=0, parity_data=(X, y))
        n_before = 6
        for _ in range(n_before):
            svc.predict(payload)
        ctl.stage(cand)  # min_requests=0: immediate verified deploy
        assert engine.version == cand
        for _ in range(6):
            svc.predict(payload)
        # a deadline-shed request must carry the dimensions too
        dead = svc.submit(payload, timeout_s=0.0)
        with pytest.raises(Exception):
            dead.result(timeout=30)
    spans = [r for r in tracer.records() if r["name"] == "request"]
    assert len(spans) == 13
    for s in spans:
        assert "model_version" in s["attrs"], s
        assert "staleness_rounds" in s["attrs"], s
        assert s["attrs"]["staleness_rounds"] >= 0
    served_by = {s["attrs"]["model_version"] for s in spans
                 if s["attrs"]["outcome"] == "ok"}
    assert cand in served_by  # post-swap traffic attributed to it
    # the promoted candidate is the newest publish: staleness 0
    post = [s for s in spans if s["attrs"]["model_version"] == cand]
    assert all(s["attrs"]["staleness_rounds"] == 0 for s in post)
    snap = svc.metrics.snapshot(engine)
    assert snap["model_version"] == cand
    assert snap["staleness_rounds"] == 0


# -- atomicity --------------------------------------------------------

def test_swap_atomic_under_concurrent_submit_zero_recompiles():
    """Rapid swaps against concurrent submitters: every result must be
    EXACTLY one installed version's output — params and rff of
    different versions can never mix (versions differ in BOTH, so any
    torn read would produce an output matching neither) — and the
    compiled ladder never grows."""
    rng = np.random.RandomState(2)
    W = rng.randn(8, D).astype(np.float32)
    b = rng.randn(D).astype(np.float32)
    params = {"w": rng.randn(C, D).astype(np.float32)}
    engine = ServingEngine(params, rff=(W, b), buckets=(1, 8))
    engine.warmup()
    X = rng.randn(4, 8).astype(np.float32)
    # version k: params scaled by (k+1) AND a shifted rff offset
    for k in (1, 2, 3):
        engine.install_weights(
            k, {"w": (k + 1.0) * params["w"]}, rff=(W, b + k))
    expected = {k: engine.predict(X, version=k) for k in (0, 1, 2, 3)}
    cc = engine.compile_count
    stop = threading.Event()
    failures: list = []

    def swapper():
        k = 0
        while not stop.is_set():
            engine.swap_weights(version=k % 4)
            k += 1

    with ServingService(engine, max_wait_ms=0.2) as svc:
        th = threading.Thread(target=swapper)
        th.start()
        try:
            futs = [svc.submit(X) for _ in range(200)]
            for f in futs:
                out = f.result(timeout=60)
                if not any(np.array_equal(out, e)
                           for e in expected.values()):
                    failures.append(out)
        finally:
            stop.set()
            th.join()
    assert not failures, (
        f"{len(failures)} results matched NO installed version — "
        "a torn params/rff read escaped the swap lock")
    assert engine.compile_count == cc


class _FailOnce(ServingEngine):
    """First dispatch raises a transient error; later ones serve."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.fail_next = False

    def predict(self, X, version=None):
        if self.fail_next:
            self.fail_next = False
            raise ConnectionError("remote tunnel blip")
        return super().predict(X, version=version)


def test_retry_re_resolves_live_version_across_a_swap():
    """A request queued against version k whose dispatch hits a
    transient failure, with a hot swap landing during the retry
    backoff, must be answered by the NEW live version — the retry
    re-resolves instead of dispatching against a half-swapped
    engine."""
    rng = np.random.RandomState(3)
    engine = _FailOnce(base_params(), buckets=(1, 8))
    engine.warmup()
    X = rng.randn(2, D).astype(np.float32)
    out_old = engine.predict(X)
    with ServingService(engine, max_wait_ms=0.2, retries=2,
                        retry_backoff_ms=150.0) as svc:
        engine.fail_next = True
        fut = svc.submit(X)
        time.sleep(0.03)  # let the worker dispatch, fail, start backoff
        engine.swap_weights(base_params(2.0))  # swap DURING the backoff
        out = fut.result(timeout=60)
    np.testing.assert_allclose(out, 2 * out_old, rtol=1e-5)
    assert svc.metrics.retries == 1
    assert svc.metrics.requests_retried == 1


# -- fractional ramp (PR 8 satellite; PR 6 follow-on) ------------------

def _staged_ramp_controller(svc, reg, **ramp_kw):
    """A staged candidate under a ramping controller, with
    min_requests high enough that observe() never promotes during the
    ramp assertions (the ramp is about EXPOSURE, not survival)."""
    cand = reg.publish(base_params(2.0), round_idx=3)
    ctl = RolloutController(svc, reg, mode="ab", min_requests=10_000,
                            error_budget=2, **ramp_kw)
    assert ctl.stage(cand) is True
    return ctl, cand


def test_ramp_grows_fraction_on_error_free_windows():
    """Each error-free ramp_every-dispatch window multiplies the split
    by ramp_factor, capped at max_fraction — exposure is EARNED from
    the observed error budget, not scheduled."""
    engine = make_engine()
    reg = ModelRegistry()
    with ServingService(engine, max_wait_ms=0.5) as svc:
        ctl, cand = _staged_ramp_controller(
            svc, reg, fraction=0.1, ramp_every=10, ramp_factor=2.0,
            max_fraction=0.8)
        assert ctl.split() == (cand, 0.1, "ab")
        ctl.observe(cand, served=10)
        assert ctl.split()[1] == pytest.approx(0.2)
        ctl.observe(cand, served=4)   # mid-window: no growth yet
        assert ctl.split()[1] == pytest.approx(0.2)
        ctl.observe(cand, served=6)   # window completes error-free
        assert ctl.split()[1] == pytest.approx(0.4)
        ctl.observe(cand, served=10)
        assert ctl.split()[1] == pytest.approx(0.8)  # capped
        ctl.observe(cand, served=10)
        assert ctl.split()[1] == pytest.approx(0.8)  # stays capped
        ramps = [e for e in ctl.events if e["event"] == "ramped"]
        assert [e["fraction"] for e in ramps] == \
            [pytest.approx(f) for f in (0.2, 0.4, 0.8)]
        ctl.rollback("test done")


def test_ramp_window_with_error_holds_fraction():
    """A window that observed a candidate error (still within the
    budget) holds the current exposure; the NEXT error-free window
    grows it again. Exceeding the budget still rolls the canary back
    from whatever fraction the ramp reached."""
    engine = make_engine()
    reg = ModelRegistry()
    with ServingService(engine, max_wait_ms=0.5) as svc:
        ctl, cand = _staged_ramp_controller(
            svc, reg, fraction=0.25, ramp_every=8, ramp_factor=2.0)
        ctl.observe(cand, served=7, errors=1)  # window closes dirty
        assert ctl.split()[1] == pytest.approx(0.25)  # held, not grown
        ctl.observe(cand, served=8)            # clean window
        assert ctl.split()[1] == pytest.approx(0.5)
        # budget exceeded (error_budget=2): full rollback, ramp or not
        ctl.observe(cand, served=2, errors=2)
        assert ctl.split() is None
        assert ctl.events[-1]["event"] == "rollback"


def test_ramp_restarts_at_base_fraction_for_each_candidate():
    """A new stage() must re-earn exposure from the configured base —
    the prior rollout's grown fraction was ITS trust, not the next
    candidate's."""
    engine = make_engine()
    reg = ModelRegistry()
    with ServingService(engine, max_wait_ms=0.5) as svc:
        ctl, cand = _staged_ramp_controller(
            svc, reg, fraction=0.1, ramp_every=5, ramp_factor=4.0)
        ctl.observe(cand, served=5)
        assert ctl.split()[1] == pytest.approx(0.4)
        ctl.rollback("operator")
        cand2 = reg.publish(base_params(3.0), round_idx=4)
        assert ctl.stage(cand2) is True
        assert ctl.split() == (cand2, pytest.approx(0.1), "ab")
        ctl.rollback("test done")


def test_ramp_growth_keeps_assigned_ids_assigned():
    """The ramp composes with the deterministic hash split: growing
    the fraction is monotone — every id on the candidate at the
    smaller split is still on it at the larger one (no flapping
    mid-ramp), which is the property that makes a ramped rollout's
    per-id behavior reproducible."""
    ids = [f"req-{i}" for i in range(400)]
    fractions = [0.1, 0.2, 0.4, 0.8, 1.0]
    assigned = [{i for i in ids if assigned_to_candidate(i, f)}
                for f in fractions]
    for smaller, larger in zip(assigned, assigned[1:]):
        assert smaller <= larger
    # and the ramp actually exposes more traffic at each step
    assert all(len(a) < len(b) for a, b in zip(assigned, assigned[1:]))


def test_ramp_constructor_validation():
    engine = make_engine()
    reg = ModelRegistry()
    with ServingService(engine, max_wait_ms=0.5) as svc:
        with pytest.raises(ValueError, match="ramp_every"):
            RolloutController(svc, reg, ramp_every=0)
        with pytest.raises(ValueError, match="ramp_factor"):
            RolloutController(svc, reg, ramp_every=5, ramp_factor=1.0)
        with pytest.raises(ValueError, match="max_fraction"):
            RolloutController(svc, reg, fraction=0.5, ramp_every=5,
                              max_fraction=0.25)
        # the slot must be clean after refused constructions
        ctl = RolloutController(svc, reg, ramp_every=5)
        assert ctl.status()["ramp_every"] == 5
        ctl.detach()


def test_ramp_batched_report_closes_multiple_windows():
    """A single batched observe() carries its residual across window
    boundaries: served=25 at ramp_every=10 closes two windows (two
    growth steps) and leaves 5 dispatches toward the third — a
    reset-to-zero would silently stretch the configured schedule for
    workers that report in large batches."""
    engine = make_engine()
    reg = ModelRegistry()
    with ServingService(engine, max_wait_ms=0.5) as svc:
        ctl, cand = _staged_ramp_controller(
            svc, reg, fraction=0.1, ramp_every=10, ramp_factor=2.0)
        ctl.observe(cand, served=25)
        assert ctl.split()[1] == pytest.approx(0.4)  # two windows
        ctl.observe(cand, served=5)                  # residual + 5
        assert ctl.split()[1] == pytest.approx(0.8)
        ctl.rollback("test done")


# -- off-thread shadow probe (ISSUE 13 satellite) ---------------------

class _ThreadRecordingEngine(ServingEngine):
    """Records which thread ran every CANDIDATE-version dispatch."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.candidate_threads: list = []

    def predict(self, X, version=None, record_timings=True):
        if version is not None:
            self.candidate_threads.append(
                threading.current_thread().name)
        return super().predict(X, version=version,
                               record_timings=record_timings)


def test_shadow_probe_runs_off_the_worker_thread():
    """The PR 7 carried follow-on: shadow warm dispatch must ride the
    dedicated probe thread, never the serving worker (where it would
    serialize candidate dispatch behind live traffic) — and every
    accepted probe is still processed before stop() returns, so the
    post-stop snapshot carries the full shadow count."""
    engine = _ThreadRecordingEngine(base_params(), buckets=(1, 8, 32))
    engine.warmup()
    rng = np.random.RandomState(17)
    reg = ModelRegistry()
    cand = reg.publish(base_params(2.0), round_idx=1)
    payload = rng.randn(2, D).astype(np.float32)
    with ServingService(engine, max_wait_ms=0.5) as svc:
        ctl = RolloutController(svc, reg, mode="shadow", fraction=1.0,
                                min_requests=10 ** 6)  # never promotes
        assert ctl.stage(cand) is True
        for f in [svc.submit(payload) for _ in range(12)]:
            f.result(timeout=30)
    snap = svc.metrics.snapshot(engine)
    # every probe landed (stop drains the probe queue before joining)
    assert snap["shadow_requests"] == 12
    assert snap["shadow_probes_dropped"] == 0
    assert engine.candidate_threads  # probes actually dispatched
    assert set(engine.candidate_threads) == {"serve-shadow-probe"}
    ctl.rollback("test done")
