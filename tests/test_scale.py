"""Scale-config regression tests (CPU-sized stand-ins for scale_bench.py).

The BASELINE.md scale configs (1024/4096 Dirichlet-alpha=0.1 clients,
min_size=0) hit packing edge cases the reference never could — clients
with zero training samples, whole buckets of empty clients — so these
pin the behavior at the real client counts with small feature dims.
"""

import numpy as np
import pytest

from fedamw_tpu.algorithms import FedAMW, FedAvg, prepare_setup
from fedamw_tpu.data import FederatedDataset, dirichlet_partition
from fedamw_tpu.data.pack import pack_partitions
from fedamw_tpu.data.synthetic import synthetic_classification


def _dataset(n, d, classes, clients, seed=3):
    X, y, Xt, yt = synthetic_classification(n, d, classes, seed=seed)
    parts, _ = dirichlet_partition(y, clients, alpha=0.1, seed=2020,
                                   min_size=0)
    return FederatedDataset(
        name="scale-synth", task_type="classification",
        num_classes=classes, d=d, X_train=X, y_train=y, X_test=Xt,
        y_test=yt, parts=parts, source="synthetic",
    )


@pytest.fixture(scope="module")
def ds1024():
    # 1024 clients over 8192 samples: alpha=0.1 + min_size=0 leaves many
    # clients with zero training rows after the 80/20 val split.
    return _dataset(8192, 20, 7, 1024)


def test_1024_clients_partition_covers_all(ds1024):
    all_idx = np.sort(np.concatenate(ds1024.parts))
    np.testing.assert_array_equal(all_idx, np.arange(len(ds1024.y_train)))


def test_1024_clients_bucketed_fedavg_runs(ds1024):
    setup = prepare_setup(ds1024, kernel_type="linear", seed=100,
                          rng=np.random.RandomState(100), model="mlp16",
                          buckets=16)
    assert setup.num_clients == 1024
    res = FedAvg(setup, lr=0.2, epoch=1, batch_size=32, round=2, seed=0,
                 lr_mode="constant")
    assert np.all(np.isfinite(res["test_loss"]))
    assert res["test_acc"][-1] > 100.0 / 7  # beats chance in 2 rounds


def test_1024_clients_fedamw_runs(ds1024):
    setup = prepare_setup(ds1024, kernel_type="linear", seed=100,
                          rng=np.random.RandomState(100), buckets=16)
    res = FedAMW(setup, lr=0.2, epoch=1, batch_size=32, round=2,
                 lambda_reg=1e-4, lr_p=1e-3, seed=0, lr_mode="constant")
    assert np.all(np.isfinite(res["test_loss"]))


def test_all_empty_pack_is_inert():
    # A bucket of only empty clients (seen at 4096 clients) packs to a
    # 1-wide masked sample axis instead of a zero-size gather.
    pack = pack_partitions([np.zeros(0, np.int64)] * 4)
    assert pack.n_max == 1
    assert pack.mask.sum() == 0.0


def test_empty_clients_stay_empty_through_training(ds1024):
    setup = prepare_setup(ds1024, kernel_type="linear", seed=100,
                          rng=np.random.RandomState(100), buckets=16)
    sizes = np.asarray(setup.sizes)
    assert (sizes == 0).any()  # the regime this test exists for
    p = np.asarray(setup.p_fixed)
    assert np.all(p[sizes == 0] == 0)
