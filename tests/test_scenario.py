"""Scenario fuzzing plane (ISSUE 16): spec grammar, splittable seeds,
property oracle, shrinker, campaign determinism.

Tier-1 scope: the seed-derivation pins (bit-for-bit — changing
``derive_seed`` invalidates every committed campaign regression, so
the exact values are law here), the composed same-seed-bitwise-same-
schedule contract across all four grammars plus the event schedule,
the oracle's clean verdict on live serve legs, every injectable
invariant break caught AND shrunk to a still-failing minimum, the
announce-gap regression story (resync disabled fails, the shipped fix
passes), and the campaign artifact's same-seed determinism modulo
wall-clock. ISSUE 18 adds the hunter's pins: the byzantine grammar
growth (``announce_restarts``/``forges``/``mut`` — token-compatible
with every pre-growth canonical string), targeted mutation re-keying,
the hunt pool, signatures and near-miss detection, ``run_search``
determinism, and the committed ``CAMPAIGN_r18.json`` re-derivation.
The >=200-scenario sweep is the slow-marked ``campaign_sweep``
nightly at the bottom.
"""

import dataclasses
import json
import os
import sys
from unittest import mock

import numpy as np
import pytest

from fedamw_tpu.scenario import (INVARIANTS, OracleEngine,
                                 PropertyOracle, ScenarioEvent,
                                 ScenarioSpec, Verdict, Violation,
                                 load_regression, run_campaign,
                                 run_search, shrink, write_regression)
from fedamw_tpu.scenario.campaign import campaign_digest, scenario_grid
from fedamw_tpu.scenario.search import (COVERAGE_AXES,
                                        actual_signature, hunt_grid,
                                        near_miss_streams,
                                        predicted_signature,
                                        search_digest)
from fedamw_tpu.serving.transport import PodWorker
from fedamw_tpu.utils.seeds import derive_rng, derive_seed

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.scenario


# -- splittable seed derivation (satellite: the collision fix) ---------

def test_derive_seed_exact_values_are_pinned():
    # bit-for-bit law: committed campaign regressions replay through
    # these exact sub-seeds. If this test breaks, the derivation
    # changed and every campaigns/regressions/*.json is invalidated.
    assert derive_seed(0, "faults") == 1095587872
    assert derive_seed(7, "chaos") == 2567416841
    assert derive_seed(7, "scenario", 0) == 2467899191
    assert derive_seed(1729, "net") == 400296186


def test_derive_seed_is_deterministic_and_in_domain():
    for master in (0, 1, 7, 2**31):
        for labels in (("faults",), ("x", 3), ("scenario", 0, "deep")):
            a = derive_seed(master, *labels)
            assert a == derive_seed(master, *labels)
            assert 0 <= a < 2**32


def test_no_adjacent_master_collisions():
    # the seed+offset collision machine this helper replaces: master
    # m's stream under one label must not equal master m+k's under
    # another. Pin the grammar labels over a band of masters.
    labels = ("faults", "chaos", "load", "net", "events", "classes")
    seen = {}
    for master in range(64):
        for lab in labels:
            s = derive_seed(master, lab)
            assert s not in seen, (
                f"collision: ({master},{lab}) and {seen[s]}")
            seen[s] = (master, lab)


def test_two_grammars_under_one_spec_never_share_a_stream():
    # the satellite's headline pin, at the ScenarioSpec surface: all
    # four sub-grammar seeds under one master are pairwise distinct,
    # and their first RNG draws diverge (independent streams, not
    # merely unequal labels)
    spec = ScenarioSpec(seed=7)
    seeds = {
        "faults": spec.fault_spec().seed,
        "chaos": spec.chaos_spec().seed,
        "load": spec.load_spec().seed,
        "net": spec.net_spec().seed,
    }
    assert len(set(seeds.values())) == len(seeds), seeds
    draws = {k: np.random.RandomState(s).random_sample(8).tobytes()
             for k, s in seeds.items()}
    assert len(set(draws.values())) == len(draws)


def test_derive_seed_rejects_degenerate_inputs():
    with pytest.raises(ValueError):
        derive_seed(-1, "x")
    with pytest.raises(ValueError):
        derive_seed(7)  # no labels: would re-derive the shared master
    with pytest.raises(TypeError):
        derive_seed(7, 3.14)
    assert derive_rng(7, "x").randint(100) == \
        derive_rng(7, "x").randint(100)


# -- spec grammar ------------------------------------------------------

def test_spec_parse_canonical_roundtrip():
    text = ("seed=7,rounds=2,clients=4,replicas=3,requests=16,"
            "faults=0.3,chaos=0.2,load=0.5,net=0.1,swaps=1,kills=1,"
            "scales=2")
    spec = ScenarioSpec.parse(text)
    assert spec.canonical() == text
    assert ScenarioSpec.parse(spec.canonical()) == spec
    # sparse spellings default the rest
    sparse = ScenarioSpec.parse("seed=3,faults=0.5")
    assert sparse.seed == 3 and sparse.faults == 0.5
    assert sparse.rounds == 3 and sparse.kills == 0


def test_spec_rejects_malformed_input():
    with pytest.raises(ValueError, match="unknown scenario spec key"):
        ScenarioSpec.parse("seed=1,bogus=2")
    with pytest.raises(ValueError, match="key=value"):
        ScenarioSpec.parse("seed")
    with pytest.raises(ValueError, match="intensity"):
        ScenarioSpec(faults=1.5)
    with pytest.raises(ValueError, match="replicas >= 2"):
        ScenarioSpec(kills=1, replicas=1)
    with pytest.raises(ValueError, match="mid-stream events"):
        ScenarioSpec(swaps=1, requests=4)


def test_event_schedule_structure():
    spec = ScenarioSpec(seed=11, replicas=2, requests=16, swaps=1,
                        kills=1, scales=2)
    events = spec.events()
    assert list(events) == sorted(
        events, key=lambda e: (e.at, e.kind, e.arg) and
        (e.at,))  # sorted by submit index
    kinds = [e.kind for e in events]
    assert kinds.count("kill") == 1 and kinds.count("restart") == 1
    assert kinds.count("swap") == 1
    assert kinds.count("scale_up") == 1 and kinds.count(
        "scale_down") == 1
    kill = next(e for e in events if e.kind == "kill")
    restart = next(e for e in events if e.kind == "restart")
    assert kill.arg == restart.arg and kill.at < restart.at
    assert all(0 <= e.at < spec.requests for e in events)
    with pytest.raises(ValueError, match="event kind"):
        ScenarioEvent(at=0, kind="explode")


def test_composed_same_seed_bitwise_schedule():
    # the tentpole determinism contract: all four grammars + swaps +
    # kills + autoscale events under ONE master, expanded twice and
    # re-parsed from the canonical string — bitwise-identical
    spec = ScenarioSpec(seed=1729, rounds=3, clients=6, replicas=2,
                        requests=20, faults=0.4, chaos=0.3, load=0.6,
                        net=0.2, swaps=2, kills=1, scales=2)
    d1 = spec.expand().digest()
    d2 = spec.expand().digest()
    d3 = ScenarioSpec.parse(spec.canonical()).schedule_digest()
    assert d1 == d2 == d3
    # and a different master moves EVERY schedule
    other = dataclasses.replace(spec, seed=1730)
    assert other.schedule_digest() != d1


def test_spec_plan_covers_scaled_fleet():
    spec = ScenarioSpec(seed=5, replicas=2, requests=16, scales=3)
    assert spec.max_fleet() == 4
    plan = spec.expand()
    assert plan.chaos_plan.roles.shape[0] == 4
    assert plan.net_plan.roles.shape[0] == 4
    assert len(plan.classes) == spec.requests
    assert plan.gaps.shape == (spec.requests,)


# -- the oracle engine -------------------------------------------------

def test_oracle_engine_pads_to_ladder_and_counts_novel_shapes():
    eng = OracleEngine(np.eye(3, 8, dtype=np.float32))
    eng.warmup()
    assert eng.compile_count == 0
    for n in (1, 3, 5, 8, 32):  # all covered by buckets (1, 8, 32)
        eng.predict(np.zeros((n, 8), np.float32))
    assert eng.compile_count == 0
    eng.predict(np.zeros((33, 8), np.float32))  # beyond the ladder
    assert eng.compile_count == 1
    with pytest.raises(ValueError, match="shape"):
        eng.swap_weights({"w": np.zeros((2, 2), np.float32)})
    v = eng.swap_weights({"w": np.ones((3, 8), np.float32)},
                         version=9)
    assert v == 9 and eng.version == 9


# -- the oracle --------------------------------------------------------

def test_oracle_clean_run_all_grammars():
    spec = ScenarioSpec(seed=7, rounds=2, clients=4, replicas=2,
                        requests=12, faults=0.3, chaos=0.2, load=0.3,
                        net=0.1)
    v = PropertyOracle().run(spec)
    assert v.ok, v.violations
    assert v.counts["served"] + v.counts["typed_failures"] == 12
    assert v.counts["lost"] == 0
    assert v.spec == spec.canonical()


def test_oracle_clean_run_with_events_and_verdict_determinism():
    spec = ScenarioSpec(seed=11, rounds=2, clients=4, replicas=2,
                        requests=16, faults=0.2, chaos=0.1, net=0.1,
                        swaps=2, kills=1, scales=2)
    a = PropertyOracle().run(spec)
    b = PropertyOracle().run(spec)
    assert a.ok, a.violations
    assert a.counts["kills"] == 1 and a.counts["restarts"] == 1
    assert a.counts["scale_ups"] == 1
    assert a.codes() == b.codes() and a.digest == b.digest


@pytest.mark.parametrize("inject,code", [
    ("lose_request", "LOST_REQUEST"),
    ("dup_span", "SPAN_DUPLICATE"),
    ("recompile", "RECOMPILE"),
])
def test_injected_invariant_breaks_are_caught(inject, code):
    spec = ScenarioSpec(seed=3, rounds=1, clients=4, replicas=2,
                        requests=8)
    v = PropertyOracle(inject=(inject,), lost_wait_s=0.5,
                       request_timeout_s=2.0).run(spec)
    assert code in v.codes(), v.violations
    assert not v.ok
    assert code in INVARIANTS  # every emitted code is documented


def test_violation_rejects_unknown_code():
    with pytest.raises(ValueError, match="unknown violation code"):
        Violation("MADE_UP", "nope")
    with pytest.raises(ValueError, match="unknown inject token"):
        PropertyOracle(inject=("made_up",))


def test_announce_gap_regression_story():
    # the satellite fix, pinned end-to-end: a swap broadcast while a
    # worker is SIGKILLed, rejoin after. With the sync handshake
    # disabled (the pre-fix world) the rejoiner serves stale weights
    # under the pod's name; with it, the pod converges.
    spec = ScenarioSpec(seed=7, rounds=1, clients=4, replicas=2,
                        requests=16, swaps=1, kills=1)
    with mock.patch.object(PodWorker, "resync",
                           lambda self, timeout_s=5.0: None):
        pre = PropertyOracle().run(spec)
    assert pre.codes() == ("VERSION_DISAGREEMENT",), pre.violations
    post = PropertyOracle().run(spec)
    assert post.ok, post.violations


# -- the shrinker ------------------------------------------------------

def test_shrink_reduces_injected_failure_to_minimal_still_failing():
    oracle = PropertyOracle(inject=("recompile",))
    spec = ScenarioSpec(seed=13, rounds=2, clients=8, replicas=2,
                        requests=16, faults=0.5, chaos=0.2, load=0.4,
                        net=0.3)
    minimal, trace = shrink(spec, oracle)
    # the injected recompile survives every reduction, so the fixpoint
    # is the floor of every knob
    assert minimal.faults == 0 and minimal.chaos == 0
    assert minimal.load == 0 and minimal.net == 0
    assert minimal.clients == 2 and minimal.rounds == 1
    assert minimal.replicas == 1 and minimal.requests == 1
    # minimality is an OBLIGATION: the minimum still fails...
    assert "RECOMPILE" in oracle.run(minimal).codes()
    # ...and the trace shows every kept step still failing
    kept = [t for t in trace if t["kept"]]
    assert kept and all("RECOMPILE" in t["codes"] for t in kept)
    assert all(ScenarioSpec.parse(t["spec"]) for t in trace)


def test_shrink_refuses_a_passing_scenario():
    with pytest.raises(ValueError, match="failing scenario"):
        shrink(ScenarioSpec(seed=3, rounds=1, clients=4, replicas=2,
                            requests=8),
               PropertyOracle())


def test_regression_roundtrip(tmp_path):
    spec = ScenarioSpec(seed=7, rounds=1, clients=2, replicas=2,
                        requests=8, swaps=1, kills=1)
    path = write_regression(
        str(tmp_path), spec, ["VERSION_DISAGREEMENT"],
        [{"action": "zero:swaps", "spec": spec.canonical(),
          "codes": [], "kept": False}],
        campaign_seed=7, note="test")
    rec = load_regression(path)
    assert rec["spec"] == spec.canonical()
    assert rec["fixed_codes"] == ["VERSION_DISAGREEMENT"]
    broken = dict(rec)
    broken["schema"] = "WRONG.v1"
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(broken))
    with pytest.raises(ValueError, match="schema"):
        load_regression(str(bad))


# -- the campaign ------------------------------------------------------

def test_scenario_grid_is_deterministic_and_seed_split():
    a = scenario_grid(5, 6)
    b = scenario_grid(5, 6)
    assert [s.canonical() for s in a] == [s.canonical() for s in b]
    assert len({s.seed for s in a}) == 6  # one master per scenario
    assert scenario_grid(6, 6)[0].canonical() != a[0].canonical()
    with pytest.raises(ValueError):
        scenario_grid(5, 0)


def test_campaign_same_seed_same_artifact_modulo_wall():
    # the acceptance pin: one campaign seed, run twice — identical
    # CAMPAIGN.v1 artifact modulo wall-clock
    a = run_campaign(1, 4, oracle=PropertyOracle())
    b = run_campaign(1, 4, oracle=PropertyOracle())
    assert a["digest"] == b["digest"]
    a.pop("wall_s"), b.pop("wall_s")
    assert a == b
    assert a["schema"] == "CAMPAIGN.v1"
    assert a["scenarios"] == 4 and a["failures"] == 0
    assert len(a["verdicts"]) == 4


def test_campaign_artifact_validates_and_digest_is_verdict_only():
    art = run_campaign(2, 3, oracle=PropertyOracle())
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import check_bench_schema as cbs
    assert cbs.check_campaign_artifact(art, "CAMPAIGN_x.json") == []
    # the digest is a pure function of the verdict facts
    verdicts = [Verdict(spec=v["spec"], digest=v["digest"],
                        violations=(), counts={})
                for v in art["verdicts"]]
    assert campaign_digest(verdicts) == art["digest"]


def test_committed_campaign_artifact_matches_regeneration():
    # the committed artifact is not a snapshot of a machine that once
    # existed: the same seed re-derives it bitwise (modulo wall_s)
    path = os.path.join(REPO, "CAMPAIGN_r16.json")
    committed = json.load(open(path))
    art = run_campaign(committed["seed"], committed["budget"],
                       oracle=PropertyOracle())
    assert art["digest"] == committed["digest"]
    assert art["verdicts"] == committed["verdicts"]


# -- the byzantine grammar growth (ISSUE 18) ---------------------------

def test_byzantine_knobs_roundtrip_and_stay_token_compatible():
    # the grammar growth: announce_restarts / forges / mut spell
    # canonically and re-parse bitwise...
    text = ("seed=7,rounds=2,clients=4,replicas=6,requests=16,"
            "faults=0.3,chaos=0,load=0,net=0.1,swaps=2,kills=1,"
            "scales=0,announce_restarts=1,forges=2,mut=events@1+net@2")
    spec = ScenarioSpec.parse(text)
    assert spec.canonical() == text
    assert ScenarioSpec.parse(spec.canonical()) == spec
    assert spec.mut == (("events", 1), ("net", 2))
    # ...and a spec that never arms them emits NO new tokens — every
    # pre-ISSUE-18 canonical string (committed regressions included)
    # survives the growth byte-for-byte
    plain = ScenarioSpec(seed=7, replicas=2, requests=16, swaps=1,
                         kills=1)
    for token in ("announce_restarts", "forges", "mut"):
        assert token not in plain.canonical()
    assert ScenarioSpec.parse(plain.canonical()) == plain


def test_byzantine_knobs_reject_unsatisfiable_scenarios():
    with pytest.raises(ValueError, match="needs one"):
        ScenarioSpec(announce_restarts=1)  # no announce to race
    with pytest.raises(ValueError, match="replicas >= 6"):
        # the fingerprint-quorum floor: 2 forgers need 2*2+2 hosts
        ScenarioSpec(replicas=4, forges=2, kills=1, requests=16)
    with pytest.raises(ValueError, match="must be one of"):
        ScenarioSpec(mut=(("bogus", 1),))
    with pytest.raises(ValueError, match=">= 1"):
        ScenarioSpec(mut=(("events", 0),))
    with pytest.raises(ValueError, match="STREAM@N"):
        ScenarioSpec.parse("seed=1,mut=events")


def test_mutation_tail_rekeys_only_its_stream():
    # mut=STREAM@N is a targeted re-key: the named sub-grammar's seed
    # moves, every other stream stays bitwise
    base = ScenarioSpec(seed=1729, replicas=2, requests=16, swaps=1,
                        kills=1, faults=0.3, chaos=0.2, load=0.5,
                        net=0.1)
    mutant = dataclasses.replace(base, mut=(("faults", 1),))
    assert mutant.fault_spec().seed != base.fault_spec().seed
    assert mutant.chaos_spec() == base.chaos_spec()
    assert mutant.load_spec() == base.load_spec()
    assert mutant.net_spec() == base.net_spec()
    # distinct attempts on one stream draw distinct re-keys
    again = dataclasses.replace(base, mut=(("faults", 2),))
    assert again.fault_spec().seed != mutant.fault_spec().seed
    # and the schedule digest moves with the mutated stream
    assert mutant.schedule_digest() != base.schedule_digest()


def test_hunt_grid_is_deterministic_and_arms_both_fault_classes():
    a = hunt_grid(18, 24)
    b = hunt_grid(18, 24)
    assert [s.canonical() for s in a] == [s.canonical() for s in b]
    # the hunt pool draws from its OWN streams: a hunt and a sweep
    # under one campaign seed never share grammar randomness
    sweep = scenario_grid(18, 24)
    assert a[0].seed != sweep[0].seed
    # the wider structural range actually arms the ISSUE 18 classes
    assert any(s.announce_restarts > 0 for s in a)
    assert any(s.forges > 0 for s in a)
    # every draw satisfies the spec's own validation (construction
    # would have raised), and armed forgers always have a sync victim
    assert all(s.kills or s.announce_restarts
               for s in a if s.forges)
    with pytest.raises(ValueError):
        hunt_grid(18, 0)


def test_signatures_and_near_miss_streams():
    spec = ScenarioSpec(seed=5, replicas=4, requests=16, swaps=1,
                        kills=1, announce_restarts=1, forges=1,
                        faults=0.3, mut=(("events", 1),))
    predicted = predicted_signature(spec)
    assert {"announce_restart", "forge", "mutant", "kill", "resync",
            "swap", "faults"} <= predicted
    assert predicted <= set(COVERAGE_AXES)
    # the actual signature is count-driven + armed grammars, sorted
    v = Verdict(spec=spec.canonical(), digest="d", violations=(),
                counts={"kills": 1, "restarts": 1, "resyncs": 1,
                        "forge_rejected": 1, "swaps_applied": 1})
    sig = actual_signature(spec, v)
    assert sig == tuple(sorted(sig))
    assert "forge_rejected" in sig and "announce_restart" in sig
    # near-miss: a resync beside an announce perturbs "events"; a
    # fired defense perturbs "net"
    assert near_miss_streams(spec, v) == ("events", "net")
    quiet = Verdict(spec=spec.canonical(), digest="d", violations=(),
                    counts={"kills": 1})
    assert near_miss_streams(spec, quiet) == ()
    # a violation is a FAILURE, not a near-miss — it goes to the
    # shrinker, never back into the mutation queue
    red = Verdict(spec=spec.canonical(), digest="d",
                  violations=(Violation("RECOMPILE", "x"),),
                  counts={"resyncs": 1, "forge_rejected": 1,
                          "swaps_applied": 1})
    assert near_miss_streams(spec, red) == ()


def test_search_same_seed_same_artifact_modulo_wall():
    a = run_search(4, 3, oracle=PropertyOracle())
    b = run_search(4, 3, oracle=PropertyOracle())
    assert a["digest"] == b["digest"]
    a.pop("wall_s"), b.pop("wall_s")
    assert a == b
    assert a["schema"] == "CAMPAIGN.v2"
    assert a["scenarios"] == 3 and a["failures"] == 0
    for v in a["verdicts"]:
        assert v["origin"]["kind"] in ("grid", "mutation")
        assert v["signature"] == sorted(v["signature"])


def test_search_artifact_validates_under_v2_rules():
    art = run_search(4, 3, oracle=PropertyOracle())
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import check_bench_schema as cbs
    assert cbs.check_campaign_artifact(art, "CAMPAIGN_x.json") == []
    # the digest is a pure function of the recorded hunt facts
    entries = [(Verdict(spec=v["spec"], digest=v["digest"],
                        violations=(), counts={}),
                v["origin"], tuple(v["signature"]))
               for v in art["verdicts"]]
    assert search_digest(entries) == art["digest"]


def test_committed_hunt_artifact_matches_regeneration():
    # CAMPAIGN_r18.json is not a snapshot of a machine that once
    # existed: the same seed re-derives the whole hunt — scheduling
    # order, mutation lineage, coverage tally — bitwise (modulo wall)
    path = os.path.join(REPO, "CAMPAIGN_r18.json")
    committed = json.load(open(path))
    assert committed["schema"] == "CAMPAIGN.v2"
    assert committed["failures"] == 0
    art = run_search(committed["seed"], committed["budget"],
                     oracle=PropertyOracle())
    assert art["digest"] == committed["digest"]
    assert art["verdicts"] == committed["verdicts"]
    assert art["coverage"] == committed["coverage"]
    # the acceptance floor: the hunt actually hunted — at least one
    # committed scenario descends from a near-miss mutation, and both
    # ISSUE 18 fault classes fired with the defense observing them
    origins = [v["origin"]["kind"] for v in committed["verdicts"]]
    assert "mutation" in origins
    for axis in ("announce_restart", "forge", "forge_rejected",
                 "resync"):
        assert committed["coverage"].get(axis, 0) > 0, axis
    # mutation lineage is well-founded: parents ran earlier
    for i, v in enumerate(committed["verdicts"]):
        if v["origin"]["kind"] == "mutation":
            assert 0 <= v["origin"]["parent"] < i


# -- the nightly sweep -------------------------------------------------

@pytest.mark.slow
@pytest.mark.campaign_sweep
def test_campaign_sweep_200_scenarios():
    """The nightly: >= 200 coverage-guided scenarios under one seed,
    zero invariant violations, deterministic digest (re-derived from
    the verdict records, not re-run — the budget IS the wall-clock).
    ``CAMPAIGN_WALL_S`` caps the hunt's wall-clock: a capped nightly
    may come up short only by saying so (``truncated``)."""
    wall = float(os.environ.get("CAMPAIGN_WALL_S", 0)) or None
    art = run_search(16, 200, oracle=PropertyOracle(),
                     wall_budget_s=wall)
    assert art["schema"] == "CAMPAIGN.v2"
    assert art["failures"] == 0, json.dumps(
        art["violations"], indent=2)[:4000]
    assert art["scenarios"] >= 200 or art["truncated"]
    assert art["wall_budget_s"] == wall
    entries = [(Verdict(spec=v["spec"], digest=v["digest"],
                        violations=(), counts={}),
                v["origin"], tuple(v["signature"]))
               for v in art["verdicts"]]
    assert search_digest(entries) == art["digest"]
