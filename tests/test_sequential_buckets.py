"""Sequential-compat mode across size buckets.

The reference's contamination artifact (client i+1 trains from client
i's final weights, ``tools.py:341``) must chain through bucket
boundaries: bucket g+1's first client continues from bucket g's last.
Pinned by bit-matching the bucketed round against manual per-bucket
chaining, and by checking the chain actually happened (outputs differ
from the parallel mode).
"""

import jax
import jax.numpy as jnp
import numpy as np

from fedamw_tpu.algorithms import FedAvg, prepare_setup
from fedamw_tpu.data import load_dataset
from fedamw_tpu.fedcore import make_bucketed_round, make_client_round


def _setup():
    ds = load_dataset("digits", num_partitions=10, alpha=0.5)
    return prepare_setup(ds, kernel_type="linear", seed=3,
                         rng=np.random.RandomState(3), buckets=2)


def test_sequential_chains_across_buckets():
    setup = _setup()
    idx_tup, mask_tup = setup.round_arrays()
    keys = jax.random.split(jax.random.PRNGKey(5), setup.num_clients)
    params = setup.model.init(jax.random.PRNGKey(0), setup.D,
                              setup.num_classes)
    args = (jnp.float32(0.3), jnp.float32(0.0), jnp.float32(0.0))

    bucketed = make_bucketed_round(
        setup.model.apply, setup.task, 1, 16,
        setup.n_maxes, setup.bucket_counts, sequential=True,
    )
    stacked, losses, _ = bucketed(params, setup.X, setup.y, idx_tup,
                                  mask_tup, keys, *args)

    # manual chaining: run each bucket's sequential round, feeding the
    # last client's weights into the next bucket
    carry = params
    chunks, offset = [], 0
    for g, (idx_g, mask_g) in enumerate(zip(idx_tup, mask_tup)):
        rf = make_client_round(setup.model.apply, setup.task, 1, 16,
                               int(idx_g.shape[1]), sequential=True)
        j_g = int(idx_g.shape[0])
        s_g, _, _ = rf(carry, setup.X, setup.y, idx_g, mask_g,
                       keys[offset:offset + j_g], *args)
        chunks.append(s_g["w"])
        carry = jax.tree.map(lambda s: s[-1], s_g)
        offset += j_g
    np.testing.assert_allclose(
        np.asarray(stacked["w"]), np.asarray(jnp.concatenate(chunks)),
        atol=1e-6,
    )


def test_sequential_differs_from_parallel_and_runs_e2e():
    setup = _setup()
    kw = dict(lr=0.3, epoch=1, round=2, seed=0, lr_mode="constant")
    res_par = FedAvg(setup, sequential=False, **kw)
    res_seq = FedAvg(setup, sequential=True, **kw)
    assert np.all(np.isfinite(res_seq["test_loss"]))
    # the artifact must actually change the trajectory
    assert not np.allclose(res_par["train_loss"], res_seq["train_loss"])
