"""The driver contract of serve_bench.py (mirror of
test_bench_contract.py for the serving side).

Pins: JSON lines on stdout with the headline LAST; a BENCH_SERVE
artifact with per-bucket p50/p95/p99 + throughput for >= 3 rungs;
ZERO recompiles after warmup across the mixed-size stream (the
bucket-ladder shape discipline, read from the jit compile-cache
counter); exact serving/evaluate accuracy parity; and the strict-
backend guard — BENCH_STRICT_TPU must abort rc=1 on a leaked CPU
backend BEFORE measuring anything, exactly like bench.py, so a CPU
capture can never be harvested as TPU evidence.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SMALL = dict(
    SERVE_BUCKETS="1,8,32", SERVE_D="64", SERVE_N="1024",
    SERVE_TRAIN_ROUNDS="1", SERVE_ITERS="5", SERVE_REQUESTS="40",
)


def test_serve_bench_emits_driver_contract_json(tmp_path):
    out_path = str(tmp_path / "BENCH_SERVE_test.json")
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", SERVE_OUT=out_path, **_SMALL)
    env.pop("BENCH_STRICT_TPU", None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "serve_bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=570,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [json.loads(ln) for ln in out.stdout.splitlines() if ln]

    # headline LAST (the driver records the final line)
    head = lines[-1]
    assert head["metric"] == "serve_requests_per_sec"
    assert head["unit"] == "requests/s"
    assert head["value"] > 0
    assert head["platform"] == "cpu"
    assert head["recompiles_after_warmup"] == 0
    for q in ("p50_ms", "p95_ms", "p99_ms"):
        assert head[q] > 0

    # one latency line per bucket rung, >= 3 rungs
    bucket_lines = [l for l in lines
                    if l["metric"] == "serve_bucket_latency"]
    assert len(bucket_lines) >= 3
    for rec in bucket_lines:
        assert rec["p50_ms"] > 0 and rec["p99_ms"] >= rec["p50_ms"]
        assert rec["throughput_rows_per_s"] > 0

    # the artifact mirrors the lines and carries the parity verdict
    with open(out_path) as f:
        art = json.load(f)
    assert art["schema"] == "BENCH_SERVE.v1"
    assert art["recompiles_after_warmup"] == 0
    assert len(art["bucket_latency"]) >= 3
    assert art["parity"]["match"] is True
    assert art["parity"]["engine_acc"] == art["parity"]["evaluate_acc"]
    assert art["mixed_stream"]["requests"] == 40
    assert art["mixed_stream"]["shed_deadline"] == 0
    assert art["mixed_stream"]["shed_overload"] == 0
    assert art["warmup"]["compile_count"] == 3  # one program per rung


def test_serve_strict_tpu_refuses_cpu_backend(tmp_path):
    """Same dominance property as bench.py's strict mode: a leaked
    JAX_PLATFORMS=cpu under BENCH_STRICT_TPU=1 aborts before any
    metric line or artifact is produced."""
    out_path = str(tmp_path / "BENCH_SERVE_strict.json")
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", BENCH_STRICT_TPU="1",
               SERVE_OUT=out_path, **_SMALL)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "serve_bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 1
    assert "BENCH_STRICT_TPU set but the resolved backend" in out.stderr
    assert not out.stdout.strip()  # no metric lines to mis-harvest
    assert not os.path.exists(out_path)  # no artifact either
