"""The driver contract of serve_bench.py (mirror of
test_bench_contract.py for the serving side).

Pins: JSON lines on stdout with the headline LAST; a BENCH_SERVE
artifact with per-bucket p50/p95/p99 + throughput for >= 3 rungs;
ZERO recompiles after warmup across the mixed-size stream (the
bucket-ladder shape discipline, read from the jit compile-cache
counter) — a pin that now spans the TRACED streams too; exact
serving/evaluate accuracy parity; the ISSUE 5 trace plane — per-stage
(queue/pad/device) percentile families in the mixed-stream snapshot,
a trace section holding every submitted request id exactly once, the
phases breakdown, and the serve_trace_overhead line before the
headline; the ISSUE 6 rollout leg — >= 3 hot swaps with zero
recompiles, a promoted shadow canary, a parity-failure rollback
drill, model_version/staleness_rounds dimensions in the snapshot and
in every request span, and the rollout leg's spans STREAMED through
rotating JSONL parts; the ISSUE 7 chaos leg — scripted replica kills
mid-stream on a 3-replica fleet with zero lost requests, dead-replica
requeues, zero recompiles across failovers, and the p95-with/without-
chaos comparison in a v3 ``chaos`` section; the ISSUE 9 cold-start
leg — compile-warmup start vs AOT-artifact-load start side by side in
a v4 ``cold_start`` section, the artifact path coming up AND serving
with ``compile_count == 0``, plus the chaos leg composed with a
mid-stream hot swap whose new model_version lands on every post-swap
span; the ISSUE 13 continuous-batching leg — a fixed-drain baseline
vs continuous admission over a traffic-learned ladder, paired on one
seeded open-loop schedule in a v6 ``continuous_batching`` section
with zero recompiles after ladder freeze, plus the headline mixed
stream now OPEN-LOOP paced (queue percentiles measure service under
load: ``queue_depth_peak < requests``); the ISSUE 14 overload leg —
the burn-rate admission controller + autoscaled fleet against every
fixed-N fleet under one seeded flash crowd in a v7 ``overload``
section, the beat / interactive-protection / zero-lost /
zero-recompile / exactly-once pins all held; the ISSUE 15 pod leg —
a multi-process worker pod over the socket frame protocol, one
worker SIGKILLed and one partitioned mid-stream under scripted
network chaos, a mid-stream version announce, zero lost accepted
requests / exactly-once spans / trace-propagated-across-the-wire /
zero survivor recompiles in a v8 ``pod`` section; and the
strict-backend guard — BENCH_STRICT_TPU
must abort rc=1 on a leaked CPU backend BEFORE measuring anything,
exactly like bench.py, so a CPU capture can never be harvested as TPU
evidence.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SMALL = dict(
    SERVE_BUCKETS="1,8,32", SERVE_D="64", SERVE_N="1024",
    SERVE_TRAIN_ROUNDS="1", SERVE_ITERS="5", SERVE_REQUESTS="40",
)


def test_serve_bench_emits_driver_contract_json(tmp_path):
    out_path = str(tmp_path / "BENCH_SERVE_test.json")
    trace_dir = str(tmp_path / "trace")
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", SERVE_OUT=out_path,
               SERVE_TRACE=trace_dir, **_SMALL)
    env.pop("BENCH_STRICT_TPU", None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "serve_bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=570,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [json.loads(ln) for ln in out.stdout.splitlines() if ln]

    # headline LAST (the driver records the final line)
    head = lines[-1]
    assert head["metric"] == "serve_requests_per_sec"
    assert head["unit"] == "requests/s"
    assert head["value"] > 0
    assert head["platform"] == "cpu"
    assert head["recompiles_after_warmup"] == 0
    for q in ("p50_ms", "p95_ms", "p99_ms"):
        assert head[q] > 0

    # one latency line per bucket rung, >= 3 rungs
    bucket_lines = [l for l in lines
                    if l["metric"] == "serve_bucket_latency"]
    assert len(bucket_lines) >= 3
    for rec in bucket_lines:
        assert rec["p50_ms"] > 0 and rec["p99_ms"] >= rec["p50_ms"]
        assert rec["throughput_rows_per_s"] > 0

    # the trace-overhead line prints before the headline (which must
    # stay LAST for the driver's final-line parse)
    trace_lines = [l for l in lines
                   if l["metric"] == "serve_trace_overhead"]
    assert len(trace_lines) == 1 and trace_lines[0] == lines[-2]
    assert trace_lines[0]["value"] > 0
    assert trace_lines[0]["tracing_on_req_per_s"] > 0
    # every request of the traced stream (floored at 200 for timing
    # stability) landed exactly one span
    assert trace_lines[0]["request_spans"] == 200

    # ISSUE 7 pins — the chaos line prints before the rollout line
    # (headline still LAST): kills fired mid-stream, the dead
    # replicas' in-flight batches requeued, nothing was lost, and the
    # shared-ladder zero-recompile pin covers the failovers
    chaos_lines = [l for l in lines if l["metric"] == "serve_chaos"]
    assert len(chaos_lines) == 1 and chaos_lines[0] == lines[-5]
    cl = chaos_lines[0]
    assert cl["kills"] >= 1
    assert cl["requeues"] >= 1
    assert cl["lost"] == 0
    assert cl["recompiles_during_chaos"] == 0
    assert cl["value"] > 0  # p95 under chaos
    assert cl["p95_ms_clean"] > 0

    # ISSUE 9 pins — the cold-start line prints between the rollout
    # and trace-overhead lines (headline still LAST): the artifact
    # path came up in positive milliseconds having compiled NOTHING
    cold_lines = [l for l in lines if l["metric"] == "serve_cold_start"]
    assert len(cold_lines) == 1 and cold_lines[0] == lines[-3]
    cold_l = cold_lines[0]
    assert cold_l["value"] > 0  # ms-to-ready on the artifact path
    assert cold_l["artifact_compile_count"] == 0
    assert cold_l["compile_warmup_s"] > 0
    assert cold_l["rungs"] == 3

    # ISSUE 6 pins — the rollout line prints before the cold-start
    # line (headline still LAST): swaps took, the shadow canary
    # promoted, the parity drill rolled back, and the zero-recompile
    # pin covers the swapped streams
    roll_lines = [l for l in lines if l["metric"] == "serve_rollout"]
    assert len(roll_lines) == 1 and roll_lines[0] == lines[-4]
    roll = roll_lines[0]
    assert roll["swaps"] >= 3
    assert roll["canary"] == "promoted"
    assert roll["rollback_drill"] == "rolled_back"
    assert roll["recompiles_during_swaps"] == 0
    assert roll["value"] > 0  # swap p50 ms

    # ISSUE 12 pins — the telemetry-plane line prints first of the leg
    # lines (all later positions unmoved, headline still LAST): the
    # whole plane priced paired, per-class SLO evaluated, device
    # attribution recorded (the honest CPU fallback on this backend)
    tel_lines = [l for l in lines
                 if l["metric"] == "serve_telemetry_overhead"]
    assert len(tel_lines) == 1 and tel_lines[0] == lines[-6]
    tl = tel_lines[0]
    assert tl["value"] > 0
    assert tl["plane_on_req_per_s"] > 0
    assert tl["plane_off_req_per_s"] > 0
    assert tl["registry_points"] > 0
    assert tl["slo_classes"] == 2
    assert tl["device_attribution"] == "none"  # CPU: no device lane

    # ISSUE 13 pins — the continuous-batching line prints first of the
    # leg lines (all later positions unmoved, headline still LAST):
    # paired p95s measured, the abort-grade pins held (the >=2x ratio
    # itself is the COMMITTED-capture expectation, not a tier-1 gate —
    # a loaded CI box must not flake on scheduler noise)
    cb_lines = [l for l in lines
                if l["metric"] == "serve_continuous_batching"]
    assert len(cb_lines) == 1 and cb_lines[0] == lines[-7]
    cbl = cb_lines[0]
    assert cbl["value"] > 0  # p95 improvement ratio recorded
    assert cbl["baseline_p95_ms"] > 0
    assert cbl["continuous_p95_ms"] > 0
    assert cbl["recompiles_after_freeze"] == 0
    assert cbl["spans_exactly_once"] is True
    assert cbl["ladder"]  # a non-empty learned rung list

    # ISSUE 14 pins — the overload line (position unmoved, headline
    # still LAST): the elastic
    # fleet beat every fixed fleet on SLO-good work per
    # replica-second, interactive held while batch shed, the
    # autoscaler actually scaled, nothing lost, nothing compiled
    ov_lines = [l for l in lines if l["metric"] == "serve_overload"]
    assert len(ov_lines) == 1 and ov_lines[0] == lines[-8]
    ovl = ov_lines[0]
    assert ovl["value"] > ovl["best_fixed"] > 0
    assert ovl["beats_every_fixed"] is True
    assert ovl["interactive_attainment"] >= 0.8
    assert ovl["batch_shed"] >= 1
    assert ovl["scale_ups"] >= 1
    assert ovl["lost_accepted"] == 0
    assert ovl["recompiles_during_overload"] == 0
    assert ovl["spans_exactly_once"] is True

    # ISSUE 15 pins — the pod line prints first of the leg lines (all
    # later positions unmoved, headline still LAST): the pod survived
    # a real SIGKILL and a real partition on a real wire, requeued
    # the in-flight batches, lost nothing, compiled nothing, and the
    # trace crossed the hop intact
    pod_lines = [l for l in lines if l["metric"] == "serve_pod"]
    assert len(pod_lines) == 1 and pod_lines[0] == lines[-9]
    pl = pod_lines[0]
    assert pl["workers"] == 3
    assert pl["kills_fired"] >= 1
    assert pl["partitions_fired"] >= 1
    assert pl["value"] >= 1  # requeues across processes
    assert pl["lost"] == 0
    assert pl["survivor_recompiles"] == 0
    assert pl["spans_exactly_once"] is True
    assert pl["trace_propagated"] is True
    assert isinstance(pl["swap_version"], int)

    # the artifact mirrors the lines and carries the parity verdict
    with open(out_path) as f:
        art = json.load(f)
    assert art["schema"] == "BENCH_SERVE.v8"
    assert art["recompiles_after_warmup"] == 0
    assert len(art["bucket_latency"]) >= 3
    assert art["parity"]["match"] is True
    assert art["parity"]["engine_acc"] == art["parity"]["evaluate_acc"]
    assert art["mixed_stream"]["requests"] == 40
    assert art["mixed_stream"]["shed_deadline"] == 0
    assert art["mixed_stream"]["shed_overload"] == 0
    assert art["warmup"]["compile_count"] == 3  # one program per rung

    # ISSUE 5 pins — per-stage percentile families in the snapshot:
    # a tail regression must localize to queue vs pad vs device
    stream = art["mixed_stream"]
    for stage in ("queue", "pad", "device"):
        for q in ("p50_ms", "p95_ms", "p99_ms"):
            assert stream[f"{stage}_{q}"] >= 0
    # the per-request retry surface (satellite of ISSUE 5): aggregate
    # counter AND the request-level view
    assert stream["retries"] == 0
    assert stream["requests_retried"] == 0
    assert stream["max_request_retries"] == 0
    # the exported trace held every submitted request id exactly once
    assert art["trace"]["all_ids_unique_once"] is True
    assert art["trace"]["request_spans"] == \
        art["trace"]["unique_request_ids"] == 200
    assert art["trace"]["dropped"] == 0
    # trace overhead measured, not assumed; phases attribute the
    # bench's own wall-clock
    assert art["trace_overhead"]["value"] > 0
    assert art["trace_overhead"]["tracing_on_req_per_s"] > 0
    for k in ("build_s", "compile_warmup_s", "timed_run_s"):
        assert art["phases"][k] >= 0

    # the rollout section: the continuous-deployment evidence the v2
    # schema requires (tools/check_bench_schema.py gates it)
    rollout = art["rollout"]
    assert rollout["swaps"] >= 3
    assert rollout["swap_p50_ms"] > 0
    assert rollout["inflight_p95_ms"] > 0
    assert rollout["recompiles_during_swaps"] == 0
    assert rollout["canary"] == "promoted"
    assert rollout["rollback_drill"] == "rolled_back"
    assert rollout["drill_gate"]["checked"] is True
    assert rollout["drill_gate"]["match"] is False  # the lie was caught
    assert rollout["shadow_requests"] > 0
    assert rollout["rollbacks"] == 1  # exactly the drill
    assert rollout["final_version"] >= 3
    # the drill's rejected publish is withdrawn, so a green run ends
    # serving the newest SERVABLE model: zero staleness
    assert rollout["staleness_rounds"] == 0
    assert art["phases"]["rollout_s"] >= 0

    # the chaos section: the failover evidence the v3 schema requires
    # (tools/check_bench_schema.py gates it) — the acceptance pins of
    # ISSUE 7, emitted not just enforced
    chaos = art["chaos"]
    assert chaos["replicas"] == 3
    assert chaos["kills_observed"] == chaos["kills_planned"] == 2
    assert chaos["requeues"] >= 2  # each kill's in-flight batch moved
    assert chaos["lost"] == 0
    assert chaos["resolved_ok"] + chaos["deadline_exceeded"] == \
        chaos["requests"]
    assert chaos["recompiles_during_chaos"] == 0
    assert chaos["spans_exactly_once"] is True
    assert chaos["p95_ms_clean"] > 0 and chaos["p95_ms_chaos"] > 0
    # two replicas died; the survivor(s) carried the stream
    dead = [r for r in chaos["per_replica"].values()
            if r["state"] == "dead"]
    assert len(dead) == 2
    assert all(r["requeued"] == 1 for r in dead)
    assert art["phases"]["chaos_s"] >= 0

    # the chaos-under-rollout composition (ISSUE 9 satellite): a hot
    # swap landed MID-chaos-stream, every request submitted after it
    # carried the new version, and the recompile pin covered the swap
    assert chaos["post_swap_requests"] >= 1
    assert chaos["post_swap_version_ok"] is True
    assert isinstance(chaos["midstream_swap_version"], int)
    assert chaos["hedges_cancelled"] >= 0

    # the cold-start section: the AOT-artifact evidence the v4 schema
    # requires (tools/check_bench_schema.py gates it) — both start
    # modes timed, zero compiles on the load path, exact parity
    cold = art["cold_start"]
    assert cold["compile_warmup_s"] > 0
    assert cold["compile_count_compiled"] == 3  # one per rung
    assert cold["artifact_export_s"] > 0
    assert cold["artifact_load_s"] > 0
    assert cold["artifact_compile_count"] == 0
    assert cold["speedup_x"] > 1  # load beats compile, or why bother
    assert cold["rungs"] == 3 and cold["artifact_bytes"] > 0
    assert cold["parity"]["match"] is True
    assert cold["parity"]["engine_acc"] == cold["parity"]["evaluate_acc"]
    assert art["phases"]["cold_start_s"] >= 0
    # no BENCH_COMPILE_CACHE in this run: cold by construction
    assert art["phases"]["compile_cache"] is None

    # the mixed stream predates any swap: served by the seed version,
    # zero staleness, and the new dimensions are present
    assert stream["model_version"] == 0
    assert stream["staleness_rounds"] == 0
    assert stream["weight_swaps"] == 0

    # the telemetry_overhead section: the v5 contract
    # (tools/check_bench_schema.py gates it) — paired plane cost, the
    # abort-grade pins re-emitted, the SLO evaluation, the reservoir
    # honesty triple, and the graceful device-attribution fallback
    tel = art["telemetry_overhead"]
    assert tel["overhead_x"] > 0
    # sanity bound only (the strict <=1.05 is the committed-artifact
    # gate's job — a loaded CI box must not flake tier-1 on scheduler
    # noise; best-of-5 paired legs keep this comfortably near 1.0)
    assert tel["overhead_x"] < 1.5
    assert tel["reps"] >= 1
    assert tel["requests_per_leg"] == 200
    assert tel["spans_exactly_once"] is True
    assert tel["recompiles_during_telemetry"] == 0
    assert tel["registry_points"] > 0
    assert tel["registry_instruments"] > 0
    slo = tel["slo"]
    assert set(slo["classes"]) == {"interactive", "batch"}
    for cls in slo["classes"].values():
        # the 300s window comfortably covers the whole leg even on a
        # slow box; 60s could age the winning rep's samples out
        w = cls["windows"]["300s"]
        assert w["total"] == 100  # 200 requests, two classes cycled
        assert w["attainment"] is not None
        assert w["burn_rate"] is not None
    attr = tel["device_attribution"]
    assert attr["source"] == "none"  # CPU: profiler has no device lane
    assert "reason" in attr and attr["reason"]
    acct = tel["latency_accounting"]
    assert acct["seen"] == 200 and acct["reservoir_degraded"] is False
    # the honesty triple also rides the mixed-stream snapshot
    assert stream["latency_seen"] == stream["requests"]
    assert stream["reservoir_degraded"] is False
    assert stream["device_attribution"] is None  # none installed there
    assert art["phases"]["telemetry_s"] >= 0

    # the mixed-stream realism satellite (ISSUE 13): the headline
    # stream is open-loop paced, so the queue family measures service
    # under load — backlog drain would peak at requests exactly
    assert stream["arrival_req_per_s"] > 0
    assert stream["calibration_req_per_s"] > 0
    assert stream["queue_depth_peak"] < stream["requests"]
    assert stream["mode"] == "continuous"

    # the continuous_batching section: the v6 contract
    # (tools/check_bench_schema.py gates it) — paired legs on one
    # seeded schedule, the learned ladder with its costs charged, and
    # the abort-grade pins re-emitted
    cb = art["continuous_batching"]
    assert cb["baseline"]["mode"] == "drain"
    assert cb["continuous"]["mode"] == "continuous"
    assert cb["baseline"]["requests"] == cb["continuous"]["requests"] \
        == cb["requests_per_leg"]
    assert cb["arrival_req_per_s"] > 0
    assert cb["p95_improvement_x"] > 0
    assert cb["recompiles_after_freeze"] == 0
    assert cb["spans_exactly_once"] is True
    ladder = cb["ladder"]
    assert ladder["fixed"] == [1, 8, 32]  # this run's SERVE_BUCKETS
    assert ladder["learned"] and ladder["frozen"] is True
    assert ladder["recompiles_charged"] == len(ladder["installed"])
    assert ladder["recompiles_charged"] <= ladder["recompile_budget"]
    assert len(ladder["learned"]) <= ladder["max_rungs"]
    if ladder["installed"]:
        # learning happened: the explicit cost model must show why
        assert ladder["waste_fraction_learned"] < \
            ladder["waste_fraction_fixed"]
    assert art["phases"]["continuous_batching_s"] >= 0

    # the overload section: the v7 contract
    # (tools/check_bench_schema.py gates it) — every fleet's
    # attainment-per-replica-second recorded, the autoscaled one on
    # top, class-aware shedding visible per class, the autoscaler's
    # event log and attach timings present (scale-out is
    # load-milliseconds on the artifact plane)
    ov = art["overload"]
    fleets = ov["fleets"]
    assert "autoscaled" in fleets
    assert any(k.startswith("fixed_") for k in fleets)
    auto = fleets["autoscaled"]
    for name, rec in fleets.items():
        assert rec["requests"] == ov["load"]["requests"]
        assert rec["replica_seconds"] > 0
        assert rec["lost"] == 0
        assert rec["spans_exactly_once"] is True
        assert rec["recompiles"] == 0
        if name != "autoscaled":
            assert auto["good_per_replica_s"] > \
                rec["good_per_replica_s"]
    assert auto["scale_ups"] >= 1
    assert auto["replicas_peak"] > auto["replicas_start"]
    assert auto["shed_by_class"].get("batch", 0) == ov["batch_shed"] \
        >= 1
    assert all(ms >= 0 for ms in auto["attach_ms"])
    assert any(e["action"] == "up" for e in auto["events"])
    assert ov["interactive_attainment_ok"] is True
    assert ov["classes"]["interactive"]["objective"] <= \
        auto["attainment"]["interactive"]
    assert art["phases"]["overload_s"] >= 0

    # the pod section: the v8 contract
    # (tools/check_bench_schema.py gates it) — the cross-process
    # evidence in full: every accepted request resolved typed, the
    # scripted chaos actually fired against real processes, the swap
    # announce reached the survivors under one agreed version, and
    # the worker-side spans joined the router's traces
    pod = art["pod"]
    assert pod["workers"] == 3
    assert pod["requests"] == 120
    assert pod["resolved_ok"] + pod["deadline_exceeded"] == \
        pod["requests"]
    assert pod["lost"] == 0
    assert pod["kills_fired"] == pod["kills_planned"] == 1
    assert pod["workers_dead"] == 1
    assert pod["partitions_fired"] >= 1
    assert pod["requeues"] >= 1
    assert pod["spans_exactly_once"] is True
    assert pod["trace_propagated"] is True
    assert pod["pod_dispatch_spans"] >= 1
    assert pod["survivor_recompiles"] == 0
    assert pod["survivor_dispatches"] >= 1
    assert pod["post_swap_requests"] >= 1
    assert pod["post_swap_version_ok"] is True
    assert pod["swap_acks"] >= 2
    # one per_worker row per spawned process; exactly one read dead
    assert len(pod["per_worker"]) == 3
    assert sum(1 for m in pod["per_worker"] if m.get("dead")) == 1
    assert art["phases"]["pod_s"] >= 0

    # SERVE_TRACE exported the traced leg's spans as readable JSONL
    from fedamw_tpu.utils.trace import read_jsonl

    assert art["trace"]["exported"] == os.path.join(
        trace_dir, "serve_trace.jsonl")
    header, spans = read_jsonl(art["trace"]["exported"])
    req_ids = [s["trace_id"] for s in spans if s["name"] == "request"]
    assert len(req_ids) == len(set(req_ids)) == 200
    # every span of the traced stream carries the rollout dimensions
    for s in spans:
        if s["name"] == "request":
            assert "model_version" in s["attrs"]
            assert "staleness_rounds" in s["attrs"]

    # the rollout leg STREAMED its spans (rotating parts, in-memory
    # collector bypassed) into the same SERVE_TRACE directory
    parts = sorted(p for p in os.listdir(trace_dir)
                   if p.startswith("serve_loop-"))
    assert len(parts) == rollout["trace_parts"] >= 1
    streamed = 0
    for p in parts:
        h, ss = read_jsonl(os.path.join(trace_dir, p))
        assert h["streaming"] is True
        streamed += len(ss)
    assert streamed == rollout["trace_spans"] > 0


def test_serve_strict_tpu_refuses_cpu_backend(tmp_path):
    """Same dominance property as bench.py's strict mode: a leaked
    JAX_PLATFORMS=cpu under BENCH_STRICT_TPU=1 aborts before any
    metric line or artifact is produced."""
    out_path = str(tmp_path / "BENCH_SERVE_strict.json")
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", BENCH_STRICT_TPU="1",
               SERVE_OUT=out_path, **_SMALL)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "serve_bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 1
    assert "BENCH_STRICT_TPU set but the resolved backend" in out.stderr
    assert not out.stdout.strip()  # no metric lines to mis-harvest
    assert not os.path.exists(out_path)  # no artifact either
